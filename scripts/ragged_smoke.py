"""CI smoke for the ragged serving hot path (scripts/ci.sh --ragged).

Serves a mixed long/short greedy+sampled workload — two waves sharing a
long prompt prefix — through the ragged engine and asserts the ISSUE-9
acceptance observables:

* compile count: the WHOLE run (chunked prefills, decodes, mixed
  batches, both waves) dispatches exactly ONE compiled step shape;
* zero attention-path padding (padded_token_frac == 0), while the same
  workload on the bucketed engine pads;
* the shared prefix hits the COW prefix cache on wave 2;
* long prompts were chunked under the token budget;
* token parity: ragged == bucketed for every request, greedy AND
  sampled;
* exact block accounting at the end (invariants + all blocks free).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams


def build_model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def make_requests(vocab):
    rng = np.random.default_rng(42)
    shared = list(map(int, rng.integers(0, vocab, size=24)))
    prompts = [
        shared + list(map(int, rng.integers(0, vocab, size=8))),  # long
        list(map(int, rng.integers(0, vocab, size=3))),           # short
        shared + list(map(int, rng.integers(0, vocab, size=5))),  # long
        list(map(int, rng.integers(0, vocab, size=6))),           # short
    ]
    samplings = [
        SamplingParams(max_new_tokens=6),
        SamplingParams(max_new_tokens=5, temperature=0.8, seed=7),
        SamplingParams(max_new_tokens=6),
        SamplingParams(max_new_tokens=4),
    ]
    return prompts, samplings


def serve(model, ragged):
    prompts, samplings = make_requests(model.config.vocab_size)
    eng = LLMEngine(model, EngineConfig(
        block_size=4, max_num_seqs=4, max_model_len=64,
        max_batched_tokens=16,       # < the long prompts: forces chunks
        ragged=ragged,
        chunked_prefill=ragged, prefix_cache=ragged))
    outs = []
    for wave in range(2):            # wave 2 re-sends the shared prefix
        rids = [eng.add_request(f"w{wave}-r{i}", p, sampling=sp)
                for i, (p, sp) in enumerate(zip(prompts, samplings))]
        while eng.has_unfinished():
            eng.step()
            eng.block_manager.check_invariants()
        outs.append([eng.get_request(r).generated for r in rids])
    return eng, outs


def main():
    model = build_model()
    eng_r, outs_r = serve(model, ragged=True)
    eng_b, outs_b = serve(model, ragged=False)

    shapes = eng_r._seen_shapes
    assert len(shapes) == 1, \
        f"ragged run compiled {len(shapes)} step shapes: {shapes}"
    assert len(eng_b._seen_shapes) > 1   # the bucket lattice it replaces

    snap_r = eng_r.metrics.snapshot()
    snap_b = eng_b.metrics.snapshot()
    assert snap_r["padded_token_frac"] == 0.0, snap_r["padded_token_frac"]
    assert snap_b["padded_token_frac"] > 0.0, snap_b["padded_token_frac"]
    assert snap_r["serving_prefix_cache_hits"] > 0, \
        "wave-2 shared prefix never hit the cache"
    assert snap_r["serving_prefill_chunks"] > 0, \
        "the 16-token budget never chunked a 29+-token prompt"
    assert snap_r["mixed_steps"] > 0, \
        "no mixed chunk+decode batch was ever scheduled"

    assert outs_r == outs_b, "ragged != bucketed token streams"

    for eng in (eng_r, eng_b):
        assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
        eng.block_manager.check_invariants()

    print("ragged smoke OK:"
          f" shapes={sorted(shapes)}"
          f" prefix_hits={snap_r['serving_prefix_cache_hits']}"
          f" hit_tokens={snap_r['serving_prefix_cache_hit_tokens']}"
          f" chunks={snap_r['serving_prefill_chunks']}"
          f" mixed_steps={snap_r['mixed_steps']}"
          f" bucketed_padded_frac={snap_b['padded_token_frac']}")


if __name__ == "__main__":
    main()
