#!/usr/bin/env bash
# CI gate: tier-1 tests + repo-wide tpulint (the ROADMAP "wire
# --baseline into CI" follow-up).
#
#   scripts/ci.sh            tier-1 suite, then lint
#   scripts/ci.sh --lint     lint only (fast pre-push check)
#   scripts/ci.sh --fleet    fleet serving smoke only (2 tiny in-proc
#                            replicas + a mid-run replica kill, then 2
#                            subprocess workers with a real SIGKILL
#                            mid-decode and token parity; ~2 min)
#   scripts/ci.sh --ragged   ragged hot-path smoke only (mixed long/
#                            short prompts with shared prefixes;
#                            asserts ONE compiled step shape, zero
#                            padding, prefix-cache hits, chunked
#                            prefill, bucketed token parity; ~1 min)
#   scripts/ci.sh --spec     speculative-decoding smoke only (self-
#                            draft k=3; asserts acceptance > 0, greedy
#                            token parity vs the non-spec engine, and
#                            zero logits fetches; ~1 min)
#   scripts/ci.sh --disagg   disaggregated serving smoke only (2
#                            prefill + 2 decode subprocess workers,
#                            KV-block shipping prefill→decode, a real
#                            SIGKILL of a decode worker mid-run; token
#                            parity + ship counters; ~2 min)
#   scripts/ci.sh --peer     peer data plane smoke only (2 prefill +
#                            2 decode subprocess workers, KV shipped
#                            worker↔worker under signed tickets, a real
#                            SIGKILL of a destination decode worker;
#                            asserts peer_ship_bytes > 0, ZERO router
#                            relay bytes in steady state, exact ticket
#                            accounting, and token parity; ~2 min)
#   scripts/ci.sh --routers  replicated control plane smoke only (2
#                            router PROCESSES over 4 TCP-reachable
#                            subprocess workers sharing a FileStore
#                            lease store; a real SIGKILL of the router
#                            that owns leased in-flight requests; the
#                            survivor adopts them and must match a
#                            single-engine reference bit-for-bit with
#                            fleet/router_failovers == 1; ~2 min)
#   scripts/ci.sh --prefix   fleet prefix-cache smoke only (2 tiny
#                            replicas, shared-prefix workload; asserts
#                            a proactive hot-prefix ship, a positive
#                            fleet hit rate on the second replica
#                            WITHOUT it ever prefilling the shared
#                            header, and token parity; ~1 min)
#   scripts/ci.sh --tiers    tiered KV smoke only (a request whose
#                            context exceeds the device pool finishes
#                            greedy+sampled token-identical via host-
#                            tier demotion; park/resume re-prefills
#                            ZERO prompt tokens counter-asserted; 3
#                            subprocess workers offload a parked
#                            session to a peer under the ticket ladder
#                            and a real SIGKILL of the adopter
#                            degrades the resume to a clean counted
#                            recompute; ~2 min)
#   scripts/ci.sh --tp       TP-sharded serving smoke only (forced
#                            4-device host mesh; TP=2 token-identical
#                            to TP=1 through preemption + prefix hits,
#                            a TP=1→TP=2 KV ship landed through
#                            redistribute with zero tokens recomputed,
#                            fleet drain hand-off across degrees with
#                            the fault-injected ladder fallback, and a
#                            checkpoint restored onto the TP=2 layouts
#                            bit-identically; ~2 min)
#
# tpulint runs over the linted tree (paddle_tpu/ + tests/mp_scripts —
# the same set tests/test_lint_clean.py gates) and subtracts
# .tpulint-baseline.json when present, so pre-existing accepted
# findings never fail CI while ANY new finding does. The repo is
# currently clean, so the baseline is empty; regenerate it after an
# intentional acceptance with:
#   python -m paddle_tpu.analysis paddle_tpu tests/mp_scripts \
#       --baseline .tpulint-baseline.json --write-baseline
set -euo pipefail
cd "$(dirname "$0")/.."

LINT_PATHS=(paddle_tpu tests/mp_scripts)
BASELINE=.tpulint-baseline.json

run_lint() {
    echo "== tpulint =="
    # --stats prints the per-rule finding/suppression table so a CI
    # log shows WHERE the suppression budget sits, not just "0"
    if [[ -f "$BASELINE" ]]; then
        python -m paddle_tpu.analysis "${LINT_PATHS[@]}" \
            --baseline "$BASELINE" --stats
    else
        python -m paddle_tpu.analysis "${LINT_PATHS[@]}" --stats
    fi
}

run_fleet() {
    echo "== fleet smoke =="
    # 420s: the subprocess phase spawns 2 worker processes that each
    # build their own model before the first ping
    timeout -k 10 420 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/fleet_smoke.py
}

if [[ "${1:-}" == "--lint" ]]; then
    run_lint
    exit 0
fi

run_ragged() {
    echo "== ragged smoke =="
    timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/ragged_smoke.py
}

if [[ "${1:-}" == "--fleet" ]]; then
    run_fleet
    exit 0
fi

if [[ "${1:-}" == "--ragged" ]]; then
    run_ragged
    exit 0
fi

run_spec() {
    echo "== spec smoke =="
    timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/spec_smoke.py
}

if [[ "${1:-}" == "--spec" ]]; then
    run_spec
    exit 0
fi

run_disagg() {
    echo "== disagg smoke =="
    # 600s: four worker processes each build a model before first ping
    timeout -k 10 600 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/disagg_smoke.py
}

if [[ "${1:-}" == "--disagg" ]]; then
    run_disagg
    exit 0
fi

run_peer() {
    echo "== peer smoke =="
    # 600s: four worker processes each build a model before first ping
    timeout -k 10 600 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/peer_smoke.py
}

if [[ "${1:-}" == "--peer" ]]; then
    run_peer
    exit 0
fi

run_routers() {
    echo "== routers smoke =="
    # 420s: four worker processes each build a model before first ping
    timeout -k 10 420 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/router_smoke.py
}

if [[ "${1:-}" == "--routers" ]]; then
    run_routers
    exit 0
fi

run_prefix() {
    echo "== prefix smoke =="
    timeout -k 10 300 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/prefix_smoke.py
}

if [[ "${1:-}" == "--prefix" ]]; then
    run_prefix
    exit 0
fi

run_tiers() {
    echo "== tiers smoke =="
    # 600s: phase C spawns three worker processes that each build
    # their own model before the first ping
    timeout -k 10 600 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/tiers_smoke.py
}

if [[ "${1:-}" == "--tiers" ]]; then
    run_tiers
    exit 0
fi

run_tp() {
    echo "== tp smoke =="
    # tp_smoke.py forces its own 4-device host mesh via XLA_FLAGS
    # before importing jax; 420s covers the extra SPMD compiles
    timeout -k 10 420 env JAX_PLATFORMS=cpu PYTHONPATH=. \
        python scripts/tp_smoke.py
}

if [[ "${1:-}" == "--tp" ]]; then
    run_tp
    exit 0
fi

echo "== tier-1 tests =="
# the ROADMAP tier-1 verify command, verbatim semantics: CPU backend,
# not-slow subset, fail on first collection error kept visible.
# set -e is suspended around the pipeline so the rc capture and the
# DOTS_PASSED diagnostic still run when tests FAIL (the case they
# exist for).
rm -f /tmp/_t1.log
set +e
# 1500s: the suite keeps growing with the repo — it ran 831s at
# PR 10 and 1152s at PR 16 — and box-load variance was tripping
# spurious rc=124 timeouts when the budget sat too close to the
# quiet-box wall time.
timeout -k 10 1500 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
set -e
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
[[ $rc -eq 0 ]] || exit $rc

run_lint
echo "CI OK"
