"""Tiered KV smoke: device → host → peer hierarchy end to end.

The ``scripts/ci.sh --tiers`` stage. Three phases, each pinned to the
subsystem's core promise (demote instead of evict, promote instead of
recompute, degrade instead of lose):

A. **Over-pool serving** — a single request whose context NEEDS more
   KV blocks than the device pool holds (8 device blocks = 32 tokens;
   the request spans 52). The engine demotes cold blocks to the host
   tier mid-flight and completes token-identical — greedy AND sampled
   — to an unconstrained big-pool reference.
B. **Park / resume** — a finished turn parks (chain demoted to host),
   then a continuation prompt resumes it with ZERO prompt tokens
   recomputed, counter-asserted (``num_resume_recomputed_tokens == 0``
   and resume hit == tokens covered). Uses a 22-token prompt so the
   partial-tail byte restore path is the one exercised.
C. **Peer tier + SIGKILL** — 3 subprocess workers with tiered engines
   behind a router whose ``tier_offload_watermark`` forces the parked
   session off its pressured holder onto a cold peer over the ticketed
   prefix ladder. The ADOPTER — the peer now holding the demoted
   chain — takes a real ``SIGKILL`` mid-run; the resume degrades
   cleanly to the recompute floor (counted, token-identical, no hang)
   and every issued ticket lands in exactly one outcome bucket.

Exit 0 on success; any broken invariant raises.
"""
import os
import signal
import tempfile

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import (
    FleetConfig, FleetRouter, ReplicaSupervisor, SupervisorConfig,
    WorkerSpec,
)

_BASE = dict(block_size=4, max_num_seqs=8, max_model_len=96,
             drain_grace_s=0.0)
GREEDY = SamplingParams(max_new_tokens=8)
SAMPLED = SamplingParams(max_new_tokens=8, temperature=0.8, top_k=20,
                         seed=7)


def _run(eng, max_steps=600):
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to converge"
    if eng._kvtier is not None:
        eng._kvtier.apply_moves()
    eng.block_manager.check_invariants()


def _reference(model, prompts_by_rid):
    eng = LLMEngine(model, EngineConfig(num_blocks=256, **_BASE))
    for rid, (prompt, sp) in prompts_by_rid.items():
        eng.add_request(rid, prompt, sampling=sp)
    _run(eng)
    return {rid: list(eng.get_request(rid).generated)
            for rid in prompts_by_rid}


def phase_a_over_pool(model):
    rng = np.random.default_rng(31)
    prompt = [int(t) for t in rng.integers(0, 255, size=40)]
    cases = {"big-g": (prompt, SamplingParams(max_new_tokens=12)),
             "big-s": (prompt, SamplingParams(max_new_tokens=12,
                                              temperature=0.8,
                                              top_k=20, seed=7))}
    ref = _reference(model, cases)
    eng = LLMEngine(model, EngineConfig(
        num_blocks=8, kv_tiers={"num_host_blocks": 24}, **_BASE))
    need = (len(prompt) + 12 + eng.cfg.block_size - 1) \
        // eng.cfg.block_size
    assert need > 8, "scenario no longer exceeds the device pool"
    for rid, (p, sp) in cases.items():
        eng.add_request(rid, p, sampling=sp)
        _run(eng)  # serially: each alone still exceeds the pool
        assert list(eng.get_request(rid).generated) == ref[rid], (
            "over-pool stream diverged from the unconstrained "
            "reference", rid)
    snap = eng.metrics.snapshot()
    assert snap["serving_kv_tier_demotes"] > 0, snap
    print("TIERS_A_OK need_blocks=%d device_blocks=8 demotes=%d "
          "promotes=%d" % (need, snap["serving_kv_tier_demotes"],
                           snap["serving_kv_tier_promotes"]),
          flush=True)


def phase_b_park_resume(model):
    rng = np.random.default_rng(32)
    # 22-token prompt -> covered % block_size != 0: the resume must
    # restore the stashed partial-tail bytes, not just share full blocks
    prompt = [int(t) for t in rng.integers(0, 255, size=22)]
    eng = LLMEngine(model, EngineConfig(
        num_blocks=16, kv_tiers=True, **_BASE))
    eng.add_request("turn1", prompt, sampling=GREEDY)
    _run(eng)
    turn1 = list(eng.get_request("turn1").generated)
    eng.release_request("turn1")
    info = eng.park_session("turn1")
    assert info is not None and info["parked"], info
    prompt2 = prompt + turn1 + [int(t) for t in
                                rng.integers(0, 255, size=5)]
    hit = eng.resume_session("turn2", "turn1", prompt2,
                             sampling=GREEDY)
    assert hit == info["tokens_covered"], (hit, info)
    _run(eng)
    kvt = eng._kvtier
    assert kvt.num_resume_recomputed_tokens == 0, \
        kvt.num_resume_recomputed_tokens
    snap = eng.metrics.snapshot()
    assert snap["serving_kv_tier_park_resumes"] == 1, snap
    ref = _reference(model, {"turn2": (prompt2, GREEDY)})
    assert list(eng.get_request("turn2").generated) == ref["turn2"], \
        "resumed stream diverged from the fresh-prefill reference"
    print("TIERS_B_OK hit=%d recomputed=0 park_resumes=1"
          % hit, flush=True)


def phase_c_peer_kill(model):
    engine = dict(num_blocks=16, kv_tiers={"num_host_blocks": 16},
                  **_BASE)
    sup = ReplicaSupervisor(
        WorkerSpec(model="tiny_llama", seed=0, engine=engine,
                   peer=True),
        SupervisorConfig(
            store_dir=tempfile.mkdtemp(prefix="tiers_smoke_hb_")))
    try:
        handles = [sup.spawn() for _ in range(3)]
        for h in handles:
            assert h.peer_endpoint, f"{h.replica_id} has no peer"
        router = FleetRouter(
            handles, FleetConfig(tier_offload_watermark=1e-6),
            registry=sup.registry)
        sup.router = router

        rng = np.random.default_rng(33)
        prompt = [int(t) for t in rng.integers(0, 255, size=21)]
        rid = router.add_request("sess", prompt, sampling=GREEDY)
        steps = 0
        while router.has_unfinished():
            router.step()
            steps += 1
            assert steps < 500, "router failed to converge (turn1)"
        fr = router.get_request(rid)
        turn1, holder = list(fr.generated), fr.replica_id
        assert router.park_session(rid) is not None

        # the sweep fires past the (absurdly low) watermark: the chain
        # ships holder -> coldest peer over the ticket ladder and the
        # peer adopts the session
        router.step()
        assert router.num_session_offloads == 1, \
            router.num_session_offloads
        adopter = router._sessions[rid]["holder"]
        assert adopter != holder, "offload kept the session home"
        assert sum(router.ticket_outcomes.values()) \
            == router.num_tickets_issued, (router.ticket_outcomes,
                                           router.num_tickets_issued)

        # SIGKILL the peer now holding the demoted chain
        victim = next(h for h in handles if h.replica_id == adopter)
        os.kill(victim.proc.pid, signal.SIGKILL)
        victim.proc.wait(timeout=10)

        prompt2 = prompt + turn1 + [9, 8, 7]
        rid2 = router.resume_session(rid, prompt2, sampling=GREEDY)
        steps = 0
        while router.has_unfinished():
            router.step()
            steps += 1
            assert steps < 500, "router failed to converge (resume)"
        fr2 = router.get_request(rid2)
        assert fr2.finish_reason in ("stop", "length"), \
            fr2.finish_reason
        assert fr2.replica_id != adopter
        # the park was spent on the corpse: the resume degraded to the
        # recompute floor — counted, not hung, not duplicated
        assert router.num_session_resumes == 0, \
            router.num_session_resumes
        assert router.num_session_resume_recomputes == 1, \
            router.num_session_resume_recomputes
        ref = _reference(model, {rid2: (prompt2, GREEDY)})
        assert list(fr2.generated) == ref[rid2], \
            "post-kill recompute diverged from reference"
        assert sum(router.ticket_outcomes.values()) \
            == router.num_tickets_issued, (router.ticket_outcomes,
                                           router.num_tickets_issued)
        snap = router.snapshot()
        assert snap["fleet_session_offloads"] == 1, snap
        print("TIERS_C_OK offloads=1 adopter_killed=%s outcomes=%s "
              "resume_recomputes=%d"
              % (adopter, snap["fleet_ticket_outcomes"],
                 snap["fleet_session_resume_recomputes"]),
              flush=True)
    finally:
        sup.shutdown()


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    phase_a_over_pool(model)
    phase_b_park_resume(model)
    phase_c_peer_kill(model)
    print("TIERS_SMOKE_OK", flush=True)


if __name__ == "__main__":
    main()
