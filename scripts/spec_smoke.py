"""CI smoke for speculative decoding (scripts/ci.sh --spec).

Serves a greedy + seeded-sampled workload through a speculative engine
(the target drafting for itself — every greedy proposal verifies) and
asserts the ISSUE-11 acceptance observables:

* acceptance actually happened: ``spec_accepted > 0`` and the
  acceptance rate is nonzero (greedy rows with a perfect draft verify
  ~everything, so the rate is high, not merely positive);
* token parity at temperature 0: the speculative engine's greedy
  outputs are identical to a non-speculative engine's — fewer engine
  steps, same tokens;
* the hot path stays fetchless: ``num_logits_fetches == 0`` on BOTH
  engines, speculative and baseline alike (in-graph sampling);
* exact block accounting after rejected-slot rollback (invariants +
  all blocks free).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams


def build_model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def make_requests(vocab):
    rng = np.random.default_rng(11)
    prompts = [list(map(int, rng.integers(0, vocab, size=n)))
               for n in (5, 8, 3, 6)]
    samplings = [
        SamplingParams(max_new_tokens=8),
        SamplingParams(max_new_tokens=6),
        SamplingParams(max_new_tokens=8, temperature=0.8, top_p=0.9,
                       seed=21),
        SamplingParams(max_new_tokens=8),
    ]
    return prompts, samplings


def serve(model, spec):
    prompts, samplings = make_requests(model.config.vocab_size)
    cfg = dict(block_size=4, max_num_seqs=4, max_model_len=64)
    if spec:
        cfg.update(draft_model=model, num_spec_tokens=3)
    eng = LLMEngine(model, EngineConfig(**cfg))
    rids = [eng.add_request(p, sampling=s)
            for p, s in zip(prompts, samplings)]
    steps, done_at = 0, {}
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < 200, "engine failed to converge"
        for r in rids:
            if r not in done_at and eng.get_request(r).is_finished:
                done_at[r] = steps
    outs = [eng.get_request(r).generated for r in rids]
    return eng, outs, [done_at[r] for r in rids]


def main():
    model = build_model()
    base_eng, base_outs, base_done = serve(model, spec=False)
    spec_eng, spec_outs, spec_done = serve(model, spec=True)

    # greedy token parity: requests 0/1/3 are temperature-0 — rejection
    # sampling with a greedy target degenerates to exact prefix match
    for i in (0, 1, 3):
        assert spec_outs[i] == base_outs[i], (
            f"greedy request {i} diverged: {spec_outs[i]} vs "
            f"{base_outs[i]}")

    # acceptance happened, and it bought fewer target dispatches: each
    # greedy request finishes in strictly fewer engine steps (the
    # sampled request rejects most random-weight proposals, so TOTAL
    # step count is gated by it — per-request completion is the
    # speculation observable)
    assert spec_eng.num_spec_proposed > 0
    assert spec_eng.num_spec_accepted > 0, "no draft token ever accepted"
    rate = spec_eng.spec_acceptance_rate
    assert rate > 0.0, rate
    for i in (0, 1, 3):
        assert spec_done[i] < base_done[i], (i, spec_done, base_done)

    # zero logits fetches on the whole run, both engines
    assert base_eng.num_logits_fetches == 0
    assert spec_eng.num_logits_fetches == 0

    # rejected-slot rollback left the allocator exact
    for eng in (base_eng, spec_eng):
        assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
        eng.block_manager.check_invariants()

    print(f"spec smoke OK: acceptance={rate:.3f} "
          f"proposed={spec_eng.num_spec_proposed} "
          f"accepted={spec_eng.num_spec_accepted} "
          f"greedy done@ {base_done}->{spec_done} logits_fetches=0")


if __name__ == "__main__":
    main()
