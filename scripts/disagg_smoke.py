"""Disaggregated serving smoke: prefill/decode roles over real workers.

The ``scripts/ci.sh --disagg`` stage. A :class:`ReplicaSupervisor`
spawns 2 PREFILL + 2 DECODE worker processes; 8 sampled requests go
in. Every request prefills on a prefill worker, has its committed KV
blocks SHIPPED over the RPC socket to a decode worker (no prompt
recompute), and decodes there. Four router steps in, one DECODE worker
takes a real ``SIGKILL`` — its in-flight continuations fall back to
recompute on the survivors. Asserts:

* token streams bit-identical to an uninterrupted single-engine
  reference (sampled, so RNG state rode the ship/fallback correctly);
* every measured request was KV-shipped at least once and the router
  recomputed zero prompt tokens BEFORE the kill;
* exactly one replica died and the ship/fallback counters moved the
  way the crash story says they should.

Exit 0 on success; any broken invariant raises.
"""
import os
import signal
import tempfile

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import (
    FleetRouter, ReplicaSupervisor, SupervisorConfig, WorkerSpec,
)

_ENGINE = dict(block_size=4, max_num_seqs=8, max_model_len=64,
               drain_grace_s=0.0)


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()

    rng = np.random.default_rng(31)
    prompts = [list(map(int, rng.integers(
        0, model.config.vocab_size, size=5 + i % 4)))
        for i in range(8)]
    sp = SamplingParams(max_new_tokens=8, temperature=0.8, top_p=0.9)
    ids = [f"d{i}" for i in range(8)]

    # uninterrupted single-engine reference (worker twins: seed 0)
    eng = LLMEngine(model, EngineConfig(**_ENGINE))
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    while eng.has_unfinished():
        eng.step()
    ref = {rid: list(eng.get_request(rid).generated) for rid in ids}

    sup = ReplicaSupervisor(
        WorkerSpec(model="tiny_llama", seed=0, engine=dict(_ENGINE)),
        SupervisorConfig(
            store_dir=tempfile.mkdtemp(prefix="disagg_smoke_hb_")))
    try:
        handles = ([sup.spawn(role="prefill") for _ in range(2)]
                   + [sup.spawn(role="decode") for _ in range(2)])
        router = FleetRouter(handles, registry=sup.registry)
        sup.router = router
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        for _ in range(4):
            router.step()              # prefills shipped, decodes going
        ships_pre_kill = router.num_kv_ship_requests
        recomputed_pre_kill = router.num_tokens_recomputed
        assert ships_pre_kill >= 1, "no KV ship before the kill"
        assert recomputed_pre_kill == 0, (
            "ship path recomputed prompt tokens", recomputed_pre_kill)

        victim = handles[2]            # first decode worker
        os.kill(victim.proc.pid, signal.SIGKILL)
        steps = 0
        while router.has_unfinished():
            router.step()
            steps += 1
            assert steps < 500, "router failed to converge"

        got = {rid: list(router.get_request(rid).generated)
               for rid in ids}
        assert got == ref, "disagg token streams diverged from reference"
        for rid in ids:
            assert router.get_request(rid).finish_reason == "length"
        assert victim.proc.wait(timeout=10) == -signal.SIGKILL
        assert router.num_replicas_dead == 1
        assert router.num_kv_ship_requests >= ships_pre_kill
        snap = router.snapshot()
        assert snap["fleet_kv_ship_bytes"] > 0, snap
        print("DISAGG_SMOKE_OK ships=%d blocks=%d bytes=%d "
              "recomputed=%d fallbacks=%d dead=%d"
              % (snap["fleet_kv_ship_requests"],
                 snap["fleet_kv_ship_blocks"],
                 snap["fleet_kv_ship_bytes"],
                 snap["fleet_tokens_recomputed"],
                 snap["fleet_recompute_fallbacks"],
                 snap["fleet_replicas_dead"]),
              flush=True)
    finally:
        sup.shutdown()


if __name__ == "__main__":
    main()
