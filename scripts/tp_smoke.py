"""CI smoke for TP-sharded serving (scripts/ci.sh --tp).

Runs on a FORCED 4-device host mesh (tier-1 stays single-device) and
pins the ISSUE-17 acceptance observables:

* TP=2 ragged serving is token-identical to the TP=1 engine on a
  mixed greedy+sampled workload — through a forced-OOM preemption and
  prefix-cache hits — with zero attention-path padding;
* the same workload under ``swap_mode='host'``: the OOM victim's KV
  spills to host RAM as layout-sharded frames (``Layout.shard_frames``)
  and restores on readmit bit-exactly at both degrees;
* a KV ship from a TP=1 exporter into a TP=2 importer lands through
  ``redistribute`` (reshard counter + redistribute stats asserted)
  with ZERO prompt tokens recomputed (exactly the one mandatory
  position is computed on the importer);
* the same cross-degree ship at FLEET level: draining a TP=1 replica
  hands its in-flight requests to a TP=2 peer with token parity and
  ``fleet/tokens_recomputed == 0``, and an injected scatter fault
  falls back down the PR-14 ladder to recompute — never loss or
  duplication;
* ``CheckpointManager.restore(target_layout=...)`` restores one
  checkpoint onto the TP=2 layouts with logits bit-identical to the
  unsharded restore.
"""
import os

# the mesh must exist before jax initialises — set both knobs before
# ANY jax-importing module loads
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.redistribute import get_stats, reset_stats
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import FleetRouter, InProcessReplica
from paddle_tpu.testing import faults


def build_model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def _ecfg(tp, **kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("num_blocks", 48)
    kw.setdefault("drain_grace_s", 0.0)
    return EngineConfig(tp_degree=tp, **kw)


def make_workload(vocab):
    rng = np.random.default_rng(17)
    shared = list(map(int, rng.integers(0, vocab, size=16)))
    prompts = [
        shared + list(map(int, rng.integers(0, vocab, size=6))),
        list(map(int, rng.integers(0, vocab, size=5))),
        shared + list(map(int, rng.integers(0, vocab, size=3))),
        list(map(int, rng.integers(0, vocab, size=8))),
        shared + list(map(int, rng.integers(0, vocab, size=9))),
        list(map(int, rng.integers(0, vocab, size=4))),
    ]
    samplings = [
        SamplingParams(max_new_tokens=6),
        SamplingParams(max_new_tokens=5, temperature=0.8, seed=11),
        SamplingParams(max_new_tokens=6),
        SamplingParams(max_new_tokens=4, temperature=0.7, top_p=0.9,
                       seed=3),
        SamplingParams(max_new_tokens=6),
        SamplingParams(max_new_tokens=5),
    ]
    return prompts, samplings


def serve(model, tp):
    """The mixed workload on one engine, with a forced-OOM preemption
    of request r0 mid-decode (same fault schedule both degrees)."""
    prompts, samplings = make_workload(model.config.vocab_size)
    eng = LLMEngine(model, _ecfg(tp))
    rids = [eng.add_request(f"r{i}", p, sampling=sp)
            for i, (p, sp) in enumerate(zip(prompts, samplings))]
    faults.install("serving.force_oom.r0:flag*1")
    try:
        while eng.has_unfinished():
            eng.step()
            eng.block_manager.check_invariants()
    finally:
        faults.clear()
    return eng, {r: list(eng.get_request(r).generated) for r in rids}


def parity_phase(model):
    e1, out1 = serve(model, tp=1)
    e2, out2 = serve(model, tp=2)
    assert out1 == out2, "TP=2 diverged from TP=1:\n%r\n%r" % (out1, out2)
    s1, s2 = e1.metrics.snapshot(), e2.metrics.snapshot()
    for s in (s1, s2):
        assert s["preemptions"] >= 1, s["preemptions"]
        assert s["serving_prefix_cache_hits"] >= 1, s
        assert s["padded_token_frac"] == 0.0, s["padded_token_frac"]
    assert e2.tp_degree == 2 and e2.kv_layout.size == 2
    print("TP_PARITY_OK reqs=%d preempt=%d prefix_hits=%d"
          % (len(out1), s2["preemptions"],
             s2["serving_prefix_cache_hits"]), flush=True)


def host_swap_phase(model):
    """Swap-based preemption at TP=2: the forced-OOM victim's KV
    blocks spill to HOST memory as layout-sharded frames
    (``Layout.shard_frames``) and restore on readmit — token parity
    against the TP=1 host-swap engine proves the per-shard frame
    round-trip reassembled bit-exactly."""
    outs, snaps = {}, {}
    for tp in (1, 2):
        prompts, samplings = make_workload(model.config.vocab_size)
        eng = LLMEngine(model, _ecfg(tp, swap_mode="host",
                                     num_blocks=16))
        rids = [eng.add_request(f"r{i}", p, sampling=sp)
                for i, (p, sp) in enumerate(zip(prompts, samplings))]
        faults.install("serving.force_oom.r0:flag*1")
        try:
            while eng.has_unfinished():
                eng.step()
                eng.block_manager.check_invariants()
        finally:
            faults.clear()
        outs[tp] = {r: list(eng.get_request(r).generated)
                    for r in rids}
        snaps[tp] = eng.metrics.snapshot()
    assert outs[1] == outs[2], \
        "TP=2 host-swap diverged from TP=1:\n%r\n%r" % (outs[1],
                                                        outs[2])
    for tp, s in snaps.items():
        assert s["serving_swapped_out"] >= 1 \
                and s["serving_swapped_in"] >= 1, (tp, s)
    print("TP_HOST_SWAP_OK swapped_out=%d swapped_in=%d"
          % (snaps[2]["serving_swapped_out"],
             snaps[2]["serving_swapped_in"]),
          flush=True)


def cross_degree_ship_phase(model):
    """TP=1 exporter -> TP=2 importer, direct engine seam."""
    rng = np.random.default_rng(23)
    prompt = list(map(int, rng.integers(0, model.config.vocab_size,
                                        size=32)))
    max_new = 6
    ref_eng = LLMEngine(model, _ecfg(1))
    ref = ref_eng.generate([prompt],
                           SamplingParams(max_new_tokens=max_new))[0]

    e1 = LLMEngine(model, _ecfg(1))
    e1.add_request("ship", prompt,
                   sampling=SamplingParams(max_new_tokens=max_new))
    for _ in range(2):
        e1.step()
    done = list(e1.get_request("ship").generated)
    meta, payload = e1.export_kv("ship")
    assert meta["layout"]["mesh_axes"] == [["tp", 1]]

    e2 = LLMEngine(model, _ecfg(2))
    reset_stats()
    full_prompt = prompt + done
    e2.import_kv("ship", full_prompt,
                 sampling=SamplingParams(max_new_tokens=max_new
                                         - len(done)),
                 meta=meta, payload=payload)
    while e2.has_unfinished():
        e2.step()
    got = done + list(e2.get_request("ship").generated)
    assert got == ref, "shipped continuation diverged:\n%r\n%r" % (got,
                                                                   ref)
    st = get_stats()
    assert e2.num_kv_reshards == 1
    assert st["num_redistributes"] >= 1 and st["bytes_total"] > 0, st
    # zero recompute: the importer computed exactly the ONE mandatory
    # uncovered position, nothing else
    covered = meta["tokens_covered"]
    computed = e2.metrics.snapshot()["num_prompt_tokens"]
    assert computed == len(full_prompt) - covered == 1, \
        (computed, len(full_prompt), covered)
    snap = e2.metrics.snapshot()
    assert snap["serving_kv_reshards"] == 1
    assert snap["serving_continuation_resumes"] >= 1
    print("TP_CROSS_SHIP_OK covered=%d computed=%d redistributes=%d "
          "bytes_total=%d" % (covered, computed, st["num_redistributes"],
                              st["bytes_total"]), flush=True)


def _drain_router(router, max_steps=600):
    steps = 0
    while router.has_unfinished():
        router.step()
        steps += 1
        assert steps < max_steps, "router failed to converge"
    return steps


def fleet_handoff_phase(model, inject_fault):
    """Drain a TP=1 replica mid-run: its in-flight requests ship to
    the TP=2 peer. Clean path = zero tokens recomputed; injected
    scatter fault = one rung down the ladder (recompute), same
    tokens either way."""
    prompts, samplings = make_workload(model.config.vocab_size)
    ref_eng = LLMEngine(model, _ecfg(1))
    rids_ref = [ref_eng.add_request(f"f{i}", p, sampling=sp)
                for i, (p, sp) in enumerate(zip(prompts, samplings))]
    while ref_eng.has_unfinished():
        ref_eng.step()
    ref = {r: list(ref_eng.get_request(r).generated) for r in rids_ref}

    r1 = InProcessReplica(model, _ecfg(1), replica_id="tp1")
    r2 = InProcessReplica(model, _ecfg(2), replica_id="tp2")
    router = FleetRouter([r1, r2])
    for i, (p, sp) in enumerate(zip(prompts, samplings)):
        router.add_request(f"f{i}", p, sp)
    for _ in range(3):                  # everything dispatches + decodes
        router.step()
    if inject_fault:
        faults.install("serving.kv_scatter:raise*1")
    try:
        router.retire_replica(r1, reason="tp-migration")
        _drain_router(router)
    finally:
        faults.clear()
    got = {f"f{i}": list(router.get_request(f"f{i}").generated)
           for i in range(len(prompts))}
    assert got == ref, "fleet hand-off diverged:\n%r\n%r" % (got, ref)
    snap = router.snapshot()
    assert snap["fleet_finish"] == {"length": len(prompts)}, snap
    if inject_fault:
        assert snap["fleet_recompute_fallbacks"] >= 1, snap
        print("TP_FLEET_FAULT_OK fallbacks=%d recomputed=%d"
              % (snap["fleet_recompute_fallbacks"],
                 snap["fleet_tokens_recomputed"]), flush=True)
    else:
        assert snap["fleet_kv_ship_requests"] >= 1, snap
        assert snap["fleet_tokens_recomputed"] == 0, snap
        assert snap["fleet_recompute_fallbacks"] == 0, snap
        assert r2.engine.num_kv_reshards >= 1
        print("TP_FLEET_SHIP_OK ships=%d reshards=%d recomputed=0"
              % (snap["fleet_kv_ship_requests"],
                 r2.engine.num_kv_reshards), flush=True)


def checkpoint_reshard_phase(model, tmp="/tmp/_tp_smoke_ckpt"):
    """One saved checkpoint, two restores: unsharded and onto the
    TP=2 serving layouts. The restore itself is bit-identical (every
    gathered parameter equals the unsharded restore exactly); the
    sharded FORWARD is float32-reduction-order away from the dense
    one (GSPMD partitions the matmuls), so logits are pinned to tight
    float32 tolerance and the served tokens must match exactly."""
    import shutil

    shutil.rmtree(tmp, ignore_errors=True)
    mgr = CheckpointManager(tmp, dedupe_chunks=True)
    mgr.save(1, model.state_dict(), block=True)

    eng2 = LLMEngine(model, _ecfg(2))
    layouts = eng2.param_layouts()

    paddle.seed(123)
    plain = LlamaForCausalLM(LlamaConfig.tiny())
    plain.eval()
    mgr.restore(plain.state_dict(), step=1)

    paddle.seed(456)
    sharded = LlamaForCausalLM(LlamaConfig.tiny())
    sharded.eval()
    sd = sharded.state_dict()
    mgr.restore(sd, step=1,
                target_layout={k: layouts[k] for k in sd
                               if k in layouts},
                devices=eng2._tp_devices)

    # the restore moved ZERO bits: every resharded parameter gathers
    # back to exactly the unsharded restore's bytes
    psd = plain.state_dict()
    for k, v in sd.items():
        np.testing.assert_array_equal(
            np.asarray(v._data), np.asarray(psd[k]._data), err_msg=k)

    rng = np.random.default_rng(5)
    ids = paddle.to_tensor(rng.integers(
        0, model.config.vocab_size, size=(2, 12)).astype(np.int32))
    ref = plain(ids).numpy()
    got = sharded(ids).numpy()
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)

    # and the tokens the TP=2 engine serves from the resharded weights
    # match the unsharded-restore engine exactly
    prompt = list(map(int, rng.integers(
        0, model.config.vocab_size, size=20)))
    sp = SamplingParams(max_new_tokens=6)
    toks_plain = LLMEngine(plain, _ecfg(1)).generate([prompt], sp)[0]
    toks_shard = LLMEngine(sharded, _ecfg(2)).generate([prompt], sp)[0]
    assert toks_shard == toks_plain, (toks_shard, toks_plain)
    shutil.rmtree(tmp, ignore_errors=True)
    print("TP_CKPT_RESHARD_OK params_resharded=%d"
          % sum(1 for l in layouts.values()
                if any(p is not None for p in l.dim_placements)),
          flush=True)


def main():
    import jax

    assert len(jax.devices()) >= 4, jax.devices()
    model = build_model()
    parity_phase(model)
    host_swap_phase(model)
    cross_degree_ship_phase(model)
    fleet_handoff_phase(model, inject_fault=False)
    fleet_handoff_phase(model, inject_fault=True)
    checkpoint_reshard_phase(model)
    print("TP_SMOKE_OK", flush=True)


if __name__ == "__main__":
    main()
