"""Fleet-global prefix cache smoke: ship a hot prefix, serve on it.

The ``scripts/ci.sh --prefix`` stage, two phases over a two-replica
:class:`FleetRouter` on XLA:CPU with a shared 3-block header:

1. **warm + ship** — four shared-header requests served one at a time
   all land on ``x0`` (prefix-affine dispatch concentrates them), the
   router's hot-prefix tracker crosses its ship threshold, and the
   shared header is PROACTIVELY shipped to cold ``x1`` — which must
   now hold the header as cached-free blocks while having computed
   ZERO prompt tokens;
2. **serve on the shipped copy** — ``x0`` retires, three more
   shared-header requests land on ``x1``, and every one must
   prefix-hit the shipped header: ``x1`` computes exactly the
   non-shared suffixes (it never prefills the shared header — its
   ``num_prompt_tokens`` proves it), the fleet-wide hit rate goes
   positive, and all seven token streams are bit-identical to an
   uninterrupted single-engine reference.

Exit 0 on success; any broken invariant raises.
"""
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import (
    FleetConfig, FleetRouter, InProcessReplica,
)

_ENGINE = dict(block_size=4, max_num_seqs=4, max_model_len=64,
               drain_grace_s=0.0)
MAX_NEW = 8


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    vocab = model.config.vocab_size

    rng = np.random.default_rng(11)
    shared = [int(t) for t in rng.integers(1, vocab, size=12)]  # 3 blocks
    tails = [[int(t) for t in rng.integers(1, vocab, size=4)]
             for _ in range(7)]
    prompts = [shared + t for t in tails]
    ids = [f"p{i}" for i in range(7)]
    sp = SamplingParams(max_new_tokens=MAX_NEW)

    # uninterrupted single-engine reference (greedy: placement must
    # never change tokens)
    eng = LLMEngine(model, EngineConfig(**_ENGINE))
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    while eng.has_unfinished():
        eng.step()
    ref = {rid: list(eng.get_request(rid).generated) for rid in ids}

    router = FleetRouter(
        [InProcessReplica(model, EngineConfig(**_ENGINE),
                          replica_id=f"x{i}") for i in range(2)],
        FleetConfig(prefix_ship_threshold=2, prefix_decay_s=30.0))
    x0, x1 = router.replicas

    # phase 1: serial shared-header traffic concentrates on x0 and
    # heats the shared chain past the ship threshold
    got = {}
    for rid, p in zip(ids[:4], prompts[:4]):
        router.add_request(rid, p, sampling=sp)
        while router.has_unfinished():
            router.step()
        got[rid] = list(router.release_request(rid).generated)
    for _ in range(3):
        router.step()  # let a threshold crossed on the last dispatch ship

    assert router.num_prefix_ships >= 1, router.num_prefix_ships
    assert router.num_prefix_ship_bytes > 0
    assert x0.engine.metrics.num_prompt_tokens > 0
    # the shipped header landed on x1 without x1 computing ANYTHING
    assert x1.engine.num_prefix_imports >= 1, x1.engine.num_prefix_imports
    assert x1.engine.metrics.num_prompt_tokens == 0, \
        x1.engine.metrics.num_prompt_tokens
    assert x1.engine.block_manager.match_prefix(shared) == len(shared)

    # phase 2: x0 retires; the remaining traffic must serve on x1's
    # SHIPPED copy of the header — computing only the 4-token suffixes
    router.retire_replica(x0)
    for rid, p in zip(ids[4:], prompts[4:]):
        router.add_request(rid, p, sampling=sp)
    steps = 0
    while router.has_unfinished():
        router.step()
        steps += 1
        assert steps < 500, "router failed to converge"
    for rid in ids[4:]:
        fr = router.get_request(rid)
        assert fr.finish_reason == "length", (rid, fr.finish_reason)
        got[rid] = list(router.release_request(rid).generated)

    assert got == ref, "prefix-cache path changed tokens"
    n, suffix = len(ids[4:]), len(tails[0])
    assert x1.engine.metrics.num_prompt_tokens == n * suffix, \
        x1.engine.metrics.num_prompt_tokens
    assert (x1.engine.block_manager.num_prefix_hit_tokens
            >= n * len(shared))
    snap = router.snapshot()
    assert snap["fleet_prefix_hit_rate"] > 0, snap["fleet_prefix_hit_rate"]
    assert snap["replicas"]["x1"]["serving_prefix_cache_hit_tokens"] \
        >= n * len(shared)
    print("PREFIX_SMOKE_OK ships=%d bytes=%d x1_hit_tokens=%d "
          "x1_computed=%d fleet_hit_rate=%.4f"
          % (router.num_prefix_ships, router.num_prefix_ship_bytes,
             x1.engine.block_manager.num_prefix_hit_tokens,
             x1.engine.metrics.num_prompt_tokens,
             snap["fleet_prefix_hit_rate"]), flush=True)


if __name__ == "__main__":
    main()
