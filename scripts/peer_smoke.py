"""Peer data plane smoke: ticketed worker↔worker KV over real processes.

The ``scripts/ci.sh --peer`` stage. A :class:`ReplicaSupervisor`
spawns 2 PREFILL + 2 DECODE worker processes with peer listeners on; 8
sampled requests go in. Every request prefills on a prefill worker,
whose KV blocks move STRAIGHT to a decode worker's peer listener under
a router-issued signed ticket — the router carries only the ticket and
the commit verb, never the payload. Mid-run one DECODE worker takes a
real ``SIGKILL``; its continuations fall back down the ladder on the
survivors. Asserts:

* token streams bit-identical to an uninterrupted single-engine
  reference (sampled, so RNG state rode the ticketed ship correctly);
* ``fleet/peer_ship_bytes`` > 0 and, pre-kill (steady state), router
  relay bytes == 0 — ZERO KV payload bytes through the router;
* every issued ticket is accounted:
  ``sum(ticket_outcomes) == tickets_issued``;
* exactly one replica died and the fleet still converged.

Exit 0 on success; any broken invariant raises.
"""
import os
import signal
import tempfile

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import (
    FleetRouter, ReplicaSupervisor, SupervisorConfig, WorkerSpec,
)

_ENGINE = dict(block_size=4, max_num_seqs=8, max_model_len=64,
               drain_grace_s=0.0)


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()

    rng = np.random.default_rng(47)
    prompts = [list(map(int, rng.integers(
        0, model.config.vocab_size, size=5 + i % 4)))
        for i in range(8)]
    sp = SamplingParams(max_new_tokens=8, temperature=0.8, top_p=0.9)
    ids = [f"p{i}" for i in range(8)]

    # uninterrupted single-engine reference (worker twins: seed 0)
    eng = LLMEngine(model, EngineConfig(**_ENGINE))
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    while eng.has_unfinished():
        eng.step()
    ref = {rid: list(eng.get_request(rid).generated) for rid in ids}

    sup = ReplicaSupervisor(
        WorkerSpec(model="tiny_llama", seed=0, engine=dict(_ENGINE),
                   peer=True),
        SupervisorConfig(
            store_dir=tempfile.mkdtemp(prefix="peer_smoke_hb_")))
    try:
        handles = ([sup.spawn(role="prefill") for _ in range(2)]
                   + [sup.spawn(role="decode") for _ in range(2)])
        for h in handles:
            assert h.peer_endpoint, f"{h.replica_id} has no peer endpoint"
        router = FleetRouter(handles, registry=sup.registry)
        sup.router = router
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        for _ in range(4):
            router.step()        # prefills ticketed+pushed, decodes going
        peer_pre_kill = router.num_peer_ship_requests
        assert peer_pre_kill >= 1, "no peer ship before the kill"
        # steady state: the payload NEVER touched the router
        assert router.num_relay_bytes == 0, (
            "router relayed KV bytes with the peer plane up",
            router.num_relay_bytes)
        assert router.num_tokens_recomputed == 0, (
            "peer path recomputed prompt tokens",
            router.num_tokens_recomputed)

        victim = handles[2]            # first decode worker: a transfer
        os.kill(victim.proc.pid, signal.SIGKILL)   # DESTINATION dies
        steps = 0
        while router.has_unfinished():
            router.step()
            steps += 1
            assert steps < 500, "router failed to converge"

        got = {rid: list(router.get_request(rid).generated)
               for rid in ids}
        assert got == ref, "peer token streams diverged from reference"
        for rid in ids:
            assert router.get_request(rid).finish_reason == "length"
        assert victim.proc.wait(timeout=10) == -signal.SIGKILL
        assert router.num_replicas_dead == 1
        # the kill forced the ladder down at least one rung somewhere
        assert (router.num_relay_fallbacks + router.num_recompute_fallbacks
                + router.num_handoffs) >= 1, "kill left no fallback trace"
        assert router.num_tickets_issued == \
            sum(router.ticket_outcomes.values()), (
            router.num_tickets_issued, router.ticket_outcomes)
        snap = router.snapshot()
        assert snap["fleet_peer_ship_bytes"] > 0, snap
        print("PEER_SMOKE_OK peer_ships=%d peer_bytes=%d relay_bytes=%d "
              "tickets=%d outcomes=%s recomputes=%d dead=%d"
              % (snap["fleet_peer_ship_requests"],
                 snap["fleet_peer_ship_bytes"],
                 snap["fleet_relay_bytes"],
                 snap["fleet_tickets_issued"],
                 snap["fleet_ticket_outcomes"],
                 snap["fleet_recompute_fallbacks"],
                 snap["fleet_replicas_dead"]),
              flush=True)
    finally:
        sup.shutdown()


if __name__ == "__main__":
    main()
