"""Fleet serving smoke: 2 tiny replicas + a mid-run replica kill.

The ``scripts/ci.sh --fleet`` stage: boots a two-replica
:class:`FleetRouter` on XLA:CPU, admits 8 requests across two tenants,
kills replica r0 through the ``fleet.kill_replica`` fault four router
steps in, and asserts the fleet absorbs the loss — every request
finishes ``'length'`` token-complete, at least one hand-off happened,
and the fleet counters say exactly one replica died. Exit 0 on
success; any broken invariant raises.
"""
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, SamplingParams
from paddle_tpu.serving.fleet import FleetRouter, InProcessReplica
from paddle_tpu.testing import faults


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    router = FleetRouter([
        InProcessReplica(
            model,
            EngineConfig(block_size=4, max_num_seqs=4, max_model_len=64),
            replica_id=f"r{i}")
        for i in range(2)])

    rng = np.random.default_rng(5)
    max_new = 8
    rids = [router.add_request(
        list(map(int, rng.integers(0, model.config.vocab_size,
                                   size=3 + (i % 4)))),
        SamplingParams(max_new_tokens=max_new,
                       tenant_id=("a" if i % 2 else "b")))
        for i in range(8)]

    faults.install("fleet.kill_replica:flag:r0@4*1")
    steps = 0
    try:
        while router.has_unfinished():
            router.step()
            steps += 1
            assert steps < 500, "router failed to converge"
    finally:
        faults.clear()

    for rid in rids:
        fr = router.get_request(rid)
        assert fr.finish_reason == "length", (rid, fr.finish_reason)
        assert len(fr.generated) == max_new, (rid, len(fr.generated))
    snap = router.snapshot()
    assert snap["fleet_replicas_dead"] == 1, snap
    assert snap["fleet_handoffs"] >= 1, snap
    assert snap["fleet_finish"] == {"length": 8}, snap
    assert router._by_id("r0").alive is False
    assert set(snap["fleet_tenants"]) == {"a", "b"}
    print("FLEET_SMOKE_OK steps=%d handoffs=%d dead=%d"
          % (steps, snap["fleet_handoffs"], snap["fleet_replicas_dead"]),
          flush=True)


if __name__ == "__main__":
    main()
