"""Fleet serving smoke: in-process kill recovery, then subprocess.

The ``scripts/ci.sh --fleet`` stage, two phases:

1. **in-process** — boots a two-replica :class:`FleetRouter` on
   XLA:CPU, admits 8 requests across two tenants, kills replica r0
   through the ``fleet.kill_replica`` fault four router steps in, and
   asserts the fleet absorbs the loss — every request finishes
   ``'length'`` token-complete, at least one hand-off happened, and
   the fleet counters say exactly one replica died;
2. **subprocess** — a :class:`ReplicaSupervisor` spawns 2 worker
   PROCESSES, 6 requests go in, one worker takes a real ``SIGKILL``
   mid-decode, and every request must finish with token streams
   bit-identical to an uninterrupted single-engine reference.

Exit 0 on success; any broken invariant raises.
"""
import os
import signal
import tempfile

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import (
    FleetRouter, InProcessReplica, ReplicaSupervisor, SupervisorConfig,
    WorkerSpec,
)
from paddle_tpu.testing import faults

_ENGINE = dict(block_size=4, max_num_seqs=8, max_model_len=64,
               drain_grace_s=0.0)


def subprocess_phase(model):
    prompts = [list(map(int, np.random.default_rng(9).integers(
        0, model.config.vocab_size, size=3 + i % 4)))
        for i in range(6)]
    sp = SamplingParams(max_new_tokens=8, temperature=0.8, top_p=0.9)
    ids = [f"s{i}" for i in range(6)]

    # uninterrupted single-engine reference (worker twins: seed 0)
    eng = LLMEngine(model, EngineConfig(**_ENGINE))
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    while eng.has_unfinished():
        eng.step()
    ref = {rid: list(eng.get_request(rid).generated) for rid in ids}

    sup = ReplicaSupervisor(
        WorkerSpec(model="tiny_llama", seed=0, engine=dict(_ENGINE)),
        SupervisorConfig(
            store_dir=tempfile.mkdtemp(prefix="fleet_smoke_hb_")))
    try:
        handles = [sup.spawn() for _ in range(2)]
        router = FleetRouter(handles, registry=sup.registry)
        sup.router = router
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        for _ in range(3):
            router.step()                  # tokens in flight
        victim = handles[0]
        os.kill(victim.proc.pid, signal.SIGKILL)
        steps = 0
        while router.has_unfinished():
            router.step()
            steps += 1
            assert steps < 500, "router failed to converge"
        got = {rid: list(router.get_request(rid).generated)
               for rid in ids}
        assert got == ref, "post-SIGKILL token streams diverged"
        for rid in ids:
            assert router.get_request(rid).finish_reason == "length"
        assert victim.proc.wait(timeout=10) == -signal.SIGKILL
        assert router.num_replicas_dead == 1
        assert router.num_handoffs >= 1
        print("FLEET_SMOKE_SUBPROCESS_OK handoffs=%d dead=%d"
              % (router.num_handoffs, router.num_replicas_dead),
              flush=True)
    finally:
        sup.shutdown()


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    router = FleetRouter([
        InProcessReplica(
            model,
            EngineConfig(block_size=4, max_num_seqs=4, max_model_len=64),
            replica_id=f"r{i}")
        for i in range(2)])

    rng = np.random.default_rng(5)
    max_new = 8
    rids = [router.add_request(
        list(map(int, rng.integers(0, model.config.vocab_size,
                                   size=3 + (i % 4)))),
        SamplingParams(max_new_tokens=max_new,
                       tenant_id=("a" if i % 2 else "b")))
        for i in range(8)]

    faults.install("fleet.kill_replica:flag:r0@4*1")
    steps = 0
    try:
        while router.has_unfinished():
            router.step()
            steps += 1
            assert steps < 500, "router failed to converge"
    finally:
        faults.clear()

    for rid in rids:
        fr = router.get_request(rid)
        assert fr.finish_reason == "length", (rid, fr.finish_reason)
        assert len(fr.generated) == max_new, (rid, len(fr.generated))
    snap = router.snapshot()
    assert snap["fleet_replicas_dead"] == 1, snap
    assert snap["fleet_handoffs"] >= 1, snap
    assert snap["fleet_finish"] == {"length": 8}, snap
    assert router._by_id("r0").alive is False
    assert set(snap["fleet_tenants"]) == {"a", "b"}
    print("FLEET_SMOKE_OK steps=%d handoffs=%d dead=%d"
          % (steps, snap["fleet_handoffs"], snap["fleet_replicas_dead"]),
          flush=True)
    subprocess_phase(model)


if __name__ == "__main__":
    main()
