"""Replicated control plane smoke: 2 router PROCESSES, real SIGKILL.

The ``scripts/ci.sh --routers`` stage. Topology:

* 4 worker processes spawned by a :class:`ReplicaSupervisor` with
  ``WorkerSpec(tcp=True)`` — each advertises a TCP control endpoint in
  its heartbeat meta, so routers other than the spawning supervisor
  can drive it (:func:`connect_replica`);
* router **B** lives in this driver process (supervisor socketpair
  handles); router **A** is a CHILD PROCESS that attaches to the same
  workers over TCP and shares the FileStore-backed registries and
  :class:`LeaseStore`.

Requests are tenant-partitioned across A and B. Once A reports (via a
marker file) that every one of its requests holds a store lease with
tokens already decoded, the driver sends the A process a real
``SIGKILL`` mid-flight. B must detect the stale router record, adopt
A's leased requests at a bumped fencing generation, and finish them
with token streams bit-identical to an uninterrupted single-engine
reference — and the ``fleet/router_failovers`` gauge must read
exactly 1.

Exit 0 on success; any broken invariant raises.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from paddle_tpu.distributed.replica_registry import ReplicaRegistry
from paddle_tpu.distributed.store import FileStore
from paddle_tpu.serving import SamplingParams
from paddle_tpu.serving.fleet import (
    FleetConfig, FleetRouter, LeaseStore, ReplicaSupervisor,
    SupervisorConfig, WorkerSpec, connect_replica, rendezvous_owner,
    tenant_home,
)

_ENGINE = dict(block_size=4, max_num_seqs=8, max_model_len=64,
               drain_grace_s=0.0)
MAX_NEW = 10
ROUTER_TTL_S = 3.0
LEASE_TTL_S = 6.0


def _fleet_config() -> FleetConfig:
    return FleetConfig(heartbeat_interval_s=0.0,
                       router_ttl_s=ROUTER_TTL_S,
                       lease_ttl_s=LEASE_TTL_S,
                       prefix_affinity=False, peer_data_plane=False)


def _sampling(tenant: str) -> SamplingParams:
    return SamplingParams(max_new_tokens=MAX_NEW, temperature=0.8,
                          top_p=0.9, tenant_id=tenant)


# -- child: router A in its own process ----------------------------------

def child(cfg_path: str) -> None:
    with open(cfg_path) as f:
        cfg = json.load(f)
    handles = [connect_replica(rid, ep)
               for rid, ep in sorted(cfg["workers"].items())]
    store_dir = cfg["store_dir"]
    router = FleetRouter(
        handles, _fleet_config(),
        registry=ReplicaRegistry(FileStore(store_dir)),
        lease_store=LeaseStore(FileStore(store_dir),
                               ttl_s=LEASE_TTL_S),
        router_id=cfg["router_id"])
    for r in cfg["requests"]:
        router.add_request(r["rid"], r["prompt"],
                           sampling=_sampling(r["tenant"]))
    ready = False
    deadline = time.monotonic() + 150
    while time.monotonic() < deadline:
        router.step()
        if not ready:
            mine = [router.get_request(r["rid"])
                    for r in cfg["requests"]]
            if all(fr.lease_gen is not None and not fr.finished
                   and len(fr.generated) >= 2 for fr in mine):
                tmp = cfg["ready_path"] + ".tmp"
                with open(tmp, "w") as f:
                    f.write("ready")
                os.replace(tmp, cfg["ready_path"])
                ready = True
                # hold still so the SIGKILL provably lands while every
                # request is mid-decode (nothing can finish asleep);
                # short of the router TTL, so A never LOOKS dead before
                # it actually is
                time.sleep(min(2.0, ROUTER_TTL_S - 1.0))
        time.sleep(0.005)
    sys.exit(3)  # the driver never killed us: smoke failure


# -- driver: reference, workers, router B, the kill ----------------------

def _requests(model):
    """6 requests over tenants t0..t5, partitioned by tenant_home over
    routers {A, B} exactly as the client side would."""
    rng = np.random.default_rng(11)
    reqs = []
    for i in range(6):
        tenant = f"t{i}"
        reqs.append({
            "rid": f"q{i}", "tenant": tenant,
            "home": tenant_home(tenant, ["A", "B"]),
            "prompt": list(map(int, rng.integers(
                0, model.config.vocab_size, size=3 + i % 4)))})
    return reqs


def main() -> None:
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import EngineConfig, LLMEngine

    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    reqs = _requests(model)
    a_reqs = [r for r in reqs if r["home"] == "A"]
    b_reqs = [r for r in reqs if r["home"] == "B"]
    assert a_reqs and b_reqs, "tenant partition must cover both routers"

    # uninterrupted single-engine reference (worker twins: seed 0)
    eng = LLMEngine(model, EngineConfig(**_ENGINE))
    for r in reqs:
        eng.add_request(r["rid"], r["prompt"],
                        sampling=_sampling(r["tenant"]))
    while eng.has_unfinished():
        eng.step()
    ref = {r["rid"]: list(eng.get_request(r["rid"]).generated)
           for r in reqs}

    tmp = tempfile.mkdtemp(prefix="router_smoke_")
    store_dir = os.path.join(tmp, "store")
    sup = ReplicaSupervisor(
        WorkerSpec(model="tiny_llama", seed=0, engine=dict(_ENGINE),
                   peer=False, tcp=True),
        SupervisorConfig(store_dir=store_dir))
    proc_a = None
    try:
        handles = [sup.spawn() for _ in range(4)]
        # both routers must own at least one worker or the victim's
        # requests would just be orphan-handed over (no failover path)
        owners = {h.replica_id: rendezvous_owner(h.replica_id,
                                                 ["A", "B"])
                  for h in handles}
        assert len(set(owners.values())) == 2, owners

        # the workers' advertised TCP control endpoints, for A
        endpoints = {}
        deadline = time.monotonic() + 60
        while len(endpoints) < len(handles):
            assert time.monotonic() < deadline, "no rpc endpoints"
            for h in handles:
                rec = sup.registry.record(h.replica_id) or {}
                ep = (rec.get("meta") or {}).get("rpc")
                if ep:
                    endpoints[h.replica_id] = ep
            time.sleep(0.05)

        router_b = FleetRouter(
            handles, _fleet_config(), registry=sup.registry,
            lease_store=LeaseStore(FileStore(store_dir),
                                   ttl_s=LEASE_TTL_S),
            router_id="B")

        ready_path = os.path.join(tmp, "A.ready")
        cfg_path = os.path.join(tmp, "A.json")
        with open(cfg_path, "w") as f:
            json.dump({"router_id": "A", "store_dir": store_dir,
                       "workers": endpoints, "requests": a_reqs,
                       "ready_path": ready_path}, f)
        proc_a = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--child", cfg_path],
            env=dict(os.environ, JAX_PLATFORMS="cpu"))

        # wait for A to join the router registry before admitting, so
        # B's replica-ownership view is stable from the first dispatch
        router_reg = ReplicaRegistry(FileStore(store_dir),
                                     prefix="fleet_routers",
                                     ttl_s=ROUTER_TTL_S)
        deadline = time.monotonic() + 120
        while router_reg.record("A") is None:
            assert proc_a.poll() is None, "router A died during boot"
            assert time.monotonic() < deadline, "router A never joined"
            time.sleep(0.05)

        for r in b_reqs:
            router_b.add_request(r["rid"], r["prompt"],
                                 sampling=_sampling(r["tenant"]))

        killed = False
        t_kill = None
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            router_b.step()
            if not killed and os.path.exists(ready_path):
                os.kill(proc_a.pid, signal.SIGKILL)
                killed, t_kill = True, time.monotonic()
                print("ROUTER_SMOKE_KILLED pid=%d" % proc_a.pid,
                      flush=True)
            done = [router_b._requests.get(r["rid"]) for r in reqs]
            if (killed and all(fr is not None and fr.finished
                               for fr in done)
                    and router_b.lease_store.active() == 0):
                break
            time.sleep(0.005)
        else:
            raise AssertionError("router B failed to converge")

        assert killed and t_kill is not None
        assert proc_a.wait(timeout=10) == -signal.SIGKILL

        got = {r["rid"]: list(router_b.get_request(r["rid"]).generated)
               for r in reqs}
        assert got == ref, "post-SIGKILL token streams diverged"
        for r in reqs:
            fr = router_b.get_request(r["rid"])
            assert fr.finish_reason == "length", (
                r["rid"], fr.finish_reason)
        snap = router_b.snapshot()
        assert snap["fleet_router_failovers"] == 1, snap
        assert router_b.lease_store.num_adopted == len(a_reqs), (
            router_b.lease_store.num_adopted, len(a_reqs))
        assert router_b.lease_store.active() == 0
        print("ROUTER_SMOKE_OK adopted=%d failovers=%d took=%.1fs"
              % (router_b.lease_store.num_adopted,
                 snap["fleet_router_failovers"],
                 time.monotonic() - t_kill), flush=True)
    finally:
        if proc_a is not None and proc_a.poll() is None:
            proc_a.kill()
            proc_a.wait(timeout=10)
        sup.shutdown()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2])
    else:
        main()
