"""tracecheck rule tests: every launch rule catches its seeded
violations (zero false negatives on the fixtures) and stays quiet on
the near-miss set (no false positives). Plus suppressions, the
baseline machinery, and the CLI contract (exit codes, --format=json)."""
import json
import textwrap

import pytest

from paddle_tpu.analysis import (
    analyze_paths, analyze_source, get_rules, load_baseline,
    write_baseline,
)
from paddle_tpu.analysis.cli import main as cli_main


def run(src):
    return analyze_source(textwrap.dedent(src), path="fixture.py")


def run_at(src, path):
    """Path-gated rules (counter-snapshot-drift is serving/fleet-scoped)
    see whatever path we claim for the fixture."""
    return analyze_source(textwrap.dedent(src), path=path)


def rules_of(findings):
    return [f.rule for f in findings]


def test_rule_catalog_has_all_launch_rules():
    names = set(get_rules())
    assert {"host-sync-in-traced", "use-after-donate",
            "trace-time-impurity", "tensor-bool-branch",
            "counter-provider-leak", "block-until-ready-in-loop",
            "unlocked-shared-state", "lock-order-cycle",
            "blocking-under-lock", "signal-handler-unsafe",
            "collective-divergence", "finish-reason-literal",
            "leaked-resource-on-raise", "counter-snapshot-drift",
            "fault-point-literal", "rpc-verb-unclassified",
            "unbounded-rpc-deadline"} <= names
    assert len(names) == 17
    for r in get_rules().values():
        assert r.summary and r.doc  # per-rule docs are part of the API


# ---------------------------------------------------------------------------
# host-sync-in-traced
# ---------------------------------------------------------------------------
class TestHostSync:
    def test_numpy_item_float_inside_jit(self):
        fs = run("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                a = np.asarray(x)
                b = x.numpy()
                c = float(x)
                d = x.item()
                return a, b, c, d
        """)
        assert rules_of(fs) == ["host-sync-in-traced"] * 4

    def test_reachable_through_one_helper_call(self):
        fs = run("""
            import jax

            def helper(t):
                return t.item()

            def entry(x):
                return helper(x)

            g = jax.jit(entry)
        """)
        assert rules_of(fs) == ["host-sync-in-traced"]
        # the finding lands in helper's body, attributed to the traced
        # caller the call graph followed
        assert fs[0].line == 5
        assert "entry" in fs[0].message

    def test_partial_jit_decorator_is_traced(self):
        # @partial(jax.jit, static_argnums=...) is THE jit-with-options
        # idiom and must get the same analysis
        fs = run("""
            from functools import partial

            import jax
            import numpy as np

            @partial(jax.jit, static_argnums=(1,))
            def f(x, n):
                return np.asarray(x)

            g = jax.jit(partial(f, n=2))  # partial as wrapper arg too
        """)
        assert rules_of(fs) == ["host-sync-in-traced"]

    def test_annotated_dispatch_result_fetch_flagged(self):
        fs = run("""
            import jax
            import numpy as np

            def go(f, x):
                step = jax.jit(f)
                out: jax.Array = step(x)
                return np.asarray(out)
        """)
        assert rules_of(fs) == ["host-sync-in-traced"]

    def test_factory_returned_step_fn_is_traced(self):
        fs = run("""
            import jax

            def make_step(flag):
                def step_fn(x):
                    return float(x)
                return step_fn

            jitted = jax.jit(make_step(True), static_argnums=())
        """)
        assert rules_of(fs) == ["host-sync-in-traced"]

    def test_dispatch_result_fetch_flagged(self):
        fs = run("""
            import jax
            import numpy as np

            class Eng:
                def __init__(self, f):
                    self._jstep = jax.jit(f)

                def step(self, ids):
                    logits, cache = self._jstep(ids)
                    return np.asarray(logits)
        """)
        assert rules_of(fs) == ["host-sync-in-traced"]
        assert "compiled dispatch" in fs[0].message

    def test_near_miss_host_side_numpy_clean(self):
        fs = run("""
            import numpy as np

            def host_fn(t):
                return np.asarray(t)  # no traced scope anywhere

            def loader(batch):
                return [float(x) for x in batch]
        """)
        assert fs == []

    def test_near_miss_float_of_literal_clean(self):
        fs = run("""
            import jax

            @jax.jit
            def f(x):
                return x * float(2)  # constant, not a tensor sync
        """)
        assert fs == []

    def test_near_miss_trace_time_constants_clean(self):
        # literal lookup tables and static shape reads are host-safe
        fs = run("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                table = np.asarray([0.0, 1.0, 2.0])
                n = int(x.shape[0])
                d = x.ndim
                return x * table[0] * n * d
        """)
        assert fs == []

    def test_dispatch_result_method_fetch_flagged(self):
        # .item()/.numpy() ARE the headline spellings — method calls
        # have no positional args, so the receiver is the fetched value
        fs = run("""
            import jax

            class Eng:
                def __init__(self, f):
                    self._jstep = jax.jit(f)

                def step(self, ids):
                    out = self._jstep(ids)
                    return out.item(), out.numpy()
        """)
        assert rules_of(fs) == ["host-sync-in-traced"] * 2

    def test_near_miss_nested_def_binds_stay_scoped(self):
        # a closure's dispatch result must not taint the enclosing
        # function's same-named host variable
        fs = run("""
            import jax
            import numpy as np

            def outer(step_fn, data):
                out = list(data)
                step = jax.jit(step_fn)

                def inner(x):
                    out = step(x)
                    return out

                return np.asarray(out), inner
        """)
        assert fs == []

    def test_near_miss_dispatch_result_rebound_clean(self):
        # a reassigned name no longer aliases the dispatch output
        fs = run("""
            import jax
            import numpy as np

            def go(f, x):
                step = jax.jit(f)
                out = step(x)
                out = [1, 2, 3]
                return np.asarray(out)
        """)
        assert fs == []

    def test_cross_method_self_attr_fetch_flagged(self):
        # self._last parked in step(), fetched host-side in result() —
        # the None placeholder in __init__ must not clear the bind
        fs = run("""
            import jax
            import numpy as np

            class Eng:
                def __init__(self, f):
                    self._jstep = jax.jit(f)
                    self._last = None

                def step(self, ids):
                    self._last = self._jstep(ids)

                def result(self):
                    return np.asarray(self._last)
        """)
        assert rules_of(fs) == ["host-sync-in-traced"]
        assert "self._last" in fs[0].message
        assert "step()" in fs[0].message

    def test_cross_method_self_attr_via_local_flagged(self):
        # the dispatch result routes through a local before parking on
        # self — the local's live bind must propagate to the attribute
        fs = run("""
            import jax

            class Eng:
                def __init__(self, f):
                    self._jstep = jax.jit(f)

                def step(self, ids):
                    out = self._jstep(ids)
                    self._logits = out

                def sample(self):
                    return self._logits.numpy()
        """)
        assert rules_of(fs) == ["host-sync-in-traced"]
        assert "self._logits" in fs[0].message

    def test_near_miss_self_attr_reassigned_non_dispatch_clean(self):
        # an attribute REBOUND from host data anywhere in the class is
        # conservatively cleared: method order is unknowable statically
        fs = run("""
            import jax
            import numpy as np

            class Eng:
                def __init__(self, f):
                    self._jstep = jax.jit(f)
                    self._last = None

                def step(self, ids):
                    self._last = self._jstep(ids)

                def reset(self, ids):
                    self._last = list(ids)

                def result(self):
                    return np.asarray(self._last)
        """)
        assert fs == []

    def test_near_miss_self_attr_never_dispatch_clean(self):
        # host-only attributes fetched with numpy stay clean
        fs = run("""
            import jax
            import numpy as np

            class Eng:
                def __init__(self, f, table):
                    self._jstep = jax.jit(f)
                    self._table = table

                def lookup(self):
                    return np.asarray(self._table)
        """)
        assert fs == []

    def test_near_miss_other_class_attr_clean(self):
        # the dispatch-carrying attribute lives on Eng; a different
        # class fetching its own same-named attribute is unrelated
        fs = run("""
            import jax
            import numpy as np

            class Eng:
                def __init__(self, f):
                    self._jstep = jax.jit(f)

                def step(self, ids):
                    self._last = self._jstep(ids)

            class Logger:
                def __init__(self, rows):
                    self._last = rows

                def flush(self):
                    return np.asarray(self._last)
        """)
        assert fs == []

    def test_cross_method_tuple_elementwise_tracked(self):
        # `self._k, self._v = k, v` with dispatch-carrying locals binds
        # both attributes elementwise
        fs = run("""
            import jax

            class Eng:
                def __init__(self, f):
                    self._jstep = jax.jit(f)

                def step(self, ids):
                    logits, k, v = self._jstep(ids)
                    self._k, self._v = k, v
                    return logits

                def swap_out(self):
                    return self._k.numpy(), self._v.numpy()
        """)
        assert rules_of(fs) == ["host-sync-in-traced"] * 2


# ---------------------------------------------------------------------------
# use-after-donate
# ---------------------------------------------------------------------------
class TestUseAfterDonate:
    def test_read_after_donation_flagged(self):
        fs = run("""
            import jax

            def go(f, x, y):
                step = jax.jit(f, donate_argnums=(0,))
                out = step(x, y)
                return x.sum()
        """)
        assert rules_of(fs) == ["use-after-donate"]
        assert "'x'" in fs[0].message

    def test_self_attr_binding_cross_method(self):
        fs = run("""
            import jax

            class Eng:
                def __init__(self, f, cache):
                    self._step = jax.jit(f, donate_argnums=(1,))
                    self._cache = cache

                def run(self, a):
                    out = self._step(a, self._cache)
                    return self._cache
        """)
        assert rules_of(fs) == ["use-after-donate"]
        assert "self._cache" in fs[0].message

    def test_conditional_donate_argnums_union(self):
        fs = run("""
            import jax

            def go(f, x, donate):
                step = jax.jit(f, donate_argnums=(0,) if donate else ())
                out = step(x)
                return x + 1
        """)
        assert rules_of(fs) == ["use-after-donate"]

    def test_near_miss_reassigned_before_reuse_clean(self):
        fs = run("""
            import jax

            def go(f, x):
                step = jax.jit(f, donate_argnums=(0,))
                x = step(x)
                return x + 1
        """)
        assert fs == []

    def test_near_miss_same_statement_rebind_clean(self):
        # the engine.py idiom: donated caches rebound by the same stmt
        fs = run("""
            import jax

            class Eng:
                def __init__(self, f):
                    self._jstep = jax.jit(f, donate_argnums=(0, 1))

                def step(self):
                    self._k, self._v = self._jstep(self._k, self._v)
                    return self._k
        """)
        assert fs == []

    def test_near_miss_else_branch_not_poisoned(self):
        # if/else are mutually exclusive: a donation in the `if` arm
        # must not kill the name for the `else` arm
        fs = run("""
            import jax

            def go(f, x, fast):
                step = jax.jit(f, donate_argnums=(0,))
                if fast:
                    y = step(x)
                else:
                    y = x + 1
                    z = x * 2
                return y
        """)
        assert fs == []

    def test_use_after_either_branch_donation_flagged(self):
        fs = run("""
            import jax

            def go(f, x, fast):
                step = jax.jit(f, donate_argnums=(0,))
                if fast:
                    y = step(x)
                else:
                    y = x + 1
                return x.sum()
        """)
        assert rules_of(fs) == ["use-after-donate"]

    def test_dead_name_passed_to_another_dispatch_flagged(self):
        # jax raises 'Array has been deleted' when a dead buffer feeds
        # ANY later dispatch, not just host code
        fs = run("""
            import jax

            def go(f, g, x):
                step = jax.jit(f, donate_argnums=(0,))
                other = jax.jit(g)
                y = step(x)
                return other(x)
        """)
        assert rules_of(fs) == ["use-after-donate"]

    def test_near_miss_undonated_jit_clean(self):
        fs = run("""
            import jax

            def go(f, x):
                step = jax.jit(f)
                out = step(x)
                return x + 1
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# trace-time-impurity
# ---------------------------------------------------------------------------
class TestTraceImpurity:
    def test_time_random_environ_in_traced(self):
        fs = run("""
            import jax
            import os
            import time
            import numpy as np

            @jax.jit
            def f(x):
                t = time.time()
                r = np.random.randn(3)
                e = os.environ["SEED"]
                g = os.environ.get("SEED2")
                return x * t
        """)
        assert rules_of(fs) == ["trace-time-impurity"] * 4

    def test_closure_mutation_in_traced(self):
        fs = run("""
            import jax

            losses = []
            cache = {}

            @jax.jit
            def f(x):
                losses.append(x)
                cache["last"] = x
                return x
        """)
        assert rules_of(fs) == ["trace-time-impurity"] * 2

    def test_scan_body_is_traced(self):
        fs = run("""
            import time

            import jax

            def body(carry, x):
                return carry + time.time(), None

            def run(xs):
                return jax.lax.scan(body, 0.0, xs)
        """)
        assert rules_of(fs) == ["trace-time-impurity"]

    def test_near_miss_host_side_impurity_clean(self):
        fs = run("""
            import time
            import numpy as np

            def profile_step(fn):
                t0 = time.time()
                events = []
                events.append(fn())
                return time.time() - t0, np.random.rand()
        """)
        assert fs == []

    def test_nested_helper_local_does_not_mask_closure_mutation(self):
        # `hits` is bound only inside the nested helper: the OUTER
        # body's append is still a closure mutation
        fs = run("""
            import jax

            hits = []

            @jax.jit
            def step(x):
                def helper(y):
                    hits = [y]
                    return hits
                hits.append(x)
                return helper(x)
        """)
        assert rules_of(fs) == ["trace-time-impurity"]
        assert "hits.append" in fs[0].snippet

    def test_near_miss_local_list_in_traced_clean(self):
        fs = run("""
            import jax

            @jax.jit
            def f(xs):
                acc = []
                for x in xs:
                    acc.append(x * 2)  # local: trace-time unrolling, fine
                return acc
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# tensor-bool-branch
# ---------------------------------------------------------------------------
class TestTensorBool:
    def test_if_and_while_on_tensor(self):
        fs = run("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                y = jnp.sum(x)
                if y > 0:
                    y = y * 2
                while y < 10:
                    y = y + 1
                return y
        """)
        assert rules_of(fs) == ["tensor-bool-branch"] * 2

    def test_taint_through_arithmetic_and_methods(self):
        fs = run("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                nf = jnp.any(jnp.isnan(x))
                flag = nf | jnp.any(jnp.isinf(x))
                if flag:
                    return x * 0
                return x
        """)
        assert rules_of(fs) == ["tensor-bool-branch"]

    def test_near_miss_host_flag_clean(self):
        fs = run("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x, training):
                if training:          # host param: static under jit
                    x = x * 2
                y = jnp.sum(x)
                if y is None:         # identity test is host-safe
                    return x
                if x.ndim > 1:        # static attr, not a tracer
                    return y
                return y
        """)
        assert fs == []

    def test_for_loop_target_inherits_taint(self):
        fs = run("""
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(xs):
                grads = jnp.split(xs, 2)
                for g in grads:
                    if g.sum() > 0:
                        return g
                return xs
        """)
        assert rules_of(fs) == ["tensor-bool-branch"]

    def test_near_miss_untraced_function_clean(self):
        fs = run("""
            import jax.numpy as jnp

            def host_filter(x):
                y = jnp.sum(x)
                if y > 0:   # eager host code: legal (blocking) sync
                    return y
                return -y
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# counter-provider-leak
# ---------------------------------------------------------------------------
class TestCounterLeak:
    def test_register_without_unregister_flagged(self):
        fs = run("""
            from paddle_tpu import profiler

            class Metrics:
                def __init__(self):
                    profiler.register_counter_provider("m/x", lambda: 1)
        """)
        assert rules_of(fs) == ["counter-provider-leak"]

    def test_near_miss_weakref_finalize_clean(self):
        fs = run("""
            import weakref

            from paddle_tpu import profiler

            class Metrics:
                def __init__(self, owner):
                    profiler.register_counter_provider("m/x", lambda: 1)
                    weakref.finalize(
                        owner, profiler.unregister_counter_provider,
                        "m/x")
        """)
        assert fs == []

    def test_near_miss_direct_unregister_clean(self):
        fs = run("""
            from paddle_tpu.profiler import (
                register_counter_provider, unregister_counter_provider,
            )

            def attach(name):
                register_counter_provider(name, lambda: 0)

            def detach(name):
                unregister_counter_provider(name)
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# block-until-ready-in-loop
# ---------------------------------------------------------------------------
class TestBlockUntilReadyLoop:
    def test_function_spelling_in_for_loop(self):
        fs = run("""
            import jax

            def train(data, step, state):
                for batch in data:
                    state = step(state, batch)
                    jax.block_until_ready(state)
                return state
        """)
        assert rules_of(fs) == ["block-until-ready-in-loop"]
        assert "EVERY iteration" in fs[0].message

    def test_method_spelling_in_while_loop(self):
        fs = run("""
            def drain(q):
                while q:
                    out = q.pop()
                    out.block_until_ready()
        """)
        assert rules_of(fs) == ["block-until-ready-in-loop"]

    def test_comprehension_counts_as_loop(self):
        fs = run("""
            import jax

            def collect(outs):
                return [jax.block_until_ready(o) for o in outs]
        """)
        assert rules_of(fs) == ["block-until-ready-in-loop"]

    def test_near_miss_sync_after_loop_clean(self):
        # the fix pattern itself: one sync on the final value
        fs = run("""
            import jax

            def train(data, step, state):
                for batch in data:
                    state = step(state, batch)
                jax.block_until_ready(state)
                return state
        """)
        assert fs == []

    def test_near_miss_def_inside_loop_clean(self):
        # a function DEFINED under a loop is not executed per
        # iteration; flagging it would poison every closure factory
        fs = run("""
            import jax

            def make_waiters(arrays):
                waiters = []
                for a in arrays:
                    def wait(a=a):
                        jax.block_until_ready(a)
                    waiters.append(wait)
                return waiters
        """)
        assert fs == []

    def test_suppression_with_reason_honored(self):
        fs = run("""
            import jax

            def probe_loop(q):
                while True:
                    arrays = q.get()
                    jax.block_until_ready(arrays)  # tpulint: disable=block-until-ready-in-loop (prober parks on purpose)
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------
class TestSuppressions:
    def test_inline_with_reason_silences(self):
        fs = run("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)  # tpulint: disable=host-sync-in-traced (fixture: testing the suppression path)
        """)
        assert fs == []

    def test_standalone_comment_covers_next_line(self):
        fs = run("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                # tpulint: disable=host-sync-in-traced (fixture reason)
                return np.asarray(x)
        """)
        assert fs == []

    def test_suppression_on_last_line_of_wrapped_statement(self):
        # auto-formatters wrap long lines: a trailing comment lands on
        # the statement's LAST physical line, which must still cover
        # the finding anchored at its first
        fs = run("""
            import jax
            import numpy as np

            @jax.jit
            def f(out):
                host = np.asarray(
                    out)  # tpulint: disable=host-sync-in-traced (fixture: wrapped stmt)
                return host
        """)
        assert fs == []

    def test_missing_reason_is_bad_suppression(self):
        fs = run("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)  # tpulint: disable=host-sync-in-traced
        """)
        assert rules_of(fs) == ["bad-suppression"]
        assert "reason" in fs[0].message

    def test_unknown_rule_is_bad_suppression(self):
        fs = run("""
            x = 1  # tpulint: disable=no-such-rule (whatever)
        """)
        assert rules_of(fs) == ["bad-suppression"]
        assert "no-such-rule" in fs[0].message

    def test_reason_may_contain_parentheses(self):
        fs = run("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)  # tpulint: disable=host-sync-in-traced (see PR (2) notes)
        """)
        assert fs == []

    def test_docstring_mention_is_not_a_live_suppression(self):
        # documentation of the syntax inside a string literal must not
        # register (nor report bad-suppression for a reasonless example)
        fs = run('''
            def helper():
                """Docs: silence with  # tpulint: disable=host-sync-in-traced
                on the offending line."""
                return 1
        ''')
        assert fs == []

    def test_stacked_standalone_suppressions_all_apply(self):
        body = """
            import jax
            import numpy as np

            def go(f, x):
                step = jax.jit(f, donate_argnums=(0,))
                y = step(x)
                {s1}
                {s2}
                return np.asarray(y) + x.sum()
        """
        # unsuppressed: one finding per rule on the return line
        fs = run(body.format(s1="pass", s2="pass"))
        assert sorted(rules_of(fs)) == ["host-sync-in-traced",
                                        "use-after-donate"]
        # two stacked standalone disables both apply to the statement
        fs = run(body.format(
            s1="# tpulint: disable=use-after-donate (fixture: stack 1)",
            s2="# tpulint: disable=host-sync-in-traced (fixture: stack "
               "2)"))
        assert fs == []

    def test_wrong_rule_does_not_silence(self):
        fs = run("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return np.asarray(x)  # tpulint: disable=use-after-donate (wrong rule on purpose)
        """)
        assert rules_of(fs) == ["host-sync-in-traced"]


# ---------------------------------------------------------------------------
# baseline + CLI
# ---------------------------------------------------------------------------
VIOLATING = """
import jax
import numpy as np

@jax.jit
def f(x):
    return np.asarray(x)
"""


class TestBaselineAndCli:
    def _write(self, tmp_path, name="bad.py", body=VIOLATING):
        p = tmp_path / name
        p.write_text(body)
        return str(p)

    def test_exit_codes_and_text_output(self, tmp_path, capsys):
        path = self._write(tmp_path)
        assert cli_main([path]) == 1
        out = capsys.readouterr().out
        assert "host-sync-in-traced" in out
        clean = self._write(tmp_path, "clean.py", "x = 1\n")
        assert cli_main([clean]) == 0
        assert cli_main([]) == 2
        assert cli_main([str(tmp_path / "missing.py")]) == 2
        assert cli_main([path, "--disable", "typo-rule"]) == 2

    def test_json_format(self, tmp_path, capsys):
        path = self._write(tmp_path)
        assert cli_main([path, "--format=json"]) == 1
        data = json.loads(capsys.readouterr().out)
        assert data["count"] == 1
        f = data["findings"][0]
        assert f["rule"] == "host-sync-in-traced"
        assert f["path"] == path
        assert f["line"] == 7

    def test_baseline_roundtrip(self, tmp_path, capsys):
        path = self._write(tmp_path)
        base = str(tmp_path / "baseline.json")
        assert cli_main([path, "--baseline", base,
                         "--write-baseline"]) == 0
        capsys.readouterr()
        # existing violation is baselined -> clean exit
        assert cli_main([path, "--baseline", base]) == 0
        out = capsys.readouterr().out
        assert "suppressed by baseline" in out
        # a NEW violation still fails even with the baseline
        with open(path, "a") as fh:
            fh.write("\n\n@jax.jit\ndef g(x):\n    return x.item()\n")
        assert cli_main([path, "--baseline", base]) == 1

    def test_baseline_survives_line_shifts(self, tmp_path):
        path = self._write(tmp_path)
        base = str(tmp_path / "baseline.json")
        findings = analyze_paths([path])
        write_baseline(base, findings)
        # prepend unrelated lines: fingerprints hash line TEXT, not
        # numbers
        body = open(path).read()
        with open(path, "w") as fh:
            fh.write("# a new header comment\nimport os  # noqa\n" + body)
        assert cli_main([path, "--baseline", base]) == 0
        assert len(load_baseline(base)) == 1

    def test_list_rules(self, capsys):
        assert cli_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for name in get_rules():
            assert name in out

    def test_disable_rule(self, tmp_path):
        path = self._write(tmp_path)
        assert cli_main([path, "--disable",
                         "host-sync-in-traced"]) == 0

    def test_parse_error_reported_not_raised(self, tmp_path):
        path = self._write(tmp_path, "broken.py", "def f(:\n")
        fs = analyze_paths([path])
        assert rules_of(fs) == ["parse-error"]

    def test_non_utf8_file_reported_not_raised(self, tmp_path):
        bad = tmp_path / "latin1.py"
        bad.write_bytes("x = '\xe9'\n".encode("latin-1"))
        good = self._write(tmp_path, "ok.py", "x = 1\n")
        fs = analyze_paths([str(bad), good])
        assert rules_of(fs) == ["parse-error"]
        assert "cannot read" in fs[0].message
        assert cli_main([str(tmp_path)]) == 1  # reported, not crashed


# ---------------------------------------------------------------------------
# unlocked-shared-state (lockcheck)
# ---------------------------------------------------------------------------
class TestUnlockedSharedState:
    def test_thread_writes_main_reads_no_lock(self):
        fs = run("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._loop,
                                               daemon=True)

                def start(self):
                    self._t.start()

                def _loop(self):
                    while True:
                        self.count += 1

                def snapshot(self):
                    return self.count
        """)
        assert rules_of(fs) == ["unlocked-shared-state"]
        assert "count" in fs[0].message
        assert "thread:_loop" in fs[0].message

    def test_near_miss_lock_on_both_sides_clean(self):
        fs = run("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._lock = threading.Lock()
                    self._t = threading.Thread(target=self._loop,
                                               daemon=True)

                def start(self):
                    self._t.start()

                def _loop(self):
                    while True:
                        with self._lock:
                            self.count += 1

                def snapshot(self):
                    with self._lock:
                        return self.count
        """)
        assert fs == []

    def test_near_miss_read_only_shared_attr_clean(self):
        # both roots only READ the attr: no write, no race
        fs = run("""
            import threading

            class Worker:
                def __init__(self, cfg):
                    self.cfg = cfg
                    self._t = threading.Thread(target=self._loop,
                                               daemon=True)

                def start(self):
                    self._t.start()

                def _loop(self):
                    print(self.cfg)

                def snapshot(self):
                    return self.cfg
        """)
        assert fs == []

    def test_near_miss_sync_object_attr_clean(self):
        # threading.Event is itself a synchronization primitive
        fs = run("""
            import threading

            class Worker:
                def __init__(self):
                    self._flag = threading.Event()
                    self._t = threading.Thread(target=self._loop,
                                               daemon=True)

                def start(self):
                    self._t.start()

                def _loop(self):
                    self._flag.set()

                def done(self):
                    return self._flag.is_set()
        """)
        assert fs == []

    def test_timer_and_finalizer_count_as_roots(self):
        fs = run("""
            import threading
            import weakref

            class Cache:
                def __init__(self, obj):
                    self.hits = 0
                    weakref.finalize(obj, self._evict)
                    self._timer = threading.Timer(5.0, self._tick)

                def _evict(self):
                    self.hits = 0

                def _tick(self):
                    self.hits += 1

                def lookup(self):
                    self.hits += 1
        """)
        assert rules_of(fs) == ["unlocked-shared-state"]

    def test_peer_listener_unlocked_inbox_flagged(self):
        # the peer-listener concurrency root pattern (ISSUE 15): an
        # accept-loop thread staging frames into an inbox dict the
        # service loop pops from — unlocked, that's a real race
        fs = run("""
            import threading

            class Listener:
                def __init__(self):
                    self._inbox = {}
                    self._t = threading.Thread(target=self._serve,
                                               daemon=True)
                    self._t.start()

                def _serve(self):
                    while True:
                        self._inbox = dict(self._inbox, t1=b"frame")

                def take(self, ticket_id):
                    return self._inbox.pop(ticket_id, None)
        """)
        assert rules_of(fs) == ["unlocked-shared-state"]
        assert "_inbox" in fs[0].message

    def test_peer_listener_locked_inbox_clean(self):
        # near miss: the shipped PeerListener discipline — every inbox
        # touch under one lock, socket IO outside it — is clean
        fs = run("""
            import threading

            class Listener:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._inbox = {}
                    self._t = threading.Thread(target=self._serve,
                                               daemon=True)
                    self._t.start()

                def _serve(self):
                    while True:
                        with self._lock:
                            self._inbox = dict(self._inbox, t1=b"f")

                def take(self, ticket_id):
                    with self._lock:
                        return self._inbox.pop(ticket_id, None)
        """)
        assert fs == []

    def test_suppression_with_reason_honored(self):
        fs = run("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._loop,
                                               daemon=True)

                def start(self):
                    self._t.start()

                def _loop(self):
                    self.count += 1  # tpulint: disable=unlocked-shared-state (joined before any read)

                def snapshot(self):
                    return self.count
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# lock-order-cycle
# ---------------------------------------------------------------------------
class TestLockOrderCycle:
    def test_inverted_pair_flagged(self):
        fs = run("""
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def ba(self):
                    with self._b:
                        with self._a:
                            pass
        """)
        assert rules_of(fs) == ["lock-order-cycle"]
        assert "->" in fs[0].message

    def test_near_miss_consistent_order_clean(self):
        fs = run("""
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def ab2(self):
                    with self._a:
                        with self._b:
                            pass
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------
class TestBlockingUnderLock:
    def test_sleep_inside_with_lock(self):
        fs = run("""
            import threading
            import time

            class Poller:
                def __init__(self):
                    self._lock = threading.Lock()

                def poll(self):
                    with self._lock:
                        time.sleep(1.0)
        """)
        assert rules_of(fs) == ["blocking-under-lock"]
        assert "_lock" in fs[0].message

    def test_store_rpc_inside_registry_lock(self):
        fs = run("""
            import threading

            class Registry:
                def __init__(self, store):
                    self._lock = threading.Lock()
                    self._store = store

                def publish(self, k, v):
                    with self._lock:
                        self._store.set(k, v)
        """)
        assert rules_of(fs) == ["blocking-under-lock"]

    def test_near_miss_sleep_after_release_clean(self):
        fs = run("""
            import threading
            import time

            class Poller:
                def __init__(self):
                    self._lock = threading.Lock()

                def poll(self):
                    with self._lock:
                        x = 1
                    time.sleep(1.0)
        """)
        assert fs == []

    def test_lease_renew_store_write_under_lock(self):
        # the trap the replicated control plane's LeaseStore avoids by
        # being lock-free: a store write (an RPC on FileStore/TCPStore
        # backends) inside the lease mutex would serialize every
        # renew-before-emit on the slowest store round-trip
        fs = run("""
            import threading

            class LockedLeaseStore:
                def __init__(self, store):
                    self._lock = threading.Lock()
                    self._store = store
                    self._seq = {}

                def renew(self, rid, rec):
                    with self._lock:
                        self._seq[rid] = self._seq.get(rid, 0) + 1
                        rec["seq"] = self._seq[rid]
                        self._store.set(rid, rec)
        """)
        assert rules_of(fs) == ["blocking-under-lock"]
        assert "self._store.set()" in fs[0].message
        assert "LockedLeaseStore._lock" in fs[0].message

    def test_near_miss_lease_seq_under_lock_write_after_clean(self):
        # the correct shape: bump the sequence under the lock, release,
        # THEN do the store round-trip with the captured value
        fs = run("""
            import threading

            class LeaseStore:
                def __init__(self, store):
                    self._lock = threading.Lock()
                    self._store = store
                    self._seq = {}

                def renew(self, rid, rec):
                    with self._lock:
                        self._seq[rid] = self._seq.get(rid, 0) + 1
                        seq = self._seq[rid]
                    rec["seq"] = seq
                    self._store.set(rid, rec)
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# signal-handler-unsafe
# ---------------------------------------------------------------------------
class TestSignalHandlerUnsafe:
    def test_store_rpc_in_handler(self):
        fs = run("""
            import signal

            class Mon:
                def __init__(self, store):
                    self._store = store

                def install(self):
                    signal.signal(signal.SIGTERM, self._on_term)

                def _on_term(self, signum, frame):
                    self._store.set("preempt", "1")
        """)
        assert rules_of(fs) == ["signal-handler-unsafe"]
        assert "_on_term" in fs[0].message

    def test_lock_acquire_in_handler_callee(self):
        # reached transitively: handler -> self._record() -> with lock
        fs = run("""
            import signal
            import threading

            class Mon:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def install(self):
                    signal.signal(signal.SIGTERM, self._on_term)

                def _on_term(self, signum, frame):
                    self._record()

                def _record(self):
                    with self._lock:
                        self.n += 1
        """)
        assert "signal-handler-unsafe" in rules_of(fs)

    def test_near_miss_flag_only_handler_clean(self):
        fs = run("""
            import signal
            import threading

            class Mon:
                def __init__(self):
                    self._flag = threading.Event()

                def install(self):
                    signal.signal(signal.SIGTERM, self._on_term)

                def _on_term(self, signum, frame):
                    self._flag.set()
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# collective-divergence
# ---------------------------------------------------------------------------
class TestCollectiveDivergence:
    def test_psum_under_rank_branch_in_shard_map(self):
        fs = run("""
            import jax
            from jax import lax
            from jax.experimental.shard_map import shard_map

            def body(x):
                if lax.axis_index("dp") == 0:
                    x = lax.psum(x, "dp")
                return x

            f = shard_map(body, mesh=None, in_specs=None,
                          out_specs=None)
        """)
        assert "collective-divergence" in rules_of(fs)
        f = [x for x in fs if x.rule == "collective-divergence"][0]
        assert "psum" in f.message and "deadlock" in f.message

    def test_collective_inside_cond_branch(self):
        fs = run("""
            import jax
            from jax import lax

            @jax.jit
            def step(x, p):
                def tru(x):
                    return lax.psum(x, "dp")
                def fls(x):
                    return x
                return lax.cond(p, tru, fls, x)
        """)
        assert rules_of(fs) == ["collective-divergence"]
        assert "lax.cond" in fs[0].message

    def test_near_miss_hoisted_collective_clean(self):
        # the fix pattern: every rank issues the collective
        fs = run("""
            import jax
            from jax import lax
            from jax.experimental.shard_map import shard_map

            def body(x):
                s = lax.psum(x, "dp")
                return s

            f = shard_map(body, mesh=None, in_specs=None,
                          out_specs=None)
        """)
        assert fs == []

    def test_near_miss_host_static_branch_clean(self):
        # `if causal:` is a Python bool closed over at trace time —
        # every rank traces the same arm
        fs = run("""
            import jax
            from jax import lax
            from jax.experimental.shard_map import shard_map

            def make(causal):
                def body(x):
                    if causal:
                        x = lax.psum(x, "dp")
                    return x
                return shard_map(body, mesh=None, in_specs=None,
                                 out_specs=None)
        """)
        assert fs == []

    def test_near_miss_host_code_clean(self):
        # no traced scope at all: a collective name in host code is
        # someone else's problem (it would fail loudly anyway)
        fs = run("""
            from jax import lax

            def host(x, rank):
                if rank == 0:
                    return lax.psum(x, "dp")
                return x
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# finish-reason-literal
# ---------------------------------------------------------------------------
class TestFinishReasonLiteral:
    def test_unknown_literal_in_abort_call(self):
        fs = run("""
            from paddle_tpu.serving.request import Request

            def kill(eng, rid):
                eng.abort(rid, "expire")
        """)
        assert rules_of(fs) == ["finish-reason-literal"]
        assert "'expire'" in fs[0].message

    def test_unknown_literal_in_assignment_and_kwarg(self):
        fs = run("""
            from paddle_tpu.serving.request import Request

            def finish(req, eng, rid):
                req.finish_reason = "aborted:oom"
                eng._finalize(rid, finish_reason="done")
        """)
        assert rules_of(fs) == ["finish-reason-literal"] * 2

    def test_near_miss_vocabulary_literal_clean(self):
        fs = run("""
            from paddle_tpu.serving.request import Request

            def kill(eng, rid):
                eng.abort(rid, "aborted:user")
        """)
        assert fs == []

    def test_near_miss_module_without_serving_import_clean(self):
        # the vocabulary only applies where serving.request is in play
        fs = run("""
            def kill(eng, rid):
                eng.abort(rid, "expire")
        """)
        assert fs == []


# ---------------------------------------------------------------------------
# lockcheck rules: baseline + CLI integration
# ---------------------------------------------------------------------------
RACY = """import threading


class Worker:
    def __init__(self):
        self.count = 0
        self._t = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._t.start()

    def _loop(self):
        self.count += 1

    def snapshot(self):
        return self.count
"""


class TestLockcheckBaselineAndCli:
    def test_new_rule_findings_baseline_roundtrip(self, tmp_path, capsys):
        path = tmp_path / "racy.py"
        path.write_text(RACY)
        base = str(tmp_path / "baseline.json")
        assert cli_main([str(path)]) == 1
        capsys.readouterr()
        assert cli_main([str(path), "--baseline", base,
                         "--write-baseline"]) == 0
        capsys.readouterr()
        assert cli_main([str(path), "--baseline", base]) == 0

    def test_only_flag_restricts_rule_set(self, tmp_path, capsys):
        path = tmp_path / "racy.py"
        path.write_text(RACY)
        assert cli_main([str(path), "--only",
                         "unlocked-shared-state"]) == 1
        out = capsys.readouterr().out
        assert "unlocked-shared-state" in out
        assert cli_main([str(path), "--only", "lock-order-cycle"]) == 0
        assert cli_main([str(path), "--only", "typo-rule"]) == 2

    def test_only_does_not_hide_bad_suppressions(self, tmp_path):
        # meta rules stay active under --only: a reasonless suppression
        # must not sneak in through a narrowed lint run
        path = tmp_path / "sup.py"
        path.write_text(
            "import os\n"
            "x = os.getpid()  # tpulint: disable=host-sync-in-traced\n")
        assert cli_main([str(path), "--only", "lock-order-cycle"]) == 1

    def test_write_baseline_order_independent(self, tmp_path):
        """Identical trees must produce byte-identical baselines no
        matter how the caller ordered the findings (occurrence
        numbering is order-sensitive without the internal sort)."""
        path = tmp_path / "racy.py"
        # two identical racy lines -> identical snippets -> occurrence
        # disambiguation kicks in
        path.write_text(RACY.replace(
            "        self.count += 1\n",
            "        self.count += 1\n        self.count += 1\n"))
        findings = analyze_paths([str(path)])
        assert len(findings) >= 1
        b1, b2 = str(tmp_path / "b1.json"), str(tmp_path / "b2.json")
        write_baseline(b1, findings)
        write_baseline(b2, list(reversed(findings)))
        assert open(b1).read() == open(b2).read()


# ---------------------------------------------------------------------------
# leaked-resource-on-raise (flowcheck)
# ---------------------------------------------------------------------------
class TestLeakedResource:
    def test_pr14_import_kv_scatter_leak_flagged(self):
        """Re-introducing the PR 14 bug — blocks landed, scatter faults,
        no rollback — must be caught at commit time, not by chaos."""
        fs = run("""
            class Engine:
                def import_kv(self, request_id, blocks, frames):
                    self.block_manager.import_blocks(request_id, blocks)
                    self._scatter(frames)
                    self.sessions[request_id] = blocks
        """)
        assert rules_of(fs) == ["leaked-resource-on-raise"]
        assert "import_blocks" in fs[0].message

    def test_rollback_in_except_then_reraise_clean(self):
        """The PR 14 FIX shape: release in the handler, re-raise."""
        fs = run("""
            class Engine:
                def import_kv(self, request_id, blocks, frames):
                    self.block_manager.import_blocks(request_id, blocks)
                    try:
                        self._scatter(frames)
                    except Exception:
                        self.block_manager.free(request_id)
                        raise
                    self.sessions[request_id] = blocks
        """)
        assert rules_of(fs) == []

    def test_release_in_finally_clean(self):
        fs = run("""
            class Probe:
                def measure(self, request_id):
                    self.block_manager.allocate(request_id, 4)
                    try:
                        self._touch(request_id)
                    finally:
                        self.block_manager.free(request_id)
        """)
        assert rules_of(fs) == []

    def test_swallowing_handler_releases_clean(self):
        fs = run("""
            class Sched:
                def admit(self, req):
                    self.block_manager.allocate(req.request_id, 4)
                    try:
                        self._kick()
                    except Exception:
                        self.block_manager.free(req.request_id)
                        return
                    self.running.append(req)
        """)
        assert rules_of(fs) == []

    def test_conditional_release_still_flagged(self):
        """A release under only one branch does not cover the raise
        edge — held-on-any-path merging."""
        fs = run("""
            class Sched:
                def admit(self, req, ok):
                    self.block_manager.allocate(req.request_id, 4)
                    if ok:
                        self.block_manager.free(req.request_id)
                    self._kick()
        """)
        assert rules_of(fs) == ["leaked-resource-on-raise"]

    def test_transfer_before_fallible_call_clean(self):
        fs = run("""
            class Sched:
                def admit(self, req):
                    self.block_manager.allocate(req.request_id, 4)
                    self.running.append(req)
                    self._kick()
        """)
        assert rules_of(fs) == []

    def test_swap_out_host_slots_pairing(self):
        fs = run("""
            class Sched:
                def evict(self, victim):
                    self.block_manager.swap_out(victim.request_id, 2)
                    self._copy(victim)
                    self.swapped.append(victim)
        """)
        assert rules_of(fs) == ["leaked-resource-on-raise"]
        assert "swap_out" in fs[0].message


# ---------------------------------------------------------------------------
# counter-snapshot-drift (flowcheck)
# ---------------------------------------------------------------------------
class TestCounterDrift:
    def test_bumped_but_never_read_flagged(self):
        fs = run_at("""
            class Sched:
                def step(self):
                    self.num_zz_invisible_counter += 1
        """, "paddle_tpu/serving/fixture.py")
        assert rules_of(fs) == ["counter-snapshot-drift"]
        assert "num_zz_invisible_counter" in fs[0].message

    def test_counter_with_real_reader_clean(self):
        # num_swap_outs is surfaced by the serving metrics layer
        fs = run_at("""
            class Sched:
                def step(self):
                    self.num_swap_outs += 1
        """, "paddle_tpu/serving/fixture.py")
        assert rules_of(fs) == []

    def test_out_of_scope_module_ignored(self):
        fs = run_at("""
            class Opt:
                def step(self):
                    self.num_zz_invisible_counter += 1
        """, "paddle_tpu/optimizer/fixture.py")
        assert rules_of(fs) == []

    def test_gauge_without_getter_flagged(self):
        fs = run_at("""
            class M:
                GAUGES = ("good", "orphan")
                _E_GAUGES = {"good": lambda e: e.num_swap_outs}
        """, "paddle_tpu/serving/fixture_metrics.py")
        assert rules_of(fs) == ["counter-snapshot-drift"]
        assert "orphan" in fs[0].message

    def test_getter_key_missing_from_gauges_flagged(self):
        fs = run_at("""
            class M:
                GAUGES = ("good",)
                _E_GAUGES = {"good": lambda e: e.num_swap_outs,
                             "stray": lambda e: e.num_swap_outs}
        """, "paddle_tpu/serving/fixture_metrics.py")
        assert rules_of(fs) == ["counter-snapshot-drift"]
        assert "stray" in fs[0].message

    def test_ghost_gauge_flagged(self):
        fs = run_at("""
            class M:
                GAUGES = ("g",)
                _E_GAUGES = {"g": lambda e: e.num_zz_ghost_counter}
        """, "paddle_tpu/serving/fixture_metrics.py")
        assert rules_of(fs) == ["counter-snapshot-drift"]
        assert "never assigned" in fs[0].message

    def test_coherent_metrics_class_clean(self):
        fs = run_at("""
            class M:
                GAUGES = ("g", "chain")
                _E_GAUGES = {"g": lambda e: e.num_swap_outs}

                def provider(self, name):
                    if name == "chain":
                        return 0
        """, "paddle_tpu/serving/fixture_metrics.py")
        assert rules_of(fs) == []


# ---------------------------------------------------------------------------
# fault-point-literal (flowcheck)
# ---------------------------------------------------------------------------
class TestFaultPointLiteral:
    def test_raw_literal_call_site_flagged(self):
        fs = run("""
            from paddle_tpu.testing import faults

            class Engine:
                def step(self):
                    faults.fire("serving.step")
        """)
        assert rules_of(fs) == ["fault-point-literal"]
        assert "serving.step" in fs[0].message

    def test_literal_led_fstring_flagged(self):
        fs = run("""
            from paddle_tpu.testing import faults

            class BM:
                def allocate(self, request_id):
                    faults.check(f"serving.force_oom.{request_id}")
        """)
        assert rules_of(fs) == ["fault-point-literal"]

    def test_registry_constant_forms_clean(self):
        fs = run("""
            from paddle_tpu.testing import faults

            class Engine:
                def step(self, request_id):
                    faults.fire(faults.SERVING_STEP)
                    faults.check(
                        f"{faults.SERVING_FORCE_OOM}.{request_id}")
        """)
        assert rules_of(fs) == []

    def test_unrelated_fire_method_clean(self):
        fs = run("""
            class Trigger:
                def pull(self):
                    self.gun.fire("bang")
        """)
        assert rules_of(fs) == []

    def test_unreferenced_registry_point_flagged(self):
        """Direction 2: a FAULT_POINTS member no test or script ever
        mentions is dead chaos surface."""
        # the coverage corpus includes THIS file, so the dead point's
        # name is assembled at runtime to keep it out of the corpus
        dead = "zz.nobody_" + "ever_installs"
        fs = run(f"""
            ZZ = "{dead}"
            OK = "fleet.slow_replica"
            FAULT_POINTS = frozenset({{ZZ, OK}})
        """)
        assert rules_of(fs) == ["fault-point-literal"]
        assert dead in fs[0].message

    def test_covered_registry_clean(self):
        fs = run("""
            A = "fleet.slow_replica"
            B = "ckpt.committed"
            FAULT_POINTS = frozenset({A, B})
        """)
        assert rules_of(fs) == []


# ---------------------------------------------------------------------------
# rpc-verb-unclassified (flowcheck)
# ---------------------------------------------------------------------------
SERVICER_HEAD = """
    IDEMPOTENT_METHODS = frozenset({"ping"})
    MUTATION_METHODS = frozenset({"step"})

    class WorkerServicer:
        def _dispatch(self, method, args):
            if method == "ping":
                return "pong"
            if method == "step":
                return self.eng.step()
"""


class TestRpcVerbUnclassified:
    def test_unclassified_dispatch_arm_flagged(self):
        # the PR 19 tier_stats shape: dispatched, classified nowhere
        fs = run(SERVICER_HEAD + """\
                if method == "tier_stats":
                    return self.eng.stats()
        """)
        assert rules_of(fs) == ["rpc-verb-unclassified"]
        assert "tier_stats" in fs[0].message

    def test_total_partition_clean(self):
        fs = run(SERVICER_HEAD)
        assert rules_of(fs) == []

    def test_verb_in_both_sets_flagged(self):
        fs = run("""
            IDEMPOTENT_METHODS = frozenset({"ping", "step"})
            MUTATION_METHODS = frozenset({"step"})

            class WorkerServicer:
                def _dispatch(self, method, args):
                    if method == "ping":
                        return "pong"
                    if method == "step":
                        return self.eng.step()
        """)
        assert rules_of(fs) == ["rpc-verb-unclassified"]
        assert "BOTH" in fs[0].message

    def test_stale_set_entry_flagged(self):
        fs = run("""
            IDEMPOTENT_METHODS = frozenset({"ping", "vanished"})
            MUTATION_METHODS = frozenset()

            class WorkerServicer:
                def _dispatch(self, method, args):
                    if method == "ping":
                        return "pong"
        """)
        assert rules_of(fs) == ["rpc-verb-unclassified"]
        assert "vanished" in fs[0].message

    def test_one_sided_partition_flagged(self):
        fs = run("""
            IDEMPOTENT_METHODS = frozenset({"ping"})

            class WorkerServicer:
                def _dispatch(self, method, args):
                    if method == "ping":
                        return "pong"
        """)
        assert rules_of(fs) == ["rpc-verb-unclassified"]
        assert "one-sided" in fs[0].message

    def test_module_without_servicer_clean(self):
        fs = run("""
            IDEMPOTENT_METHODS = frozenset({"stale_but_unchecked"})

            class Plain:
                def _dispatch(self, method, args):
                    return None
        """)
        # Plain is not a *Servicer: the rule stays out of non-RPC code
        assert rules_of(fs) == []


# ---------------------------------------------------------------------------
# unbounded-rpc-deadline (flowcheck)
# ---------------------------------------------------------------------------
class TestRpcDeadline:
    def test_call_without_deadline_flagged(self):
        fs = run("""
            class Handle:
                def ping(self):
                    return self.client.call("ping", {})
        """)
        assert rules_of(fs) == ["unbounded-rpc-deadline"]
        assert "deadline_s" in fs[0].message

    def test_call_with_deadline_clean(self):
        fs = run("""
            class Handle:
                def ping(self):
                    return self.client.call("ping", {}, deadline_s=5.0)
        """)
        assert rules_of(fs) == []

    def test_splat_kwargs_clean(self):
        fs = run("""
            class Handle:
                def ping(self, **kw):
                    return self.rpc_client.call("ping", {}, **kw)
        """)
        assert rules_of(fs) == []

    def test_non_client_receiver_clean(self):
        fs = run("""
            class Handle:
                def ping(self):
                    return self.conn.call("ping", {})
        """)
        assert rules_of(fs) == []

    def test_ticket_without_deadline_ms_flagged(self):
        fs = run("""
            class Router:
                def ship(self, src, dst, rid):
                    return self._issue_ticket(src, dst, rid)
        """)
        assert rules_of(fs) == ["unbounded-rpc-deadline"]
        assert "deadline_ms" in fs[0].message

    def test_ticket_with_deadline_ms_clean(self):
        fs = run("""
            class Router:
                def ship(self, src, dst, rid):
                    return self._issue_ticket(
                        src, dst, rid,
                        deadline_ms=self._rung_deadline_ms(1))
        """)
        assert rules_of(fs) == []


# ---------------------------------------------------------------------------
# flowcheck rules through the CLI: --only, baseline, github, --stats
# ---------------------------------------------------------------------------
LEAKY = textwrap.dedent("""\
    class Engine:
        def import_kv(self, request_id, blocks, frames):
            self.block_manager.import_blocks(request_id, blocks)
            self._scatter(frames)
            self.sessions[request_id] = blocks
""")


class TestFlowcheckCli:
    def test_only_selects_flowcheck_rule(self, tmp_path, capsys):
        p = tmp_path / "leaky.py"
        p.write_text(LEAKY + "\nimport jax\n\n@jax.jit\ndef f(x):\n"
                     "    return x.item()\n")
        assert cli_main([str(p), "--only",
                         "leaked-resource-on-raise"]) == 1
        out = capsys.readouterr().out
        assert "leaked-resource-on-raise" in out
        assert "host-sync-in-traced" not in out

    def test_baseline_roundtrip_flowcheck(self, tmp_path, capsys):
        p = tmp_path / "leaky.py"
        p.write_text(LEAKY)
        base = str(tmp_path / "b.json")
        assert cli_main([str(p), "--baseline", base,
                         "--write-baseline"]) == 0
        capsys.readouterr()
        assert cli_main([str(p), "--baseline", base]) == 0
        # a new leak is NOT absorbed by the old baseline
        p.write_text(LEAKY + textwrap.dedent("""\

            class Probe:
                def grab(self, request_id):
                    self.block_manager.allocate(request_id, 4)
                    self._touch(request_id)
        """))
        assert cli_main([str(p), "--baseline", base]) == 1

    def test_github_format_annotations(self, tmp_path, capsys):
        p = tmp_path / "leaky.py"
        p.write_text(LEAKY)
        assert cli_main([str(p), "--format=github"]) == 1
        out = capsys.readouterr().out
        assert f"::error file={p}," in out
        assert "::leaked-resource-on-raise:" in out
        assert "line=3," in out

    def test_stats_counts_suppressions(self, tmp_path, capsys):
        p = tmp_path / "leaky.py"
        p.write_text(LEAKY.replace(
            "self.block_manager.import_blocks(request_id, blocks)",
            "self.block_manager.import_blocks(request_id, blocks)"
            "  # tpulint: disable=leaked-resource-on-raise (fixture)"))
        assert cli_main([str(p), "--stats"]) == 0
        out = capsys.readouterr().out
        assert "0 finding(s)" in out
        assert "leaked-resource-on-raise" in out
        # the table row shows 0 findings / 1 suppression
        row = [ln for ln in out.splitlines()
               if ln.startswith("leaked-resource-on-raise")][0]
        assert row.split()[-2:] == ["0", "1"]
