"""LLMEngine end-to-end on XLA:CPU (tiny Llama, GQA config).

Pins the PR's acceptance criteria: >= 8 concurrent requests of unequal
lengths served to completion with continuous batching (a late arrival
joins the running batch), paged greedy decode token-identical to the
naive full-recompute ``generate``, and preemption-on-OOM reclaiming
blocks while still completing every request."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    EngineConfig, LLMEngine, SamplingParams,
)


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny()          # 4 heads / 2 KV heads: GQA path
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _naive(model, prompt, max_new):
    ids = paddle.to_tensor(np.asarray([prompt], np.int32))
    out = model.generate(ids, max_new_tokens=max_new, use_cache=False)
    return [int(t) for t in out.numpy()[0][len(prompt):]]


def _prompts(rng, vocab, lens):
    return [list(map(int, rng.integers(0, vocab, size=n))) for n in lens]


def test_prefill_logits_match_naive_forward(tiny_model):
    """One paged prefill == the dense causal forward's last-token
    logits (the compiled serving step computes the same math)."""
    m = tiny_model
    cfg = m.config
    rng = np.random.default_rng(0)
    s, bs, nb = 6, 4, 8
    ids = rng.integers(0, cfg.vocab_size, size=(1, s)).astype(np.int32)
    L = cfg.num_hidden_layers
    kh = cfg.num_key_value_heads
    hd = cfg.hidden_size // cfg.num_attention_heads
    kcs = np.zeros((L, nb, bs, kh, hd), np.float32)
    vcs = np.zeros_like(kcs)
    bt = np.asarray([[0, 1]], np.int32)
    logits, kcs2, vcs2 = m.forward_paged(
        ids, kcs, vcs, bt,
        np.asarray([s], np.int32), np.asarray([0], np.int32),
        np.asarray([s], np.int32))
    ref = m(paddle.to_tensor(ids)).numpy()[:, -1]
    np.testing.assert_allclose(logits.numpy(), ref, rtol=2e-4, atol=2e-4)
    # prefill wrote the cache: the first layer's block 0 is nonzero
    assert float(np.abs(np.asarray(kcs2)[0, 0]).sum()) > 0


def test_e2e_concurrent_unequal_lengths_with_late_arrival(tiny_model):
    """8 unequal-length requests + 1 late arrival that must join the
    already-running batch; every request finishes, every greedy output
    is token-identical to the naive generate."""
    m = tiny_model
    rng = np.random.default_rng(1)
    prompts = _prompts(rng, m.config.vocab_size,
                       [3, 5, 7, 9, 4, 6, 11, 2])
    late_prompt = _prompts(rng, m.config.vocab_size, [5])[0]
    max_new = 6
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=9,
                                    max_model_len=64))
    sp = SamplingParams(max_new_tokens=max_new)
    rids = [eng.add_request(p, sampling=sp) for p in prompts]

    step_outputs = []
    late_rid = None
    while eng.has_unfinished():
        outs = eng.step()
        step_outputs.append(outs)
        if late_rid is None and eng.metrics.decode_steps >= 2:
            assert eng.scheduler.num_running > 0  # batch is mid-flight
            late_rid = eng.add_request(late_prompt, sampling=sp)
    assert late_rid is not None

    # the late request shared at least one decode iteration with an
    # original request — continuous batching, not drain-and-refill
    early = set(rids)
    shared = [outs for outs in step_outputs
              if any(o.request_id == late_rid for o in outs)
              and any(o.request_id in early for o in outs)]
    assert shared, "late arrival never joined the running batch"

    for rid, p in zip(rids + [late_rid], prompts + [late_prompt]):
        req = eng.get_request(rid)
        assert req.is_finished and req.num_generated == max_new
        assert req.generated == _naive(m, p, max_new), rid
    # all KV blocks reclaimed at completion
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
    eng.block_manager.check_invariants()


def test_preemption_on_oom_reclaims_blocks_and_completes(tiny_model):
    """Cache sized so the batch cannot all reach full length: the engine
    must preempt (reclaiming blocks), re-admit, and still produce
    token-identical greedy output for EVERY request."""
    m = tiny_model
    rng = np.random.default_rng(2)
    prompts = _prompts(rng, m.config.vocab_size, [6, 8, 5, 7])
    max_new = 8
    # 10 blocks * 4 slots = 40 token slots < 4 requests * up to 16 tokens
    eng = LLMEngine(m, EngineConfig(block_size=4, num_blocks=10,
                                    max_num_seqs=4, max_model_len=32))
    sp = SamplingParams(max_new_tokens=max_new)
    rids = [eng.add_request(p, sampling=sp) for p in prompts]
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < 500, "engine failed to converge"
        eng.block_manager.check_invariants()
    assert eng.scheduler.num_preemptions > 0, \
        "test config was supposed to force preemption"
    for rid, p in zip(rids, prompts):
        assert eng.get_request(rid).generated == _naive(m, p, max_new)
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks


def test_generate_default_uses_paged_path_and_matches_naive(tiny_model):
    m = tiny_model
    rng = np.random.default_rng(3)
    ids = rng.integers(0, m.config.vocab_size, size=(2, 7)).astype(
        np.int32)
    x = paddle.to_tensor(ids)
    out_paged = m.generate(x, max_new_tokens=5)           # default: paged
    assert getattr(m, "_serving_engine", None) is not None
    out_naive = m.generate(x, max_new_tokens=5, use_cache=False)
    np.testing.assert_array_equal(out_paged.numpy(), out_naive.numpy())
    # engine is cached and reused across calls
    eng = m._serving_engine
    out2 = m.generate(x, max_new_tokens=5)
    assert m._serving_engine is eng
    np.testing.assert_array_equal(out2.numpy(), out_paged.numpy())


def test_streaming_callback_order_and_eos(tiny_model):
    m = tiny_model
    rng = np.random.default_rng(4)
    p = list(map(int, rng.integers(0, m.config.vocab_size, size=5)))
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=64))
    # find the greedy continuation, then replay with its 2nd token as EOS
    first = eng.generate([p], SamplingParams(max_new_tokens=4))[0]
    events = []
    rid = eng.add_request(
        p, sampling=SamplingParams(max_new_tokens=4,
                                   eos_token_id=first[1]),
        callback=lambda r, tok, done: events.append((r, tok, done)))
    eng.run()
    req = eng.get_request(rid)
    assert req.is_finished
    assert [t for _, t, _ in events] == first[:2]  # stopped AT the EOS
    assert [d for _, _, d in events] == [False, True]
    assert all(r == rid for r, _, _ in events)


def test_serving_counters_registered_in_profiler(tiny_model):
    from paddle_tpu import profiler

    m = tiny_model
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=32))
    eng.add_request([1, 2, 3], sampling=SamplingParams(max_new_tokens=2))
    c = profiler.counters()
    mine = {k: v for k, v in c.items()
            if k.startswith("serving/") and k.endswith(f"#{id(eng)}")}
    assert mine[f"serving/queue_depth#{id(eng)}"] == 1
    assert mine[f"serving/kv_block_utilization#{id(eng)}"] == 0.0
    eng.run()
    c = profiler.counters()
    assert c[f"serving/num_waiting#{id(eng)}"] == 0
    assert c[f"serving/tokens_per_sec#{id(eng)}"] > 0
    snap = eng.metrics.snapshot()
    assert snap["num_finished"] == 1
    assert snap["ttft_ms_avg"] > 0


def test_cow_copies_surfaced_by_metrics(tiny_model):
    """BlockManager.num_cow_copies was bumped since PR 13 but surfaced
    by no gauge or snapshot key — the counter-snapshot-drift class."""
    from paddle_tpu import profiler
    from paddle_tpu.serving.metrics import ServingMetrics

    assert "cow_copies" in ServingMetrics.GAUGES
    m = tiny_model
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=32))
    eng.add_request([1, 2, 3], sampling=SamplingParams(max_new_tokens=2))
    eng.run()
    c = profiler.counters()
    assert c[f"serving/cow_copies#{id(eng)}"] == \
        eng.block_manager.num_cow_copies
    assert eng.metrics.snapshot()["serving_cow_copies"] == \
        eng.block_manager.num_cow_copies


def test_engine_admission_validation(tiny_model):
    m = tiny_model
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=16))
    with pytest.raises(ValueError, match="max_model_len"):
        eng.add_request(list(range(1, 15)),
                        sampling=SamplingParams(max_new_tokens=8))
    eng.add_request("dup", [1, 2], SamplingParams(max_new_tokens=2))
    with pytest.raises(ValueError, match="duplicate"):
        eng.add_request("dup", [3, 4], SamplingParams(max_new_tokens=2))


def test_sampled_decode_is_reproducible_per_request(tiny_model):
    """temperature>0 through the engine: per-request RNG streams make
    the same (seed, prompt) reproduce the same tokens."""
    m = tiny_model
    rng = np.random.default_rng(5)
    p = list(map(int, rng.integers(0, m.config.vocab_size, size=4)))
    sp = SamplingParams(max_new_tokens=5, temperature=0.8, top_p=0.9,
                        seed=123)
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=32))
    a = eng.generate([p], sp)[0]
    b = eng.generate([p], sp)[0]
    assert a == b
    assert all(0 <= t < m.config.vocab_size for t in a)


def test_greedy_decode_never_fetches_full_logits(tiny_model):
    """Fully in-graph sampling (ISSUE 11): greedy AND sampled workloads
    ship one packed int row per slot each step and NEVER pull the
    B×vocab logits to host — ``num_logits_fetches`` stays 0 for both."""
    m = tiny_model
    rng = np.random.default_rng(6)
    prompts = _prompts(rng, m.config.vocab_size, [4, 6])
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=32))
    outs = eng.generate(prompts, SamplingParams(max_new_tokens=4))
    assert eng.num_logits_fetches == 0
    assert all(len(o) == 4 for o in outs)
    # sampled decode used to flip to a B×vocab fetch; the in-graph
    # sampler keeps the boundary at B ints
    eng.generate([prompts[0]],
                 SamplingParams(max_new_tokens=3, temperature=0.7,
                                seed=1))
    assert eng.num_logits_fetches == 0
    assert eng.num_sampled_steps > 0


def test_mixed_greedy_and_sampled_batch_parity(tiny_model):
    """A batch mixing greedy and sampled requests runs ONE in-graph
    sampling path (greedy rows one-hot), the greedy request's tokens
    still match the naive generate exactly, and no step fetches
    logits."""
    m = tiny_model
    rng = np.random.default_rng(7)
    pg, ps = _prompts(rng, m.config.vocab_size, [5, 5])
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=32))
    rg = eng.add_request(pg, sampling=SamplingParams(max_new_tokens=4))
    rs = eng.add_request(
        ps, sampling=SamplingParams(max_new_tokens=4, temperature=0.8,
                                    seed=9))
    eng.run()
    assert eng.get_request(rg).generated == _naive(m, pg, 4)
    assert len(eng.get_request(rs).generated) == 4
    assert eng.num_logits_fetches == 0


@pytest.mark.slow
def test_bench_serving_smoke():
    """The bench.py --serving --tiny smoke: BENCH_serving JSON fields
    present and every request completes within the tier budget."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..",
                              "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    out = bench.bench_serving(tiny=True)
    assert out["metric"] == "serving_tokens_per_sec"
    assert out["value"] > 0
    ex = out["extra"]
    assert ex["num_finished"] == 10
    for key in ("ttft_ms_avg", "tpot_ms_avg", "batch_occupancy",
                "kv_block_utilization", "preemptions"):
        assert key in ex
    assert ex["batch_occupancy"] > 0
    # the ISSUE-6 resilience counters ride the JSON, with real traffic
    # from the swap+drain smoke phase
    for key in ("serving_swapped_out", "serving_rejected",
                "serving_expired", "serving_drain_completed"):
        assert key in ex
    smoke = ex["resilience_smoke"]
    assert smoke["serving_swapped_out"] > 0
    assert smoke["serving_swapped_in"] == smoke["serving_swapped_out"]
    assert smoke["serving_drain_completed"] == 1
    # ISSUE-9 ragged-vs-bucketed comparison phase: padding gone, one
    # compiled step, the shared prefix actually hit the COW cache
    cmp = ex["ragged_comparison"]
    assert cmp["ragged_padded_token_frac"] == 0.0
    assert cmp["bucketed_padded_token_frac"] > 0.0
    assert cmp["ragged_compiled_step_shapes"] == 1
    assert cmp["bucketed_compiled_step_shapes"] > 1
    assert cmp["prefix_cache_hits"] > 0
    assert cmp["prefill_chunks"] > 0
    # ISSUE-11 in-graph sampling + speculative phases: both fetchless,
    # the self-draft spec run actually proposed and accepted tokens
    smp = ex["sampled_decode"]
    assert smp["tokens_per_sec"] > 0
    assert smp["sampled_steps"] > 0
    assert smp["logits_fetches"] == 0
    spc = ex["speculative"]
    assert spc["tokens_per_sec"] > 0
    assert spc["spec_proposed"] > 0
    assert spc["spec_accepted"] > 0
    assert 0.0 < spc["spec_acceptance_rate"] <= 1.0
    assert spc["logits_fetches"] == 0
