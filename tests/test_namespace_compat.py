"""Behavior tests for the namespace-completion compat surfaces
(distributed/compat.py, distributed/io.py, incubate/compat.py, static
additions, io/vision/distribution/jit extras) — the review-hardened
contracts, not just symbol existence."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import incubate, io, nn, optimizer, static


def test_alltoall_single_roundtrip():
    out = dist.alltoall_single(paddle.zeros([2]),
                               paddle.to_tensor([1.0, 2.0]))
    np.testing.assert_allclose(out.numpy(), [1.0, 2.0])


def test_gather_and_object_collectives():
    g = dist.gather(paddle.ones([2]))
    assert len(g) == 1
    np.testing.assert_allclose(g[0].numpy(), [1.0, 1.0])
    objs = [{"a": 1}, 7]
    dist.broadcast_object_list(objs)
    assert objs == [{"a": 1}, 7]
    assert dist.is_available()
    assert dist.get_backend() in ("xla", "gloo")
    dist.wait(paddle.ones([2]))


def test_strategy_and_dist_attr():
    s = dist.Strategy()
    assert hasattr(s.sharding, "stage")
    assert hasattr(s.pipeline, "accumulate_steps")
    a = dist.DistAttr(sharding_specs=["x", None])
    assert a.sharding_specs == ["x", None]
    assert dist.ReduceType.kRedSum == "sum"


def test_ps_stack_stubs_raise():
    with pytest.raises(NotImplementedError):
        dist.InMemoryDataset()
    with pytest.raises(NotImplementedError):
        dist.split(paddle.ones([2, 2]), (2, 2), "linear")


def test_distributed_io_roundtrip(tmp_path, static_mode=None):
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            xv = static.data("x", [2, 2], "float32")
            w = static.create_parameter([2, 1], "float32")
            out = paddle.matmul(xv, w)
        exe = static.Executor()
        exe.run(main, feed={"x": np.ones((2, 2), "float32")},
                fetch_list=[out])
        # filename WITHOUT .npz must round-trip (np.savez appends it)
        names = dist.io.save_persistables(exe, str(tmp_path),
                                          main_program=main,
                                          filename="ckpt")
        assert names
        old = np.asarray(w._data).copy()
        w._data = w._data * 0.0
        dist.io.load_persistables(exe, str(tmp_path),
                                  main_program=main, filename="ckpt")
        np.testing.assert_allclose(np.asarray(w._data), old)
    finally:
        paddle.disable_static()


def test_static_state_io_and_ema(tmp_path):
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            xv = static.data("x", [2, 2], "float32")
            w = static.create_parameter([2, 1], "float32")
            out = paddle.matmul(xv, w)
        exe = static.Executor()
        exe.run(main, feed={"x": np.ones((2, 2), "float32")},
                fetch_list=[out])
        prefix = str(tmp_path / "model")
        static.save(main, prefix)
        old = np.asarray(w._data).copy()
        w._data = w._data * 0.0
        static.load(main, prefix)
        np.testing.assert_allclose(np.asarray(w._data), old)
        blob = static.serialize_persistables(None, None, program=main)
        w._data = w._data * 0.0
        static.deserialize_persistables(main, blob)
        np.testing.assert_allclose(np.asarray(w._data), old)
        # EMA swaps and restores
        ema = static.ExponentialMovingAverage(0.5)
        ema.update(program=main)
        live = np.asarray(w._data).copy()
        with ema.apply(program=main):
            pass
        np.testing.assert_allclose(np.asarray(w._data), live)
        with pytest.raises(NotImplementedError):
            static.serialize_program(None, None)
        with pytest.raises(NotImplementedError):
            static.auc(paddle.ones([4, 2]), paddle.ones([4, 1]),
                       curve="PR")
    finally:
        paddle.disable_static()


def test_static_places_and_metrics():
    assert len(static.cpu_places(2)) == 2
    acc = static.accuracy(
        paddle.to_tensor(np.asarray([[0.1, 0.9], [0.8, 0.2]],
                                    "float32")),
        paddle.to_tensor(np.asarray([[1], [0]], "int64")))
    np.testing.assert_allclose(float(acc.numpy()), 1.0)
    scores = paddle.to_tensor(
        np.asarray([[0.2, 0.8], [0.7, 0.3], [0.4, 0.6], [0.9, 0.1]],
                   "float32"))
    labels = paddle.to_tensor(np.asarray([[1], [0], [1], [0]], "int64"))
    v = float(static.auc(scores, labels).numpy())
    assert v == 1.0  # separable example
    with pytest.raises(RuntimeError):
        static.xpu_places()
    with pytest.raises(NotImplementedError):
        static.IpuStrategy()


def test_incubate_segments_and_wrappers():
    s = incubate.segment_mean(
        paddle.to_tensor([1.0, 2.0, 3.0, 4.0]),
        paddle.to_tensor(np.asarray([0, 0, 1, 1])))
    np.testing.assert_allclose(s.numpy(), [1.5, 3.5])
    sm = incubate.segment_max(
        paddle.to_tensor([1.0, 5.0, 2.0]),
        paddle.to_tensor(np.asarray([0, 0, 1])))
    np.testing.assert_allclose(sm.numpy(), [5.0, 2.0])
    att = incubate.softmax_mask_fuse_upper_triangle(
        paddle.ones([1, 1, 3, 3]))
    np.testing.assert_allclose(att.numpy()[0, 0, 0], [1.0, 0.0, 0.0],
                               atol=1e-6)
    # graph sampling on a tiny CSC graph
    row = paddle.to_tensor(np.asarray([1, 2, 0, 0], "int64"))
    colptr = paddle.to_tensor(np.asarray([0, 2, 3, 4], "int64"))
    nbrs, cnt = incubate.graph_sample_neighbors(
        row, colptr, paddle.to_tensor(np.asarray([0], "int64")))
    assert sorted(np.asarray(nbrs._data).tolist()) == [1, 2]


def test_lookahead_and_model_average():
    paddle.seed(0)
    lin = nn.Linear(4, 1)
    la = incubate.LookAhead(optimizer.SGD(0.1,
                                          parameters=lin.parameters()),
                            k=2)
    X = paddle.randn([8, 4])
    Y = paddle.randn([8, 1])
    l0 = None
    for _ in range(6):
        loss = ((lin(X) - Y) ** 2).mean()
        la.minimize(loss)
        if l0 is None:
            l0 = float(loss.numpy())
    assert float(loss.numpy()) < l0
    ma = incubate.ModelAverage(parameters=list(lin.parameters()))
    ma.step()
    live = lin.weight.numpy().copy()
    with ma.apply():
        pass
    np.testing.assert_allclose(lin.weight.numpy(), live)


def test_register_kl_specificity():
    from paddle_tpu import distribution as D

    @D.register_kl(D.Distribution, D.Distribution)
    def _fallback(p, q):
        return paddle.to_tensor([-1.0])

    try:
        n1, n2 = D.Normal(0.0, 1.0), D.Normal(1.0, 1.0)
        v = float(np.asarray(
            D.kl_divergence(n1, n2).numpy()).reshape(-1)[0])
        assert abs(v - 0.5) < 1e-5  # exact builtin beats the fallback
    finally:
        D._KL_REGISTRY.pop((D.Distribution, D.Distribution), None)


def test_io_compose_and_subset_sampler():
    class DS(io.Dataset):
        def __init__(self, n):
            self.n = n

        def __len__(self):
            return self.n

        def __getitem__(self, i):
            return (i, i * 2)

    c = io.ComposeDataset([DS(3), DS(3)])
    assert len(c) == 3 and c[1] == (1, 2, 1, 2)
    with pytest.raises(ValueError):
        io.ComposeDataset([DS(3), DS(4)])
    paddle.seed(5)
    o1 = list(io.SubsetRandomSampler([4, 8, 2]))
    paddle.seed(5)
    o2 = list(io.SubsetRandomSampler([4, 8, 2]))
    assert o1 == o2 and sorted(o1) == [2, 4, 8]


def test_vision_image_backend(tmp_path):
    from paddle_tpu import vision

    assert vision.get_image_backend() == "pil"
    with pytest.raises(ValueError):
        vision.set_image_backend("opencv")
    with pytest.raises(ValueError):
        vision.image_load("x.png", backend="weird")
    import numpy as _np
    from PIL import Image

    f = str(tmp_path / "t.png")
    Image.fromarray(_np.zeros((4, 4, 3), _np.uint8)).save(f)
    img = vision.image_load(f)
    assert img.size == (4, 4)


def test_autograd_saved_tensors_hooks_raises():
    from paddle_tpu import autograd

    with pytest.raises(NotImplementedError):
        with autograd.saved_tensors_hooks(lambda x: x, lambda x: x):
            pass


def test_linalg_tail_and_sampling():
    import scipy.linalg as sla

    rng = np.random.default_rng(0)
    # cholesky_solve
    A = rng.standard_normal((3, 3)); A = A @ A.T + 3 * np.eye(3)
    L = np.linalg.cholesky(A).astype("float32")
    b = rng.standard_normal((3, 2)).astype("float32")
    got = paddle.cholesky_solve(paddle.to_tensor(b),
                                paddle.to_tensor(L)).numpy()
    np.testing.assert_allclose(got, np.linalg.solve(A, b), rtol=1e-3,
                               atol=1e-4)
    # eig on host (complex results live on the CPU backend)
    M = rng.standard_normal((4, 4)).astype("float32")
    w, v = paddle.eig(paddle.to_tensor(M))
    np.testing.assert_allclose(np.sort(w.numpy().real),
                               np.sort(np.linalg.eigvals(M).real),
                               rtol=1e-4)
    # batched lu_unpack reconstructs each batch
    Ms = rng.standard_normal((2, 3, 3))
    lus, pivs = zip(*[sla.lu_factor(Ms[i]) for i in range(2)])
    lu = np.stack(lus).astype("float32")
    piv = np.stack([(p + 1).astype("int32") for p in pivs])
    P, Lm, U = paddle.lu_unpack(paddle.to_tensor(lu),
                                paddle.to_tensor(piv))
    for i in range(2):
        np.testing.assert_allclose(
            P.numpy()[i] @ Lm.numpy()[i] @ U.numpy()[i], Ms[i],
            rtol=1e-3, atol=1e-4)
    Pn, Ln, Un = paddle.lu_unpack(paddle.to_tensor(lu),
                                  paddle.to_tensor(piv),
                                  unpack_ludata=False)
    assert Ln is None and Un is None and Pn is not None
    # ormqr applies the FULL implicit Q (tall factor + transpose)
    A2 = rng.standard_normal((4, 2))
    qr, tau = sla.lapack.dgeqrf(A2.copy())[:2]
    B = rng.standard_normal((4, 3)).astype("float64")
    Q = np.eye(4)
    for i, ti in enumerate(tau):
        vv = np.zeros(4)
        vv[i] = 1.0
        vv[i + 1:] = qr[i + 1:, i]
        Q = Q @ (np.eye(4) - ti * np.outer(vv, vv))
    got = paddle.ormqr(paddle.to_tensor(qr), paddle.to_tensor(tau),
                       paddle.to_tensor(B)).numpy()
    np.testing.assert_allclose(got, Q @ B, rtol=1e-5, atol=1e-6)
    # svd_lowrank singular values
    X = rng.standard_normal((6, 4)).astype("float32")
    _, S, _ = paddle.svd_lowrank(paddle.to_tensor(X), q=3)
    np.testing.assert_allclose(S.numpy(),
                               np.linalg.svd(X, compute_uv=False)[:3],
                               rtol=1e-4)
    # top_p: threshold floors tokens; seed reproduces
    probs = paddle.to_tensor(
        np.asarray([[0.5, 0.3, 0.15, 0.05]], "float32"))
    seen = set()
    for _ in range(30):
        _, i = paddle.top_p_sampling(
            probs, paddle.to_tensor(np.asarray([0.99], "float32")),
            threshold=paddle.to_tensor(np.asarray([0.2], "float32")))
        seen.add(int(i.numpy()[0, 0]))
    assert seen <= {0, 1}
    i1 = paddle.top_p_sampling(
        probs, paddle.to_tensor(np.asarray([0.9], "float32")),
        seed=5)[1].numpy()
    i2 = paddle.top_p_sampling(
        probs, paddle.to_tensor(np.asarray([0.9], "float32")),
        seed=5)[1].numpy()
    assert (i1 == i2).all()
    # in-place random fills + method binding
    x = paddle.zeros([64])
    x.uniform_(0.0, 1.0)
    assert 0.0 <= x.numpy().min() and x.numpy().max() <= 1.0
    x.exponential_(2.0)
    assert (x.numpy() >= 0).all()
    m2 = paddle.to_tensor(np.eye(2, dtype="float32"))
    np.testing.assert_allclose(m2.mm(m2).numpy(), np.eye(2))
