"""Behavior tests for the namespace-completion compat surfaces
(distributed/compat.py, distributed/io.py, incubate/compat.py, static
additions, io/vision/distribution/jit extras) — the review-hardened
contracts, not just symbol existence."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import incubate, io, nn, optimizer, static


def test_alltoall_single_roundtrip():
    out = dist.alltoall_single(paddle.zeros([2]),
                               paddle.to_tensor([1.0, 2.0]))
    np.testing.assert_allclose(out.numpy(), [1.0, 2.0])


def test_gather_and_object_collectives():
    g = dist.gather(paddle.ones([2]))
    assert len(g) == 1
    np.testing.assert_allclose(g[0].numpy(), [1.0, 1.0])
    objs = [{"a": 1}, 7]
    dist.broadcast_object_list(objs)
    assert objs == [{"a": 1}, 7]
    assert dist.is_available()
    assert dist.get_backend() in ("xla", "gloo")
    dist.wait(paddle.ones([2]))


def test_strategy_and_dist_attr():
    s = dist.Strategy()
    assert hasattr(s.sharding, "stage")
    assert hasattr(s.pipeline, "accumulate_steps")
    a = dist.DistAttr(sharding_specs=["x", None])
    assert a.sharding_specs == ["x", None]
    assert dist.ReduceType.kRedSum == "sum"


def test_ps_stack_stubs_raise():
    with pytest.raises(NotImplementedError):
        dist.InMemoryDataset()
    with pytest.raises(NotImplementedError):
        dist.split(paddle.ones([2, 2]), (2, 2), "linear")


def test_distributed_io_roundtrip(tmp_path, static_mode=None):
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            xv = static.data("x", [2, 2], "float32")
            w = static.create_parameter([2, 1], "float32")
            out = paddle.matmul(xv, w)
        exe = static.Executor()
        exe.run(main, feed={"x": np.ones((2, 2), "float32")},
                fetch_list=[out])
        # filename WITHOUT .npz must round-trip (np.savez appends it)
        names = dist.io.save_persistables(exe, str(tmp_path),
                                          main_program=main,
                                          filename="ckpt")
        assert names
        old = np.asarray(w._data).copy()
        w._data = w._data * 0.0
        dist.io.load_persistables(exe, str(tmp_path),
                                  main_program=main, filename="ckpt")
        np.testing.assert_allclose(np.asarray(w._data), old)
    finally:
        paddle.disable_static()


def test_static_state_io_and_ema(tmp_path):
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            xv = static.data("x", [2, 2], "float32")
            w = static.create_parameter([2, 1], "float32")
            out = paddle.matmul(xv, w)
        exe = static.Executor()
        exe.run(main, feed={"x": np.ones((2, 2), "float32")},
                fetch_list=[out])
        prefix = str(tmp_path / "model")
        static.save(main, prefix)
        old = np.asarray(w._data).copy()
        w._data = w._data * 0.0
        static.load(main, prefix)
        np.testing.assert_allclose(np.asarray(w._data), old)
        blob = static.serialize_persistables(None, None, program=main)
        w._data = w._data * 0.0
        static.deserialize_persistables(main, blob)
        np.testing.assert_allclose(np.asarray(w._data), old)
        # EMA swaps and restores
        ema = static.ExponentialMovingAverage(0.5)
        ema.update(program=main)
        live = np.asarray(w._data).copy()
        with ema.apply(program=main):
            pass
        np.testing.assert_allclose(np.asarray(w._data), live)
        with pytest.raises(NotImplementedError):
            static.serialize_program(None, None)
        with pytest.raises(NotImplementedError):
            static.auc(paddle.ones([4, 2]), paddle.ones([4, 1]),
                       curve="PR")
    finally:
        paddle.disable_static()


def test_static_places_and_metrics():
    assert len(static.cpu_places(2)) == 2
    acc = static.accuracy(
        paddle.to_tensor(np.asarray([[0.1, 0.9], [0.8, 0.2]],
                                    "float32")),
        paddle.to_tensor(np.asarray([[1], [0]], "int64")))
    np.testing.assert_allclose(float(acc.numpy()), 1.0)
    scores = paddle.to_tensor(
        np.asarray([[0.2, 0.8], [0.7, 0.3], [0.4, 0.6], [0.9, 0.1]],
                   "float32"))
    labels = paddle.to_tensor(np.asarray([[1], [0], [1], [0]], "int64"))
    v = float(static.auc(scores, labels).numpy())
    assert v == 1.0  # separable example
    with pytest.raises(RuntimeError):
        static.xpu_places()
    with pytest.raises(NotImplementedError):
        static.IpuStrategy()


def test_incubate_segments_and_wrappers():
    s = incubate.segment_mean(
        paddle.to_tensor([1.0, 2.0, 3.0, 4.0]),
        paddle.to_tensor(np.asarray([0, 0, 1, 1])))
    np.testing.assert_allclose(s.numpy(), [1.5, 3.5])
    sm = incubate.segment_max(
        paddle.to_tensor([1.0, 5.0, 2.0]),
        paddle.to_tensor(np.asarray([0, 0, 1])))
    np.testing.assert_allclose(sm.numpy(), [5.0, 2.0])
    att = incubate.softmax_mask_fuse_upper_triangle(
        paddle.ones([1, 1, 3, 3]))
    np.testing.assert_allclose(att.numpy()[0, 0, 0], [1.0, 0.0, 0.0],
                               atol=1e-6)
    # graph sampling on a tiny CSC graph
    row = paddle.to_tensor(np.asarray([1, 2, 0, 0], "int64"))
    colptr = paddle.to_tensor(np.asarray([0, 2, 3, 4], "int64"))
    nbrs, cnt = incubate.graph_sample_neighbors(
        row, colptr, paddle.to_tensor(np.asarray([0], "int64")))
    assert sorted(np.asarray(nbrs._data).tolist()) == [1, 2]


def test_lookahead_and_model_average():
    paddle.seed(0)
    lin = nn.Linear(4, 1)
    la = incubate.LookAhead(optimizer.SGD(0.1,
                                          parameters=lin.parameters()),
                            k=2)
    X = paddle.randn([8, 4])
    Y = paddle.randn([8, 1])
    l0 = None
    for _ in range(6):
        loss = ((lin(X) - Y) ** 2).mean()
        la.minimize(loss)
        if l0 is None:
            l0 = float(loss.numpy())
    assert float(loss.numpy()) < l0
    ma = incubate.ModelAverage(parameters=list(lin.parameters()))
    ma.step()
    live = lin.weight.numpy().copy()
    with ma.apply():
        pass
    np.testing.assert_allclose(lin.weight.numpy(), live)


def test_register_kl_specificity():
    from paddle_tpu import distribution as D

    @D.register_kl(D.Distribution, D.Distribution)
    def _fallback(p, q):
        return paddle.to_tensor([-1.0])

    try:
        n1, n2 = D.Normal(0.0, 1.0), D.Normal(1.0, 1.0)
        v = float(np.asarray(
            D.kl_divergence(n1, n2).numpy()).reshape(-1)[0])
        assert abs(v - 0.5) < 1e-5  # exact builtin beats the fallback
    finally:
        D._KL_REGISTRY.pop((D.Distribution, D.Distribution), None)


def test_io_compose_and_subset_sampler():
    class DS(io.Dataset):
        def __init__(self, n):
            self.n = n

        def __len__(self):
            return self.n

        def __getitem__(self, i):
            return (i, i * 2)

    c = io.ComposeDataset([DS(3), DS(3)])
    assert len(c) == 3 and c[1] == (1, 2, 1, 2)
    with pytest.raises(ValueError):
        io.ComposeDataset([DS(3), DS(4)])
    paddle.seed(5)
    o1 = list(io.SubsetRandomSampler([4, 8, 2]))
    paddle.seed(5)
    o2 = list(io.SubsetRandomSampler([4, 8, 2]))
    assert o1 == o2 and sorted(o1) == [2, 4, 8]


def test_vision_image_backend(tmp_path):
    from paddle_tpu import vision

    assert vision.get_image_backend() == "pil"
    with pytest.raises(ValueError):
        vision.set_image_backend("opencv")
    with pytest.raises(ValueError):
        vision.image_load("x.png", backend="weird")
    import numpy as _np
    from PIL import Image

    f = str(tmp_path / "t.png")
    Image.fromarray(_np.zeros((4, 4, 3), _np.uint8)).save(f)
    img = vision.image_load(f)
    assert img.size == (4, 4)


def test_autograd_saved_tensors_hooks_raises():
    from paddle_tpu import autograd

    with pytest.raises(NotImplementedError):
        with autograd.saved_tensors_hooks(lambda x: x, lambda x: x):
            pass


def test_linalg_tail_and_sampling():
    import scipy.linalg as sla

    rng = np.random.default_rng(0)
    # cholesky_solve
    A = rng.standard_normal((3, 3)); A = A @ A.T + 3 * np.eye(3)
    L = np.linalg.cholesky(A).astype("float32")
    b = rng.standard_normal((3, 2)).astype("float32")
    got = paddle.cholesky_solve(paddle.to_tensor(b),
                                paddle.to_tensor(L)).numpy()
    np.testing.assert_allclose(got, np.linalg.solve(A, b), rtol=1e-3,
                               atol=1e-4)
    # eig on host (complex results live on the CPU backend)
    M = rng.standard_normal((4, 4)).astype("float32")
    w, v = paddle.eig(paddle.to_tensor(M))
    np.testing.assert_allclose(np.sort(w.numpy().real),
                               np.sort(np.linalg.eigvals(M).real),
                               rtol=1e-4)
    # batched lu_unpack reconstructs each batch
    Ms = rng.standard_normal((2, 3, 3))
    lus, pivs = zip(*[sla.lu_factor(Ms[i]) for i in range(2)])
    lu = np.stack(lus).astype("float32")
    piv = np.stack([(p + 1).astype("int32") for p in pivs])
    P, Lm, U = paddle.lu_unpack(paddle.to_tensor(lu),
                                paddle.to_tensor(piv))
    for i in range(2):
        np.testing.assert_allclose(
            P.numpy()[i] @ Lm.numpy()[i] @ U.numpy()[i], Ms[i],
            rtol=1e-3, atol=1e-4)
    Pn, Ln, Un = paddle.lu_unpack(paddle.to_tensor(lu),
                                  paddle.to_tensor(piv),
                                  unpack_ludata=False)
    assert Ln is None and Un is None and Pn is not None
    # ormqr applies the FULL implicit Q (tall factor + transpose)
    A2 = rng.standard_normal((4, 2))
    qr, tau = sla.lapack.dgeqrf(A2.copy())[:2]
    B = rng.standard_normal((4, 3)).astype("float64")
    Q = np.eye(4)
    for i, ti in enumerate(tau):
        vv = np.zeros(4)
        vv[i] = 1.0
        vv[i + 1:] = qr[i + 1:, i]
        Q = Q @ (np.eye(4) - ti * np.outer(vv, vv))
    got = paddle.ormqr(paddle.to_tensor(qr), paddle.to_tensor(tau),
                       paddle.to_tensor(B)).numpy()
    np.testing.assert_allclose(got, Q @ B, rtol=1e-5, atol=1e-6)
    # svd_lowrank singular values
    X = rng.standard_normal((6, 4)).astype("float32")
    _, S, _ = paddle.svd_lowrank(paddle.to_tensor(X), q=3)
    np.testing.assert_allclose(S.numpy(),
                               np.linalg.svd(X, compute_uv=False)[:3],
                               rtol=1e-4)
    # top_p: threshold floors tokens; seed reproduces
    probs = paddle.to_tensor(
        np.asarray([[0.5, 0.3, 0.15, 0.05]], "float32"))
    seen = set()
    for _ in range(30):
        _, i = paddle.top_p_sampling(
            probs, paddle.to_tensor(np.asarray([0.99], "float32")),
            threshold=paddle.to_tensor(np.asarray([0.2], "float32")))
        seen.add(int(i.numpy()[0, 0]))
    assert seen <= {0, 1}
    i1 = paddle.top_p_sampling(
        probs, paddle.to_tensor(np.asarray([0.9], "float32")),
        seed=5)[1].numpy()
    i2 = paddle.top_p_sampling(
        probs, paddle.to_tensor(np.asarray([0.9], "float32")),
        seed=5)[1].numpy()
    assert (i1 == i2).all()
    # in-place random fills + method binding
    x = paddle.zeros([64])
    x.uniform_(0.0, 1.0)
    assert 0.0 <= x.numpy().min() and x.numpy().max() <= 1.0
    x.exponential_(2.0)
    assert (x.numpy() >= 0).all()
    m2 = paddle.to_tensor(np.eye(2, dtype="float32"))
    np.testing.assert_allclose(m2.mm(m2).numpy(), np.eye(2))


def test_nn_functional_extras():
    from paddle_tpu.nn import functional as F

    rng = np.random.default_rng(0)
    t = lambda a: paddle.to_tensor(np.asarray(a, "float32"))
    m = F.maxout(t(rng.standard_normal((2, 4, 3, 3))), groups=2)
    assert tuple(m.shape) == (2, 2, 3, 3)
    np.testing.assert_allclose(
        F.pairwise_distance(t([[1.0, 0.0]]), t([[0.0, 0.0]])).numpy(),
        [1.0], rtol=1e-4)
    np.testing.assert_allclose(
        F.square_error_cost(t([2.0]), t([1.0])).numpy(), [1.0])
    x1 = rng.standard_normal((3, 4)).astype("float32")
    x2 = rng.standard_normal((3, 5)).astype("float32")
    W = rng.standard_normal((2, 4, 5)).astype("float32")
    np.testing.assert_allclose(
        F.bilinear(t(x1), t(x2), t(W)).numpy(),
        np.einsum("bi,oij,bj->bo", x1, W, x2), rtol=1e-4)
    # gather_tree backtracks ancestry
    ids = np.asarray([[[1, 2]], [[3, 4]]], "int32")
    par = np.asarray([[[0, 0]], [[0, 0]]], "int32")
    gt = F.gather_tree(paddle.to_tensor(ids),
                       paddle.to_tensor(par)).numpy()
    np.testing.assert_allclose(gt[:, 0, 1], [1, 4])
    # margin_cross_entropy softmax rows normalize
    logits = t(rng.standard_normal((4, 10)) * 0.1)
    lab = paddle.to_tensor(rng.integers(0, 10, 4).astype("int64"))
    loss, sm = F.margin_cross_entropy(logits, lab, return_softmax=True)
    assert float(loss.numpy()) > 0
    np.testing.assert_allclose(sm.numpy().sum(-1), np.ones(4),
                               rtol=1e-5)
    # dropout2d zeroes whole channels
    d2 = F.dropout2d(t(np.ones((2, 8, 4, 4))), p=0.5).numpy()
    for b in d2.reshape(2, 8, -1):
        for c in b:
            assert np.all(c == 0) or np.all(c == c[0])
    # in-place activation rebinding
    z = t([-1.0, 2.0])
    F.relu_(z)
    np.testing.assert_allclose(z.numpy(), [0.0, 2.0])
    with pytest.raises(NotImplementedError):
        F.sparse_attention(None, None, None, None, None)


def test_vision_transforms_functional():
    from paddle_tpu.vision import transforms as T

    rng = np.random.default_rng(0)
    img = rng.random((3, 8, 8)).astype("float32")
    np.testing.assert_allclose(T.hflip(T.hflip(img)), img)
    np.testing.assert_allclose(T.vflip(T.vflip(img)), img)
    assert T.resize(img, 4).shape == (3, 4, 4)
    assert T.crop(img, 1, 2, 3, 4).shape == (3, 3, 4)
    np.testing.assert_allclose(T.adjust_brightness(img, 0.5),
                               np.clip(img * 0.5, 0, 1), rtol=1e-5)
    pts = [(0, 0), (7, 0), (7, 7), (0, 7)]
    np.testing.assert_allclose(T.perspective(img, pts, pts), img)
    e = T.erase(img.copy(), 1, 1, 2, 2, 0.0)
    assert (e[:, 1:3, 1:3] == 0).all()
    tt = T.to_tensor((img.transpose(1, 2, 0) * 255).astype("uint8"))
    assert tuple(tt.shape) == (3, 8, 8)

    class Doubler(T.BaseTransform):
        def _apply_image(self, im):
            return im * 2

    np.testing.assert_allclose(Doubler()(img), img * 2)


def test_audio_io_roundtrip(tmp_path):
    from paddle_tpu import audio

    sr = 8000
    wave = np.sin(np.linspace(0, 200, 4000)).astype("float32")[None, :]
    f = str(tmp_path / "t.wav")
    audio.save(f, paddle.to_tensor(wave), sr)
    meta = audio.info(f)
    assert meta.sample_rate == sr and meta.num_channels == 1
    w2, sr2 = audio.load(f)
    assert sr2 == sr
    np.testing.assert_allclose(w2.numpy(), wave, atol=2e-4)
    assert "wave" in audio.backends()


def test_initializer_extras():
    from paddle_tpu.nn import initializer as init

    b = init.Bilinear()([2, 2, 4, 4])
    assert b.shape == (2, 2, 4, 4)
    assert float(np.asarray(b)[0, 1].sum()) == 0.0
    init.set_global_initializer(init.Constant(0.25))
    try:
        lin = nn.Linear(3, 2)
        np.testing.assert_allclose(lin.weight.numpy(), 0.25)
    finally:
        init.set_global_initializer(None)


def test_leaf_namespace_parity():
    """vision.transforms / audio / nn.functional / nn.initializer match
    the reference __all__ (dynamic sweep, skipped without the mounted
    reference)."""
    import ast

    ref_root = "/root/reference/python/paddle"
    if not os.path.isdir(ref_root):
        pytest.skip("reference tree not mounted")

    def public_names(path):
        names = set()
        if not os.path.exists(path):
            return names
        for node in ast.walk(ast.parse(open(path).read())):
            if isinstance(node, ast.Assign):
                for t_ in node.targets:
                    if isinstance(t_, ast.Name) and t_.id == "__all__":
                        try:
                            names |= set(ast.literal_eval(node.value))
                        except Exception:
                            pass
        return names

    pairs = [("paddle_tpu.vision.transforms",
              "vision/transforms/__init__.py"),
             ("paddle_tpu.audio", "audio/__init__.py"),
             ("paddle_tpu.nn.functional", "nn/functional/__init__.py"),
             ("paddle_tpu.nn.initializer",
              "nn/initializer/__init__.py")]
    problems = {}
    for mod, rel in pairs:
        ours = __import__(mod, fromlist=["_"])
        ref = public_names(os.path.join(ref_root, rel))
        missing = sorted(n for n in ref if not hasattr(ours, n))
        if missing:
            problems[mod] = missing
    assert not problems, problems


def test_transforms_functional_review_contracts():
    from paddle_tpu.vision import transforms as T

    img = np.random.default_rng(0).random((3, 8, 8)).astype("float32")
    # affine with scalar shear must not crash
    assert T.affine(img, 10.0, (0, 0), 1.0, 0.0).shape == img.shape
    # perspective maps start -> end (content moves right for +x shift)
    marked = np.zeros((1, 8, 8), "float32")
    marked[0, 4, 2] = 1.0
    out = T.perspective(marked, [(0, 0), (7, 0), (7, 7), (0, 7)],
                        [(2, 0), (9, 0), (9, 7), (2, 7)])
    assert out[0, 4, 4] == 1.0
    # to_tensor scales by DTYPE, not data max
    dark = np.zeros((4, 4), np.uint8)
    dark[0, 0] = 1
    assert abs(float(T.to_tensor(dark).numpy().max()) - 1 / 255) < 1e-6


def test_nhwc_layouts_and_global_init():
    from paddle_tpu.nn import functional as F
    from paddle_tpu.nn import initializer as init

    z = F.zeropad2d(paddle.ones([1, 4, 4, 3]), 1, data_format="NHWC")
    assert tuple(z.shape) == (1, 6, 6, 3)
    ts = F.temporal_shift(paddle.ones([4, 4, 4, 8]), 2,
                          data_format="NHWC")
    assert tuple(ts.shape) == (4, 4, 4, 8)
    init.set_global_initializer(init.Constant(0.25), init.Constant(9.0))
    try:
        p1 = paddle.create_parameter([2, 2], "float32")
        np.testing.assert_allclose(p1.numpy(), 0.25)
        init.set_global_initializer(init.Constant(0.5))
        lin = nn.Linear(2, 2)
        np.testing.assert_allclose(lin.weight.numpy(), 0.5)
        np.testing.assert_allclose(lin.bias.numpy(), 0.0)  # bias reset
    finally:
        init.set_global_initializer(None)


def test_audio_24bit_and_unnormalized(tmp_path):
    import wave as _wave

    from paddle_tpu import audio

    f = str(tmp_path / "x24.wav")
    with _wave.open(f, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(3)
        w.setframerate(8000)
        vals = (np.asarray([0.5, -0.5]) * (2 ** 23 - 1)).astype(
            np.int32)
        raw = b"".join(int(v).to_bytes(3, "little", signed=True)
                       for v in vals)
        w.writeframes(raw)
    wv, sr = audio.load(f)
    np.testing.assert_allclose(wv.numpy()[0], [0.5, -0.5], atol=1e-5)
    # normalize=False keeps integer PCM for 16-bit
    f2 = str(tmp_path / "x16.wav")
    audio.save(f2, paddle.to_tensor(np.asarray([[0.5, -0.5]],
                                               "float32")), 8000)
    raw16, _ = audio.load(f2, normalize=False)
    assert raw16.numpy().dtype in (np.int16, np.int32)
