"""Surface breadth: vision transforms zoo, Flowers/VOC datasets, text
datasets (Imikolov/Movielens/Conll05st/WMT), audio datasets
(TESS/ESC50), resnext models (reference: vision/transforms/,
vision/datasets/, text/datasets/, audio/datasets/,
vision/models/resnet.py)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision import transforms as T


def _img(h=16, w=16):
    return np.random.default_rng(0).uniform(
        0, 1, size=(3, h, w)).astype("float32")


def test_photometric_transforms_preserve_shape_and_range():
    x = _img()
    np.random.seed(0)
    for t in [T.BrightnessTransform(0.4), T.ContrastTransform(0.4),
              T.SaturationTransform(0.4), T.HueTransform(0.2),
              T.ColorJitter(0.4, 0.4, 0.4, 0.1)]:
        y = t(x)
        assert y.shape == x.shape
        assert y.min() >= -1e-6 and y.max() <= 1.0 + 1e-6


def test_grayscale_and_flip():
    x = _img()
    g = T.Grayscale(3)(x)
    assert g.shape == x.shape
    np.testing.assert_allclose(g[0], g[1])
    np.random.seed(0)
    v = T.RandomVerticalFlip(prob=1.0)(x)
    np.testing.assert_allclose(v[:, ::-1, :], x)


def test_rotation_affine_perspective_erasing():
    x = _img(32, 32)
    np.random.seed(1)
    r = T.RandomRotation(30)(x)
    assert r.shape == x.shape and np.isfinite(r).all()
    a = T.RandomAffine(15, translate=(0.1, 0.1), scale=(0.9, 1.1),
                       shear=10)(x)
    assert a.shape == x.shape and np.isfinite(a).all()
    p = T.RandomPerspective(prob=1.0, distortion_scale=0.3)(x)
    assert p.shape == x.shape and np.isfinite(p).all()
    e = T.RandomErasing(prob=1.0, value=0.0)(x)
    assert e.shape == x.shape
    assert (e == 0).sum() > (x == 0).sum()  # something was erased


def test_rotation_zero_degrees_identity():
    x = _img(24, 24)
    np.random.seed(0)
    r = T.RandomRotation((0.0, 0.0))(x)
    np.testing.assert_allclose(r, x, atol=1e-4)


def test_flowers_voc_synthetic():
    from paddle_tpu.vision.datasets import VOC2012, Flowers

    f = Flowers(mode="train")
    img, label = f[0]
    assert img.shape == (3, 64, 64) and 0 <= int(label) < 102
    v = VOC2012(mode="train")
    img, mask = v[0]
    assert img.shape == (3, 64, 64) and mask.shape == (64, 64)
    assert mask.max() >= 1  # a class blob exists


def test_text_datasets_shapes():
    from paddle_tpu.text import WMT14, Conll05st, Imikolov, Movielens

    ik = Imikolov(window_size=5)
    ctx, nxt = ik[0]
    assert len(ctx) == 4 and len(nxt) == 1
    ml = Movielens(mode="train")
    u, m, r = ml[0]
    assert u.dtype == np.int64 and 1.0 <= float(r) <= 5.0
    c5 = Conll05st()
    w, p, l = c5[0]
    assert len(w) == len(p) == len(l)
    wmt = WMT14(mode="train")
    src, trg, trg_next = wmt[0]
    assert trg[0] == 0 and trg_next[-1] == 1  # <s> ... </e>
    assert len(trg) == len(trg_next)


def test_audio_datasets_and_feature_pipeline():
    from paddle_tpu.audio.datasets import ESC50, TESS

    t = TESS(mode="train")
    x, y = t[0]
    assert x.ndim == 1 and 0 <= int(y) < 7
    e = ESC50(mode="train", feat_type="melspectrogram", n_fft=256,
              hop_length=128, n_mels=32, sr=4000)
    feat, y = e[0]
    assert feat.ndim == 2 and feat.shape[0] == 32
    assert np.isfinite(feat).all()


def test_resnext_and_wide_resnet_structure():
    from paddle_tpu.vision.models import (resnext50_32x4d,
                                          wide_resnet50_2)

    paddle.seed(0)
    rx = resnext50_32x4d(num_classes=7)
    out = rx(paddle.ones([1, 3, 32, 32]))
    assert tuple(out.shape) == (1, 7)
    # grouped conv actually present: the 3x3 conv weights carry
    # Cin/groups channels
    convs = [m for m in rx.sublayers()
             if m.__class__.__name__ == "Conv2D"
             and getattr(m, "groups", 1) == 32]
    assert convs, "resnext must use grouped 3x3 convs"
    wr = wide_resnet50_2(num_classes=3)
    assert tuple(wr(paddle.ones([1, 3, 32, 32])).shape) == (1, 3)
