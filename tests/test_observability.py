"""Memory stats + nan/inf checking observability (VERDICT r2 item 10).

Reference: paddle/fluid/memory/stats.cc (max_memory_allocated) and
paddle/fluid/eager/nan_inf_utils.h (FLAGS_check_nan_inf hooked into
dispatch everywhere, including compiled paths).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def test_memory_api_surface():
    from paddle_tpu import device

    assert device.device_count() >= 1
    # CPU/mock runtimes may not export allocator stats; the API must
    # still answer with well-typed values
    assert isinstance(device.memory_allocated(), int)
    assert isinstance(device.max_memory_allocated(), int)
    assert device.max_memory_allocated() >= device.memory_allocated() \
        or device.max_memory_allocated() == 0
    info = device.get_memory_info()
    assert set(info) == {"allocated", "peak_allocated", "limit"}
    device.reset_max_memory_allocated()
    device.empty_cache()


def test_compiled_memory_analysis():
    import jax
    import jax.numpy as jnp

    from paddle_tpu import device

    f = jax.jit(lambda x: (x @ x).sum())
    lowered = f.lower(jnp.zeros((64, 64), jnp.float32))
    compiled = lowered.compile()
    ma = device.compiled_memory_analysis(compiled)
    assert ma.get("argument_size_in_bytes", 0) >= 64 * 64 * 4


def test_check_nan_inf_eager():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError, match="divide"):
            _ = paddle.ops.divide(x, paddle.to_tensor(
                np.array([1.0, 0.0], np.float32)))
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_check_nan_inf_inside_compiled_step():
    """The flag must fire INSIDE TrainStep (round 2 skipped tracers so
    compiled training never checked anything)."""
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        paddle.seed(0)
        model = nn.Linear(4, 4)
        # poison one weight with inf: the first matmul output is nonfinite
        w = model.parameters()[0]
        bad = np.array(w.numpy(), copy=True)
        bad[0, 0] = np.inf
        w.set_value(paddle.to_tensor(bad))
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        step = paddle.jit.TrainStep(model, nn.MSELoss(), opt)
        X = paddle.to_tensor(np.ones((2, 4), np.float32))
        Y = paddle.to_tensor(np.zeros((2, 4), np.float32))
        with pytest.raises(Exception, match="nan/inf"):
            loss = step(X, Y)
            float(loss._data)  # force execution
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_check_nan_inf_off_by_default():
    x = paddle.to_tensor(np.array([1.0], np.float32))
    y = paddle.ops.divide(x, paddle.to_tensor(np.array([0.0], np.float32)))
    assert np.isinf(y.numpy()).all()
