"""Ragged serving hot path (ISSUE 9): parity + compile-count pins.

The ragged engine (single-shape packed step + chunked prefill + COW
prefix caching) must be TOKEN-IDENTICAL to the bucketed engine it
replaces — greedy and sampled, through chunking, preemption and fleet
hand-off — while compiling exactly ONE step function for a whole mixed
prefill/decode workload (the bucket lattice it collapses compiles one
function per (batch, seq) bucket)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.watchdog import PreemptionMonitor
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import FleetRouter, InProcessReplica


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def _naive(model, prompt, max_new):
    ids = paddle.to_tensor(np.asarray([prompt], np.int32))
    out = model.generate(ids, max_new_tokens=max_new, use_cache=False)
    return [int(t) for t in out.numpy()[0][len(prompt):]]


def _prompts(seed, vocab, lens):
    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(0, vocab, size=n))) for n in lens]


def _cfg(ragged, **kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("max_model_len", 64)
    return EngineConfig(ragged=ragged, chunked_prefill=ragged,
                        prefix_cache=ragged, **kw)


def _serve(model, prompts, samplings, ragged, **cfg_kw):
    eng = LLMEngine(model, _cfg(ragged, **cfg_kw))
    rids = [eng.add_request(f"r{i}", p, sampling=sp)
            for i, (p, sp) in enumerate(zip(prompts, samplings))]
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < 500, "engine failed to converge"
    return eng, [eng.get_request(r).generated for r in rids]


# ---------------------------------------------------------------------------
# config resolution
# ---------------------------------------------------------------------------
def test_ragged_is_the_default_for_ragged_capable_models(tiny_model):
    eng = LLMEngine(tiny_model, EngineConfig(
        block_size=4, max_num_seqs=2, max_model_len=32))
    assert eng._ragged
    assert eng.cfg.chunked_prefill and eng.cfg.prefix_cache
    # explicit opt-out restores the bucketed lattice wholesale
    eng_b = LLMEngine(tiny_model, EngineConfig(
        block_size=4, max_num_seqs=2, max_model_len=32, ragged=False))
    assert not eng_b._ragged
    assert not eng_b.cfg.chunked_prefill and not eng_b.cfg.prefix_cache


def test_invalid_knob_combinations_raise(tiny_model):
    with pytest.raises(ValueError, match="chunked_prefill"):
        LLMEngine(tiny_model, EngineConfig(
            block_size=4, max_num_seqs=2, max_model_len=32,
            ragged=True, chunked_prefill=False))
    with pytest.raises(ValueError, match="prefix_cache"):
        LLMEngine(tiny_model, EngineConfig(
            block_size=4, max_num_seqs=2, max_model_len=32,
            ragged=False, prefix_cache=True))


# ---------------------------------------------------------------------------
# parity + compile count
# ---------------------------------------------------------------------------
def test_mixed_workload_parity_and_single_compiled_shape(tiny_model):
    """Long prompts over the token budget (forced chunks), short
    prompts, a sampled row: ragged == bucketed for every request, the
    greedy rows == naive generate, and the WHOLE ragged run (chunked
    prefills, mixed batches, shrinking decode tails) dispatched ONE
    compiled step shape while the bucketed run walked its lattice."""
    m = tiny_model
    prompts = _prompts(21, m.config.vocab_size, [29, 3, 22, 6])
    sps = [SamplingParams(max_new_tokens=6),
           SamplingParams(max_new_tokens=5, temperature=0.8, seed=3),
           SamplingParams(max_new_tokens=6),
           SamplingParams(max_new_tokens=4)]
    # budget 16 < the 29/22-token prompts: the ragged engine must chunk
    eng_r, outs_r = _serve(m, prompts, sps, True, max_batched_tokens=16)
    eng_b, outs_b = _serve(m, prompts, sps, False, max_batched_tokens=16)
    assert outs_r == outs_b
    for i in (0, 2, 3):          # greedy rows vs the full-recompute oracle
        assert outs_r[i] == _naive(m, prompts[i], sps[i].max_new_tokens)
    assert len(eng_r._seen_shapes) == 1, eng_r._seen_shapes
    assert len(eng_b._seen_shapes) > 1
    snap = eng_r.metrics.snapshot()
    assert snap["serving_prefill_chunks"] > 0
    assert snap["mixed_steps"] > 0, \
        "chunk continuations never shared a step with decode rows"
    assert snap["padded_token_frac"] == 0.0
    assert eng_r.metrics.num_generated_tokens == \
        eng_b.metrics.num_generated_tokens


def test_parity_through_preemption(tiny_model):
    """Cache sized so the batch cannot all reach full length on either
    engine: both preempt, both still produce identical streams."""
    m = tiny_model
    prompts = _prompts(22, m.config.vocab_size, [6, 8, 5, 7])
    sps = [SamplingParams(max_new_tokens=8),
           SamplingParams(max_new_tokens=8),
           SamplingParams(max_new_tokens=8, temperature=0.7, seed=11),
           SamplingParams(max_new_tokens=8)]
    kw = dict(num_blocks=10, max_model_len=32)
    eng_r, outs_r = _serve(m, prompts, sps, True, **kw)
    eng_b, outs_b = _serve(m, prompts, sps, False, **kw)
    assert eng_r.scheduler.num_preemptions > 0
    assert eng_b.scheduler.num_preemptions > 0
    assert outs_r == outs_b
    for i in (0, 1, 3):
        assert outs_r[i] == _naive(m, prompts[i], 8)
    for eng in (eng_r, eng_b):
        assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
        eng.block_manager.check_invariants()


def test_prefix_cache_hit_cap_and_cow_keep_parity(tiny_model):
    """Re-sent identical prompts hit the full-prompt cache, which is
    capped at total-1 so one token is always computed; the capped write
    lands in a shared block -> COW. Outputs must equal the cold run's
    exactly, and the pool must return to full."""
    m = tiny_model
    # length 12 = exactly 3 full blocks: the whole prompt is cacheable,
    # so the hit is capped and the capped write lands in a SHARED full
    # block (a 13-token prompt would put it in a fresh partial block
    # and never exercise COW)
    prompt = _prompts(23, m.config.vocab_size, [12])[0]
    sp = SamplingParams(max_new_tokens=6)
    eng = LLMEngine(m, _cfg(True))
    waves = []
    for wave in range(2):
        # two concurrent identical prompts per wave: wave 2 shares
        # wave 1's committed blocks AND the pair shares within the wave
        rids = [eng.add_request(f"w{wave}-{i}", list(prompt), sampling=sp)
                for i in range(2)]
        steps = 0
        while eng.has_unfinished():
            eng.step()
            eng.block_manager.check_invariants()
            steps += 1
            assert steps < 200
        waves.append([eng.get_request(r).generated for r in rids])
    assert waves[0][0] == waves[0][1] == waves[1][0] == waves[1][1]
    assert waves[0][0] == _naive(m, prompt, 6)
    bm = eng.block_manager
    assert bm.num_prefix_hits > 0
    # eff cap: a full 12-token match reports at most 11 cached tokens
    assert 0 < bm.last_hit_tokens < len(prompt)
    assert bm.num_cow_copies > 0, \
        "capped write into a shared block never copy-on-wrote"
    for rid in [f"w{w}-{i}" for w in range(2) for i in range(2)]:
        eng.release_request(rid)
    assert bm.num_free_blocks == eng.cfg.num_blocks
    bm.check_invariants()


def test_fleet_handoff_parity_ragged(tiny_model):
    """Drain one ragged replica of two mid-run: every request finishes
    with generations identical to an uninterrupted BUCKETED single
    engine — hand-off resume-by-recompute and the ragged step compose
    without disturbing token streams."""
    m = tiny_model
    prompts = _prompts(24, m.config.vocab_size, [3, 5, 4, 6, 2, 5])
    sp = SamplingParams(max_new_tokens=8)
    ids = [f"h{i}" for i in range(len(prompts))]
    ref_eng = LLMEngine(m, _cfg(False))
    for rid, p in zip(ids, prompts):
        ref_eng.add_request(rid, p, sampling=sp)
    steps = 0
    while ref_eng.has_unfinished():
        ref_eng.step()
        steps += 1
        assert steps < 500
    ref = {rid: list(ref_eng.get_request(rid).generated) for rid in ids}

    mon = PreemptionMonitor()
    router = FleetRouter([
        InProcessReplica(m, _cfg(True, drain_grace_s=0.0),
                         replica_id="r0", monitor=mon),
        InProcessReplica(m, _cfg(True, drain_grace_s=0.0),
                         replica_id="r1")])
    try:
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        outs = []
        for _ in range(3):
            outs.extend(router.step())
        assert router._by_id("r0").engine.scheduler.num_running > 0
        mon.request()            # r0 drains -> hand-off to r1
        for _ in range(500):
            if not router.has_unfinished():
                break
            outs.extend(router.step())
    finally:
        mon.uninstall()
    final = {o.request_id: o for o in outs if o.finished}
    assert set(final) == set(ids)
    assert all(final[r].finish_reason in ("stop", "length") for r in ids)
    for rid in ids:
        assert final[rid].generated == ref[rid], rid
    assert router.num_handoffs >= 1
