"""skip_nonfinite on the parallel engines (ROADMAP PR-3 follow-up):
the in-graph NaN/Inf guard + device-carried skip counter, previously
jit.TrainStep-only, now on ParallelTrainStep and PipelineTrainStep.

Contract (same as jit.TrainStep): a non-finite loss/grad makes the
step an identity update — params, optimizer slots, buffers and the
device step counter bit-identical to before; only the RNG chain
advances — counted on device and surfaced via ``skipped_steps`` and
``profiler.counters()``."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, profiler
from paddle_tpu.distributed.engine import ParallelTrainStep
from paddle_tpu.distributed.fleet.pipeline_parallel import (
    LayerDesc, PipelineLayer,
)
from paddle_tpu.distributed.fleet.pp_engine import PipelineTrainStep
from paddle_tpu.distributed.mesh import ProcessMesh


def _mlp():
    return nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))


def _batch(rng, bad=None):
    X = rng.standard_normal((8, 16)).astype(np.float32)
    Y = rng.standard_normal((8, 16)).astype(np.float32)
    if bad is not None:
        X[0, 0] = bad
    return paddle.to_tensor(X), paddle.to_tensor(Y)


def _param_state(model):
    return [p.numpy().copy() for p in model.parameters()]


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)
        self.norm = nn.LayerNorm(d)

    def forward(self, x):
        return self.norm(x + self.fc2(paddle.ops.gelu(self.fc1(x))))


def _pipe(d=8, n_layers=4):
    return PipelineLayer(
        layers=[nn.Linear(d, d)] +
               [LayerDesc(Block, d) for _ in range(n_layers)] +
               [nn.Linear(d, d)],
        num_stages=1,
        loss_fn=nn.MSELoss())


@pytest.mark.parametrize("bad", [np.nan, np.inf])
def test_parallel_train_step_skips_nonfinite(bad):
    rng = np.random.default_rng(0)
    paddle.seed(7)
    m = _mlp()
    opt = optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
    step = ParallelTrainStep(m, nn.MSELoss(), opt, mesh,
                             skip_nonfinite=True)

    l0 = float(step(*_batch(rng)).item())
    assert np.isfinite(l0)
    assert step.skipped_steps == 0
    before = _param_state(m)
    slots_before = {k: np.asarray(v).copy()
                    for k, v in opt._slots[id(m[0].weight)].items()}

    lbad = float(step(*_batch(rng, bad=bad)).item())
    if np.isnan(bad):
        assert not np.isfinite(lbad)
    # (an inf INPUT saturates Tanh to a finite loss — the guard fires
    # on the NaN gradients, which is exactly why it checks grads too)
    for b, p in zip(before, m.parameters()):
        np.testing.assert_array_equal(b, p.numpy())  # bit-identical
    for k, v in opt._slots[id(m[0].weight)].items():
        np.testing.assert_array_equal(slots_before[k], np.asarray(v))
    assert step.skipped_steps == 1
    # the device-applied step rolled back: checkpoint resume must not
    # jump Adam bias correction ahead by the skips
    assert int(np.asarray(step._carry[0])) == opt._step_count - 1

    # counter surfaced through the profiler pull API
    c = profiler.counters()
    assert c[f"train_step/nonfinite_skipped#{id(step)}"] == 1

    # training resumes: params move again on a clean batch
    l2 = float(step(*_batch(rng)).item())
    assert np.isfinite(l2)
    assert any(not np.array_equal(b, p.numpy())
               for b, p in zip(before, m.parameters()))
    assert step.skipped_steps == 1


def test_parallel_guard_off_by_default_matches_on_for_clean_data():
    rng = np.random.default_rng(1)
    X = rng.standard_normal((8, 16)).astype(np.float32)
    Y = rng.standard_normal((8, 16)).astype(np.float32)

    def train(skip):
        paddle.seed(3)
        m = _mlp()
        opt = optimizer.AdamW(learning_rate=0.01,
                              parameters=m.parameters())
        mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
        step = ParallelTrainStep(m, nn.MSELoss(), opt, mesh,
                                 skip_nonfinite=skip)
        losses = [float(step(paddle.to_tensor(X),
                             paddle.to_tensor(Y)).item())
                  for _ in range(4)]
        return losses, _param_state(m)

    l_off, w_off = train(False)
    l_on, w_on = train(True)
    # the guard's jnp.where ops change XLA fusion, so the clean path is
    # numerically equal, not bit-equal (the bit-identity contract is
    # for the SKIPPED step's state, pinned above)
    np.testing.assert_allclose(l_off, l_on, rtol=1e-6)
    for a, b in zip(w_off, w_on):
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=1e-7)


def test_pipeline_train_step_skips_nonfinite():
    rng = np.random.default_rng(2)
    paddle.seed(11)
    pipe = _pipe()
    opt = optimizer.Adam(learning_rate=0.01,
                         parameters=pipe.parameters())
    mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
    step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                             n_microbatches=2, skip_nonfinite=True)

    X = rng.standard_normal((8, 8)).astype(np.float32)
    Y = rng.standard_normal((8, 8)).astype(np.float32)
    l0 = float(step(paddle.to_tensor(X), paddle.to_tensor(Y)).item())
    assert np.isfinite(l0)

    pre_before = pipe.pre_layers[0].weight.numpy().copy()
    body_before = [np.asarray(s).copy() for s in step._stacked_body]
    post_before = pipe.post_layers[0].weight.numpy().copy()

    Xbad = X.copy()
    Xbad[3, 3] = np.inf
    lbad = float(step(paddle.to_tensor(Xbad),
                      paddle.to_tensor(Y)).item())
    assert not np.isfinite(lbad)
    np.testing.assert_array_equal(pre_before,
                                  pipe.pre_layers[0].weight.numpy())
    np.testing.assert_array_equal(post_before,
                                  pipe.post_layers[0].weight.numpy())
    for b, s in zip(body_before, step._stacked_body):
        np.testing.assert_array_equal(b, np.asarray(s))
    assert step.skipped_steps == 1
    assert int(np.asarray(step._carry[0])) == opt._step_count - 1
    assert profiler.counters()[
        f"train_step/nonfinite_skipped#{id(step)}"] == 1

    # recovers on the clean batch
    l2 = float(step(paddle.to_tensor(X), paddle.to_tensor(Y)).item())
    assert np.isfinite(l2)
    assert not np.array_equal(pre_before,
                              pipe.pre_layers[0].weight.numpy())
    assert step.skipped_steps == 1


def test_pipeline_guard_off_matches_on_for_clean_data():
    rng = np.random.default_rng(4)
    X = rng.standard_normal((8, 8)).astype(np.float32)
    Y = rng.standard_normal((8, 8)).astype(np.float32)

    def train(skip):
        paddle.seed(13)
        pipe = _pipe()
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=pipe.parameters())
        mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
        step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                                 n_microbatches=2, skip_nonfinite=skip)
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item())
                for _ in range(3)]

    np.testing.assert_allclose(train(False), train(True), rtol=1e-6)
