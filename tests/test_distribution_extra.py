"""Wider distribution zoo + transforms + signal.stft/istft (reference:
python/paddle/distribution/*.py, python/paddle/signal.py). Numeric
references: scipy.stats where available, else closed forms."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D

try:
    from scipy import stats as S
    HAVE_SCIPY = True
except ImportError:
    HAVE_SCIPY = False


def _np(t):
    return np.asarray(t._data)


@pytest.mark.skipif(not HAVE_SCIPY, reason="scipy unavailable")
@pytest.mark.parametrize("dist,ref,xs", [
    (lambda: D.Beta(2.0, 3.0), lambda: S.beta(2, 3), [0.2, 0.5, 0.9]),
    (lambda: D.Gamma(2.0, 1.5), lambda: S.gamma(2, scale=1 / 1.5),
     [0.5, 1.0, 3.0]),
    (lambda: D.LogNormal(0.3, 0.8), lambda: S.lognorm(0.8,
     scale=np.exp(0.3)), [0.5, 1.0, 2.0]),
    (lambda: D.Cauchy(0.5, 2.0), lambda: S.cauchy(0.5, 2.0),
     [-1.0, 0.5, 3.0]),
    (lambda: D.StudentT(5.0, 0.0, 1.0), lambda: S.t(5), [-1.0, 0.0, 2.0]),
    (lambda: D.Poisson(3.0), lambda: S.poisson(3.0), [0.0, 2.0, 5.0]),
    (lambda: D.Geometric(0.3), lambda: S.geom(0.3, loc=-1),
     [0.0, 1.0, 4.0]),
    (lambda: D.Binomial(10.0, 0.4), lambda: S.binom(10, 0.4),
     [0.0, 4.0, 10.0]),
])
def test_log_prob_matches_scipy(dist, ref, xs):
    d, r = dist(), ref()
    for x in xs:
        got = float(_np(d.log_prob(paddle.to_tensor(np.float32(x)))))
        want = r.logpmf(x) if hasattr(r, "pmf") else r.logpdf(x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.skipif(not HAVE_SCIPY, reason="scipy unavailable")
def test_dirichlet_and_multinomial_log_prob():
    conc = np.asarray([1.5, 2.0, 3.0], "float32")
    d = D.Dirichlet(paddle.to_tensor(conc))
    x = np.asarray([0.2, 0.3, 0.5], "float32")
    np.testing.assert_allclose(
        float(_np(d.log_prob(paddle.to_tensor(x)))),
        S.dirichlet(conc).logpdf(x), rtol=1e-4)
    m = D.Multinomial(6, paddle.to_tensor(
        np.asarray([0.2, 0.3, 0.5], "float32")))
    counts = np.asarray([1.0, 2.0, 3.0], "float32")
    np.testing.assert_allclose(
        float(_np(m.log_prob(paddle.to_tensor(counts)))),
        S.multinomial(6, [0.2, 0.3, 0.5]).logpmf([1, 2, 3]), rtol=1e-4)


@pytest.mark.skipif(not HAVE_SCIPY, reason="scipy unavailable")
def test_multivariate_normal_log_prob_and_sampling():
    mu = np.asarray([1.0, -1.0], "float32")
    cov = np.asarray([[2.0, 0.6], [0.6, 1.0]], "float32")
    d = D.MultivariateNormal(paddle.to_tensor(mu), paddle.to_tensor(cov))
    x = np.asarray([0.5, 0.0], "float32")
    np.testing.assert_allclose(
        float(_np(d.log_prob(paddle.to_tensor(x)))),
        S.multivariate_normal(mu, cov).logpdf(x), rtol=1e-4)
    paddle.seed(0)
    s = _np(d.sample((20000,)))
    np.testing.assert_allclose(s.mean(0), mu, atol=0.05)
    np.testing.assert_allclose(np.cov(s.T), cov, atol=0.1)


def test_sampling_moments():
    paddle.seed(0)
    g = D.Gamma(3.0, 2.0)
    s = _np(g.sample((20000,)))
    np.testing.assert_allclose(s.mean(), 1.5, atol=0.05)
    b = D.Beta(2.0, 2.0)
    np.testing.assert_allclose(_np(b.sample((20000,))).mean(), 0.5,
                               atol=0.02)
    p = D.Poisson(4.0)
    np.testing.assert_allclose(_np(p.sample((20000,))).mean(), 4.0,
                               atol=0.1)


def test_kl_closed_forms_vs_monte_carlo():
    paddle.seed(0)
    for p, q in [(D.Gamma(2.0, 1.0), D.Gamma(3.0, 1.5)),
                 (D.Beta(2.0, 3.0), D.Beta(3.0, 2.0)),
                 (D.Poisson(3.0), D.Poisson(5.0))]:
        kl = float(_np(D.kl_divergence(p, q)))
        s = p.sample((50000,))
        mc = float(_np(p.log_prob(s) - q.log_prob(s)).mean())
        np.testing.assert_allclose(kl, mc, rtol=0.1, atol=0.02)
        assert kl > 0


def test_kl_base_pairs_still_work():
    kl = float(_np(D.kl_divergence(D.Normal(0.0, 1.0),
                                   D.Normal(1.0, 2.0))))
    assert kl > 0


def test_independent_sums_event_dims():
    base = D.Normal(paddle.to_tensor(np.zeros((3, 4), "float32")),
                    paddle.to_tensor(np.ones((3, 4), "float32")))
    ind = D.Independent(base, 1)
    x = paddle.to_tensor(np.zeros((3, 4), "float32"))
    lp = _np(ind.log_prob(x))
    assert lp.shape == (3,)
    np.testing.assert_allclose(lp, _np(base.log_prob(x)).sum(-1),
                               rtol=1e-6)


def test_transformed_distribution_lognormal_equivalence():
    """exp(Normal) through TransformedDistribution == LogNormal."""
    td = D.TransformedDistribution(D.Normal(0.2, 0.7),
                                   [D.ExpTransform()])
    ln = D.LogNormal(0.2, 0.7)
    for x in (0.5, 1.0, 2.5):
        np.testing.assert_allclose(
            float(_np(td.log_prob(paddle.to_tensor(np.float32(x))))),
            float(_np(ln.log_prob(paddle.to_tensor(np.float32(x))))),
            rtol=1e-5)


def test_affine_chain_transform_roundtrip():
    t = D.ChainTransform([D.AffineTransform(1.0, 2.0),
                          D.TanhTransform()])
    x = paddle.to_tensor(np.asarray([0.1, -0.3], "float32"))
    y = t.forward(x)
    back = t.inverse(y)
    np.testing.assert_allclose(_np(back), _np(x), rtol=1e-4, atol=1e-6)


def test_stickbreaking_simplex_roundtrip():
    t = D.StickBreakingTransform()
    x = paddle.to_tensor(np.asarray([0.3, -0.2, 0.5], "float32"))
    y = _np(t.forward(x))
    assert y.shape == (4,)
    np.testing.assert_allclose(y.sum(), 1.0, rtol=1e-5)
    assert (y > 0).all()
    np.testing.assert_allclose(_np(t.inverse(paddle.to_tensor(y))),
                               _np(x), rtol=1e-4, atol=1e-5)


def test_grad_flows_through_log_prob():
    a = paddle.to_tensor(np.float32(2.0))
    a.stop_gradient = False
    d = D.Gamma(a, 1.0)
    lp = d.log_prob(paddle.to_tensor(np.float32(1.5)))
    lp.backward()
    assert a.grad is not None and np.isfinite(float(a.grad._data))


# ---------------------------------------------------------------------------
# signal
# ---------------------------------------------------------------------------

def test_stft_istft_roundtrip():
    paddle.seed(0)
    t = 2048
    x = np.random.default_rng(0).normal(size=(2, t)).astype("float32")
    n_fft, hop = 256, 64
    win = np.hanning(n_fft).astype("float32")
    spec = paddle.signal.stft(paddle.to_tensor(x), n_fft,
                              hop_length=hop,
                              window=paddle.to_tensor(win))
    assert tuple(spec.shape)[:2] == (2, n_fft // 2 + 1)
    back = paddle.signal.istft(spec, n_fft, hop_length=hop,
                               window=paddle.to_tensor(win), length=t)
    got = np.asarray(back._data)
    # interior reconstruction exact (edges lose half-window coverage)
    sl = slice(n_fft, t - n_fft)
    np.testing.assert_allclose(got[:, sl], x[:, sl], rtol=1e-3,
                               atol=1e-4)


def test_stft_matches_numpy_frame_dft():
    x = np.random.default_rng(1).normal(size=(512,)).astype("float32")
    n_fft, hop = 128, 32
    spec = paddle.signal.stft(paddle.to_tensor(x), n_fft,
                              hop_length=hop, center=False)
    got = np.asarray(spec._data)
    n_frames = 1 + (512 - n_fft) // hop
    assert got.shape == (n_fft // 2 + 1, n_frames)
    for fi in (0, 3, n_frames - 1):
        frame = x[fi * hop: fi * hop + n_fft]
        ref = np.fft.rfft(frame)
        np.testing.assert_allclose(got[:, fi], ref, rtol=1e-3, atol=1e-3)
