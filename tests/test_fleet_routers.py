"""Replicated control plane acceptance pins (ISSUE 16).

Four layers:

* unit — rendezvous hashing (stability under membership change),
  ``LeaseStore`` lifecycle + exact incarnation accounting,
  reader-monotonic TTL (wall skew cannot steal a live lease),
  generation fencing (``fence_request``), keyed fault flags;
* model-free — loopback router twins over :class:`SimReplica`:
  orphan hand-over when rendezvous gives a router zero replicas,
  supervisor restart keyed by (worker id, generation);
* tiny-Llama e2e — the headline guarantee: a 2-router fleet whose
  request-owning router is SIGKILLed mid-decode produces BIT-IDENTICAL
  streams (greedy AND sampled) to an uninterrupted single-router
  reference, through both adoption paths (attach-in-place when the
  engine copy survives, recompute-from-lease when the replica died
  with its router);
* simulation — the discrete-event fleet sim at tier-1 scale, plus the
  100-replica acceptance run (slow-marked) with the <60 s wall bound.
"""
import time

import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.replica_registry import MemStore, ReplicaRegistry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, SamplingParams
from paddle_tpu.serving.fleet import (
    ChaosEvent, FleetConfig, FleetRouter, FleetSim, InProcessReplica,
    LeaseStore, LoadThresholdPolicy, ReplicaHandle, SimReplica,
    diurnal_trace, rendezvous_owner, sim_token, spike_trace,
)
from paddle_tpu.serving.fleet.supervisor import (
    ReplicaSupervisor, SupervisorConfig, _Slot,
)
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _no_fault_leak():
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# rendezvous hashing
# ---------------------------------------------------------------------------
class TestRendezvous:
    def test_deterministic_and_total(self):
        owners = ["R0", "R1", "R2"]
        for key in ("tenant-a", "sr042", "adopt:req-7"):
            assert rendezvous_owner(key, owners) == \
                rendezvous_owner(key, list(reversed(owners)))
            assert rendezvous_owner(key, owners) in owners
        assert rendezvous_owner("x", []) is None

    def test_member_removal_only_moves_its_keys(self):
        owners = [f"R{i}" for i in range(4)]
        keys = [f"k{i}" for i in range(200)]
        before = {k: rendezvous_owner(k, owners) for k in keys}
        after = {k: rendezvous_owner(k, owners[:-1]) for k in keys}
        for k in keys:
            if before[k] != "R3":
                assert after[k] == before[k]  # others never reshuffle
        moved = [k for k in keys if before[k] == "R3"]
        assert moved and all(after[k] != "R3" for k in moved)

    def test_spreads_load(self):
        owners = ["R0", "R1", "R2"]
        hist = {o: 0 for o in owners}
        for i in range(300):
            hist[rendezvous_owner(f"key{i}", owners)] += 1
        assert all(v > 50 for v in hist.values()), hist


# ---------------------------------------------------------------------------
# LeaseStore
# ---------------------------------------------------------------------------
class TestLeaseStore:
    def test_lifecycle_and_accounting(self):
        ls = LeaseStore(MemStore(), ttl_s=5.0)
        gen = ls.acquire("r1", "A", {"progress": []})
        assert gen == 0 and ls.active() == 1
        assert ls.renew("r1", "A", gen, progress=[1, 2])
        assert ls._load("r1")["progress"] == [1, 2]
        assert ls.release("r1", "A", gen)
        assert ls.active() == 0
        assert (ls.num_acquired, ls.num_completed) == (1, 1)

    def test_fresh_foreign_lease_not_acquirable(self):
        ls = LeaseStore(MemStore(), ttl_s=5.0)
        assert ls.acquire("r1", "A", {}) == 0
        assert ls.acquire("r1", "B", {}) is None
        assert ls.acquire("r1", "A", {}) == 0  # own retry keeps gen

    def test_stale_foreign_supersede_buckets_expired(self):
        store = MemStore()
        ls = LeaseStore(store, ttl_s=0.5)
        t = [0.0]
        ls._mono = lambda: t[0]
        assert ls.acquire("r1", "A", {}) == 0
        assert ls.fresh("r1")  # first sighting counts as a change
        t[0] = 10.0  # TTL lapses with no seq change
        assert ls.fresh("r1") is False
        gen = ls.acquire("r1", "B", {})
        assert gen == 1  # superseded with a bumped generation
        assert ls.num_expired == 1 and ls.num_acquired == 2

    def test_renew_and_release_fence_on_owner_and_gen(self):
        ls = LeaseStore(MemStore(), ttl_s=5.0)
        gen = ls.acquire("r1", "A", {})
        assert not ls.renew("r1", "B", gen)       # wrong owner
        assert not ls.renew("r1", "A", gen + 1)   # wrong generation
        assert not ls.release("r1", "B", gen)
        assert ls.num_fence_refusals == 3
        assert ls.active() == 1  # fenced calls never mutate

    def test_adopt_bumps_gen_and_fences_old_owner(self):
        ls = LeaseStore(MemStore(), ttl_s=5.0)
        gen = ls.acquire("r1", "A", {"progress": [1]})
        res = ls.adopt("r1", "B", outcome="adopted")
        assert res is not None
        new_gen, old = res
        assert new_gen == gen + 1 and old["owner"] == "A"
        assert not ls.renew("r1", "A", gen)  # stale owner fenced
        assert ls.renew("r1", "B", new_gen)
        assert ls.adopt("r1", "B", outcome="adopted") is None  # own
        assert (ls.num_acquired, ls.num_adopted) == (2, 1)
        assert ls.release("r1", "B", new_gen)
        # fleet-total invariant: every incarnation in exactly one bucket
        assert ls.num_acquired == \
            ls.num_completed + ls.num_adopted + ls.num_expired

    def test_adoption_clears_orphan_flag(self):
        ls = LeaseStore(MemStore(), ttl_s=5.0)
        ls.acquire("r1", "A", {"orphan": True})
        ls.adopt("r1", "B", outcome="adopted")
        assert "orphan" not in ls._load("r1")

    def test_wall_clock_skew_cannot_steal(self):
        """Freshness runs on the READER's monotonic clock: a writer
        whose wall clock is hours behind still holds its lease as long
        as its seq keeps changing."""
        store = MemStore()
        writer = LeaseStore(store, ttl_s=0.5)
        reader = LeaseStore(store, ttl_s=0.5)
        rt = [0.0]
        reader._mono = lambda: rt[0]
        gen = writer.acquire("r1", "A", {})
        for _ in range(5):
            rt[0] += 0.4  # under TTL between renew sightings
            assert writer.renew("r1", "A", gen)
            assert reader.fresh("r1")
        rt[0] += 10.0  # renewals stop: NOW it goes stale
        assert not reader.fresh("r1")

    def test_expire_fault_drops_write_and_returns_false(self):
        ls = LeaseStore(MemStore(), ttl_s=5.0)
        gen = ls.acquire("r1", "A", {"progress": []})
        faults.install("fleet.lease_expire:flag:r1*1")
        assert not ls.renew("r1", "A", gen, progress=[1])
        assert ls.num_renew_dropped == 1
        assert ls._load("r1")["progress"] == []  # write really dropped
        assert ls.renew("r1", "A", gen, progress=[1])  # budget spent

    def test_rid_validation(self):
        ls = LeaseStore(MemStore())
        with pytest.raises(ValueError):
            ls.acquire("a/b", "A", {})
        with pytest.raises(ValueError):
            ls.acquire("a__b", "A", {})


# ---------------------------------------------------------------------------
# keyed fault flags + replica-side generation fence
# ---------------------------------------------------------------------------
class TestFencing:
    def test_keyed_flag_only_hits_matching_key(self):
        inj = faults.install("p:flag:target*1")
        assert faults.check("p", key="other") == []
        assert inj.faults("p")[0].hits == 0  # budget NOT burned
        assert faults.check("p", key="target") == ["target"]
        assert faults.check("p", key="target") == []  # *1 spent

    def test_argless_flag_matches_every_key(self):
        faults.install("p:flag")
        assert faults.check("p", key="anything") == [None]
        assert faults.check("p") == [None]

    def test_fence_request_refuses_stale_generation(self):
        h = SimReplica("sr0")
        assert h.fence_request("r1", 0)
        assert h.fence_request("r1", 0)      # idempotent re-assert
        assert h.fence_request("r1", 2)
        assert not h.fence_request("r1", 1)  # stale owner refused
        assert h.fence_request("r1", 2)

    def test_fence_table_bounded(self):
        h = SimReplica("sr0")
        for i in range(400):
            h.fence_request(f"r{i}", 1)
        assert len(h._request_fences) <= 256


# ---------------------------------------------------------------------------
# SimReplica: deterministic streams + adoption surface
# ---------------------------------------------------------------------------
class TestSimReplica:
    def test_stream_is_position_keyed_and_exact(self):
        h = SimReplica("sr0")
        h.add_request("r1", [1, 2, 3], SamplingParams(max_new_tokens=4))
        gens = []
        while h.has_unfinished():
            gens += h.step()
        assert gens[-1].finished and gens[-1].finish_reason == "length"
        assert gens[-1].generated == [sim_token("r1", i)
                                      for i in range(4)]

    def test_rng_state_rides_position_through_adoption(self):
        a = SimReplica("sra")
        a.add_request("r1", [1], SamplingParams(max_new_tokens=6))
        a.step(); a.step()
        state = a.rng_state("r1")
        assert state == {"pos": 2}
        b = SimReplica("srb")
        b.add_request("r1", [1], SamplingParams(max_new_tokens=4),
                      rng_state=state)
        outs = []
        while b.has_unfinished():
            outs += b.step()
        # resumed copy continues the ABSOLUTE position stream
        assert outs[-1].generated == [sim_token("r1", 2 + i)
                                      for i in range(4)]

    def test_duplicate_rid_raises(self):
        h = SimReplica("sr0")
        h.add_request("r1", [1], SamplingParams())
        with pytest.raises(ValueError):
            h.add_request("r1", [1], SamplingParams())

    def test_zombie_rng_survives_abort_until_release(self):
        h = SimReplica("sr0")
        h.add_request("r1", [1], SamplingParams(max_new_tokens=8))
        h.step()
        assert h.abort_request("r1")
        assert h.rng_state("r1") == {"pos": 1}  # adoption window
        h.release_request("r1")
        assert h.rng_state("r1") is None

    def test_traces_are_deterministic_per_seed(self):
        kw = dict(duration_s=5.0, tenants=["a", "b"], seed=3)
        assert diurnal_trace(**kw) == diurnal_trace(**kw)
        t = spike_trace(duration_s=5.0, tenants=["a"], spike_at=[2.0],
                        spike_n=7, seed=3)
        assert sum(1 for a in t if a.t == 2.0) == 7


# ---------------------------------------------------------------------------
# loopback twins over SimReplica (model-free routed behavior)
# ---------------------------------------------------------------------------
def _twin_routers(replicas, **cfg_kw):
    store = MemStore()
    cfg = FleetConfig(heartbeat_interval_s=0.0, router_ttl_s=0.5,
                      lease_ttl_s=1.0, prefix_affinity=False,
                      peer_data_plane=False, **cfg_kw)
    routers = []
    for name in ("A", "B"):
        reg = ReplicaRegistry(store, ttl_s=30.0)
        routers.append(FleetRouter(
            replicas, cfg, reg,
            lease_store=LeaseStore(store, ttl_s=cfg.lease_ttl_s),
            router_id=name))
    for r in routers:
        r.step()  # discover each other
    return routers


class TestTwinRouters:
    def test_lease_fencing_counters_surfaced_by_metrics(self):
        """PR 18 bumped num_fence_refusals/num_renew_dropped but no
        fleet gauge surfaced either — the counter-snapshot-drift class
        this PR's linter now catches at commit time."""
        ra, _rb = _twin_routers([SimReplica("sr0")])
        ls = ra.lease_store
        gen = ls.acquire("r1", ra.router_id, {})
        assert not ls.renew("r1", "intruder", gen)   # fenced
        snap = ra.snapshot()
        assert snap["fleet_lease_fence_refusals"] == \
            ls.num_fence_refusals == 1
        assert snap["fleet_lease_renew_dropped"] == ls.num_renew_dropped

    def test_replica_ownership_partitions(self):
        replicas = [SimReplica(f"sr{i}") for i in range(8)]
        ra, rb = _twin_routers(replicas)
        own_a = {h.replica_id for h in ra._own_dispatchable()}
        own_b = {h.replica_id for h in rb._own_dispatchable()}
        assert own_a and own_b
        assert own_a.isdisjoint(own_b)
        assert own_a | own_b == {h.replica_id for h in replicas}

    def test_orphan_handover_when_owning_no_replica(self):
        # one replica: rendezvous gives it to exactly one router; the
        # OTHER router admits for the fleet and hands the request over
        # through an orphan lease (adopted immediately, no TTL wait)
        h = SimReplica("sr0")
        ra, rb = _twin_routers([h])
        loser = ra if not ra._own_dispatchable() else rb
        winner = rb if loser is ra else ra
        assert winner._own_dispatchable()
        loser.add_request("req-0", [1, 2],
                          SamplingParams(max_new_tokens=3))
        got = {}
        for _ in range(30):
            for r in (loser, winner):
                for out in r.step():
                    if out.finished:
                        got[out.request_id] = out
            if "req-0" in got:
                break
        out = got["req-0"]
        assert out.generated == [sim_token("req-0", i)
                                 for i in range(3)]
        assert loser.num_requests_handed_over == 1
        ls = loser.lease_store
        assert ls.active() == 0
        total_acq = sum(r.lease_store.num_acquired for r in (ra, rb))
        total_done = sum(r.lease_store.num_completed +
                         r.lease_store.num_adopted +
                         r.lease_store.num_expired for r in (ra, rb))
        assert total_acq == total_done

    def test_late_commit_from_stale_router_is_refused(self):
        """The double-execution guard: after a steal, the old owner's
        next renew-before-emit returns False and it drops its copy
        without emitting — the client never sees two streams."""
        replicas = [SimReplica(f"sr{i}") for i in range(2)]
        ra, rb = _twin_routers(replicas)
        ra.add_request("req-0", [1], SamplingParams(max_new_tokens=6))
        # step until some router holds the dispatched lease (an orphan
        # hand-over may have moved it off the admitting router)
        owner = None
        for _ in range(20):
            ra.step(); rb.step()
            for r in (ra, rb):
                fr = r._open.get("req-0")
                if fr is not None and fr.lease_gen is not None \
                        and fr.replica_id is not None:
                    owner = r
            if owner is not None:
                break
        assert owner is not None
        other = rb if owner is ra else ra
        # a peer force-adopts the LIVE lease out from under the owner
        faults.install("fleet.lease_steal:flag:req-0*1")
        finished = {}
        for _ in range(40):
            for r in (owner, other):
                for out in r.step():
                    if out.finished:
                        finished.setdefault(out.request_id, []).append(
                            (r.router_id, out.generated))
            if "req-0" in finished:
                break
        # exactly one terminal, exact stream — wherever the request
        # ends up (the stealing adopter may own no replica and hand it
        # straight back through an orphan lease; still exactly-once)
        assert len(finished["req-0"]) == 1
        _, gen = finished["req-0"][0]
        assert gen == [sim_token("req-0", i) for i in range(6)]
        assert owner.num_requests_fenced >= 1  # the late renew refused

    def test_heal_migration_resumes_from_emitted_progress(self):
        """A partition-heal hazard: while B was out, A dispatched onto
        a replica that rendezvous gives BACK to B at the heal. B's
        first step advances the engine copy and drops the foreign
        output on the floor — so when A migrates the request off the
        disowned replica, the live engine state runs AHEAD of A's
        emissions. The recovery point must be the emit-committed
        (progress, rng) pair; resuming from the live read would skip
        the unemitted position forever."""
        rep_id = next(f"mr{i}" for i in range(64)
                      if rendezvous_owner(f"mr{i}", ["A", "B"]) == "B")
        h = SimReplica(rep_id)
        ra, rb = _twin_routers([h])
        rb.partitioned = True
        ra.step()        # A observes B's last heartbeat...
        time.sleep(0.6)  # ...which then ages past router_ttl_s
        ra.step()        # A's view shrinks to {A}: it owns h now
        assert ra._routers_view == ["A"]
        ra.add_request("req-0", [1, 2],
                       SamplingParams(max_new_tokens=6))
        for _ in range(3):
            ra.step()
        fr = ra._open["req-0"]
        assert fr.replica_id == rep_id and 2 <= len(fr.progress) < 6
        # heal: B re-joins and steps h (its replica again) before A
        # notices — the engine produces a token nobody emits
        rb.partitioned = False
        rb.step()
        finished = {}
        for _ in range(60):
            for r in (ra, rb):
                for out in r.step():
                    if out.finished:
                        finished.setdefault(
                            out.request_id, []).append(
                                (r.router_id, list(out.generated)))
            if "req-0" in finished:
                break
        assert len(finished["req-0"]) == 1
        _, gen = finished["req-0"][0]
        assert gen == [sim_token("req-0", i) for i in range(6)]
        total_acq = sum(r.lease_store.num_acquired for r in (ra, rb))
        total_done = sum(r.lease_store.num_completed +
                         r.lease_store.num_adopted +
                         r.lease_store.num_expired for r in (ra, rb))
        assert total_acq == total_done
        assert ra.lease_store.active() == 0


# ---------------------------------------------------------------------------
# supervisor: restarts are keyed by (worker id, generation)
# ---------------------------------------------------------------------------
class _Corpse:
    """A dead SubprocessReplica stand-in."""

    def __init__(self, replica_id):
        self.replica_id = replica_id
        self.alive = False
        self.retiring = False
        self.created_at = time.monotonic()

    def close(self):
        pass


class TestSupervisorRestartKey:
    def _sup(self, tmp_path):
        return ReplicaSupervisor(config=SupervisorConfig(
            store_dir=str(tmp_path / "store"),
            restart_backoff_s=0.0, max_restarts=3))

    def test_reobserved_corpse_buys_no_second_restart(self, tmp_path,
                                                      monkeypatch):
        sup = self._sup(tmp_path)
        slot = _Slot("w0")
        corpse = _Corpse("w0-g0")
        slot.handle = corpse
        slot.proc = None
        sup._slots["w0"] = slot
        launched = []

        def fake_launch(s):
            h = _Corpse(f"{s.name}-g{s.generation}")
            h.alive = True
            s.generation += 1
            s.handle = h
            launched.append(h.replica_id)
            return h

        monkeypatch.setattr(sup, "_launch", fake_launch)
        sup.poll()              # schedules the (zero-backoff) restart
        events = sup.poll()     # executes it
        assert [e["event"] for e in events] == ["restarted"]
        assert launched == ["w0-g0"] and sup.num_restarts == 1
        # adoption re-observes the SAME corpse: the (id, generation)
        # key says its death already bought a restart — no second one
        slot.handle = corpse
        assert sup.poll() == []
        assert sup.num_restarts == 1 and launched == ["w0-g0"]
        slot.handle = _Corpse("w0-g5")  # a NEW generation's death does
        sup.poll()
        events = sup.poll()
        assert [e["event"] for e in events] == ["restarted"]
        assert sup.num_restarts == 2

    def test_failed_boot_does_not_mark_generation_handled(
            self, tmp_path, monkeypatch):
        sup = self._sup(tmp_path)
        slot = _Slot("w0")
        slot.handle = _Corpse("w0-g0")
        slot.proc = None
        sup._slots["w0"] = slot
        calls = [0]

        def flaky_launch(s):
            calls[0] += 1
            if calls[0] == 1:
                raise RuntimeError("boot failed")
            h = _Corpse(f"{s.name}-g{s.generation}")
            h.alive = True
            s.generation += 1
            s.handle = h
            return h

        monkeypatch.setattr(sup, "_launch", flaky_launch)
        sup.poll()                      # backoff
        assert sup.poll() == []         # boot fails; gen NOT handled
        assert "w0-g0" not in slot.handled_gens
        sup.poll()                      # reschedule
        events = sup.poll()             # retry succeeds
        assert [e["event"] for e in events] == ["restarted"]
        assert "w0-g0" in slot.handled_gens


# ---------------------------------------------------------------------------
# tiny-Llama e2e: SIGKILL failover is bit-identical
# ---------------------------------------------------------------------------
PROMPTS = [[1, 5, 7, 9], [2, 4, 6], [3, 8, 2, 1, 9]]


def _build_replicas(n):
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    return [InProcessReplica(model, EngineConfig(), replica_id=f"r{i}")
            for i in range(n)]


def _reference_streams(sampling):
    router = FleetRouter(_build_replicas(2),
                         FleetConfig(heartbeat_interval_s=0.0))
    rids = [router.add_request(f"req-{i}", p, sampling)
            for i, p in enumerate(PROMPTS)]
    router.run()
    return {rid: router.release_request(rid).generated for rid in rids}


def _failover_run(sampling, kill_replicas=False, n_replicas=2):
    """Two replicated routers; SIGKILL the one owning req traffic
    mid-decode (optionally its replicas too, forcing the
    recompute-from-lease adoption path); return terminal streams.
    Streams are per-request deterministic (greedy, or per-request
    seeded sampling), so replica count never changes the tokens."""
    store = MemStore()
    cfg = FleetConfig(heartbeat_interval_s=0.0, router_ttl_s=0.3,
                      lease_ttl_s=0.6)
    replicas = _build_replicas(n_replicas)
    routers = []
    for name in ("A", "B"):
        reg = ReplicaRegistry(store, ttl_s=30.0)
        routers.append(FleetRouter(
            replicas, cfg, reg,
            lease_store=LeaseStore(store, ttl_s=cfg.lease_ttl_s),
            router_id=name))
    ra, rb = routers
    ra.step(); rb.step()
    got = {}

    def collect(router):
        for out in router.step():
            if out.finished:
                got[out.request_id] = (router.router_id, out)

    for i, p in enumerate(PROMPTS):
        (ra if i % 2 == 0 else rb).add_request(f"req-{i}", p, sampling)
    for _ in range(3):
        collect(ra); collect(rb)
    victim = ra if any(
        fr.lease_gen is not None and not fr.finished
        for fr in ra._open.values()) else rb
    survivor = rb if victim is ra else ra
    faults.install(f"fleet.router_kill:flag:{victim.router_id}*1")
    collect(victim)  # dies at its own step prologue
    assert victim.router_dead
    if kill_replicas:
        # the host died, taking router AND replicas: the survivor must
        # keep at least one replica or there is nothing to recompute on
        doomed = victim_owned(victim)
        assert len(doomed) < len(victim.replicas)
        for h in doomed:
            h.alive = False
    deadline = time.monotonic() + 60
    while len(got) < len(PROMPTS) and time.monotonic() < deadline:
        collect(ra); collect(rb)
        time.sleep(0.01)
    assert len(got) == len(PROMPTS), sorted(got)
    assert survivor.num_router_failovers == 1
    total_acq = sum(r.lease_store.num_acquired for r in routers)
    total_closed = sum(r.lease_store.num_completed +
                       r.lease_store.num_adopted +
                       r.lease_store.num_expired for r in routers)
    assert total_acq == total_closed
    assert routers[0].lease_store.active() == 0
    return {rid: out.generated for rid, (_, out) in got.items()}


def victim_owned(victim):
    return [h for h in victim.replicas if victim._steps_replica(h)]


@pytest.mark.parametrize("sampling", [
    SamplingParams(max_new_tokens=12),
    SamplingParams(max_new_tokens=12, temperature=0.8, seed=7),
], ids=["greedy", "sampled"])
def test_router_sigkill_failover_bit_identical(sampling):
    ref = _reference_streams(sampling)
    got = _failover_run(sampling)
    assert got == ref


def test_router_and_replica_sigkill_recompute_bit_identical():
    """The harder path: the router dies WITH its replicas, so the
    survivor cannot attach in place — it recomputes from the lease's
    committed progress and RNG, and the sampled stream still matches
    the uninterrupted reference bit for bit."""
    sampling = SamplingParams(max_new_tokens=12, temperature=0.8,
                              seed=7)
    ref = _reference_streams(sampling)
    got = _failover_run(sampling, kill_replicas=True, n_replicas=3)
    assert got == ref


# ---------------------------------------------------------------------------
# fleet simulation
# ---------------------------------------------------------------------------
class TestFleetSim:
    def test_small_fleet_full_chaos_exact(self):
        sim = FleetSim(n_replicas=12, n_routers=2, seed=1)
        trace = diurnal_trace(duration_s=6.0, tenants=["a", "b", "c"],
                              base_rps=3, peak_rps=12, period_s=4,
                              seed=1)
        chaos = [ChaosEvent(t=1.0, kind="router_kill", arg="R0"),
                 ChaosEvent(t=2.0, kind="lease_expire"),
                 ChaosEvent(t=3.0, kind="lease_steal"),
                 ChaosEvent(t=4.0, kind="replica_kill")]
        sim.run(trace, chaos=chaos, max_virtual_s=120.0)
        summary = sim.check()
        assert summary["requests"] > 20
        assert summary["router_failovers"] >= 1

    def test_partition_heals_without_duplication(self):
        sim = FleetSim(n_replicas=12, n_routers=3, seed=2)
        trace = diurnal_trace(duration_s=6.0, tenants=["a", "b"],
                              base_rps=4, peak_rps=8, period_s=4,
                              seed=2)
        chaos = [ChaosEvent(t=1.0, kind="partition", arg="R1",
                            duration_s=1.5)]
        sim.run(trace, chaos=chaos, max_virtual_s=120.0)
        sim.check()

    def test_one_tenant_spike_needs_tenant_signal(self):
        """ISSUE 17 satellite: the fleet-MEAN load policy sleeps
        through a single tenant's burst (capacity absorbs it, the
        mean stays in band), while the same thresholds plus
        ``tenant_high`` see the dispatch-skew-amplified signal and
        scale up. Exactness invariants hold in both runs."""
        def build(policy):
            sim = FleetSim(n_replicas=12, n_routers=1, seed=7,
                           autoscale=policy)
            trace = spike_trace(
                duration_s=8.0, tenants=["a", "b", "c", "hot"],
                base_rps=4, spike_at=[2.0], spike_n=40,
                spike_tenant="hot", max_new=8, seed=7)
            # poll fast enough (virtual 50 ms) to catch the burst
            # in flight — it drains in ~8 decode steps
            sim.run(trace, autoscale_every_s=0.05,
                    max_virtual_s=240.0)
            sim.check()
            return sim

        scalar = build(LoadThresholdPolicy(
            high=0.95, low=0.0, max_replicas=20))
        assert scalar.scale_events == []
        assert scalar.routers[0].num_scale_ups == 0

        tenant = build(LoadThresholdPolicy(
            high=0.95, low=0.0, max_replicas=20, tenant_high=0.6))
        assert tenant.routers[0].num_scale_ups >= 1
        assert any(e["scale_to"] > 12 for e in tenant.scale_events)
        # the gauge that fed the trigger recorded the skew
        disp = tenant.routers[0].tenant_dispatches
        assert disp["hot"] >= 40
        assert disp["hot"] > max(disp.get(t, 0) for t in "abc")

    @pytest.mark.slow
    def test_hundred_replica_acceptance(self):
        """ISSUE 16 acceptance: >=100 replicas under a bursty trace
        with the full chaos menu, exact accounting, <60 s wall."""
        sim = FleetSim(n_replicas=100, n_routers=3, seed=2)
        trace = diurnal_trace(
            duration_s=20.0, tenants=[f"t{i}" for i in range(8)],
            base_rps=10, peak_rps=60, period_s=10, seed=2)
        chaos = [ChaosEvent(t=2.0, kind="router_kill", arg="R1"),
                 ChaosEvent(t=4.0, kind="lease_expire"),
                 ChaosEvent(t=6.0, kind="lease_steal"),
                 ChaosEvent(t=8.0, kind="partition", arg="R2",
                            duration_s=2.0),
                 ChaosEvent(t=10.0, kind="replica_kill"),
                 ChaosEvent(t=12.0, kind="lease_expire")]
        t0 = time.perf_counter()
        sim.run(trace, chaos=chaos)
        wall = time.perf_counter() - t0
        summary = sim.check()
        assert summary["requests"] > 400
        assert summary["router_failovers"] >= 1
        assert wall < 60.0, f"sim took {wall:.1f}s"
