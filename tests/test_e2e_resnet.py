"""Minimum end-to-end slice (SURVEY.md §7 step 5 / BASELINE.json config #1):
ResNet on CIFAR-10-like data, eager + compiled, loss must descend."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader
from paddle_tpu.jit import TrainStep
from paddle_tpu.vision.datasets import Cifar10
from paddle_tpu.vision.models import resnet18, resnet50


def test_resnet50_forward():
    m = resnet50(num_classes=10)
    m.eval()
    out = m(paddle.randn([2, 3, 32, 32]))
    assert out.shape == [2, 10]


def test_resnet18_train_loss_descends():
    paddle.seed(42)
    np.random.seed(42)
    m = resnet18(num_classes=10)
    m.train()
    opt = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=m.parameters())
    loss_fn = nn.CrossEntropyLoss()
    step = TrainStep(m, loss_fn, opt)

    # tiny fixed batch — overfit it
    X = paddle.randn([16, 3, 32, 32])
    Y = paddle.to_tensor(np.random.randint(0, 10, 16).astype(np.int64))
    losses = [float(step(X, Y).item()) for _ in range(12)]
    assert losses[-1] < losses[0], losses


def test_dataloader_with_cifar_synthetic():
    ds = Cifar10(mode="test")
    dl = DataLoader(ds, batch_size=32, shuffle=True, drop_last=True)
    xb, yb = next(iter(dl))
    assert xb.shape == [32, 3, 32, 32]
    assert yb.shape == [32]
    assert len(dl) == len(ds) // 32
