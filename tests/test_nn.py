import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_linear_forward_shape():
    layer = nn.Linear(4, 7)
    x = paddle.randn([2, 4])
    out = layer(x)
    assert out.shape == [2, 7]
    np.testing.assert_allclose(
        out.numpy(),
        x.numpy() @ layer.weight.numpy() + layer.bias.numpy(), rtol=1e-4,
        atol=1e-5)


def test_parameter_registration():
    layer = nn.Linear(3, 3)
    names = [n for n, _ in layer.named_parameters()]
    assert names == ["weight", "bias"]
    assert all(not p.stop_gradient for p in layer.parameters())


def test_sequential_and_sublayers():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    assert len(list(m.named_parameters())) == 4
    out = m(paddle.randn([3, 4]))
    assert out.shape == [3, 2]


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
    m2 = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
    m2.set_state_dict(m1.state_dict())
    x = paddle.randn([2, 4])
    np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_batchnorm_running_stats_and_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.randn([8, 3, 4, 4]) * 3.0 + 1.0
    bn.train()
    out = bn(x)
    assert abs(out.numpy().mean()) < 0.1
    m_after = bn._mean.numpy().copy()
    assert not np.allclose(m_after, 0)
    bn.eval()
    out_eval = bn(x)
    # eval uses running stats, not batch stats
    assert abs(out_eval.numpy().mean()) > 1e-4


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = paddle.ones([100, 100])
    d.train()
    y = d(x)
    assert (y.numpy() == 0).mean() > 0.3
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_embedding_layer():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor(np.array([[0, 3], [5, 0]]))
    out = emb(idx)
    assert out.shape == [2, 2, 4]
    np.testing.assert_allclose(out.numpy()[0, 0], np.zeros(4))


def test_conv_bn_relu_stack():
    m = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8), nn.ReLU(),
        nn.MaxPool2D(2, 2))
    out = m(paddle.randn([2, 3, 8, 8]))
    assert out.shape == [2, 8, 4, 4]


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 5, 16])
    out = mha(x)
    assert out.shape == [2, 5, 16]


def test_transformer_encoder():
    enc = nn.TransformerEncoder(
        nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0), 2)
    out = enc(paddle.randn([2, 5, 16]))
    assert out.shape == [2, 5, 16]


def test_lstm_shapes():
    lstm = nn.LSTM(8, 16, num_layers=2)
    x = paddle.randn([4, 6, 8])  # [B, T, I]
    out, (h, c) = lstm(x)
    assert out.shape == [4, 6, 16]
    assert h.shape == [2, 4, 16]
    assert c.shape == [2, 4, 16]


def test_bidirectional_gru():
    gru = nn.GRU(8, 16, direction="bidirect")
    out, h = gru(paddle.randn([2, 5, 8]))
    assert out.shape == [2, 5, 32]


def test_grad_flows_through_layer():
    layer = nn.Linear(4, 2)
    x = paddle.randn([3, 4])
    loss = layer(x).sum()
    loss.backward()
    assert layer.weight.grad is not None
    assert layer.weight.grad.shape == [4, 2]


def test_forward_hooks():
    layer = nn.Linear(2, 2)
    calls = []
    layer.register_forward_pre_hook(lambda l, i: calls.append("pre"))
    layer.register_forward_post_hook(lambda l, i, o: calls.append("post"))
    layer(paddle.randn([1, 2]))
    assert calls == ["pre", "post"]


def test_apply_and_to_dtype():
    m = nn.Linear(3, 3)
    m.to(dtype="bfloat16")
    assert m.weight.dtype == paddle.bfloat16
    m.to(dtype="float32")
    assert m.weight.dtype == paddle.float32


def test_clip_grad_by_global_norm():
    from paddle_tpu.nn import ClipGradByGlobalNorm

    p = paddle.to_tensor(np.ones(4, np.float32), stop_gradient=False)
    g = paddle.to_tensor(np.full(4, 10.0, np.float32))
    clip = ClipGradByGlobalNorm(1.0)
    (p2, g2), = clip([(p, g)])
    np.testing.assert_allclose(np.linalg.norm(g2.numpy()), 1.0, rtol=1e-5)


def test_initializers():
    from paddle_tpu.nn import initializer as I

    w = I.XavierUniform()([100, 100], "float32")
    assert abs(np.asarray(w).mean()) < 0.01
    k = I.KaimingNormal()([64, 64], "float32")
    assert 0.1 < np.asarray(k).std() < 0.3
    c = I.Constant(3.0)([5], "float32")
    np.testing.assert_allclose(np.asarray(c), 3.0)
    o = I.Orthogonal()([8, 8], "float32")
    np.testing.assert_allclose(np.asarray(o) @ np.asarray(o).T, np.eye(8),
                               atol=1e-4)
