"""Perf smoke test (slow-marked): donation actually removes copies.

The regression this tripwires: someone drops ``donate_argnums`` (or
breaks the aliasing contract) and every step silently goes back to
allocate-and-copy for the whole parameter/optimizer state — exactly the
copy_frac=0.545 regime BENCH_r05 measured. Runs entirely on CPU: XLA:CPU
honors input/output aliasing, a frozen (stop_gradient) parameter is a
pass-through output that MUST be copied without donation and aliased
with it, so the donated executable provably contains and executes fewer
copy ops. Verified two ways — statically in the compiled HLO, and
dynamically by counting copy events with profiler.device_phases over a
tiny compiled step loop.
"""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, profiler

pytestmark = pytest.mark.slow


def _fresh(donate):
    paddle.seed(5)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    # frozen first layer: its weight/bias thread through the step
    # unchanged — pass-through outputs are where undonated executables
    # must materialize copies
    for p in m[0].parameters():
        p.stop_gradient = True
    opt = optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    return m, paddle.jit.TrainStep(m, nn.CrossEntropyLoss(), opt,
                                   donate=donate)


def _batch():
    rng = np.random.default_rng(0)
    X = paddle.to_tensor(rng.normal(size=(16, 8)).astype("float32"))
    Y = paddle.to_tensor(rng.integers(0, 4, 16).astype("int64"))
    return X, Y


def _compiled_text(step, X, Y):
    """The HLO text of the step exactly as TrainStep dispatches it."""
    args = (1, step._carry, [p._data for p in step._params],
            step._slots, [b._data for b in step._buffers],
            step._lr_arr, step._scaler_state, X._data, Y._data)
    return step._jitted.lower(*args).compile().as_text()


def _count_hlo_copies(text):
    return len(re.findall(r"= \S+ copy\(", text))


def test_donated_step_issues_fewer_copy_ops():
    X, Y = _batch()
    _, step_d = _fresh(donate=True)
    _, step_u = _fresh(donate=False)
    step_d(X, Y)  # compile + set _lr_arr
    step_u(X, Y)

    # static check: the donated executable aliases state into place
    txt_d = _compiled_text(step_d, X, Y)
    txt_u = _compiled_text(step_u, X, Y)
    assert "input_output_alias" in txt_d
    assert "input_output_alias" not in txt_u
    copies_d, copies_u = _count_hlo_copies(txt_d), _count_hlo_copies(txt_u)
    assert copies_d < copies_u, (
        f"donated step compiled to {copies_d} copy ops vs {copies_u} "
        f"undonated — donation is not removing copies")

    # dynamic check: run a tiny step loop under the profiler and count
    # executed copy ops via the public phase API (skipped, not failed,
    # if this platform produces no usable trace)
    ph_d = profiler.device_phases(lambda: step_d(X, Y), steps=3, warmup=0)
    ph_u = profiler.device_phases(lambda: step_u(X, Y), steps=3, warmup=0)
    if not ph_d or not ph_u or ph_u.get("total_device_ms", 0) == 0:
        pytest.skip("no device trace available on this platform")
    assert ph_d["copy_ops"] < ph_u["copy_ops"], (
        f"profiled copy ops: donated {ph_d['copy_ops']} vs undonated "
        f"{ph_u['copy_ops']}")


def test_phase_api_reports_copy_fraction():
    """device_phases exposes copy_frac as a first-class metric for any
    step fn (what bench.py records per config)."""
    X, Y = _batch()
    _, step = _fresh(donate=True)
    ph = profiler.device_phases(lambda: step(X, Y), steps=2)
    if not ph:
        pytest.skip("no device trace available on this platform")
    assert set(ph) >= {"compute_ms", "collective_ms", "copy_ms",
                       "total_device_ms", "compute_ops", "copy_ops"}
    if ph["total_device_ms"] > 0:
        assert 0.0 <= ph["copy_frac"] <= 1.0
