"""Real binary dataset formats: CIFAR pickle-tar and MNIST idx-gzip
parsing from local files (reference vision/datasets/cifar.py, mnist.py
parse the same formats after download; egress-free here, so the tests
synthesize format-faithful files)."""
import gzip
import io
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.vision.datasets import MNIST, Cifar10, Cifar100


def _write_cifar10_tar(path, n_train=20, n_test=10):
    rng = np.random.RandomState(0)

    def batch(n, label_key=b"labels"):
        return pickle.dumps({
            b"data": rng.randint(0, 256, (n, 3072), dtype=np.uint8),
            label_key: rng.randint(0, 10, n).tolist()})

    with tarfile.open(path, "w:gz") as tf:
        for i in range(2):
            raw = batch(n_train // 2)
            info = tarfile.TarInfo(f"cifar-10-batches-py/data_batch_{i+1}")
            info.size = len(raw)
            tf.addfile(info, io.BytesIO(raw))
        raw = batch(n_test)
        info = tarfile.TarInfo("cifar-10-batches-py/test_batch")
        info.size = len(raw)
        tf.addfile(info, io.BytesIO(raw))


def _write_mnist_idx(img_path, lbl_path, n=32):
    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, (n, 28, 28), dtype=np.uint8)
    lbls = rng.randint(0, 10, n, dtype=np.uint8)
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(lbls.tobytes())
    return imgs, lbls


def test_cifar10_parses_real_tar(tmp_path):
    p = str(tmp_path / "cifar-10-python.tar.gz")
    _write_cifar10_tar(p)
    train = Cifar10(data_file=p, mode="train")
    test = Cifar10(data_file=p, mode="test")
    assert len(train) == 20 and len(test) == 10
    img, label = train[0]
    assert img.shape == (3, 32, 32) and img.dtype == np.float32
    assert 0.0 <= img.min() and img.max() <= 1.0
    assert 0 <= int(label) < 10


def test_cifar100_fine_labels(tmp_path):
    rng = np.random.RandomState(2)
    p = str(tmp_path / "cifar-100-python.tar.gz")
    with tarfile.open(p, "w:gz") as tf:
        for name in ("cifar-100-python/train", "cifar-100-python/test"):
            raw = pickle.dumps({
                b"data": rng.randint(0, 256, (12, 3072), dtype=np.uint8),
                b"fine_labels": rng.randint(0, 100, 12).tolist()})
            info = tarfile.TarInfo(name)
            info.size = len(raw)
            tf.addfile(info, io.BytesIO(raw))
    ds = Cifar100(data_file=p, mode="train")
    assert len(ds) == 12
    _, label = ds[3]
    assert 0 <= int(label) < 100


def test_mnist_parses_idx_gzip(tmp_path):
    ip, lp = str(tmp_path / "img.gz"), str(tmp_path / "lbl.gz")
    imgs, lbls = _write_mnist_idx(ip, lp)
    ds = MNIST(image_path=ip, label_path=lp, mode="train")
    assert len(ds) == 32
    img, label = ds[5]
    np.testing.assert_allclose(
        img[0], imgs[5].astype(np.float32) / 255.0)
    assert int(label) == int(lbls[5])


def test_synthetic_fallback_when_files_absent(tmp_path):
    ds = Cifar10(data_file=str(tmp_path / "missing.tar.gz"),
                 mode="test")
    assert len(ds) > 0  # deterministic synthetic data keeps pipelines up
    img, label = ds[0]
    assert img.shape == (3, 32, 32)
