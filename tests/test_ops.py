"""Numpy-referenced op tests (the OpTest pattern,
reference: test/legacy_test/op_test.py:418)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(x, sg=True):
    return paddle.to_tensor(np.asarray(x, dtype=np.float32),
                            stop_gradient=sg)


class TestElementwise:
    def test_binary_broadcast(self):
        a = np.random.randn(3, 1, 4).astype(np.float32)
        b = np.random.randn(2, 4).astype(np.float32)
        np.testing.assert_allclose(paddle.add(t(a), t(b)).numpy(), a + b,
                                   rtol=1e-6)
        np.testing.assert_allclose(paddle.maximum(t(a), t(b)).numpy(),
                                   np.maximum(a, b))

    def test_unary_suite(self):
        x = np.random.rand(10).astype(np.float32) * 0.8 + 0.1
        for name, ref in [("exp", np.exp), ("log", np.log),
                          ("sqrt", np.sqrt), ("tanh", np.tanh),
                          ("floor", np.floor), ("ceil", np.ceil),
                          ("abs", np.abs), ("square", np.square)]:
            got = getattr(paddle, name)(t(x)).numpy()
            np.testing.assert_allclose(got, ref(x), rtol=1e-3, atol=1e-5,
                                       err_msg=name)

    def test_scale_clip(self):
        x = np.array([-2.0, 0.5, 3.0], dtype=np.float32)
        np.testing.assert_allclose(
            paddle.scale(t(x), scale=2.0, bias=1.0).numpy(), x * 2 + 1)
        np.testing.assert_allclose(paddle.clip(t(x), -1, 1).numpy(),
                                   np.clip(x, -1, 1))

    def test_where(self):
        c = np.array([True, False])
        np.testing.assert_allclose(
            paddle.where(paddle.to_tensor(c), t([1.0, 2.0]),
                         t([3.0, 4.0])).numpy(), [1.0, 4.0])


class TestReductions:
    def test_reductions(self):
        x = np.random.randn(3, 4, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.sum(t(x), axis=1).numpy(),
                                   x.sum(1), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.mean(t(x), axis=[0, 2], keepdim=True).numpy(),
            x.mean((0, 2), keepdims=True), rtol=1e-5)
        np.testing.assert_allclose(paddle.max(t(x), axis=-1).numpy(),
                                   x.max(-1))
        assert paddle.argmax(t(x), axis=1).numpy().tolist() == \
            x.argmax(1).tolist()

    def test_cumsum_logsumexp(self):
        x = np.random.randn(4, 3).astype(np.float32)
        np.testing.assert_allclose(paddle.cumsum(t(x), axis=0).numpy(),
                                   x.cumsum(0), rtol=1e-5)
        from scipy.special import logsumexp as slse
        np.testing.assert_allclose(paddle.logsumexp(t(x), axis=1).numpy(),
                                   slse(x, axis=1), rtol=1e-4)

    def test_var_std(self):
        x = np.random.randn(10).astype(np.float32)
        np.testing.assert_allclose(paddle.var(t(x)).numpy(), x.var(ddof=1),
                                   rtol=1e-4)
        np.testing.assert_allclose(
            paddle.std(t(x), unbiased=False).numpy(), x.std(), rtol=1e-4)


class TestManipulation:
    def test_reshape_family(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        assert paddle.reshape(t(x), [4, 6]).shape == [4, 6]
        assert paddle.flatten(t(x), 1).shape == [2, 12]
        assert paddle.squeeze(t(x[None]), 0).shape == [2, 3, 4]
        assert paddle.unsqueeze(t(x), [0, 2]).shape == [1, 2, 1, 3, 4]

    def test_concat_stack_split(self):
        a, b = np.ones((2, 3), np.float32), np.zeros((2, 3), np.float32)
        np.testing.assert_allclose(
            paddle.concat([t(a), t(b)], axis=0).numpy(),
            np.concatenate([a, b], 0))
        np.testing.assert_allclose(paddle.stack([t(a), t(b)], -1).numpy(),
                                   np.stack([a, b], -1))
        parts = paddle.split(t(np.arange(10, dtype=np.float32)), [3, -1])
        assert parts[0].shape == [3] and parts[1].shape == [7]

    def test_gather_scatter(self):
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        idx = np.array([0, 2])
        np.testing.assert_allclose(
            paddle.gather(t(x), paddle.to_tensor(idx)).numpy(), x[idx])
        np.testing.assert_allclose(
            paddle.index_select(t(x), paddle.to_tensor(idx), axis=1).numpy(),
            x[:, idx])
        got = paddle.scatter(t(x), paddle.to_tensor(np.array([1])),
                             t(np.full((1, 3), 9.0))).numpy()
        ref = x.copy()
        ref[1] = 9
        np.testing.assert_allclose(got, ref)

    def test_topk_sort(self):
        x = np.random.randn(5, 6).astype(np.float32)
        vals, idx = paddle.topk(t(x), 3, axis=1)
        ref = np.sort(x, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)
        np.testing.assert_allclose(paddle.sort(t(x), axis=0).numpy(),
                                   np.sort(x, axis=0))

    def test_pad(self):
        x = np.ones((1, 2, 3, 3), np.float32)
        out = paddle.ops.pad(t(x), [1, 1, 2, 2])
        assert out.shape == [1, 2, 7, 5]

    def test_tile_expand(self):
        x = np.array([[1.0, 2.0]], dtype=np.float32)
        assert paddle.tile(t(x), [2, 3]).shape == [2, 6]
        assert paddle.expand(t(x), [4, 2]).shape == [4, 2]
        assert paddle.broadcast_to(t(x), [5, 2]).shape == [5, 2]

    def test_take_put_along_axis(self):
        x = np.random.randn(3, 4).astype(np.float32)
        idx = np.array([[0, 1], [2, 0], [1, 3]])
        np.testing.assert_allclose(
            paddle.take_along_axis(t(x), paddle.to_tensor(idx), 1).numpy(),
            np.take_along_axis(x, idx, 1))

    def test_one_hot_unique(self):
        oh = paddle.one_hot(paddle.to_tensor(np.array([0, 2])), 3).numpy()
        np.testing.assert_allclose(oh, [[1, 0, 0], [0, 0, 1]])
        u = paddle.unique(paddle.to_tensor(np.array([3, 1, 3, 2]))).numpy()
        assert u.tolist() == [1, 2, 3]


class TestLinalg:
    def test_matmul_variants(self):
        a = np.random.randn(2, 3, 4).astype(np.float32)
        b = np.random.randn(2, 4, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.matmul(t(a), t(b)).numpy(),
                                   a @ b, rtol=1e-5)
        np.testing.assert_allclose(
            paddle.matmul(t(a), t(b.transpose(0, 2, 1)),
                          transpose_y=True).numpy(), a @ b, rtol=1e-5)

    def test_solve_inverse_det(self):
        a = np.random.randn(4, 4).astype(np.float32)
        a = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        b = np.random.randn(4, 2).astype(np.float32)
        np.testing.assert_allclose(paddle.solve(t(a), t(b)).numpy(),
                                   np.linalg.solve(a, b), rtol=1e-3,
                                   atol=1e-4)
        np.testing.assert_allclose(paddle.inverse(t(a)).numpy(),
                                   np.linalg.inv(a), rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(paddle.det(t(a)).numpy(),
                                   np.linalg.det(a), rtol=1e-3)

    def test_norm(self):
        x = np.random.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(paddle.ops.norm(t(x)).numpy(),
                                   np.linalg.norm(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.ops.norm(t(x), p=1, axis=1).numpy(),
                                   np.abs(x).sum(1), rtol=1e-5)

    def test_einsum_free(self):
        a = np.random.randn(5, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.ops.trace(t(a)).numpy(),
                                   np.trace(a), rtol=1e-5)


class TestNNOps:
    def test_softmax_logsoftmax(self):
        x = np.random.randn(3, 5).astype(np.float32)
        sm = paddle.softmax(t(x), axis=-1).numpy()
        e = np.exp(x - x.max(-1, keepdims=True))
        np.testing.assert_allclose(sm, e / e.sum(-1, keepdims=True),
                                   rtol=1e-5)
        np.testing.assert_allclose(paddle.ops.log_softmax(t(x)).numpy(),
                                   np.log(sm), rtol=1e-4, atol=1e-5)

    def test_conv2d_vs_naive(self):
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        w = np.random.randn(5, 3, 3, 3).astype(np.float32)
        out = paddle.ops.conv2d(t(x), t(w), stride=1, padding=1).numpy()
        assert out.shape == (2, 5, 8, 8)
        # check one output position against the direct sum
        xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
        ref = np.einsum("cij,ocij->o", xp[0, :, 3:6, 3:6], w)
        np.testing.assert_allclose(out[0, :, 3, 3], ref, rtol=1e-3,
                                   atol=1e-4)

    def test_conv_groups(self):
        x = np.random.randn(1, 4, 6, 6).astype(np.float32)
        w = np.random.randn(8, 2, 3, 3).astype(np.float32)
        out = paddle.ops.conv2d(t(x), t(w), padding=1, groups=2)
        assert out.shape == [1, 8, 6, 6]

    def test_pools(self):
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        mp = paddle.ops.max_pool2d(t(x), 2, 2).numpy()
        ref = x.reshape(1, 2, 2, 2, 2, 2).max((3, 5))
        np.testing.assert_allclose(mp, ref)
        ap = paddle.ops.avg_pool2d(t(x), 2, 2).numpy()
        np.testing.assert_allclose(ap, x.reshape(1, 2, 2, 2, 2, 2).mean(
            (3, 5)), rtol=1e-6)
        aap = paddle.ops.adaptive_avg_pool2d(t(x), 1).numpy()
        np.testing.assert_allclose(aap[..., 0, 0], x.mean((2, 3)), rtol=1e-6)

    def test_batch_norm_training_stats(self):
        x = np.random.randn(8, 3, 4, 4).astype(np.float32)
        rm = np.zeros(3, np.float32)
        rv = np.ones(3, np.float32)
        out, m, v = paddle.ops.batch_norm(t(x), t(rm), t(rv),
                                          training=True)
        np.testing.assert_allclose(m.numpy(), x.mean((0, 2, 3)), rtol=1e-4,
                                    atol=1e-5)
        np.testing.assert_allclose(out.numpy().mean((0, 2, 3)),
                                   np.zeros(3), atol=1e-5)

    def test_layer_norm(self):
        x = np.random.randn(2, 5).astype(np.float32)
        out = paddle.ops.layer_norm(t(x)).numpy()
        ref = (x - x.mean(-1, keepdims=True)) / np.sqrt(
            x.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_rms_norm(self):
        x = np.random.randn(2, 8).astype(np.float32)
        w = np.random.randn(8).astype(np.float32)
        out = paddle.ops.rms_norm(t(x), t(w)).numpy()
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_cross_entropy(self):
        logits = np.random.randn(4, 7).astype(np.float32)
        labels = np.array([0, 3, 6, 2])
        loss = paddle.ops.cross_entropy(t(logits),
                                        paddle.to_tensor(labels)).numpy()
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-5)

    def test_cross_entropy_ignore_index(self):
        logits = np.random.randn(4, 7).astype(np.float32)
        labels = np.array([0, -100, 6, -100])
        loss = paddle.ops.cross_entropy(t(logits),
                                        paddle.to_tensor(labels),
                                        ignore_index=-100).numpy()
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 6]]).mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-4)

    def test_embedding(self):
        w = np.random.randn(10, 4).astype(np.float32)
        idx = np.array([[1, 3], [0, 9]])
        out = paddle.ops.embedding(paddle.to_tensor(idx), t(w)).numpy()
        np.testing.assert_allclose(out, w[idx])

    def test_dropout_eval_and_scale(self):
        x = np.ones((100, 100), np.float32)
        out_eval = paddle.ops.dropout(t(x), p=0.5, training=False)
        np.testing.assert_allclose(out_eval.numpy(), x)
        out = paddle.ops.dropout(t(x), p=0.5, training=True).numpy()
        assert abs(out.mean() - 1.0) < 0.05  # upscale_in_train keeps E[x]
        assert (out == 0).mean() > 0.4

    def test_attention_causal(self):
        q = np.random.randn(2, 6, 2, 8).astype(np.float32)
        out = paddle.ops.scaled_dot_product_attention(
            t(q), t(q), t(q), is_causal=True)
        assert out.shape == [2, 6, 2, 8]
        # first position attends only to itself -> equals v[0]
        np.testing.assert_allclose(out.numpy()[:, 0], q[:, 0], rtol=1e-4,
                                   atol=1e-5)


class TestGradThroughOps:
    def test_conv_grad_shape(self):
        x = paddle.to_tensor(np.random.randn(1, 2, 5, 5).astype(np.float32),
                             stop_gradient=False)
        w = paddle.to_tensor(np.random.randn(3, 2, 3, 3).astype(np.float32),
                             stop_gradient=False)
        out = paddle.ops.conv2d(x, w, padding=1)
        out.sum().backward()
        assert x.grad.shape == x.shape
        assert w.grad.shape == w.shape

    def test_softmax_ce_grad_rowsum_zero(self):
        logits = paddle.to_tensor(
            np.random.randn(3, 5).astype(np.float32), stop_gradient=False)
        loss = paddle.ops.cross_entropy(
            logits, paddle.to_tensor(np.array([1, 2, 3])))
        loss.backward()
        np.testing.assert_allclose(logits.grad.numpy().sum(-1),
                                   np.zeros(3), atol=1e-6)

    def test_gather_grad(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32),
                             stop_gradient=False)
        out = paddle.gather(x, paddle.to_tensor(np.array([1, 1, 4])))
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0, 2, 0, 0, 1, 0])


class TestRandom:
    def test_seed_determinism(self):
        paddle.seed(7)
        a = paddle.rand([4]).numpy()
        paddle.seed(7)
        b = paddle.rand([4]).numpy()
        np.testing.assert_allclose(a, b)

    def test_shapes_ranges(self):
        u = paddle.uniform([1000], min=2.0, max=3.0).numpy()
        assert u.min() >= 2.0 and u.max() <= 3.0
        r = paddle.randint(0, 5, [100]).numpy()
        assert r.min() >= 0 and r.max() < 5
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))


def test_yaml_registry_complete():
    """Every yaml op must resolve and be callable; registry is authoritative."""
    from paddle_tpu.ops.registry import API, OPS
    assert len(OPS) > 200
    for name in OPS:
        assert callable(API[name])


def test_dataloader_multiprocess_workers_deterministic():
    """num_workers>0: forked workers fetch/collate; order matches the
    single-process loader exactly (reorder buffer)."""
    from paddle_tpu.io import DataLoader, Dataset

    class Squares(Dataset):
        def __len__(self):
            return 37

        def __getitem__(self, i):
            return np.asarray([i * i], np.float32), np.int64(i)

    ds = Squares()
    single = [(x.numpy(), y.numpy()) for x, y in
              DataLoader(ds, batch_size=5)]
    multi = [(x.numpy(), y.numpy()) for x, y in
             DataLoader(ds, batch_size=5, num_workers=3)]
    assert len(single) == len(multi) == 8
    for (xs, ys), (xm, ym) in zip(single, multi):
        np.testing.assert_array_equal(xs, xm)
        np.testing.assert_array_equal(ys, ym)


def test_dataloader_multiprocess_worker_init_and_info():
    from paddle_tpu.io import DataLoader, IterableDataset, get_worker_info

    class Stream(IterableDataset):
        def __iter__(self):
            info = get_worker_info()
            assert info is not None and info.num_workers == 2
            # each worker emits its own shard
            for i in range(info.id, 8, info.num_workers):
                yield np.asarray([i], np.int64)

    out = sorted(int(b.numpy().ravel()[0]) for b in
                 DataLoader(Stream(), batch_size=1, num_workers=2))
    assert out == list(range(8)), out


def test_dataloader_multiprocess_error_propagates():
    from paddle_tpu.io import DataLoader, Dataset

    class Bad(Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            if i == 2:
                raise ValueError("poison item")
            return np.asarray([i], np.float32)

    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="poison item"):
        list(DataLoader(Bad(), batch_size=2, num_workers=2))
