import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _quadratic_steps(opt_cls, steps=150, lr=0.1, **kw):
    w = paddle.to_tensor(np.array([5.0, -3.0], np.float32),
                         stop_gradient=False)
    opt = opt_cls(learning_rate=lr, parameters=[w], **kw)
    for _ in range(steps):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return np.abs(w.numpy()).max()


@pytest.mark.parametrize("opt_cls,kw", [
    (optimizer.SGD, {}),
    (optimizer.Momentum, {"momentum": 0.9}),
    (optimizer.Adam, {}),
    (optimizer.AdamW, {"weight_decay": 0.01}),
    (optimizer.RMSProp, {}),
    (optimizer.Adagrad, {"lr": 1.0}),
    (optimizer.Adamax, {}),
    (optimizer.Lamb, {}),
    (optimizer.NAdam, {}),
    (optimizer.RAdam, {}),
])
def test_optimizers_converge_on_quadratic(opt_cls, kw):
    final = _quadratic_steps(opt_cls, **kw)
    assert final < 1.0, f"{opt_cls.__name__} did not descend: {final}"


def test_sgd_exact_update():
    w = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    opt = optimizer.SGD(learning_rate=0.5, parameters=[w])
    (w * 3.0).backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.5 * 3.0])


def test_adamw_decoupled_decay():
    w = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    opt = optimizer.AdamW(learning_rate=0.0, parameters=[w],
                          weight_decay=0.1)
    (w * 1.0).backward()
    opt.step()
    # lr=0 -> decoupled decay term also 0 (paddle semantics: lr*coeff*p)
    np.testing.assert_allclose(w.numpy(), [1.0])


def test_optimizer_state_dict_roundtrip():
    w = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    opt = optimizer.Adam(parameters=[w])
    (w * w).sum().backward()
    opt.step()
    sd = opt.state_dict()
    opt2 = optimizer.Adam(parameters=[w])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    np.testing.assert_allclose(
        np.asarray(opt2._slots[id(w)]["moment1"]),
        np.asarray(opt._slots[id(w)]["moment1"]))


def test_grad_clip_in_optimizer():
    w = paddle.to_tensor(np.array([1.0], np.float32), stop_gradient=False)
    opt = optimizer.SGD(learning_rate=1.0, parameters=[w],
                        grad_clip=nn.ClipGradByGlobalNorm(0.1))
    (w * 100.0).backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1], rtol=1e-5)


def test_lr_scheduler_basic():
    sched = optimizer.lr.StepDecay(learning_rate=1.0, step_size=2, gamma=0.5)
    opt = optimizer.SGD(learning_rate=sched)
    lrs = []
    for _ in range(5):
        lrs.append(opt.get_lr())
        sched.step()
    np.testing.assert_allclose(lrs, [1.0, 1.0, 0.5, 0.5, 0.25])


def test_cosine_and_warmup():
    cos = optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(cos() - 1.0) < 1e-6
    for _ in range(10):
        cos.step()
    assert cos() < 1e-6
    warm = optimizer.lr.LinearWarmup(1.0, warmup_steps=10, start_lr=0.0,
                                     end_lr=1.0)
    warm.step(5)
    assert abs(warm() - 0.5) < 1e-6


def test_reduce_on_plateau():
    s = optimizer.lr.ReduceOnPlateau(1.0, patience=1, factor=0.5)
    for loss in [1.0, 1.0, 1.0, 1.0]:
        s.step(loss)
    assert s() == 0.5


def test_minimize_api():
    w = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    opt = optimizer.SGD(learning_rate=0.1, parameters=[w])
    loss = (w * w).sum()
    opt.minimize(loss)
    np.testing.assert_allclose(w.numpy(), [2.0 - 0.1 * 4.0], rtol=1e-6)
    assert w.grad is None


def test_bf16_param_dtype_stable_across_steps():
    """bf16 params must stay bf16 after optimizer updates (the rule
    computes in f32 internally); a silent f32 upcast retraces every
    compiled step and doubles param HBM."""
    w = paddle.to_tensor(np.array([5.0, -3.0], np.float32),
                         dtype="bfloat16", stop_gradient=False)
    opt = optimizer.AdamW(learning_rate=0.1, parameters=[w])
    for _ in range(3):
        loss = (w.astype("float32") ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert w.dtype.name == "bfloat16"
    slots = opt._slots[id(w)]
    assert all(v.dtype == np.dtype("bfloat16") or str(v.dtype) == "bfloat16" for v in slots.values())


def test_multi_precision_master_weights():
    """multi_precision=True keeps an f32 master copy for bf16 params and
    applies updates there (reference optimizer.py _create_master_weight)."""
    w = paddle.to_tensor(np.array([5.0, -3.0], np.float32),
                         dtype="bfloat16", stop_gradient=False)
    opt = optimizer.AdamW(learning_rate=0.05, parameters=[w],
                          multi_precision=True)
    for _ in range(120):
        loss = (w.astype("float32") ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    slots = opt._slots[id(w)]
    assert "master_weight" in slots
    assert slots["master_weight"].dtype == np.float32
    assert w.dtype.name == "bfloat16"
    # master weights track the true trajectory; bf16 copy mirrors them
    np.testing.assert_allclose(
        np.asarray(slots["master_weight"]).astype(np.float32),
        w.astype("float32").numpy(), rtol=1e-2, atol=1e-2)
    assert np.abs(w.astype("float32").numpy()).max() < 1.0


def test_trainstep_bf16_no_retrace():
    """Compiled TrainStep with bf16 params: params/slots keep dtype so the
    second step hits the jit cache (regression: bf16 1B bench retraced)."""
    paddle.set_default_dtype("bfloat16")
    try:
        model = nn.Linear(8, 8)
        opt = optimizer.AdamW(learning_rate=0.01,
                              parameters=model.parameters())
        step = paddle.jit.TrainStep(model, nn.MSELoss(), opt)
        X = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32)
                             ).astype("bfloat16")
        Y = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32)
                             ).astype("bfloat16")
        step(X, Y)
        p0 = model.parameters()[0]
        assert p0.dtype.name == "bfloat16"
        step(X, Y)
        assert model.parameters()[0].dtype.name == "bfloat16"
    finally:
        paddle.set_default_dtype("float32")
