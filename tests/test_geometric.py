"""paddle.geometric parity: message passing + segment math + sampling.

Reference: python/paddle/geometric/message_passing/send_recv.py
(send_u_recv/send_ue_recv/send_uv docstring examples give the expected
numerics), math.py, reindex.py, sampling/neighbors.py."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric as G


def test_send_u_recv_sum_mean_max_min():
    # the reference docstring graph: edges (0->1),(1->2),(2->1),(0->0)
    x = paddle.to_tensor(np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]],
                                  np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
    dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))
    out = G.send_u_recv(x, src, dst, reduce_op="sum")
    np.testing.assert_allclose(
        out.numpy(),
        np.array([[0, 2, 3], [2, 8, 10], [1, 4, 5]], np.float32))
    out = G.send_u_recv(x, src, dst, reduce_op="mean")
    np.testing.assert_allclose(
        out.numpy(),
        np.array([[0, 2, 3], [1, 4, 5], [1, 4, 5]], np.float32))
    out = G.send_u_recv(x, src, dst, reduce_op="max")
    np.testing.assert_allclose(
        out.numpy(),
        np.array([[0, 2, 3], [2, 6, 7], [1, 4, 5]], np.float32))
    out = G.send_u_recv(x, src, dst, reduce_op="min")
    np.testing.assert_allclose(
        out.numpy(),
        np.array([[0, 2, 3], [0, 2, 3], [1, 4, 5]], np.float32))


def test_send_u_recv_out_size_and_empty_segment():
    x = paddle.to_tensor(np.eye(3, dtype=np.float32))
    src = paddle.to_tensor(np.array([0, 1], np.int32))
    dst = paddle.to_tensor(np.array([0, 0], np.int32))
    out = G.send_u_recv(x, src, dst, reduce_op="max", out_size=2)
    assert out.shape == [2, 3]
    # empty segment 1 fills with zeros (reference semantics), not -inf
    np.testing.assert_allclose(out.numpy()[1], np.zeros(3))


def test_send_ue_recv_message_ops():
    x = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    e = paddle.to_tensor(np.array([[10.0, 10.0], [2.0, 2.0]], np.float32))
    src = paddle.to_tensor(np.array([0, 1], np.int32))
    dst = paddle.to_tensor(np.array([1, 1], np.int32))
    out = G.send_ue_recv(x, e, src, dst, message_op="add",
                         reduce_op="sum")
    np.testing.assert_allclose(out.numpy()[1], [16.0, 18.0])
    out = G.send_ue_recv(x, e, src, dst, message_op="mul",
                         reduce_op="sum")
    np.testing.assert_allclose(out.numpy()[1], [16.0, 28.0])


def test_send_uv():
    x = paddle.to_tensor(np.array([[1.0], [2.0]], np.float32))
    y = paddle.to_tensor(np.array([[10.0], [20.0]], np.float32))
    src = paddle.to_tensor(np.array([0, 1], np.int32))
    dst = paddle.to_tensor(np.array([1, 0], np.int32))
    out = G.send_uv(x, y, src, dst, message_op="add")
    np.testing.assert_allclose(out.numpy(), [[21.0], [12.0]])


def test_segment_math():
    data = paddle.to_tensor(np.array([[1.0, 2], [3, 4], [5, 6]],
                                     np.float32))
    seg = paddle.to_tensor(np.array([0, 0, 1], np.int32))
    np.testing.assert_allclose(G.segment_sum(data, seg).numpy(),
                               [[4, 6], [5, 6]])
    np.testing.assert_allclose(G.segment_mean(data, seg).numpy(),
                               [[2, 3], [5, 6]])
    np.testing.assert_allclose(G.segment_min(data, seg).numpy(),
                               [[1, 2], [5, 6]])
    np.testing.assert_allclose(G.segment_max(data, seg).numpy(),
                               [[3, 4], [5, 6]])


def test_reindex_graph():
    x = paddle.to_tensor(np.array([0, 5, 9], np.int64))
    neighbors = paddle.to_tensor(np.array([8, 9, 0, 4, 7, 6], np.int64))
    count = paddle.to_tensor(np.array([2, 3, 1], np.int64))
    rsrc, rdst, nodes = G.reindex_graph(x, neighbors, count)
    # original nodes keep ids 0..2; new neighbors get 3,4,...
    assert nodes.numpy()[:3].tolist() == [0, 5, 9]
    assert rdst.numpy().tolist() == [0, 0, 1, 1, 1, 2]
    assert rsrc.numpy()[1] == 2   # neighbor 9 is existing node id 2
    assert rsrc.numpy()[2] == 0   # neighbor 0 is existing node id 0
    assert len(set(rsrc.numpy().tolist())) == 6


def test_sample_neighbors():
    # CSC: node i's in-neighbors = row[colptr[i]:colptr[i+1]]
    row = paddle.to_tensor(np.array([1, 2, 3, 0, 2, 0], np.int64))
    colptr = paddle.to_tensor(np.array([0, 3, 5, 6], np.int64))
    nodes = paddle.to_tensor(np.array([0, 2], np.int64))
    neigh, counts = G.sample_neighbors(row, colptr, nodes, sample_size=2)
    assert counts.numpy().tolist() == [2, 1]
    assert set(neigh.numpy()[:2]).issubset({1, 2, 3})
    assert neigh.numpy()[2] == 0


def test_gcn_layer_trains():
    """A tiny GCN built from send_u_recv must train under autograd."""
    from paddle_tpu import nn, optimizer

    paddle.seed(0)
    n, d = 12, 8
    rng = np.random.RandomState(0)
    feats = paddle.to_tensor(rng.randn(n, d).astype(np.float32))
    src = paddle.to_tensor(rng.randint(0, n, 40).astype(np.int32))
    dst = paddle.to_tensor(rng.randint(0, n, 40).astype(np.int32))
    y = paddle.to_tensor(rng.randn(n, 1).astype(np.float32))

    lin = nn.Linear(d, 1)
    opt = optimizer.Adam(learning_rate=0.05,
                         parameters=lin.parameters())
    losses = []
    for _ in range(25):
        h = G.send_u_recv(lin(feats), src, dst, reduce_op="mean")
        loss = nn.MSELoss()(h, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7
