"""API-surface compatibility: paddle.version / iinfo / finfo /
utils.unique_name / linalg namespace / bucketize / vander /
Tensor.cuda-cpu / cuda RNG state / nn.functional.flash_attention module
path (reference: python/paddle/version, pybind iinfo/finfo,
utils/unique_name, python/paddle/linalg.py, tensor/search.py,
nn/functional/flash_attention.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_version_surface():
    assert paddle.__version__ == paddle.version.full_version
    assert paddle.version.major.isdigit()
    paddle.version.show()


def test_iinfo_finfo():
    assert paddle.iinfo(paddle.int32).max == 2**31 - 1
    assert paddle.iinfo(paddle.int8).min == -128
    fi = paddle.finfo(paddle.float32)
    assert 1e-8 < fi.eps < 1e-6 and fi.bits == 32
    bf = paddle.finfo(paddle.bfloat16)
    assert bf.bits == 16 and bf.eps > fi.eps


def test_unique_name_and_guard():
    a = paddle.utils.unique_name.generate("fc")
    b = paddle.utils.unique_name.generate("fc")
    assert a != b
    with paddle.utils.unique_name.guard():
        c = paddle.utils.unique_name.generate("fc")
        assert c == "fc_0"  # fresh scope
    d = paddle.utils.unique_name.generate("fc")
    assert d not in (a, b, c) or d != c


def test_linalg_namespace():
    x = paddle.to_tensor(np.asarray([[2.0, 0.0], [0.0, 3.0]], "float32"))
    u, s, vt = paddle.linalg.svd(x)
    np.testing.assert_allclose(np.sort(s.numpy()), [2.0, 3.0], rtol=1e-5)
    inv = paddle.linalg.inv(x).numpy()
    np.testing.assert_allclose(inv, [[0.5, 0.0], [0.0, 1.0 / 3.0]],
                               rtol=1e-5)


def test_bucketize_and_vander():
    edges = paddle.to_tensor(np.asarray([1.0, 2.0, 3.0, 4.0], "float32"))
    got = paddle.bucketize(paddle.to_tensor(
        np.asarray([0.5, 1.5, 3.7], "float32")), edges).numpy()
    np.testing.assert_array_equal(got, [0, 1, 3])
    v = paddle.vander(paddle.to_tensor(np.asarray([1.0, 2.0], "float32")),
                      3, increasing=True).numpy()
    np.testing.assert_allclose(v, [[1, 1, 1], [1, 2, 4]])


def test_tensor_device_moves_and_rng_state():
    t = paddle.ones([2, 2])
    assert t.cuda() is t and t.tpu() is t and t.pin_memory() is t
    c = t.cpu()
    np.testing.assert_allclose(c.numpy(), 1.0)
    st = paddle.get_cuda_rng_state()
    a = paddle.randn([4]).numpy()
    paddle.set_cuda_rng_state(st)
    b = paddle.randn([4]).numpy()
    np.testing.assert_allclose(a, b)  # state restore reproduces draws


def test_flash_attention_module_path():
    import paddle_tpu.nn.functional as F
    from paddle_tpu.nn.functional.flash_attention import (
        flash_attention, flash_attn_unpadded,
    )

    assert callable(F.flash_attention)  # function, not module
    assert callable(flash_attention) and callable(flash_attn_unpadded)
    rng = np.random.default_rng(0)
    q = paddle.to_tensor(rng.normal(size=(6, 2, 8)).astype("float32"))
    cu = paddle.to_tensor(np.asarray([0, 2, 6], "int32"))
    out, _ = flash_attn_unpadded(q, q, q, cu, cu, 4, 4, causal=True)
    assert tuple(out.numpy().shape) == (6, 2, 8)
    # each packed sequence attends only within itself: compare seq 0
    import paddle_tpu.ops.pallas_attention  # noqa: F401
    qb = q.numpy()[:2][None].transpose(0, 2, 1, 3)
    from paddle_tpu.incubate.nn.functional import (
        variable_length_memory_efficient_attention as vlma,
    )

    ref = vlma(paddle.to_tensor(qb), paddle.to_tensor(qb),
               paddle.to_tensor(qb),
               paddle.to_tensor(np.asarray([2], "int32")),
               paddle.to_tensor(np.asarray([2], "int32")),
               causal=True).numpy()
    np.testing.assert_allclose(out.numpy()[:2],
                               ref[0].transpose(1, 0, 2), rtol=1e-4,
                               atol=1e-5)


def test_run_check():
    paddle.utils.run_check()
