"""Layout algebra + redistribute: randomized property tests.

All on the single-device CPU CI platform: the numpy oracle is the
reference implementation, so every (mesh shape x placement) pair is
exercised through host indexing, and the jax device path is checked
only where one device suffices (1-device meshes are identity).
Multi-device agreement between the oracle and the device path is the
tp smoke's job (scripts/tp_smoke.py runs under a forced-host-device
mesh).

Properties pinned here, per ISSUE 17:
* roundtrip: redistribute(redistribute(x, a, b), b, a) == x
* composition: a->b->c lands the same shards as a->c directly
* degenerate 1-device mesh is the identity (zero bytes moved)
* numpy oracle parity: assemble(shards(x)) == x for every layout
"""
import itertools
import random

import numpy as np
import pytest

from paddle_tpu.distributed.redistribute import (
    Layout, get_stats, redistribute_host, reset_stats, transfer_bytes,
)

MESH_SIZES = [1, 2, 4, 8]


def _random_layout(rng, ndim, size):
    """A random layout of total device count ``size``: factor the size
    into named axes, then scatter the axes over tensor dims (or leave
    them as pure replication axes)."""
    axes = []
    remaining = size
    i = 0
    while remaining > 1:
        f = rng.choice([d for d in (2, 4, remaining)
                        if d <= remaining and remaining % d == 0])
        axes.append((f"ax{i}", f))
        remaining //= f
        i += 1
    if not axes:
        axes = [("ax0", 1)]
    placements = [None] * ndim
    dims = list(range(ndim))
    rng.shuffle(dims)
    for (name, sz), d in zip(axes, dims):
        if sz > 1 and rng.random() < 0.8:
            placements[d] = name
    return Layout(axes, placements)


def _shape_for(layouts, rng, ndim):
    """A global shape every layout in ``layouts`` divides evenly."""
    shape = []
    for d in range(ndim):
        lcm = 1
        for lt in layouts:
            deg = lt.sharding_degree(d)
            lcm = lcm * deg // np.gcd(lcm, deg)
        shape.append(lcm * rng.randint(1, 3))
    return tuple(shape)


def test_oracle_parity_shards_assemble_roundtrip():
    rng = random.Random(0)
    for size in MESH_SIZES:
        for ndim in (1, 2, 3):
            for _ in range(8):
                lt = _random_layout(rng, ndim, size)
                shape = _shape_for([lt], rng, ndim)
                x = np.arange(np.prod(shape), dtype=np.float32
                              ).reshape(shape)
                shards = lt.shards(x)
                assert len(shards) == lt.size
                for i, sh in enumerate(shards):
                    assert sh.shape == lt.local_shape(shape)
                    np.testing.assert_array_equal(
                        sh, x[lt.shard_slices(shape, i)])
                np.testing.assert_array_equal(lt.assemble(shards), x)


def test_redistribute_roundtrip_and_composition():
    rng = random.Random(1)
    for size_a, size_b in itertools.product(MESH_SIZES, MESH_SIZES):
        for _ in range(4):
            ndim = rng.choice([2, 3])
            a = _random_layout(rng, ndim, size_a)
            b = _random_layout(rng, ndim, size_b)
            c = _random_layout(rng, ndim, rng.choice(MESH_SIZES))
            shape = _shape_for([a, b, c], rng, ndim)
            x = np.random.RandomState(7).randn(*shape).astype(
                np.float32)
            sa = a.shards(x)
            sb = redistribute_host(sa, a, b)
            # roundtrip
            back = redistribute_host(sb, b, a)
            for s0, s1 in zip(sa, back):
                np.testing.assert_array_equal(s0, s1)
            # composition: a->b->c == a->c
            via = redistribute_host(sb, b, c)
            direct = redistribute_host(sa, a, c)
            for s0, s1 in zip(via, direct):
                np.testing.assert_array_equal(s0, s1)


def test_one_device_mesh_is_identity_and_free():
    lt = Layout.replicated(3)
    assert lt.size == 1
    x = np.arange(24, dtype=np.int32).reshape(2, 3, 4)
    reset_stats()
    (out,) = redistribute_host([x], lt, lt)
    np.testing.assert_array_equal(out, x)
    st = get_stats()
    assert st["num_redistributes"] == 1
    assert st["bytes_moved"] == 0  # nothing crosses devices


def test_transfer_bytes_pricing():
    # replicated -> 2-way sharded on the same 2 devices: each device
    # already holds its slice => zero bytes
    rep2 = Layout((("tp", 2),), (None, None))
    shard2 = Layout((("tp", 2),), ("tp", None))
    assert transfer_bytes(rep2, shard2, (4, 6), 4) == 0
    # sharded -> replicated: each device must fetch the other half
    assert transfer_bytes(shard2, rep2, (4, 6), 4) == 2 * (2 * 6) * 4
    # resharding dim0 -> dim1 on 2 devices: each needs half its new
    # shard from the peer (2x1x... blocks)
    shard_d1 = Layout((("tp", 2),), (None, "tp"))
    assert transfer_bytes(shard2, shard_d1, (4, 6), 4) == 2 * (2 * 3) * 4
    # cross-degree embed: tp=1 -> tp=2 over the common 2-device mesh;
    # device 0 holds everything (replica), device 1 must receive its
    # half
    rep1 = Layout.replicated(2)
    assert transfer_bytes(rep1, shard2, (4, 6), 4) == 0
    # 1-device source is NOT resident on device 1? With the
    # trailing-replication embedding the tp=1 layout replicates over
    # both devices, so the bytes above are 0; the priced cost model is
    # intra-mesh. A genuinely cold destination is priced by the full
    # dst volume:
    assert transfer_bytes(shard2, shard2, (4, 6), 4) == 0


def test_layout_validation_errors():
    with pytest.raises(ValueError):
        Layout((("tp", 2), ("tp", 4)), (None,))  # dup axis name
    with pytest.raises(ValueError):
        Layout((("tp", 2),), ("tp", "tp"))  # axis shards two dims
    with pytest.raises(ValueError):
        Layout((("tp", 2),), ("dp",))  # unknown axis
    lt = Layout((("tp", 2),), ("tp", None))
    with pytest.raises(ValueError):
        lt.validate_shape((3, 4))  # 3 not divisible by 2
    with pytest.raises(ValueError):
        lt.assemble([np.zeros((1, 4))])  # wrong shard count


def test_wire_meta_roundtrip():
    rng = random.Random(2)
    for size in MESH_SIZES:
        lt = _random_layout(rng, 3, size)
        assert Layout.from_meta(lt.to_meta()) == lt
        # json-safe
        import json

        assert Layout.from_meta(
            json.loads(json.dumps(lt.to_meta()))) == lt


def test_tp_sharded_constructor():
    lt = Layout.tp_sharded(5, 3, 2)
    assert lt.dim_placements == (None, None, None, "tp", None)
    assert lt.size == 2
    assert lt.local_shape((2, 3, 4, 8, 16)) == (2, 3, 4, 4, 16)
    # degree=1 degenerates to replicated-on-one
    lt1 = Layout.tp_sharded(5, 3, 1)
    assert lt1.is_replicated and lt1.size == 1


def test_device_path_single_device_identity():
    """The jax path on the 1-device CI platform: 1-device layouts only,
    but it exercises the jit + NamedSharding lowering end to end."""
    import jax

    lt = Layout.replicated(2)
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    from paddle_tpu.distributed.redistribute import redistribute

    y = redistribute(x, lt, lt, devices=jax.devices()[:1])
    np.testing.assert_array_equal(np.asarray(y), x)
