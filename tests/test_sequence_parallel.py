"""Megatron sequence-parallel layers on the virtual CPU mesh.

Reference behavior: distributed/fleet/utils/sequence_parallel_utils.py —
SP must be numerically identical to TP-only (the layout differs, the math
does not), and the activation between blocks must be sequence-sharded.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.engine import ParallelTrainStep
from paddle_tpu.distributed.fleet.utils import (
    AllGatherOp, ColumnSequenceParallelLinear, GatherOp, ReduceScatterOp,
    RowSequenceParallelLinear, ScatterOp,
)
from paddle_tpu.distributed.mesh import ProcessMesh
from paddle_tpu.models.llama import (
    LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
)


def test_sp_ops_identity_without_mesh():
    """Outside a mesh context the SP ops are no-ops on values."""
    x = paddle.randn([4, 6, 8])
    for op in (ScatterOp, GatherOp, AllGatherOp, ReduceScatterOp):
        y = op.apply(x, axis=1)
        np.testing.assert_allclose(y.numpy(), x.numpy())


def _sp_linear_pair(d=16, m=32, seq_axis=0):
    paddle.seed(7)
    col = ColumnSequenceParallelLinear(d, m, has_bias=True,
                                       seq_axis=seq_axis)
    row = RowSequenceParallelLinear(m, d, has_bias=True,
                                    seq_axis=seq_axis)
    return col, row


def test_sp_linears_match_dense_on_mesh():
    """Column->Row SP pair equals the dense computation under the
    compiled mesh step (GSPMD inserts allgather/reduce-scatter)."""
    import jax

    d, m, s, b = 16, 32, 8, 4
    col, row = _sp_linear_pair(d, m, seq_axis=0)
    # dense reference from the same weights
    wc, bc = col.weight.numpy(), col.bias.numpy()
    wr, br = row.weight.numpy(), row.bias.numpy()
    x = np.random.RandomState(0).randn(s, b, d).astype(np.float32)
    ref = np.maximum(x @ wc + bc, 0.0) @ wr + br

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col, self.row = col, row

        def forward(self, x):
            h = ScatterOp.apply(x, axis=0)
            h = self.col(h)
            h = paddle.ops.relu(h)
            return self.row(h)

    from paddle_tpu.distributed.engine import set_current_mesh

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    from paddle_tpu.jit.trace import functionalize

    net = Net()
    apply_fn, (_, params), (_, bufs) = functionalize(net)
    from paddle_tpu.distributed.engine import shard_model_parameters

    shard_model_parameters(net, mesh)
    set_current_mesh(mesh)
    try:
        out = jax.jit(lambda pd, x: apply_fn(pd, [], jax.random.PRNGKey(0),
                                             x)[0])(
            [p._data for p in params], x)
    finally:
        set_current_mesh(None)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def _llama_losses(sequence_parallel, n_steps=2):
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=32, use_flash_attention=False,
        sequence_parallel=sequence_parallel)
    rng = np.random.RandomState(0)
    X = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    Y = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    paddle.seed(42)
    m = LlamaForCausalLM(cfg)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    step = ParallelTrainStep(m, LlamaPretrainingCriterion(cfg), opt, mesh)
    return [float(step(paddle.to_tensor(X), paddle.to_tensor(Y)).item())
            for _ in range(n_steps)]


def test_llama_sp_matches_tp_only():
    """SP Llama loss-aligns with TP-only Llama (VERDICT r2 item 4)."""
    tp = _llama_losses(False)
    sp = _llama_losses(True)
    np.testing.assert_allclose(tp, sp, rtol=5e-4, atol=1e-5)
