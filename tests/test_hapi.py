"""hapi.Model / callbacks / summary (reference: python/paddle/hapi/model.py
fit:1750, callbacks.py, model_summary.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import hapi, metric, nn, optimizer
from paddle_tpu.io import Dataset


class RandClsDataset(Dataset):
    """Synthetic separable 2-class dataset."""

    def __init__(self, n=64, d=8):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, d).astype(np.float32)
        self.y = (self.x.sum(axis=1) > 0).astype(np.int64)
        self.x[self.y == 1] += 1.0

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def make_model():
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = paddle.Model(net)
    m.prepare(optimizer.Adam(learning_rate=0.05,
                             parameters=net.parameters()),
              nn.CrossEntropyLoss(), metric.Accuracy())
    return m


def test_fit_evaluate_predict(capsys):
    m = make_model()
    ds = RandClsDataset()
    history = m.fit(ds, epochs=3, batch_size=16, verbose=0)
    assert len(history) == 3
    assert history[-1]["loss"] < history[0]["loss"]

    res = m.evaluate(ds, batch_size=16, verbose=0)
    assert res["acc"] > 0.8
    assert "loss" in res

    preds = m.predict(ds, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 2)


def test_fit_with_eval_and_early_stopping():
    m = make_model()
    ds = RandClsDataset()
    es = hapi.EarlyStopping(monitor="loss", patience=1, verbose=0)
    m.fit(ds, eval_data=ds, epochs=20, batch_size=16, verbose=0,
          callbacks=[es])
    # separable data keeps improving a while but must stop before 20 epochs
    # only if patience triggers; at minimum the attribute works
    assert hasattr(m, "stop_training")


def test_model_checkpoint_and_load(tmp_path):
    m = make_model()
    ds = RandClsDataset()
    m.fit(ds, epochs=1, batch_size=16, verbose=0,
          callbacks=[hapi.ModelCheckpoint(save_dir=str(tmp_path))])
    assert os.path.exists(tmp_path / "final.pdparams")
    assert os.path.exists(tmp_path / "final.pdopt")

    m2 = make_model()
    m2.load(str(tmp_path / "final"))
    np.testing.assert_array_equal(
        m2.network[0].weight.numpy(), m.network[0].weight.numpy())


def test_model_checkpoint_manager_delegation(tmp_path):
    """keep_last_n/async_save switch ModelCheckpoint onto the
    fault-tolerant CheckpointManager: committed step dirs, retention,
    and restore_or_initialize resume."""
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    m = make_model()
    ds = RandClsDataset()
    m.fit(ds, epochs=3, batch_size=16, verbose=0,
          callbacks=[hapi.ModelCheckpoint(save_dir=str(tmp_path),
                                          save_freq=2, keep_last_n=2,
                                          async_save=True)])
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
    # epoch 2 via the interval; epoch 3 (the trained result, off the
    # save_freq boundary) via the forced end-of-training save
    assert mgr.all_steps() == [2, 3]
    assert os.path.exists(tmp_path / "step_3" / "COMMITTED")

    m2 = make_model()
    state = {"model": m2.network.state_dict(),
             "opt": m2._optimizer.state_dict()}
    assert mgr.restore_or_initialize(state) == 3
    np.testing.assert_array_equal(
        m2.network[0].weight.numpy(), m.network[0].weight.numpy())


def test_fit_auto_resumes_from_manager_checkpoints(tmp_path):
    """ROADMAP PR-3 follow-up: Model.fit + manager-backed ModelCheckpoint
    auto-resumes — a restarted fit restores the newest committed step
    and trains only the remaining epochs."""
    m = make_model()
    ds = RandClsDataset()
    cb = hapi.ModelCheckpoint(save_dir=str(tmp_path), keep_last_n=3)
    m.fit(ds, epochs=2, batch_size=16, verbose=0, callbacks=[cb])
    w_trained = m.network[0].weight.numpy().copy()
    opt_step = m._optimizer._step_count

    # restart: fresh model, same save_dir -> resumes at epoch 2, runs 2
    # more; the restored weights match the step-2 checkpoint exactly
    m2 = make_model()
    cb2 = hapi.ModelCheckpoint(save_dir=str(tmp_path), keep_last_n=3)
    restored = {}
    orig = hapi.ModelCheckpoint.restore_or_initialize

    def spy(self, model=None):
        step = orig(self, model)
        if step is not None:
            restored["step"] = step
            restored["w"] = model.network[0].weight.numpy().copy()
            restored["opt_step"] = model._optimizer._step_count
        return step

    hapi.ModelCheckpoint.restore_or_initialize = spy
    try:
        history = m2.fit(ds, epochs=4, batch_size=16, verbose=0,
                         callbacks=[cb2])
    finally:
        hapi.ModelCheckpoint.restore_or_initialize = orig
    assert restored["step"] == 2
    np.testing.assert_array_equal(restored["w"], w_trained)
    assert restored["opt_step"] == opt_step  # Adam bias correction resumes
    assert len(history) == 2  # only epochs 2 and 3 ran

    # fully-trained dir: resume == epochs, zero epochs run
    m3 = make_model()
    h3 = m3.fit(ds, epochs=4, batch_size=16, verbose=0,
                callbacks=[hapi.ModelCheckpoint(save_dir=str(tmp_path),
                                                keep_last_n=3)])
    assert h3 == []

    # opt-out knob trains from scratch
    m4 = make_model()
    h4 = m4.fit(ds, epochs=1, batch_size=16, verbose=0,
                callbacks=[hapi.ModelCheckpoint(save_dir=str(tmp_path),
                                                keep_last_n=3,
                                                auto_resume=False)])
    assert len(h4) == 1


def test_model_checkpoint_async_alone_keeps_everything(tmp_path):
    """async_save=True without keep_last_n must not silently enable
    retention — the legacy path kept every epoch checkpoint."""
    cb = hapi.ModelCheckpoint(save_dir=str(tmp_path), async_save=True)
    assert cb._get_manager()._keep >= 10 ** 9
    cb2 = hapi.ModelCheckpoint(save_dir=str(tmp_path), keep_last_n=3)
    assert cb2._get_manager()._keep == 3


def test_lr_scheduler_callback():
    net = nn.Sequential(nn.Linear(8, 2))
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                   gamma=0.5)
    opt = optimizer.SGD(learning_rate=sched, parameters=net.parameters())
    m = paddle.Model(net)
    m.prepare(opt, nn.CrossEntropyLoss())
    ds = RandClsDataset(n=32)
    m.fit(ds, epochs=1, batch_size=16, verbose=0,
          callbacks=[hapi.LRScheduler(by_step=True)])
    assert opt.get_lr() < 0.1


def test_summary_and_flops(capsys):
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    res = paddle.summary(net, input_size=(4, 8))
    out = capsys.readouterr().out
    assert "Total params" in out
    expected = 8 * 16 + 16 + 16 * 2 + 2
    assert res["total_params"] == expected
    fl = paddle.flops(net, input_size=(4, 8))
    assert fl == 2 * 4 * (8 * 16 + 16 * 2)


def test_summary_resnet():
    from paddle_tpu.vision.models import resnet18

    res = paddle.summary(resnet18(num_classes=10),
                         input_size=(1, 3, 32, 32))
    assert res["total_params"] > 1e7 * 1.1  # ~11.2M
    assert res["flops"] > 0


def test_visualdl_callback_records_scalars(tmp_path):
    """VisualDL callback (reference callbacks.py:883) — without the
    visualdl package the scalars land in scalars.jsonl."""
    import json

    from paddle_tpu.hapi.callbacks import VisualDL

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    model = hapi.Model(net)
    model.prepare(optimizer.SGD(learning_rate=0.05,
                                parameters=net.parameters()),
                  nn.MSELoss())
    X = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    Y = np.random.RandomState(1).randn(32, 1).astype(np.float32)
    ds = [(X[i], Y[i]) for i in range(32)]
    logdir = str(tmp_path / "vdl")
    model.fit(ds, batch_size=8, epochs=2, verbose=0,
              callbacks=[VisualDL(log_dir=logdir)])
    lines = [json.loads(l) for l in
             open(f"{logdir}/scalars.jsonl").read().splitlines()]
    assert lines, "no scalars recorded"
    tags = {l["tag"] for l in lines}
    assert any(t.startswith("train/loss") for t in tags)
    assert all({"tag", "step", "value"} <= set(l) for l in lines)


def test_model_prepare_amp_and_fit():
    """prepare(amp_configs='O1') trains under bf16 autocast with the
    compiled step (reference model.py prepare amp_configs)."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = hapi.Model(net)
    model.prepare(optimizer.Adam(0.01, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), amp_configs="O1")
    rng = np.random.default_rng(0)
    X = rng.normal(size=(32, 8)).astype("float32")
    Y = rng.integers(0, 4, (32,)).astype("int64")
    ds = [(X[i:i + 8], Y[i:i + 8]) for i in range(0, 32, 8)]
    hist = model.fit(ds, epochs=6, verbose=0)
    assert hist[-1]["loss"] < hist[0]["loss"]


def test_model_train_metrics_in_fit():
    Accuracy = metric.Accuracy

    paddle.seed(0)
    net = nn.Linear(4, 3)
    model = hapi.Model(net)
    model.prepare(optimizer.SGD(0.1, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), metrics=Accuracy())
    rng = np.random.default_rng(1)
    X = rng.normal(size=(16, 4)).astype("float32")
    Y = rng.integers(0, 3, (16,)).astype("int64")
    ds = [(X[i:i + 4], Y[i:i + 4]) for i in range(0, 16, 4)]
    logs = {}

    class Grab(hapi.Callback):
        def on_train_batch_end(self, step, l=None):
            logs.update(l or {})

    model.fit(ds, epochs=1, verbose=0, callbacks=[Grab()], log_freq=1)
    assert "acc" in logs, f"train metrics missing from logs: {logs}"


def test_model_save_inference_and_reload(tmp_path):
    from paddle_tpu.jit import InputSpec

    paddle.seed(0)
    net = nn.Linear(6, 2)
    model = hapi.Model(net, inputs=[InputSpec([4, 6], "float32")])
    path = str(tmp_path / "exp" / "m")
    model.save(path, training=False)
    loaded = paddle.jit.load(path)
    x = np.random.default_rng(0).normal(size=(4, 6)).astype("float32")
    got = loaded(paddle.to_tensor(x))
    ref = net(paddle.to_tensor(x))
    np.testing.assert_allclose(np.asarray(got._data),
                               np.asarray(ref._data), rtol=1e-5)


def test_model_save_inference_without_spec_raises(tmp_path):
    model = hapi.Model(nn.Linear(2, 2))
    with pytest.raises(RuntimeError):
        model.save(str(tmp_path / "x"), training=False)


def test_accuracy_counts_all_sample_dims():
    """A (B, S, k) correct matrix counts B*S samples — the ratio can
    never exceed 1.0 (regression: shape[0]-only counting)."""
    acc = metric.Accuracy()
    pred = np.zeros((2, 4, 3), "float32")
    pred[..., 1] = 1.0  # argmax = class 1 everywhere
    label = np.ones((2, 4), "int64")
    acc.update(acc.compute(paddle.to_tensor(pred),
                           paddle.to_tensor(label)))
    assert acc.accumulate() == 1.0
    assert acc.count[0] == 8


def test_fit_with_multi_topk_accuracy():
    """Accuracy(topk=(1,2)) names a list; fit/evaluate must fan values
    out instead of using the list as a dict key."""
    paddle.seed(0)
    net = nn.Linear(4, 3)
    model = hapi.Model(net)
    model.prepare(optimizer.SGD(0.1, parameters=net.parameters()),
                  nn.CrossEntropyLoss(),
                  metrics=metric.Accuracy(topk=(1, 2)))
    rng = np.random.default_rng(2)
    X = rng.normal(size=(16, 4)).astype("float32")
    Y = rng.integers(0, 3, (16,)).astype("int64")

    class DS(Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            return X[i], Y[i]

    model.fit(DS(), batch_size=4, epochs=1, verbose=0, log_freq=1)
    ev = model.evaluate(DS(), batch_size=4, verbose=0)
    assert "acc_top1" in ev and "acc_top2" in ev
    assert 0.0 <= ev["acc_top1"] <= ev["acc_top2"] <= 1.0
