"""hapi.Model / callbacks / summary (reference: python/paddle/hapi/model.py
fit:1750, callbacks.py, model_summary.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import hapi, metric, nn, optimizer
from paddle_tpu.io import Dataset


class RandClsDataset(Dataset):
    """Synthetic separable 2-class dataset."""

    def __init__(self, n=64, d=8):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, d).astype(np.float32)
        self.y = (self.x.sum(axis=1) > 0).astype(np.int64)
        self.x[self.y == 1] += 1.0

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def make_model():
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = paddle.Model(net)
    m.prepare(optimizer.Adam(learning_rate=0.05,
                             parameters=net.parameters()),
              nn.CrossEntropyLoss(), metric.Accuracy())
    return m


def test_fit_evaluate_predict(capsys):
    m = make_model()
    ds = RandClsDataset()
    history = m.fit(ds, epochs=3, batch_size=16, verbose=0)
    assert len(history) == 3
    assert history[-1]["loss"] < history[0]["loss"]

    res = m.evaluate(ds, batch_size=16, verbose=0)
    assert res["acc"] > 0.8
    assert "loss" in res

    preds = m.predict(ds, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 2)


def test_fit_with_eval_and_early_stopping():
    m = make_model()
    ds = RandClsDataset()
    es = hapi.EarlyStopping(monitor="loss", patience=1, verbose=0)
    m.fit(ds, eval_data=ds, epochs=20, batch_size=16, verbose=0,
          callbacks=[es])
    # separable data keeps improving a while but must stop before 20 epochs
    # only if patience triggers; at minimum the attribute works
    assert hasattr(m, "stop_training")


def test_model_checkpoint_and_load(tmp_path):
    m = make_model()
    ds = RandClsDataset()
    m.fit(ds, epochs=1, batch_size=16, verbose=0,
          callbacks=[hapi.ModelCheckpoint(save_dir=str(tmp_path))])
    assert os.path.exists(tmp_path / "final.pdparams")
    assert os.path.exists(tmp_path / "final.pdopt")

    m2 = make_model()
    m2.load(str(tmp_path / "final"))
    np.testing.assert_array_equal(
        m2.network[0].weight.numpy(), m.network[0].weight.numpy())


def test_lr_scheduler_callback():
    net = nn.Sequential(nn.Linear(8, 2))
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                   gamma=0.5)
    opt = optimizer.SGD(learning_rate=sched, parameters=net.parameters())
    m = paddle.Model(net)
    m.prepare(opt, nn.CrossEntropyLoss())
    ds = RandClsDataset(n=32)
    m.fit(ds, epochs=1, batch_size=16, verbose=0,
          callbacks=[hapi.LRScheduler(by_step=True)])
    assert opt.get_lr() < 0.1


def test_summary_and_flops(capsys):
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    res = paddle.summary(net, input_size=(4, 8))
    out = capsys.readouterr().out
    assert "Total params" in out
    expected = 8 * 16 + 16 + 16 * 2 + 2
    assert res["total_params"] == expected
    fl = paddle.flops(net, input_size=(4, 8))
    assert fl == 2 * 4 * (8 * 16 + 16 * 2)


def test_summary_resnet():
    from paddle_tpu.vision.models import resnet18

    res = paddle.summary(resnet18(num_classes=10),
                         input_size=(1, 3, 32, 32))
    assert res["total_params"] > 1e7 * 1.1  # ~11.2M
    assert res["flops"] > 0


def test_visualdl_callback_records_scalars(tmp_path):
    """VisualDL callback (reference callbacks.py:883) — without the
    visualdl package the scalars land in scalars.jsonl."""
    import json

    from paddle_tpu.hapi.callbacks import VisualDL

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
    model = hapi.Model(net)
    model.prepare(optimizer.SGD(learning_rate=0.05,
                                parameters=net.parameters()),
                  nn.MSELoss())
    X = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    Y = np.random.RandomState(1).randn(32, 1).astype(np.float32)
    ds = [(X[i], Y[i]) for i in range(32)]
    logdir = str(tmp_path / "vdl")
    model.fit(ds, batch_size=8, epochs=2, verbose=0,
              callbacks=[VisualDL(log_dir=logdir)])
    lines = [json.loads(l) for l in
             open(f"{logdir}/scalars.jsonl").read().splitlines()]
    assert lines, "no scalars recorded"
    tags = {l["tag"] for l in lines}
    assert any(t.startswith("train/loss") for t in tags)
    assert all({"tag", "step", "value"} <= set(l) for l in lines)
