"""MoE / expert parallelism (reference
incubate/distributed/models/moe/moe_layer.py:263) + first direct
all_to_all collective test (VERDICT round-1 weak item 7)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.engine import ParallelTrainStep
from paddle_tpu.distributed.mesh import ProcessMesh
from paddle_tpu.incubate.moe import MoELayer, SwitchGate


class Expert(nn.Layer):
    def __init__(self, d, h):
        super().__init__()
        self.fc1 = nn.Linear(d, h)
        self.fc2 = nn.Linear(h, d)

    def forward(self, x):
        return self.fc2(paddle.ops.gelu(self.fc1(x)))


class MoEModel(nn.Layer):
    def __init__(self, d=16, n_experts=8, gate="gshard", ep_axis=None):
        super().__init__()
        self.inp = nn.Linear(d, d)
        self.moe = MoELayer(
            d, [Expert(d, 2 * d) for _ in range(n_experts)], gate=gate,
            capacity_factor=2.0, ep_axis=ep_axis)
        self.out = nn.Linear(d, d)

    def forward(self, x):
        return self.out(self.moe(self.inp(x)))


def test_moe_forward_shapes_and_aux():
    paddle.seed(0)
    m = MoEModel(ep_axis=None)
    x = paddle.randn([4, 8, 16])
    y = m(x)
    assert y.shape == [4, 8, 16]
    assert m.moe.aux_loss is not None
    assert float(m.moe.aux_loss.item()) > 0.0


@pytest.mark.parametrize("gate", ["gshard", "switch"])
def test_moe_trains_eager_and_matches_loss_direction(gate):
    paddle.seed(1)
    m = MoEModel(gate=gate, ep_axis=None)
    opt = optimizer.AdamW(learning_rate=5e-3, parameters=m.parameters())
    loss_fn = nn.MSELoss()
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randn(16, 4, 16).astype(np.float32))
    Y = paddle.to_tensor(np.tanh(X.numpy()))

    losses = []
    for _ in range(12):
        out = m(X)
        loss = loss_fn(out, Y) + 0.01 * m.moe.aux_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.9
    # expert params actually got gradients/updates
    assert m.moe.stacked_params[0].grad is None  # cleared
    assert np.isfinite(losses).all()


def test_moe_expert_parallel_compiled_step():
    """8 experts sharded over an ep axis inside ParallelTrainStep; loss
    matches the unsharded run."""
    rng = np.random.RandomState(2)
    X = rng.randn(16, 4, 16).astype(np.float32)
    Y = np.tanh(X)

    def run(parallel):
        paddle.seed(3)
        m = MoEModel(ep_axis="ep" if parallel else None)
        opt = optimizer.AdamW(learning_rate=5e-3,
                              parameters=m.parameters())

        def loss_fn(out, y):
            return nn.MSELoss()(out, y) + 0.01 * m.moe.aux_loss

        if parallel:
            mesh = ProcessMesh(np.arange(8), dim_names=["ep"])
            step = ParallelTrainStep(m, loss_fn, opt, mesh,
                                     n_model_inputs=1)
        else:
            step = paddle.jit.TrainStep(m, loss_fn, opt)
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item()) for _ in range(4)]

    base = run(False)
    ep = run(True)
    np.testing.assert_allclose(base, ep, rtol=2e-3, atol=1e-5)


def test_moe_capacity_drops_tokens():
    """With capacity_factor tiny, dispatch drops tokens instead of
    erroring; output stays finite."""
    paddle.seed(4)
    m = MoEModel(ep_axis=None)
    m.moe.capacity_factor = 0.1
    x = paddle.randn([8, 8, 16])
    y = m(x)
    assert np.isfinite(y.numpy()).all()


def test_all_to_all_direct():
    """Direct all_to_all collective exercise (first direct test of the
    API — VERDICT weak item 7) via shard_map."""
    from jax.sharding import PartitionSpec as P

    mesh = ProcessMesh(np.arange(8), dim_names=["x"])
    jm = mesh.jax_mesh()
    data = jnp.arange(8 * 8, dtype=jnp.float32).reshape(8, 8)

    def body(x):  # x: [1, 8] per rank
        out = jax.lax.all_to_all(x, "x", split_axis=1, concat_axis=0,
                                 tiled=True)  # -> [8, 1] per rank
        return out.reshape(1, 8)

    out = jax.jit(jax.shard_map(body, mesh=jm, in_specs=P("x"),
                                out_specs=P("x"), check_vma=False))(data)
    # rank r ends up holding column r => global result is the transpose
    np.testing.assert_allclose(np.asarray(out), np.asarray(data).T)


def test_eager_collective_apis_in_spmd():
    """paddle_tpu.distributed collective wrappers lower inside shard_map
    (all_reduce / all_gather / reduce_scatter)."""
    import paddle_tpu.distributed as dist
    from jax.sharding import PartitionSpec as P

    mesh = ProcessMesh(np.arange(8), dim_names=["x"])
    g = dist.new_group(axis_name="x")  # bind the group to the mesh axis
    jm = mesh.jax_mesh()
    data = jnp.ones((8, 4), jnp.float32)

    def body(x):
        t = paddle.to_tensor(x)
        dist.all_reduce(t, group=g)
        gathered = dist.all_gather(None, paddle.to_tensor(x), group=g)
        rs = dist.reduce_scatter(None, gathered, group=g)
        return t._data, (rs._data if hasattr(rs, "_data") else rs)

    out, rs = jax.jit(jax.shard_map(
        body, mesh=jm, in_specs=P("x"), out_specs=(P("x"), P("x")),
        check_vma=False))(data)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))
    # all_gather -> [8,4] per rank; reduce_scatter back -> [1,4] of 8s
    np.testing.assert_allclose(np.asarray(rs), np.full((8, 4), 8.0))


def test_switch_gate_jitter_changes_routing_across_steps():
    """SwitchGate applies logit jitter only while training (reference
    switch_gate.py:52-56): train-mode dispatch varies with the RNG,
    eval-mode is deterministic."""
    import paddle_tpu as paddle
    from paddle_tpu.incubate.moe.moe_layer import MoELayer, SwitchGate

    paddle.seed(0)
    experts = [nn.Linear(8, 8) for _ in range(4)]
    layer = MoELayer(8, experts, gate=SwitchGate(8, 4, switch_eps=2.0))
    x = paddle.randn([32, 8])
    layer.train()
    paddle.seed(1)
    a = layer(x).numpy()
    paddle.seed(2)
    b = layer(x).numpy()
    assert not np.allclose(a, b), "jitter should perturb routing"
    layer.eval()
    e1 = layer(x).numpy()
    e2 = layer(x).numpy()
    np.testing.assert_array_equal(e1, e2)


def test_gshard_random_routing_drops_weak_second_expert():
    """GShard random routing keeps the 2nd expert with prob ~2*g2
    (reference _random_routing): with near-uniform gates (g2 ~ 1/E) a
    fraction of tokens must lose their 2nd expert."""
    import jax.numpy as jnp
    from paddle_tpu.incubate.moe.moe_layer import _top2_dispatch
    import jax

    t, e = 512, 8
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(t, e).astype(np.float32) * 0.01)
    c_norand, d_norand, _ = _top2_dispatch(logits, capacity=t)
    rand = jax.random.uniform(jax.random.PRNGKey(0), (t,))
    c_rand, d_rand, _ = _top2_dispatch(logits, capacity=t, rand=rand)
    used_norand = float(jnp.sum(d_norand))
    used_rand = float(jnp.sum(d_rand))
    # ~every token uses 2 experts without random routing; with it, the
    # 2nd slot survives with prob ~2*g2 ~ 2/8
    assert used_norand > 1.9 * t
    assert used_rand < 1.5 * t
    assert used_rand > 1.0 * t


def test_gshard_capacity_train_vs_eval():
    """Gate capacity factors: 1.2 in train, 2.4 in eval (reference
    gshard_gate.py:66). Under total skew (every token picks expert 0)
    only `capacity` tokens survive, so the surviving-token count
    directly reveals the per-mode capacity."""
    import jax.numpy as jnp
    from paddle_tpu.incubate.moe.moe_layer import _top1_dispatch

    t, e = 32, 4
    logits = jnp.tile(jnp.asarray([[10.0, 0.0, 0.0, 0.0]], jnp.float32),
                      (t, 1))
    cap_train = int(np.ceil(t / e * 1.2))   # 10
    cap_eval = int(np.ceil(t / e * 2.4))    # 20
    _, d_train, _ = _top1_dispatch(logits, capacity=cap_train)
    _, d_eval, _ = _top1_dispatch(logits, capacity=cap_eval)
    assert int(jnp.sum(d_train)) == cap_train
    assert int(jnp.sum(d_eval)) == cap_eval


def test_moe_grad_clip_matches_global_norm():
    """ClipGradForMOEByGlobalNorm == plain global-norm clip when expert
    grads are global-view (the cross-rank reduction is subsumed)."""
    import paddle_tpu as paddle
    from paddle_tpu.incubate.moe.grad_clip import ClipGradForMOEByGlobalNorm
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm

    rng = np.random.RandomState(0)
    params = [paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
              for _ in range(3)]
    params[1].is_expert = True
    grads = [paddle.to_tensor(rng.randn(4, 4).astype(np.float32) * 10)
             for _ in range(3)]
    moe_clip = ClipGradForMOEByGlobalNorm(
        1.0, is_expert_param_func=lambda p: getattr(p, "is_expert", False))
    plain_clip = ClipGradByGlobalNorm(1.0)
    a = moe_clip(list(zip(params, grads)))
    b = plain_clip(list(zip(params, grads)))
    for (pa, ga), (pb, gb) in zip(a, b):
        np.testing.assert_allclose(ga.numpy(), gb.numpy(), rtol=1e-6)
    # clipped global norm == clip_norm
    tot = sum(float((g.numpy() ** 2).sum()) for _, g in a)
    np.testing.assert_allclose(np.sqrt(tot), 1.0, rtol=1e-5)


def test_moe_expert_balance_statistics():
    """Aux loss pushes balance: with uniform logits the top-1 routing
    fractions are near-uniform across experts (statistics, not shapes)."""
    import jax.numpy as jnp
    from paddle_tpu.incubate.moe.moe_layer import _top1_dispatch

    t, e = 4096, 8
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(t, e).astype(np.float32) * 0.01)
    combine, dispatch, aux = _top1_dispatch(logits, capacity=t)
    frac = np.asarray(jnp.sum(jnp.any(dispatch, axis=-1), axis=0),
                      np.float64)
    frac = frac / frac.sum()
    assert np.all(np.abs(frac - 1.0 / e) < 0.02), frac
    # aux for a perfectly balanced router ~ 1.0 (E * E * (1/E) * (1/E))
    np.testing.assert_allclose(float(aux), 1.0, atol=0.05)
