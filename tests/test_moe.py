"""MoE / expert parallelism (reference
incubate/distributed/models/moe/moe_layer.py:263) + first direct
all_to_all collective test (VERDICT round-1 weak item 7)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.engine import ParallelTrainStep
from paddle_tpu.distributed.mesh import ProcessMesh
from paddle_tpu.incubate.moe import MoELayer, SwitchGate


class Expert(nn.Layer):
    def __init__(self, d, h):
        super().__init__()
        self.fc1 = nn.Linear(d, h)
        self.fc2 = nn.Linear(h, d)

    def forward(self, x):
        return self.fc2(paddle.ops.gelu(self.fc1(x)))


class MoEModel(nn.Layer):
    def __init__(self, d=16, n_experts=8, gate="gshard", ep_axis=None):
        super().__init__()
        self.inp = nn.Linear(d, d)
        self.moe = MoELayer(
            d, [Expert(d, 2 * d) for _ in range(n_experts)], gate=gate,
            capacity_factor=2.0, ep_axis=ep_axis)
        self.out = nn.Linear(d, d)

    def forward(self, x):
        return self.out(self.moe(self.inp(x)))


def test_moe_forward_shapes_and_aux():
    paddle.seed(0)
    m = MoEModel(ep_axis=None)
    x = paddle.randn([4, 8, 16])
    y = m(x)
    assert y.shape == [4, 8, 16]
    assert m.moe.aux_loss is not None
    assert float(m.moe.aux_loss.item()) > 0.0


@pytest.mark.parametrize("gate", ["gshard", "switch"])
def test_moe_trains_eager_and_matches_loss_direction(gate):
    paddle.seed(1)
    m = MoEModel(gate=gate, ep_axis=None)
    opt = optimizer.AdamW(learning_rate=5e-3, parameters=m.parameters())
    loss_fn = nn.MSELoss()
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randn(16, 4, 16).astype(np.float32))
    Y = paddle.to_tensor(np.tanh(X.numpy()))

    losses = []
    for _ in range(12):
        out = m(X)
        loss = loss_fn(out, Y) + 0.01 * m.moe.aux_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.9
    # expert params actually got gradients/updates
    assert m.moe.stacked_params[0].grad is None  # cleared
    assert np.isfinite(losses).all()


def test_moe_expert_parallel_compiled_step():
    """8 experts sharded over an ep axis inside ParallelTrainStep; loss
    matches the unsharded run."""
    rng = np.random.RandomState(2)
    X = rng.randn(16, 4, 16).astype(np.float32)
    Y = np.tanh(X)

    def run(parallel):
        paddle.seed(3)
        m = MoEModel(ep_axis="ep" if parallel else None)
        opt = optimizer.AdamW(learning_rate=5e-3,
                              parameters=m.parameters())

        def loss_fn(out, y):
            return nn.MSELoss()(out, y) + 0.01 * m.moe.aux_loss

        if parallel:
            mesh = ProcessMesh(np.arange(8), dim_names=["ep"])
            step = ParallelTrainStep(m, loss_fn, opt, mesh,
                                     n_model_inputs=1)
        else:
            step = paddle.jit.TrainStep(m, loss_fn, opt)
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item()) for _ in range(4)]

    base = run(False)
    ep = run(True)
    np.testing.assert_allclose(base, ep, rtol=2e-3, atol=1e-5)


def test_moe_capacity_drops_tokens():
    """With capacity_factor tiny, dispatch drops tokens instead of
    erroring; output stays finite."""
    paddle.seed(4)
    m = MoEModel(ep_axis=None)
    m.moe.capacity_factor = 0.1
    x = paddle.randn([8, 8, 16])
    y = m(x)
    assert np.isfinite(y.numpy()).all()


def test_all_to_all_direct():
    """Direct all_to_all collective exercise (first direct test of the
    API — VERDICT weak item 7) via shard_map."""
    from jax.sharding import PartitionSpec as P

    mesh = ProcessMesh(np.arange(8), dim_names=["x"])
    jm = mesh.jax_mesh()
    data = jnp.arange(8 * 8, dtype=jnp.float32).reshape(8, 8)

    def body(x):  # x: [1, 8] per rank
        out = jax.lax.all_to_all(x, "x", split_axis=1, concat_axis=0,
                                 tiled=True)  # -> [8, 1] per rank
        return out.reshape(1, 8)

    out = jax.jit(jax.shard_map(body, mesh=jm, in_specs=P("x"),
                                out_specs=P("x"), check_vma=False))(data)
    # rank r ends up holding column r => global result is the transpose
    np.testing.assert_allclose(np.asarray(out), np.asarray(data).T)


def test_eager_collective_apis_in_spmd():
    """paddle_tpu.distributed collective wrappers lower inside shard_map
    (all_reduce / all_gather / reduce_scatter)."""
    import paddle_tpu.distributed as dist
    from jax.sharding import PartitionSpec as P

    mesh = ProcessMesh(np.arange(8), dim_names=["x"])
    g = dist.new_group(axis_name="x")  # bind the group to the mesh axis
    jm = mesh.jax_mesh()
    data = jnp.ones((8, 4), jnp.float32)

    def body(x):
        t = paddle.to_tensor(x)
        dist.all_reduce(t, group=g)
        gathered = dist.all_gather(None, paddle.to_tensor(x), group=g)
        rs = dist.reduce_scatter(None, gathered, group=g)
        return t._data, (rs._data if hasattr(rs, "_data") else rs)

    out, rs = jax.jit(jax.shard_map(
        body, mesh=jm, in_specs=P("x"), out_specs=(P("x"), P("x")),
        check_vma=False))(data)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))
    # all_gather -> [8,4] per rank; reduce_scatter back -> [1,4] of 8s
    np.testing.assert_allclose(np.asarray(rs), np.full((8, 4), 8.0))
