"""Incubate fused-op functional surface (reference:
python/paddle/incubate/nn/functional/ — each is the reference kernel's
documented pseudo-code composed over registry ops; XLA fuses the
composition, so numerics are checked against direct numpy math).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import functional as IF


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


def test_fused_linear_family(rng):
    x = rng.standard_normal((4, 8)).astype("float32")
    w = rng.standard_normal((8, 6)).astype("float32")
    b = rng.standard_normal(6).astype("float32")
    np.testing.assert_allclose(
        IF.fused_matmul_bias(paddle.to_tensor(x), paddle.to_tensor(w),
                             paddle.to_tensor(b)).numpy(),
        x @ w + b, rtol=1e-4)
    np.testing.assert_allclose(
        IF.fused_linear(paddle.to_tensor(x), paddle.to_tensor(w),
                        paddle.to_tensor(b)).numpy(),
        x @ w + b, rtol=1e-4)
    np.testing.assert_allclose(
        IF.fused_linear_activation(
            paddle.to_tensor(x), paddle.to_tensor(w),
            paddle.to_tensor(b), activation="relu").numpy(),
        np.maximum(x @ w + b, 0), rtol=1e-4)


def test_fused_layer_norm_bias_residual(rng):
    xn = rng.standard_normal((2, 3, 8)).astype("float32")
    res = rng.standard_normal((2, 3, 8)).astype("float32")
    bb = rng.standard_normal(8).astype("float32")
    gw = rng.standard_normal(8).astype("float32")
    gb = rng.standard_normal(8).astype("float32")
    got = IF.fused_layer_norm(
        paddle.to_tensor(xn), paddle.to_tensor(gw), paddle.to_tensor(gb),
        1e-5, residual_alpha=0.5, begin_norm_axis=2,
        bias=paddle.to_tensor(bb), residual=paddle.to_tensor(res)).numpy()
    y = xn + bb + 0.5 * res
    want = ((y - y.mean(-1, keepdims=True))
            / np.sqrt(y.var(-1, keepdims=True) + 1e-5) * gw + gb)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    # norm_weight=None -> just the fused add (reference contract)
    np.testing.assert_allclose(
        IF.fused_layer_norm(paddle.to_tensor(xn), None, None, 1e-5,
                            bias=paddle.to_tensor(bb)).numpy(),
        xn + bb, rtol=1e-6)


def test_fused_dropout_add(rng):
    xn = rng.standard_normal((2, 3, 8)).astype("float32")
    res = rng.standard_normal((2, 3, 8)).astype("float32")
    np.testing.assert_allclose(
        IF.fused_dropout_add(paddle.to_tensor(xn), paddle.to_tensor(res),
                             p=0.7, training=False).numpy(),
        xn + res, rtol=1e-6)
    # training: kept positions upscaled, zeros elsewhere; sum of output
    # minus res equals upscaled surviving x entries
    out = IF.fused_dropout_add(paddle.to_tensor(np.ones_like(xn)),
                               paddle.to_tensor(res), p=0.5,
                               training=True).numpy() - res
    assert set(np.round(np.unique(out), 4)).issubset({0.0, 2.0})


def test_fused_ec_moe_matches_loop(rng):
    B, S, Dm, E, Ff = 2, 3, 4, 3, 5
    xm = rng.standard_normal((B, S, Dm)).astype("float32")
    gate = rng.standard_normal((B, S, E)).astype("float32")
    w0 = rng.standard_normal((E, Dm, Ff)).astype("float32")
    b0 = rng.standard_normal((E, 1, Ff)).astype("float32")
    w1 = rng.standard_normal((E, Ff, Dm)).astype("float32")
    b1 = rng.standard_normal((E, 1, Dm)).astype("float32")
    got = IF.fused_ec_moe(
        paddle.to_tensor(xm), paddle.to_tensor(gate),
        paddle.to_tensor(w0), paddle.to_tensor(b0),
        paddle.to_tensor(w1), paddle.to_tensor(b1), "relu").numpy()
    probs = np.exp(gate - gate.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros((B, S, Dm), "float32")
    for e in range(E):
        want += probs[..., e:e + 1] * (
            np.maximum(xm @ w0[e] + b0[e, 0], 0) @ w1[e] + b1[e, 0])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_masked_multihead_attention_decode(rng):
    B, H, Dh, Smax = 2, 2, 4, 6
    cache = np.zeros((2, B, H, Smax, Dh), "float32")
    cache[:, :, :, :3] = rng.standard_normal((2, B, H, 3, Dh))
    xq = rng.standard_normal((B, 3 * H * Dh)).astype("float32")
    lens = np.array([3, 2], "int32")
    out, newc = IF.masked_multihead_attention(
        paddle.to_tensor(xq), paddle.to_tensor(cache),
        sequence_lengths=paddle.to_tensor(lens))
    qkv = xq.reshape(B, 3, H, Dh)
    for b in range(B):
        L = lens[b]
        kc = cache[0, b].copy()
        vc = cache[1, b].copy()
        kc[:, L] = qkv[b, 1]
        vc[:, L] = qkv[b, 2]
        for h in range(H):
            lg = (kc[h, :L + 1] @ qkv[b, 0, h]) / np.sqrt(Dh)
            p = np.exp(lg - lg.max())
            p /= p.sum()
            np.testing.assert_allclose(
                out.numpy()[b, h * Dh:(h + 1) * Dh],
                p @ vc[h, :L + 1], rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(newc.numpy()[0, b], kc, rtol=1e-6)


def test_fused_feedforward_pre_ln(rng):
    E2 = 8
    xf = rng.standard_normal((2, 3, E2)).astype("float32")
    w1 = rng.standard_normal((E2, 16)).astype("float32")
    w2 = rng.standard_normal((16, E2)).astype("float32")
    got = IF.fused_feedforward(
        paddle.to_tensor(xf), paddle.to_tensor(w1), paddle.to_tensor(w2),
        dropout1_rate=0.0, dropout2_rate=0.0, training=False,
        pre_layer_norm=True).numpy()
    ln = ((xf - xf.mean(-1, keepdims=True))
          / np.sqrt(xf.var(-1, keepdims=True) + 1e-5))
    np.testing.assert_allclose(got, xf + np.maximum(ln @ w1, 0) @ w2,
                               rtol=1e-4, atol=1e-4)


def test_fused_multi_head_attention_shapes(rng):
    E2 = 8
    xf = rng.standard_normal((2, 3, E2)).astype("float32")
    qkvw = rng.standard_normal((3, 2, 4, E2)).astype("float32")
    lw = rng.standard_normal((E2, E2)).astype("float32")
    got = IF.fused_multi_head_attention(
        paddle.to_tensor(xf), paddle.to_tensor(qkvw),
        paddle.to_tensor(lw), pre_layer_norm=True, dropout_rate=0.0,
        attn_dropout_rate=0.0, training=False).numpy()
    assert got.shape == (2, 3, E2)
    assert np.isfinite(got).all()


def test_fused_bias_dropout_residual_layer_norm(rng):
    xf = rng.standard_normal((2, 3, 8)).astype("float32")
    res = rng.standard_normal((2, 3, 8)).astype("float32")
    bb = rng.standard_normal(8).astype("float32")
    got = IF.fused_bias_dropout_residual_layer_norm(
        paddle.to_tensor(xf), paddle.to_tensor(res),
        bias=paddle.to_tensor(bb), dropout_rate=0.0,
        training=False).numpy()
    y = res + xf + bb
    want = ((y - y.mean(-1, keepdims=True))
            / np.sqrt(y.var(-1, keepdims=True) + 1e-5))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_serving_megakernels_raise_with_pointer():
    with pytest.raises(NotImplementedError):
        IF.fused_multi_transformer()
    with pytest.raises(NotImplementedError):
        IF.fused_gate_attention()


def test_fused_sdpa_scaling_factor(rng):
    q = rng.standard_normal((1, 3, 2, 4)).astype("float32")
    k = rng.standard_normal((1, 3, 2, 4)).astype("float32")
    v = rng.standard_normal((1, 3, 2, 4)).astype("float32")
    got = IF.fused_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        scaling_factor=0.5, is_training=False).numpy()
    qh, kh, vh = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
    lg = np.einsum("bhsd,bhtd->bhst", qh, kh) * 0.5
    p = np.exp(lg - lg.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.einsum("bhst,bhtd->bhsd", p, vh).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_fused_ops_loud_errors(rng):
    q = paddle.ones([1, 3, 2, 4])
    with pytest.raises(NotImplementedError):  # causal + explicit mask
        IF.fused_dot_product_attention(q, q, q,
                                       mask=paddle.ones([1, 1, 3, 3]),
                                       is_causal_masking=True)
    with pytest.raises(ValueError):           # unsupported activation
        IF.fused_linear_activation(paddle.ones([2, 2]),
                                   paddle.ones([2, 2]),
                                   activation="geglu")
    # KV-cache overflow must raise in eager, not silently drop the token
    cache = paddle.to_tensor(np.zeros((2, 1, 1, 4, 4), "float32"))
    xq = paddle.to_tensor(
        rng.standard_normal((1, 12)).astype("float32"))
    with pytest.raises(ValueError):
        IF.masked_multihead_attention(
            xq, cache,
            sequence_lengths=paddle.to_tensor(np.array([4], "int32")))
    # ec_moe rejects ambiguous bmm1 layout instead of sniffing
    with pytest.raises(ValueError):
        IF.fused_ec_moe(paddle.ones([1, 2, 4]), paddle.ones([1, 2, 2]),
                        paddle.ones([2, 4, 4]), paddle.ones([2, 1, 4]),
                        paddle.ones([2, 5, 4]), paddle.ones([2, 1, 4]),
                        "relu")


def test_fused_layer_classes(rng):
    """incubate.nn layer classes (reference incubate/nn/__init__.py
    export set) wrap the functional surface."""
    from paddle_tpu.incubate import nn as inn

    lin = inn.FusedLinear(8, 4)
    assert tuple(lin(paddle.ones([2, 8])).shape) == (2, 4)
    assert len(list(lin.parameters())) == 2

    moe = inn.FusedEcMoe(8, 16, 3, act_type="relu")
    y = moe(paddle.randn([2, 5, 8]), paddle.randn([2, 5, 3]))
    assert tuple(y.shape) == (2, 5, 8)

    bdr = inn.FusedBiasDropoutResidualLayerNorm(8, dropout_rate=0.0)
    z = bdr(paddle.randn([2, 3, 8]), paddle.randn([2, 3, 8]))
    assert tuple(z.shape) == (2, 3, 8)

    da = inn.FusedDropoutAdd(p=0.3)
    da.eval()
    np.testing.assert_allclose(
        da(paddle.ones([2, 2]), paddle.ones([2, 2])).numpy(), 2.0)

    dr = inn.FusedDropout(p=0.5)
    dr.eval()
    np.testing.assert_allclose(dr(paddle.ones([3])).numpy(), 1.0)

    with pytest.raises(NotImplementedError):
        inn.FusedMultiTransformer()


def test_fused_linear_layer_trains(rng):
    from paddle_tpu import optimizer
    from paddle_tpu.incubate import nn as inn

    paddle.seed(0)
    lin = inn.FusedLinear(4, 1)
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    X = paddle.to_tensor(rng.standard_normal((16, 4)).astype("float32"))
    Y = paddle.to_tensor(rng.standard_normal((16, 1)).astype("float32"))
    l0 = None
    for _ in range(20):
        loss = ((lin(X) - Y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if l0 is None:
            l0 = float(loss.numpy())
    assert float(loss.numpy()) < l0


def test_fused_layers_honor_param_attrs(rng):
    """weight_attr/bias_attr contracts: custom initializers are applied
    and bias_attr=False removes the bias parameters (reference API)."""
    from paddle_tpu.incubate import nn as inn
    from paddle_tpu import ParamAttr
    from paddle_tpu.nn import initializer

    lin = inn.FusedLinear(4, 3, weight_attr=ParamAttr(
        initializer=initializer.Constant(0.5)), bias_attr=False)
    assert lin.bias is None
    assert len(list(lin.parameters())) == 1
    np.testing.assert_allclose(lin.weight.numpy(), 0.5)
    np.testing.assert_allclose(
        lin(paddle.ones([2, 4])).numpy(), 2.0, rtol=1e-6)

    moe = inn.FusedEcMoe(4, 8, 2, act_type="relu", bias_attr=False)
    assert len(list(moe.parameters())) == 2  # only the two weights
    y = moe(paddle.randn([1, 3, 4]), paddle.randn([1, 3, 2]))
    assert tuple(y.shape) == (1, 3, 4)

    bdr = inn.FusedBiasDropoutResidualLayerNorm(4, dropout_rate=0.0,
                                                bias_attr=False)
    assert bdr.linear_bias is None and bdr.ln_bias is None
    out = bdr(paddle.randn([2, 4]), paddle.randn([2, 4]))
    assert tuple(out.shape) == (2, 4)

    # FusedDropout IS nn.Dropout (one implementation to maintain)
    from paddle_tpu import nn as base_nn

    assert issubclass(inn.FusedDropout, base_nn.Dropout)
