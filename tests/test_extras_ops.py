"""Long-tail op surface (ops/extras.py): stack/split family, special
math, indexed scatter, predicates — numpy/scipy-referenced numerics
plus gradient-flow checks.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(a, dt="float32"):
    return paddle.to_tensor(np.asarray(a, dt))


def test_stack_split_family():
    np.testing.assert_allclose(
        paddle.hstack([t([1, 2]), t([3])]).numpy(), [1, 2, 3])
    np.testing.assert_allclose(
        paddle.vstack([t([[1, 2]]), t([[3, 4]])]).numpy(),
        [[1, 2], [3, 4]])
    np.testing.assert_allclose(
        paddle.column_stack([t([1, 2]), t([3, 4])]).numpy(),
        [[1, 3], [2, 4]])
    parts = paddle.tensor_split(t(np.arange(7)), 3)
    assert [tuple(p.shape) for p in parts] == [(3,), (2,), (2,)]
    hs = paddle.hsplit(t(np.arange(12).reshape(3, 4)), 2)
    assert [tuple(p.shape) for p in hs] == [(3, 2), (3, 2)]
    us = paddle.unstack(t(np.arange(6).reshape(2, 3)))
    assert len(us) == 2 and tuple(us[0].shape) == (3,)
    uf = paddle.unflatten(t(np.arange(6)), 0, [2, 3])
    assert tuple(uf.shape) == (2, 3)


def test_math_long_tail():
    np.testing.assert_allclose(
        paddle.addmm(t(np.ones((2, 2))), t(np.eye(2)), t(2 * np.eye(2)),
                     beta=0.5, alpha=2.0).numpy(),
        0.5 + 4.0 * np.eye(2))
    np.testing.assert_allclose(
        paddle.copysign(t([1.0, -2.0]), t([-1.0, 1.0])).numpy(),
        [-1.0, 2.0])
    np.testing.assert_allclose(
        paddle.logcumsumexp(t([0.0, 0.0])).numpy(),
        [0.0, np.log(2)], rtol=1e-6)
    np.testing.assert_allclose(paddle.sgn(t([-3.0, 0.0, 5.0])).numpy(),
                               [-1.0, 0.0, 1.0])
    np.testing.assert_allclose(paddle.gammaln(t([4.0])).numpy(),
                               [np.log(6.0)], rtol=1e-5)
    np.testing.assert_allclose(
        paddle.stanh(t([0.0])).numpy(), [0.0], atol=1e-7)
    m, e = paddle.frexp(t([8.0]))
    np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), [8.0])
    np.testing.assert_allclose(
        paddle.trapezoid(t([1.0, 1.0, 1.0])).numpy(), 2.0)
    np.testing.assert_allclose(
        paddle.cumulative_trapezoid(t([0.0, 1.0, 2.0])).numpy(),
        [0.5, 2.0])
    np.testing.assert_allclose(paddle.rad2deg(t([np.pi])).numpy(),
                               [180.0], rtol=1e-6)
    np.testing.assert_allclose(paddle.i0(t([0.0])).numpy(), [1.0],
                               rtol=1e-6)


def test_distance_ops():
    import scipy.spatial.distance as ssd

    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 3)).astype("float32")
    y = rng.standard_normal((5, 3)).astype("float32")
    np.testing.assert_allclose(paddle.cdist(t(x), t(y)).numpy(),
                               ssd.cdist(x, y), rtol=1e-4)
    np.testing.assert_allclose(paddle.pdist(t(x)).numpy(),
                               ssd.pdist(x), rtol=1e-4)
    # p=1 and p=inf variants
    np.testing.assert_allclose(
        paddle.cdist(t(x), t(y), p=1.0).numpy(),
        ssd.cdist(x, y, "minkowski", p=1), rtol=1e-4)
    # gradient flows
    xt = t(x)
    xt.stop_gradient = False
    paddle.cdist(xt, t(y)).sum().backward()
    assert np.isfinite(xt.grad.numpy()).all()


def test_kthvalue_mode():
    v, i = paddle.kthvalue(t([3.0, 1.0, 2.0]), 2)
    assert float(v.numpy()) == 2.0 and int(i.numpy()) == 2
    mv, mi = paddle.mode(t([1.0, 2.0, 2.0, 3.0]))
    assert float(mv.numpy()) == 2.0 and int(mi.numpy()) == 2


def test_scatter_family():
    np.testing.assert_allclose(
        paddle.diag_embed(t([1.0, 2.0])).numpy(), np.diag([1.0, 2.0]))
    d = paddle.diagonal_scatter(t(np.zeros((3, 3))), t([5.0, 6.0, 7.0]))
    np.testing.assert_allclose(np.diag(d.numpy()), [5.0, 6.0, 7.0])
    s = paddle.select_scatter(t(np.zeros((2, 3))), t([1.0, 2.0, 3.0]),
                              axis=0, index=1)
    np.testing.assert_allclose(s.numpy()[1], [1.0, 2.0, 3.0])
    sl = paddle.slice_scatter(t(np.zeros(5)), t([9.0, 9.0]),
                              axes=[0], starts=[1], ends=[3],
                              strides=[1])
    np.testing.assert_allclose(sl.numpy(), [0, 9, 9, 0, 0])
    fi = paddle.index_fill(t(np.zeros(4)),
                           paddle.to_tensor(np.asarray([1, 3])), 0, 7.0)
    np.testing.assert_allclose(fi.numpy(), [0, 7, 0, 7])
    sn = paddle.scatter_nd(paddle.to_tensor(np.asarray([[1], [3]])),
                           t([10.0, 20.0]), [5])
    np.testing.assert_allclose(sn.numpy(), [0, 10, 0, 20, 0])


def test_take_slice_reverse_crop():
    np.testing.assert_allclose(
        paddle.take(t([[1.0, 2.0], [3.0, 4.0]]),
                    paddle.to_tensor(np.asarray([0, 3]))).numpy(),
        [1.0, 4.0])
    np.testing.assert_allclose(
        paddle.slice(t(np.arange(10)), [0], [2], [5]).numpy(),
        [2, 3, 4])
    np.testing.assert_allclose(
        paddle.strided_slice(t(np.arange(10)), [0], [0], [8],
                             [2]).numpy(), [0, 2, 4, 6])
    np.testing.assert_allclose(
        paddle.reverse(t([1.0, 2.0, 3.0]), 0).numpy(), [3.0, 2.0, 1.0])
    np.testing.assert_allclose(
        paddle.crop(t(np.arange(9).reshape(3, 3)), shape=[2, 2],
                    offsets=[1, 0]).numpy(), [[3, 4], [6, 7]])


def test_complex_views():
    c = paddle.as_complex(t([[1.0, 2.0]]))
    assert paddle.is_complex(c)
    np.testing.assert_allclose(paddle.as_real(c).numpy(), [[1.0, 2.0]])


def test_predicates_and_misc():
    assert bool(paddle.isposinf(t([np.inf])).numpy()[0])
    assert bool(paddle.isneginf(t([-np.inf])).numpy()[0])
    assert not bool(paddle.is_empty(t([1.0])).numpy())
    un = paddle.unique_consecutive(
        paddle.to_tensor(np.asarray([1, 1, 2, 2, 3, 1])))
    np.testing.assert_allclose(un.numpy(), [1, 2, 3, 1])
    out, inv, cnt = paddle.unique_consecutive(
        paddle.to_tensor(np.asarray([1, 1, 2])), return_inverse=True,
        return_counts=True)
    np.testing.assert_allclose(cnt.numpy(), [2, 1])
    mp = paddle.multiplex([t([[1.0], [2.0]]), t([[10.0], [20.0]])],
                          paddle.to_tensor(np.asarray([[0], [1]])))
    np.testing.assert_allclose(mp.numpy(), [[1.0], [20.0]])
    comb = paddle.combinations(t([1.0, 2.0, 3.0]), 2)
    assert tuple(comb.shape) == (3, 2)
    np.testing.assert_allclose(
        paddle.renorm(t(np.asarray([[3.0, 4.0], [0.3, 0.4]]).T), p=2.0,
                      axis=1, max_norm=1.0).numpy().T[0],
        [0.6, 0.8], rtol=1e-5)


def test_random_long_tail():
    paddle.seed(0)
    b = paddle.binomial(t(np.full(200, 10.0)), t(np.full(200, 0.5)))
    assert 3.0 < float(b.numpy().mean()) < 7.0
    g = paddle.standard_gamma(t(np.full(200, 2.0)))
    assert 1.0 < float(g.numpy().mean()) < 3.0
    r = paddle.randint_like(t(np.zeros(50)), 0, 5)
    assert set(np.unique(r.numpy().astype(int))) <= {0, 1, 2, 3, 4}


def test_bit_shifts():
    x = paddle.to_tensor(np.asarray([8, -8], "int32"))
    np.testing.assert_allclose(
        paddle.bitwise_left_shift(
            x, paddle.to_tensor(np.asarray([1, 1], "int32"))).numpy(),
        [16, -16])
    np.testing.assert_allclose(
        paddle.bitwise_right_shift(
            x, paddle.to_tensor(np.asarray([2, 2], "int32"))).numpy(),
        [2, -2])


def test_where_inplace_targets_x_not_condition():
    cond = paddle.to_tensor(np.array([True, False]))
    x = t([1.0, 2.0])
    y = t([10.0, 20.0])
    r = paddle.where_(cond, x, y)
    assert r is x
    np.testing.assert_allclose(x.numpy(), [1.0, 20.0])
    assert cond.numpy().dtype == np.bool_  # mask untouched


def test_take_raise_mode_raises():
    with pytest.raises(IndexError):
        paddle.take(t(np.arange(5.0)),
                    paddle.to_tensor(np.asarray([10])))


def test_histogramdd_pair_contract():
    h, edges = paddle.histogramdd(
        t(np.random.default_rng(0).random((10, 2))), bins=4)
    assert tuple(h.shape) == (4, 4)
    assert isinstance(edges, list) and len(edges) == 2


def test_diag_embed_nondefault_dims():
    d = paddle.diag_embed(t(np.ones((2, 3))), dim1=0, dim2=1)
    assert tuple(d.shape) == (3, 3, 2)
    np.testing.assert_allclose(d.numpy()[0, 0], np.ones(2))


def test_cdist_matmul_path_matches_diff_path():
    rng = np.random.default_rng(2)
    a = rng.standard_normal((6, 4)).astype("float32")
    b = rng.standard_normal((7, 4)).astype("float32")
    fast = paddle.cdist(t(a), t(b)).numpy()
    slow = paddle.cdist(t(a), t(b),
                        compute_mode="donot_use_mm_for_euclid_dist"
                        ).numpy()
    np.testing.assert_allclose(fast, slow, rtol=1e-3, atol=1e-4)
