"""BERT encoder family (models/bert.py) + the Tensor.__deepcopy__
buffer-copy regression it exposed (TransformerEncoder clones layers via
deepcopy; shared buffers broke whole-step donation)."""
import copy

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.models.bert import (
    BertConfig, BertForMaskedLM, BertForSequenceClassification,
    BertModel, BertPretrainingCriterion,
)


def _cfg():
    return BertConfig(vocab_size=300, hidden_size=32,
                      num_hidden_layers=2, num_attention_heads=4,
                      intermediate_size=64, max_position_embeddings=32)


def test_bert_model_shapes_and_padding_mask():
    paddle.seed(0)
    m = BertModel(_cfg())
    m.eval()
    ids = paddle.to_tensor(np.asarray(
        [[5, 6, 7, 0, 0], [8, 9, 10, 11, 12]], "int32"))
    seq, pooled = m(ids)
    assert tuple(seq.shape) == (2, 5, 32)
    assert tuple(pooled.shape) == (2, 32)
    # padding positions must not influence real ones: change a padded id
    ids2 = paddle.to_tensor(np.asarray(
        [[5, 6, 7, 99, 99], [8, 9, 10, 11, 12]], "int32"))
    mask = paddle.to_tensor(np.asarray(
        [[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], "int32"))
    s1, _ = m(ids, attention_mask=mask)
    s2, _ = m(ids2, attention_mask=mask)
    np.testing.assert_allclose(s1.numpy()[0, :3], s2.numpy()[0, :3],
                               rtol=1e-4, atol=1e-5)


def test_bert_mlm_trains_and_ties_embeddings():
    paddle.seed(0)
    cfg = _cfg()
    m = BertForMaskedLM(cfg)
    crit = BertPretrainingCriterion(cfg)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(1, 300, (4, 16)).astype("int32"))
    labels_np = np.full((4, 16), -100, "int32")
    labels_np[:, 3] = np.asarray(rng.integers(1, 300, 4))
    labels = paddle.to_tensor(labels_np)
    opt = optimizer.AdamW(1e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, crit, opt)
    losses = [float(step(ids, labels)) for _ in range(10)]
    assert losses[-1] < losses[0]
    # tied: exactly one vocab x hidden matrix among the parameters
    big = [p for p in m.parameters()
           if tuple(p.shape) == (cfg.vocab_size, cfg.hidden_size)]
    assert len(big) == 1


def test_bert_classifier_forward():
    paddle.seed(0)
    cls = BertForSequenceClassification(_cfg(), num_classes=5)
    ids = paddle.to_tensor(
        np.random.default_rng(1).integers(1, 300, (3, 8)).astype("int32"))
    out = cls(ids)
    assert tuple(out.shape) == (3, 5)


def test_encoder_layers_have_distinct_buffers():
    """TransformerEncoder deep-copies its layer; copies must own their
    buffers (identity sharing breaks XLA donation: donate(a), donate(a))."""
    layer = nn.TransformerEncoderLayer(16, 2, 32)
    clone = copy.deepcopy(layer)
    for a, b in zip(layer.parameters(), clone.parameters()):
        assert a._data is not b._data
        np.testing.assert_allclose(np.asarray(a._data),
                                   np.asarray(b._data))


def test_trainstep_over_transformer_encoder():
    """Regression: whole-step compile + donation over deepcopy-cloned
    encoder layers (failed with 'donate the same buffer twice')."""
    paddle.seed(0)
    enc = nn.Sequential(
        nn.Embedding(50, 16),
        nn.TransformerEncoder(nn.TransformerEncoderLayer(16, 2, 32), 2),
        nn.Linear(16, 4))

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.enc = enc

        def forward(self, x):
            return self.enc(x)[:, 0]

    m = Head()
    opt = optimizer.Adam(1e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.CrossEntropyLoss(), opt)
    ids = paddle.to_tensor(
        np.random.default_rng(0).integers(0, 50, (4, 8)).astype("int32"))
    y = paddle.to_tensor(np.asarray([0, 1, 2, 3], "int64"))
    losses = [float(step(ids, y)) for _ in range(10)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_incubate_fused_transformer_layers():
    """Fused-layer surface (reference incubate/nn/layer/
    fused_transformer.py): pre/post-norm variants run and train."""
    from paddle_tpu.incubate.nn import (
        FusedFeedForward, FusedMultiHeadAttention,
        FusedTransformerEncoderLayer,
    )

    paddle.seed(0)
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(2, 5, 16))
        .astype("float32"))
    for pre in (True, False):
        attn = FusedMultiHeadAttention(16, 2, dropout_rate=0.0,
                                       attn_dropout_rate=0.0,
                                       normalize_before=pre)
        assert tuple(attn(x).shape) == (2, 5, 16)
        ffn = FusedFeedForward(16, 32, dropout_rate=0.0,
                               normalize_before=pre)
        assert tuple(ffn(x).shape) == (2, 5, 16)
    layer = FusedTransformerEncoderLayer(16, 2, 32, dropout_rate=0.0)
    out = layer(x)
    loss = paddle.sum(out * out)
    loss.backward()
    grads = [p.grad for p in layer.parameters() if p.grad is not None]
    assert grads, "fused layer must be trainable"


def test_fused_attention_cache_and_cross_attention_guard():
    from paddle_tpu.incubate.nn import FusedMultiHeadAttention

    paddle.seed(0)
    attn = FusedMultiHeadAttention(16, 2, dropout_rate=0.0,
                                   attn_dropout_rate=0.0)
    attn.eval()
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(1, 4, 16))
        .astype("float32"))
    other = paddle.to_tensor(np.zeros((1, 4, 16), "float32"))
    try:
        attn(x, other, other)
        raised = False
    except NotImplementedError:
        raised = True
    assert raised, "cross-attention must raise (self-attention only)"
    out = attn(x)
    assert tuple(out.shape) == (1, 4, 16)
