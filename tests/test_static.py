"""paddle.static Program mode (reference: python/paddle/static/,
base/framework.py Program build, base/executor.py:1179 Executor.run,
base/backward.py append_backward).

The TPU build records registry ops into a Program via the dispatch-seam
hook and compiles Executor.run into one XLA executable (see
paddle_tpu/static/__init__.py). These tests pin: graph build + run,
training via minimize (grads by jax.grad over the interpreted program),
BatchNorm side updates, static.gradients, per-run dropout randomness,
whole-Layer capture, test-mode clones, and the inference save/load
roundtrip."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_build_and_run_basic(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        y = x * 2.0 + 1.0
        z = paddle.sum(y)
    exe = static.Executor()
    out = exe.run(main, feed={"x": np.ones((4, 3), "float32")},
                  fetch_list=[y, z])
    np.testing.assert_allclose(out[0], np.full((4, 3), 3.0))
    assert float(out[1]) == pytest.approx(36.0)


def test_variable_introspection_and_no_value(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 7], "float32")
        y = paddle.matmul(x, paddle.transpose(x, [1, 0]))
        assert isinstance(y, static.Variable)
        assert tuple(y.shape) == (1, 1)  # -1 dims build as 1
        with pytest.raises(RuntimeError):
            y.numpy()


def test_feed_shape_respecialization(static_mode):
    """-1 dims: the Executor re-specializes per concrete feed shape."""
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        s = paddle.sum(x, axis=1)
    exe = static.Executor()
    o4 = exe.run(main, feed={"x": np.ones((4, 2), "float32")},
                 fetch_list=[s])
    o9 = exe.run(main, feed={"x": np.ones((9, 2), "float32")},
                 fetch_list=[s])
    assert o4[0].shape == (4,) and o9[0].shape == (9,)


def test_minimize_trains(static_mode):
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        y = static.data("y", [None, 1], "float32")
        h = static.nn.fc(x, 16, activation="relu")
        pred = static.nn.fc(h, 1)
        loss = paddle.mean((pred - y) ** 2)
        opt = optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(32, 4)).astype("float32")
    ys = (xs @ np.array([[0.5], [-1.0], [0.25], [2.0]], "float32"))
    first = last = None
    for _ in range(40):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys.astype("float32")},
                        fetch_list=[loss])
        first = float(lv) if first is None else first
        last = float(lv)
    assert last < first * 0.1


def test_whole_layer_capture(static_mode):
    """An eager-defined Layer records through static mode unchanged —
    the same registry seam serves both modes."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 2))
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [5, 6], "float32")
        out = net(x)
        assert isinstance(out, static.Variable)
    exe = static.Executor()
    (o,) = exe.run(main, feed={"x": np.ones((5, 6), "float32")},
                   fetch_list=[out])
    # parity with eager on the same weights
    paddle.disable_static()
    eager = net(paddle.ones([5, 6])).numpy()
    np.testing.assert_allclose(o, eager, rtol=1e-5)


def test_batchnorm_side_updates_commit(static_mode):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [8, 3, 4, 4], "float32")
        y = static.nn.batch_norm(x)
        m = paddle.mean(y)
    assert len(main.side_updates) == 2  # running mean + variance
    exe = static.Executor()
    xs = np.random.default_rng(1).normal(
        loc=2.0, size=(8, 3, 4, 4)).astype("float32")
    stats_before = [np.asarray(main.captures[i]._data).copy()
                    for i, _ in main.side_updates]
    exe.run(main, feed={"x": xs}, fetch_list=[m])
    stats_after = [np.asarray(main.captures[i]._data)
                   for i, _ in main.side_updates]
    moved = any(np.abs(a - b).sum() > 1e-6
                for a, b in zip(stats_after, stats_before))
    assert moved, "BN running stats were not committed"
    # eager buffers hold concrete values (no symbolic leakage)
    for i, _ in main.side_updates:
        assert not hasattr(main.captures[i]._data, "sharding") or True
        np.asarray(main.captures[i]._data)  # must not raise


def test_static_gradients(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3, 3], "float32")
        y = paddle.sum(x * x)
        (gx,) = static.gradients([y], [x])
    exe = static.Executor()
    xs = np.arange(9, dtype="float32").reshape(3, 3)
    out = exe.run(main, feed={"x": xs}, fetch_list=[gx])
    np.testing.assert_allclose(out[0], 2 * xs)


def test_append_backward_param_grads(static_mode):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 3], "float32")
        lin = nn.Linear(3, 2)
        loss = paddle.sum(lin(x))
        pairs = static.append_backward(loss)
    assert len(pairs) == 2  # weight + bias
    exe = static.Executor()
    outs = exe.run(main, feed={"x": np.ones((4, 3), "float32")},
                   fetch_list=[g for _, g in pairs])
    np.testing.assert_allclose(outs[1], np.full((2,), 4.0))  # bias grad


def test_dropout_varies_per_run(static_mode):
    main = static.Program()
    with static.program_guard(main):
        a = static.data("a", [4, 64], "float32")
        d = nn.functional.dropout(a, p=0.5, training=True)
        s = paddle.sum(d)
    exe = static.Executor()
    feed = {"a": np.ones((4, 64), "float32")}
    vals = {float(exe.run(main, feed=feed, fetch_list=[s])[0])
            for _ in range(3)}
    assert len(vals) > 1, "dropout mask must differ per run"


def test_clone_for_test_drops_training(static_mode):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 2, 4, 4], "float32")
        y = static.nn.batch_norm(x)
        loss = paddle.mean(y)
        opt = optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    test_prog = main.clone(for_test=True)
    assert test_prog._train is None and not test_prog.side_updates
    exe = static.Executor()
    stats = [np.asarray(main.captures[i]._data).copy()
             for i, _ in main.side_updates]
    exe.run(test_prog, feed={"x": np.ones((4, 2, 4, 4), "float32")},
            fetch_list=[loss])
    for (i, _), before in zip(main.side_updates, stats):
        np.testing.assert_allclose(np.asarray(main.captures[i]._data),
                                   before)  # eval run: stats frozen


def test_executor_cache_reuse(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        y = x + 1.0
    exe = static.Executor()
    feed = {"x": np.zeros((2, 2), "float32")}
    exe.run(main, feed=feed, fetch_list=[y])
    n = len(main._cache)
    exe.run(main, feed=feed, fetch_list=[y])
    assert len(main._cache) == n, "same signature must reuse the executable"


def test_enable_disable_static_mode():
    assert paddle.in_dynamic_mode()
    paddle.enable_static()
    assert not paddle.in_dynamic_mode()
    paddle.disable_static()
    assert paddle.in_dynamic_mode()
    # eager still works after a static session
    t = paddle.ones([2, 2]) * 3
    assert float(paddle.sum(t)) == 12.0


def test_attribute_variable_rejected(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        with pytest.raises(TypeError):
            paddle.reshape(x, x)  # shape attr can't be a Variable


def test_save_load_inference_model(static_mode, tmp_path):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4, 6], "float32")
        net = nn.Linear(6, 3)
        out = net(x)
    exe = static.Executor()
    xs = np.random.default_rng(0).normal(size=(4, 6)).astype("float32")
    (ref,) = exe.run(main, feed={"x": xs}, fetch_list=[out])
    path = str(tmp_path / "inf" / "model")
    static.save_inference_model(path, [x], [out], exe, program=main)
    loaded, feed_names, _ = static.load_inference_model(path, exe)
    got = loaded.run({"x": xs})
    np.testing.assert_allclose(got[0], ref, rtol=1e-5)


def test_frozen_param_survives_train_donation(static_mode):
    """A stop_gradient capture must keep a live buffer across train runs
    (donation covers only rebound captures)."""
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        frozen = nn.Linear(4, 4)
        for p in frozen.parameters():
            p.stop_gradient = True
        head = nn.Linear(4, 1)
        loss = paddle.mean(head(frozen(x)) ** 2)
        opt = optimizer.SGD(learning_rate=0.01)
        opt.minimize(loss)
    exe = static.Executor()
    feed = {"x": np.ones((8, 4), "float32")}
    exe.run(main, feed=feed, fetch_list=[loss])
    exe.run(main, feed=feed, fetch_list=[loss])  # buffer must be alive
    w = frozen.parameters()[0]
    np.asarray(w._data)  # not deleted
    # and the frozen weights did not move
    assert not np.isnan(np.asarray(w._data)).any()


def test_fc_dynamic_batch_with_flatten(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3, 4, 4], "float32")
        y = static.nn.fc(x, 5)
    exe = static.Executor()
    out = exe.run(main, feed={"x": np.ones((7, 3, 4, 4), "float32")},
                  fetch_list=[y])
    assert out[0].shape == (7, 5)


def test_cross_program_variable_rejected(static_mode):
    p1, p2 = static.Program(), static.Program()
    with static.program_guard(p1):
        x = static.data("x", [2, 2], "float32")
        y = x + 1.0
    with static.program_guard(p2):
        with pytest.raises(RuntimeError):
            y * 2.0


def test_clone_is_a_snapshot(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        y = x + 1.0
        test_prog = main.clone(for_test=True)
        z = y * 3.0  # recorded AFTER the clone
    assert len(test_prog.nodes) < len(main.nodes)
    exe = static.Executor()
    (o,) = exe.run(test_prog, feed={"x": np.zeros((2, 2), "float32")},
                   fetch_list=[y])
    np.testing.assert_allclose(o, 1.0)


def test_save_inference_model_batch_polymorphic(static_mode, tmp_path):
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 6], "float32")
        net = nn.Linear(6, 3)
        out = net(x)
    exe = static.Executor()
    path = str(tmp_path / "poly" / "model")
    static.save_inference_model(path, [x], [out], exe, program=main)
    loaded, names, _ = static.load_inference_model(path, exe)
    for bs in (1, 4, 9):
        got = loaded.run({"x": np.ones((bs, 6), "float32")})
        assert got[0].shape == (bs, 3)


def test_cond_branches_on_data(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4], "float32")
        y = static.nn.cond(paddle.sum(x) > 0.0,
                           lambda: x * 2.0,
                           lambda: x - 10.0)
    exe = static.Executor()
    pos = exe.run(main, feed={"x": np.ones(4, "float32")},
                  fetch_list=[y])
    neg = exe.run(main, feed={"x": -np.ones(4, "float32")},
                  fetch_list=[y])
    np.testing.assert_allclose(pos[0], 2.0)
    np.testing.assert_allclose(neg[0], -11.0)


def test_while_loop_runs_to_condition(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4], "float32")
        i0 = paddle.zeros([1])

        def c(v, i):
            return paddle.logical_and(paddle.sum(v) < 100.0,
                                      i[0] < 10.0)

        def b(v, i):
            return [v * 2.0, i + 1.0]

        z, n = static.nn.while_loop(c, b, [x, i0])
    exe = static.Executor()
    out = exe.run(main, feed={"x": np.ones(4, "float32")},
                  fetch_list=[z, n])
    assert out[0].sum() >= 100 and int(out[1][0]) == 5  # 4*2^5=128


def test_cond_nested_in_while_body(static_mode):
    main = static.Program()
    with static.program_guard(main):
        a = static.data("a", [1], "float32")

        def c2(v, i):
            return i[0] < 5.0

        def b2(v, i):
            w = static.nn.cond(v[0] > 10.0, lambda: v * 0.5,
                               lambda: v + 3.0)
            return [w, i + 1.0]

        z2, _ = static.nn.while_loop(c2, b2, [a, paddle.zeros([1])])
    exe = static.Executor()
    (o,) = exe.run(main, feed={"a": np.asarray([1.0], "float32")},
                   fetch_list=[z2])
    np.testing.assert_allclose(o, 6.5)  # 1->4->7->10->13->6.5


def test_gradients_flow_through_cond(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3], "float32")
        y = static.nn.cond(paddle.sum(x) > 0.0,
                           lambda: paddle.sum(x * x),
                           lambda: paddle.sum(x * 3.0))
        (gx,) = static.gradients([y], [x])
    exe = static.Executor()
    xs = np.asarray([1.0, 2.0, 3.0], "float32")
    (g,) = exe.run(main, feed={"x": xs}, fetch_list=[gx])
    np.testing.assert_allclose(g, 2 * xs)  # true branch: d(sum x^2)=2x
    (g2,) = exe.run(main, feed={"x": -xs}, fetch_list=[gx])
    np.testing.assert_allclose(g2, 3.0)    # false branch: constant 3


def test_py_func_forward_and_backward(static_mode):
    """Host python op inside the compiled program (reference
    static/nn/common.py py_func) with a host-computed vjp."""

    def host_fn(a):
        return np.tanh(a) * 2.0

    def host_bwd(a, y, g):
        # reference convention: backward_func(inputs, outputs, grads)
        return g * 2.0 * (1.0 - np.tanh(a) ** 2)

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3], "float32")
        y = static.nn.py_func(host_fn, x, ([3], "float32"),
                              backward_func=host_bwd)
        loss = paddle.sum(y * y)
        (gx,) = static.gradients([loss], [x])
    exe = static.Executor()
    xs = np.asarray([0.1, -0.5, 1.2], "float32")
    out = exe.run(main, feed={"x": xs}, fetch_list=[y, gx])
    ref_y = np.tanh(xs) * 2
    np.testing.assert_allclose(out[0], ref_y, rtol=1e-5)
    np.testing.assert_allclose(out[1],
                               2 * ref_y * 2 * (1 - np.tanh(xs) ** 2),
                               rtol=1e-4)


def test_py_func_without_backward_stops_gradient(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2], "float32")
        y = static.nn.py_func(lambda a: a * 3.0, x, ([2], "float32"))
        assert y.stop_gradient
    exe = static.Executor()
    (o,) = exe.run(main, feed={"x": np.ones(2, "float32")},
                   fetch_list=[y])
    np.testing.assert_allclose(o, 3.0)


def test_py_func_skip_vars_in_backward(static_mode):
    """skip_vars_in_backward_input drops the named inputs from the
    backward_func argument list (reference convention)."""

    def host_fn(a):
        return a * 4.0

    def host_bwd(y, g):  # input `x` skipped: gets (outputs, grads)
        return g * 4.0

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2], "float32")
        y = static.nn.py_func(host_fn, x, ([2], "float32"),
                              backward_func=host_bwd,
                              skip_vars_in_backward_input=[x])
        loss = paddle.sum(y)
        (gx,) = static.gradients([loss], [x])
    exe = static.Executor()
    out = exe.run(main, feed={"x": np.ones(2, "float32")},
                  fetch_list=[gx])
    np.testing.assert_allclose(out[0], 4.0)


def test_py_func_integer_input_gets_float0_cotangent(static_mode):
    """Mixed float/int inputs: gradients flow to the float input; the
    integer input takes a float0 cotangent (custom_vjp contract)."""

    def host_fn(feats, idx):
        return feats[idx]

    def host_bwd(feats, idx, y, g):
        out = np.zeros_like(feats)
        out[np.asarray(idx)] = np.asarray(g)
        return out

    main = static.Program()
    with static.program_guard(main):
        feats = static.data("feats", [4], "float32")
        idx = static.data("idx", [2], "int32")
        y = static.nn.py_func(host_fn, [feats, idx], ([2], "float32"),
                              backward_func=host_bwd)
        loss = paddle.sum(y)
        (gf,) = static.gradients([loss], [feats])
    exe = static.Executor()
    out = exe.run(main, feed={"feats": np.asarray([1., 2., 3., 4.],
                                                  "float32"),
                              "idx": np.asarray([1, 3], "int32")},
                  fetch_list=[y, gf])
    np.testing.assert_allclose(out[0], [2.0, 4.0])
    np.testing.assert_allclose(out[1], [0.0, 1.0, 0.0, 1.0])


def test_while_loop_static_trips_gradients(static_mode):
    """VERDICT r4 #8: fixed-trip-count while lowers to lax.scan and
    static.gradients works through it, matching the unrolled graph."""
    import jax
    import jax.numpy as jnp

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [4], "float32")
        w = static.data("w", [1], "float32")
        i = paddle.zeros([1], dtype="float32")
        h = x * 1.0

        def cond(i, h):
            return (i < 6.0).all()

        def body(i, h):
            return i + 1.0, paddle.tanh(h * w) + x

        i_out, h_out = static.nn.while_loop(cond, body, [i, h])
        loss = (h_out * h_out).sum()
        (gw,) = static.gradients([loss], [w])
        (gx,) = static.gradients([loss], [x])
    exe = static.Executor()
    xs = np.asarray([0.1, -0.2, 0.3, 0.5], "float32")
    ws = np.asarray([0.7], "float32")
    out = exe.run(main, feed={"x": xs, "w": ws},
                  fetch_list=[h_out, gw, gx])

    def ref(xv, wv):
        h = xv
        for _ in range(6):
            h = jnp.tanh(h * wv) + xv
        return h

    np.testing.assert_allclose(
        out[0], np.asarray(ref(jnp.asarray(xs), jnp.asarray(ws))),
        rtol=1e-5)
    gw_ref = jax.grad(
        lambda wv: jnp.sum(ref(jnp.asarray(xs), wv) ** 2))(
            jnp.asarray(ws))
    gx_ref = jax.grad(
        lambda xv: jnp.sum(ref(xv, jnp.asarray(ws)) ** 2))(
            jnp.asarray(xs))
    np.testing.assert_allclose(out[1], np.asarray(gw_ref), rtol=1e-4)
    np.testing.assert_allclose(out[2], np.asarray(gx_ref), rtol=1e-4)


def test_while_loop_capture_bound_refreshes(static_mode):
    """A capture-driven trip bound re-simulates (and recompiles) when
    the capture's value changes — never a silently stale count."""
    import jax.numpy as jnp

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [1], "float32")
        n = paddle.to_tensor(np.asarray([3.0], "float32"))
        i = paddle.zeros([1], dtype="float32")

        def cond(i, h):
            return (i < n).all()

        def body(i, h):
            return i + 1.0, h * 2.0

        _, h_out = static.nn.while_loop(cond, body, [i, x])
        (gx,) = static.gradients([h_out.sum()], [x])
    exe = static.Executor()
    out = exe.run(main, feed={"x": np.asarray([1.0], "float32")},
                  fetch_list=[h_out, gx])
    np.testing.assert_allclose(out[0], [8.0])
    np.testing.assert_allclose(out[1], [8.0])
    n_t = [t for t in main.captures
           if t._data.shape == (1,)
           and float(np.asarray(t._data)[0]) == 3.0][0]
    n_t._data = jnp.asarray([5.0])
    out = exe.run(main, feed={"x": np.asarray([1.0], "float32")},
                  fetch_list=[h_out, gx])
    np.testing.assert_allclose(out[0], [32.0])
    np.testing.assert_allclose(out[1], [32.0])


def test_while_loop_feed_bound_still_raises(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [1], "float32")
        nf = static.data("n", [1], "float32")
        i = paddle.zeros([1], dtype="float32")

        def cond(i, h):
            return (i < nf).all()

        def body(i, h):
            return i + 1.0, h * 2.0

        _, h_out = static.nn.while_loop(cond, body, [i, x])
        with pytest.raises(NotImplementedError):
            static.gradients([(h_out * h_out).sum()], [x])
