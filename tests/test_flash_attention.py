"""Pallas flash attention vs XLA SDPA fallback (interpret mode on the CPU
mesh — VERDICT.md round-1 item 2: numerics-verify pallas vs fallback)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas import flash_attention as fa
from paddle_tpu.ops.registry import API


def _sdpa_ref(q, k, v, causal):
    # plain [B,S,H,D] attention in f32
    qt = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)
    kt = jnp.transpose(k, (0, 2, 1, 3)).astype(jnp.float32)
    vt = jnp.transpose(v, (0, 2, 1, 3)).astype(jnp.float32)
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2:]
        # bottom-right aligned (reference FA2 semantics for sq != sk)
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.transpose(o, (0, 2, 1, 3))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 128, 2, 64), (1, 256, 4, 32)])
def test_flash_forward_matches_reference(causal, shape):
    b, s, h, d = shape
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, h, d), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), dtype=jnp.float32)
    out = fa.flash_attention_data(q, k, v, causal=causal, block_q=64,
                                  block_k=64, interpret=True)
    ref = _sdpa_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("sq,sk", [(64, 128), (128, 256), (64, 256)])
def test_flash_causal_cross_length_bottom_right(sq, sk):
    """ADVICE r2 (high): causal mask must be bottom-right aligned when
    q_seq != k_seq, matching the SDPA fallback and FA2 semantics."""
    b, h, d = 1, 2, 32
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(b, sq, h, d), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(b, sk, h, d), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(b, sk, h, d), dtype=jnp.float32)
    out = fa.flash_attention_data(q, k, v, causal=True, block_q=64,
                                  block_k=64, interpret=True)
    ref = _sdpa_ref(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)

    def f_flash(q, k, v):
        return jnp.sum(fa.flash_attention_data(
            q, k, v, causal=True, block_q=64, block_k=64,
            interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_sdpa_ref(q, k, v, True) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    b, s, h, d = 1, 128, 2, 32
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, s, h, d), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), dtype=jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(fa.flash_attention_data(
            q, k, v, causal=causal, block_q=64, block_k=64,
            interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_sdpa_ref(q, k, v, causal) ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-4)


def test_flash_attention_op_on_tape():
    """Tensor-level op participates in eager autograd."""
    paddle.seed(0)
    q = paddle.randn([1, 128, 2, 32])
    k = paddle.randn([1, 128, 2, 32])
    v = paddle.randn([1, 128, 2, 32])
    q.stop_gradient = False
    out = API["flash_attention"](q, k, v, causal=True)
    out.sum().backward()
    assert q.grad is not None
    assert q.grad.shape == [1, 128, 2, 32]


def test_entrypoint_uses_pallas_for_tileable_shapes():
    from paddle_tpu.ops import pallas_attention

    paddle.seed(0)
    q = paddle.randn([1, 256, 2, 32])
    k = paddle.randn([1, 256, 2, 32])
    v = paddle.randn([1, 256, 2, 32])
    out = pallas_attention.flash_attention(q, k, v, causal=True)
    ref = _sdpa_ref(q._data, k._data, v._data, True)
    np.testing.assert_allclose(out.numpy(), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)
