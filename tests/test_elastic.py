"""Elastic membership (scale-in/out) + step watchdog hang-abort.

Reference: fleet/elastic/manager.py:124 (membership watch, scale in/out,
relaunch), launch --nnodes min:max; phi/core/distributed/
comm_task_manager.cc (hang watchdog abort)."""
import glob
import json
import os
import sys
import threading
import time
import types

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(os.path.dirname(__file__), "mp_scripts")


def _args(**kw):
    a = types.SimpleNamespace(
        nproc_per_node=1, nnodes="1", node_rank=0, master=None,
        log_dir=None, max_restart=0, restart_interval=0.2,
        training_script="", training_script_args=[], elastic_dir=None,
        hb_timeout=3.0)
    for k, v in kw.items():
        setattr(a, k, v)
    return a


def test_parse_nnodes():
    from paddle_tpu.distributed.launch.elastic import parse_nnodes

    assert parse_nnodes("4") == (4, 4)
    assert parse_nnodes("2:4") == (2, 4)
    assert parse_nnodes(3) == (3, 3)
    with pytest.raises(ValueError):
        parse_nnodes("4:2")


def test_heartbeat_membership(tmp_path):
    from paddle_tpu.distributed.launch.elastic import (
        ElasticManager, Heartbeat, request_join,
    )

    d = str(tmp_path)
    mgr = ElasticManager(d, 2, 4, hb_timeout=1.0)
    hb1 = Heartbeat(d, "w0", interval=0.2).start()
    hb2 = Heartbeat(d, "w1", interval=0.2).start()
    time.sleep(0.3)
    assert mgr.live_nodes() == {"w0", "w1"}
    hb2.stop()
    time.sleep(1.2)
    assert mgr.live_nodes() == {"w0"}
    # scale decisions
    assert mgr.decide_world(4, lost=1) == 3
    assert mgr.decide_world(2, lost=1) is None  # below min
    request_join(d, "n9")
    assert mgr.decide_world(3) == 4
    assert mgr.decide_world(4) == 4  # capped at max
    mgr.clear_join_requests()
    assert mgr.decide_world(3) == 3
    hb1.stop()


@pytest.mark.parametrize("registry", ["file", "tcp"])
def test_elastic_scale_in_then_out(tmp_path, registry):
    """Kill one worker of 4 -> gang re-forms at 3 and resumes from
    checkpoint; a join request scales back to 4 (VERDICT item 5). The
    'tcp' variant runs the membership registry through a TCPStore with
    NO shared directory (VERDICT r4 #7 — the reference's etcd role)."""
    from paddle_tpu.distributed.launch import launch
    from paddle_tpu.distributed.launch.elastic import request_join

    out_dir = str(tmp_path / "out")
    if registry == "tcp":
        from paddle_tpu.distributed.store import TCPStore

        elastic_dir, _stop = TCPStore.serve("127.0.0.1", 0)
    else:
        elastic_dir = str(tmp_path / "elastic")
    os.makedirs(out_dir)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    args = _args(nnodes="2:4",
                 training_script=os.path.join(SCRIPTS,
                                              "elastic_worker.py"),
                 elastic_dir=elastic_dir, max_restart=5,
                 log_dir=str(tmp_path / "logs"))
    # a loaded CI host can stall heartbeat threads past the 3 s default,
    # which reads as a dead node and derails the scripted scale sequence
    args.hb_timeout = 15.0
    extra = {"ELASTIC_TEST_DIR": out_dir,
             "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", "")}

    # post the join request once attempt 1 (world 3) is running
    def joiner():
        deadline = time.time() + 120
        while time.time() < deadline:
            if glob.glob(os.path.join(out_dir, "attempt1.rank0.json")):
                time.sleep(0.5)
                request_join(elastic_dir, "newnode")
                return
            time.sleep(0.2)

    t = threading.Thread(target=joiner, daemon=True)
    t.start()
    rc = launch(args, extra_env=extra)
    t.join(timeout=5)
    assert rc == 0

    def worlds(attempt):
        rows = []
        for f in sorted(glob.glob(os.path.join(
                out_dir, f"attempt{attempt}.rank*.json"))):
            rows.append(json.load(open(f))["world"])
        return rows

    assert worlds(0) == [4, 4, 4, 4]
    assert worlds(1) == [3, 3, 3]      # scale-in after the lost worker
    assert worlds(2) == [4, 4, 4, 4]   # scale-out after the join request
    # checkpoint resume: final step advanced past the attempt-0 value
    steps = [int(np.load(f)["step"]) for f in
             glob.glob(os.path.join(out_dir, "ckpt.rank*.npz"))]
    assert steps and all(s >= 6 for s in steps)


def test_watchdog_unit_fires_on_hung_step():
    """arm() before dispatch; a step that never completes (no attach)
    must fire the monitor with the step's tag."""
    from paddle_tpu.distributed.watchdog import StepWatchdog

    fired = []
    wd = StepWatchdog(timeout=0.5, on_timeout=lambda e: fired.append(e))
    wd.arm("hung-step")
    deadline = time.time() + 5
    while not fired and time.time() < deadline:
        time.sleep(0.05)
    assert fired, "watchdog did not fire on a hung step"
    assert fired[0][0][0] == "hung-step"


def test_watchdog_fast_step_does_not_fire():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.watchdog import StepWatchdog

    fired = []
    wd = StepWatchdog(timeout=0.6, on_timeout=lambda e: fired.append(e))
    eid = wd.arm("fast-step")
    out = jax.jit(lambda x: x + 1)(jnp.zeros(()))
    wd.attach(eid, out)
    time.sleep(1.2)
    assert not fired


def test_watchdog_disabled_is_noop():
    from paddle_tpu.distributed.watchdog import StepWatchdog

    wd = StepWatchdog(timeout=0)
    wd.track(None, "x")  # must not start threads or throw
    assert not wd.fired


def test_watchdog_abort_and_gang_relaunch(tmp_path):
    """A hung compiled step aborts within the timeout and the launcher
    relaunches the gang; the retry completes (VERDICT item 6)."""
    from paddle_tpu.distributed.launch import launch

    env = dict(os.environ)
    args = _args(training_script=os.path.join(SCRIPTS, "hang_worker.py"),
                 max_restart=1, log_dir=str(tmp_path / "logs"))
    extra = {"PADDLE_STEP_TIMEOUT": "2",
             "PADDLE_STEP_COMPILE_ALLOWANCE": "3",
             "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", "")}
    t0 = time.time()
    rc = launch(args, extra_env=extra)
    assert rc == 0
    log0 = open(os.path.join(str(tmp_path / "logs"),
                             "workerlog.0")).read()
    assert "[watchdog]" in log0            # abort message + stacks
    assert "HANG_WORKER_DONE attempt=1" in log0
    assert time.time() - t0 < 60


def test_watchdog_cross_rank_abort(tmp_path):
    """One rank's hang must kill the whole gang fast, with 'rank R,
    tag T' in the logs (VERDICT r4 #6: step-attributable hang diagnosis
    + store-based abort broadcast)."""
    import subprocess
    import sys as _sys

    store_dir = str(tmp_path / "store")
    env = dict(os.environ)
    env.update({
        "PADDLE_STEP_TIMEOUT": "2",
        "PADDLE_STORE_DIR": store_dir,
        "PADDLE_ABORT_POLL": "0.5",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "JAX_PLATFORMS": "cpu",
    })
    script = os.path.join(SCRIPTS, "gang_abort_worker.py")
    procs = []
    for r in (0, 1):
        e = dict(env)
        e["PADDLE_TRAINER_ID"] = str(r)
        procs.append(subprocess.Popen(
            [_sys.executable, script], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    t0 = time.time()
    outs, codes = [], []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
        codes.append(p.returncode)
    elapsed = time.time() - t0
    # rank 1: its own watchdog fired with the collective tag
    assert codes[1] == 6, outs[1]
    assert "rank 1" in outs[1] and "all_reduce@ranks[0, 1]" in outs[1], \
        outs[1]
    # rank 0: learned of the abort via the store and named the culprit
    assert codes[0] == 7, outs[0]
    assert "rank 1 aborted" in outs[0] and "all_reduce" in outs[0], \
        outs[0]
    # the whole gang died within ~2x the timeout (+ startup)
    assert elapsed < 4 * 2 + 12, elapsed


def test_stale_abort_record_ignored(tmp_path, monkeypatch):
    """An abort record left by a PREVIOUS gang incarnation must not kill
    the relaunched ranks (else one transient hang crash-loops every
    restart). The guard is generation-based (baseline = the record seen
    on first poll), so cross-host clock skew cannot break it in either
    direction."""
    import json as _json

    from paddle_tpu.distributed import watchdog as wdm
    from paddle_tpu.distributed.store import FileStore

    store = FileStore(str(tmp_path))
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")

    hits = []
    wd = wdm.StepWatchdog(timeout=1.0,
                          on_remote_abort=lambda info: hits.append(info))
    wd._store = store
    # stale record already present when this "process" first looks
    store.set(wdm.ABORT_KEY, _json.dumps(
        {"rank": 1, "tags": "x", "gen": "old"}))
    wd._check_remote_abort()   # first poll: records the baseline
    wd._check_remote_abort()   # unchanged record -> no fire
    assert not hits and not wd.fired
    # CHANGED record (a fresh abort from a peer) -> handler fires
    store.set(wdm.ABORT_KEY, _json.dumps(
        {"rank": 1, "tags": "all_reduce@ranks[0, 1]", "gen": "new"}))
    wd._check_remote_abort()
    assert hits and hits[0]["rank"] == 1
