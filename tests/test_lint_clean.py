"""Tier-1 self-lint gate: the repo stays tracecheck-clean.

``python -m paddle_tpu.analysis paddle_tpu tests/mp_scripts`` must exit
0 — every true positive fixed, every accepted violation suppressed
inline WITH a reason (a reasonless suppression is itself a
``bad-suppression`` finding, so the policy is self-enforcing)."""
import os
import re

from paddle_tpu.analysis import analyze_paths, iter_python_files

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINTED = [os.path.join(REPO, "paddle_tpu"),
          os.path.join(REPO, "tests", "mp_scripts")]


def test_repo_is_lint_clean():
    findings = analyze_paths(LINTED)
    assert findings == [], "tracecheck found new violations:\n" + \
        "\n".join(f.render() for f in findings)


def test_lint_covers_a_real_file_set():
    """The gate must actually be looking at the tree (guard against a
    silently-empty walk making the clean assertion vacuous)."""
    files = iter_python_files(LINTED)
    assert len(files) > 150
    assert any(f.endswith("serving/engine.py") for f in files)


def _audited_files():
    """Everything linted except the analyzer package itself, whose
    docstrings/messages legitimately spell out the suppression syntax."""
    marker = os.path.join("paddle_tpu", "analysis") + os.sep
    return [f for f in iter_python_files(LINTED) if marker not in f]


def test_every_suppression_in_tree_names_its_rule_and_reason():
    """Grep-level audit, independent of the analyzer's own parsing:
    each `tpulint: disable=` carries (reason) text."""
    pat = re.compile(r"tpulint:\s*disable=([\w\-,\s]+?)\s*\(([^)]+)\)")
    bare = re.compile(r"tpulint:\s*disable=")
    for path in _audited_files():
        with open(path, encoding="utf-8") as fh:
            for i, line in enumerate(fh, 1):
                if bare.search(line):
                    assert pat.search(line), \
                        f"{path}:{i}: suppression without a reason"


def test_suppression_inventory_is_intentional():
    """Every suppression in the linted tree is one we wrote on purpose;
    new ones should be added consciously (update this list with a
    justification, mirroring the inline reason)."""
    expected = {
        # serving/engine.py: the engine's deliberate host boundaries —
        # ONE packed B-sized int fetch per step (tokens + emit counts +
        # advanced RNG keys; sampling is fully in-graph, so the old
        # B×vocab sampled-decode fetch is GONE), the B-bool
        # nonfinite-guard fetch, the swap-out KV spill (device->host
        # is the POINT of swap-based preemption), and the swapper's
        # tier-aware gather (reading host-tier frames back for
        # export/park IS a host copy by definition)
        "paddle_tpu/serving/engine.py": 4,
        # serving/kvtier/store.py: the demote copy — moving cold KV
        # blocks device->host is the tier boundary itself, off the
        # step's critical path
        "paddle_tpu/serving/kvtier/store.py": 1,
        # serving/spec.py: the draft proposer's B×k int proposal fetch —
        # its whole host boundary, same O(B) order as the engine's
        # packed-token fetch
        "paddle_tpu/serving/spec.py": 1,
        # watchdog prober: blocking per queued step on a daemon thread
        # IS the hang-detection mechanism
        "paddle_tpu/distributed/watchdog.py": 1,
        # profiler trace-window close barrier: once per trace, every
        # leaf must retire before the xplane window stops
        "paddle_tpu/profiler/__init__.py": 1,
        # async checkpoint writer: the runner thread's `self._error = e`
        # is read only through wait(), whose Thread.join() provides the
        # happens-before edge — a lock would be theater
        "paddle_tpu/distributed/checkpoint/manager.py": 1,
        # elastic heartbeat: start() beats once on the caller's thread
        # BEFORE Thread.start(); after that _misses is thread-local to
        # the heartbeat loop
        "paddle_tpu/distributed/launch/elastic.py": 1,
        # shm_queue one-time double-checked build: makedirs + g++ +
        # os.replace deliberately run under _BUILD_LOCK — serializing
        # the slow compile is the lock's entire purpose
        "paddle_tpu/io/shm_queue.py": 3,
        # fleet/router.py ×3 (leaked-resource-on-raise): the KV-ship
        # ticket ladders — every walk ends in exactly one counted
        # outcome because the ReplicaHandle RPC wrappers catch all
        # transport errors and return None rather than raising; the
        # walker can't see that cross-module no-raise contract
        "paddle_tpu/serving/fleet/router.py": 3,
        # request.py (counter-snapshot-drift): num_swaps is a
        # per-request diagnostic asserted directly by the resilience
        # tests; the fleet-visible aggregate is the scheduler's
        # swapped_out gauge
        "paddle_tpu/serving/request.py": 1,
        # fleet/sim.py (counter-snapshot-drift): num_steps is a
        # per-tick work flag the sim loop itself reads and resets to
        # pace stepping — not a lifetime counter
        "paddle_tpu/serving/fleet/sim.py": 1,
        # fleet/supervisor.py ×2 (counter-snapshot-drift): the
        # num_spawns/num_restarts ledger is asserted directly by the
        # failover tests; the supervisor runs beside the router fleet,
        # outside the router-scoped gauge maps
        "paddle_tpu/serving/fleet/supervisor.py": 2,
    }
    found = {}
    bare = re.compile(r"tpulint:\s*disable=")
    for path in _audited_files():
        rel = os.path.relpath(path, REPO)
        with open(path, encoding="utf-8") as fh:
            n = sum(1 for line in fh if bare.search(line))
        if n:
            found[rel] = n
    assert found == expected, (
        f"suppression inventory changed: {found} != {expected} — if "
        f"intentional, update test_lint_clean.py with the reason")
