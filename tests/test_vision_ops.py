"""paddle.vision.ops (reference: python/paddle/vision/ops.py; kernels
phi/kernels/gpu/{roi_align,roi_pool,psroi_pool,deformable_conv,
box_coder}_kernel.cu). Numeric references are hand-built numpy
implementations (the OpTest pattern, test/legacy_test/op_test.py:418)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


def _t(x):
    return paddle.to_tensor(np.asarray(x))


# ---------------------------------------------------------------------------
# nms
# ---------------------------------------------------------------------------

def _nms_ref(boxes, scores, thr):
    order = np.argsort(-scores)
    keep = []
    sup = np.zeros(len(boxes), bool)
    for i in order:
        if sup[i]:
            continue
        keep.append(i)
        for j in order:
            if sup[j] or j == i:
                continue
            bi, bj = boxes[i], boxes[j]
            iw = max(0.0, min(bi[2], bj[2]) - max(bi[0], bj[0]))
            ih = max(0.0, min(bi[3], bj[3]) - max(bi[1], bj[1]))
            inter = iw * ih
            ai = (bi[2] - bi[0]) * (bi[3] - bi[1])
            aj = (bj[2] - bj[0]) * (bj[3] - bj[1])
            if inter / (ai + aj - inter + 1e-10) > thr:
                sup[j] = True
    return np.asarray(keep)


def test_nms_matches_reference():
    rng = np.random.default_rng(0)
    xy = rng.uniform(0, 50, (40, 2))
    wh = rng.uniform(5, 25, (40, 2))
    boxes = np.concatenate([xy, xy + wh], axis=1).astype("float32")
    scores = rng.uniform(size=40).astype("float32")
    got = vops.nms(_t(boxes), 0.4, _t(scores)).numpy()
    ref = _nms_ref(boxes, scores, 0.4)
    np.testing.assert_array_equal(np.sort(got), np.sort(ref))
    # scores must be descending along the kept order
    assert (np.diff(scores[got]) <= 1e-6).all()


def test_nms_categories_do_not_cross_suppress():
    boxes = np.asarray([[0, 0, 10, 10], [1, 1, 10.5, 10.5]], "float32")
    scores = np.asarray([0.9, 0.8], "float32")
    cats = np.asarray([0, 1], "int64")
    got = vops.nms(_t(boxes), 0.3, _t(scores), _t(cats), [0, 1])
    assert len(got.numpy()) == 2  # same box, different class: both kept


def test_nms_top_k():
    boxes = np.asarray([[i * 20, 0, i * 20 + 10, 10] for i in range(6)],
                       "float32")
    scores = np.linspace(1, 0.5, 6).astype("float32")
    got = vops.nms(_t(boxes), 0.5, _t(scores), top_k=3).numpy()
    assert len(got) == 3


# ---------------------------------------------------------------------------
# roi_align / roi_pool / psroi_pool
# ---------------------------------------------------------------------------

def test_roi_align_constant_map():
    """On a constant feature map every aligned average is the constant."""
    x = np.full((1, 3, 16, 16), 7.0, "float32")
    boxes = np.asarray([[2, 2, 10, 10], [0, 0, 15, 15]], "float32")
    out = vops.roi_align(_t(x), _t(boxes), _t(np.asarray([2])),
                         output_size=4, spatial_scale=1.0)
    assert tuple(out.shape) == (2, 3, 4, 4)
    np.testing.assert_allclose(out.numpy(), 7.0, rtol=1e-6)


def test_roi_align_linear_ramp_center():
    """On f(y,x)=x the aligned value equals the sample-x mean (exact
    under bilinear interpolation of a linear function)."""
    H = W = 16
    x = np.tile(np.arange(W, dtype="float32"), (H, 1))[None, None]
    boxes = np.asarray([[4.0, 4.0, 12.0, 12.0]], "float32")
    out = vops.roi_align(_t(x), _t(boxes), _t(np.asarray([1])),
                         output_size=2, spatial_scale=1.0,
                         sampling_ratio=2, aligned=True)
    # aligned=True: bin 0 covers [3.5, 7.5) in x; 2x2 samples at
    # 3.5 + {1,3}*8/2/2/2... centers: x1=3.5, bin_w=4, samples at
    # 3.5 + (0.5, 1.5)*4/2 -> 4.5, 6.5 -> mean 5.5
    np.testing.assert_allclose(out.numpy()[0, 0, 0, 0], 5.5, atol=1e-5)


def test_roi_align_grad_flows():
    x = paddle.to_tensor(
        np.random.default_rng(0).normal(size=(1, 2, 8, 8))
        .astype("float32"))
    x.stop_gradient = False
    boxes = _t(np.asarray([[1, 1, 6, 6]], "float32"))
    out = vops.roi_align(x, boxes, _t(np.asarray([1])), output_size=3)
    paddle.sum(out).backward()
    g = x.grad.numpy()
    assert g.shape == (1, 2, 8, 8) and np.abs(g).sum() > 0


def test_roi_pool_max_semantics():
    x = np.zeros((1, 1, 8, 8), "float32")
    x[0, 0, 2, 2] = 5.0
    x[0, 0, 5, 5] = 9.0
    boxes = np.asarray([[0, 0, 7, 7]], "float32")
    out = vops.roi_pool(_t(x), _t(boxes), _t(np.asarray([1])),
                        output_size=2)
    o = out.numpy()[0, 0]
    assert o[0, 0] == 5.0 and o[1, 1] == 9.0


def test_psroi_pool_channel_groups():
    ph = pw = 2
    out_c = 3
    x = np.zeros((1, out_c * ph * pw, 6, 6), "float32")
    # each position-sensitive channel holds its own constant
    for c in range(out_c * ph * pw):
        x[0, c] = float(c)
    boxes = np.asarray([[0, 0, 6, 6]], "float32")
    out = vops.psroi_pool(_t(x), _t(boxes), _t(np.asarray([1])),
                          output_size=(ph, pw))
    assert tuple(out.shape) == (1, out_c, ph, pw)
    o = out.numpy()[0]
    # channel group layout: out[c, i, j] pools channel c*ph*pw + i*pw + j
    for c in range(out_c):
        for i in range(ph):
            for j in range(pw):
                assert o[c, i, j] == c * ph * pw + i * pw + j


# ---------------------------------------------------------------------------
# deform_conv2d
# ---------------------------------------------------------------------------

def test_deform_conv_zero_offset_matches_conv():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 4, 9, 9)).astype("float32")
    w = rng.normal(size=(6, 4, 3, 3)).astype("float32") * 0.1
    off = np.zeros((2, 2 * 9, 7, 7), "float32")
    got = vops.deform_conv2d(_t(x), _t(off), _t(w)).numpy()
    import paddle_tpu.nn.functional as F
    ref = F.conv2d(_t(x), _t(w)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_deform_conv_mask_scales_v2():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(1, 2, 6, 6)).astype("float32")
    w = rng.normal(size=(3, 2, 3, 3)).astype("float32")
    off = np.zeros((1, 18, 4, 4), "float32")
    half = np.full((1, 9, 4, 4), 0.5, "float32")
    full_ = vops.deform_conv2d(_t(x), _t(off), _t(w)).numpy()
    masked = vops.deform_conv2d(_t(x), _t(off), _t(w),
                                mask=_t(half)).numpy()
    np.testing.assert_allclose(masked, full_ * 0.5, rtol=1e-4, atol=1e-5)


def test_deform_conv_layer_trains():
    layer = vops.DeformConv2D(2, 4, 3, padding=1)
    x = paddle.ones([1, 2, 5, 5])
    off = paddle.zeros([1, 18, 5, 5])
    out = layer(x, off)
    assert tuple(out.shape) == (1, 4, 5, 5)
    loss = paddle.sum(out * out)
    loss.backward()
    assert layer.weight.grad is not None


# ---------------------------------------------------------------------------
# box_coder / prior_box / yolo
# ---------------------------------------------------------------------------

def test_box_coder_roundtrip():
    rng = np.random.default_rng(0)
    priors = np.asarray([[10, 10, 30, 40], [5, 5, 20, 25]], "float32")
    targets = np.asarray([[12, 11, 28, 35]], "float32")
    enc = vops.box_coder(_t(priors), [1., 1., 1., 1.], _t(targets),
                         code_type="encode_center_size").numpy()
    dec = vops.box_coder(_t(priors), [1., 1., 1., 1.],
                         _t(enc.transpose(1, 0, 2)),
                         code_type="decode_center_size", axis=0).numpy()
    # decode(encode(t)) must give back the target against each prior
    for pi in range(2):
        np.testing.assert_allclose(dec[pi, 0], targets[0], atol=1e-3)


def test_prior_box_shapes_and_range():
    x = paddle.ones([1, 8, 4, 4])
    img = paddle.ones([1, 3, 32, 32])
    boxes, vars_ = vops.prior_box(x, img, min_sizes=[8.0],
                                  aspect_ratios=[2.0], clip=True)
    assert boxes.shape[0] == 4 and boxes.shape[1] == 4
    b = boxes.numpy()
    assert b.min() >= 0.0 and b.max() <= 1.0
    assert vars_.numpy().shape == b.shape


def test_yolo_box_shapes_and_threshold():
    n, na, cn, h = 1, 3, 5, 4
    x = np.zeros((n, na * (5 + cn), h, h), "float32")
    x[:, 4::5 + cn] = -10.0  # all conf ~ 0 -> below threshold
    boxes, scores = vops.yolo_box(
        _t(x), _t(np.asarray([[64, 64]], "int32")),
        anchors=[10, 13, 16, 30, 33, 23], class_num=cn,
        conf_thresh=0.5, downsample_ratio=16)
    assert tuple(boxes.shape) == (n, na * h * h, 4)
    assert tuple(scores.shape) == (n, na * h * h, cn)
    assert np.abs(scores.numpy()).max() == 0.0  # thresholded out


def test_yolo_loss_decreases_on_fit():
    """Training signal sanity: optimizing the head on one gt reduces
    the loss (differentiability + target construction)."""
    rng = np.random.default_rng(0)
    cn = 3
    x = paddle.to_tensor(
        rng.normal(scale=0.1, size=(1, 3 * (5 + cn), 4, 4))
        .astype("float32"))
    x.stop_gradient = False
    gtb = _t(np.asarray([[[0.5, 0.5, 0.3, 0.4]]], "float32"))
    gtl = _t(np.asarray([[1]], "int32"))
    anchors = [10, 13, 16, 30, 33, 23]
    loss0 = None
    opt_x = x
    for i in range(25):
        loss = vops.yolo_loss(opt_x, gtb, gtl, anchors, [0, 1, 2], cn,
                              ignore_thresh=0.7, downsample_ratio=8)
        lv = float(paddle.sum(loss))
        if loss0 is None:
            loss0 = lv
        paddle.sum(loss).backward()
        opt_x = paddle.to_tensor(
            opt_x.numpy() - 0.1 * opt_x.grad.numpy())
        opt_x.stop_gradient = False
    assert lv < loss0


# ---------------------------------------------------------------------------
# proposals / fpn routing / matrix nms
# ---------------------------------------------------------------------------

def test_generate_proposals_runs_and_clips():
    rng = np.random.default_rng(0)
    n, a, h, w = 1, 3, 4, 4
    scores = rng.uniform(size=(n, a, h, w)).astype("float32")
    deltas = rng.normal(scale=0.1, size=(n, 4 * a, h, w)).astype("float32")
    anchors = rng.uniform(0, 30, (h, w, a, 4)).astype("float32")
    anchors[..., 2:] += anchors[..., :2] + 5
    var = np.full((h, w, a, 4), 1.0, "float32")
    rois, rscores, num = vops.generate_proposals(
        _t(scores), _t(deltas), _t(np.asarray([[32, 32]], "float32")),
        _t(anchors), _t(var), pre_nms_top_n=40, post_nms_top_n=10,
        nms_thresh=0.6, min_size=1.0, return_rois_num=True)
    r = rois.numpy()
    assert r.shape[1] == 4 and r.shape[0] == int(num.numpy()[0])
    assert r.min() >= 0.0 and r.max() <= 32.0


def test_distribute_fpn_proposals_routing_and_restore():
    rois = np.asarray([
        [0, 0, 10, 10],      # small -> low level
        [0, 0, 200, 200],    # large -> high level
        [0, 0, 56, 56],      # refer scale @ refer level
    ], "float32")
    outs, restore = vops.distribute_fpn_proposals(
        _t(rois), min_level=2, max_level=5, refer_level=4,
        refer_scale=224)
    sizes = [o.numpy().shape[0] for o in outs]
    assert sum(sizes) == 3 and len(outs) == 4
    # restore index maps concatenated-by-level order back to input order
    cat = np.concatenate([o.numpy() for o in outs if o.numpy().size],
                         axis=0)
    ri = restore.numpy().reshape(-1)
    np.testing.assert_allclose(cat[ri], rois)


def test_matrix_nms_decay_keeps_best():
    boxes = np.asarray([[[0, 0, 10, 10], [0.5, 0.5, 10, 10],
                         [20, 20, 30, 30]]], "float32")
    scores = np.asarray([[[0.9, 0.85, 0.8]]], "float32")
    out, nums = vops.matrix_nms(_t(boxes), _t(scores),
                                score_threshold=0.1, post_threshold=0.5,
                                background_label=-1)
    o = out.numpy()
    # best box and the disjoint box survive; the heavy overlap decays
    assert int(nums.numpy()[0]) == 2
    assert o[0, 1] == pytest.approx(0.9, abs=1e-5)


def test_conv_norm_activation_block():
    block = vops.ConvNormActivation(3, 8, 3)
    out = block(paddle.ones([2, 3, 8, 8]))
    assert tuple(out.shape) == (2, 8, 8, 8)
    assert float(out.numpy().min()) >= 0.0  # ReLU applied


def test_read_file_roundtrip(tmp_path):
    p = tmp_path / "blob.bin"
    p.write_bytes(bytes(range(16)))
    t = vops.read_file(str(p))
    np.testing.assert_array_equal(t.numpy(), np.arange(16, dtype="uint8"))
