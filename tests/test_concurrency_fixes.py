"""Regression tests for the concurrency fixes that fell out of the
lockcheck self-application (PR: interprocedural concurrency analysis).

Each test pins one fix:

* ServingMetrics.estimated_ttft_ms snapshots the rolling step-time
  deque before iterating (the engine thread appends concurrently).
* The profiler counter-provider registry is lock-protected, and
  counters() invokes providers OUTSIDE the lock (re-entrant
  registration must not deadlock).
* LLMEngine's hung-step tag hand-off (monitor thread -> dispatch
  thread) is synchronized by _hung_lock.
* PreemptionMonitor's signal handler only sets the Event; the store
  broadcast is deferred to the next requested() poll and happens
  exactly once.
"""
import signal
import threading
import time

import pytest


# ---------------------------------------------------------------------------
# ServingMetrics rolling deque
# ---------------------------------------------------------------------------
class _EngineStub:
    """Just enough engine for ServingMetrics to weakref and register."""


def test_ttft_estimate_survives_concurrent_step_records():
    """estimated_ttft_ms iterates the step-time window while the engine
    thread appends to it; without the tuple() snapshot a bounded deque
    that rotates mid-sum raises 'deque mutated during iteration'."""
    from paddle_tpu.serving.metrics import ServingMetrics

    eng = _EngineStub()
    m = ServingMetrics(eng)
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            m.record_step("decode", 1, 1, 8, dt_s=0.01 + (i % 7) * 1e-4)
            i += 1

    def reader():
        try:
            while not stop.is_set():
                m.estimated_ttft_ms(queue_depth=3)
        except RuntimeError as e:  # "deque mutated during iteration"
            errors.append(e)

    threads = [threading.Thread(target=writer, daemon=True),
               threading.Thread(target=reader, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors
    assert m.estimated_ttft_ms(queue_depth=0) is not None


# ---------------------------------------------------------------------------
# profiler counter-provider registry
# ---------------------------------------------------------------------------
def test_counter_registry_survives_concurrent_mutation():
    """register/unregister arrive from arbitrary threads (weakref
    finalizers); counters() must not see the dict change size under
    its iteration."""
    from paddle_tpu import profiler

    stop = threading.Event()
    errors = []

    def churn(tag):
        i = 0
        while not stop.is_set():
            name = f"test/churn-{tag}-{i % 16}"
            profiler.register_counter_provider(name, lambda: 1.0)
            profiler.unregister_counter_provider(name)
            i += 1

    def read():
        try:
            while not stop.is_set():
                profiler.counters()
        except RuntimeError as e:  # "dictionary changed size ..."
            errors.append(e)

    threads = [threading.Thread(target=churn, args=(k,), daemon=True)
               for k in range(2)]
    threads.append(threading.Thread(target=read, daemon=True))
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    for k in range(2):
        for i in range(16):
            profiler.unregister_counter_provider(f"test/churn-{k}-{i}")
    assert not errors


def test_counter_provider_may_register_reentrantly():
    """counters() calls providers OUTSIDE the registry lock, so a
    provider that itself registers a counter (e.g. lazy init on first
    read) must not deadlock."""
    from paddle_tpu import profiler

    def chained():
        return 7.0

    def provider():
        profiler.register_counter_provider("test/chained", chained)
        return 1.0

    profiler.register_counter_provider("test/reentrant", provider)
    try:
        done = []

        def run():
            done.append(profiler.counters())

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout=5)
        assert done, "counters() deadlocked on re-entrant registration"
        assert done[0]["test/reentrant"] == 1.0
        assert profiler.counters()["test/chained"] == 7.0
    finally:
        profiler.unregister_counter_provider("test/reentrant")
        profiler.unregister_counter_provider("test/chained")


def test_counter_dead_provider_dropped():
    from paddle_tpu import profiler

    profiler.register_counter_provider("test/dead", lambda: None)
    out = profiler.counters()
    assert "test/dead" not in out
    # dropped from the registry, not just skipped
    assert "test/dead" not in profiler.counters()


# ---------------------------------------------------------------------------
# engine hung-step tag hand-off
# ---------------------------------------------------------------------------
def test_hung_tag_write_synchronized_with_consumer():
    """_on_step_timeout (watchdog MONITOR thread) and the dispatch-side
    swap both take _hung_lock: while the consumer holds it, the monitor
    write must block rather than interleave."""
    from paddle_tpu.serving.engine import LLMEngine

    eng = object.__new__(LLMEngine)  # just the hand-off attrs
    eng._hung_lock = threading.Lock()
    eng._hung_tags = None

    wrote = threading.Event()

    def monitor():
        eng._on_step_timeout([("decode:b8", 0.1, 0.5)])
        wrote.set()

    with eng._hung_lock:  # consumer mid-swap
        t = threading.Thread(target=monitor, daemon=True)
        t.start()
        assert not wrote.wait(0.2), \
            "_on_step_timeout wrote _hung_tags without taking _hung_lock"
        assert eng._hung_tags is None
    t.join(timeout=5)
    assert wrote.is_set()
    assert eng._hung_tags == "decode:b8"


# ---------------------------------------------------------------------------
# PreemptionMonitor: flag-only handler, deferred single post
# ---------------------------------------------------------------------------
def _posts_counted(mon):
    """Wrap mon._post with a counter; returns the count list."""
    calls = []
    orig = mon._post

    def counted():
        calls.append(1)
        orig()

    mon._post = counted
    return calls


def test_signal_handler_defers_store_post(tmp_path):
    """SIGTERM sets the flag but posts NOTHING from handler context
    (store RPC at an arbitrary interruption point is async-signal
    unsafe); the next requested() poll broadcasts the notice exactly
    once, and peers then see it."""
    from paddle_tpu.distributed.store import FileStore
    from paddle_tpu.distributed.watchdog import PreemptionMonitor

    store = FileStore(str(tmp_path))
    a, b = PreemptionMonitor(), PreemptionMonitor()
    a._store = b._store = store
    b._read_baseline()
    posts = _posts_counted(a)
    a.install()
    try:
        signal.raise_signal(signal.SIGTERM)
        assert a._flag.is_set()
        assert posts == [], "handler posted to the store directly"
        b._last_poll = -1e9
        assert not b.requested()      # nothing broadcast yet
        assert a.requested()          # poll context: safe to post now
        assert len(posts) == 1
        assert a.requested()          # idempotent: one record total
        assert len(posts) == 1
        b._last_poll = -1e9
        assert b.requested()          # peer sees the deferred notice
    finally:
        a.uninstall()


def test_programmatic_request_posts_synchronously(tmp_path):
    """request() runs on an ordinary thread — it must post before
    returning (schedulers rely on peers seeing the notice immediately)
    and must not re-post on later polls."""
    from paddle_tpu.distributed.store import FileStore
    from paddle_tpu.distributed.watchdog import PreemptionMonitor

    store = FileStore(str(tmp_path))
    a, b = PreemptionMonitor(), PreemptionMonitor()
    a._store = b._store = store
    b._read_baseline()
    posts = _posts_counted(a)
    a.request()
    assert len(posts) == 1
    b._last_poll = -1e9
    assert b.requested()
    assert a.requested()
    assert len(posts) == 1


def test_remote_notice_is_not_echoed(tmp_path):
    """A rank that learns of preemption FROM the store must not post
    its own copy of the record back (echo storm across the gang)."""
    from paddle_tpu.distributed.store import FileStore
    from paddle_tpu.distributed.watchdog import PreemptionMonitor

    store = FileStore(str(tmp_path))
    a, b = PreemptionMonitor(), PreemptionMonitor()
    a._store = b._store = store
    b._read_baseline()
    posts = _posts_counted(b)
    a.request()
    b._last_poll = -1e9
    assert b.requested()
    assert b.requested()
    assert posts == []
