"""Real multi-process collective + eager-DP tests.

Reference pattern: a unittest driver spawns real subprocesses per rank
and the workers assert collective results / loss alignment
(test/legacy_test/test_dist_base.py:952, test/collective/
collective_allreduce_api.py). Here workers run on the CPU backend with
gloo cross-process collectives — the Gloo-CPU-ProcessGroup role.
"""
import os
import socket
import subprocess
import sys

import pytest

SCRIPTS = os.path.join(os.path.dirname(__file__), "mp_scripts")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_world(script, world=2, timeout=240, extra_env=None):
    port = _free_port()
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # children don't need 8 virtual devs
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_MASTER": f"127.0.0.1:{port}",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        })
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(SCRIPTS, script)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, \
            f"rank {rank} failed (rc={p.returncode}):\n{out[-4000:]}"
    return outs


def test_collectives_two_processes():
    outs = _spawn_world("collectives_worker.py", world=2)
    for rank, out in enumerate(outs):
        assert f"rank{rank} COLLECTIVES_OK" in out, out[-2000:]


def test_eager_dp_matches_serial():
    outs = _spawn_world("eager_dp_worker.py", world=2)
    for rank, out in enumerate(outs):
        assert f"rank{rank} EAGER_DP_OK" in out, out[-2000:]
