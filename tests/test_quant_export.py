"""Quantized inference export: PTQ -> convert -> jit.save produces an
int8-weight module that jit.load runs with matching outputs and ~4x
smaller weight payload.

Reference role: static/quantization/post_training_quantization.py
feeding the AnalysisPredictor; here the predictor is AOT StableHLO
(jit.save/load) and the int8 weights are export inputs with the dequant
compiled into the graph."""
import os
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import PTQ, QuantConfig
from paddle_tpu.jit import InputSpec


def _model():
    paddle.seed(0)
    return nn.Sequential(
        nn.Linear(64, 256), nn.ReLU(),
        nn.Linear(256, 256), nn.ReLU(),
        nn.Linear(256, 16))


def _calibrated_converted():
    model = _model()
    ptq = PTQ(QuantConfig())
    qmodel = ptq.quantize(model)
    rng = np.random.RandomState(0)
    for _ in range(4):  # calibration passes
        qmodel(paddle.to_tensor(rng.randn(8, 64).astype(np.float32)))
    return model, ptq.convert(qmodel)


def test_converted_layer_stores_int8_buffer():
    _, conv = _calibrated_converted()
    bufs = dict(conv.named_buffers())
    qw = [v for k, v in bufs.items() if k.endswith("qweight")]
    assert len(qw) == 3
    assert all(str(b.dtype).endswith("int8") for b in qw)
    # the f32 weight is gone from the state
    assert not any(k.endswith(".weight") and "qweight" not in k
                   for k in conv.state_dict())


def test_int8_export_roundtrip_and_size(tmp_path):
    model, conv = _calibrated_converted()
    X = paddle.to_tensor(
        np.random.RandomState(1).randn(4, 64).astype(np.float32))
    want = conv(X).numpy()

    qpath = str(tmp_path / "int8_model")
    paddle.jit.save(conv, qpath, input_spec=[InputSpec([4, 64],
                                                       "float32")])
    dpath = str(tmp_path / "dense_model")
    paddle.jit.save(model, dpath, input_spec=[InputSpec([4, 64],
                                                        "float32")])

    loaded = paddle.jit.load(qpath)
    got = loaded(X).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # and the quantized graph stays close to the dense model
    dense_out = model(X).numpy()
    err = np.abs(got - dense_out).max() / (np.abs(dense_out).max() + 1e-9)
    assert err < 0.1

    # weight payload shrinks ~4x (int8 vs f32 for every Linear weight)
    def weight_bytes(path):
        with open(path + ".pdmodel", "rb") as f:
            payload = pickle.load(f)
        return sum(a.nbytes for a in payload["params"]) + \
            sum(a.nbytes for a in payload["buffers"])

    qb, db = weight_bytes(qpath), weight_bytes(dpath)
    assert qb < db / 3.2, (qb, db)


def test_int8_saved_stablehlo_takes_int8_input(tmp_path):
    _, conv = _calibrated_converted()
    path = str(tmp_path / "m")
    paddle.jit.save(conv, path, input_spec=[InputSpec([4, 64],
                                                      "float32")])
    with open(path + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    assert any(a.dtype == np.int8 for a in payload["buffers"])
    assert "i8" in payload["stablehlo"]
