"""DevicePrefetcher: the device-resident async input pipeline.

Contracts under test: batches come out in order and device-COMMITTED
(a committed jax array takes the C++ fast dispatch path — no implicit
transfer at use time), the producer thread's exceptions surface in the
consumer, exhaustion terminates cleanly and the wrapper re-iterates,
per-dtype coalescing is value-preserving across mixed trees, and
mesh placements land batches directly in the requested NamedSharding.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io import DataLoader, Dataset, DevicePrefetcher, \
    prefetch_to_device


def _batches(n=6, batch=4):
    rng = np.random.default_rng(0)
    return [
        (np.full((batch, 3), i, np.float32),
         rng.normal(size=(batch, 2)).astype(np.float32),
         np.full((batch,), i, np.int64))
        for i in range(n)
    ]


def test_ordering_and_values():
    data = _batches()
    out = list(prefetch_to_device(data, depth=2))
    assert len(out) == len(data)
    for i, (x, z, y) in enumerate(out):
        assert isinstance(x, Tensor)
        assert float(np.asarray(x._data)[0, 0]) == i
        assert int(np.asarray(y._data)[0]) == i
        np.testing.assert_array_equal(np.asarray(z._data), data[i][1])


def test_yields_committed_device_arrays():
    for x, z, y in prefetch_to_device(_batches(3), depth=2):
        for t in (x, z, y):
            assert t._data.committed, \
                "prefetched array is uncommitted: use-time dispatch " \
                "would pay an implicit transfer"
    # int64 was canonicalized on HOST (the staging buffer is what lands
    # on device, byte-identical)
    assert str(y._data.dtype) == "int32"


def test_exhaustion_and_reiteration():
    pf = prefetch_to_device(_batches(4), depth=2)
    assert len(list(pf)) == 4
    assert len(list(pf)) == 4  # a list source supports a second epoch
    assert len(pf) == 4


def test_producer_exception_propagates():
    def gen():
        yield _batches(1)[0]
        raise RuntimeError("producer exploded")

    it = iter(DevicePrefetcher(gen(), depth=2))
    next(it)
    with pytest.raises(RuntimeError, match="producer exploded"):
        for _ in it:
            pass


def test_early_break_shuts_down_producer():
    pf = prefetch_to_device(_batches(50), depth=2)
    for i, b in enumerate(pf):
        if i == 2:
            break
    # a second full pass still works (fresh producer thread)
    assert len(list(pf)) == 50


def test_coalescing_matches_direct_transfer():
    """Mixed-dtype tree goes through per-dtype packed staging; values
    must match a plain per-leaf device_put exactly."""
    rng = np.random.default_rng(1)
    batch = {
        "a": rng.normal(size=(5, 7)).astype(np.float32),
        "b": rng.normal(size=(3,)).astype(np.float32),
        "nested": [rng.integers(0, 9, (2, 2)).astype(np.int32),
                   rng.integers(0, 9, (4,)).astype(np.int32)],
        "scalar": np.float32(2.5),
    }
    (out,) = list(prefetch_to_device([batch], depth=1))
    np.testing.assert_array_equal(np.asarray(out["a"]._data), batch["a"])
    np.testing.assert_array_equal(np.asarray(out["b"]._data), batch["b"])
    np.testing.assert_array_equal(np.asarray(out["nested"][0]._data),
                                  batch["nested"][0])
    np.testing.assert_array_equal(np.asarray(out["nested"][1]._data),
                                  batch["nested"][1])
    assert float(np.asarray(out["scalar"]._data)) == 2.5


def test_mesh_placements():
    from paddle_tpu.distributed.mesh import ProcessMesh, Shard

    mesh = ProcessMesh(np.arange(8), ["dp"])
    data = [(np.ones((8, 3), np.float32), np.ones((8,), np.int64))]
    (got,) = list(prefetch_to_device(data, depth=1, mesh=mesh,
                                     placements=[Shard(0)]))
    x, y = got
    assert str(x._data.sharding.spec) == "PartitionSpec('dp',)" or \
        tuple(x._data.sharding.spec) == ("dp", None)
    # both leaves batch-dim sharded over dp, and committed
    assert x._data.committed and y._data.committed
    shard_shapes = {tuple(s.data.shape) for s in x._data.addressable_shards}
    assert shard_shapes == {(1, 3)}


def test_mesh_partial_tail_batch_degrades_to_replicated():
    """drop_last=False leaves a final batch whose dim is not divisible
    by the mesh axis; it must land replicated (resharded by the compiled
    step) instead of crashing the producer at epoch end."""
    from paddle_tpu.distributed.mesh import ProcessMesh, Shard

    mesh = ProcessMesh(np.arange(8), ["dp"])
    data = [(np.ones((8, 3), np.float32), np.ones((8,), np.int64)),
            (np.ones((3, 3), np.float32), np.ones((3,), np.int64))]
    got = list(prefetch_to_device(data, depth=1, mesh=mesh,
                                  placements=[Shard(0)]))
    assert len(got) == 2
    full, tail = got
    assert tuple(full[0]._data.sharding.spec) == ("dp", None)
    assert all(d is None for d in tail[0]._data.sharding.spec)
    np.testing.assert_array_equal(np.asarray(tail[0]._data), data[1][0])


def test_non_array_leaves_pass_through():
    """String/object metadata in a batch (e.g. filenames from a custom
    collate) must pass through untouched, as on the plain loader path —
    not crash the producer or get coerced to device arrays."""
    data = [(np.ones((4, 2), np.float32), ["a.jpg", "b.jpg"], 7)]
    (got,) = list(prefetch_to_device(data, depth=1))
    x, names, n = got
    assert isinstance(x, Tensor) and x._data.committed
    assert names == ["a.jpg", "b.jpg"]
    assert n == 7 and isinstance(n, int)


def test_mesh_replicated_leaves_still_coalesce():
    """Shard(1) applies to the 2-D input but degrades to Replicate for
    the 1-D label — which must still flow through the packed replicated
    staging path, not a per-leaf transfer."""
    from paddle_tpu.distributed.mesh import ProcessMesh, Shard

    mesh = ProcessMesh(np.arange(8), ["mp"])
    rng = np.random.default_rng(2)
    data = [(rng.normal(size=(4, 8)).astype(np.float32),
             rng.normal(size=(4,)).astype(np.float32),
             rng.normal(size=(6,)).astype(np.float32))]
    (got,) = list(prefetch_to_device(data, depth=1, mesh=mesh,
                                     placements=[Shard(1)]))
    x, y, z = got
    assert tuple(x._data.sharding.spec) == (None, "mp")
    # replicated leaves: full value on every device
    for t, ref in ((y, data[0][1]), (z, data[0][2])):
        assert all(d is None for d in t._data.sharding.spec)  # replicated
        np.testing.assert_array_equal(np.asarray(t._data), ref)
        shard_shapes = {tuple(s.data.shape)
                        for s in t._data.addressable_shards}
        assert shard_shapes == {ref.shape}


def test_mesh_scalar_leaf_singleton_dtype():
    """A rank-0 side value whose dtype no other leaf shares must not
    crash the mesh path (the rank-1 staging sharding is invalid for
    rank-0; the leaf's own replicated sharding applies)."""
    from paddle_tpu.distributed.mesh import ProcessMesh, Shard

    mesh = ProcessMesh(np.arange(8), ["dp"])
    data = [{"x": np.ones((8, 3), np.float32),
             "scale": np.int16(7)}]  # lone member of its dtype group
    (got,) = list(prefetch_to_device(data, depth=1, mesh=mesh,
                                     placements=[Shard(0)]))
    assert int(np.asarray(got["scale"]._data)) == 7
    assert got["x"]._data.committed and got["scale"]._data.committed


class _NumpyDataset(Dataset):
    def __init__(self, n=12):
        self.n = n

    def __getitem__(self, i):
        return (np.full((3,), i, np.float32),
                np.asarray(i, np.int64))

    def __len__(self):
        return self.n


def test_dataloader_use_device_prefetch():
    dl = DataLoader(_NumpyDataset(), batch_size=4,
                    use_device_prefetch=True)
    seen = []
    for x, y in dl:
        assert isinstance(x, Tensor) and x._data.committed
        assert y._data.committed
        seen.extend(np.asarray(y._data).tolist())
    assert seen == list(range(12))


def test_dataloader_prefetch_custom_collate_keeps_bf16():
    """The numpy staging path must be dtype-preserving: Tensor.numpy()
    widens bf16 to f32, which would silently retrace the train step when
    use_device_prefetch is flipped on under a bf16 collate."""
    from paddle_tpu.io import default_collate_fn

    def collate(batch):
        x, y = default_collate_fn(batch)
        return x.astype("bfloat16"), y

    dl = DataLoader(_NumpyDataset(), batch_size=4, collate_fn=collate,
                    use_device_prefetch=True)
    x, y = next(iter(dl))
    assert "bfloat16" in str(x.dtype)
    assert x._data.committed


def test_dataloader_device_prefetch_tensor_dataset():
    """In-process datasets may yield device Tensors; the numpy staging
    path must fetch them to host rather than trip the worker-process
    guard."""
    from paddle_tpu.io import TensorDataset

    xs = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(8, 3))
    ys = paddle.to_tensor(np.arange(8, dtype=np.int64))
    dl = DataLoader(TensorDataset([xs, ys]), batch_size=4,
                    use_device_prefetch=True)
    got = [np.asarray(y._data) for _, y in dl]
    np.testing.assert_array_equal(np.concatenate(got), np.arange(8))


def test_dataloader_device_prefetch_with_workers():
    dl = DataLoader(_NumpyDataset(), batch_size=4, num_workers=2,
                    use_shared_memory=False, use_device_prefetch=True)
    seen = []
    for x, y in dl:
        assert x._data.committed
        seen.extend(np.asarray(y._data).tolist())
    assert seen == list(range(12))


def test_dataloader_prefetch_factor_queue_capacity():
    """Reference semantics: buffered-reader queue capacity is
    prefetch_factor * max(1, num_workers), not a flat floor of 2."""
    import queue as _q
    import threading
    from unittest import mock

    captured = {}
    real_queue = _q.Queue

    def spy(maxsize=0):
        captured.setdefault("maxsize", maxsize)
        return real_queue(maxsize=maxsize)

    dl = DataLoader(_NumpyDataset(), batch_size=4, prefetch_factor=5)
    with mock.patch("paddle_tpu.io.queue.Queue", side_effect=spy):
        list(dl)
    assert captured["maxsize"] == 5
    with pytest.raises(ValueError):
        DataLoader(_NumpyDataset(), batch_size=4, prefetch_factor=0)
