"""Peer-to-peer KV data plane pins (ISSUE 15).

Layers, cheapest first:

* ticket/listener units — HMAC signature at the door, CRC refusal,
  duplicate idempotence, bounded staging inbox, orphan-ticket GC;
* **loopback** fleet tests — the full ticketed path over real sockets:
  the router issues a signed ticket, the prefill-side replica pushes
  the KV frame straight to the decode-side listener, the commit verb
  imports it, and ZERO payload bytes cross the router. Every peer
  fault point degrades one rung down the ladder (peer-push →
  router-relay → recompute) with bit-identical output and every
  issued ticket accounted (``sum(ticket_outcomes) == tickets_issued``);
* satellite pins — expire-before-ship, import partial-failure cleanup
  (``serving.kv_scatter``), decorrelated RPC retry jitter, and the
  registry heartbeat-meta size guard.
"""
import socket
import threading
import time
import zlib

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.replica_registry import MemStore, ReplicaRegistry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    EngineConfig, LLMEngine, SamplingParams,
)
from paddle_tpu.serving.fleet import (
    FleetConfig, FleetRouter, InProcessReplica, PeerListener,
    ReplicaHandle, ReplicaLoad, ReplicaServicer, RpcClient, RpcTimeout,
    SubprocessReplica, peer_push, sign_ticket,
)
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _no_fault_leak():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    return model


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_num_seqs", 8)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("drain_grace_s", 0.0)
    return EngineConfig(**kw)


def _prompts(model, n, seed=7):
    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(0, model.config.vocab_size,
                                       size=3 + i % 4)))
            for i in range(n)]


def _reference(model, prompts, sp, ids):
    eng = LLMEngine(model, _ecfg())
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    while eng.has_unfinished():
        eng.step()
    return {rid: list(eng.get_request(rid).generated) for rid in ids}


def _drain_router(router, max_steps=400):
    outs = []
    for _ in range(max_steps):
        if not router.has_unfinished():
            return outs
        outs.extend(router.step())
    raise AssertionError("router failed to converge")


def _sp(sampled):
    if sampled:
        return SamplingParams(max_new_tokens=8, temperature=0.8,
                              top_p=0.9)
    return SamplingParams(max_new_tokens=8)


def _token_counts(outs):
    counts = {}
    for o in outs:
        if o.token is not None:
            counts[o.request_id] = counts.get(o.request_id, 0) + 1
    return counts


def _ticket(listener, tid="t1", deadline_ms=30_000, **over):
    t = {"ticket_id": tid, "src": "a", "dst": "b", "kind": "kv",
         "request_id": "r0", "deadline_ms": deadline_ms}
    t.update(over)
    t["sig"] = sign_ticket(t, listener._secret)
    return t


def _meta(payload):
    return {"crc32": zlib.crc32(payload) & 0xFFFFFFFF}


# ---------------------------------------------------------------------------
# ticket + listener units
# ---------------------------------------------------------------------------
class TestPeerListener:
    def test_push_take_roundtrip(self):
        lis = PeerListener()
        try:
            payload = b"kv-bytes" * 100
            t = _ticket(lis)
            receipt = peer_push(lis.endpoint, t, _meta(payload), payload)
            assert receipt["ok"] is True
            ticket, meta, got = lis.take("t1")
            assert got == payload
            assert ticket["ticket_id"] == "t1"
            assert meta["crc32"] == zlib.crc32(payload) & 0xFFFFFFFF
            assert lis.stats()["received"] == 1
            assert lis.pending_count == 0
        finally:
            lis.close()

    def test_signature_checked_at_the_door(self):
        # the listener's secret differs from the sender's: forged or
        # cross-fleet tickets are refused in the receipt, never staged
        lis = PeerListener(secret=b"other-fleet-secret")
        try:
            payload = b"x" * 64
            t = _ticket(lis)
            t["sig"] = "0" * 64            # forged
            receipt = peer_push(lis.endpoint, t, _meta(payload), payload)
            assert receipt["ok"] is False
            assert "signature" in receipt["error"]
            assert lis.take("t1") is None
            assert lis.stats()["refused"] == 1
        finally:
            lis.close()

    def test_tampered_ticket_field_fails_signature(self):
        lis = PeerListener()
        try:
            payload = b"x" * 64
            t = _ticket(lis)
            t["dst"] = "someone-else"      # signed fields are sealed
            receipt = peer_push(lis.endpoint, t, _meta(payload), payload)
            assert receipt["ok"] is False
        finally:
            lis.close()

    def test_crc_mismatch_refused(self):
        lis = PeerListener()
        try:
            payload = b"y" * 64
            meta = _meta(payload)
            corrupt = b"\x00" + payload[1:]
            receipt = peer_push(lis.endpoint, _ticket(lis), meta, corrupt)
            assert receipt["ok"] is False
            assert "checksum" in receipt["error"]
            assert lis.take("t1") is None
        finally:
            lis.close()

    def test_duplicate_delivery_idempotent(self):
        # ambiguous peer_send timeouts make duplicates NORMAL: the
        # second delivery acks ok without re-staging, and a duplicate
        # AFTER the commit stays a no-op too
        lis = PeerListener()
        try:
            payload = b"z" * 32
            t = _ticket(lis)
            assert peer_push(lis.endpoint, t, _meta(payload),
                             payload)["ok"]
            dup = peer_push(lis.endpoint, t, _meta(payload), payload)
            assert dup["ok"] and dup.get("duplicate")
            assert lis.pending_count == 1      # staged once
            assert lis.take("t1") is not None
            late = peer_push(lis.endpoint, t, _meta(payload), payload)
            assert late["ok"] and late.get("duplicate")
            assert lis.take("t1") is None      # committed: gone for good
            assert lis.stats()["duplicates"] == 2
        finally:
            lis.close()

    def test_inbox_capacity_refusal(self):
        lis = PeerListener(max_entries=1)
        try:
            p = b"a" * 16
            assert peer_push(lis.endpoint, _ticket(lis, "t1"), _meta(p),
                             p)["ok"]
            full = peer_push(lis.endpoint, _ticket(lis, "t2"), _meta(p), p)
            assert full["ok"] is False
            assert "full" in full["error"]
            assert lis.take("t1") is not None  # original undisturbed
        finally:
            lis.close()

    def test_orphan_ticket_gc(self):
        # a staged frame whose commit never arrives (router died
        # mid-transfer) is collected at its deadline and the late
        # commit finds nothing
        lis = PeerListener()
        try:
            p = b"orphan" * 10
            t = _ticket(lis, deadline_ms=20)
            assert peer_push(lis.endpoint, t, _meta(p), p)["ok"]
            assert lis.pending_count == 1
            time.sleep(0.05)
            assert lis.gc() == 1
            assert lis.take("t1") is None
            st = lis.stats()
            assert st["orphans_gcd"] == 1
            assert st["staged_bytes"] == 0
        finally:
            lis.close()

    def test_peer_fault_points(self):
        lis = PeerListener()
        try:
            p = b"f" * 32
            with faults.injected("fleet.peer_connect_fail:flag*1"):
                with pytest.raises(OSError):
                    peer_push(lis.endpoint, _ticket(lis), _meta(p), p)
            with faults.injected("fleet.peer_send_drop:flag*1"):
                with pytest.raises(OSError):
                    peer_push(lis.endpoint, _ticket(lis), _meta(p), p)
            with faults.injected("fleet.peer_frame_corrupt:flag*1"):
                r = peer_push(lis.endpoint, _ticket(lis), _meta(p), p)
                assert r["ok"] is False    # CRC refusal at the door
            with faults.injected("fleet.peer_stall:sleep:0.1"):
                with pytest.raises(OSError):   # stall ate the deadline
                    peer_push(lis.endpoint, _ticket(lis), _meta(p), p,
                              timeout_s=0.05)
        finally:
            lis.close()


# ---------------------------------------------------------------------------
# loopback fleet: the ticketed peer path end to end
# ---------------------------------------------------------------------------
class Loopback:
    def __init__(self, inner, client_kw=None, peer=True):
        self.inner = inner
        a, b = socket.socketpair()
        self._server_sock = b
        threading.Thread(target=ReplicaServicer(inner).serve, args=(b,),
                         daemon=True).start()
        self.client = RpcClient(a, name=inner.replica_id,
                                **(client_kw or {}))
        self.handle = SubprocessReplica(inner.replica_id, self.client)
        self.handle.hard_kill = self.sever
        if peer:
            # what the supervisor learns from the worker's first ping
            self.handle.peer_endpoint = inner.start_peer()

    def sever(self):
        try:
            self._server_sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._server_sock.close()


def _peer_pair(model, prefix="P", **cfg_kw):
    lb_p = Loopback(InProcessReplica(model, _ecfg(),
                                     replica_id=f"{prefix}pre"))
    lb_d = Loopback(InProcessReplica(model, _ecfg(),
                                     replica_id=f"{prefix}dec"))
    router = FleetRouter(
        [lb_p.handle, lb_d.handle],
        FleetConfig(roles={f"{prefix}pre": "prefill",
                           f"{prefix}dec": "decode"}, **cfg_kw))
    return lb_p, lb_d, router


def _assert_ticket_accounting(router):
    # the acceptance invariant: every issued ticket ends in exactly one
    # counted outcome — none lost, none double-counted
    assert router.num_tickets_issued == \
        sum(router.ticket_outcomes.values())


class TestPeerShipE2E:
    @pytest.mark.parametrize("sampled", [False, True],
                             ids=["greedy", "sampled"])
    def test_peer_ship_parity_zero_router_bytes(self, tiny_model,
                                                sampled):
        # THE tentpole pin: prefill→decode KV moves worker↔worker over
        # the ticketed peer channel; token streams stay bit-identical
        # to an uninterrupted single engine and the router carries ZERO
        # payload bytes (relay_bytes == 0) in steady state.
        sp = _sp(sampled)
        n = 5
        prompts = _prompts(tiny_model, n)
        ids = [f"p{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _peer_pair(tiny_model,
                                        "S" if sampled else "G")
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert _token_counts(outs) == {r: len(ref[r]) for r in ids}
        assert router.num_peer_ship_requests == n
        assert router.num_peer_ship_bytes > 0
        assert router.num_peer_ship_blocks > 0
        # aggregate ship counters still count the peer path
        assert router.num_kv_ship_requests == n
        assert router.num_tokens_recomputed == 0
        assert router.num_recompute_fallbacks == 0
        assert router.num_handoffs == 0
        # zero KV payload bytes through the router
        assert router.num_relay_bytes == 0
        assert router.num_relay_fallbacks == 0
        assert router.num_tickets_issued >= n
        assert router.ticket_outcomes["peer"] >= n
        _assert_ticket_accounting(router)
        assert lb_d.inner.engine.num_continuation_admits == n
        # no destination is left holding uncommitted staged payloads
        assert lb_d.inner.peer_listener.pending_count == 0
        assert lb_p.inner._parked == {}    # sources released their stash
        snap = router.snapshot()
        assert snap["fleet_peer_ship_requests"] == n
        assert snap["fleet_relay_bytes"] == 0
        assert snap["fleet_ticket_outcomes"]["peer"] >= n

    @pytest.mark.parametrize("fault", [
        "fleet.peer_connect_fail:flag",
        "fleet.peer_send_drop:flag",
        "fleet.peer_frame_corrupt:flag",
    ], ids=["connect_fail", "send_drop", "frame_corrupt"])
    def test_peer_fault_degrades_to_relay(self, tiny_model, fault):
        # rung 2: a dead/corrupt peer push falls back to the
        # router-relay path — same bytes, same tokens, one counted
        # relay fallback per ticket, ZERO recomputes
        sp = _sp(True)
        n = 4
        prompts = _prompts(tiny_model, n)
        ids = [f"r{fault[11]}{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _peer_pair(tiny_model, fault[11:13].upper())
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install(f"{fault}*{n}")
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert _token_counts(outs) == {r: len(ref[r]) for r in ids}
        assert router.num_peer_ship_requests == 0
        assert router.num_kv_ship_requests == n     # relay landed them
        assert router.num_relay_fallbacks == n
        assert router.num_relay_bytes > 0
        assert router.num_recompute_fallbacks == 0
        assert router.ticket_outcomes["relay"] == n
        _assert_ticket_accounting(router)
        assert lb_d.inner.engine.num_continuation_admits == n

    def test_peer_stall_degrades_to_relay(self, tiny_model):
        # rung deadline: a stalled push that outlives the ticket's
        # deadline budget fails the rung; the ladder relays
        sp = _sp(False)
        n = 2
        prompts = _prompts(tiny_model, n)
        ids = [f"st{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _peer_pair(tiny_model, "T",
                                        peer_deadline_s=0.05)
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install(f"fleet.peer_stall:sleep:0.2*{n}")
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert router.num_peer_ship_requests == 0
        assert router.num_relay_fallbacks == n
        assert router.num_recompute_fallbacks == 0
        _assert_ticket_accounting(router)

    def test_peer_and_relay_faults_degrade_to_recompute(self,
                                                        tiny_model):
        # rung 3: peer push dies AND the relay export is dropped — the
        # ladder bottoms out at recompute, still bit-identical
        sp = _sp(True)
        n = 3
        prompts = _prompts(tiny_model, n)
        ids = [f"rc{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _peer_pair(tiny_model, "R")
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install(f"fleet.peer_connect_fail:flag*{n};"
                       f"fleet.kv_ship_drop:flag*{n}")
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert _token_counts(outs) == {r: len(ref[r]) for r in ids}
        assert router.num_peer_ship_requests == 0
        assert router.num_kv_ship_requests == 0
        assert router.num_recompute_fallbacks == n
        assert router.ticket_outcomes["recompute"] == n
        assert router.num_tokens_recomputed > 0
        _assert_ticket_accounting(router)
        assert lb_d.inner.engine.num_continuation_admits == 0

    def test_src_sigkill_mid_transfer_recomputes(self, tiny_model):
        # the SOURCE dies after parking but before the ticketed push:
        # both data rungs are gone and the request resumes by recompute
        sp = _sp(True)
        n = 3
        prompts = _prompts(tiny_model, n)
        ids = [f"sk{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _peer_pair(tiny_model, "K")
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        outs = []
        for _ in range(200):
            outs.extend(router.step())
            if any(router._requests[r].ship_src is not None
                   for r in ids):
                break
        else:
            raise AssertionError("no request ever parked")
        lb_p.sever()                      # SIGKILL as the client sees it
        outs += _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert _token_counts(outs) == {r: len(ref[r]) for r in ids}
        assert not lb_p.handle.alive
        assert router.num_recompute_fallbacks >= 1
        _assert_ticket_accounting(router)

    def test_dst_sigkill_mid_run_recovers(self, tiny_model):
        # the DESTINATION dies mid-decode: its continuations re-enqueue
        # from router bookkeeping and land on the surviving decode
        # replica — bit-identical, every ticket still accounted
        sp = _sp(True)
        n = 4
        prompts = _prompts(tiny_model, n)
        ids = [f"dk{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                         replica_id="Dpre"))
        lb_d0 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                          replica_id="Ddec0"))
        lb_d1 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                          replica_id="Ddec1"))
        router = FleetRouter(
            [lb_p.handle, lb_d0.handle, lb_d1.handle],
            FleetConfig(roles={"Dpre": "prefill", "Ddec0": "decode",
                               "Ddec1": "decode"}))
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install("fleet.worker_kill:flag:Ddec0@4*1")
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert _token_counts(outs) == {r: len(ref[r]) for r in ids}
        assert not lb_d0.handle.alive
        assert router.num_replicas_dead == 1
        _assert_ticket_accounting(router)
        for lb in (lb_p, lb_d1):
            assert lb.inner.peer_listener.pending_count == 0
            bm = lb.inner.engine.block_manager
            assert bm.num_free_blocks == bm.num_blocks

    def test_expire_before_ship_skips_transfer(self, tiny_model):
        # satellite: a request whose deadline passed while its KV
        # transfer was pending is finalized "expired" — the snapshot is
        # abandoned (source stash released), never shipped
        sp = SamplingParams(max_new_tokens=8, deadline_ms=30_000)
        lb_p, lb_d, router = _peer_pair(tiny_model, "E")
        router.add_request("exp0", _prompts(tiny_model, 1)[0],
                           sampling=sp)
        for _ in range(200):
            router.step()
            fr = router._requests["exp0"]
            if fr.ship_src is not None or fr.finished:
                break
        assert fr.ship_src is not None, "request never parked"
        fr.deadline_abs = time.monotonic() - 1.0   # budget exhausted
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert final["exp0"].finish_reason == "expired"
        assert router.num_ship_skipped_expired == 1
        assert router.num_tickets_issued == 0      # never even ticketed
        assert lb_p.inner._parked == {}            # stash released
        snap = router.snapshot()
        assert snap["fleet_ship_skipped_expired"] == 1

    def test_peer_disabled_pins_fleet_to_relay(self, tiny_model):
        # the bench-comparison knob: peer_data_plane=False never issues
        # tickets and all payloads relay through the router as before
        sp = _sp(False)
        n = 3
        prompts = _prompts(tiny_model, n)
        ids = [f"nd{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _peer_pair(tiny_model, "N",
                                        peer_data_plane=False)
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert router.num_tickets_issued == 0
        assert router.num_peer_ship_requests == 0
        assert router.num_kv_ship_requests == n
        assert router.num_relay_bytes > 0
        assert router.num_recompute_fallbacks == 0


# ---------------------------------------------------------------------------
# endpoint discovery through the registry
# ---------------------------------------------------------------------------
class _StubReplica(ReplicaHandle):
    def __init__(self):
        self.replica_id = "stub"
        self.alive = True
        self.retiring = False
        self.self_heartbeat = True
        self.role = None

    def admission_verdict(self, prompt_tokens):
        return None

    def estimated_ttft_ms(self, prompt_tokens):
        return 1.0

    def load(self):
        return ReplicaLoad()

    @property
    def is_draining(self):
        return False

    @property
    def drained(self):
        return False

    def has_unfinished(self):
        return False

    def add_request(self, request_id, prompt_ids, sampling, *,
                    rng_state=None):
        pass

    def abort_request(self, request_id):
        return False

    def release_request(self, request_id):
        pass

    def rng_state(self, request_id):
        return None

    def step(self):
        return []

    def start_drain(self, reason="manual"):
        return []


class TestEndpointDiscovery:
    def test_peer_endpoint_learned_from_heartbeat_meta(self):
        # restart story: a rebuilt router attaches handles without
        # endpoints; the worker's self-heartbeat meta carries "peer"
        # and the next health sweep re-learns where to ticket pushes
        reg = ReplicaRegistry(MemStore(), ttl_s=30.0)
        h = _StubReplica()
        h.replica_id = "w0-g2"
        router = FleetRouter([h], registry=reg)
        reg.heartbeat("w0-g2", meta={"role": "decode",
                                     "peer": "127.0.0.1:45999"})
        router.step()
        assert h.peer_endpoint == "127.0.0.1:45999"
        assert h.role == "decode"
        # sticky: later beats without meta must not erase it
        reg.heartbeat("w0-g2", meta={"pid": 1})
        router.step()
        assert h.peer_endpoint == "127.0.0.1:45999"


# ---------------------------------------------------------------------------
# satellite: import partial-failure cleanup (serving.kv_scatter)
# ---------------------------------------------------------------------------
class TestImportPartialFailure:
    def _warm_source(self, model):
        eng = InProcessReplica(model, _ecfg(), replica_id="ws").engine
        prompt = _prompts(model, 1)[0] * 3     # multi-block prompt
        eng.add_request("src", prompt, sampling=SamplingParams(
            max_new_tokens=4))
        eng.step()
        return eng, prompt

    def test_import_kv_scatter_fault_frees_blocks(self, tiny_model):
        eng_a, prompt = self._warm_source(tiny_model)
        eng_b = InProcessReplica(tiny_model, _ecfg(),
                                 replica_id="wb").engine
        meta, payload = eng_a.export_kv("src")
        sp = SamplingParams(max_new_tokens=4)
        toks = list(eng_a.get_request("src").tokens)
        with faults.injected("serving.kv_scatter:raise*1"):
            with pytest.raises(ValueError, match="blocks freed"):
                eng_b.import_kv("dst", toks, sampling=sp, meta=meta,
                                payload=payload)
        bm = eng_b.block_manager
        assert bm.num_free_blocks == bm.num_blocks   # nothing leaked
        bm.check_invariants()
        assert "dst" not in eng_b._requests          # nothing admitted
        # the same import succeeds once the fault is gone — the failed
        # attempt left no residue behind
        eng_b.import_kv("dst", toks, sampling=sp, meta=meta,
                        payload=payload)
        assert eng_b.get_request("dst").num_cached > 0

    def test_import_prefix_scatter_fault_frees_blocks(self, tiny_model):
        eng_a, _ = self._warm_source(tiny_model)
        eng_b = InProcessReplica(tiny_model, _ecfg(),
                                 replica_id="pb").engine
        digest = eng_a.prefix_digest()
        assert digest["h"], "source trie never committed a prefix"
        ch = next(iter(digest["h"]))
        meta, payload = eng_a.export_prefix(ch)
        with faults.injected("serving.kv_scatter:raise*1"):
            with pytest.raises(ValueError, match="blocks freed"):
                eng_b.import_prefix(meta=meta, payload=payload)
        bm = eng_b.block_manager
        assert bm.num_free_blocks == bm.num_blocks
        bm.check_invariants()
        # clean retry commits the prefix
        assert eng_b.import_prefix(meta=meta, payload=payload) > 0

    def test_router_degrades_when_dst_import_always_fails(self,
                                                          tiny_model):
        # end to end: every import (peer commit AND relay) fails at
        # scatter — the ladder bottoms out at recompute, bit-identical,
        # and the destination pool ends exactly full
        sp = _sp(True)
        n = 3
        prompts = _prompts(tiny_model, n)
        ids = [f"sc{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _peer_pair(tiny_model, "C")
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install("serving.kv_scatter:raise")
        outs = _drain_router(router)
        faults.clear()
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert router.num_recompute_fallbacks == n
        assert router.ticket_outcomes["recompute"] == n
        _assert_ticket_accounting(router)
        bm = lb_d.inner.engine.block_manager
        assert bm.num_free_blocks == bm.num_blocks
        bm.check_invariants()


# ---------------------------------------------------------------------------
# satellite: decorrelated retry jitter
# ---------------------------------------------------------------------------
class TestRetryJitter:
    def _backoffs(self, seed):
        a, _b = socket.socketpair()
        cl = RpcClient(a, retries=5, backoff_base_s=0.001,
                       backoff_max_s=0.004, jitter_seed=seed)
        # every attempt times out instantly at the injected drop, so
        # the full retry schedule runs deterministically and fast
        with faults.injected("fleet.rpc_drop:flag"):
            with pytest.raises(RpcTimeout):
                cl.call("ping", {}, deadline_s=1.0)
        out = list(cl.stats["backoffs"])
        cl.close()
        _b.close()
        return out

    def test_seeded_schedule_is_deterministic(self):
        assert self._backoffs(42) == self._backoffs(42)

    def test_schedules_decorrelate_across_seeds(self):
        a, b = self._backoffs(1), self._backoffs(2)
        assert len(a) == len(b) == 5
        # first sleep is exactly the base for every client (thundering
        # herd protection starts at retry 2); later sleeps diverge
        assert a[0] == b[0] == 0.001
        assert a[1:] != b[1:]

    def test_jitter_respects_bounds(self):
        for d in self._backoffs(7):
            assert 0.001 <= d <= 0.004


# ---------------------------------------------------------------------------
# satellite: heartbeat meta size guard
# ---------------------------------------------------------------------------
class TestMetaSizeGuard:
    def test_digest_dropped_first_essentials_never(self):
        reg = ReplicaRegistry(MemStore(), ttl_s=30.0, meta_cap_bytes=120)
        big = {f"h{i}": 16 for i in range(50)}
        reg.heartbeat("r0", meta={"role": "decode",
                                  "peer": "127.0.0.1:40001", "pid": 7,
                                  "prefix": {"bs": 4, "n": 50, "h": big},
                                  "zz_extra": "x" * 200})
        meta = reg.record("r0")["meta"]
        assert meta["role"] == "decode"
        assert meta["peer"] == "127.0.0.1:40001"
        assert meta["pid"] == 7
        assert "prefix" not in meta        # first against the wall
        assert "zz_extra" not in meta
        assert reg.num_meta_keys_dropped == 2

    def test_under_cap_meta_untouched(self):
        reg = ReplicaRegistry(MemStore(), ttl_s=30.0)
        meta = {"role": "prefill", "peer": "127.0.0.1:1", "pid": 1,
                "prefix": {"bs": 4, "n": 1, "h": {"ab": 4}}}
        reg.heartbeat("r1", meta=dict(meta))
        assert reg.record("r1")["meta"] == meta
        assert reg.num_meta_keys_dropped == 0

    def test_drop_stops_once_under_cap(self):
        # "prefix" alone brings the record under the cap: the other
        # non-essential key survives
        reg = ReplicaRegistry(MemStore(), ttl_s=30.0, meta_cap_bytes=120)
        reg.heartbeat("r2", meta={"role": "decode",
                                  "prefix": {"h": {f"h{i}": 8
                                                   for i in range(40)}},
                                  "note": "small"})
        meta = reg.record("r2")["meta"]
        assert "prefix" not in meta
        assert meta["note"] == "small"
        assert reg.num_meta_keys_dropped == 1

    def test_all_essential_oversize_sent_as_is(self):
        reg = ReplicaRegistry(MemStore(), ttl_s=30.0, meta_cap_bytes=16)
        meta = {"role": "decode", "peer": "127.0.0.1:40001", "pid": 99}
        reg.heartbeat("r3", meta=dict(meta))
        # better a fat beat than a fleet that forgets its topology
        assert reg.record("r3")["meta"] == meta
        assert reg.num_meta_keys_dropped == 0
