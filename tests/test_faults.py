"""Fault-tolerance layer, fast (in-process) tier-1 tests.

Covers: atomic save_state_dict staging, manifest validation, the
CheckpointManager commit/retention/retry protocol, the TrainStep
skip_nonfinite guard's bit-identity pins, the GradScaler divergence
guard, preemption signalling, and the DataLoader killed-worker path.
End-to-end subprocess kill/resume proofs live in test_fault_e2e.py
(slow-marked); the injectors here come from paddle_tpu.testing.faults.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(os.path.dirname(__file__), "mp_scripts")


# ---------------------------------------------------------------------------
# fault-injector harness itself
# ---------------------------------------------------------------------------
def test_fault_spec_parsing():
    f = faults.Fault.parse("ckpt.data_written:sleep:2.5@1*3")
    assert (f.point, f.action, f.arg, f.skip, f.times) == \
        ("ckpt.data_written", "sleep", "2.5", 1, 3)
    with pytest.raises(ValueError):
        faults.Fault.parse("nonsense")


def test_fault_point_registry_total_and_covered():
    """The FAULT_POINTS registry contract: every SERVING_*/FLEET_*/
    CKPT_* constant is registered, every registered point is exercised
    somewhere under tests/ or scripts/ (no dead chaos surface), and —
    via the fault-point-literal lint rule that test_lint_clean gates —
    every production fire()/check() site references the registry."""
    consts = {v for k, v in vars(faults).items()
              if isinstance(v, str)
              and k.split("_")[0] in ("SERVING", "FLEET", "CKPT")
              and "_" in k}
    assert consts == set(faults.FAULT_POINTS)
    assert len(faults.FAULT_POINTS) >= 26
    from paddle_tpu.analysis.dataflow import reference_text
    corpus = reference_text()
    missing = sorted(p for p in faults.FAULT_POINTS if p not in corpus)
    assert missing == [], \
        f"registered fault points never exercised: {missing}"


def test_fault_skip_and_times():
    with faults.injected("p:raise@1*1") as inj:
        faults.fire("p")  # skipped
        with pytest.raises(OSError):
            faults.fire("p")
        faults.fire("p")  # times exhausted
        assert inj.faults("p")[0].hits == 3
        assert inj.faults("p")[0].fired == 1
    faults.fire("p")  # injector restored: no-op


# ---------------------------------------------------------------------------
# satellite: atomic save_state_dict
# ---------------------------------------------------------------------------
def test_save_crash_midwrite_keeps_old_checkpoint(tmp_path):
    """A save that dies mid-write must leave the previous checkpoint at
    ``path`` fully readable (staging + rename, never in-place)."""
    p = str(tmp_path / "ck")
    ckpt.save_state_dict({"x": paddle.ones([4])}, p)
    with faults.injected("ckpt.data_written:raise"):
        with pytest.raises(OSError):
            ckpt.save_state_dict({"x": paddle.zeros([4])}, p)
    y = paddle.zeros([4])
    ckpt.load_state_dict({"x": y}, p)
    np.testing.assert_array_equal(y.numpy(), np.ones(4, np.float32))
    # and a later save recovers despite the leftover staging dir
    ckpt.save_state_dict({"x": paddle.full([4], 7.0)}, p)
    ckpt.load_state_dict({"x": y}, p)
    np.testing.assert_array_equal(y.numpy(), np.full(4, 7.0, np.float32))


def test_save_never_tears_destination(tmp_path):
    """Even a crash at the commit point leaves either the old or the new
    checkpoint at ``path`` — never a half-written mix."""
    p = str(tmp_path / "ck")
    ckpt.save_state_dict({"x": paddle.ones([4])}, p)
    files_before = sorted(os.listdir(p))
    with faults.injected("ckpt.before_commit:raise"):
        with pytest.raises(OSError):
            ckpt.save_state_dict({"x": paddle.zeros([4])}, p)
    assert sorted(os.listdir(p)) == files_before


# ---------------------------------------------------------------------------
# satellite: manifest validation
# ---------------------------------------------------------------------------
def test_missing_chunk_file_named_error(tmp_path):
    p = str(tmp_path / "ck")
    ckpt.save_state_dict({"w": paddle.ones([2, 2])}, p)
    os.remove(os.path.join(p, "data_0.npz"))
    with pytest.raises(ValueError, match="'w'.*missing"):
        ckpt.load_state_dict({"w": paddle.zeros([2, 2])}, p)


def test_manifest_coverage_hole_named_error(tmp_path):
    p = str(tmp_path / "ck")
    ckpt.save_state_dict({"w": paddle.ones([4, 4])}, p)
    mpath = os.path.join(p, "metadata.json")
    meta = json.load(open(mpath))
    # shrink the chunk so it no longer tiles the global shape
    meta["tensors"]["w"]["chunks"][0]["local_shape"] = [2, 4]
    json.dump(meta, open(mpath, "w"))
    with pytest.raises(ValueError, match="'w'.*coverage hole"):
        ckpt.load_state_dict({"w": paddle.zeros([4, 4])}, p)


def test_torn_npz_key_named_error(tmp_path):
    p = str(tmp_path / "ck")
    ckpt.save_state_dict({"w": paddle.ones([2, 2])}, p)
    # replace the data file with one missing the tensor's key
    np.savez(os.path.join(p, "data_0.npz"), other=np.zeros(1))
    with pytest.raises(ValueError, match="'w'"):
        ckpt.load_state_dict({"w": paddle.zeros([2, 2])}, p)


# ---------------------------------------------------------------------------
# tentpole: CheckpointManager
# ---------------------------------------------------------------------------
def _mgr_state(value=1.0):
    return {"x": paddle.full([4], value)}


def test_manager_commit_latest_restore(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=5)
    assert mgr.latest_step() is None
    assert mgr.restore_or_initialize(_mgr_state()) is None
    mgr.save(1, _mgr_state(1.0), block=True)
    mgr.save(2, _mgr_state(2.0), block=True)
    assert mgr.all_steps() == [1, 2]
    marker = json.load(open(tmp_path / "step_2" / "COMMITTED"))
    assert marker["step"] == 2
    st = _mgr_state(0.0)
    assert mgr.restore_or_initialize(st) == 2
    np.testing.assert_array_equal(st["x"].numpy(),
                                  np.full(4, 2.0, np.float32))


def test_committed_fault_fires_after_marker_durable(tmp_path):
    """``ckpt.committed`` fires strictly AFTER the COMMITTED marker is
    durable: a crash injected there is survivable — the retry finds the
    already-committed copy of the same step and preserves it whole."""
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
    with faults.injected("ckpt.committed:raise*1") as inj:
        mgr.save(1, _mgr_state(3.0), block=True)
        assert inj.faults("ckpt.committed")[0].fired == 1
    assert mgr.latest_step() == 1
    st = _mgr_state(0.0)
    assert mgr.restore_or_initialize(st) == 1
    np.testing.assert_array_equal(st["x"].numpy(),
                                  np.full(4, 3.0, np.float32))


def test_manager_async_save_and_wait(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=3)
    assert mgr.save(1, _mgr_state())
    mgr.wait()
    assert mgr.latest_step() == 1


def test_manager_save_interval(tmp_path):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=3)
    assert not mgr.save(1, _mgr_state())
    assert not mgr.save(2, _mgr_state())
    assert mgr.save(3, _mgr_state(), block=True)
    assert mgr.save(5, _mgr_state(), block=True, force=True)
    assert mgr.all_steps() == [3, 5]


def test_manager_retention_gc(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
    for s in (1, 2, 3):
        mgr.save(s, _mgr_state(float(s)), block=True)
    assert mgr.all_steps() == [2, 3]
    assert sorted(os.listdir(tmp_path)) == ["step_2", "step_3"]


def test_manager_skips_and_gcs_uncommitted(tmp_path):
    """A torn step dir (no COMMITTED marker — a SIGKILL mid-commit) is
    never restored from and is garbage-collected by the next save."""
    mgr = CheckpointManager(str(tmp_path), keep_last_n=3)
    mgr.save(5, _mgr_state(5.0), block=True)
    torn = tmp_path / "step_7"
    torn.mkdir()
    (torn / "data_0.npz").write_bytes(b"half a npz")
    stale = tmp_path / "step_9.tmp"
    stale.mkdir()
    assert mgr.latest_step() == 5
    st = _mgr_state(0.0)
    assert mgr.restore_or_initialize(st) == 5
    np.testing.assert_array_equal(st["x"].numpy(),
                                  np.full(4, 5.0, np.float32))
    mgr.save(8, _mgr_state(8.0), block=True)
    assert sorted(os.listdir(tmp_path)) == ["step_5", "step_8"]


def test_manager_resave_same_step_preserves_committed(tmp_path):
    """Re-saving an already-committed step (the forced preemption save
    after an async one) must never delete the committed copy before the
    rewrite has fully landed."""
    mgr = CheckpointManager(str(tmp_path), max_retries=0)
    mgr.save(1, _mgr_state(1.0), block=True)
    mgr.save(1, _mgr_state(1.5), block=True, force=True)  # clean re-save
    st = _mgr_state(0.0)
    assert mgr.restore(st, step=1) == 1
    np.testing.assert_array_equal(st["x"].numpy(),
                                  np.full(4, 1.5, np.float32))
    # crash between the rewrite and its marker: the old committed bytes
    # survive on disk (parked at step_1.old), nothing is half-written
    with faults.injected("ckpt.before_marker:raise"):
        with pytest.raises(OSError):
            mgr.save(1, _mgr_state(2.0), block=True, force=True)
    assert os.path.exists(tmp_path / "step_1.old" / "COMMITTED")
    # a restarted process (fresh manager) recovers the parked copy
    mgr2 = CheckpointManager(str(tmp_path), max_retries=0)
    assert mgr2.latest_step() == 1
    st = _mgr_state(0.0)
    assert mgr2.restore(st, step=1) == 1
    np.testing.assert_array_equal(st["x"].numpy(),
                                  np.full(4, 1.5, np.float32))
    mgr2.save(2, _mgr_state(2.0), block=True)
    assert sorted(os.listdir(tmp_path)) == ["step_1", "step_2"]


def test_save_recovers_checkpoint_parked_at_old(tmp_path):
    """save_state_dict crash window between its two commit renames: the
    complete checkpoint at <path>.old is recovered, not deleted."""
    p = str(tmp_path / "ck")
    ckpt.save_state_dict({"x": paddle.ones([4])}, p)
    os.rename(p, p + ".old")  # the state a crash at that instant leaves
    ckpt.save_state_dict({"x": paddle.full([4], 2.0)}, p)
    y = paddle.zeros([4])
    ckpt.load_state_dict({"x": y}, p)
    np.testing.assert_array_equal(y.numpy(), np.full(4, 2.0, np.float32))
    assert not os.path.exists(p + ".old")


def test_manager_retry_never_deletes_parked_committed(tmp_path):
    """A FAILED re-save attempt leaves a torn ``step_N`` and the
    committed copy parked at ``step_N.old``; the retry (and any later
    failure) must drop only the torn dir — never the parked bytes."""
    mgr = CheckpointManager(str(tmp_path), max_retries=1,
                            backoff_base=0.01)
    mgr.save(1, _mgr_state(1.5), block=True)
    # every attempt dies between the rewrite and its marker
    with faults.injected("ckpt.before_marker:raise"):
        with pytest.raises(OSError):
            mgr.save(1, _mgr_state(9.0), block=True, force=True)
    assert os.path.exists(tmp_path / "step_1.old" / "COMMITTED")
    mgr2 = CheckpointManager(str(tmp_path))  # recovers the parked copy
    st = _mgr_state(0.0)
    assert mgr2.restore_or_initialize(st) == 1
    np.testing.assert_array_equal(st["x"].numpy(),
                                  np.full(4, 1.5, np.float32))


def test_overlapping_chunks_cannot_mask_coverage_hole(tmp_path):
    """Overlapping chunks whose volumes SUM past the global size but
    leave an element uncovered must still raise — a summed coverage
    check would pass and return uninitialized np.empty memory."""
    p = str(tmp_path / "ck")
    ckpt.save_state_dict({"w": paddle.ones([5])}, p)
    mpath = os.path.join(p, "metadata.json")
    meta = json.load(open(mpath))
    chunk = meta["tensors"]["w"]["chunks"][0]
    c0 = dict(chunk, global_offset=[0], local_shape=[3])
    c1 = dict(chunk, global_offset=[1], local_shape=[3])
    meta["tensors"]["w"]["chunks"] = [c0, c1]  # union [0,4): hole at 4
    json.dump(meta, open(mpath, "w"))
    with pytest.raises(ValueError, match="'w'.*coverage hole"):
        ckpt.load_state_dict({"w": paddle.zeros([5])}, p)


def test_load_recovers_checkpoint_parked_at_old(tmp_path):
    """A restart that only LOADS (no save first) after a crash between
    save_state_dict's two commit renames must still find the complete
    checkpoint parked at <path>.old."""
    p = str(tmp_path / "ck")
    ckpt.save_state_dict({"x": paddle.full([4], 3.0)}, p)
    os.rename(p, p + ".old")  # the state a crash at that instant leaves
    y = paddle.zeros([4])
    ckpt.load_state_dict({"x": y}, p)
    np.testing.assert_array_equal(y.numpy(), np.full(4, 3.0, np.float32))
    assert os.path.isdir(p) and not os.path.exists(p + ".old")


def test_save_refuses_to_replace_non_checkpoint_dir(tmp_path):
    """The atomic commit replaces ``path`` wholesale — a populated
    directory that is NOT a checkpoint (user logs, configs) must be
    refused, never silently deleted."""
    p = str(tmp_path / "run_dir")
    os.makedirs(p)
    with open(os.path.join(p, "config.yaml"), "w") as f:
        f.write("lr: 0.1\n")
    with pytest.raises(ValueError, match="refusing to replace"):
        ckpt.save_state_dict({"x": paddle.ones([2])}, p)
    assert os.path.exists(os.path.join(p, "config.yaml"))
    # an innocent sibling named <path>.old is protected the same way
    p2 = str(tmp_path / "job")
    os.makedirs(p2 + ".old")
    with open(os.path.join(p2 + ".old", "notes.txt"), "w") as f:
        f.write("keep me\n")
    with pytest.raises(ValueError, match="refusing to replace"):
        ckpt.save_state_dict({"x": paddle.ones([2])}, p2)
    assert os.path.exists(os.path.join(p2 + ".old", "notes.txt"))


def test_nonnumeric_state_travels_in_sidecar(tmp_path):
    """Scheduler-style string state (e.g. ReduceOnPlateau's mode='min')
    must round-trip through save/load instead of crashing jnp.asarray —
    it rides in the objects.json sidecar, not the chunk format."""
    p = str(tmp_path / "ck")
    state = {"w": paddle.ones([3]),
             "opt": {"step": 4,
                     "LR_Scheduler": {"mode": "min", "factor": 0.5,
                                      "threshold_mode": "rel"}}}
    ckpt.save_state_dict(state, p)
    assert os.path.exists(os.path.join(p, "objects.json"))
    dst = {"w": paddle.zeros([3]),
           "opt": {"step": 0,
                   "LR_Scheduler": {"mode": "max", "factor": 0.0,
                                    "threshold_mode": "abs"}}}
    ckpt.load_state_dict(dst, p)
    np.testing.assert_array_equal(dst["w"].numpy(),
                                  np.ones(3, np.float32))
    assert dst["opt"]["step"] == 4
    assert dst["opt"]["LR_Scheduler"] == {"mode": "min", "factor": 0.5,
                                          "threshold_mode": "rel"}


def test_manager_keep_last_n_floor(tmp_path):
    """keep_last_n is clamped to >= 1: retention must never be silently
    disabled (committed[:-0] would classify nothing as stale) and never
    delete the only resumable checkpoint."""
    mgr = CheckpointManager(str(tmp_path), keep_last_n=0)
    for s in (1, 2, 3):
        mgr.save(s, _mgr_state(float(s)), block=True)
    assert mgr.all_steps() == [3]
    assert sorted(os.listdir(tmp_path)) == ["step_3"]


def test_manager_barrier_namespace_advances(tmp_path):
    """Every save gets a fresh store-barrier namespace — a reused tag
    would release peers out of a PREVIOUS save's counters (FileStore
    counters persist; the coordination service rejects reused ids)."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _mgr_state(), block=True)
    seq1 = mgr._seq
    mgr.save(1, _mgr_state(), block=True, force=True)  # same-step re-save
    assert mgr._seq > seq1
    assert mgr._ns_prefix.startswith("r")


def test_manager_refuses_uncommitted_restore(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, _mgr_state(), block=True)
    os.remove(tmp_path / "step_1" / "COMMITTED")
    with pytest.raises(ValueError, match="COMMITTED"):
        mgr.restore(_mgr_state(), step=1)


def test_manager_retry_with_backoff(tmp_path):
    """Transient filesystem errors are retried with exponential backoff;
    persistent ones surface after max_retries attempts."""
    mgr = CheckpointManager(str(tmp_path), max_retries=3,
                            backoff_base=0.01)
    with faults.injected("ckpt.data_written:raise*2") as inj:
        mgr.save(1, _mgr_state(), block=True)
    assert inj.faults()[0].fired == 2  # two failures, third attempt won
    assert mgr.latest_step() == 1
    with faults.injected("ckpt.data_written:raise"):
        with pytest.raises(OSError, match="after 4 attempts"):
            mgr.save(2, _mgr_state(), block=True)
    assert mgr.latest_step() == 1  # failed save committed nothing


def test_manager_async_error_surfaces_on_wait(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_retries=0,
                            backoff_base=0.01)
    with faults.injected("ckpt.data_written:raise"):
        mgr.save(1, _mgr_state())
        with pytest.raises(OSError):
            mgr.wait()
    mgr.save(2, _mgr_state(), block=True)  # manager still usable
    assert mgr.latest_step() == 2


def test_manager_trainstep_resume_roundtrip(tmp_path):
    """Model+optimizer resume through the manager: restored params and
    slots are bit-identical and training continues from the right step."""
    paddle.seed(0)
    m = nn.Linear(4, 4)
    opt = optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    train = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    X, Y = paddle.randn([8, 4]), paddle.randn([8, 4])
    train(X, Y)
    train(X, Y)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(2, {"model": m.state_dict(), "opt": opt.state_dict()},
             block=True)
    ref_w = m.weight.numpy().copy()

    paddle.seed(9)
    m2 = nn.Linear(4, 4)
    opt2 = optimizer.Adam(learning_rate=0.01, parameters=m2.parameters())
    paddle.jit.TrainStep(m2, nn.MSELoss(), opt2)  # materialize slots
    st = {"model": m2.state_dict(), "opt": opt2.state_dict()}
    assert mgr.restore_or_initialize(st) == 2
    opt2.set_state_dict(st["opt"])
    np.testing.assert_array_equal(m2.weight.numpy(), ref_w)
    assert opt2._step_count == 2


# ---------------------------------------------------------------------------
# preemption signalling
# ---------------------------------------------------------------------------
def test_preemption_monitor_sigterm_sets_flag(tmp_path):
    from paddle_tpu.distributed.watchdog import PreemptionMonitor

    mon = PreemptionMonitor()
    mon._store = False  # no store in this test
    mon.install()
    try:
        assert not mon.requested()
        signal.raise_signal(signal.SIGTERM)
        assert mon.requested()
    finally:
        mon.uninstall()


def test_preemption_broadcasts_through_store(tmp_path):
    """One rank's notice reaches peers via the gang store; a stale
    record from a previous incarnation does not (generation baseline)."""
    from paddle_tpu.distributed.store import FileStore
    from paddle_tpu.distributed.watchdog import PreemptionMonitor

    store = FileStore(str(tmp_path))
    a, b = PreemptionMonitor(), PreemptionMonitor()
    a._store = b._store = store
    b._last_poll = -1e9
    assert not b.requested()   # first poll records the (empty) baseline
    a.request()
    b._last_poll = -1e9        # bypass the poll rate limit
    assert b.requested()


def test_preemption_baseline_read_eagerly_at_install(tmp_path):
    """A peer's notice posted BEFORE this rank's first poll (e.g. during
    a long first compile) must still be seen: the stale-record baseline
    is read at install time, not lazily on the first poll."""
    from paddle_tpu.distributed.store import FileStore
    from paddle_tpu.distributed.watchdog import PreemptionMonitor

    store = FileStore(str(tmp_path))
    store.set("preempt_notice", b'{"rank": 9, "gen": "previous-run"}')
    a, b = PreemptionMonitor(), PreemptionMonitor()
    a._store = b._store = store
    b._read_baseline()          # what install() does
    a.request()                 # peer preempted before b ever polled
    b._last_poll = -1e9
    assert b.requested()


def test_manager_preemption_forces_save(tmp_path):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    assert not mgr.should_save(7)
    mon = mgr.install_preemption_handler()
    try:
        mon._store = False
        mon.request()
        assert mgr.reached_preemption(7)
        assert mgr.should_save(7)  # interval is overridden
        mgr.save(7, _mgr_state(), block=True, force=True)
        assert mgr.latest_step() == 7
    finally:
        mon.uninstall()
        mon._flag.clear()  # module singleton: don't leak into other tests


# ---------------------------------------------------------------------------
# TrainStep(skip_nonfinite=True) — acceptance-criteria pins
# ---------------------------------------------------------------------------
def _guard_setup(dtype, donate, skip_nonfinite=True, seed=0):
    paddle.seed(seed)
    m = nn.Linear(3, 3)
    if dtype == "bfloat16":
        m.to(dtype="bfloat16")
    opt = optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt, donate=donate,
                                skip_nonfinite=skip_nonfinite)
    rng = np.random.default_rng(3)
    X = paddle.to_tensor(rng.normal(size=(4, 3)).astype(np.float32)
                         ).astype(dtype)
    Y = paddle.to_tensor(rng.normal(size=(4, 3)).astype(np.float32)
                         ).astype(dtype)
    return m, opt, step, X, Y


def _host_state(m, opt):
    """Bit-exact host copies of params + optimizer slots (survives
    donation of the device buffers)."""
    params = {k: np.asarray(v._data).copy()
              for k, v in m.state_dict().items()}
    slots = [{k: np.asarray(v).copy() for k, v in s.items()}
             for s in opt._slots.values()]
    return params, slots


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("donate", [True, False])
def test_skip_nonfinite_identity_update(dtype, donate):
    """A NaN batch leaves params AND optimizer slots bit-identical, for
    f32/bf16 x donated/undonated, and bumps the skip counter."""
    m, opt, step, X, Y = _guard_setup(dtype, donate)
    step(X, Y)  # one clean step so slots are non-trivial
    before_p, before_s = _host_state(m, opt)
    Xn = paddle.to_tensor(
        np.full((4, 3), np.nan, np.float32)).astype(dtype)
    loss = step(Xn, Y)
    assert not np.isfinite(float(np.asarray(loss._data, np.float32)))
    after_p, after_s = _host_state(m, opt)
    for k in before_p:
        np.testing.assert_array_equal(
            before_p[k].view(np.uint8), after_p[k].view(np.uint8),
            err_msg=k)
    for bs, as_ in zip(before_s, after_s):
        for k in bs:
            np.testing.assert_array_equal(
                bs[k].view(np.uint8), as_[k].view(np.uint8), err_msg=k)
    assert step.skipped_steps == 1
    # the guard recovers: a clean step after the skip still trains
    step(X, Y)
    assert step.skipped_steps == 1


def test_skip_nonfinite_clean_run_bitwise_matches_guard_off():
    """With no non-finite step, the guard must be a bit-exact no-op."""
    m_on, _, step_on, X, Y = _guard_setup("float32", True,
                                          skip_nonfinite=True)
    m_off, _, step_off, X2, Y2 = _guard_setup("float32", True,
                                              skip_nonfinite=False)
    for _ in range(3):
        step_on(X, Y)
        step_off(X2, Y2)
    on = {k: np.asarray(v._data) for k, v in m_on.state_dict().items()}
    off = {k: np.asarray(v._data) for k, v in m_off.state_dict().items()}
    for k in on:
        np.testing.assert_array_equal(on[k].view(np.uint8),
                                      off[k].view(np.uint8), err_msg=k)


def test_skip_counter_surfaces_in_profiler():
    from paddle_tpu import profiler

    m, opt, step, X, Y = _guard_setup("float32", True)
    Xn = paddle.to_tensor(np.full((4, 3), np.nan, np.float32))
    step(Xn, Y)
    key = f"train_step/nonfinite_skipped#{id(step)}"
    assert profiler.counters().get(key) == 1


def test_skip_counter_provider_unregisters_on_gc():
    """Apps that never read counters() must not leak one registry entry
    per TrainStep (weakref.finalize cleans up at GC)."""
    import gc

    from paddle_tpu import profiler

    m, opt, step, X, Y = _guard_setup("float32", True)
    key = f"train_step/nonfinite_skipped#{id(step)}"
    assert key in profiler._counter_providers
    del step
    gc.collect()
    assert key not in profiler._counter_providers


def test_skip_nonfinite_state_dict_persists_applied_step():
    """The host _step_count advances per DISPATCH; a skipped step rolls
    the device step back. opt.state_dict() must persist the APPLIED
    count, or a restore jumps bias-corrected rules over the skips."""
    m, opt, step, X, Y = _guard_setup("float32", True)
    step(X, Y)
    Xn = paddle.to_tensor(np.full((4, 3), np.nan, np.float32))
    step(Xn, Y)  # skipped: dispatches=2, applied=1
    step(X, Y)   # dispatches=3, applied=2
    assert opt._step_count == 3          # eager mirror (schedulers)
    assert opt.state_dict()["step"] == 2  # persisted: device truth


# ---------------------------------------------------------------------------
# satellite: GradScaler divergence guard
# ---------------------------------------------------------------------------
def test_gradscaler_divergence_raises_eager():
    from paddle_tpu import amp

    scaler = amp.GradScaler(init_loss_scaling=2.0 ** 10,
                            max_consecutive_skips=3)
    for _ in range(2):
        scaler._found_inf = True
        scaler.update()
    assert scaler.skipped_steps == 2
    scaler._found_inf = False
    scaler.update()  # a good step resets the consecutive counter
    for _ in range(2):
        scaler._found_inf = True
        scaler.update()
    scaler._found_inf = True
    with pytest.raises(RuntimeError, match="diverged"):
        scaler.update()
    assert scaler.skipped_steps == 5


def test_gradscaler_divergence_raises_compiled():
    """The compiled TrainStep path hits the same guard: every step NaN
    -> RuntimeError after max_consecutive_skips, with counters synced."""
    from paddle_tpu import amp

    paddle.seed(0)
    m = nn.Linear(3, 3)
    opt = optimizer.SGD(learning_rate=0.01, parameters=m.parameters())
    scaler = amp.GradScaler(init_loss_scaling=4.0,
                            max_consecutive_skips=2)
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt, scaler=scaler)
    Xn = paddle.to_tensor(np.full((4, 3), np.nan, np.float32))
    Y = paddle.zeros([4, 3])
    step(Xn, Y)
    assert scaler.skipped_steps == 1
    with pytest.raises(RuntimeError, match="diverged"):
        step(Xn, Y)
    assert scaler.skipped_steps == 2


# ---------------------------------------------------------------------------
# satellite: watchdog timeout contract (dump + exit code 6)
# ---------------------------------------------------------------------------
def test_watchdog_hung_step_dumps_stacks_and_exits_6(tmp_path):
    """A hung compiled step must produce the host stack dump and abort
    with exit code 6 — the dump-and-abort contract the launcher's
    restart loop relies on."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({
        "PADDLE_STEP_TIMEOUT": "2",
        "PADDLE_STEP_COMPILE_ALLOWANCE": "3",
        "PADDLE_RESTART_COUNT": "0",  # hang_worker hangs on attempt 0
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    p = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "hang_worker.py")],
        env=env, capture_output=True, text=True, timeout=120)
    assert p.returncode == 6, (p.returncode, p.stderr[-2000:])
    assert "[watchdog]" in p.stderr
    assert "exceeded" in p.stderr
    # faulthandler's all-thread dump: every thread section starts with
    # "Thread 0x..." / "Current thread 0x..."
    assert "Current thread" in p.stderr or "Thread 0x" in p.stderr


# ---------------------------------------------------------------------------
# DataLoader worker killed by the OS
# ---------------------------------------------------------------------------
class _SlowDataset:
    def __len__(self):
        return 64

    def __getitem__(self, i):
        time.sleep(0.05)
        return np.float32([i])


def test_dataloader_killed_worker_raises(tmp_path):
    """SIGKILLing a worker (the OOM-killer scenario) must surface as a
    clear error instead of hanging the iteration forever."""
    from paddle_tpu.io import DataLoader

    loader = DataLoader(_SlowDataset(), batch_size=1, num_workers=2,
                        use_shared_memory=False)
    it = iter(loader)
    next(it)  # workers are up and producing
    victim = faults.kill_one_child()
    assert victim is not None
    with pytest.raises(RuntimeError, match="worker died"):
        deadline = time.time() + 30
        while time.time() < deadline:
            next(it)
