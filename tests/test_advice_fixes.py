"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def test_recompute_propagates_param_grads():
    """high: eager recompute() must populate weight grads of the
    recomputed Layer (reference RecomputeFunction semantics)."""
    from paddle_tpu.distributed.fleet.recompute import recompute

    paddle.seed(0)
    layer = nn.Linear(8, 8)
    x = paddle.randn([4, 8])

    out = recompute(layer, x)
    out.sum().backward()
    assert layer.weight.grad is not None
    assert layer.bias.grad is not None

    # grads must match the non-recomputed path
    paddle.seed(0)
    layer2 = nn.Linear(8, 8)
    out2 = layer2(paddle.to_tensor(x.numpy()))
    out2.sum().backward()
    np.testing.assert_allclose(layer.weight.grad.numpy(),
                               layer2.weight.grad.numpy(), rtol=1e-5)


def test_recompute_sequential_propagates_param_grads():
    from paddle_tpu.distributed.fleet.recompute import recompute_sequential

    paddle.seed(0)
    seq = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 8))
    x = paddle.randn([4, 8])
    out = recompute_sequential({"segments": 2}, seq, x)
    out.sum().backward()
    assert seq[0].weight.grad is not None
    assert seq[2].weight.grad is not None


def test_trainstep_n_model_inputs_retrace():
    """medium: changing n_model_inputs between calls must retrace, not
    silently reuse the first split."""

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, a, b=None):
            out = self.fc(a)
            if b is not None:
                out = out + b
            return out

    paddle.seed(0)
    m = TwoIn()
    opt = optimizer.SGD(learning_rate=0.0, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    a = paddle.randn([2, 4])
    b = paddle.zeros([2, 4])
    y = paddle.zeros([2, 4])
    l1 = float(step(a, y).item())
    # same arity of batch, different split: model gets (a, b) now
    l2 = float(step(a, b, y, n_model_inputs=2).item())
    # b==0 so the losses agree; the point is no stale-split crash/garbage
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_radam_traceable_in_trainstep():
    """low: RAdam's rectification branch must be traceable (jnp.where)."""
    paddle.seed(0)
    m = nn.Linear(4, 4)
    opt = optimizer.RAdam(learning_rate=0.01, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    x, y = paddle.randn([8, 4]), paddle.randn([8, 4])
    losses = [float(step(x, y).item()) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_radam_eager_matches_reference_rectification():
    """RAdam eager: early steps take the unrectified branch, later the
    rectified one; both must be finite and loss must fall."""
    paddle.seed(1)
    m = nn.Linear(4, 1)
    opt = optimizer.RAdam(learning_rate=0.05, parameters=m.parameters())
    x = paddle.randn([16, 4])
    y = paddle.randn([16, 1])
    loss_fn = nn.MSELoss()
    losses = []
    for _ in range(8):
        loss = loss_fn(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0]


def test_broadcast_raises_outside_spmd():
    """low: broadcast on a multi-rank group outside SPMD must raise, like
    the other collectives, instead of silently no-opping."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.mesh import init_mesh

    init_mesh([8], ["x"])
    dist.init_parallel_env()
    t = paddle.ones([4])
    with pytest.raises(RuntimeError):
        dist.broadcast(t, src=0)


def test_second_backward_raises_clear_error():
    """low: backward twice without retain_graph -> clear RuntimeError."""
    x = paddle.randn([4])
    x.stop_gradient = False
    y = (x * x).sum()
    y.backward(retain_graph=False)
    z = x * 1.0  # reuse freed graph? build second backward through y
    with pytest.raises(RuntimeError, match="retain_graph"):
        y.backward()
