"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def test_recompute_propagates_param_grads():
    """high: eager recompute() must populate weight grads of the
    recomputed Layer (reference RecomputeFunction semantics)."""
    from paddle_tpu.distributed.fleet.recompute import recompute

    paddle.seed(0)
    layer = nn.Linear(8, 8)
    x = paddle.randn([4, 8])

    out = recompute(layer, x)
    out.sum().backward()
    assert layer.weight.grad is not None
    assert layer.bias.grad is not None

    # grads must match the non-recomputed path
    paddle.seed(0)
    layer2 = nn.Linear(8, 8)
    out2 = layer2(paddle.to_tensor(x.numpy()))
    out2.sum().backward()
    np.testing.assert_allclose(layer.weight.grad.numpy(),
                               layer2.weight.grad.numpy(), rtol=1e-5)


def test_recompute_sequential_propagates_param_grads():
    from paddle_tpu.distributed.fleet.recompute import recompute_sequential

    paddle.seed(0)
    seq = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 8))
    x = paddle.randn([4, 8])
    out = recompute_sequential({"segments": 2}, seq, x)
    out.sum().backward()
    assert seq[0].weight.grad is not None
    assert seq[2].weight.grad is not None


def test_trainstep_n_model_inputs_retrace():
    """medium: changing n_model_inputs between calls must retrace, not
    silently reuse the first split."""

    class TwoIn(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, a, b=None):
            out = self.fc(a)
            if b is not None:
                out = out + b
            return out

    paddle.seed(0)
    m = TwoIn()
    opt = optimizer.SGD(learning_rate=0.0, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    a = paddle.randn([2, 4])
    b = paddle.zeros([2, 4])
    y = paddle.zeros([2, 4])
    l1 = float(step(a, y).item())
    # same arity of batch, different split: model gets (a, b) now
    l2 = float(step(a, b, y, n_model_inputs=2).item())
    # b==0 so the losses agree; the point is no stale-split crash/garbage
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_radam_traceable_in_trainstep():
    """low: RAdam's rectification branch must be traceable (jnp.where)."""
    paddle.seed(0)
    m = nn.Linear(4, 4)
    opt = optimizer.RAdam(learning_rate=0.01, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    x, y = paddle.randn([8, 4]), paddle.randn([8, 4])
    losses = [float(step(x, y).item()) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_radam_eager_matches_reference_rectification():
    """RAdam eager: early steps take the unrectified branch, later the
    rectified one; both must be finite and loss must fall."""
    paddle.seed(1)
    m = nn.Linear(4, 1)
    opt = optimizer.RAdam(learning_rate=0.05, parameters=m.parameters())
    x = paddle.randn([16, 4])
    y = paddle.randn([16, 1])
    loss_fn = nn.MSELoss()
    losses = []
    for _ in range(8):
        loss = loss_fn(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0]


def test_broadcast_raises_outside_spmd():
    """low: broadcast on a multi-rank group outside SPMD must raise, like
    the other collectives, instead of silently no-opping."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.mesh import init_mesh

    init_mesh([8], ["x"])
    dist.init_parallel_env()
    t = paddle.ones([4])
    with pytest.raises(RuntimeError):
        dist.broadcast(t, src=0)


def test_second_backward_raises_clear_error():
    """low: backward twice without retain_graph -> clear RuntimeError."""
    x = paddle.randn([4])
    x.stop_gradient = False
    y = (x * x).sum()
    y.backward(retain_graph=False)
    z = x * 1.0  # reuse freed graph? build second backward through y
    with pytest.raises(RuntimeError, match="retain_graph"):
        y.backward()


# ---------------------------------------------------------------------------
# round-4 advisor findings
# ---------------------------------------------------------------------------

@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_static_gradients_wrt_intermediate_variable(static_mode):
    """medium: static.gradients() wrt an op-produced Variable must work
    (reference paddle.static.gradients supports arbitrary Variables)."""
    from paddle_tpu import static

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [3], "float32")
        h = x * 3.0          # intermediate _OP Variable
        loss = paddle.sum(h * h)
        (gh,) = static.gradients([loss], [h])
        (gx,) = static.gradients([loss], [x])
    exe = static.Executor()
    xs = np.asarray([1.0, 2.0, -1.0], "float32")
    out = exe.run(main, feed={"x": xs}, fetch_list=[gh, gx])
    np.testing.assert_allclose(out[0], 2 * 3 * xs, rtol=1e-6)   # 2h
    np.testing.assert_allclose(out[1], 18 * xs, rtol=1e-6)      # 18x


def test_py_func_backward_per_input_convention(static_mode):
    """low: backward_func returning one grad per input (None for the int
    input) must align even when the int input precedes the float one."""
    from paddle_tpu import static

    def host_fn(idx, feats):
        return feats * 2.0

    def host_bwd(idx, feats, y, g):
        return None, np.asarray(g) * 2.0  # per-input: (d idx, d feats)

    main = static.Program()
    with static.program_guard(main):
        idx = static.data("idx", [2], "int32")
        feats = static.data("feats", [2], "float32")
        y = static.nn.py_func(host_fn, [idx, feats], ([2], "float32"),
                              backward_func=host_bwd)
        loss = paddle.sum(y)
        (gf,) = static.gradients([loss], [feats])
    exe = static.Executor()
    out = exe.run(main, feed={"idx": np.asarray([0, 1], "int32"),
                              "feats": np.asarray([1.5, -2.0], "float32")},
                  fetch_list=[y, gf])
    np.testing.assert_allclose(out[0], [3.0, -4.0])
    np.testing.assert_allclose(out[1], [2.0, 2.0])


def test_program_clone_for_train_is_independent(static_mode):
    """low: Program.clone(for_test=False) must not share node/capture
    containers — ops recorded into the clone stay out of the original."""
    from paddle_tpu import static

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2], "float32")
        y = x * 2.0
    n_nodes = len(main.nodes)
    clone = main.clone(for_test=False)
    with static.program_guard(clone):
        z = y + 1.0  # records into the clone only
    assert len(main.nodes) == n_nodes, \
        "op recorded into clone leaked into the original Program"
    assert len(clone.nodes) == n_nodes + 1


def test_write_cache_drops_unallocated_block_writes():
    """low: a position mapping to a -1 block-table entry must be dropped,
    not written into physical block 0."""
    import jax.numpy as jnp

    from paddle_tpu.incubate.nn.functional.block_attention import (
        _write_cache,
    )

    bs, nb, kh, d = 4, 3, 1, 2
    cache = jnp.zeros((nb, bs, kh, d), "float32")
    # batch 0 owns physical block 0 only; logical block 1 is UNALLOCATED
    block_tables = jnp.asarray([[0, -1]], "int32")
    # two tokens: position 0 (block 0, ok) and position 4 (block 1 -> -1)
    positions = jnp.asarray([[0, 4]], "int32")
    blocks = jnp.ones((1, 2, kh, d), "float32")
    out = _write_cache(cache, blocks, block_tables, positions)
    assert float(out[0, 0, 0, 0]) == 1.0          # allocated write landed
    # the unallocated write must NOT clobber block 0 slot 0 (pos 4 % 4 = 0)
    assert float(out.sum()) == pytest.approx(d * 1.0), \
        "write through -1 block-table entry leaked into the cache"


def test_flash_attn_unpadded_gqa_heads():
    """low: varlen flash attention with num_heads_k < num_heads_q (GQA)
    must not shape-error on the padded K/V buffers."""
    from paddle_tpu.nn.functional.flash_attention import flash_attn_unpadded

    h, kh, d = 4, 2, 8
    total_q, total_k = 6, 6
    q = paddle.randn([total_q, h, d])
    k = paddle.randn([total_k, kh, d])
    v = paddle.randn([total_k, kh, d])
    cu = np.asarray([0, 3, 6], "int32")
    out, _ = flash_attn_unpadded(q, k, v, cu, cu, 3, 3,
                                 scale=1.0 / np.sqrt(d))
    assert tuple(out.shape) == (total_q, h, d)
    assert np.isfinite(out.numpy()).all()
