"""serving.BlockManager / Scheduler invariants (model-free fast tests).

Pins the tentpole's allocator + scheduler contracts: exact free-block
accounting under randomized admit/decode/free/preempt sequences, no
double allocation, preempted requests re-admit and finish, and the
FCFS starvation guard (waiting requests eventually run)."""
import numpy as np
import pytest

from paddle_tpu.serving import (
    BlockManager, NoFreeBlocksError, Request, RequestStatus,
    SamplingParams, Scheduler, SchedulerConfig,
)


def _req(rid, n_prompt, max_new=4, arrival=None):
    r = Request(request_id=str(rid), prompt_ids=list(range(1, n_prompt + 1)),
                sampling=SamplingParams(max_new_tokens=max_new))
    if arrival is not None:
        r.arrival_time = arrival
    return r


# ---------------------------------------------------------------------------
# BlockManager
# ---------------------------------------------------------------------------
def test_block_manager_allocate_append_free_accounting():
    bm = BlockManager(num_blocks=8, block_size=4)
    t = bm.allocate("a", 10)             # 3 blocks
    assert len(t) == 3 and bm.num_free_blocks == 5
    # growth inside the last block costs nothing
    assert bm.append_slot("a", 11) == t and bm.num_free_blocks == 5
    assert bm.append_slot("a", 12) == t
    # crossing a block boundary claims exactly one
    t2 = bm.append_slot("a", 13)
    assert len(t2) == 4 and bm.num_free_blocks == 4
    assert bm.free("a") == 4
    assert bm.num_free_blocks == 8
    assert bm.free("a") == 0             # idempotent
    bm.check_invariants()


def test_block_manager_rejects_double_allocation():
    bm = BlockManager(num_blocks=4, block_size=4)
    bm.allocate("a", 4)
    with pytest.raises(ValueError, match="already holds"):
        bm.allocate("a", 4)


def test_block_manager_oom_signals():
    bm = BlockManager(num_blocks=2, block_size=4)
    bm.allocate("a", 8)
    assert not bm.can_allocate(1)
    with pytest.raises(NoFreeBlocksError):
        bm.allocate("b", 1)
    with pytest.raises(NoFreeBlocksError):
        bm.append_slot("a", 9)
    bm.check_invariants()


def test_block_manager_randomized_invariants():
    """Randomized admit/grow/free/preempt storm; the exact-accounting
    invariants must hold after EVERY operation."""
    rng = np.random.default_rng(0)
    bm = BlockManager(num_blocks=16, block_size=4)
    lens = {}
    for step in range(2000):
        op = rng.integers(0, 3)
        if op == 0:  # admit
            rid = f"r{step}"
            n = int(rng.integers(1, 20))
            if bm.can_allocate(n):
                bm.allocate(rid, n)
                lens[rid] = n
            else:
                with pytest.raises(NoFreeBlocksError):
                    bm.allocate(rid, n)
        elif op == 1 and lens:  # grow (a decode slot)
            rid = list(lens)[int(rng.integers(0, len(lens)))]
            new_len = lens[rid] + 1
            if bm.can_append(rid, new_len):
                bm.append_slot(rid, new_len)
                lens[rid] = new_len
            else:
                with pytest.raises(NoFreeBlocksError):
                    bm.append_slot(rid, new_len)
        elif op == 2 and lens:  # free (finish OR preempt-reclaim)
            rid = list(lens)[int(rng.integers(0, len(lens)))]
            got = bm.free(rid)
            assert got == bm.blocks_needed(lens.pop(rid))
        bm.check_invariants()
    for rid in list(lens):
        bm.free(rid)
    assert bm.num_free_blocks == 16


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------
def _drive(sched, max_iters=200):
    """Minimal engine loop: run scheduled batches, append one token per
    scheduled request per iteration, retire finished requests. Returns
    the per-iteration batch kinds."""
    kinds = []
    for _ in range(max_iters):
        if not sched.has_unfinished():
            break
        batch = sched.schedule()
        kinds.append(batch.kind)
        assert not (batch.is_empty and batch.kind != "idle")
        for r in batch.requests:
            r.num_cached += len(r.tokens_to_run())
            if r.append_token(7):
                sched.finish(r)
        sched.block_manager.check_invariants()
    assert not sched.has_unfinished(), "starved requests remain"
    return kinds


def test_scheduler_interleaves_prefill_and_decode():
    bm = BlockManager(num_blocks=64, block_size=4)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4,
                                      max_batched_tokens=64))
    for i in range(3):
        s.add(_req(i, n_prompt=5, max_new=3, arrival=float(i)))
    kinds = _drive(s)
    assert kinds[0] == "prefill"
    assert "decode" in kinds
    assert bm.num_free_blocks == 64


def test_scheduler_token_budget_splits_prefill_batches():
    bm = BlockManager(num_blocks=64, block_size=4)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=8,
                                      max_batched_tokens=10))
    for i in range(4):
        s.add(_req(i, n_prompt=6, max_new=1, arrival=float(i)))
    b1 = s.schedule()
    assert b1.kind == "prefill" and len(b1.requests) == 1  # 6+6 > 10
    b2 = s.schedule()
    assert b2.kind == "prefill" and len(b2.requests) == 1


def test_scheduler_overbudget_prompt_admitted_alone():
    bm = BlockManager(num_blocks=64, block_size=4)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=8,
                                      max_batched_tokens=8))
    s.add(_req("big", n_prompt=20, max_new=1))
    b = s.schedule()
    assert b.kind == "prefill" and len(b.requests) == 1


def test_scheduler_preempts_latest_arrival_on_oom():
    """Two requests decoding in a cache with room for only one to grow:
    the LATER arrival is evicted, reclaims its blocks, lands at the
    front of the waiting queue, and its progress is preserved."""
    bm = BlockManager(num_blocks=4, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4))
    a = _req("a", n_prompt=4, max_new=8, arrival=1.0)
    b = _req("b", n_prompt=4, max_new=8, arrival=2.0)
    for r in (a, b):
        s.add(r)
    batch = s.schedule()       # both prefill: 2 blocks each, cache full
    assert [r.request_id for r in batch.requests] == ["a", "b"]
    for r in batch.requests:
        r.num_cached += len(r.tokens_to_run())
        r.append_token(7)
    batch = s.schedule()       # both need a slot; only b's eviction frees one
    assert batch.kind == "decode"
    assert [r.request_id for r in batch.requests] == ["a"]
    assert [r.request_id for r in batch.preempted] == ["b"]
    assert b.status == RequestStatus.WAITING
    assert b.num_cached == 0 and len(b.tokens) == 5  # progress kept
    assert b.num_preemptions == 1
    assert s.waiting[0] is b
    bm.check_invariants()


def test_scheduler_starvation_guard_all_requests_finish():
    """More requests than max_num_seqs and a tight cache: every request
    (including preempted ones) must still run to completion — FCFS
    admission + evict-from-the-back guarantees forward progress."""
    bm = BlockManager(num_blocks=8, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=2,
                                      max_batched_tokens=16))
    reqs = [_req(i, n_prompt=3 + (i % 3), max_new=4, arrival=float(i))
            for i in range(6)]
    for r in reqs:
        s.add(r)
    _drive(s)
    assert all(r.is_finished for r in reqs)
    assert bm.num_free_blocks == 8


def test_scheduler_randomized_storm():
    """Random arrivals + tight memory: preempted requests re-admit and
    finish; block accounting stays exact throughout."""
    rng = np.random.default_rng(1)
    bm = BlockManager(num_blocks=10, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=3,
                                      max_batched_tokens=32))
    reqs = []
    for it in range(400):
        if len(reqs) < 20 and rng.random() < 0.2:
            r = _req(f"s{len(reqs)}", n_prompt=int(rng.integers(1, 8)),
                     max_new=int(rng.integers(1, 6)), arrival=float(it))
            reqs.append(r)
            s.add(r)
        if not s.has_unfinished():
            continue
        batch = s.schedule()
        for r in batch.requests:
            r.num_cached += len(r.tokens_to_run())
            if r.append_token(int(rng.integers(0, 100))):
                s.finish(r)
        bm.check_invariants()
    _drive(s, max_iters=500)
    assert len(reqs) == 20 and all(r.is_finished for r in reqs)
    assert bm.num_free_blocks == 10


def test_scheduler_abort():
    bm = BlockManager(num_blocks=8, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4))
    a, b = _req("a", 4), _req("b", 4)
    s.add(a), s.add(b)
    s.schedule()
    assert s.abort("a") and not s.abort("zz")
    assert a.status == RequestStatus.FINISHED
    assert "a" not in [r.request_id for r in s.running]
    bm.check_invariants()


def test_request_and_sampling_validation():
    with pytest.raises(ValueError, match="empty prompt"):
        Request(request_id="x", prompt_ids=[])
    with pytest.raises(ValueError):
        SamplingParams(max_new_tokens=0)
    with pytest.raises(ValueError):
        SamplingParams(top_p=0.0)
    r = _req("x", 3, max_new=2)
    assert r.append_token(5) is False
    assert r.append_token(6) is True      # max_new_tokens reached
    assert r.is_finished and r.generated == [5, 6]
    r2 = Request(request_id="y", prompt_ids=[1, 2],
                 sampling=SamplingParams(max_new_tokens=9,
                                         eos_token_id=42))
    assert r2.append_token(41) is False
    assert r2.append_token(42) is True    # EOS

# ---------------------------------------------------------------------------
# host swap pool + abort-leak invariants (ISSUE 6)
# ---------------------------------------------------------------------------
class _StubSwapper:
    """Model-free KV mover: records traffic, moves no bytes."""

    def __init__(self):
        self.out_calls = []
        self.in_calls = []

    def copy_out(self, request, dev_table, host_table):
        self.out_calls.append((request.request_id, list(dev_table),
                               list(host_table)))

    def copy_in(self, request, host_table, dev_table):
        self.in_calls.append((request.request_id, list(host_table),
                              list(dev_table)))


def test_block_manager_swap_accounting():
    bm = BlockManager(num_blocks=4, block_size=2, num_host_blocks=3)
    bm.allocate("a", 5)                      # 3 device blocks
    assert bm.can_swap_out("a", 5)
    dev, host = bm.swap_out("a", 5)
    assert len(dev) == 3 and len(host) == 3
    assert bm.num_free_blocks == 4           # device side fully back
    assert bm.num_free_host_blocks == 0
    assert not bm.has_table("a") and bm.has_host_table("a")
    bm.check_invariants()
    # restore: host slots come back, device blocks claimed again
    host2, dev2 = bm.swap_in("a")
    assert host2 == host and len(dev2) == 3
    assert bm.num_free_host_blocks == 3
    assert bm.num_free_blocks == 1
    bm.check_invariants()
    assert bm.free("a") == 3
    bm.check_invariants()


def test_block_manager_swap_rejects_when_pool_small():
    bm = BlockManager(num_blocks=8, block_size=2, num_host_blocks=1)
    bm.allocate("a", 6)                      # needs 3 host slots
    assert not bm.can_swap_out("a", 6)
    with pytest.raises(NoFreeBlocksError, match="swap out"):
        bm.swap_out("a", 6)
    # no-pool manager never swaps
    bm0 = BlockManager(num_blocks=4, block_size=2)
    bm0.allocate("a", 2)
    assert not bm0.can_swap_out("a", 2)


def test_block_manager_free_releases_host_slots_too():
    """The abort-while-swapped leak class: free() must drop BOTH
    sides, and is idempotent."""
    bm = BlockManager(num_blocks=4, block_size=2, num_host_blocks=4)
    bm.allocate("a", 4)
    bm.swap_out("a", 4)
    assert bm.num_free_host_blocks == 2
    assert bm.free("a") == 0                 # no device blocks held
    assert bm.num_free_host_blocks == 4      # host slots reclaimed
    assert bm.free("a") == 0
    bm.check_invariants()


def test_scheduler_swap_preempts_and_restores():
    """Eviction with a host pool spills instead of recomputing: the
    victim keeps num_cached, rejoins running via swap-in when blocks
    free, and the swapper sees matching out/in traffic."""
    bm = BlockManager(num_blocks=4, block_size=2, num_host_blocks=4)
    sw = _StubSwapper()
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4), swap_mode="host",
                  kv_swapper=sw)
    a = _req("a", n_prompt=4, max_new=8, arrival=1.0)
    b = _req("b", n_prompt=4, max_new=8, arrival=2.0)
    for r in (a, b):
        s.add(r)
    s.schedule()                             # both prefill, cache full
    for r in (a, b):
        r.num_cached += len(r.tokens_to_run())
        r.append_token(7)
    batch = s.schedule()                     # OOM -> b swaps out
    assert [r.request_id for r in batch.requests] == ["a"]
    assert [r.request_id for r in batch.preempted] == ["b"]
    assert b.status == RequestStatus.SWAPPED
    assert b.num_cached == 4                 # cached prefix KEPT
    assert b.num_swaps == 1 and s.num_swap_outs == 1
    assert len(sw.out_calls) == 1
    bm.check_invariants()
    # finish a -> blocks free -> b swaps back in and decodes
    a.num_cached += 1
    while not a.append_token(7):
        pass
    s.finish(a)
    batch = s.schedule()
    assert [r.request_id for r in batch.swapped_in] == ["b"]
    assert batch.kind == "decode"
    assert [r.request_id for r in batch.requests] == ["b"]
    assert b.status == RequestStatus.RUNNING
    assert s.num_swap_ins == 1 and len(sw.in_calls) == 1
    # the restored device table covers the cached prefix
    assert len(bm.block_table("b")) >= 2
    bm.check_invariants()


def test_scheduler_host_pool_exhaustion_falls_back_to_recompute():
    """A full host pool must not deadlock eviction: the victim falls
    back to the recompute path (WAITING, num_cached reset)."""
    bm = BlockManager(num_blocks=4, block_size=2, num_host_blocks=1)
    sw = _StubSwapper()
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4), swap_mode="host",
                  kv_swapper=sw)
    a = _req("a", n_prompt=4, max_new=8, arrival=1.0)
    b = _req("b", n_prompt=4, max_new=8, arrival=2.0)
    for r in (a, b):
        s.add(r)
    s.schedule()
    for r in (a, b):
        r.num_cached += len(r.tokens_to_run())
        r.append_token(7)
    batch = s.schedule()                     # b evicted; pool too small
    assert [r.request_id for r in batch.preempted] == ["b"]
    assert b.status == RequestStatus.WAITING
    assert b.num_cached == 0 and s.num_swap_outs == 0
    assert sw.out_calls == []
    bm.check_invariants()


def test_scheduler_torn_spill_copy_frees_host_slots():
    """A copy_out that dies mid-spill must not strand the victim's host
    slots (the leaked-resource-on-raise class this PR's linter flags):
    the slots come back and the victim demotes to the recompute path."""
    class _TornSwapper(_StubSwapper):
        def copy_out(self, request, dev_table, host_table):
            raise RuntimeError("DMA torn mid-frame")

    bm = BlockManager(num_blocks=4, block_size=2, num_host_blocks=4)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4), swap_mode="host",
                  kv_swapper=_TornSwapper())
    a = _req("a", n_prompt=4, max_new=8, arrival=1.0)
    b = _req("b", n_prompt=4, max_new=8, arrival=2.0)
    for r in (a, b):
        s.add(r)
    s.schedule()
    for r in (a, b):
        r.num_cached += len(r.tokens_to_run())
        r.append_token(7)
    batch = s.schedule()                     # OOM -> spill of b tears
    assert [r.request_id for r in batch.preempted] == ["b"]
    assert b.status == RequestStatus.WAITING  # recompute, not SWAPPED
    assert b.num_cached == 0
    assert s.num_swap_outs == 0              # the spill never counted
    assert not bm.has_host_table("b")        # host slots reclaimed
    assert bm.num_free_host_blocks == 4
    bm.check_invariants()


def test_scheduler_priority_orders_admission_and_eviction():
    """priority < 0 beats FCFS: a late VIP admits first and is never
    the eviction victim while a lower-priority peer remains."""
    bm = BlockManager(num_blocks=4, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=2))
    lo = Request(request_id="lo", prompt_ids=[1, 2, 3, 4],
                 sampling=SamplingParams(max_new_tokens=8))
    lo.arrival_time = 1.0
    vip = Request(request_id="vip", prompt_ids=[1, 2, 3, 4],
                  sampling=SamplingParams(max_new_tokens=8, priority=-1))
    vip.arrival_time = 2.0                   # later, but outranks
    s.add(lo), s.add(vip)
    batch = s.schedule()
    assert [r.request_id for r in batch.requests] == ["vip", "lo"]
    for r in batch.requests:
        r.num_cached += len(r.tokens_to_run())
        r.append_token(7)
    batch = s.schedule()                     # OOM: LO is the victim
    assert [r.request_id for r in batch.requests] == ["vip"]
    assert [r.request_id for r in batch.preempted] == ["lo"]
    assert lo.status == RequestStatus.WAITING
    bm.check_invariants()


def test_scheduler_expire_deadlines_every_queue():
    import time as _time

    bm = BlockManager(num_blocks=8, block_size=2, num_host_blocks=8)
    sw = _StubSwapper()
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=2), swap_mode="host",
                  kv_swapper=sw)
    mk = lambda rid: Request(  # noqa: E731
        request_id=rid, prompt_ids=[1, 2, 3],
        sampling=SamplingParams(max_new_tokens=4, deadline_ms=1e-3))
    r_wait, r_run, r_swap = mk("w"), mk("r"), mk("s")
    # place one per queue, bypassing schedule for direct control
    s.waiting.append(r_wait)
    bm.allocate("r", 3)
    r_run.status = RequestStatus.RUNNING
    s.running.append(r_run)
    bm.allocate("s", 3)
    r_swap.num_cached = 3
    bm.swap_out("s", 3)
    r_swap.status = RequestStatus.SWAPPED
    s.swapped.append(r_swap)
    _time.sleep(0.002)
    expired = s.expire_deadlines()
    assert sorted(r.request_id for r in expired) == ["r", "s", "w"]
    assert all(r.finish_reason == "expired" for r in expired)
    assert not s.has_unfinished()
    assert bm.num_free_blocks == 8 and bm.num_free_host_blocks == 8
    bm.check_invariants()


def test_randomized_abort_interleaving_never_leaks_blocks():
    """Satellite-1 acceptance: after ANY interleaving of admission,
    decode, preemption (swap AND recompute), expiry, and abort —
    across every lifecycle state — both free lists return to full.
    400 iterations of a seeded random storm, invariants checked every
    step."""
    rng = np.random.default_rng(7)
    bm = BlockManager(num_blocks=10, block_size=2, num_host_blocks=4)
    sw = _StubSwapper()
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=3,
                                      max_batched_tokens=32),
                  swap_mode="host", kv_swapper=sw)
    reqs = []
    n_aborted = 0
    for it in range(400):
        if len(reqs) < 24 and rng.random() < 0.25:
            r = Request(
                request_id=f"r{len(reqs)}",
                prompt_ids=list(range(1, int(rng.integers(2, 9)))),
                sampling=SamplingParams(
                    max_new_tokens=int(rng.integers(1, 6)),
                    priority=int(rng.integers(-1, 2)),
                    # a slice of requests carries a TTL that will
                    # expire mid-storm
                    deadline_ms=(float(rng.integers(1, 20))
                                 if rng.random() < 0.3 else None)))
            r.arrival_time = float(it)
            reqs.append(r)
            s.add(r)
        # random abort of a random LIVE request, in ANY state
        # (waiting / running / swapped alike)
        if rng.random() < 0.15:
            live = [r for r in reqs if not r.is_finished]
            if live:
                victim = live[int(rng.integers(0, len(live)))]
                assert s.abort(victim.request_id)
                n_aborted += 1
        if not s.has_unfinished():
            continue
        batch = s.schedule()
        for r in batch.requests:
            r.num_cached += len(r.tokens_to_run())
            if r.append_token(int(rng.integers(0, 100))):
                s.finish(r)
        bm.check_invariants()
    # drain the stragglers (aborting a random half on the way out)
    guard = 0
    while s.has_unfinished():
        guard += 1
        assert guard < 300, "storm failed to converge"
        live = [r for r in reqs if not r.is_finished]
        if live and rng.random() < 0.3:
            s.abort(live[0].request_id)
            n_aborted += 1
        batch = s.schedule()
        for r in batch.requests:
            r.num_cached += len(r.tokens_to_run())
            if r.append_token(int(rng.integers(0, 100))):
                s.finish(r)
        bm.check_invariants()
    assert len(reqs) == 24 and all(r.is_finished for r in reqs)
    assert n_aborted > 0, "storm never exercised abort"
    # the satellite's pin: NOTHING leaks, device or host side
    assert bm.num_free_blocks == bm.num_blocks
    assert bm.num_free_host_blocks == bm.num_host_blocks
    bm.check_invariants()


def test_prefix_cache_cow_refcount_randomized_storm():
    """ISSUE-9 satellite: randomized storm on the PREFIX-CACHING
    allocator. Admissions draw from a prompt pool with genuine shared
    prefixes (so blocks really get refcounted across requests),
    growth follows the scheduler's chunked-prefill shape (write_from
    mid-prompt) then decodes, aborts strike at any phase, and host
    swap in/out interleaves throughout. COW pairs are drained exactly
    the way the engine drains them (take_cow_pairs before each step)
    and the exact-accounting invariants must hold after EVERY
    operation; at the end both free lists return to full."""
    rng = np.random.default_rng(5)
    bm = BlockManager(num_blocks=24, block_size=4, num_host_blocks=8,
                      enable_prefix_cache=True)
    # three 16-token stems, each with divergent tails; the bare
    # 8-token stem (2 exactly-full blocks) is the full-prompt-hit
    # case whose capped write forces COW while a peer holds the block
    stems = [list(map(int, rng.integers(0, 40, size=16)))
             for _ in range(3)]
    pool = [stem[:k] + list(map(int, rng.integers(40, 80, size=t)))
            for stem in stems
            for (k, t) in ((16, 3), (16, 6), (12, 5), (8, 0))]
    live = {}     # rid -> {"tokens", "covered", "target"}
    swapped = {}  # rid -> same dict, parked on host slots

    def drain_cow():
        for src, dst in bm.take_cow_pairs():
            assert src != dst, "COW copied a block onto itself"
            assert bm.ref_count(dst) >= 1, \
                "COW destination freed before the copy was drained"

    def pick(d):
        return list(d)[int(rng.integers(0, len(d)))]

    for it in range(1500):
        op = int(rng.integers(0, 5))
        if op == 0:  # admit, scheduler-shaped (match -> eff cap -> chunk)
            rid = f"s{it}"
            tokens = list(pool[int(rng.integers(0, len(pool)))])
            total = len(tokens)
            hit = bm.match_prefix(tokens)
            eff = min(hit, total - 1)
            n = int(rng.integers(1, total - eff + 1))
            try:
                bm.allocate(rid, eff + n, tokens=tokens)
            except NoFreeBlocksError:
                bm.check_invariants()
                continue
            covered = bm.last_hit_tokens + n
            live[rid] = {"tokens": tokens, "covered": covered,
                         "target": total + int(rng.integers(1, 6))}
            bm.commit_prefix(rid, tokens, covered)
        elif op == 1 and live:  # grow: chunk continuation, then decode
            rid = pick(live)
            st = live[rid]
            if st["covered"] >= st["target"]:
                bm.free(rid)
                live.pop(rid)
            else:
                remaining_prompt = len(st["tokens"]) - st["covered"]
                n = (int(rng.integers(1, remaining_prompt + 1))
                     if remaining_prompt > 0 else 1)
                try:
                    bm.append_slot(rid, st["covered"] + n,
                                   write_from=st["covered"])
                except NoFreeBlocksError:
                    bm.check_invariants()
                    continue
                st["covered"] += n
                bm.commit_prefix(rid, st["tokens"], st["covered"])
        elif op == 2 and live:  # abort/finish at any phase
            rid = pick(live)
            bm.free(rid)
            live.pop(rid)
        elif op == 3 and live:  # swap out (drops device refs)
            rid = pick(live)
            if bm.can_swap_out(rid, live[rid]["covered"]):
                bm.swap_out(rid, live[rid]["covered"])
                swapped[rid] = live.pop(rid)
        elif op == 4 and swapped:  # swap back in, or abort-while-swapped
            rid = pick(swapped)
            if rng.random() < 0.25:
                bm.free(rid)
                swapped.pop(rid)
            elif bm.can_swap_in(rid):
                bm.swap_in(rid)
                live[rid] = swapped.pop(rid)
        drain_cow()
        bm.check_invariants()
    for rid in list(live) + list(swapped):
        bm.free(rid)
    drain_cow()
    bm.check_invariants()
    assert bm.num_free_blocks == bm.num_blocks
    assert bm.num_free_host_blocks == bm.num_host_blocks
    # the storm actually exercised the machinery it pins
    assert bm.num_prefix_hits > 0, "no admission ever shared a prefix"
    assert bm.num_cow_copies > 0, "no write ever copy-on-wrote"


# ---------------------------------------------------------------------------
# fleet KV-ship: export_blocks / import_blocks (ISSUE 13)
# ---------------------------------------------------------------------------
def test_block_manager_export_import_basics():
    bm = BlockManager(num_blocks=8, block_size=4)
    bm.allocate("src", 10)                      # 3 blocks
    # export is read-only: leading blocks, no accounting change
    free_before = bm.num_free_blocks
    exported = bm.export_blocks("src", 7)       # 2 blocks cover 7
    assert exported == bm.block_table("src")[:2]
    assert bm.num_free_blocks == free_before
    bm.check_invariants()
    with pytest.raises(KeyError):
        bm.export_blocks("nope", 4)
    with pytest.raises(ValueError):
        bm.export_blocks("src", 999)            # table too short
    # import claims fresh refcount-1 unregistered blocks
    table = bm.import_blocks("dst", 7)
    assert len(table) == 2 and bm.has_table("dst")
    assert all(bm.ref_count(b) == 1 for b in table)
    assert set(table).isdisjoint(exported)
    bm.check_invariants()
    with pytest.raises(ValueError):
        bm.import_blocks("dst", 4)              # table already exists
    with pytest.raises(NoFreeBlocksError):
        bm.import_blocks("big", 100)
    bm.free("src")
    bm.free("dst")
    bm.check_invariants()
    assert bm.num_free_blocks == bm.num_blocks


def test_export_import_interleaved_with_cow_swap_storm():
    """ISSUE-13 satellite: the COW/refcount/swap storm with randomized
    export/import interleaved. Exports must be pure reads; imported
    tables join the same lifecycle (growth, COW via prefix commits,
    swap, abort) and the exact-accounting invariants hold after every
    operation; at the end both free lists return to full and the trie
    bijection (checked inside ``check_invariants``) survives."""
    rng = np.random.default_rng(13)
    bm = BlockManager(num_blocks=24, block_size=4, num_host_blocks=8,
                      enable_prefix_cache=True)
    stems = [list(map(int, rng.integers(0, 40, size=16)))
             for _ in range(3)]
    pool = [stem[:k] + list(map(int, rng.integers(40, 80, size=t)))
            for stem in stems
            for (k, t) in ((16, 3), (16, 6), (12, 5), (8, 0))]
    live = {}
    swapped = {}
    n_exports = n_imports = 0

    def drain_cow():
        for src, dst in bm.take_cow_pairs():
            assert src != dst
            assert bm.ref_count(dst) >= 1

    def pick(d):
        return list(d)[int(rng.integers(0, len(d)))]

    for it in range(1500):
        op = int(rng.integers(0, 7))
        if op == 0:  # admit, scheduler-shaped
            rid = f"s{it}"
            tokens = list(pool[int(rng.integers(0, len(pool)))])
            total = len(tokens)
            hit = bm.match_prefix(tokens)
            eff = min(hit, total - 1)
            n = int(rng.integers(1, total - eff + 1))
            try:
                bm.allocate(rid, eff + n, tokens=tokens)
            except NoFreeBlocksError:
                bm.check_invariants()
                continue
            covered = bm.last_hit_tokens + n
            live[rid] = {"tokens": tokens, "covered": covered,
                         "target": total + int(rng.integers(1, 6))}
            bm.commit_prefix(rid, tokens, covered)
        elif op == 1 and live:  # grow
            rid = pick(live)
            st = live[rid]
            if st["covered"] >= st["target"]:
                bm.free(rid)
                live.pop(rid)
            else:
                remaining = len(st["tokens"]) - st["covered"]
                n = (int(rng.integers(1, remaining + 1))
                     if remaining > 0 else 1)
                try:
                    bm.append_slot(rid, st["covered"] + n,
                                   write_from=st["covered"])
                except NoFreeBlocksError:
                    bm.check_invariants()
                    continue
                st["covered"] += n
                bm.commit_prefix(rid, st["tokens"], st["covered"])
        elif op == 2 and live:  # abort/finish
            rid = pick(live)
            bm.free(rid)
            live.pop(rid)
        elif op == 3 and live:  # swap out
            rid = pick(live)
            if bm.can_swap_out(rid, live[rid]["covered"]):
                bm.swap_out(rid, live[rid]["covered"])
                swapped[rid] = live.pop(rid)
        elif op == 4 and swapped:  # swap in / abort-while-swapped
            rid = pick(swapped)
            if rng.random() < 0.25:
                bm.free(rid)
                swapped.pop(rid)
            elif bm.can_swap_in(rid):
                bm.swap_in(rid)
                live[rid] = swapped.pop(rid)
        elif op == 5 and live:  # export: a pure read
            rid = pick(live)
            covered = live[rid]["covered"]
            if covered > 0:
                free_before = bm.num_free_blocks
                table = bm.export_blocks(rid, covered)
                assert table == bm.block_table(rid)[:len(table)]
                assert bm.num_free_blocks == free_before
                n_exports += 1
        elif op == 6:  # import: fresh blocks enter the lifecycle
            rid = f"i{it}"
            tokens = list(pool[int(rng.integers(0, len(pool)))])
            covered = int(rng.integers(1, len(tokens)))
            try:
                table = bm.import_blocks(rid, covered)
            except NoFreeBlocksError:
                bm.check_invariants()
                continue
            assert all(bm.ref_count(b) == 1 for b in table)
            live[rid] = {"tokens": tokens, "covered": covered,
                         "target": len(tokens)
                         + int(rng.integers(1, 6))}
            # the engine registers imported full blocks in the trie
            # (peers can prefix-hit onto shipped KV)
            bm.commit_prefix(rid, tokens, covered)
            n_imports += 1
        drain_cow()
        bm.check_invariants()
    for rid in list(live) + list(swapped):
        bm.free(rid)
    drain_cow()
    bm.check_invariants()
    assert bm.num_free_blocks == bm.num_blocks
    assert bm.num_free_host_blocks == bm.num_host_blocks
    assert n_exports > 0, "storm never exported"
    assert n_imports > 0, "storm never imported"
    assert bm.num_cow_copies > 0, "no write ever copy-on-wrote"
