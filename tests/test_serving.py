"""serving.BlockManager / Scheduler invariants (model-free fast tests).

Pins the tentpole's allocator + scheduler contracts: exact free-block
accounting under randomized admit/decode/free/preempt sequences, no
double allocation, preempted requests re-admit and finish, and the
FCFS starvation guard (waiting requests eventually run)."""
import numpy as np
import pytest

from paddle_tpu.serving import (
    BlockManager, NoFreeBlocksError, Request, RequestStatus,
    SamplingParams, Scheduler, SchedulerConfig,
)


def _req(rid, n_prompt, max_new=4, arrival=None):
    r = Request(request_id=str(rid), prompt_ids=list(range(1, n_prompt + 1)),
                sampling=SamplingParams(max_new_tokens=max_new))
    if arrival is not None:
        r.arrival_time = arrival
    return r


# ---------------------------------------------------------------------------
# BlockManager
# ---------------------------------------------------------------------------
def test_block_manager_allocate_append_free_accounting():
    bm = BlockManager(num_blocks=8, block_size=4)
    t = bm.allocate("a", 10)             # 3 blocks
    assert len(t) == 3 and bm.num_free_blocks == 5
    # growth inside the last block costs nothing
    assert bm.append_slot("a", 11) == t and bm.num_free_blocks == 5
    assert bm.append_slot("a", 12) == t
    # crossing a block boundary claims exactly one
    t2 = bm.append_slot("a", 13)
    assert len(t2) == 4 and bm.num_free_blocks == 4
    assert bm.free("a") == 4
    assert bm.num_free_blocks == 8
    assert bm.free("a") == 0             # idempotent
    bm.check_invariants()


def test_block_manager_rejects_double_allocation():
    bm = BlockManager(num_blocks=4, block_size=4)
    bm.allocate("a", 4)
    with pytest.raises(ValueError, match="already holds"):
        bm.allocate("a", 4)


def test_block_manager_oom_signals():
    bm = BlockManager(num_blocks=2, block_size=4)
    bm.allocate("a", 8)
    assert not bm.can_allocate(1)
    with pytest.raises(NoFreeBlocksError):
        bm.allocate("b", 1)
    with pytest.raises(NoFreeBlocksError):
        bm.append_slot("a", 9)
    bm.check_invariants()


def test_block_manager_randomized_invariants():
    """Randomized admit/grow/free/preempt storm; the exact-accounting
    invariants must hold after EVERY operation."""
    rng = np.random.default_rng(0)
    bm = BlockManager(num_blocks=16, block_size=4)
    lens = {}
    for step in range(2000):
        op = rng.integers(0, 3)
        if op == 0:  # admit
            rid = f"r{step}"
            n = int(rng.integers(1, 20))
            if bm.can_allocate(n):
                bm.allocate(rid, n)
                lens[rid] = n
            else:
                with pytest.raises(NoFreeBlocksError):
                    bm.allocate(rid, n)
        elif op == 1 and lens:  # grow (a decode slot)
            rid = list(lens)[int(rng.integers(0, len(lens)))]
            new_len = lens[rid] + 1
            if bm.can_append(rid, new_len):
                bm.append_slot(rid, new_len)
                lens[rid] = new_len
            else:
                with pytest.raises(NoFreeBlocksError):
                    bm.append_slot(rid, new_len)
        elif op == 2 and lens:  # free (finish OR preempt-reclaim)
            rid = list(lens)[int(rng.integers(0, len(lens)))]
            got = bm.free(rid)
            assert got == bm.blocks_needed(lens.pop(rid))
        bm.check_invariants()
    for rid in list(lens):
        bm.free(rid)
    assert bm.num_free_blocks == 16


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------
def _drive(sched, max_iters=200):
    """Minimal engine loop: run scheduled batches, append one token per
    scheduled request per iteration, retire finished requests. Returns
    the per-iteration batch kinds."""
    kinds = []
    for _ in range(max_iters):
        if not sched.has_unfinished():
            break
        batch = sched.schedule()
        kinds.append(batch.kind)
        assert not (batch.is_empty and batch.kind != "idle")
        for r in batch.requests:
            r.num_cached += len(r.tokens_to_run())
            if r.append_token(7):
                sched.finish(r)
        sched.block_manager.check_invariants()
    assert not sched.has_unfinished(), "starved requests remain"
    return kinds


def test_scheduler_interleaves_prefill_and_decode():
    bm = BlockManager(num_blocks=64, block_size=4)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4,
                                      max_batched_tokens=64))
    for i in range(3):
        s.add(_req(i, n_prompt=5, max_new=3, arrival=float(i)))
    kinds = _drive(s)
    assert kinds[0] == "prefill"
    assert "decode" in kinds
    assert bm.num_free_blocks == 64


def test_scheduler_token_budget_splits_prefill_batches():
    bm = BlockManager(num_blocks=64, block_size=4)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=8,
                                      max_batched_tokens=10))
    for i in range(4):
        s.add(_req(i, n_prompt=6, max_new=1, arrival=float(i)))
    b1 = s.schedule()
    assert b1.kind == "prefill" and len(b1.requests) == 1  # 6+6 > 10
    b2 = s.schedule()
    assert b2.kind == "prefill" and len(b2.requests) == 1


def test_scheduler_overbudget_prompt_admitted_alone():
    bm = BlockManager(num_blocks=64, block_size=4)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=8,
                                      max_batched_tokens=8))
    s.add(_req("big", n_prompt=20, max_new=1))
    b = s.schedule()
    assert b.kind == "prefill" and len(b.requests) == 1


def test_scheduler_preempts_latest_arrival_on_oom():
    """Two requests decoding in a cache with room for only one to grow:
    the LATER arrival is evicted, reclaims its blocks, lands at the
    front of the waiting queue, and its progress is preserved."""
    bm = BlockManager(num_blocks=4, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4))
    a = _req("a", n_prompt=4, max_new=8, arrival=1.0)
    b = _req("b", n_prompt=4, max_new=8, arrival=2.0)
    for r in (a, b):
        s.add(r)
    batch = s.schedule()       # both prefill: 2 blocks each, cache full
    assert [r.request_id for r in batch.requests] == ["a", "b"]
    for r in batch.requests:
        r.num_cached += len(r.tokens_to_run())
        r.append_token(7)
    batch = s.schedule()       # both need a slot; only b's eviction frees one
    assert batch.kind == "decode"
    assert [r.request_id for r in batch.requests] == ["a"]
    assert [r.request_id for r in batch.preempted] == ["b"]
    assert b.status == RequestStatus.WAITING
    assert b.num_cached == 0 and len(b.tokens) == 5  # progress kept
    assert b.num_preemptions == 1
    assert s.waiting[0] is b
    bm.check_invariants()


def test_scheduler_starvation_guard_all_requests_finish():
    """More requests than max_num_seqs and a tight cache: every request
    (including preempted ones) must still run to completion — FCFS
    admission + evict-from-the-back guarantees forward progress."""
    bm = BlockManager(num_blocks=8, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=2,
                                      max_batched_tokens=16))
    reqs = [_req(i, n_prompt=3 + (i % 3), max_new=4, arrival=float(i))
            for i in range(6)]
    for r in reqs:
        s.add(r)
    _drive(s)
    assert all(r.is_finished for r in reqs)
    assert bm.num_free_blocks == 8


def test_scheduler_randomized_storm():
    """Random arrivals + tight memory: preempted requests re-admit and
    finish; block accounting stays exact throughout."""
    rng = np.random.default_rng(1)
    bm = BlockManager(num_blocks=10, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=3,
                                      max_batched_tokens=32))
    reqs = []
    for it in range(400):
        if len(reqs) < 20 and rng.random() < 0.2:
            r = _req(f"s{len(reqs)}", n_prompt=int(rng.integers(1, 8)),
                     max_new=int(rng.integers(1, 6)), arrival=float(it))
            reqs.append(r)
            s.add(r)
        if not s.has_unfinished():
            continue
        batch = s.schedule()
        for r in batch.requests:
            r.num_cached += len(r.tokens_to_run())
            if r.append_token(int(rng.integers(0, 100))):
                s.finish(r)
        bm.check_invariants()
    _drive(s, max_iters=500)
    assert len(reqs) == 20 and all(r.is_finished for r in reqs)
    assert bm.num_free_blocks == 10


def test_scheduler_abort():
    bm = BlockManager(num_blocks=8, block_size=2)
    s = Scheduler(bm, SchedulerConfig(max_num_seqs=4))
    a, b = _req("a", 4), _req("b", 4)
    s.add(a), s.add(b)
    s.schedule()
    assert s.abort("a") and not s.abort("zz")
    assert a.status == RequestStatus.FINISHED
    assert "a" not in [r.request_id for r in s.running]
    bm.check_invariants()


def test_request_and_sampling_validation():
    with pytest.raises(ValueError, match="empty prompt"):
        Request(request_id="x", prompt_ids=[])
    with pytest.raises(ValueError):
        SamplingParams(max_new_tokens=0)
    with pytest.raises(ValueError):
        SamplingParams(top_p=0.0)
    r = _req("x", 3, max_new=2)
    assert r.append_token(5) is False
    assert r.append_token(6) is True      # max_new_tokens reached
    assert r.is_finished and r.generated == [5, 6]
    r2 = Request(request_id="y", prompt_ids=[1, 2],
                 sampling=SamplingParams(max_new_tokens=9,
                                         eos_token_id=42))
    assert r2.append_token(41) is False
    assert r2.append_token(42) is True    # EOS
