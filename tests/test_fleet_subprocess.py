"""True multiprocess fleet e2e (slow tier).

The loopback tests in test_fleet_transport.py pin the protocol and the
recovery math; these pin the parts only real processes can: SIGKILL
delivered by the kernel, SIGTERM caught by the worker's preemption
monitor, supervisor restart generations, and hang detection through
FileStore heartbeats written by an actual worker heartbeat thread.

Every parity assert compares client-visible token streams against an
uninterrupted single-engine run of the same tiny model (workers build
the identical model from ``WorkerSpec(seed=0)``).
"""
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import (
    FleetConfig, FleetRouter, ReplicaSupervisor, SupervisorConfig,
    WorkerSpec,
)

pytestmark = pytest.mark.slow

_ENGINE = {"block_size": 4, "max_num_seqs": 8, "max_model_len": 64,
           "drain_grace_s": 0.0}


@pytest.fixture(scope="module")
def tiny_model():
    # the reference twin of what each worker builds from its spec
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    return model


def _mk_fleet(tmp_path, n=2, **cfg_kw):
    cfg_kw.setdefault("store_dir", str(tmp_path / "store"))
    sup = ReplicaSupervisor(WorkerSpec(model="tiny_llama", seed=0,
                                       engine=dict(_ENGINE)),
                            SupervisorConfig(**cfg_kw))
    handles = [sup.spawn() for _ in range(n)]
    router = FleetRouter(handles, FleetConfig(),
                         registry=sup.registry)
    sup.router = router   # restarts from poll() attach themselves
    return sup, router


def _prompts(model, n, seed=11):
    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(0, model.config.vocab_size,
                                       size=3 + i % 4)))
            for i in range(n)]


def _reference(model, prompts, sp, ids):
    eng = LLMEngine(model, EngineConfig(**_ENGINE))
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    while eng.has_unfinished():
        eng.step()
    return {rid: list(eng.get_request(rid).generated) for rid in ids}


def _drain(router, max_steps=300):
    outs = []
    for _ in range(max_steps):
        if not router.has_unfinished():
            return outs
        outs.extend(router.step())
    raise AssertionError("router failed to converge")


_SP = SamplingParams(max_new_tokens=8, temperature=0.8, top_p=0.9)


def test_sigkill_mid_decode_resume_and_supervised_restart(tiny_model,
                                                          tmp_path):
    sup, router = _mk_fleet(tmp_path, restart_backoff_s=0.05)
    try:
        prompts = _prompts(tiny_model, 5)
        ids = [f"k{i}" for i in range(5)]
        ref = _reference(tiny_model, prompts, _SP, ids)
        outs = []
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=_SP)
        for _ in range(3):
            outs.extend(router.step())        # some tokens in flight
        victim = sup.handles()[0]
        os.kill(victim.proc.pid, signal.SIGKILL)
        outs += _drain(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert all(final[r].finish_reason == "length" for r in ids)
        assert victim.proc.wait(timeout=10) == -signal.SIGKILL
        assert not victim.alive
        assert router.num_replicas_dead == 1
        assert router.num_handoffs >= 1
        # exactly-once: each client-visible token stream has no extras
        counts = {}
        for o in outs:
            if o.token is not None:
                counts[o.request_id] = counts.get(o.request_id, 0) + 1
        assert counts == {r: len(ref[r]) for r in ids}

        # the supervisor notices and relaunches under a new generation
        deadline = time.monotonic() + 120.0
        events = []
        while time.monotonic() < deadline:
            events += sup.poll()
            if any(e["event"] == "restarted" for e in events):
                break
            time.sleep(0.05)
        restarted = [e for e in events if e["event"] == "restarted"]
        assert restarted and restarted[0]["replica_id"] == "w0-g1"
        # ...and serves traffic: same id + prompt as a fresh single-
        # engine run (sampling streams are seeded per request id)
        ref2 = _reference(tiny_model, [prompts[0]], _SP, ["k5"])
        router.add_request("k5", prompts[0], sampling=_SP)
        outs2 = _drain(router)
        fin2 = {o.request_id: o for o in outs2 if o.finished}
        assert fin2["k5"].finish_reason == "length"
        assert list(fin2["k5"].generated) == ref2["k5"]
    finally:
        sup.shutdown()


def test_sigterm_drain_hands_off_and_worker_exits_zero(tiny_model,
                                                       tmp_path):
    sup, router = _mk_fleet(tmp_path)
    try:
        prompts = _prompts(tiny_model, 4, seed=13)
        ids = [f"d{i}" for i in range(4)]
        ref = _reference(tiny_model, prompts, _SP, ids)
        outs = []
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=_SP)
        for _ in range(2):
            outs.extend(router.step())
        victim = sup.handles()[0]
        sup.stop_worker("w0")                 # SIGTERM, no restart
        outs += _drain(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert all(final[r].finish_reason == "length" for r in ids)
        assert router.num_replicas_dead == 0  # drain is not a death
        # graceful exit: worker leaves on its own once drained
        assert victim.proc.wait(timeout=60) == 0
        assert victim.retiring                # last reply said drained_out
        assert victim.replica_id not in [     # reaped, not killed
            h.replica_id for h in router.replicas]
    finally:
        sup.shutdown()


def test_hung_worker_detected_by_heartbeat_ttl(tiny_model, tmp_path):
    # SIGSTOP: process alive, socket open, heartbeat thread frozen —
    # the failure only the registry TTL can see. The short rng_state
    # deadline bounds the one post-mortem query kill_replica makes
    # before the handle is marked dead and the cache takes over.
    sup, router = _mk_fleet(tmp_path, ttl_s=1.5, hb_interval_s=0.2,
                            deadlines={"rng_state": 0.75})
    try:
        prompts = _prompts(tiny_model, 4, seed=17)
        ids = [f"h{i}" for i in range(4)]
        ref = _reference(tiny_model, prompts, _SP, ids)
        outs = []
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=_SP)
        for _ in range(3):
            outs.extend(router.step())        # dispatch + observe beats
        victim = sup.handles()[0]
        had_work = bool(router._assigned.get(victim.replica_id))
        os.kill(victim.proc.pid, signal.SIGSTOP)
        time.sleep(2.5)                       # silence > ttl_s
        outs += _drain(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert all(final[r].finish_reason == "length" for r in ids)
        assert not victim.alive               # TTL sweep declared it
        assert router.num_replicas_dead == 1
        if had_work:
            assert router.num_handoffs >= 1
        os.kill(victim.proc.pid, signal.SIGKILL)  # SIGTERM can't land
    finally:
        sup.shutdown()


def test_disagg_fleet_sigkill_and_drain_block_transfer(tiny_model,
                                                       tmp_path):
    """Disaggregated 2-prefill + 2-decode fleet, real processes.

    Phase 1 — mid-decode SIGKILL of a decode replica: its requests
    resume by recompute, token streams stay bit-identical, and the
    supervisor restarts the slot with its sticky ``decode`` role
    (advertised back through the worker's registry heartbeat meta).

    Phase 2 — SIGTERM drain of a decode replica: the drain reply
    piggybacks the parked KV, the peer imports it, and the hand-off
    recomputes ZERO prompt tokens (counter-asserted)."""
    sup = ReplicaSupervisor(WorkerSpec(model="tiny_llama", seed=0,
                                       engine=dict(_ENGINE)),
                            SupervisorConfig(
                                store_dir=str(tmp_path / "store"),
                                restart_backoff_s=0.05))
    handles = [sup.spawn(role="prefill"), sup.spawn(role="prefill"),
               sup.spawn(role="decode"), sup.spawn(role="decode")]
    router = FleetRouter(handles, FleetConfig(), registry=sup.registry)
    sup.router = router
    try:
        # -- phase 1: SIGKILL a decode replica mid-decode -------------
        prompts = _prompts(tiny_model, 6, seed=23)
        ids = [f"z{i}" for i in range(6)]
        ref = _reference(tiny_model, prompts, _SP, ids)
        outs = []
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=_SP)
        for _ in range(4):
            outs.extend(router.step())   # prefills shipped, decoding
        victim = next(h for h in sup.handles() if h.role == "decode")
        os.kill(victim.proc.pid, signal.SIGKILL)
        outs += _drain(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert all(final[r].finish_reason == "length" for r in ids)
        counts = {}
        for o in outs:
            if o.token is not None:
                counts[o.request_id] = counts.get(o.request_id, 0) + 1
        assert counts == {r: len(ref[r]) for r in ids}
        assert router.num_kv_ship_requests >= 1
        assert router.num_replicas_dead == 1

        # the slot restarts with its role intact...
        deadline = time.monotonic() + 120.0
        events = []
        while time.monotonic() < deadline:
            events += sup.poll()
            if any(e["event"] == "restarted" for e in events):
                break
            time.sleep(0.05)
        restarted = [e for e in events if e["event"] == "restarted"]
        assert restarted
        fresh = next(h for h in sup.handles()
                     if h.replica_id == restarted[0]["replica_id"])
        assert fresh.role == "decode"
        # ...and advertises it through its own heartbeat meta, so a
        # rebuilt router could re-learn the topology from the registry
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            rec = sup.registry.record(fresh.replica_id)
            if rec and rec.get("meta", {}).get("role"):
                break
            time.sleep(0.05)
        assert rec["meta"]["role"] == "decode"

        # -- phase 2: SIGTERM drain rides the block-transfer path -----
        recomputed_before = router.num_tokens_recomputed
        ships_before = router.num_kv_ship_requests
        prompts2 = _prompts(tiny_model, 4, seed=29)
        ids2 = [f"y{i}" for i in range(4)]
        ref2 = _reference(tiny_model, prompts2, _SP, ids2)
        outs2 = []
        for rid, p in zip(ids2, prompts2):
            router.add_request(rid, p, sampling=_SP)
        for _ in range(4):
            outs2.extend(router.step())  # shipped + decoding
        # SIGTERM whichever decode worker holds requests right now
        target = next(
            (h for h in sup.handles()
             if h.role == "decode" and h.alive
             and router._assigned.get(h.replica_id)), None)
        if target is not None:
            slot_name = target.replica_id.rsplit("-g", 1)[0]
            sup.stop_worker(slot_name)
        outs2 += _drain(router)
        final2 = {o.request_id: o for o in outs2 if o.finished}
        assert {r: list(final2[r].generated) for r in ids2} == ref2
        assert all(final2[r].finish_reason == "length" for r in ids2)
        assert router.num_kv_ship_requests > ships_before
        # the drain hand-off shipped blocks instead of recomputing
        assert router.num_tokens_recomputed == recomputed_before
    finally:
        sup.shutdown()
