"""Tiered KV subsystem pins (ISSUE 19).

Layers, cheapest first:

* :class:`KVTiersConfig` parsing/validation and the engine-side config
  guards (the bucketed ``ragged=False`` fallback is degree-1-only and
  untierable; tiering without the trie is a contradiction);
* BlockManager tier mechanics — virtual host entries, the ordered
  demote/promote move ledger, chain demote (slots park cached-free and
  UNOWNED), chain evict, exact invariants throughout;
* over-device-pool serving: one request whose context exceeds device
  HBM completes greedy- AND sampled-token-identical to an
  unconstrained single-engine reference — demotion instead of
  eviction, promotion instead of recompute;
* session park/resume: a multi-turn continuation re-prefills ZERO
  prompt tokens (counter-asserted), partial-tail bytes restore, a
  diverged prompt is a clean refusal that keeps the session;
* fleet: router park/resume with holder affinity, the host-pressure
  offload over the prefix ticket ladder (exactly one counted outcome
  per issued ticket), and a dead holder degrading resume to recompute
  — never loss, never duplication;
* the randomized tier-migration storm: interleaved demote / promote /
  park / resume / abort / peer-fault waves with pool invariants
  checked per wave and full greedy+sampled parity at the end.
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.block_manager import BlockManager
from paddle_tpu.serving.fleet import (
    FleetConfig, FleetRouter, InProcessReplica,
)
from paddle_tpu.serving.kvtier import KVTiersConfig, TieredKVStore
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _no_fault_leak():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    return model


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_num_seqs", 8)
    kw.setdefault("max_model_len", 96)
    kw.setdefault("drain_grace_s", 0.0)
    return EngineConfig(**kw)


def _tiered_cfg(**kw):
    kw.setdefault("kv_tiers", True)
    return _ecfg(**kw)


def _run(eng, max_steps=600):
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < max_steps
    if eng._kvtier is not None:
        eng._kvtier.apply_moves()
    eng.block_manager.check_invariants()


def _drain_router(router, max_steps=400):
    outs = []
    for _ in range(max_steps):
        if not router.has_unfinished():
            return outs
        outs.extend(router.step())
    raise AssertionError("router failed to converge")


def _reference(model, prompts_by_rid, cfg=None):
    """Unconstrained single-engine oracle: big device pool, no tiers.
    Request ids matter — the sampling stream seeds from the id."""
    eng = LLMEngine(model, cfg or _ecfg(num_blocks=256))
    for rid, (prompt, sp) in prompts_by_rid.items():
        eng.add_request(rid, prompt, sampling=sp)
    _run(eng)
    return {rid: list(eng.get_request(rid).generated)
            for rid in prompts_by_rid}


GREEDY = SamplingParams(max_new_tokens=8)
SAMPLED = SamplingParams(max_new_tokens=8, temperature=0.8, top_k=20,
                         seed=7)


# ---------------------------------------------------------------------------
# config + guards
# ---------------------------------------------------------------------------

class TestTiersConfig:
    def test_from_any_forms(self):
        assert KVTiersConfig.from_any(None) is None
        assert KVTiersConfig.from_any(False) is None
        cfg = KVTiersConfig.from_any(True)
        assert isinstance(cfg, KVTiersConfig)
        cfg = KVTiersConfig.from_any({"num_host_blocks": 12,
                                      "host_watermark": 0.5})
        assert cfg.num_host_blocks == 12
        assert cfg.host_watermark == 0.5
        same = KVTiersConfig(max_sessions=3)
        assert KVTiersConfig.from_any(same) is same
        with pytest.raises(ValueError):
            KVTiersConfig.from_any("yes")

    def test_validation(self):
        with pytest.raises(ValueError):
            KVTiersConfig(host_watermark=1.5)
        with pytest.raises(ValueError):
            KVTiersConfig(num_host_blocks=0)
        with pytest.raises(ValueError):
            KVTiersConfig(max_sessions=0)

    def test_bucketed_fallback_rejects_tiers(self, tiny_model):
        with pytest.raises(ValueError, match="ragged"):
            LLMEngine(tiny_model, _ecfg(ragged=False, kv_tiers=True))

    def test_bucketed_fallback_rejects_tp(self, tiny_model):
        with pytest.raises(ValueError, match="degree-1"):
            LLMEngine(tiny_model, _ecfg(ragged=False, tp_degree=2))

    def test_tiers_require_prefix_cache(self, tiny_model):
        with pytest.raises(ValueError, match="prefix"):
            LLMEngine(tiny_model, _tiered_cfg(prefix_cache=False))

    def test_tiers_force_host_pool(self, tiny_model):
        eng = LLMEngine(tiny_model, _tiered_cfg(num_blocks=8))
        assert eng.cfg.num_host_blocks >= eng.cfg.num_blocks
        assert eng.block_manager.reachable_blocks > eng.cfg.num_blocks


# ---------------------------------------------------------------------------
# BlockManager tier mechanics
# ---------------------------------------------------------------------------

def _commit_chain(bm, rid, tokens):
    bm.allocate(rid, len(tokens), tokens=tokens)
    bm.commit_prefix(rid, tokens, len(tokens))


class TestTierMechanics:
    def _bm(self, **kw):
        kw.setdefault("num_blocks", 8)
        kw.setdefault("block_size", 4)
        kw.setdefault("num_host_blocks", 8)
        kw.setdefault("enable_prefix_cache", True)
        kw.setdefault("tiered", True)
        return BlockManager(**kw)

    def test_demote_cached_free_moves_cold_end(self):
        bm = self._bm()
        tokens = list(range(16))
        _commit_chain(bm, "r0", tokens)
        bm.free("r0")
        bm.check_invariants()
        free_before = bm.num_uncached_free_blocks
        got = bm.demote_cached_free(2)
        assert got == 2
        moves = bm.take_tier_moves()
        assert [m[0] for m in moves] == ["demote", "demote"]
        assert bm.num_demotes == 2
        assert bm.num_uncached_free_blocks == free_before + 2
        # content stayed discoverable: a fresh allocate shares it, with
        # the shared entries now naming HOST slots (virtual ids)
        table = bm.allocate("r1", 16, tokens=tokens)
        assert bm.last_hit_tokens > 0
        assert any(bm.is_host_entry(e) for e in table)
        # the capped full-match hit COWs the shared tail block, and a
        # COW off a host-tier source records a promote — drain it
        bm.take_tier_moves()
        bm.check_invariants()

    def test_promote_blocks_round_trip(self):
        bm = self._bm()
        tokens = list(range(16))
        _commit_chain(bm, "r0", tokens)
        bm.free("r0")
        assert bm.demote_cached_free(4) == 4
        bm.take_tier_moves()
        table = bm.allocate("r1", 16, tokens=tokens)
        virt = [e for e in table if bm.is_host_entry(e)]
        assert virt
        # the allocate above already promoted once (capped-hit COW off
        # the shared host tail) — assert the DELTA from promote_blocks
        before = bm.num_promotes
        promoted = bm.promote_blocks("r1", len(virt))
        assert promoted == len(virt)
        moves = bm.take_tier_moves()
        assert all(m[0] == "promote" for m in moves)
        assert bm.num_promotes - before == promoted
        assert not any(bm.is_host_entry(e) for e in
                       bm.block_table("r1"))
        bm.check_invariants()

    def test_demote_chain_parks_slots_unowned(self):
        bm = self._bm()
        tokens = list(range(16))
        _commit_chain(bm, "r0", tokens)
        bm.free("r0")
        demoted = bm.demote_chain(tokens, len(tokens))
        assert demoted == 4
        bm.take_tier_moves()
        # parked slots are cached-free: registered content, refcount 0,
        # still sitting in the host free list (capacity can reclaim)
        st = bm.host_tier_stats()
        assert st["registered"] == 4
        assert st["used"] == 0
        assert st["free"] == bm.num_host_blocks
        bm.check_invariants()
        # a shared resume bumps them to owned
        table, hit, tail = bm.resume_chain("r1", tokens + [99], 16,
                                           want_tail=False)
        assert hit == 16
        assert bm.host_tier_stats()["used"] == 4
        bm.check_invariants()

    def test_demote_chain_skips_referenced_blocks(self):
        bm = self._bm()
        tokens = list(range(16))
        _commit_chain(bm, "r0", tokens)  # still owned by r0
        assert bm.demote_chain(tokens, len(tokens)) == 0
        bm.check_invariants()

    def test_evict_chain_drops_both_tiers(self):
        bm = self._bm()
        tokens = list(range(16))
        _commit_chain(bm, "r0", tokens)
        bm.free("r0")
        bm.demote_chain(tokens, len(tokens))
        bm.take_tier_moves()
        dropped = bm.evict_chain(tokens, len(tokens))
        assert dropped == 4
        st = bm.host_tier_stats()
        assert st["registered"] == 0
        assert bm.match_prefix(tokens) == 0
        bm.check_invariants()

    def test_move_ledger_preserves_order(self):
        bm = self._bm(num_blocks=4, num_host_blocks=4)
        tokens = list(range(16))
        _commit_chain(bm, "r0", tokens)
        bm.free("r0")
        bm.demote_chain(tokens, len(tokens))
        # resume promotes into blocks the demote just vacated: the
        # ledger must replay demotes before the promotes that reuse
        # their source blocks
        table, hit, _ = bm.resume_chain("r1", tokens + [99], 16,
                                        want_tail=False)
        bm.promote_blocks("r1", 4)
        moves = bm.take_tier_moves()
        kinds = [m[0] for m in moves]
        assert kinds.index("promote") > kinds.index("demote")
        bm.check_invariants()


# ---------------------------------------------------------------------------
# over-device-pool serving
# ---------------------------------------------------------------------------

class TestOverPool:
    @pytest.mark.parametrize("sp", [GREEDY, SAMPLED],
                             ids=["greedy", "sampled"])
    def test_context_exceeds_device_pool(self, tiny_model, sp):
        """40-token prompt + 12 new = 13 blocks against an 8-block
        device pool: admission counts reachable-tier blocks, the
        scheduler demotes the request's own cold prefix to make room,
        and the output is token-identical to an unconstrained run."""
        sp = SamplingParams(**{**sp.__dict__, "max_new_tokens": 12})
        rng = np.random.default_rng(3)
        prompt = [int(t) for t in rng.integers(0, 255, size=40)]
        eng = LLMEngine(tiny_model, _tiered_cfg(num_blocks=8))
        assert eng.block_manager.reachable_blocks >= 13
        eng.add_request("big", prompt, sampling=sp)
        _run(eng)
        got = list(eng.get_request("big").generated)
        assert eng.block_manager.num_demotes > 0
        ref = _reference(tiny_model, {"big": (prompt, sp)})
        assert got == ref["big"]

    def test_admission_rejects_past_reachable(self, tiny_model):
        eng = LLMEngine(tiny_model, _tiered_cfg(
            num_blocks=4, kv_tiers={"num_host_blocks": 4},
            max_model_len=96))
        rng = np.random.default_rng(4)
        prompt = [int(t) for t in rng.integers(0, 255, size=60)]
        # past reachable_blocks the request could never be served even
        # alone — the engine refuses at submission, not via an output
        with pytest.raises(ValueError, match="reachable"):
            eng.add_request("huge", prompt,
                            sampling=SamplingParams(max_new_tokens=30))


# ---------------------------------------------------------------------------
# session park / resume (single engine)
# ---------------------------------------------------------------------------

class TestParkResume:
    @pytest.mark.parametrize("sp", [GREEDY, SAMPLED],
                             ids=["greedy", "sampled"])
    @pytest.mark.parametrize("plen", [21, 22],
                             ids=["aligned-tail", "partial-tail"])
    def test_zero_prompt_recompute(self, tiny_model, sp, plen):
        rng = np.random.default_rng(plen)
        prompt = [int(t) for t in rng.integers(0, 255, size=plen)]
        eng = LLMEngine(tiny_model, _tiered_cfg(num_blocks=16))
        eng.add_request("turn1", prompt, sampling=sp)
        _run(eng)
        turn1 = list(eng.get_request("turn1").generated)
        eng.release_request("turn1")  # sessions survive release
        info = eng.park_session("turn1")
        assert info is not None and info["parked"]
        assert eng.park_session("turn1")["parked"]  # idempotent

        prompt2 = prompt + turn1 + [int(t) for t in
                                    rng.integers(0, 255, size=5)]
        hit = eng.resume_session("turn2", "turn1", prompt2, sampling=sp)
        assert hit == info["tokens_covered"]
        _run(eng)
        turn2 = list(eng.get_request("turn2").generated)
        kvt = eng._kvtier
        assert kvt.num_resume_recomputed_tokens == 0
        assert kvt.num_park_resumes == 1
        assert eng.metrics.snapshot()["serving_kv_tier_park_resumes"] \
            == 1
        ref = _reference(tiny_model, {"turn2": (prompt2, sp)})
        assert turn2 == ref["turn2"]

    def test_resume_mismatch_keeps_session(self, tiny_model):
        rng = np.random.default_rng(9)
        prompt = [int(t) for t in rng.integers(0, 255, size=12)]
        eng = LLMEngine(tiny_model, _tiered_cfg(num_blocks=16))
        eng.add_request("s", prompt, sampling=GREEDY)
        _run(eng)
        eng.park_session("s")
        bad = list(reversed(prompt)) + [1, 2, 3]
        with pytest.raises(ValueError, match="extend"):
            eng.resume_session("s2", "s", bad, sampling=GREEDY)
        assert eng.session_info("s") is not None  # not consumed

    def test_resume_after_eviction_recomputes(self, tiny_model):
        """The degradation floor: the parked chain was reclaimed for
        capacity — resume admits COLD (full re-prefill), counted, and
        still token-identical."""
        rng = np.random.default_rng(10)
        prompt = [int(t) for t in rng.integers(0, 255, size=16)]
        eng = LLMEngine(tiny_model, _tiered_cfg(num_blocks=16))
        eng.add_request("s", prompt, sampling=GREEDY)
        _run(eng)
        turn1 = list(eng.get_request("s").generated)
        eng.park_session("s")
        # reclaim the chain out from under the park
        rec = eng._kvtier.sessions["s"]
        eng.block_manager.evict_chain(rec.tokens, rec.covered)
        prompt2 = prompt + turn1 + [5, 6, 7]
        hit = eng.resume_session("s2", "s", prompt2, sampling=GREEDY)
        assert hit == 0
        _run(eng)
        assert eng._kvtier.num_resume_recomputes == 1
        assert eng._kvtier.num_resume_recomputed_tokens > 0
        # both counters are part of the stats() vocabulary (they were
        # bumped-but-never-read before PR 20's drift linter)
        stats = eng.tier_stats()
        assert stats["resume_recomputes"] == 1
        assert stats["resume_recomputed_tokens"] > 0
        ref = _reference(tiny_model, {"s2": (prompt2, GREEDY)})
        assert list(eng.get_request("s2").generated) == ref["s2"]

    def test_torn_tail_restore_frees_resumed_claim(self, tiny_model):
        """A tail restore that dies mid-copy must free the whole
        resumed chain claim (the leaked-resource-on-raise class this
        PR's linter flags) while keeping the session record, so the
        SAME resume retries cleanly."""
        rng = np.random.default_rng(13)
        prompt = [int(t) for t in rng.integers(0, 255, size=22)]
        eng = LLMEngine(tiny_model, _tiered_cfg(num_blocks=16))
        eng.add_request("s", prompt, sampling=GREEDY)
        _run(eng)
        turn1 = list(eng.get_request("s").generated)
        eng.release_request("s")
        info = eng.park_session("s")
        assert info is not None and info["parked"]
        prompt2 = prompt + turn1 + [1, 2, 3]
        def torn(*a):
            raise RuntimeError("torn tail copy")
        eng._pin_caches = torn          # dies inside the tail restore
        try:
            with pytest.raises(RuntimeError, match="torn tail copy"):
                eng.resume_session("s2", "s", prompt2, sampling=GREEDY)
        finally:
            del eng._pin_caches         # back to the class method
        bm = eng.block_manager
        assert not bm.has_table("s2")     # the claim did not strand
        bm.check_invariants()
        assert eng.session_info("s") is not None  # kept for the retry
        hit = eng.resume_session("s2", "s", prompt2, sampling=GREEDY)
        assert hit == info["tokens_covered"]
        _run(eng)
        ref = _reference(tiny_model, {"s2": (prompt2, GREEDY)})
        assert list(eng.get_request("s2").generated) == ref["s2"]

    def test_session_bound(self, tiny_model):
        eng = LLMEngine(tiny_model, _tiered_cfg(
            num_blocks=32, kv_tiers={"max_sessions": 2}))
        rng = np.random.default_rng(11)
        for i in range(3):
            p = [int(t) for t in rng.integers(0, 255, size=8)]
            eng.add_request(f"s{i}", p, sampling=GREEDY)
            _run(eng)
        kvt = eng._kvtier
        assert len(kvt.sessions) == 2
        assert "s0" not in kvt.sessions  # oldest out

    def test_untired_engine_refuses_sessions(self, tiny_model):
        eng = LLMEngine(tiny_model, _ecfg())
        with pytest.raises(ValueError, match="kv_tiers"):
            eng.park_session("nope")
        assert eng.tier_stats() is None


# ---------------------------------------------------------------------------
# fleet: park / resume / offload / holder death
# ---------------------------------------------------------------------------

def _fleet(model, n=2, fcfg=None, peers=False, **ekw):
    reps = [InProcessReplica(model, _tiered_cfg(**ekw),
                             replica_id=f"rep{i}") for i in range(n)]
    if peers:
        for r in reps:
            r.start_peer()
    return reps, FleetRouter(reps, fcfg or FleetConfig())


class TestFleetSessions:
    def test_park_resume_holder_affinity(self, tiny_model):
        reps, router = _fleet(tiny_model, num_blocks=16)
        rng = np.random.default_rng(20)
        prompt = [int(t) for t in rng.integers(0, 255, size=21)]
        rid = router.add_request("t1", prompt, sampling=GREEDY)
        _drain_router(router)
        fr = router.get_request(rid)
        turn1, holder = list(fr.generated), fr.replica_id
        assert router.park_session(rid) is not None
        heng = next(r for r in reps
                    if r.replica_id == holder).engine
        prompt2 = prompt + turn1 + [1, 2, 3, 4, 5]
        rid2 = router.resume_session(rid, prompt2, sampling=GREEDY)
        _drain_router(router)
        fr2 = router.get_request(rid2)
        assert fr2.replica_id == holder  # affinity beat load balance
        assert router.num_session_resumes == 1
        assert router.num_session_resume_recomputes == 0
        assert heng._kvtier.num_resume_recomputed_tokens == 0
        ref = _reference(tiny_model, {rid2: (prompt2, GREEDY)})
        assert list(fr2.generated) == ref[rid2]
        snap = router.snapshot()
        assert snap["fleet_session_parks"] == 1
        assert snap["fleet_session_resumes"] == 1

    def test_offload_past_watermark(self, tiny_model):
        reps, router = _fleet(
            tiny_model, peers=True,
            fcfg=FleetConfig(tier_offload_watermark=1e-6),
            num_blocks=16)
        rng = np.random.default_rng(21)
        prompt = [int(t) for t in rng.integers(0, 255, size=21)]
        rid = router.add_request("sess", prompt, sampling=GREEDY)
        _drain_router(router)
        fr = router.get_request(rid)
        turn1, holder = list(fr.generated), fr.replica_id
        src = next(r for r in reps if r.replica_id == holder)
        dst = next(r for r in reps if r.replica_id != holder)
        assert router.park_session(rid) is not None
        router.step()  # offload sweep fires past the watermark
        assert router.num_session_offloads == 1
        assert router._sessions[rid]["holder"] == dst.replica_id
        assert src.engine.session_info(rid) is None
        assert dst.engine.session_info(rid) is not None
        assert src.engine.tier_stats()["peer_blocks"] > 0
        # ticket partition stays exact through the prefix-ladder ship
        assert sum(router.ticket_outcomes.values()) \
            == router.num_tickets_issued
        prompt2 = prompt + turn1 + [9, 8, 7]
        rid2 = router.resume_session(rid, prompt2, sampling=GREEDY)
        _drain_router(router)
        fr2 = router.get_request(rid2)
        assert fr2.replica_id == dst.replica_id
        assert dst.engine._kvtier.num_resume_recomputed_tokens == 0
        ref = _reference(tiny_model, {rid2: (prompt2, GREEDY)})
        assert list(fr2.generated) == ref[rid2]
        for r in reps:
            r.close_peer()

    def test_dead_holder_degrades_to_recompute(self, tiny_model):
        reps, router = _fleet(tiny_model, num_blocks=16)
        rng = np.random.default_rng(22)
        prompt = [int(t) for t in rng.integers(0, 255, size=21)]
        rid = router.add_request("t1", prompt, sampling=GREEDY)
        _drain_router(router)
        fr = router.get_request(rid)
        turn1, holder = list(fr.generated), fr.replica_id
        assert router.park_session(rid) is not None
        router.kill_replica(holder, "fault")
        assert rid not in router._sessions  # pruned with the corpse
        prompt2 = prompt + turn1 + [4, 4, 4]
        rid2 = router.resume_session(rid, prompt2, sampling=GREEDY)
        _drain_router(router)
        fr2 = router.get_request(rid2)
        assert fr2.finish_reason in ("stop", "length")
        assert fr2.replica_id != holder
        assert router.num_session_resumes == 0
        assert router.num_session_resume_recomputes == 1
        ref = _reference(tiny_model, {rid2: (prompt2, GREEDY)})
        assert list(fr2.generated) == ref[rid2]


# ---------------------------------------------------------------------------
# randomized tier-migration storm
# ---------------------------------------------------------------------------

class TestMigrationStorm:
    def test_storm(self, tiny_model):
        rng = np.random.default_rng(42)
        reps, router = _fleet(
            tiny_model, peers=True,
            fcfg=FleetConfig(tier_offload_watermark=0.05),
            num_blocks=16, max_num_seqs=4)
        seq = itertools.count()
        expectations = {}   # rid -> (prompt, sampling)
        finished = {}       # rid -> generated tokens
        aborted = set()
        resumable = []      # finished rids not yet resumed

        def sp_for():
            if rng.random() < 0.5:
                return GREEDY
            return SamplingParams(max_new_tokens=8, temperature=0.8,
                                  top_k=20, seed=int(rng.integers(1e6)))

        def absorb(outs):
            for o in outs:
                if o.finished and o.finish_reason in ("stop", "length"):
                    finished[o.request_id] = list(o.generated)
                    resumable.append(o.request_id)

        def check_wave():
            for r in reps:
                if r.alive and r.engine._kvtier is not None:
                    r.engine._kvtier.apply_moves()
                    r.engine.block_manager.check_invariants()
            assert sum(router.ticket_outcomes.values()) \
                == router.num_tickets_issued

        for wave in range(4):
            for _ in range(int(rng.integers(2, 5))):
                sp = sp_for()
                if resumable and rng.random() < 0.5:
                    sid = resumable.pop(int(rng.integers(
                        len(resumable))))
                    base = expectations[sid][0] + finished[sid]
                    prompt = base + [int(t) for t in rng.integers(
                        0, 255, size=int(rng.integers(3, 8)))]
                    if rng.random() < 0.7:
                        router.park_session(sid)
                    rid = router.resume_session(sid, prompt,
                                                sampling=sp)
                else:
                    prompt = [int(t) for t in rng.integers(
                        0, 255, size=int(rng.integers(8, 30)))]
                    rid = router.add_request(f"storm-{next(seq)}",
                                             prompt, sampling=sp)
                expectations[rid] = (prompt, sp)
            if rng.random() < 0.4:
                # one peer-plane fault for this wave: offload ships
                # degrade a rung, never lose the session
                faults.install("fleet.peer_connect_fail:flag*1")
            for _ in range(int(rng.integers(2, 6))):
                absorb(router.step())
                open_rids = list(router._open)
                if open_rids and rng.random() < 0.15:
                    victim = open_rids[int(rng.integers(
                        len(open_rids)))]
                    router.abort_request(victim)
                    aborted.add(victim)
            faults.clear()
            check_wave()

        absorb(_drain_router(router))
        check_wave()

        todo = {rid: expectations[rid] for rid in finished
                if rid not in aborted}
        assert len(todo) >= 6  # the storm actually exercised traffic
        ref = _reference(tiny_model, todo)
        for rid in todo:
            assert finished[rid] == ref[rid], rid
        for r in reps:
            r.close_peer()
