"""Model-zoo smoke tests: every family builds, forwards at the right
shape, and takes a compiled train step (reference vision/models — 14
families; pattern of test/legacy_test vision model tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.vision import models as M


def _smoke(model, in_shape=(1, 3, 64, 64), n_classes=10, eval_too=False):
    """One train-mode forward per family (each distinct graph costs an
    XLA compile on the CPU test platform, so eval-mode is exercised for
    a single representative family only)."""
    paddle.seed(0)
    model.train()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(*in_shape).astype(np.float32))
    out = model(x)
    if isinstance(out, tuple):  # googlenet aux heads
        out = out[0]
    assert list(out.shape) == [in_shape[0], n_classes], out.shape
    if eval_too:
        model.eval()
        out2 = model(x)
        if isinstance(out2, tuple):
            out2 = out2[0]
        assert list(out2.shape) == [in_shape[0], n_classes]


def test_lenet():
    _smoke(M.LeNet(num_classes=10), in_shape=(1, 1, 28, 28), eval_too=True)


def test_alexnet():
    _smoke(M.alexnet(num_classes=10), in_shape=(1, 3, 64, 64))


def test_vgg11():
    _smoke(M.vgg11(num_classes=10), in_shape=(1, 3, 32, 32))


def test_vgg16_bn():
    _smoke(M.vgg16(batch_norm=True, num_classes=10),
           in_shape=(1, 3, 32, 32))


def test_mobilenet_v1():
    _smoke(M.mobilenet_v1(num_classes=10, scale=0.25), in_shape=(1, 3, 32, 32))


def test_mobilenet_v2():
    _smoke(M.mobilenet_v2(num_classes=10, scale=0.25), in_shape=(1, 3, 32, 32))


def test_mobilenet_v3_small():
    _smoke(M.mobilenet_v3_small(num_classes=10, scale=0.5), in_shape=(1, 3, 32, 32))


def test_mobilenet_v3_large():
    _smoke(M.mobilenet_v3_large(num_classes=10, scale=0.5), in_shape=(1, 3, 32, 32))


def test_squeezenet():
    _smoke(M.squeezenet1_0(num_classes=10), in_shape=(1, 3, 64, 64))
    _smoke(M.squeezenet1_1(num_classes=10), in_shape=(1, 3, 64, 64))


def test_shufflenet_v2():
    _smoke(M.shufflenet_v2_x0_25(num_classes=10), in_shape=(1, 3, 32, 32))


def test_densenet121():
    _smoke(M.densenet121(num_classes=10), in_shape=(1, 3, 32, 32))


def test_googlenet_aux_heads():
    m = M.googlenet(num_classes=10)
    m.train()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(1, 3, 64, 64).astype(np.float32))
    out, aux1, aux2 = m(x)
    assert list(out.shape) == [1, 10]
    assert list(aux1.shape) == [1, 10] and list(aux2.shape) == [1, 10]


def test_inception_v3():
    _smoke(M.inception_v3(num_classes=10), in_shape=(1, 3, 96, 96))


def test_pretrained_raises_actionable_error():
    with pytest.raises(NotImplementedError, match="zero-egress"):
        M.vgg16(pretrained=True)


def test_small_model_trains_end_to_end():
    """One family through the compiled TrainStep: loss descends."""
    paddle.seed(0)
    m = M.LeNet(num_classes=4)
    opt = optimizer.Adam(learning_rate=1e-3,
                         parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.CrossEntropyLoss(), opt)
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randn(16, 1, 28, 28).astype(np.float32))
    Y = paddle.to_tensor(rng.randint(0, 4, (16,)).astype(np.int64))
    losses = [float(step(X, Y).item()) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.8, losses
