"""Top-level namespace parity: every symbol the reference exports
from python/paddle/__init__.py __all__ (snapshot below, 411 names)
must exist on paddle_tpu. The list is frozen here so the test is
self-contained; regenerate it from the reference __all__ if the surface
ever widens."""
import paddle_tpu

REFERENCE_TOP_LEVEL = [
    'CPUPlace', 'CUDAPinnedPlace', 'CUDAPlace', 'DataParallel', 'LazyGuard',
    'Model', 'ParamAttr', 'Tensor', 'abs', 'abs_',
    'acos', 'acos_', 'acosh', 'add', 'add_n',
    'addmm', 'addmm_', 'all', 'allclose', 'amax',
    'amin', 'angle', 'any', 'arange', 'argmax',
    'argmin', 'argsort', 'as_complex', 'as_real', 'as_strided',
    'asin', 'asinh', 'assign', 'atan', 'atan2',
    'atan_', 'atanh', 'atleast_1d', 'atleast_2d', 'atleast_3d',
    'batch', 'bernoulli', 'bfloat16', 'bincount', 'binomial',
    'bitwise_and', 'bitwise_and_', 'bitwise_left_shift', 'bitwise_left_shift_', 'bitwise_not',
    'bitwise_not_', 'bitwise_or', 'bitwise_or_', 'bitwise_right_shift', 'bitwise_right_shift_',
    'bitwise_xor', 'bitwise_xor_', 'bmm', 'bool', 'broadcast_shape',
    'broadcast_tensors', 'broadcast_to', 'bucketize', 'cast', 'cast_',
    'cauchy_', 'cdist', 'ceil', 'check_shape', 'chunk',
    'clip', 'clone', 'column_stack', 'combinations', 'complex',
    'complex128', 'complex64', 'concat', 'conj', 'copysign',
    'copysign_', 'cos', 'cos_', 'cosh', 'count_nonzero',
    'create_parameter', 'crop', 'cross', 'cummax', 'cummin',
    'cumprod', 'cumprod_', 'cumsum', 'cumsum_', 'cumulative_trapezoid',
    'deg2rad', 'diag', 'diag_embed', 'diagflat', 'diagonal',
    'diagonal_scatter', 'diff', 'digamma', 'digamma_', 'disable_signal_handler',
    'disable_static', 'dist', 'divide', 'divide_', 'dot',
    'dsplit', 'dstack', 'dtype', 'einsum', 'empty',
    'empty_like', 'enable_grad', 'enable_static', 'equal', 'equal_',
    'equal_all', 'erf', 'erf_', 'erfinv', 'exp',
    'expand', 'expand_as', 'expm1', 'expm1_', 'eye',
    'finfo', 'flatten', 'flatten_', 'flip', 'float16',
    'float32', 'float64', 'floor', 'floor_divide', 'floor_divide_',
    'floor_mod', 'floor_mod_', 'flops', 'fmax', 'fmin',
    'frac', 'frac_', 'frexp', 'full', 'full_like',
    'gammainc', 'gammainc_', 'gammaincc', 'gammaincc_', 'gammaln',
    'gammaln_', 'gather', 'gather_nd', 'gcd', 'gcd_',
    'geometric_', 'get_cuda_rng_state', 'get_default_dtype', 'get_flags', 'get_rng_state',
    'grad', 'greater_equal', 'greater_equal_', 'greater_than', 'greater_than_',
    'heaviside', 'histogram', 'histogramdd', 'hsplit', 'hstack',
    'hypot', 'hypot_', 'i0', 'i0_', 'i0e',
    'i1', 'i1e', 'iinfo', 'imag', 'in_dynamic_mode',
    'increment', 'index_add', 'index_add_', 'index_fill', 'index_fill_',
    'index_put', 'index_put_', 'index_sample', 'index_select', 'inner',
    'int16', 'int32', 'int64', 'int8', 'is_complex',
    'is_empty', 'is_floating_point', 'is_grad_enabled', 'is_integer', 'is_tensor',
    'isclose', 'isfinite', 'isinf', 'isnan', 'isneginf',
    'isposinf', 'isreal', 'kron', 'kthvalue', 'lcm',
    'lcm_', 'ldexp', 'ldexp_', 'lerp', 'less_equal',
    'less_equal_', 'less_than', 'less_than_', 'lgamma', 'lgamma_',
    'linspace', 'load', 'log', 'log10', 'log10_',
    'log1p', 'log2', 'log2_', 'log_', 'logaddexp',
    'logcumsumexp', 'logical_and', 'logical_and_', 'logical_not', 'logical_not_',
    'logical_or', 'logical_or_', 'logical_xor', 'logit', 'logit_',
    'logspace', 'logsumexp', 'masked_fill', 'masked_fill_', 'masked_scatter',
    'masked_scatter_', 'masked_select', 'matmul', 'max', 'maximum',
    'mean', 'median', 'meshgrid', 'min', 'minimum',
    'mm', 'mod', 'mod_', 'mode', 'moveaxis',
    'multigammaln', 'multigammaln_', 'multinomial', 'multiplex', 'multiply',
    'multiply_', 'mv', 'nan_to_num', 'nan_to_num_', 'nanmean',
    'nanmedian', 'nanquantile', 'nansum', 'neg', 'neg_',
    'nextafter', 'no_grad', 'nonzero', 'normal', 'normal_',
    'not_equal', 'numel', 'ones', 'ones_like', 'outer',
    'pdist', 'poisson', 'polar', 'polygamma', 'polygamma_',
    'pow', 'pow_', 'prod', 'put_along_axis', 'quantile',
    'rad2deg', 'rand', 'randint', 'randint_like', 'randn',
    'randperm', 'rank', 'real', 'reciprocal', 'reduce_as',
    'remainder', 'remainder_', 'renorm', 'renorm_', 'repeat_interleave',
    'reshape', 'reshape_', 'reverse', 'roll', 'rot90',
    'round', 'row_stack', 'rsqrt', 'save', 'scale',
    'scatter', 'scatter_', 'scatter_nd', 'scatter_nd_add', 'searchsorted',
    'seed', 'select_scatter', 'set_cuda_rng_state', 'set_default_dtype', 'set_flags',
    'set_grad_enabled', 'set_printoptions', 'set_rng_state', 'sgn', 'shape',
    'shard_index', 'sign', 'signbit', 'sin', 'sin_',
    'sinh', 'sinh_', 'slice', 'slice_scatter', 'sort',
    'split', 'sqrt', 'square', 'square_', 'squeeze',
    'squeeze_', 'stack', 'standard_gamma', 'standard_normal', 'stanh',
    'std', 'strided_slice', 'subtract', 'sum', 'summary',
    't', 't_', 'take', 'take_along_axis', 'tan',
    'tan_', 'tanh', 'tanh_', 'tensor_split', 'tensordot',
    'tile', 'to_tensor', 'tolist', 'topk', 'trace',
    'transpose', 'transpose_', 'trapezoid', 'tril', 'tril_',
    'tril_indices', 'triu', 'triu_', 'triu_indices', 'trunc',
    'trunc_', 'uint8', 'unbind', 'unflatten', 'unfold',
    'uniform', 'unique', 'unique_consecutive', 'unsqueeze', 'unsqueeze_',
    'unstack', 'vander', 'var', 'view', 'view_as',
    'vsplit', 'vstack', 'where', 'where_', 'zeros',
    'zeros_like',
]


def test_top_level_namespace_complete():
    missing = [n for n in REFERENCE_TOP_LEVEL
               if not hasattr(paddle_tpu, n)]
    assert not missing, f"missing top-level symbols: {missing}"


def test_inplace_variants_rebind():
    import numpy as np

    x = paddle_tpu.to_tensor([4.0, 9.0])
    y = paddle_tpu.sqrt_(x)
    assert y is x
    np.testing.assert_allclose(x.numpy(), [2.0, 3.0])
    z = paddle_tpu.to_tensor([1.0, 2.0])
    paddle_tpu.add_(z, paddle_tpu.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(z.numpy(), [2.0, 3.0])


def test_aliases_and_utilities():
    import numpy as np

    a = paddle_tpu.to_tensor(np.eye(2, dtype="float32"))
    np.testing.assert_allclose(
        paddle_tpu.mm(a, a).numpy(), np.eye(2))
    assert paddle_tpu.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]
    assert int(paddle_tpu.rank(a).numpy()) == 2
    np.testing.assert_allclose(paddle_tpu.shape(a).numpy(), [2, 2])
    assert paddle_tpu.is_floating_point(a)
    assert not paddle_tpu.is_complex(a)
    with paddle_tpu.LazyGuard():
        pass
    b = paddle_tpu.zeros([100])
    paddle_tpu.normal_(b)
    assert float(b.numpy().std()) > 0.1


def test_nn_namespace_complete():
    """paddle.nn must export the reference's full layer set (134 names
    from python/paddle/nn/__init__.py __all__; spot list below covers
    the round-5 additions; the hasattr sweep covers the rest)."""
    from paddle_tpu import nn

    round5 = [
        "AdaptiveAvgPool1D", "AdaptiveAvgPool3D", "AdaptiveMaxPool1D",
        "AdaptiveMaxPool3D", "AvgPool3D", "BeamSearchDecoder", "BiRNN",
        "ChannelShuffle", "Conv1DTranspose", "Conv3DTranspose", "Fold",
        "FractionalMaxPool2D", "FractionalMaxPool3D", "GaussianNLLLoss",
        "HSigmoidLoss", "HingeEmbeddingLoss", "MaxPool3D", "MaxUnPool1D",
        "MaxUnPool2D", "MaxUnPool3D", "MultiLabelSoftMarginLoss",
        "MultiMarginLoss", "PixelUnshuffle", "PoissonNLLLoss",
        "RNNCellBase", "RReLU", "SoftMarginLoss", "Softmax2D",
        "TripletMarginLoss", "TripletMarginWithDistanceLoss",
        "Unflatten", "ZeroPad2D", "dynamic_decode",
    ]
    missing = [n for n in round5 if not hasattr(nn, n)]
    assert not missing, f"missing nn symbols: {missing}"


def test_all_reference_namespaces_complete():
    """Every public symbol of every reference sub-namespace must exist
    (checked dynamically against the mounted reference's __all__; skipped
    where the reference tree is unavailable)."""
    import ast
    import os

    ref_root = "/root/reference/python/paddle"
    if not os.path.isdir(ref_root):
        import pytest

        pytest.skip("reference tree not mounted")

    def public_names(path):
        names = set()
        if not os.path.exists(path):
            return names
        for node in ast.walk(ast.parse(open(path).read())):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        try:
                            names |= set(ast.literal_eval(node.value))
                        except Exception:
                            pass
        return names

    problems = {}
    for mod in ["nn", "vision", "distributed", "static", "io", "amp",
                "distribution", "autograd", "metric", "optimizer",
                "sparse", "incubate", "signal", "fft", "jit"]:
        ours = __import__(f"paddle_tpu.{mod}", fromlist=["_"])
        ref = public_names(os.path.join(ref_root, mod, "__init__.py"))
        missing = sorted(n for n in ref if not hasattr(ours, n))
        if missing:
            problems[mod] = missing
    assert not problems, f"namespace gaps: {problems}"


def test_jit_toggles():
    import paddle_tpu

    paddle_tpu.jit.enable_to_static(False)
    try:
        def f(x):
            return x + 1

        assert paddle_tpu.jit.to_static(f) is f
    finally:
        paddle_tpu.jit.enable_to_static(True)
    # re-enabled: to_static must WRAP again (adapter, not the raw fn)
    g = paddle_tpu.jit.to_static(lambda x: x + 1)
    assert not callable(g) or type(g).__name__ == "_FunctionAdapter"
    paddle_tpu.jit.set_verbosity(1)
    paddle_tpu.jit.ignore_module([os])


import os  # noqa: E402


def test_tensor_method_surface_complete():
    """Every method of the reference Tensor prototype + its
    tensor_method_func patch table must exist on our Tensor (spot list
    of round-5 additions; dynamic sweep in the reference-mounted env)."""
    from paddle_tpu.core.tensor import Tensor

    round5 = ["cdist", "mm", "svd_lowrank", "pca_lowrank", "eig",
              "eigvals", "cholesky_solve", "lu_unpack", "ormqr",
              "top_p_sampling", "uniform_", "exponential_", "stft",
              "istft", "tensordot", "view", "view_as", "where_",
              "bucketize", "multi_dot", "add_n", "vander"]
    missing = [n for n in round5 if not hasattr(Tensor, n)]
    assert not missing, missing

    import os
    import re

    pyi = "/root/reference/python/paddle/tensor/tensor.prototype.pyi"
    if not os.path.exists(pyi):
        return
    ref = set()
    for m in re.finditer(r"^\s+def ([a-zA-Z_][a-zA-Z0-9_]*)\(",
                         open(pyi).read(), re.M):
        ref.add(m.group(1))
    src = open("/root/reference/python/paddle/tensor/__init__.py").read()
    tbl = re.search(r"tensor_method_func = \[(.*?)\]", src, re.S)
    assert tbl is not None, \
        "reference tensor_method_func table not found (format changed?)"
    for name in re.findall(r"'([a-zA-Z0-9_]+)'", tbl.group(1)):
        ref.add(name)
    gaps = sorted(n for n in ref
                  if not hasattr(Tensor, n) and not n.startswith("_"))
    assert not gaps, f"Tensor method gaps: {gaps}"
