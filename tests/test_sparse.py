"""paddle.sparse COO/CSR (reference: python/paddle/sparse/ over phi
sparse kernels; numerics vs dense numpy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _dense_example():
    d = np.zeros((4, 5), np.float32)
    d[0, 1] = 2.0
    d[2, 3] = -1.5
    d[3, 0] = 4.0
    return d


def test_sparse_coo_roundtrip():
    d = _dense_example()
    idx = np.array(np.nonzero(d))
    vals = d[tuple(idx)]
    s = sparse.sparse_coo_tensor(idx, vals, shape=d.shape)
    assert sparse.is_sparse_coo(s)
    assert s.nnz() == 3
    np.testing.assert_array_equal(s.to_dense().numpy(), d)
    np.testing.assert_array_equal(s.indices().numpy(), idx)
    np.testing.assert_allclose(s.values().numpy(), vals)


def test_sparse_csr_roundtrip():
    d = _dense_example()
    # CSR of d
    crows = [0, 1, 1, 2, 3]
    cols = [1, 3, 0]
    vals = [2.0, -1.5, 4.0]
    s = sparse.sparse_csr_tensor(crows, cols, vals, shape=d.shape)
    assert sparse.is_sparse_csr(s)
    np.testing.assert_array_equal(s.to_dense().numpy(), d)
    coo = s.to_sparse_coo()
    np.testing.assert_array_equal(coo.to_dense().numpy(), d)
    back = coo.to_sparse_csr()
    np.testing.assert_array_equal(back.to_dense().numpy(), d)


def test_tensor_to_sparse_and_back():
    d = _dense_example()
    t = paddle.to_tensor(d)
    s = t.to_sparse_coo()
    assert s.nnz() == 3
    np.testing.assert_array_equal(s.to_dense().numpy(), d)
    c = t.to_sparse_csr()
    np.testing.assert_array_equal(c.to_dense().numpy(), d)


def test_sparse_unary_zero_preserving():
    d = _dense_example()
    s = paddle.to_tensor(d).to_sparse_coo()
    np.testing.assert_allclose(sparse.relu(s).to_dense().numpy(),
                               np.maximum(d, 0))
    np.testing.assert_allclose(sparse.tanh(s).to_dense().numpy(),
                               np.tanh(d), rtol=1e-6)
    np.testing.assert_allclose(sparse.neg(s).to_dense().numpy(), -d)
    # nnz unchanged: ops act on stored values only
    assert sparse.relu(s).nnz() == s.nnz()


def test_sparse_binary_and_matmul():
    d = _dense_example()
    s = paddle.to_tensor(d).to_sparse_coo()
    other = np.ones_like(d)
    out = sparse.add(s, paddle.to_tensor(other))
    np.testing.assert_allclose(out.to_dense().numpy(), d + 1)
    rng = np.random.RandomState(0)
    w = rng.randn(5, 3).astype(np.float32)
    mm = sparse.matmul(s, paddle.to_tensor(w))
    np.testing.assert_allclose(mm.numpy(), d @ w, rtol=1e-5, atol=1e-5)


def test_masked_matmul():
    rng = np.random.RandomState(0)
    a = rng.randn(4, 6).astype(np.float32)
    b = rng.randn(6, 5).astype(np.float32)
    mask_d = (_dense_example() != 0).astype(np.float32)
    mask = paddle.to_tensor(mask_d).to_sparse_coo()
    out = sparse.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                               mask)
    np.testing.assert_allclose(out.to_dense().numpy(), (a @ b) * mask_d,
                               rtol=1e-5, atol=1e-5)


def test_sparse_transpose_and_cast():
    d = _dense_example()
    s = paddle.to_tensor(d).to_sparse_coo()
    t = sparse.transpose(s, [1, 0])
    np.testing.assert_array_equal(t.to_dense().numpy(), d.T)


# ---------------------------------------------------------------------------
# round-5 depth: reference unary/binary/multiary parity + sparse.nn
# (python/paddle/sparse/unary.py, binary.py, multiary.py, nn/)
# ---------------------------------------------------------------------------

def _rand_sparse(shape=(4, 6), density=0.4, seed=0):
    rng = np.random.default_rng(seed)
    d = (rng.standard_normal(shape).astype("float32")
         * (rng.random(shape) < density))
    return d, paddle.to_tensor(d).to_sparse_coo()


def test_sparse_unary_depth():
    d, x = _rand_sparse()
    for name, ref in [("square", np.square), ("log1p", np.log1p),
                      ("expm1", np.expm1), ("tan", np.tan),
                      ("atan", np.arctan), ("sinh", np.sinh),
                      ("asinh", np.arcsinh),
                      ("rad2deg", np.rad2deg), ("deg2rad", np.deg2rad)]:
        got = getattr(sparse, name)(x).to_dense().numpy()
        np.testing.assert_allclose(got, ref(d) * (d != 0), rtol=1e-4,
                                   atol=1e-6, err_msg=name)


def test_sparse_sum_reshape_slice():
    d, x = _rand_sparse()
    np.testing.assert_allclose(sparse.sum(x).numpy(), d.sum(),
                               rtol=1e-5)
    np.testing.assert_allclose(
        sparse.sum(x, axis=1).to_dense().numpy(), d.sum(1), rtol=1e-5)
    np.testing.assert_allclose(
        sparse.reshape(x, [6, 4]).to_dense().numpy(),
        d.reshape(6, 4), rtol=1e-6)
    np.testing.assert_allclose(
        sparse.slice(x, [0, 1], [1, 2], [3, 5]).to_dense().numpy(),
        d[1:3, 2:5], rtol=1e-6)


def test_sparse_mv_addmm_is_same_shape():
    d, x = _rand_sparse()
    rng = np.random.default_rng(1)
    v = rng.standard_normal(6).astype("float32")
    np.testing.assert_allclose(sparse.mv(x, paddle.to_tensor(v)).numpy(),
                               d @ v, rtol=1e-4)
    inp = rng.standard_normal((4, 3)).astype("float32")
    y = rng.standard_normal((6, 3)).astype("float32")
    np.testing.assert_allclose(
        sparse.addmm(paddle.to_tensor(inp), x, paddle.to_tensor(y),
                     beta=0.5, alpha=2.0).numpy(),
        0.5 * inp + 2.0 * (d @ y), rtol=1e-4)
    _, x2 = _rand_sparse(seed=2)
    assert sparse.is_same_shape(x, x2)
    _, x3 = _rand_sparse(shape=(3, 6), seed=2)
    assert not sparse.is_same_shape(x, x3)


def test_sparse_pca_lowrank():
    d, x = _rand_sparse(shape=(8, 5))
    U, S, V = sparse.pca_lowrank(x, q=3)
    assert tuple(U.shape) == (8, 3)
    assert tuple(S.shape) == (3,)
    assert tuple(V.shape) == (5, 3)
    # principal directions reconstruct the centered matrix's energy
    c = d - d.mean(0, keepdims=True)
    recon = U.numpy() @ np.diag(S.numpy()) @ V.numpy().T
    full = np.linalg.svd(c, compute_uv=False)
    assert np.abs(recon).sum() > 0
    np.testing.assert_allclose(S.numpy(), full[:3], rtol=1e-4)


def test_sparse_subm_conv_preserves_pattern():
    import jax.numpy as jnp
    from jax.experimental import sparse as jsparse

    rng = np.random.default_rng(0)
    xs = rng.standard_normal((1, 6, 6, 3)).astype("float32")
    xs = xs * (rng.random((1, 6, 6, 1)) > 0.5)
    x = sparse.SparseCooTensor(
        jsparse.BCOO.fromdense(jnp.asarray(xs), n_dense=1))
    conv = sparse.nn.SubmConv2D(3, 5, 3, padding=1)
    out = conv(x).to_dense().numpy()
    assert out.shape == (1, 6, 6, 5)
    out_active = np.any(out != 0, axis=-1)
    in_active = np.any(xs != 0, axis=-1)
    assert (out_active <= in_active).all()


def test_sparse_conv3d_matches_dense():
    import jax.numpy as jnp
    from jax.experimental import sparse as jsparse

    rng = np.random.default_rng(1)
    xs = rng.standard_normal((1, 4, 4, 4, 2)).astype("float32")
    xs = xs * (rng.random((1, 4, 4, 4, 1)) > 0.4)
    x = sparse.SparseCooTensor(
        jsparse.BCOO.fromdense(jnp.asarray(xs), n_dense=1))
    conv = sparse.nn.Conv3D(2, 3, 2)
    out = conv(x).to_dense().numpy()
    assert out.shape == (1, 3, 3, 3, 3)
    # numerics: equal to the dense conv on the densified input
    import jax
    from jax import lax

    w = conv.weight.numpy()
    b = conv.bias.numpy()
    dn = lax.conv_dimension_numbers(xs.shape, w.shape,
                                    ("NDHWC", "DHWIO", "NDHWC"))
    want = np.asarray(lax.conv_general_dilated(
        jnp.asarray(xs), jnp.asarray(w), (1, 1, 1), [(0, 0)] * 3,
        dimension_numbers=dn)) + b
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_sparse_batchnorm_and_pool():
    import jax.numpy as jnp
    from jax.experimental import sparse as jsparse

    rng = np.random.default_rng(2)
    xs = rng.standard_normal((1, 4, 4, 4, 2)).astype("float32")
    x = sparse.SparseCooTensor(
        jsparse.BCOO.fromdense(jnp.asarray(xs), n_dense=1))
    bn = sparse.nn.BatchNorm(2)
    bn.train()
    out = bn(x)
    vals = out.to_dense().numpy().reshape(-1, 2)
    np.testing.assert_allclose(vals.mean(0), 0.0, atol=1e-5)
    p = sparse.nn.functional.max_pool3d(x, 2)
    want = np.asarray(xs).reshape(1, 2, 2, 2, 2, 2, 2, 2).max(
        axis=(2, 4, 6))
    assert p.to_dense().numpy().shape == (1, 2, 2, 2, 2)


def test_sparse_activations_nn():
    d, x = _rand_sparse()
    np.testing.assert_allclose(
        sparse.nn.functional.relu6(x).to_dense().numpy(),
        np.clip(d, 0, 6) * (d != 0), rtol=1e-6)
    got = sparse.nn.functional.leaky_relu(x, 0.1).to_dense().numpy()
    np.testing.assert_allclose(got, np.where(d > 0, d, 0.1 * d) * (d != 0),
                               rtol=1e-5, atol=1e-7)


def test_subm_conv_keeps_stored_zero_sites():
    """relu can clamp an active site's values to stored 0.0; subm conv
    must STILL treat it as active (index-set semantics, not value!=0)."""
    import jax.numpy as jnp
    from jax.experimental import sparse as jsparse

    xs = np.zeros((1, 3, 3, 1), "float32")
    xs[0, 1, 1, 0] = -2.0      # one active site, negative value
    x = sparse.SparseCooTensor(
        jsparse.BCOO.fromdense(jnp.asarray(xs), n_dense=1))
    r = sparse.nn.functional.relu(x)   # value -> 0.0, index kept
    conv = sparse.nn.SubmConv2D(1, 1, 1, bias_attr=True)
    out = conv(r).to_dense().numpy()
    # 1x1 conv of value 0 + bias b must appear AT the active site
    b = float(conv.bias.numpy()[0])
    np.testing.assert_allclose(out[0, 1, 1, 0], b, rtol=1e-6)
    assert np.count_nonzero(out) <= 1 or b == 0.0
