"""paddle.sparse COO/CSR (reference: python/paddle/sparse/ over phi
sparse kernels; numerics vs dense numpy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _dense_example():
    d = np.zeros((4, 5), np.float32)
    d[0, 1] = 2.0
    d[2, 3] = -1.5
    d[3, 0] = 4.0
    return d


def test_sparse_coo_roundtrip():
    d = _dense_example()
    idx = np.array(np.nonzero(d))
    vals = d[tuple(idx)]
    s = sparse.sparse_coo_tensor(idx, vals, shape=d.shape)
    assert sparse.is_sparse_coo(s)
    assert s.nnz() == 3
    np.testing.assert_array_equal(s.to_dense().numpy(), d)
    np.testing.assert_array_equal(s.indices().numpy(), idx)
    np.testing.assert_allclose(s.values().numpy(), vals)


def test_sparse_csr_roundtrip():
    d = _dense_example()
    # CSR of d
    crows = [0, 1, 1, 2, 3]
    cols = [1, 3, 0]
    vals = [2.0, -1.5, 4.0]
    s = sparse.sparse_csr_tensor(crows, cols, vals, shape=d.shape)
    assert sparse.is_sparse_csr(s)
    np.testing.assert_array_equal(s.to_dense().numpy(), d)
    coo = s.to_sparse_coo()
    np.testing.assert_array_equal(coo.to_dense().numpy(), d)
    back = coo.to_sparse_csr()
    np.testing.assert_array_equal(back.to_dense().numpy(), d)


def test_tensor_to_sparse_and_back():
    d = _dense_example()
    t = paddle.to_tensor(d)
    s = t.to_sparse_coo()
    assert s.nnz() == 3
    np.testing.assert_array_equal(s.to_dense().numpy(), d)
    c = t.to_sparse_csr()
    np.testing.assert_array_equal(c.to_dense().numpy(), d)


def test_sparse_unary_zero_preserving():
    d = _dense_example()
    s = paddle.to_tensor(d).to_sparse_coo()
    np.testing.assert_allclose(sparse.relu(s).to_dense().numpy(),
                               np.maximum(d, 0))
    np.testing.assert_allclose(sparse.tanh(s).to_dense().numpy(),
                               np.tanh(d), rtol=1e-6)
    np.testing.assert_allclose(sparse.neg(s).to_dense().numpy(), -d)
    # nnz unchanged: ops act on stored values only
    assert sparse.relu(s).nnz() == s.nnz()


def test_sparse_binary_and_matmul():
    d = _dense_example()
    s = paddle.to_tensor(d).to_sparse_coo()
    other = np.ones_like(d)
    out = sparse.add(s, paddle.to_tensor(other))
    np.testing.assert_allclose(out.to_dense().numpy(), d + 1)
    rng = np.random.RandomState(0)
    w = rng.randn(5, 3).astype(np.float32)
    mm = sparse.matmul(s, paddle.to_tensor(w))
    np.testing.assert_allclose(mm.numpy(), d @ w, rtol=1e-5, atol=1e-5)


def test_masked_matmul():
    rng = np.random.RandomState(0)
    a = rng.randn(4, 6).astype(np.float32)
    b = rng.randn(6, 5).astype(np.float32)
    mask_d = (_dense_example() != 0).astype(np.float32)
    mask = paddle.to_tensor(mask_d).to_sparse_coo()
    out = sparse.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b),
                               mask)
    np.testing.assert_allclose(out.to_dense().numpy(), (a @ b) * mask_d,
                               rtol=1e-5, atol=1e-5)


def test_sparse_transpose_and_cast():
    d = _dense_example()
    s = paddle.to_tensor(d).to_sparse_coo()
    t = sparse.transpose(s, [1, 0])
    np.testing.assert_array_equal(t.to_dense().numpy(), d.T)
