"""Ring attention (context parallelism) vs single-device reference on the
8-device CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import ProcessMesh, init_mesh
from paddle_tpu.ops import ring_attention as ra


def _sdpa_ref(q, k, v, causal):
    qt = jnp.transpose(q, (0, 2, 1, 3)).astype(jnp.float32)
    kt = jnp.transpose(k, (0, 2, 1, 3)).astype(jnp.float32)
    vt = jnp.transpose(v, (0, 2, 1, 3)).astype(jnp.float32)
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) / np.sqrt(d)
    if causal:
        sq, sk = s.shape[-2:]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.transpose(o, (0, 2, 1, 3))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = ProcessMesh(np.arange(8), dim_names=["sp"])
    rng = np.random.RandomState(0)
    b, s, h, d = 2, 64, 2, 16
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    out = ra.ring_attention_data(q, k, v, mesh, axis_name="sp",
                                 causal=causal)
    ref = _sdpa_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_gqa():
    """GQA: compact KV chunks around the ring, grouped-query einsum."""
    mesh = ProcessMesh(np.arange(8), dim_names=["sp"])
    rng = np.random.RandomState(3)
    b, s, hq, hkv, d = 1, 64, 4, 2, 16
    q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    out = ra.ring_attention_data(q, k, v, mesh, axis_name="sp",
                                 causal=True)
    k_rep = jnp.repeat(k, hq // hkv, axis=2)
    v_rep = jnp.repeat(v, hq // hkv, axis=2)
    ref = _sdpa_ref(q, k_rep, v_rep, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients():
    mesh = ProcessMesh(np.arange(8), dim_names=["sp"])
    rng = np.random.RandomState(1)
    b, s, h, d = 1, 32, 2, 8
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    def f_ring(q, k, v):
        return jnp.sum(ra.ring_attention_data(
            q, k, v, mesh, axis_name="sp", causal=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_sdpa_ref(q, k, v, True) ** 2)

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=2e-3, atol=2e-4)


def test_ring_attention_tensor_op():
    init_mesh([8], ["sp"])
    paddle.seed(0)
    q = paddle.randn([1, 32, 2, 8])
    k = paddle.randn([1, 32, 2, 8])
    v = paddle.randn([1, 32, 2, 8])
    q.stop_gradient = False
    out = ra.ring_attention(q, k, v, axis_name="sp", causal=True)
    assert out.shape == [1, 32, 2, 8]
    out.sum().backward()
    assert q.grad is not None


def test_llama_context_parallel_matches_dense():
    """Tiny Llama with context_parallel trains under ParallelTrainStep and
    matches the non-CP model's losses (same seed, same data)."""
    from paddle_tpu import optimizer
    from paddle_tpu.distributed.engine import ParallelTrainStep
    from paddle_tpu.models.llama import (
        LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
    )

    B, S = 4, 32
    rng = np.random.RandomState(0)
    X = rng.randint(0, 128, (B, S)).astype(np.int32)
    Y = rng.randint(0, 128, (B, S)).astype(np.int32)

    def run(cp):
        paddle.seed(9)
        cfg = LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=2,
            num_key_value_heads=2, max_position_embeddings=S,
            use_flash_attention=False, context_parallel=cp)
        m = LlamaForCausalLM(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=m.parameters())
        mesh = ProcessMesh(np.arange(8).reshape(2, 4),
                           dim_names=["dp", "sp"])
        step = ParallelTrainStep(m, LlamaPretrainingCriterion(cfg), opt,
                                 mesh)
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item()) for _ in range(3)]

    dense = run(False)
    cp = run(True)
    np.testing.assert_allclose(dense, cp, rtol=5e-4, atol=1e-5)


def test_ring_attention_under_jit_with_dp():
    """jit(shard_map) composition with a 2-axis mesh (dp x sp)."""
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "sp"])
    rng = np.random.RandomState(2)
    b, s, h, d = 2, 32, 2, 8
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    f = jax.jit(lambda q, k, v: ra.ring_attention_data(
        q, k, v, mesh, axis_name="sp", causal=True, batch_axis="dp"))
    out = f(q, k, v)
    ref = _sdpa_ref(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
