"""Pipeline schedules: gpipe / interleave (VPP) / zero_bubble vs 1f1b
and vs single-device training.

Reference: meta_parallel/pipeline_parallel.py:987
(PipelineParallelWithInterleave), distributed/passes/
pipeline_scheduler_pass/{pipeline_1f1b,pipeline_vpp,
pipeline_zero_bubble}.py — the reference ships five schedules; here each
schedule is a different chunking/rotation of ONE compiled program and
all must be numerically identical to serial training.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.fleet.pipeline_parallel import (
    LayerDesc, PipelineLayer, pipeline_forward_interleaved,
)
from paddle_tpu.distributed.fleet.pp_engine import PipelineTrainStep
from paddle_tpu.distributed.mesh import ProcessMesh

D, LAYERS, BATCH = 8, 8, 16


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)
        self.norm = nn.LayerNorm(d)

    def forward(self, x):
        return self.norm(x + self.fc2(paddle.ops.gelu(self.fc1(x))))


def build_pipe(n_stages):
    paddle.seed(3)
    return PipelineLayer(
        layers=[nn.Linear(D, D)] +
               [LayerDesc(Block, D) for _ in range(LAYERS)] +
               [nn.Linear(D, D)],
        num_stages=n_stages,
        loss_fn=nn.MSELoss())


def _data():
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randn(BATCH, D).astype(np.float32))
    Y = paddle.to_tensor(rng.randn(BATCH, D).astype(np.float32))
    return X, Y


def _train(n_stages, schedule, n_micro, steps=3, **kw):
    pipe = build_pipe(n_stages)
    opt = optimizer.AdamW(learning_rate=1e-2,
                          parameters=pipe.parameters())
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"]) \
        if n_stages == 4 else ProcessMesh(np.arange(8), ["dp"])
    step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                             n_microbatches=n_micro, schedule=schedule,
                             **kw)
    X, Y = _data()
    losses = [float(step(X, Y).item()) for _ in range(steps)]
    return losses, step


def test_interleaved_rotation_identity():
    """Identity virtual stages must reproduce the input through the
    S*V-deep virtual ring."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    jm = mesh.jax_mesh()
    x = jnp.arange(8 * 2 * 3, dtype=jnp.float32).reshape(8, 2, 3)
    dummy = (jnp.zeros((8, 1)),)

    def spmd(params, mbs):
        return pipeline_forward_interleaved(
            lambda lp, s, h: h + 0.0, params, mbs, 4, 2, "pp")

    out = jax.jit(jax.shard_map(
        spmd, mesh=jm, in_specs=((P("pp"),), P()), out_specs=P(),
        axis_names={"pp"}, check_vma=False))(dummy, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


@pytest.mark.parametrize("schedule,n_micro,kw", [
    ("gpipe", 8, {}),
    ("zero_bubble", 8, {}),
    ("interleave", 8, {"interleave_degree": 2}),
])
def test_schedule_matches_single_device(schedule, n_micro, kw):
    base, _ = _train(1, "1f1b", 1)
    got, _ = _train(4, schedule, n_micro, **kw)
    np.testing.assert_allclose(got, base, rtol=5e-3, atol=1e-4)


def test_all_schedules_agree():
    a, _ = _train(4, "1f1b", 8)
    b, _ = _train(4, "gpipe", 8)
    c, _ = _train(4, "interleave", 8, interleave_degree=2)
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=5e-5)
    np.testing.assert_allclose(a, c, rtol=2e-3, atol=5e-5)


def test_bubble_fraction_reporting():
    _, s1 = _train(4, "1f1b", 8, steps=1)
    _, sg = _train(4, "gpipe", 8, steps=1)
    _, si = _train(4, "interleave", 8, steps=1, interleave_degree=2)
    # 1f1b: chunks of 4 -> (4-1)/(4+3); gpipe: all 8 -> 3/11 (smaller);
    # interleave (true VPP, V=2): (S-1)/(M*V+S-1) = 3/19 — SMALLER than
    # gpipe at equal M, the VPP property (ramp ticks cost 1/V of a stage)
    assert s1.bubble_fraction == pytest.approx(3 / 7)
    assert sg.bubble_fraction == pytest.approx(3 / 11)
    assert si.bubble_fraction == pytest.approx(3 / 19)
    assert sg.bubble_fraction < s1.bubble_fraction
    assert si.bubble_fraction < sg.bubble_fraction


def test_interleave_layer_perm_roundtrip():
    """state_dict after training must reflect the de-permuted layers."""
    pipe = build_pipe(4)
    opt = optimizer.SGD(learning_rate=0.0,
                        parameters=pipe.parameters())
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                             n_microbatches=8, schedule="interleave",
                             interleave_degree=2)
    before = {k: v.numpy().copy()
              for k, v in pipe.state_dict().items()}
    X, Y = _data()
    step(X, Y)
    step.sync_params_to_model()
    after = pipe.state_dict()
    for k in before:
        np.testing.assert_allclose(after[k].numpy(), before[k],
                                   rtol=1e-6, atol=1e-6,
                                   err_msg=f"lr=0 must not move {k}")


def test_invalid_schedule_and_degree():
    pipe = build_pipe(4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=pipe.parameters())
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    with pytest.raises(ValueError, match="schedule"):
        PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                          schedule="wavelike")
    with pytest.raises(ValueError, match="divisible"):
        PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                          n_microbatches=12, schedule="interleave",
                          interleave_degree=3)
