"""Pipeline-parallel tests on the virtual CPU mesh: the compiled ppermute
schedule must match single-device training numerically."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.fleet.pipeline_parallel import (
    LayerDesc, PipelineLayer, pipeline_forward,
)
from paddle_tpu.distributed.fleet.pp_engine import PipelineTrainStep
from paddle_tpu.distributed.mesh import ProcessMesh


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc1 = nn.Linear(d, 2 * d)
        self.fc2 = nn.Linear(2 * d, d)
        self.norm = nn.LayerNorm(d)

    def forward(self, x):
        return self.norm(x + self.fc2(paddle.ops.gelu(self.fc1(x))))


def build_pipe(d=8, n_layers=4, n_stages=1):
    return PipelineLayer(
        layers=[nn.Linear(d, d)] +
               [LayerDesc(Block, d) for _ in range(n_layers)] +
               [nn.Linear(d, d)],
        num_stages=n_stages,
        loss_fn=nn.MSELoss())


def test_pipeline_layer_segmentation():
    p = build_pipe(n_stages=4)
    assert len(p.pre_layers) == 1
    assert len(p.body_layers) == 4
    assert len(p.post_layers) == 1
    out = p(paddle.randn([2, 8]))
    assert out.shape == [2, 8]


def test_pipeline_forward_rotation_identity():
    """With identity stages, the pipeline must reproduce its input."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    jm = mesh.jax_mesh()
    x = jnp.arange(4 * 2 * 3, dtype=jnp.float32).reshape(4, 2, 3)
    dummy = (jnp.zeros((4, 1)),)  # one leaf, 1 layer per stage

    def spmd(params, mbs):
        return pipeline_forward(lambda lp, h: h + 0.0, params, mbs, 4,
                                "pp")

    out = jax.jit(jax.shard_map(
        spmd, mesh=jm, in_specs=((P("pp"),), P()), out_specs=P(),
        axis_names={"pp"}, check_vma=False))(dummy, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_pipeline_matches_single_device():
    np.random.seed(0)
    X = np.random.randn(8, 8).astype(np.float32)
    Y = np.random.randn(8, 8).astype(np.float32)

    def run(n_stages):
        paddle.seed(11)
        pipe = build_pipe(n_stages=n_stages)
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=pipe.parameters())
        if n_stages == 1:
            step = paddle.jit.TrainStep(pipe, nn.MSELoss(), opt)
            return [float(step(paddle.to_tensor(X),
                               paddle.to_tensor(Y)).item())
                    for _ in range(5)]
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
        step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                                 n_microbatches=4, remat_body=True)
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item())
                for _ in range(5)]

    single = run(1)
    piped = run(4)
    np.testing.assert_allclose(single, piped, rtol=5e-4, atol=1e-6)


def test_seg_method_layer_selector():
    """seg_method='layer:Block' picks the Block run as the body even when
    other LayerDesc runs exist (reference PipelineLayer:257 seg_method)."""
    from paddle_tpu.distributed.fleet.pipeline_parallel import PipelineLayer

    class Other(nn.Layer):
        def __init__(self, d):
            super().__init__()
            self.fc = nn.Linear(d, d)

        def forward(self, x):
            return self.fc(x)

    p = PipelineLayer(
        layers=[LayerDesc(Other, 8)] +
               [LayerDesc(Block, 8) for _ in range(4)] +
               [nn.Linear(8, 8)],
        num_stages=4, seg_method="layer:Block", loss_fn=nn.MSELoss())
    assert len(p.body_layers) == 4
    assert type(p.pre_layers[0]).__name__ == "Other"
    out = p(paddle.randn([2, 8]))
    assert out.shape == [2, 8]


class _TiedEmbed(nn.Layer):
    def __init__(self, vocab, d):
        super().__init__()
        self.weight = nn.Parameter(paddle.randn([vocab, d]).numpy() * 0.02)

    def forward(self, ids):
        return paddle.ops.embedding_lookup(ids, self.weight) \
            if hasattr(paddle.ops, "embedding_lookup") else \
            paddle.ops.gather(self.weight, ids, axis=0)


def _head_forward(layer, x):
    # tied head: logits = x @ E^T (reference SharedLayerDesc usage)
    return paddle.ops.matmul(x, layer.weight, transpose_y=True)


def test_shared_layer_desc_ties_weights():
    """SharedLayerDesc shares one Parameter between embedding and head;
    after pipeline training both stay bitwise identical and match the
    single-device run."""
    from paddle_tpu.distributed.fleet.pipeline_parallel import (
        PipelineLayer, SharedLayerDesc,
    )

    vocab, d = 32, 8

    def build(n_stages):
        paddle.seed(21)
        return PipelineLayer(
            layers=[
                SharedLayerDesc("embed", _TiedEmbed, None, "weight",
                                vocab, d),
                *[LayerDesc(Block, d) for _ in range(4)],
                SharedLayerDesc("embed", _TiedEmbed, _head_forward,
                                "weight", vocab, d),
            ],
            num_stages=n_stages, loss_fn=nn.CrossEntropyLoss())

    pipe = build(4)
    # the tie holds structurally
    emb_w = pipe.pre_layers[0].weight
    head = pipe.post_layers[0]
    assert getattr(head, "inner", head).weight is emb_w

    X = paddle.to_tensor(
        np.random.RandomState(0).randint(0, vocab, (8,)).astype(np.int64))
    Y = paddle.to_tensor(
        np.random.RandomState(1).randint(0, vocab, (8,)).astype(np.int64))

    def run(n_stages):
        paddle.seed(33)
        p = build(n_stages)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=p.parameters())
        if n_stages == 1:
            step = paddle.jit.TrainStep(p, nn.CrossEntropyLoss(), opt)
            losses = [float(step(X, Y).item()) for _ in range(4)]
            return losses, p.pre_layers[0].weight.numpy()
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
        step = PipelineTrainStep(p, nn.CrossEntropyLoss(), opt, mesh,
                                 n_microbatches=4, remat_body=False)
        losses = [float(step(X, Y).item()) for _ in range(4)]
        step.sync_params_to_model()
        # tied copies stayed identical through updates
        w_pre = np.asarray(step._pre_params[0]._data)
        w_post = np.asarray(
            step._post_params[step._shared_post and
                              list(step._shared_post)[0] or 0]._data)
        np.testing.assert_array_equal(w_pre, w_post)
        return losses, w_pre

    l1, w1 = run(1)
    l4, w4 = run(4)
    np.testing.assert_allclose(l1, l4, rtol=5e-4, atol=1e-6)
    np.testing.assert_allclose(w1, w4, rtol=2e-4, atol=1e-6)


def test_pipeline_state_sync():
    paddle.seed(5)
    pipe = build_pipe(n_stages=4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=pipe.parameters())
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                             n_microbatches=4)
    w_before = pipe.body_layers[0].fc1.weight.numpy().copy()
    step(paddle.randn([8, 8]), paddle.randn([8, 8]))
    step.sync_params_to_model()
    w_after = pipe.body_layers[0].fc1.weight.numpy()
    assert not np.allclose(w_before, w_after)


def test_pipeline_chunked_accumulation_matches_single_device():
    """n_microbatches > stages runs as chunks of S with gradient
    accumulation inside the compiled step (in-flight activations capped
    at the 1F1B bound); numerics must still match single-device."""
    np.random.seed(2)
    X = np.random.randn(16, 8).astype(np.float32)
    Y = np.random.randn(16, 8).astype(np.float32)

    def run(n_stages, M=8):
        paddle.seed(17)
        pipe = build_pipe(n_stages=n_stages)
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=pipe.parameters())
        if n_stages == 1:
            step = paddle.jit.TrainStep(pipe, nn.MSELoss(), opt)
            return [float(step(paddle.to_tensor(X),
                               paddle.to_tensor(Y)).item())
                    for _ in range(4)]
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
        step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                                 n_microbatches=M, remat_body=True)
        assert step.n_chunks == 2
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item())
                for _ in range(4)]

    single = run(1)
    piped = run(4)
    np.testing.assert_allclose(single, piped, rtol=5e-4, atol=1e-6)


def test_pipeline_rejects_ragged_microbatches():
    pipe = build_pipe(n_stages=4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=pipe.parameters())
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    with pytest.raises(ValueError, match="multiple"):
        PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh, n_microbatches=6)


def test_pipeline_pre_post_storage_sharded_over_pp():
    """Embedding/head storage (and optimizer slots) are sharded across
    the pp axis — the TPU answer to the reference's first/last-stage
    placement (pp_layers.py:257): no pp rank holds the full vocab
    tensors."""
    paddle.seed(3)
    pipe = build_pipe(n_stages=4)
    opt = optimizer.Adam(learning_rate=0.01, parameters=pipe.parameters())
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                             n_microbatches=4)
    assert any("pp" in str(sh.spec) for sh in step._pre_sh)
    assert any("pp" in str(sh.spec) for sh in step._post_sh)
    # slots share the param sharding
    w_sh = step._pre_sh[0]
    shard_shape = w_sh.shard_shape(step._pre_params[0]._data.shape)
    assert shard_shape[0] * 4 == step._pre_params[0]._data.shape[0]
    # and training still runs
    loss = step(paddle.randn([8, 8]), paddle.randn([8, 8]))
    assert np.isfinite(float(loss.item()))


def test_pipeline_grad_scaler_inside_step():
    """GradScaler now works inside the compiled pipeline step: loss is
    scaled before backward, grads unscaled after accumulation, updates
    skipped on overflow (round-2 raised NotImplementedError here)."""
    from paddle_tpu.amp import GradScaler
    from paddle_tpu.distributed.fleet.pipeline_parallel import (
        PipelineParallel,
    )

    np.random.seed(4)
    X = np.random.randn(8, 8).astype(np.float32)
    Y = np.random.randn(8, 8).astype(np.float32)

    def run(scaled):
        paddle.seed(29)
        pipe = build_pipe(n_stages=4)
        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=pipe.parameters())
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
        scaler = GradScaler(init_loss_scaling=256.0) if scaled else None
        step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                                 n_microbatches=4, scaler=scaler)
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item())
                for _ in range(4)]

    plain = run(False)
    scaled = run(True)
    # scaling cancels in the update; finite-path numerics align
    np.testing.assert_allclose(plain, scaled, rtol=5e-4, atol=1e-6)


def test_pipeline_predict_matches_single_device_forward():
    """Forward-only compiled pipeline (FleetExecutor distributed-
    inference role, fleet_executor.h:36): predict() over the pp mesh
    must equal the plain eager forward."""
    np.random.seed(1)
    X = np.random.randn(8, 8).astype(np.float32)

    paddle.seed(21)
    pipe = build_pipe(n_stages=4)
    pipe.eval()
    ref = pipe(paddle.to_tensor(X)).numpy()

    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    opt = optimizer.Adam(learning_rate=0.01,
                         parameters=pipe.parameters())
    step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                             n_microbatches=4)
    got = step.predict(paddle.to_tensor(X)).numpy()
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=1e-5)


def test_pipeline_predict_after_training_steps():
    """predict() sees the trained weights (shares the live param
    arrays with the train step)."""
    np.random.seed(2)
    X = np.random.randn(8, 8).astype(np.float32)
    Y = np.zeros((8, 8), np.float32)

    paddle.seed(22)
    pipe = build_pipe(n_stages=4)
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "pp"])
    opt = optimizer.Adam(learning_rate=0.05,
                         parameters=pipe.parameters())
    step = PipelineTrainStep(pipe, nn.MSELoss(), opt, mesh,
                             n_microbatches=4)
    before = step.predict(paddle.to_tensor(X)).numpy()
    for _ in range(5):
        step(paddle.to_tensor(X), paddle.to_tensor(Y))
    after = step.predict(paddle.to_tensor(X)).numpy()
    # trained toward zero: outputs must shrink
    assert np.abs(after).mean() < np.abs(before).mean()
