import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x          # 4
    z = y * x + y      # 8 + 4 = 12; dz/dx = 3x^2 + 2x = 16
    z.backward()
    np.testing.assert_allclose(x.grad.item(), 16.0, rtol=1e-6)


def test_branching_accumulation():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    a = x * 2.0
    b = x * 4.0
    out = a + b
    out.backward()
    np.testing.assert_allclose(x.grad.item(), 6.0)


def test_matmul_grad():
    A = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32),
                         stop_gradient=False)
    B = paddle.to_tensor(np.random.randn(4, 5).astype(np.float32),
                         stop_gradient=False)
    out = paddle.matmul(A, B).sum()
    out.backward()
    np.testing.assert_allclose(A.grad.numpy(),
                               (np.ones((3, 5)) @ B.numpy().T), rtol=1e-5)
    np.testing.assert_allclose(B.grad.numpy(),
                               (A.numpy().T @ np.ones((3, 5))), rtol=1e-5)


def test_numeric_gradient_check():
    """Finite-difference gradient check, the OpTest pattern
    (reference: test/legacy_test/op_test.py:148 get_numeric_gradient)."""
    def f(x):
        return (paddle.tanh(x) * x).sum()

    x0 = np.random.randn(4).astype(np.float32)
    x = paddle.to_tensor(x0, stop_gradient=False)
    f(x).backward()
    eps = 1e-3
    num = np.zeros_like(x0)
    for i in range(4):
        xp, xm = x0.copy(), x0.copy()
        xp[i] += eps
        xm[i] -= eps
        num[i] = (f(paddle.to_tensor(xp)).item() -
                  f(paddle.to_tensor(xm)).item()) / (2 * eps)
    np.testing.assert_allclose(x.grad.numpy(), num, atol=1e-2)


def test_no_grad():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._grad_node is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    y = (x * 2).detach()
    z = y * 3
    z.backward()
    assert x.grad is None


def test_grad_accumulate_multiple_backward():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.item(), 5.0)


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.array([3.0, 1.0, 2.0], dtype=np.float32),
                         stop_gradient=False)
    vals, idx = paddle.topk(x, 2)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 0.0, 1.0])


def test_register_hook():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    y = x * 2
    seen = []

    def hook(g):
        seen.append(float(g.item()))
        return g * 10

    x.register_hook(hook)
    y.backward()
    assert seen == [2.0]
    np.testing.assert_allclose(x.grad.item(), 20.0)


def test_paddle_grad_api():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.item(), 4.0)
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_retain_graph():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.item(), 8.0)


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor()
            return grad * 2

    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.item(), 6.0)
    y.backward()
    np.testing.assert_allclose(x.grad.item(), 2.0)


def test_functional_vjp_jvp():
    def f(x):
        return x * x

    x = paddle.to_tensor(3.0)
    out, g = paddle.autograd.vjp(f, x)
    np.testing.assert_allclose(g.item(), 6.0)
    out, t = paddle.autograd.jvp(f, x)
    np.testing.assert_allclose(t.item(), 6.0)


def test_jacobian_hessian():
    def f(x):
        return (x * x).sum()

    x = paddle.to_tensor([1.0, 2.0])
    jac = paddle.autograd.jacobian(f, x)
    np.testing.assert_allclose(jac.numpy(), [2.0, 4.0])
    hes = paddle.autograd.hessian(f, x)
    np.testing.assert_allclose(hes.numpy(), np.eye(2) * 2, atol=1e-6)


def test_backward_non_scalar_with_grad():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    y.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])
