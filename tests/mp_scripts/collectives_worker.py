"""Per-collective multi-process checks (collective_allreduce_api.py
pattern, test/collective/ in the reference). Run by test_multiprocess.py
with 2 ranks; prints COLLECTIVES_OK on success."""
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from _mp_common import bootstrap

rank, world = bootstrap()

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

assert world == 2, world

# all_reduce
t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
dist.all_reduce(t)
np.testing.assert_allclose(t.numpy(), np.full((4,), 3.0), rtol=0)

# all_reduce max
t = paddle.to_tensor(np.full((3,), float(rank + 1), np.float32))
dist.all_reduce(t, op=dist.ReduceOp.MAX)
np.testing.assert_allclose(t.numpy(), np.full((3,), 2.0), rtol=0)

# broadcast
t = paddle.to_tensor(np.full((4,), float(rank * 7 + 1), np.float32))
dist.broadcast(t, src=1)
np.testing.assert_allclose(t.numpy(), np.full((4,), 8.0), rtol=0)

# all_gather
out = []
t = paddle.to_tensor(np.full((2,), float(rank), np.float32))
dist.all_gather(out, t)
assert len(out) == 2
np.testing.assert_allclose(out[0].numpy(), np.zeros((2,)), rtol=0)
np.testing.assert_allclose(out[1].numpy(), np.ones((2,)), rtol=0)

# reduce_scatter: each rank contributes (world, chunk); gets its summed chunk
src = paddle.to_tensor(
    np.stack([np.full((3,), float(rank + 1), np.float32),
              np.full((3,), float(rank + 10), np.float32)]))
dst = paddle.zeros([3])
dist.reduce_scatter(dst, src)
expect = 3.0 if rank == 0 else 21.0
np.testing.assert_allclose(dst.numpy(), np.full((3,), expect), rtol=0)

# all_to_all
ins = [paddle.to_tensor(np.full((2,), float(rank * 10 + j), np.float32))
       for j in range(2)]
outs = []
dist.all_to_all(outs, ins)
np.testing.assert_allclose(outs[0].numpy(),
                           np.full((2,), float(rank)), rtol=0)
np.testing.assert_allclose(outs[1].numpy(),
                           np.full((2,), float(10 + rank)), rtol=0)

# scatter
if rank == 0:
    parts = [paddle.to_tensor(np.full((2,), 5.0, np.float32)),
             paddle.to_tensor(np.full((2,), 9.0, np.float32))]
else:
    parts = None
t = paddle.zeros([2])
dist.scatter(t, parts, src=0)
expect = 5.0 if rank == 0 else 9.0
np.testing.assert_allclose(t.numpy(), np.full((2,), expect), rtol=0)

# send / recv (store-backed p2p)
if rank == 0:
    dist.send(paddle.to_tensor(np.arange(4, dtype=np.float32)), dst=1)
else:
    r = paddle.zeros([4])
    dist.recv(r, src=0)
    np.testing.assert_allclose(r.numpy(), np.arange(4, dtype=np.float32))

# barrier
dist.barrier()

print(f"rank{rank} COLLECTIVES_OK", flush=True)
