"""Shared bootstrap for multi-process collective test workers.

The reference's collective tests spawn real subprocesses per rank
(test/legacy_test/test_dist_base.py:952); these workers are the same
pattern on the CPU debug backend. The axon sitecustomize pins the
platform via jax.config, so workers must override it BEFORE touching any
backend, then init the distributed runtime through the normal
paddle_tpu entry point.
"""
import os


def bootstrap():
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    from paddle_tpu.distributed import env

    env.init_parallel_env()
    return int(os.environ["PADDLE_TRAINER_ID"]), \
        int(os.environ["PADDLE_TRAINERS_NUM"])
