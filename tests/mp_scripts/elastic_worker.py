"""Elastic scale-in/out worker (driven by test_elastic.py).

Scenario across gang attempts (PADDLE_RESTART_COUNT):
  attempt 0, world 4: last rank dies -> launcher re-forms at world 3
  attempt 1, world 3: ranks train, checkpoint, then the test posts a
      join request -> launcher re-forms at world 4
  attempt 2, world 4: ranks resume from checkpoint and finish clean.

Every attempt rendezvouses for real (jax.distributed) and runs one
cross-process allreduce to prove the re-formed world actually works.
Reference pattern: fleet/elastic/manager.py scale-in/out + checkpoint
resume contract.
"""
import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from _mp_common import bootstrap

rank, world = bootstrap()
attempt = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
out_dir = os.environ["ELASTIC_TEST_DIR"]

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

# prove the re-formed world communicates
t = paddle.to_tensor(np.ones((2,), np.float32))
dist.all_reduce(t)
assert float(t.numpy()[0]) == world, (t.numpy(), world)

# record what this attempt saw
with open(os.path.join(out_dir, f"attempt{attempt}.rank{rank}.json"),
          "w") as f:
    json.dump({"world": world, "attempt": attempt}, f)

ckpt = os.path.join(out_dir, f"ckpt.rank{rank}.npz")

if attempt == 0:
    # simulate training then a node loss: last rank dies mid-job
    np.savez(ckpt, step=3)
    if rank == world - 1:
        time.sleep(0.5)
        sys.exit(1)
    time.sleep(30)  # survivors wait to be gang-killed by the launcher
    sys.exit(1)

# resumed attempts: training continues from the checkpoint
assert os.path.exists(ckpt), "checkpoint from previous attempt missing"
step = int(np.load(ckpt)["step"])
assert step >= 3

if attempt == 1:
    np.savez(ckpt, step=step + 3)
    # run "training" long enough for the test to post a join request;
    # the launcher then re-forms the gang (we get terminated, which is
    # expected — a nonzero exit here is the re-form, not a failure)
    time.sleep(30)
    sys.exit(1)

# attempt >= 2: world must have grown back; finish clean
np.savez(ckpt, step=step + 3)
print(f"rank{rank} ELASTIC_OK world={world} step={step + 3}", flush=True)
