"""Two-replica fleet worker (driven by tests/test_fault_e2e.py).

Boots a tiny-Llama :class:`FleetRouter` with two in-process replicas.
Replica r0 owns the SIGTERM preemption monitor with zero drain grace,
so the signal the driving test delivers mid-run drains r0 immediately
and its in-flight requests hand off to r1. Before serving, the worker
computes the single-engine reference generations for the same request
ids (the per-request sampling stream seeds from the id), so the result
file carries a self-contained token-parity verdict: hand-off must be
invisible AND bit-identical.

Env protocol:
  RESULT_FILE    json written on exit: {finished: {rid: reason},
                 n_tokens: {rid: n}, parity, handoffs,
                 r0_drain_aborted, replicas_dead}
  PROGRESS_FILE  rewritten with the router step number every step
                 (only during the FLEET phase — the parent keys its
                 SIGTERM off this, so the reference run is never hit)
  N_REQUESTS     total requests to admit (default 6)
  MAX_NEW        max_new_tokens per request (default 8)
  STEP_SLEEP     host sleep per router step, widens the SIGTERM window
                 (default 0.05)
"""
import json
import os
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.watchdog import PreemptionMonitor
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.fleet import FleetRouter, InProcessReplica

result_file = os.environ.get("RESULT_FILE")
progress_file = os.environ.get("PROGRESS_FILE")
n_requests = int(os.environ.get("N_REQUESTS", "6"))
max_new = int(os.environ.get("MAX_NEW", "8"))
step_sleep = float(os.environ.get("STEP_SLEEP", "0.05"))

paddle.seed(0)
model = LlamaForCausalLM(LlamaConfig.tiny())
model.eval()


def ecfg():
    return EngineConfig(block_size=4, max_num_seqs=4, max_model_len=64,
                        drain_grace_s=0.0)


rng = np.random.default_rng(21)
prompts = [list(map(int, rng.integers(0, model.config.vocab_size,
                                      size=3 + (i % 4))))
           for i in range(n_requests)]
ids = [f"q{i}" for i in range(n_requests)]
sp = SamplingParams(max_new_tokens=max_new)

# -- phase 1: uninterrupted single-engine reference (the oracle) ----------
ref_eng = LLMEngine(model, ecfg())
for rid, p in zip(ids, prompts):
    ref_eng.add_request(rid, p, sampling=sp)
while ref_eng.has_unfinished():
    ref_eng.step()
ref = {rid: list(ref_eng.get_request(rid).generated) for rid in ids}

# -- phase 2: the fleet run the parent SIGTERMs mid-flight ----------------
monitor = PreemptionMonitor()
router = FleetRouter([
    InProcessReplica(model, ecfg(), replica_id="r0", monitor=monitor),
    InProcessReplica(model, ecfg(), replica_id="r1"),
])
for rid, p in zip(ids, prompts):
    router.add_request(rid, p, sampling=sp)

outs = []
steps = 0
while router.has_unfinished():
    outs.extend(router.step())
    steps += 1
    if progress_file:
        with open(progress_file, "w") as f:
            f.write(str(steps))
    if step_sleep:
        time.sleep(step_sleep)

final = {o.request_id: o for o in outs if o.finished}
r0 = router._by_id("r0")
payload = {
    "finished": {r: final[r].finish_reason for r in ids if r in final},
    "n_tokens": {r: len(final[r].generated) for r in ids if r in final},
    "parity": all(r in final and final[r].generated == ref[r]
                  for r in ids),
    "handoffs": router.num_handoffs,
    "r0_drain_aborted": r0.engine.num_drain_aborted,
    "replicas_dead": router.num_replicas_dead,
}
if result_file:
    with open(result_file + ".tmp", "w") as f:
        json.dump(payload, f)
    os.replace(result_file + ".tmp", result_file)
print("FLEET_WORKER_DONE parity=%s handoffs=%d"
      % (payload["parity"], payload["handoffs"]), flush=True)
