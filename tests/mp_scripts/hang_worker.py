"""Watchdog integration worker (driven by test_elastic.py).

Attempt 0: the compiled train step contains a host callback that sleeps
past the step deadline — the watchdog must dump stacks and abort this
process so the launcher restarts the gang. Attempt 1: no hang; training
finishes clean. Reference contract: comm_task_manager.cc hang abort +
launcher restart loop."""
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer

attempt = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
hang = attempt == 0


class MaybeHang(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 1)

    def forward(self, x):
        h = self.fc(x)
        if hang:
            # device-side hang stand-in: a host callback that never
            # finishes within the deadline (stop_gradient: callbacks
            # have no VJP and the hang is forward-only anyway)
            d = jax.lax.stop_gradient(h.sum()._data)
            z = jax.pure_callback(
                lambda v: (time.sleep(30), np.zeros((), np.float32))[1],
                jax.ShapeDtypeStruct((), np.float32), d)
            h = h + z
        return h


paddle.seed(0)
m = MaybeHang()
opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
X = paddle.randn([8, 4])
Y = paddle.randn([8, 1])
loss = step(X, Y)
# host fetch forces us to wait on the (hung) device step; the watchdog
# must fire first and abort the process
print("loss:", float(loss.item()), flush=True)
print(f"HANG_WORKER_DONE attempt={attempt}", flush=True)
