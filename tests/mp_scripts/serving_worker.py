"""Serving worker with graceful drain (driven by tests/test_fault_e2e.py).

Boots a tiny-Llama LLMEngine, installs the SIGTERM preemption handler,
admits ``N_REQUESTS`` mixed-length requests, and serves until done or
drained. The driving test SIGTERMs this process (directly, or through
the distributed launcher's fan-out) mid-run and asserts a clean rc-0
exit with every request accounted for: completed ones with their token
counts, drained ones with ``finish_reason='aborted:drain'``.

Env protocol:
  RESULT_FILE    json written on exit: {finished: {rid: reason},
                 n_tokens: {rid: n}, drained, drain_aborted,
                 blocks_clean}
  PROGRESS_FILE  rewritten with the engine step number every step
  N_REQUESTS     total requests to admit (default 8)
  MAX_NEW        max_new_tokens per request (default 16)
  STEP_SLEEP     host sleep per step, widens the SIGTERM window
                 (default 0.05)
"""
import json
import os
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams

result_file = os.environ.get("RESULT_FILE")
progress_file = os.environ.get("PROGRESS_FILE")
n_requests = int(os.environ.get("N_REQUESTS", "8"))
max_new = int(os.environ.get("MAX_NEW", "16"))
step_sleep = float(os.environ.get("STEP_SLEEP", "0.05"))

paddle.seed(0)
model = LlamaForCausalLM(LlamaConfig.tiny())
model.eval()

eng = LLMEngine(model, EngineConfig(block_size=4, max_num_seqs=4,
                                    max_model_len=64))
eng.install_preemption_handler()

rng = np.random.default_rng(3)
sp = SamplingParams(max_new_tokens=max_new)
rids = [eng.add_request(
    list(map(int, rng.integers(0, model.config.vocab_size,
                               size=3 + (i % 4)))), sampling=sp)
    for i in range(n_requests)]

outs = []
steps = 0
while eng.has_unfinished():
    outs.extend(eng.step())
    steps += 1
    if progress_file:
        with open(progress_file, "w") as f:
            f.write(str(steps))
    if step_sleep:
        time.sleep(step_sleep)

final = {o.request_id: o for o in outs if o.finished}
payload = {
    "finished": {r: final[r].finish_reason for r in rids if r in final},
    "n_tokens": {r: len(final[r].generated)
                 for r in rids if r in final},
    "drained": eng.drained,
    "drain_aborted": eng.num_drain_aborted,
    "blocks_clean":
        eng.block_manager.num_free_blocks == eng.cfg.num_blocks,
}
if result_file:
    with open(result_file + ".tmp", "w") as f:
        json.dump(payload, f)
    os.replace(result_file + ".tmp", result_file)
print("SERVING_WORKER_DONE drained=%s" % payload["drained"], flush=True)
