"""Fault-tolerant training worker (driven by tests/test_fault_e2e.py).

Trains a tiny model with CheckpointManager auto-resume, saving every
``SAVE_EVERY`` steps. The driving test injects faults through
PADDLE_FAULTS (see paddle_tpu/testing/faults.py), SIGKILLs this process
mid-write, or SIGTERMs it to exercise the preemption save-and-exit path,
then re-runs it to prove resume lands on the last COMMITTED step.

Env protocol:
  CKPT_ROOT      checkpoint directory (required)
  TOTAL_STEPS    stop after this step (default 6)
  SAVE_EVERY     save interval in steps (default 1)
  STEP_SLEEP     host sleep per step, widens signal windows (default 0)
  RESULT_FILE    json written on clean exit:
                 {resumed_from, final_step, committed, preempted_at}
  PROGRESS_FILE  rewritten with the current step number every step
  INSTALL_PREEMPT=1  install the SIGTERM preemption handler
"""
import json
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.checkpoint import CheckpointManager

root = os.environ["CKPT_ROOT"]
total = int(os.environ.get("TOTAL_STEPS", "6"))
save_every = int(os.environ.get("SAVE_EVERY", "1"))
step_sleep = float(os.environ.get("STEP_SLEEP", "0"))
result_file = os.environ.get("RESULT_FILE")
progress_file = os.environ.get("PROGRESS_FILE")

paddle.seed(0)
m = nn.Linear(4, 4)
opt = optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
train = paddle.jit.TrainStep(m, nn.MSELoss(), opt)

mgr = CheckpointManager(root, keep_last_n=2, async_save=True,
                        save_interval_steps=save_every)
if os.environ.get("INSTALL_PREEMPT"):
    mgr.install_preemption_handler()

# state template AFTER TrainStep init so optimizer slots exist; restore
# fills the live param/slot arrays in place and set_state_dict pushes
# the step counter back so Adam bias correction resumes correctly
state = {"model": m.state_dict(), "opt": opt.state_dict()}
resumed_from = mgr.restore_or_initialize(state)
if resumed_from is not None:
    opt.set_state_dict(state["opt"])
start = resumed_from or 0

rng = np.random.default_rng(42)
X = paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32))
Y = paddle.to_tensor(rng.normal(size=(8, 4)).astype(np.float32))


def write_result(extra):
    if result_file:
        payload = {"resumed_from": resumed_from, "committed":
                   mgr.all_steps(), "opt_step": int(opt._step_count),
                   **extra}
        with open(result_file + ".tmp", "w") as f:
            json.dump(payload, f)
        os.replace(result_file + ".tmp", result_file)


step = start
for step in range(start + 1, total + 1):
    train(X, Y)
    mgr.save(step, {"model": m.state_dict(), "opt": opt.state_dict()})
    if progress_file:
        with open(progress_file, "w") as f:
            f.write(str(step))
    if step_sleep:
        time.sleep(step_sleep)
    if mgr.reached_preemption(step):
        mgr.save(step, {"model": m.state_dict(),
                        "opt": opt.state_dict()},
                 block=True, force=True)
        write_result({"preempted_at": step, "final_step": step})
        print(f"PREEMPTED_SAVED step={step}", flush=True)
        sys.exit(0)

mgr.wait()
write_result({"final_step": step})
print(f"CKPT_WORKER_DONE step={step}", flush=True)
