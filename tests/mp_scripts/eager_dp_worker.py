"""Eager DataParallel loss-alignment check (2 ranks).

Reference pattern: test/collective/fleet parallel_dygraph tests compare
DP-trained losses against a serial run (test_dist_base.py loss compare).
Each rank trains a DataParallel-wrapped MLP on its half of a fixed
batch; rank 0 also trains an identical serial model on the full batch
and asserts the loss curves match (mean loss + averaged grads == serial
full-batch mean loss). Also exercises no_sync accumulation.
Prints EAGER_DP_OK on success."""
import sys

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from _mp_common import bootstrap

rank, world = bootstrap()

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
import paddle_tpu.distributed as dist

assert world == 2


def make_model():
    paddle.seed(7)
    return nn.Sequential(
        nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 1))


rng = np.random.RandomState(0)
X = rng.randn(16, 8).astype(np.float32)
Y = rng.randn(16, 1).astype(np.float32)

# --- DP run: each rank sees its half -----------------------------------
model = make_model()
if rank == 1:
    # desync rank1's init to prove the wrap-time broadcast fixes it
    for p in model.parameters():
        p.set_value(p.numpy() + 1.0)
dp = dist.DataParallel(model)
opt = optimizer.SGD(learning_rate=0.1, parameters=dp.parameters())
loss_fn = nn.MSELoss()

xs = X[rank * 8:(rank + 1) * 8]
ys = Y[rank * 8:(rank + 1) * 8]
dp_losses = []
for step in range(4):
    loss = loss_fn(dp(paddle.to_tensor(xs)), paddle.to_tensor(ys))
    loss.backward()
    opt.step()
    opt.clear_grad()
    # global mean loss across ranks for comparison
    lt = paddle.to_tensor(np.float32(loss.item()))
    dist.all_reduce(lt, op=dist.ReduceOp.AVG)
    dp_losses.append(float(lt.numpy()))

# --- no_sync: two local accumulations, then one synced backward --------
with dp.no_sync():
    loss = loss_fn(dp(paddle.to_tensor(xs)), paddle.to_tensor(ys))
    loss.backward()
g_local = model[0].weight.grad.numpy().copy()
loss = loss_fn(dp(paddle.to_tensor(xs)), paddle.to_tensor(ys))
loss.backward()
g_synced = model[0].weight.grad.numpy()
opt.clear_grad()
# after sync, the grad is the cross-rank average of the 2x accumulated
# local grad; with identical params the accumulated local grad is 2*g1
gather = []
dist.all_gather(gather, paddle.to_tensor(g_local / 1.0))
avg_accum = (gather[0].numpy() + gather[1].numpy())  # sum of per-rank g1
np.testing.assert_allclose(g_synced, avg_accum, rtol=2e-4, atol=2e-5)

# --- serial reference on rank 0 ----------------------------------------
if rank == 0:
    ref = make_model()
    ropt = optimizer.SGD(learning_rate=0.1, parameters=ref.parameters())
    ref_losses = []
    for step in range(4):
        loss = loss_fn(ref(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        ropt.step()
        ropt.clear_grad()
        ref_losses.append(float(loss.item()))
    np.testing.assert_allclose(dp_losses, ref_losses, rtol=1e-4,
                               atol=1e-5)

print(f"rank{rank} EAGER_DP_OK", flush=True)
