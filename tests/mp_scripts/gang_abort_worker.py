"""Cross-rank abort worker (driven by test_elastic.py).

Rank 1 arms a tagged collective probe that never completes — its
watchdog must fire with the tag, broadcast the abort through the store,
and exit 6. Rank 0 is healthy (no hung work) and must learn of rank 1's
abort via the store watch and exit 7 well before its own (absent)
timeout would ever fire. Reference contract: comm_task_manager.cc abort
propagates to the whole process group."""
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")

from paddle_tpu.distributed.watchdog import default_watchdog

rank = int(os.environ["PADDLE_TRAINER_ID"])
wd = default_watchdog()
print(f"rank {rank} up, watchdog enabled={wd.enabled}", flush=True)

if rank == 1:
    # a collective that never completes: arm with the collective tag and
    # never attach/disarm (the _eager_collective probe shape)
    wd.arm("all_reduce@ranks[0, 1]")
    time.sleep(60)
    print("RANK1_SHOULD_NOT_REACH_HERE", flush=True)
else:
    wd.start_abort_watch()
    # healthy training loop stand-in
    for _ in range(600):
        time.sleep(0.1)
    print("RANK0_SHOULD_NOT_REACH_HERE", flush=True)
