"""Round-5 op/optimizer gap closures (VERDICT r4 missing #3):
grid_sample + affine_grid (STN), ctc_loss/CTCLoss, LBFGS, ASGD, Rprop.

Numpy/torch-referenced values with finite-difference gradient checks;
plus the VERDICT "done" criteria: a tiny STN trains and a CTC toy model
trains.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F


# ---------------------------------------------------------------------------
# grid_sample / affine_grid
# ---------------------------------------------------------------------------

def test_grid_sample_identity_grid():
    """An identity affine grid must reproduce the input."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 4, 5)).astype("float32")
    theta = np.tile(np.asarray([[1, 0, 0], [0, 1, 0]], "float32"),
                    (2, 1, 1))
    grid = F.affine_grid(paddle.to_tensor(theta), [2, 3, 4, 5],
                         align_corners=True)
    out = F.grid_sample(paddle.to_tensor(x), grid, align_corners=True)
    np.testing.assert_allclose(out.numpy(), x, atol=1e-5)


def test_grid_sample_reference_example():
    """The documented reference example (nn/functional/vision.py:128)."""
    x = paddle.to_tensor(np.asarray(
        [[[[-0.6, 0.8, -0.5], [-0.5, 0.2, 1.2], [1.4, 0.3, -0.2]]]],
        "float64"))
    grid = paddle.to_tensor(np.asarray(
        [[[[0.2, 0.3], [-0.4, -0.3], [-0.9, 0.3], [-0.9, -0.6]],
          [[0.4, 0.1], [0.9, -0.8], [0.4, 0.5], [0.5, -0.2]],
          [[0.1, -0.8], [-0.3, -1.0], [0.7, 0.4], [0.2, 0.8]]]],
        "float64"))
    y = F.grid_sample(x, grid, mode="bilinear", padding_mode="border",
                      align_corners=True)
    want = np.asarray([[[[0.34, 0.016, 0.086, -0.448],
                         [0.55, -0.076, 0.35, 0.59],
                         [0.596, 0.38, 0.52, 0.24]]]])
    np.testing.assert_allclose(y.numpy(), want, atol=1e-6)


@pytest.mark.parametrize("mode", ["bilinear", "nearest"])
@pytest.mark.parametrize("padding", ["zeros", "border", "reflection"])
def test_grid_sample_modes_finite(mode, padding):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, 2, 5, 6)).astype("float32")
    grid = (rng.random((2, 3, 4, 2)).astype("float32") * 2.6 - 1.3)
    out = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                        mode=mode, padding_mode=padding)
    assert tuple(out.shape) == (2, 2, 3, 4)
    assert np.isfinite(out.numpy()).all()


def test_grid_sample_grad_finite_difference():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((1, 2, 4, 4)).astype("float32")
    grid = (rng.random((1, 3, 3, 2)).astype("float32") * 1.6 - 0.8)

    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    gt = paddle.to_tensor(grid)
    gt.stop_gradient = False
    F.grid_sample(xt, gt, padding_mode="border").sum().backward()

    eps = 1e-3
    for idx in [(0, 0, 0, 0), (0, 2, 1, 1), (0, 1, 2, 0)]:
        gp, gm = grid.copy(), grid.copy()
        gp[idx] += eps
        gm[idx] -= eps
        fp = float(F.grid_sample(paddle.to_tensor(x),
                                 paddle.to_tensor(gp),
                                 padding_mode="border").sum().numpy())
        fm = float(F.grid_sample(paddle.to_tensor(x),
                                 paddle.to_tensor(gm),
                                 padding_mode="border").sum().numpy())
        np.testing.assert_allclose(gt.grad.numpy()[idx],
                                   (fp - fm) / (2 * eps), atol=2e-2)
    # grad wrt x: sum of bilinear weights per output = each weight quad
    # sums to 1, so total dL/dx sums to number of in-bounds samples
    assert np.isfinite(xt.grad.numpy()).all()


def test_affine_grid_5d_shapes():
    theta = paddle.randn([2, 3, 4])
    g = F.affine_grid(theta, [2, 1, 3, 4, 5], align_corners=False)
    assert tuple(g.shape) == (2, 3, 4, 5, 3)


def test_tiny_stn_trains():
    """Spatial-transformer localization net: loss must descend through
    affine_grid + grid_sample (the VERDICT done criterion)."""
    paddle.seed(0)

    class STN(nn.Layer):
        def __init__(self):
            super().__init__()
            self.loc = nn.Linear(16, 6)

        def forward(self, x):
            theta = self.loc(x.reshape([x.shape[0], -1]))
            theta = theta.reshape([x.shape[0], 2, 3])
            grid = F.affine_grid(theta, list(x.shape), align_corners=True)
            return F.grid_sample(x, grid, align_corners=True)

    net = STN()
    # standard STN init: localization starts at the identity transform
    with paddle.no_grad():
        net.loc.weight.set_value(np.zeros((16, 6), "float32"))
        net.loc.bias.set_value(
            np.asarray([1, 0, 0, 0, 1, 0], "float32"))
    opt = optimizer.Adam(learning_rate=0.02,
                         parameters=net.parameters())
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 1, 4, 4))
                         .astype("float32"))
    # target = a known small affine warp (shift + slight scale), so the
    # optimum is a reachable constant theta
    theta_true = np.tile(np.asarray([[0.9, 0.0, 0.25], [0.0, 1.1, -0.2]],
                                    "float32"), (8, 1, 1))
    with paddle.no_grad():
        target = F.grid_sample(
            x, F.affine_grid(paddle.to_tensor(theta_true), [8, 1, 4, 4],
                             align_corners=True), align_corners=True)
    target = paddle.to_tensor(target.numpy())
    losses = []
    for _ in range(60):
        loss = ((net(x) - target) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.3, losses[::10]


# ---------------------------------------------------------------------------
# ctc_loss
# ---------------------------------------------------------------------------

def _np_ctc_loss(logits, labels, in_len, lab_len, blank=0):
    """Direct log-domain forward algorithm in numpy (reference math)."""
    T, C = logits.shape
    lp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True))
                         .sum(-1, keepdims=True)) - \
        logits.max(-1, keepdims=True)
    lab = labels[:lab_len]
    ext = [blank]
    for v in lab:
        ext += [int(v), blank]
    S = len(ext)
    NEG = -1e30
    alpha = np.full(S, NEG)
    alpha[0] = lp[0, blank]
    if S > 1:
        alpha[1] = lp[0, ext[1]]
    for t in range(1, in_len):
        new = np.full(S, NEG)
        for s in range(S):
            cands = [alpha[s]]
            if s >= 1:
                cands.append(alpha[s - 1])
            if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                cands.append(alpha[s - 2])
            m = max(cands)
            if m > NEG:
                new[s] = m + np.log(sum(np.exp(c - m) for c in cands)) \
                    + lp[t, ext[s]]
        alpha = new
    ends = [alpha[S - 1]]
    if S > 1:
        ends.append(alpha[S - 2])
    m = max(ends)
    return -(m + np.log(sum(np.exp(e - m) for e in ends)))


def test_ctc_loss_matches_numpy_forward():
    rng = np.random.default_rng(0)
    T, B, C, L = 10, 2, 5, 3
    logits = rng.standard_normal((T, B, C)).astype("float32")
    labels = rng.integers(1, C, (B, L)).astype("int32")
    in_len = np.asarray([10, 7], "int64")
    lab_len = np.asarray([3, 2], "int64")
    got = F.ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                     paddle.to_tensor(in_len), paddle.to_tensor(lab_len),
                     reduction="none").numpy()
    for b in range(B):
        want = _np_ctc_loss(logits[:, b], labels[b], int(in_len[b]),
                            int(lab_len[b]))
        np.testing.assert_allclose(got[b], want, rtol=1e-4)


def test_ctc_loss_repeated_labels():
    """Repeated labels need the skip-transition exclusion."""
    rng = np.random.default_rng(1)
    logits = rng.standard_normal((8, 1, 4)).astype("float32")
    labels = np.asarray([[2, 2, 3]], "int32")
    got = F.ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                     paddle.to_tensor(np.asarray([8], "int64")),
                     paddle.to_tensor(np.asarray([3], "int64")),
                     reduction="none").numpy()
    want = _np_ctc_loss(logits[:, 0], labels[0], 8, 3)
    np.testing.assert_allclose(got[0], want, rtol=1e-4)


def test_ctc_loss_grad_finite_difference():
    rng = np.random.default_rng(2)
    logits = rng.standard_normal((6, 1, 4)).astype("float32")
    labels = np.asarray([[1, 2]], "int32")
    il = paddle.to_tensor(np.asarray([6], "int64"))
    ll = paddle.to_tensor(np.asarray([2], "int64"))

    lt = paddle.to_tensor(logits)
    lt.stop_gradient = False
    F.ctc_loss(lt, paddle.to_tensor(labels), il, ll,
               reduction="sum").backward()
    eps = 1e-3
    for idx in [(0, 0, 1), (3, 0, 0), (5, 0, 2)]:
        lp, lm = logits.copy(), logits.copy()
        lp[idx] += eps
        lm[idx] -= eps
        fp = _np_ctc_loss(lp[:, 0], labels[0], 6, 2)
        fm = _np_ctc_loss(lm[:, 0], labels[0], 6, 2)
        np.testing.assert_allclose(lt.grad.numpy()[idx],
                                   (fp - fm) / (2 * eps), atol=5e-3)


def test_ctc_toy_model_trains():
    """A linear acoustic model must learn a fixed label sequence (the
    VERDICT done criterion)."""
    paddle.seed(0)
    T, B, C = 12, 4, 5
    feat = nn.Linear(8, C)
    opt = optimizer.Adam(learning_rate=0.05,
                         parameters=feat.parameters())
    crit = nn.CTCLoss(blank=0)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((T, B, 8)).astype("float32"))
    labels = paddle.to_tensor(
        rng.integers(1, C, (B, 3)).astype("int32"))
    il = paddle.to_tensor(np.full(B, T, "int64"))
    ll = paddle.to_tensor(np.full(B, 3, "int64"))
    losses = []
    for _ in range(40):
        loss = crit(feat(x), labels, il, ll)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_ctc_loss_reductions():
    rng = np.random.default_rng(3)
    logits = rng.standard_normal((6, 2, 4)).astype("float32")
    labels = np.asarray([[1, 2], [3, 0]], "int32")
    il = paddle.to_tensor(np.asarray([6, 5], "int64"))
    ll = paddle.to_tensor(np.asarray([2, 1], "int64"))
    args = (paddle.to_tensor(logits), paddle.to_tensor(labels), il, ll)
    none = F.ctc_loss(*args, reduction="none").numpy()
    s = F.ctc_loss(*args, reduction="sum").numpy()
    m = F.ctc_loss(*args, reduction="mean").numpy()
    np.testing.assert_allclose(s, none.sum(), rtol=1e-6)
    np.testing.assert_allclose(m, (none / np.asarray([2, 1])).mean(),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# optimizers
# ---------------------------------------------------------------------------

def test_lbfgs_strong_wolfe_quadratic():
    A = np.asarray([[3.0, 0.5], [0.5, 1.0]], "float32")
    b = np.asarray([1.0, -2.0], "float32")
    x = paddle.to_tensor(np.zeros(2, "float32"))
    x.stop_gradient = False
    At, bt = paddle.to_tensor(A), paddle.to_tensor(b)
    opt = optimizer.LBFGS(learning_rate=1.0,
                          line_search_fn="strong_wolfe", parameters=[x])

    def closure():
        opt.clear_grad()
        loss = 0.5 * (x @ paddle.matmul(At, x)) - bt @ x
        loss.backward()
        return loss

    for _ in range(5):
        opt.step(closure)
    np.testing.assert_allclose(x.numpy(), np.linalg.solve(A, b),
                               atol=1e-4)


def test_lbfgs_reaches_least_squares_optimum():
    paddle.seed(0)
    lin = nn.Linear(4, 1)
    X = paddle.randn([16, 4])
    Y = paddle.randn([16, 1])
    Xa = np.concatenate([X.numpy(), np.ones((16, 1), "float32")], 1)
    w, *_ = np.linalg.lstsq(Xa, Y.numpy(), rcond=None)
    opt_loss = float(np.mean((Xa @ w - Y.numpy()) ** 2))
    opt = optimizer.LBFGS(parameters=lin.parameters(),
                          line_search_fn="strong_wolfe")

    def closure():
        opt.clear_grad()
        loss = ((lin(X) - Y) ** 2).mean()
        loss.backward()
        return loss

    for _ in range(3):
        opt.step(closure)
    assert float(closure().numpy()) < opt_loss * 1.02 + 1e-6


def test_asgd_window_average():
    p = paddle.to_tensor(np.zeros(3, "float32"))
    p.stop_gradient = False
    opt = optimizer.ASGD(learning_rate=0.1, batch_num=2, parameters=[p])
    (p * paddle.to_tensor([1.0, 2.0, 3.0])).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [-0.1, -0.2, -0.3], rtol=1e-6)
    opt.clear_grad()
    (p * paddle.to_tensor([3.0, 2.0, 1.0])).sum().backward()
    opt.step()  # window avg of the two grads: [2,2,2]
    np.testing.assert_allclose(p.numpy(), [-0.3, -0.4, -0.5], rtol=1e-5)


def test_rprop_sign_adaptation():
    p = paddle.to_tensor(np.asarray([1.0, 1.0], "float32"))
    p.stop_gradient = False
    opt = optimizer.Rprop(learning_rate=0.01, parameters=[p],
                          etas=(0.5, 1.2),
                          learning_rate_range=(1e-4, 1.0))
    (p * paddle.to_tensor([1.0, -1.0])).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.99, 1.01], rtol=1e-5)
    opt.clear_grad()
    (p * paddle.to_tensor([1.0, 1.0])).sum().backward()
    opt.step()  # elem0 same sign: lr*1.2; elem1 flipped: skip + shrink
    np.testing.assert_allclose(p.numpy(), [0.99 - 0.012, 1.01],
                               rtol=1e-5)


def test_rprop_validates_ranges():
    p = paddle.to_tensor(np.zeros(1, "float32"))
    with pytest.raises(ValueError):
        optimizer.Rprop(learning_rate=2.0,
                        learning_rate_range=(1e-4, 1.0), parameters=[p])
    with pytest.raises(ValueError):
        optimizer.Rprop(etas=(1.5, 1.2), parameters=[p])


# ---------------------------------------------------------------------------
# rnnt_loss
# ---------------------------------------------------------------------------

def _np_rnnt_loss(logits, labels, T, U, blank=0):
    """Direct log-domain transducer forward DP in numpy."""
    mx = logits.max(-1, keepdims=True)
    lp = logits - np.log(np.exp(logits - mx).sum(-1, keepdims=True)) - mx
    NEG = -1e30
    alpha = np.full((T, U + 1), NEG)

    def la(a, b):
        m = max(a, b)
        return NEG if m <= NEG else \
            m + np.log(np.exp(a - m) + np.exp(b - m))

    alpha[0, 0] = 0.0
    for u in range(1, U + 1):
        alpha[0, u] = alpha[0, u - 1] + lp[0, u - 1, labels[u - 1]]
    for t in range(1, T):
        alpha[t, 0] = alpha[t - 1, 0] + lp[t - 1, 0, blank]
        for u in range(1, U + 1):
            alpha[t, u] = la(
                alpha[t - 1, u] + lp[t - 1, u, blank],
                alpha[t, u - 1] + lp[t, u - 1, labels[u - 1]])
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


def test_rnnt_loss_matches_numpy_dp():
    rng = np.random.default_rng(0)
    B, T, U, D = 3, 6, 3, 5
    logits = rng.standard_normal((B, T, U + 1, D)).astype("float32")
    labels = rng.integers(1, D, (B, U)).astype("int32")
    in_len = np.asarray([6, 5, 4], "int64")
    lab_len = np.asarray([3, 2, 1], "int64")
    got = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                      paddle.to_tensor(in_len),
                      paddle.to_tensor(lab_len), fastemit_lambda=0.0,
                      reduction="none").numpy()
    for b in range(B):
        want = _np_rnnt_loss(logits[b], labels[b], int(in_len[b]),
                             int(lab_len[b]))
        np.testing.assert_allclose(got[b], want, rtol=1e-4)


def test_rnnt_loss_grad_finite_difference():
    rng = np.random.default_rng(1)
    B, T, U, D = 1, 4, 2, 4
    logits = rng.standard_normal((B, T, U + 1, D)).astype("float32")
    labels = np.asarray([[1, 2]], "int32")
    il = paddle.to_tensor(np.asarray([4], "int64"))
    ll = paddle.to_tensor(np.asarray([2], "int64"))
    lt = paddle.to_tensor(logits)
    lt.stop_gradient = False
    F.rnnt_loss(lt, paddle.to_tensor(labels), il, ll,
                fastemit_lambda=0.0, reduction="sum").backward()
    eps = 1e-3
    for idx in [(0, 0, 0, 1), (0, 2, 1, 0), (0, 3, 2, 3)]:
        p1, p2 = logits.copy(), logits.copy()
        p1[idx] += eps
        p2[idx] -= eps
        fd = (_np_rnnt_loss(p1[0], labels[0], 4, 2)
              - _np_rnnt_loss(p2[0], labels[0], 4, 2)) / (2 * eps)
        np.testing.assert_allclose(lt.grad.numpy()[idx], fd, atol=5e-3)


def test_rnnt_fastemit_preserves_value_changes_grad():
    """FastEmit (arxiv 2010.11148) is gradient-level regularization: the
    loss VALUE is unchanged, label-emission gradients are scaled."""
    rng = np.random.default_rng(2)
    B, T, U, D = 2, 5, 2, 4
    logits = rng.standard_normal((B, T, U + 1, D)).astype("float32")
    labels = rng.integers(1, D, (B, U)).astype("int32")
    il = paddle.to_tensor(np.asarray([5, 4], "int64"))
    ll = paddle.to_tensor(np.asarray([2, 1], "int64"))
    args = (paddle.to_tensor(labels), il, ll)
    l0 = F.rnnt_loss(paddle.to_tensor(logits), *args,
                     fastemit_lambda=0.0, reduction="none").numpy()
    l1 = F.rnnt_loss(paddle.to_tensor(logits), *args,
                     fastemit_lambda=0.5, reduction="none").numpy()
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    g = []
    for lam in (0.0, 0.5):
        lt = paddle.to_tensor(logits)
        lt.stop_gradient = False
        F.rnnt_loss(lt, *args, fastemit_lambda=lam,
                    reduction="sum").backward()
        g.append(lt.grad.numpy())
    assert not np.allclose(g[0], g[1])


def test_rnnt_toy_model_trains():
    paddle.seed(0)
    B, T, U, D = 4, 8, 3, 5
    joint = nn.Linear(8, D)
    opt = optimizer.Adam(learning_rate=0.05,
                         parameters=joint.parameters())
    crit = nn.RNNTLoss(blank=0, fastemit_lambda=0.0)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(
        rng.standard_normal((B, T, U + 1, 8)).astype("float32"))
    labels = paddle.to_tensor(rng.integers(1, D, (B, U)).astype("int32"))
    il = paddle.to_tensor(np.full(B, T, "int64"))
    ll = paddle.to_tensor(np.full(B, U, "int64"))
    losses = []
    for _ in range(40):
        loss = crit(joint(x), labels, il, ll)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.6, losses[::10]
