"""Fleet-global prefix cache pins (ISSUE 14).

Four layers, cheapest first:

* content chain hashes + bounded trie digests (BlockManager units) —
  the advertisement format both sides of the wire agree on;
* engine prefix export/import with no request attached — geometry and
  checksum validation, idempotence, and the no-eviction import policy;
* router policy — prefix-affine dispatch concentrates shared-prefix
  work on warm replicas, advertisement decay and STALE adverts degrade
  to plain prefill (miss, never corruption), proactive hot-prefix
  ships land on cold replicas and the ``fleet.prefix_ship_*`` fault
  points degrade to nothing worse than a cold destination;
* the randomized advertisement/eviction coherence storm — waves of
  shared-prefix traffic against deliberately tiny caches that evict
  advertised prefixes mid-flight, pinned on exact block accounting and
  greedy AND sampled token parity vs a single-engine reference.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.block_manager import (
    BlockManager, prefix_chain_hashes,
)
from paddle_tpu.serving.fleet import (
    FleetConfig, FleetRouter, InProcessReplica,
)
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _no_fault_leak():
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    return model


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_num_seqs", 8)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("drain_grace_s", 0.0)
    return EngineConfig(**kw)


def _reference(model, prompts, sp, ids, cfg=None):
    """Uninterrupted single-engine run: the token-identity oracle.
    Request ids matter — the per-request sampling stream seeds from
    the id."""
    eng = LLMEngine(model, cfg or _ecfg())
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < 600
    return {rid: list(eng.get_request(rid).generated) for rid in ids}


def _drain_router(router, max_steps=400):
    outs = []
    for _ in range(max_steps):
        if not router.has_unfinished():
            return outs
        outs.extend(router.step())
    raise AssertionError("router failed to converge")


def _evict_all_cached(bm):
    """Reclaim every cached-free block (a claim/release cycle over the
    whole pool), dropping all prefix registrations while leaving the
    pool full."""
    taken = [bm._claim() for _ in range(bm.num_free_blocks)]
    for b in taken:
        bm._release(b)


# ---------------------------------------------------------------------------
# content chain hashes
# ---------------------------------------------------------------------------
class TestChainHashes:
    def test_deterministic_and_chained(self):
        toks = list(range(12))
        a = prefix_chain_hashes(toks, 4)
        b = prefix_chain_hashes(toks, 4)
        assert a == b and len(a) == 3
        assert len(set(a)) == 3  # every depth hashes differently

    def test_partial_blocks_excluded(self):
        assert prefix_chain_hashes([1, 2, 3], 4) == []
        assert len(prefix_chain_hashes([1, 2, 3, 4, 5], 4)) == 1

    def test_chain_folds_ancestors(self):
        # equal last block, different first block -> different chain
        a = prefix_chain_hashes([1, 2, 3, 4, 9, 9, 9, 9], 4)
        b = prefix_chain_hashes([5, 6, 7, 8, 9, 9, 9, 9], 4)
        assert a[1] != b[1]

    def test_matches_block_manager_registration(self):
        bm = BlockManager(16, 4, enable_prefix_cache=True)
        toks = list(range(8))
        bm.allocate("a", 8, tokens=toks)
        bm.commit_prefix("a", toks, 8)
        assert set(prefix_chain_hashes(toks, 4)) == \
            set(bm.prefix_digest()["h"])


# ---------------------------------------------------------------------------
# trie digests + hash-addressed lookup (BlockManager)
# ---------------------------------------------------------------------------
class TestPrefixDigest:
    def _warm(self, bm, toks, rid="a"):
        bm.allocate(rid, len(toks), tokens=toks)
        bm.commit_prefix(rid, toks, len(toks))

    def test_digest_tracks_registration_and_eviction(self):
        bm = BlockManager(8, 4, enable_prefix_cache=True)
        toks = list(range(8))
        self._warm(bm, toks)
        d = bm.prefix_digest()
        assert d["bs"] == 4 and d["n"] == 2
        assert sorted(d["h"].values()) == [4, 8]
        bm.free("a")
        # cached-free: still advertised until actually reclaimed
        assert bm.prefix_digest()["n"] == 2
        # fill the whole pool with fresh content: claiming the two
        # cached-free blocks is the eviction point
        junk = list(range(100, 132))
        bm.allocate("junk", 32, tokens=junk)
        assert bm.prefix_digest()["h"] == {}
        bm.free("junk")
        bm.check_invariants()

    def test_digest_cap_keeps_shallow_entries(self):
        bm = BlockManager(64, 2, enable_prefix_cache=True)
        toks = list(range(40))  # 20 chain entries
        self._warm(bm, toks)
        d = bm.prefix_digest(max_entries=5)
        assert d["n"] == 20 and len(d["h"]) == 5
        # shallow-first: the kept entries are exactly depths 1..5, so
        # every kept entry's ancestors are kept (the router walk stays
        # break-on-first-miss correct against a capped digest)
        assert sorted(d["h"].values()) == [2, 4, 6, 8, 10]

    def test_digest_cached_per_revision(self):
        bm = BlockManager(16, 4, enable_prefix_cache=True)
        self._warm(bm, list(range(8)))
        assert bm.prefix_digest() is bm.prefix_digest()
        before = bm.prefix_digest()
        self._warm(bm, list(range(100, 108)), rid="b")
        assert bm.prefix_digest() is not before

    def test_blocks_by_hash_resolves_and_degrades(self):
        bm = BlockManager(16, 4, enable_prefix_cache=True)
        toks = list(range(12))
        self._warm(bm, toks)
        deep = prefix_chain_hashes(toks, 4)[-1]
        tokens, blocks = bm.prefix_blocks_by_hash(deep)
        assert tokens == toks and len(blocks) == 3
        assert bm.prefix_blocks_by_hash("no-such-hash") is None
        # evict the FIRST chain link only: the deep hash keeps its own
        # registration but its chain is broken -> graceful None
        first = blocks[0]
        bm.free("a")
        bm._free.remove(first)
        bm._free.append(first)   # hot end: the next claim takes it
        bm._release(bm._claim())
        assert bm.prefix_blocks_by_hash(deep) is None
        bm.check_invariants()

    def test_uncached_free_blocks(self):
        bm = BlockManager(8, 4, enable_prefix_cache=True)
        assert bm.num_uncached_free_blocks == 8
        self._warm(bm, list(range(8)))
        bm.free("a")
        assert bm.num_free_blocks == 8
        assert bm.num_uncached_free_blocks == 6


# ---------------------------------------------------------------------------
# engine prefix export/import (no request attached)
# ---------------------------------------------------------------------------
class TestEnginePrefixShip:
    def _warm_engine(self, model, prompt, **cfg):
        eng = LLMEngine(model, _ecfg(**cfg))
        eng.add_request("w", prompt, sampling=SamplingParams(
            max_new_tokens=2))
        while eng.has_unfinished():
            eng.step()
        return eng

    def test_roundtrip_then_hit(self, tiny_model):
        prompt = list(range(1, 13))
        src = self._warm_engine(tiny_model, prompt)
        dig = src.prefix_digest()
        deep = max(dig["h"], key=dig["h"].get)
        meta, payload = src.export_prefix(deep)
        assert meta["tokens"] == prompt[:dig["h"][deep]]
        dst = LLMEngine(tiny_model, _ecfg())
        assert dst.import_prefix(meta=meta, payload=payload) \
            == dig["h"][deep]
        # idempotent under RPC retry
        assert dst.import_prefix(meta=meta, payload=payload) == 0
        assert dst.block_manager.match_prefix(prompt) == dig["h"][deep]
        dst.block_manager.check_invariants()
        # the imported trie is REAL: the same prompt now prefix-hits
        # and generates bit-identically to a cold single engine
        ref = _reference(tiny_model, [prompt], SamplingParams(
            max_new_tokens=4), ["r"])
        dst.add_request("r", prompt, sampling=SamplingParams(
            max_new_tokens=4))
        while dst.has_unfinished():
            dst.step()
        assert list(dst.get_request("r").generated) == ref["r"]
        assert dst.block_manager.num_prefix_hit_tokens > 0
        assert dst.num_prefix_imports == 1
        assert src.num_prefix_exports == 1

    def test_unknown_or_evicted_hash_exports_none(self, tiny_model):
        src = self._warm_engine(tiny_model, list(range(1, 13)))
        assert src.export_prefix("beefbeefbeefbeef") is None

    def test_corrupt_payload_rejected(self, tiny_model):
        src = self._warm_engine(tiny_model, list(range(1, 13)))
        dig = src.prefix_digest()
        meta, payload = src.export_prefix(next(iter(dig["h"])))
        bad = bytearray(payload)
        bad[0] ^= 0xFF
        dst = LLMEngine(tiny_model, _ecfg())
        with pytest.raises(ValueError, match="checksum"):
            dst.import_prefix(meta=meta, payload=bytes(bad))
        dst.block_manager.check_invariants()
        assert dst.block_manager.num_free_blocks == \
            dst.block_manager.num_blocks

    def test_geometry_mismatch_rejected(self, tiny_model):
        src = self._warm_engine(tiny_model, list(range(1, 13)))
        meta, payload = src.export_prefix(
            next(iter(src.prefix_digest()["h"])))
        dst = LLMEngine(tiny_model, _ecfg())
        with pytest.raises(ValueError, match="block_size"):
            dst.import_prefix(meta={**meta, "block_size": 8},
                              payload=payload)
        with pytest.raises(ValueError, match="shape"):
            dst.import_prefix(meta={**meta, "blocks": 99},
                              payload=payload)

    def test_import_refuses_to_evict_resident_cache(self, tiny_model):
        # destination pool: nearly every free block holds registered
        # content -> a proactive import must refuse rather than evict
        prompt = list(range(1, 13))
        src = self._warm_engine(tiny_model, prompt)
        dig = src.prefix_digest()
        deep = max(dig["h"], key=dig["h"].get)
        meta, payload = src.export_prefix(deep)
        dst = self._warm_engine(tiny_model, list(range(100, 160)),
                                num_blocks=16)
        assert dst.block_manager.num_uncached_free_blocks < 3
        with pytest.raises(ValueError, match="refusing to evict"):
            dst.import_prefix(meta=meta, payload=payload)
        dst.block_manager.check_invariants()

    def test_draining_engine_rejects_import(self, tiny_model):
        src = self._warm_engine(tiny_model, list(range(1, 13)))
        meta, payload = src.export_prefix(
            next(iter(src.prefix_digest()["h"])))
        dst = LLMEngine(tiny_model, _ecfg())
        dst.start_drain("test")
        with pytest.raises(ValueError, match="draining"):
            dst.import_prefix(meta=meta, payload=payload)


# ---------------------------------------------------------------------------
# router policy: affinity, decay, staleness, ships
# ---------------------------------------------------------------------------
SHARED = list(range(1, 13))  # three full blocks at bs=4


def _tenant_prompt(i):
    return SHARED + [30 + i, 31 + i, 32 + i]


class TestPrefixAffinity:
    def _fleet(self, model, n=2, **cfg_kw):
        reps = [InProcessReplica(model, _ecfg(), replica_id=f"r{i}")
                for i in range(n)]
        return reps, FleetRouter(reps, FleetConfig(**cfg_kw))

    def _serve_one(self, router, prompt, sp=None, rid=None):
        rid = router.add_request(rid, list(prompt), sampling=sp or
                                 SamplingParams(max_new_tokens=4))
        _drain_router(router)
        return router.release_request(rid)

    def test_affine_dispatch_concentrates_on_warm_replica(
            self, tiny_model):
        reps, router = self._fleet(tiny_model, prefix_ship=False)
        for i in range(5):
            self._serve_one(router, _tenant_prompt(i))
        # request 0 landed cold somewhere; every later one followed
        # the advertisement to the same (now warm) replica
        served = [h.engine.metrics.num_finished for h in reps]
        assert sorted(served) == [0, 5]
        assert router.num_prefix_affine_dispatches == 4
        # the credit is decayed by heartbeat age (int-truncated), so
        # allow one token of slack per affine dispatch
        assert router.num_prefix_hit_tokens >= 4 * (len(SHARED) - 1)
        warm = reps[served.index(5)]
        assert warm.engine.block_manager.num_prefix_hit_tokens > 0

    def test_load_only_mode_ignores_adverts(self, tiny_model):
        reps, router = self._fleet(tiny_model, prefix_affinity=False,
                                   prefix_ship=False)
        for i in range(4):
            self._serve_one(router, _tenant_prompt(i))
        assert router.num_prefix_affine_dispatches == 0
        assert router.num_prefix_ships == 0

    def test_advert_decay_zeroes_stale_match(self, tiny_model):
        reps, router = self._fleet(tiny_model, prefix_ship=False,
                                   prefix_decay_s=5.0)
        self._serve_one(router, _tenant_prompt(0))
        router.step()  # beat + sweep: adverts populated
        warm = [h for h in reps
                if h.engine.metrics.num_finished][0]
        prompt = _tenant_prompt(1)
        m = router._affinity_match(list(reps), prompt)
        assert m.get(warm.replica_id, 0) >= len(SHARED) - 1
        # age the records on the READER's clock past the decay horizon
        reg = router.registry
        real_mono = reg._mono
        reg._mono = lambda: real_mono() + 60.0
        try:
            assert router._affinity_match(list(reps), prompt) == {}
        finally:
            reg._mono = real_mono

    def test_stale_advert_is_a_graceful_miss(self, tiny_model):
        """The acceptance pin: dispatch lands on a replica whose
        advertised prefix was EVICTED after its last heartbeat — the
        landing is a plain prefill, token-identical to a single
        engine. Never corruption, never a strand."""
        reps, router = self._fleet(tiny_model, prefix_ship=False)
        self._serve_one(router, _tenant_prompt(0), rid="warmup")
        router.step()
        warm = [h for h in reps if h.engine.metrics.num_finished][0]
        # evict everything advertised engine-side...
        bm = warm.engine.block_manager
        _evict_all_cached(bm)
        bm.check_invariants()
        assert bm.match_prefix(_tenant_prompt(1)) == 0
        # ...and freeze heartbeats so the router keeps dispatching on
        # the stale digest (in-process replicas re-advertise every
        # step otherwise)
        router._heartbeat = lambda: None
        assert router._adverts[warm.replica_id]["h"]
        sp = SamplingParams(max_new_tokens=4)
        ref = _reference(tiny_model, [_tenant_prompt(1)], sp, ["q"])
        fr = self._serve_one(router, _tenant_prompt(1), sp, rid="q")
        assert fr.generated == ref["q"]
        assert fr.finish_reason == "length"
        # it landed on the stale-advertised replica and plain-prefilled
        # (no hit tokens were ever credited engine-side)
        assert warm.engine.metrics.num_finished == 2
        assert bm.num_prefix_hit_tokens == 0
        bm.check_invariants()

    def test_hot_prefix_ships_to_cold_replica(self, tiny_model):
        reps, router = self._fleet(tiny_model, prefix_ship_threshold=2)
        for i in range(5):
            self._serve_one(router, _tenant_prompt(i))
        assert router.num_prefix_ships >= 1
        assert router.num_prefix_ship_bytes > 0
        cold = [h for h in reps if not h.engine.metrics.num_finished]
        assert len(cold) == 1
        # the cold replica now holds the shared header WITHOUT ever
        # having computed a prompt token
        assert cold[0].engine.num_prefix_imports >= 1
        assert cold[0].engine.metrics.num_prompt_tokens == 0
        assert cold[0].engine.block_manager.match_prefix(
            _tenant_prompt(9)) == len(SHARED)
        for h in reps:
            h.engine.block_manager.check_invariants()

    @pytest.mark.parametrize("point", [
        "fleet.prefix_ship_drop:flag",
        "fleet.prefix_ship_corrupt:flag",
    ], ids=["drop", "corrupt"])
    def test_ship_fault_points_degrade_to_cold(self, tiny_model, point):
        reps, router = self._fleet(tiny_model, prefix_ship_threshold=2)
        faults.install(point)
        sp = SamplingParams(max_new_tokens=4)
        ids, prompts, got = [], [], {}
        for i in range(5):
            p = _tenant_prompt(i)
            rid = f"f{i}"
            fr = self._serve_one(router, p, sp, rid=rid)
            assert fr.finish_reason == "length"
            ids.append(rid)
            prompts.append(p)
            got[rid] = fr.generated
        faults.clear()
        # the ship was attempted, failed cleanly, and was NOT retried
        # into a storm; the destination stayed cold and uncorrupted
        assert router.num_prefix_ships == 0
        assert router.num_prefix_ship_failures >= 1
        for h in reps:
            if not h.engine.metrics.num_finished:
                assert h.engine.num_prefix_imports == 0
            h.engine.block_manager.check_invariants()
        # generations unharmed: bit-identical to a single engine
        ref = _reference(tiny_model, prompts, sp, ids)
        for rid in ids:
            assert got[rid] == ref[rid], rid


# ---------------------------------------------------------------------------
# randomized advertisement/eviction coherence storm
# ---------------------------------------------------------------------------
class TestCoherenceStorm:
    @pytest.mark.parametrize("sampled", [False, True],
                             ids=["greedy", "sampled"])
    def test_storm_graceful_misses_exact_accounting_parity(
            self, tiny_model, sampled):
        """Waves of shared-prefix traffic against TINY caches that
        evict advertised prefixes constantly, plus ship faults firing
        mid-storm. Pins: every wave's generations match a single-engine
        reference bit-exactly (same request ids — the sampling stream
        seeds from the id), block accounting is exact on every replica
        after every wave, and nothing strands."""
        sp = SamplingParams(max_new_tokens=6, temperature=0.8,
                            top_p=0.9) if sampled else \
            SamplingParams(max_new_tokens=6)
        for seed in (0, 1):
            rng = np.random.default_rng(40 + seed)
            # 18 blocks of 4 = 72 cacheable tokens: three concurrent
            # requests plus registered prefixes oversubscribe the pool,
            # so advertised prefixes get evicted while their adverts
            # ride already-sent heartbeats
            def cfg():
                return _ecfg(num_blocks=18, max_num_seqs=3)
            reps = [InProcessReplica(tiny_model, cfg(),
                                     replica_id=f"e{seed}{j}")
                    for j in range(2)]
            router = FleetRouter(reps, FleetConfig(
                prefix_ship_threshold=2, prefix_decay_s=30.0))
            headers = [list(map(int, rng.integers(
                0, tiny_model.config.vocab_size, size=8)))
                for _ in range(2)]
            ref_eng = LLMEngine(tiny_model, cfg())
            n = 0
            for wave in range(4):
                ids, prompts = [], []
                for _ in range(3):
                    head = headers[int(rng.integers(0, len(headers)))]
                    tail = list(map(int, rng.integers(
                        0, tiny_model.config.vocab_size,
                        size=3 + int(rng.integers(0, 4)))))
                    prompts.append(head + tail)
                    ids.append(f"s{seed}-{n}")
                    n += 1
                if wave == 2:
                    # mid-storm ship chaos: first attempt dropped,
                    # second corrupted — both must degrade cleanly
                    faults.install(
                        "fleet.prefix_ship_drop:flag*1;"
                        "fleet.prefix_ship_corrupt:flag@1*1")
                for rid, p in zip(ids, prompts):
                    router.add_request(rid, p, sampling=sp)
                outs = _drain_router(router, max_steps=500)
                faults.clear()
                final = {o.request_id: o for o in outs if o.finished}
                assert set(ids) <= set(final)
                for rid, p in zip(ids, prompts):
                    ref_eng.add_request(rid, p, sampling=sp)
                steps = 0
                while ref_eng.has_unfinished():
                    ref_eng.step()
                    steps += 1
                    assert steps < 600
                for rid in ids:
                    assert list(final[rid].generated) == \
                        list(ref_eng.get_request(rid).generated), rid
                    router.release_request(rid)
                for h in reps:
                    bm = h.engine.block_manager
                    bm.check_invariants()
                    assert bm.num_free_blocks == bm.num_blocks
            # the storm must actually have exercised the machinery
            assert router.num_prefix_affine_dispatches > 0
