"""einsum / fft / distribution numerics vs numpy (reference test pattern:
test/legacy_test OpTest numpy comparison, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft
from paddle_tpu import distribution as D


# ---------------------------------------------------------------------------
# einsum
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("eq,shapes", [
    ("ij,jk->ik", [(3, 4), (4, 5)]),
    ("ij,jk", [(3, 4), (4, 5)]),            # implicit output
    ("bij,bjk->bik", [(2, 3, 4), (2, 4, 5)]),
    ("ii->i", [(4, 4)]),                     # diagonal
    ("ii", [(4, 4)]),                        # trace
    ("ij->", [(3, 4)]),                      # total sum
    ("...ij,...jk->...ik", [(2, 3, 4), (2, 4, 5)]),  # ellipsis
    ("i,j->ij", [(3,), (4,)]),               # outer product
])
def test_einsum_matches_numpy(eq, shapes):
    rng = np.random.RandomState(0)
    arrs = [rng.randn(*s).astype(np.float32) for s in shapes]
    out = paddle.einsum(eq, *[paddle.to_tensor(a) for a in arrs])
    np.testing.assert_allclose(out.numpy(), np.einsum(eq, *arrs),
                               rtol=2e-5, atol=2e-5)


def test_einsum_grad():
    rng = np.random.RandomState(1)
    a = paddle.to_tensor(rng.randn(3, 4).astype(np.float32),
                         stop_gradient=False)
    b = paddle.to_tensor(rng.randn(4, 5).astype(np.float32),
                         stop_gradient=False)
    out = paddle.einsum("ij,jk->ik", a, b)
    out.sum().backward()
    np.testing.assert_allclose(a.grad.numpy(),
                               np.ones((3, 5)) @ b.numpy().T, rtol=2e-5)


# ---------------------------------------------------------------------------
# fft
# ---------------------------------------------------------------------------
def test_fft_roundtrip_and_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 16).astype(np.float32)
    t = paddle.to_tensor(x)
    out = fft.fft(t)
    np.testing.assert_allclose(out.numpy(), np.fft.fft(x), rtol=1e-4,
                               atol=1e-4)
    back = fft.ifft(out)
    np.testing.assert_allclose(back.numpy().real, x, rtol=1e-4, atol=1e-4)


def test_rfft_irfft():
    rng = np.random.RandomState(1)
    x = rng.randn(8, 32).astype(np.float32)
    out = fft.rfft(paddle.to_tensor(x))
    assert list(out.shape) == [8, 17]
    np.testing.assert_allclose(out.numpy(), np.fft.rfft(x), rtol=1e-4,
                               atol=1e-4)
    back = fft.irfft(out)
    np.testing.assert_allclose(back.numpy(), x, rtol=1e-4, atol=1e-4)


def test_fft2_norm_and_shift():
    rng = np.random.RandomState(2)
    x = rng.randn(4, 8).astype(np.float32)
    out = fft.fft2(paddle.to_tensor(x), norm="ortho")
    np.testing.assert_allclose(out.numpy(), np.fft.fft2(x, norm="ortho"),
                               rtol=1e-4, atol=1e-4)
    sh = fft.fftshift(paddle.to_tensor(x))
    np.testing.assert_allclose(sh.numpy(), np.fft.fftshift(x))
    freqs = fft.fftfreq(8, d=0.5)
    np.testing.assert_allclose(freqs.numpy(), np.fft.fftfreq(8, d=0.5))


# ---------------------------------------------------------------------------
# distribution
# ---------------------------------------------------------------------------
def test_normal_log_prob_entropy_kl():
    n = D.Normal(0.0, 1.0)
    lp = n.log_prob(paddle.to_tensor(np.float32(0.5)))
    expect = -0.5 * 0.25 - 0.5 * np.log(2 * np.pi)
    np.testing.assert_allclose(float(lp.item()), expect, rtol=1e-5)
    ent = float(n.entropy().item())
    np.testing.assert_allclose(ent, 0.5 + 0.5 * np.log(2 * np.pi),
                               rtol=1e-5)
    other = D.Normal(1.0, 2.0)
    kl = float(D.kl_divergence(n, other).item())
    assert kl > 0
    np.testing.assert_allclose(
        kl, 0.5 * (0.25 + 0.25 - 1 - np.log(0.25)), rtol=1e-5)


def test_normal_sampling_statistics():
    paddle.seed(0)
    n = D.Normal(2.0, 3.0)
    s = n.sample([20000])
    assert abs(float(s.numpy().mean()) - 2.0) < 0.1
    assert abs(float(s.numpy().std()) - 3.0) < 0.1


def test_uniform_and_bernoulli():
    paddle.seed(0)
    u = D.Uniform(1.0, 3.0)
    s = u.sample([10000]).numpy()
    assert s.min() >= 1.0 and s.max() < 3.0
    np.testing.assert_allclose(float(u.entropy().item()), np.log(2.0),
                               rtol=1e-5)
    b = D.Bernoulli(probs=0.25)
    bs = b.sample([20000]).numpy()
    assert abs(bs.mean() - 0.25) < 0.02
    lp = float(b.log_prob(paddle.to_tensor(np.float32(1.0))).item())
    np.testing.assert_allclose(lp, np.log(0.25), rtol=1e-4)


def test_categorical():
    paddle.seed(0)
    logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
    c = D.Categorical(logits=paddle.to_tensor(logits))
    s = c.sample([20000]).numpy()
    freq = np.bincount(s, minlength=3) / len(s)
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.02)
    lp = c.log_prob(paddle.to_tensor(np.int64(2)))
    np.testing.assert_allclose(float(lp.item()), np.log(0.5), rtol=1e-5)
    ent = float(c.entropy().item())
    np.testing.assert_allclose(
        ent, -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5)),
        rtol=1e-5)


def test_exponential_gumbel_laplace():
    paddle.seed(0)
    e = D.Exponential(2.0)
    np.testing.assert_allclose(float(e.mean.item()), 0.5, rtol=1e-5)
    s = e.sample([20000]).numpy()
    assert abs(s.mean() - 0.5) < 0.02
    g = D.Gumbel(0.0, 1.0)
    assert np.isfinite(float(g.log_prob(
        paddle.to_tensor(np.float32(0.3))).item()))
    l = D.Laplace(0.0, 1.0)
    np.testing.assert_allclose(
        float(l.log_prob(paddle.to_tensor(np.float32(0.0))).item()),
        -np.log(2.0), rtol=1e-5)


def test_reparameterized_sampling_grad():
    """rsample carries gradients to the distribution params."""
    loc = paddle.to_tensor(np.float32(1.0), stop_gradient=False)
    paddle.seed(3)
    # manual reparameterization through the public Tensor graph
    n = D.Normal(0.0, 1.0)
    eps = n.sample([64])
    out = (loc + eps * 0.5).mean()
    out.backward()
    np.testing.assert_allclose(loc.grad.numpy(), 1.0, rtol=1e-5)


def test_distribution_params_receive_gradients():
    """Densities/KLs are built from Tensor ops, so learnable distribution
    parameters train (regression: raw-jnp internals detached the graph
    and KL(N(mu,1)||N(0,1)) never moved mu)."""
    from paddle_tpu import optimizer

    paddle.seed(0)
    mu = paddle.to_tensor(np.float32(3.0), stop_gradient=False)
    opt = optimizer.Adam(learning_rate=0.2, parameters=[mu])
    for _ in range(60):
        kl = D.kl_divergence(D.Normal(mu, 1.0), D.Normal(0.0, 1.0))
        kl.backward()
        opt.step()
        opt.clear_grad()
    assert abs(float(mu.item())) < 0.3, float(mu.item())

    # log_prob path too: maximize likelihood of data centered at -1
    loc = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
    opt = optimizer.Adam(learning_rate=0.2, parameters=[loc])
    data = paddle.to_tensor(np.full((64,), -1.0, np.float32))
    for _ in range(60):
        nll = -D.Normal(loc, 1.0).log_prob(data).mean()
        nll.backward()
        opt.step()
        opt.clear_grad()
    assert abs(float(loc.item()) + 1.0) < 0.2, float(loc.item())
