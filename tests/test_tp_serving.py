"""TP-sharded serving units that run on ONE XLA:CPU device (ISSUE 17).

Multi-device parity — TP=2 token-identical to TP=1, cross-degree KV
resharding, resharded checkpoint restore — needs a forced 8-device
host mesh and lives in ``scripts/tp_smoke.py`` (``scripts/ci.sh --tp``).
What CAN be pinned on a single device is pinned here: the engine's TP
surface at degree 1 (layouts, gauges, wire-format defaults), the
BlockManager's rank gate on shipped payloads, the transport's
at-the-door layout refusal, and the checkpoint manager's
content-addressed chunk dedupe + GC and ``target_layout`` restore.
"""
import os
import zlib

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.redistribute import Layout
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams
from paddle_tpu.serving.block_manager import BlockManager
from paddle_tpu.serving.fleet import PeerListener, peer_push, sign_ticket


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("drain_grace_s", 0.0)
    return EngineConfig(**kw)


# ---------------------------------------------------------------------------
# engine TP surface at degree 1 (the CI-visible slice)
# ---------------------------------------------------------------------------
class TestEngineTPSurface:
    def test_tp1_layouts_and_gauges(self, tiny_model):
        eng = LLMEngine(tiny_model, _ecfg())
        assert eng.tp_degree == 1
        # the cache layout names the kv-head dim of (L, NB, BS, KH, D)
        assert eng.kv_layout.ndim == 5
        assert eng.kv_layout.size == 1
        lays = eng.param_layouts()
        assert set(lays) == set(eng._pnames)
        # degree 1 = fully replicated: nothing splits
        assert all(all(p is None for p in l.dim_placements)
                   for l in lays.values())
        snap = eng.metrics.snapshot()
        assert snap["serving_kv_reshards"] == 0
        assert snap["serving_continuation_resumes"] == 0

    def test_param_layout_megatron_pairing(self):
        from paddle_tpu.serving.engine import _tp_param_layout
        # column-parallel: output features split (dim 1 of weight)
        q = _tp_param_layout("layers.0.self_attn.q_proj.weight", 2, 2)
        assert q.dim_placements == (None, "tp")
        qb = _tp_param_layout("layers.0.self_attn.q_proj.bias", 1, 2)
        assert qb.dim_placements == ("tp",)
        # row-parallel: input features split (dim 0 of weight)
        o = _tp_param_layout("layers.0.self_attn.o_proj.weight", 2, 2)
        assert o.dim_placements == ("tp", None)
        # embeddings / norms / lm_head stay replicated
        e = _tp_param_layout("embed_tokens.weight", 2, 2)
        assert e.dim_placements == (None, None)

    def test_wire_layout_default_and_rejection(self, tiny_model):
        eng = LLMEngine(tiny_model, _ecfg())
        shape = (2, 3, 4, 2, 8)
        # absent stanza = the pre-TP flat wire format: one replicated
        # frame — old exporters keep working against a TP importer
        lay = eng._wire_src_layout({}, shape)
        assert lay.size == 1 and lay.ndim == 5
        with pytest.raises(ValueError):
            eng._wire_src_layout({"layout": {"bogus": True}}, shape)
        # a layout that cannot tile the payload geometry is refused
        bad = Layout.tp_sharded(5, 3, 4).to_meta()
        with pytest.raises(ValueError):
            eng._wire_src_layout({"layout": bad}, (2, 3, 4, 2, 8)[:4])

    def test_tp_degree_must_divide_heads(self, tiny_model):
        with pytest.raises(ValueError, match="divide"):
            LLMEngine(tiny_model, _ecfg(tp_degree=3))


# ---------------------------------------------------------------------------
# BlockManager: shipped-payload rank gate
# ---------------------------------------------------------------------------
class TestBlockManagerLayoutGate:
    def test_rank_mismatch_refused_before_allocation(self):
        bm = BlockManager(8, 4, kv_layout=Layout.tp_sharded(5, 3, 1))
        with pytest.raises(ValueError, match="rank"):
            bm.import_blocks("r1", 8,
                             src_layout=Layout.tp_sharded(4, 2, 2))
        assert bm.num_free_blocks == 8      # nothing was claimed
        # matching rank lands regardless of degree (degree is the
        # engine's reshard problem, not the allocator's)
        blocks = bm.import_blocks("r1", 8,
                                  src_layout=Layout.tp_sharded(5, 3, 2))
        assert len(blocks) == 2

    def test_layoutless_manager_accepts_any(self):
        bm = BlockManager(8, 4)             # pre-TP construction
        blocks = bm.import_blocks("r1", 4,
                                  src_layout=Layout.tp_sharded(4, 2, 2))
        assert len(blocks) == 1


# ---------------------------------------------------------------------------
# transport: malformed layout stanzas refused at the door
# ---------------------------------------------------------------------------
class TestTransportLayoutGate:
    def _ticket(self, lis, tid="t1"):
        t = {"ticket_id": tid, "src": "a", "dst": "b", "kind": "kv",
             "request_id": "r0", "deadline_ms": 30_000}
        t["sig"] = sign_ticket(t, lis._secret)
        return t

    def _meta(self, payload, **extra):
        m = {"crc32": zlib.crc32(payload) & 0xFFFFFFFF}
        m.update(extra)
        return m

    def test_bad_layout_stanza_refused(self):
        lis = PeerListener()
        try:
            payload = b"x" * 64
            receipt = peer_push(
                lis.endpoint, self._ticket(lis),
                self._meta(payload, layout={"bogus": 1}), payload)
            assert receipt["ok"] is False
            assert "layout" in receipt["error"]
            assert lis.take("t1") is None
            assert lis.stats()["refused"] == 1
        finally:
            lis.close()

    def test_unframeable_payload_refused(self):
        # 2 shards need the K and V byte streams to split into 2x2
        # frames; 63 bytes cannot
        lis = PeerListener()
        try:
            payload = b"x" * 63
            lay = Layout.tp_sharded(5, 3, 2).to_meta()
            receipt = peer_push(
                lis.endpoint, self._ticket(lis),
                self._meta(payload, layout=lay), payload)
            assert receipt["ok"] is False
            assert "layout" in receipt["error"]
        finally:
            lis.close()

    def test_well_formed_layout_admitted(self):
        lis = PeerListener()
        try:
            payload = b"x" * 64
            lay = Layout.tp_sharded(5, 3, 2).to_meta()
            receipt = peer_push(
                lis.endpoint, self._ticket(lis),
                self._meta(payload, layout=lay), payload)
            assert receipt["ok"] is True
            ticket, meta, got = lis.take("t1")
            assert got == payload
            assert meta["layout"] == lay
        finally:
            lis.close()


# ---------------------------------------------------------------------------
# checkpoint: content-addressed chunk dedupe + GC, target_layout restore
# ---------------------------------------------------------------------------
def _state(step):
    # "frozen" never changes across steps (the dedupe win);
    # "hot" changes every step (must never dedupe)
    return {"frozen": paddle.full([8, 8], 3.25),
            "hot": paddle.full([4], float(step))}


class TestCheckpointCAS:
    def test_dedupe_hardlinks_identical_chunks(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=5,
                                dedupe_chunks=True)
        for s in (1, 2, 3):
            mgr.save(s, _state(s), block=True)
        # steps 2 and 3 re-linked the frozen chunk instead of
        # rewriting it
        assert mgr.last_cas_hits >= 1
        cas = tmp_path / "chunk_cas"
        assert cas.is_dir()
        nlinks = sorted(os.stat(cas / f).st_nlink
                        for f in os.listdir(cas))
        # the frozen chunk: cas copy + one link per kept step
        assert nlinks[-1] == 4
        st = _state(0)
        st["hot"] = paddle.zeros([4])
        assert mgr.restore_or_initialize(st) == 3
        np.testing.assert_array_equal(st["frozen"].numpy(),
                                      np.full((8, 8), 3.25, np.float32))
        np.testing.assert_array_equal(st["hot"].numpy(),
                                      np.full(4, 3.0, np.float32))

    def test_gc_prunes_unreferenced_chunks(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last_n=1,
                                dedupe_chunks=True)
        mgr.save(1, _state(1), block=True)
        mgr.save(2, _state(2), block=True)
        assert mgr.all_steps() == [2]
        cas = tmp_path / "chunk_cas"
        # step 1's hot chunk lost its last step reference and was
        # pruned; the frozen chunk and step 2's hot chunk survive
        for f in os.listdir(cas):
            assert os.stat(cas / f).st_nlink >= 2, f
        st = _state(0)
        assert mgr.restore_or_initialize(st) == 2
        np.testing.assert_array_equal(st["hot"].numpy(),
                                      np.full(4, 2.0, np.float32))

    def test_plain_and_dedupe_restores_agree(self, tmp_path):
        a = CheckpointManager(str(tmp_path / "plain"))
        b = CheckpointManager(str(tmp_path / "cas"),
                              dedupe_chunks=True)
        a.save(1, _state(1), block=True)
        b.save(1, _state(1), block=True)
        sa, sb = _state(0), _state(0)
        a.restore(sa, step=1)
        b.restore(sb, step=1)
        for k in sa:
            np.testing.assert_array_equal(sa[k].numpy(),
                                          sb[k].numpy(), err_msg=k)


class TestRestoreTargetLayout:
    def test_degree1_layout_restore_bit_identical(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, _state(1), block=True)
        st = _state(0)
        step = mgr.restore_or_initialize(
            st, target_layout={"frozen": Layout.tp_sharded(2, 0, 1),
                               "hot": Layout.tp_sharded(1, 0, 1)})
        assert step == 1
        np.testing.assert_array_equal(st["frozen"].numpy(),
                                      np.full((8, 8), 3.25, np.float32))
        np.testing.assert_array_equal(st["hot"].numpy(),
                                      np.full(4, 1.0, np.float32))

    def test_unknown_name_and_bad_shape_raise(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, _state(1), block=True)
        st = _state(0)
        with pytest.raises(KeyError):
            mgr.restore(st, step=1,
                        target_layout={"nope": Layout.tp_sharded(1, 0, 1)})
        with pytest.raises(ValueError):
            mgr.restore(st, step=1,
                        target_layout={"hot": Layout.tp_sharded(1, 0, 3)})
