import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_dtypes():
    t = paddle.to_tensor([1, 2, 3])
    assert t.dtype.is_integer
    t2 = t.astype("float32")
    assert t2.dtype == paddle.float32
    t3 = t2.astype(paddle.bfloat16)
    assert t3.dtype == paddle.bfloat16


def test_arithmetic_dunders():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    np.testing.assert_allclose((2.0 - a).numpy(), [1, 0])
    np.testing.assert_allclose((1.0 / a).numpy(), [1, 0.5])


def test_comparison():
    a = paddle.to_tensor([1.0, 5.0])
    b = paddle.to_tensor([2.0, 2.0])
    assert (a < b).numpy().tolist() == [True, False]
    assert (a >= b).numpy().tolist() == [False, True]


def test_getitem_setitem():
    t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_allclose(t[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(t[0:2, 1].numpy(), [1, 5])
    t[0, 0] = 99.0
    assert t.numpy()[0, 0] == 99.0


def test_item_and_len():
    t = paddle.to_tensor(3.5)
    assert abs(t.item() - 3.5) < 1e-6
    t2 = paddle.to_tensor([1, 2, 3])
    assert len(t2) == 3


def test_methods_bound():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert abs(t.sum().item() - 10.0) < 1e-6
    assert abs(t.mean().item() - 2.5) < 1e-6
    assert t.reshape([4]).shape == [4]
    assert t.T.shape == [2, 2]
    np.testing.assert_allclose(t.T.numpy(), [[1, 3], [2, 4]])


def test_inplace_variants():
    t = paddle.to_tensor([1.0, 2.0])
    t.add_(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(t.numpy(), [2, 3])
    t.scale_(2.0)
    np.testing.assert_allclose(t.numpy(), [4, 6])


def test_clone_detach():
    t = paddle.to_tensor([1.0], stop_gradient=False)
    d = t.detach()
    assert d.stop_gradient
    c = t.clone()
    assert not c.stop_gradient


def test_set_value():
    t = paddle.to_tensor([1.0, 2.0])
    t.set_value(np.array([5.0, 6.0]))
    np.testing.assert_allclose(t.numpy(), [5, 6])


def test_default_dtype():
    paddle.set_default_dtype("float32")
    assert paddle.get_default_dtype() == paddle.float32


def test_zero_dim():
    t = paddle.to_tensor(2.0)
    assert t.ndim == 0
    assert t.shape == []
    out = t * 3
    assert abs(out.item() - 6.0) < 1e-6


def test_no_view_aliasing_documented_divergence():
    """DOCUMENTED DIVERGENCE from the reference (README "Scope"):
    XLA arrays are immutable, so slices/as_strided return COPIES and
    writing through them does NOT mutate the source (the reference's
    stride kernels give zero-copy views, phi/kernels/stride/
    view_kernel.cc). This test pins the copy semantics so a future
    change is deliberate."""
    x = paddle.to_tensor(np.zeros((4, 4), np.float32))
    y = x[0:2]
    y.fill_(7.0)
    # y mutated...
    np.testing.assert_allclose(y.numpy(), np.full((2, 4), 7.0))
    # ...but x is untouched (reference would show 7s in rows 0-1)
    np.testing.assert_allclose(x.numpy(), np.zeros((4, 4)))
    # in-place setitem on the SOURCE works (rebinds the whole buffer)
    x[0:2] = 7.0
    np.testing.assert_allclose(x.numpy()[0:2], np.full((2, 4), 7.0))
