"""ASP n:m structured sparsity + sparse-attention example.

Reference: python/paddle/incubate/asp/asp.py (prune_model:302,
decorate:216), incubate/asp/utils.py (mask_1d/mask_2d patterns,
check_sparsity); sparse kernels paddle/phi/kernels/sparse/
(softmax_kernel, matmul)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, sparse
from paddle_tpu.incubate import asp


def _mlp():
    paddle.seed(0)
    return nn.Sequential(
        nn.Linear(16, 32), nn.ReLU(),
        nn.Linear(32, 32), nn.ReLU(),
        nn.Linear(32, 4))


def _task(n=256):
    rng = np.random.RandomState(0)
    X = rng.randn(n, 16).astype(np.float32)
    W = rng.randn(16, 4).astype(np.float32)
    Y = np.argmax(X @ W, axis=1).astype(np.int64)
    return X, Y


def _accuracy(model, X, Y):
    logits = model(paddle.to_tensor(X)).numpy()
    return float((logits.argmax(-1) == Y).mean())


def test_mask_1d_pattern():
    w = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    mask = asp.create_mask(w, "mask_1d", n=2, m=4)
    assert asp.check_sparsity(w * mask, n=2, m=4)
    assert mask.reshape(-1, 4).sum(1).tolist() == [2.0] * (8 * 16 // 4)
    # the kept entries are the 2 largest |values| of each group
    groups = np.abs(w.reshape(-1, 4))
    kept = groups * mask.reshape(-1, 4)
    dropped = groups * (1 - mask.reshape(-1, 4))
    assert (kept.max(1) >= dropped.max(1)).all()


def test_mask_2d_patterns():
    w = np.random.RandomState(1).randn(8, 8).astype(np.float32)
    for algo in ("mask_2d_greedy", "mask_2d_best"):
        mask = asp.create_mask(w, algo, n=2, m=4)
        pruned = w * mask
        assert asp.check_sparsity(pruned, n=2, m=4, func_name=algo)
        # 2:4 in BOTH dims on every 4x4 block
        nz = (pruned != 0).reshape(2, 4, 2, 4).transpose(0, 2, 1, 3)
        assert (nz.sum(2) == 2).all() and (nz.sum(3) == 2).all()


def test_prune_model_and_density():
    m = _mlp()
    masks = asp.prune_model(m, n=2, m=4)
    assert len(masks) == 3  # three Linear weights
    for name, p in m.named_parameters():
        if name.endswith("weight") and p._data.ndim == 2:
            assert asp.check_sparsity(p, n=2, m=4), name
            assert asp.calculate_density(p) <= 0.5 + 1e-6


def test_excluded_layers():
    asp.reset_excluded_layers()
    m = _mlp()
    asp.set_excluded_layers(["2.weight"])
    try:
        masks = asp.prune_model(m, n=2, m=4)
        assert "2.weight" not in masks and len(masks) == 2
    finally:
        asp.reset_excluded_layers()


def test_prune_finetune_keeps_accuracy():
    """prune -> finetune keeps accuracy within 1% of the dense model
    (VERDICT item 8 acceptance), with the 2:4 pattern enforced through
    compiled TrainStep updates."""
    X, Y = _task()
    loss_fn = nn.CrossEntropyLoss()

    def train(model, opt, steps=60):
        step = paddle.jit.TrainStep(model, loss_fn, opt)
        xb = paddle.to_tensor(X)
        yb = paddle.to_tensor(Y)
        for _ in range(steps):
            step(xb, yb)
        return model

    # dense baseline
    dense = _mlp()
    train(dense, optimizer.Adam(learning_rate=0.01,
                                parameters=dense.parameters()))
    acc_dense = _accuracy(dense, X, Y)

    # dense pretrain -> prune -> decorated finetune
    model = _mlp()
    train(model, optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters()))
    opt = asp.decorate(optimizer.Adam(learning_rate=0.005,
                                      parameters=model.parameters()))
    asp.prune_model(model, n=2, m=4)
    train(model, opt, steps=60)
    acc_sparse = _accuracy(model, X, Y)

    # sparsity survived 60 compiled optimizer updates
    for name, p in model.named_parameters():
        if name.endswith("weight") and p._data.ndim == 2:
            assert asp.check_sparsity(p, n=2, m=4), name
    assert acc_sparse >= acc_dense - 0.01, (acc_sparse, acc_dense)


def test_asp_eager_step_enforces():
    model = _mlp()
    opt = asp.decorate(optimizer.SGD(learning_rate=0.1,
                                     parameters=model.parameters()))
    asp.prune_model(model, n=2, m=4)
    X, Y = _task(32)
    loss = nn.CrossEntropyLoss()(model(paddle.to_tensor(X)),
                                 paddle.to_tensor(Y))
    loss.backward()
    opt.step()
    opt.clear_grad()
    w = dict(model.named_parameters())["0.weight"]
    assert asp.check_sparsity(w, n=2, m=4)


def test_sparse_attention_example():
    """Block-sparse attention built from the sparse op set: scores only
    at mask positions (masked_matmul) -> sparse softmax -> sparse @ V.
    Must match dense attention with -inf masking."""
    rng = np.random.RandomState(0)
    L, D = 16, 8
    q = paddle.to_tensor(rng.randn(L, D).astype(np.float32))
    k = paddle.to_tensor(rng.randn(L, D).astype(np.float32))
    v = paddle.to_tensor(rng.randn(L, D).astype(np.float32))
    # banded (local) attention mask
    band = (np.abs(np.arange(L)[:, None] - np.arange(L)[None, :]) <= 2)
    mask_sp = paddle.to_tensor(band.astype(np.float32)).to_sparse_coo()

    q_scaled = q * float(1.0 / np.sqrt(D))
    scores = sparse.masked_matmul(q_scaled,
                                  paddle.ops.transpose(k, [1, 0]),
                                  mask_sp)
    probs = sparse.softmax(scores)
    out = sparse.matmul(probs, v)

    dense_scores = (q.numpy() @ k.numpy().T) / np.sqrt(D)
    dense_scores[~band] = -1e30
    p = np.exp(dense_scores - dense_scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    expect = p @ v.numpy()
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)
