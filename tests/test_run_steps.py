"""TrainStep.run_steps — k optimizer steps per dispatch via lax.scan.

The reference's static-graph executor runs the whole Program per call
instead of returning to Python each op (SURVEY.md §3.3); run_steps is
the TPU analog at step granularity: one XLA dispatch covers k full
(fwd+bwd+update) steps, removing the host round-trip floor that
dominates small-model steps on remote PJRT backends. Numerics must be
IDENTICAL to k sequential __call__s."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _fresh(seed=0):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    return m, opt, paddle.jit.TrainStep(m, nn.CrossEntropyLoss(), opt)


def _batch():
    rng = np.random.default_rng(0)
    X = paddle.to_tensor(rng.normal(size=(16, 8)).astype("float32"))
    Y = paddle.to_tensor(rng.integers(0, 4, 16).astype("int64"))
    return X, Y


def test_run_steps_matches_serial():
    X, Y = _batch()
    _, opt_a, step_a = _fresh()
    serial = [float(step_a(X, Y)) for _ in range(6)]
    _, opt_b, step_b = _fresh()
    scanned = np.concatenate([np.asarray(step_b.run_steps(3, X, Y)._data),
                              np.asarray(step_b.run_steps(3, X, Y)._data)])
    np.testing.assert_allclose(serial, scanned, rtol=2e-4, atol=1e-5)
    assert opt_a._step_count == opt_b._step_count == 6


def test_run_steps_params_match_serial():
    X, Y = _batch()
    m_a, _, step_a = _fresh()
    for _ in range(4):
        step_a(X, Y)
    m_b, _, step_b = _fresh()
    step_b.run_steps(4, X, Y)
    for pa, pb in zip(m_a.parameters(), m_b.parameters()):
        np.testing.assert_allclose(np.asarray(pa._data),
                                   np.asarray(pb._data),
                                   rtol=2e-4, atol=1e-5)


def test_run_steps_stacked_microbatches():
    rng = np.random.default_rng(1)
    Xk = paddle.to_tensor(rng.normal(size=(3, 16, 8)).astype("float32"))
    Yk = paddle.to_tensor(rng.integers(0, 4, (3, 16)).astype("int64"))
    m_a, _, step_a = _fresh()
    serial = [float(step_a(paddle.to_tensor(np.asarray(Xk._data)[i]),
                           paddle.to_tensor(np.asarray(Yk._data)[i])))
              for i in range(3)]
    m_b, _, step_b = _fresh()
    scanned = np.asarray(step_b.run_steps(3, Xk, Yk, stacked=True)._data)
    np.testing.assert_allclose(serial, scanned, rtol=2e-4, atol=1e-5)


def test_run_steps_stacked_shape_check():
    X, Y = _batch()
    _, _, step = _fresh()
    with pytest.raises(ValueError):
        step.run_steps(5, X, Y, stacked=True)  # leading dim is 16, not 5


def test_run_steps_batch_dim_equal_k_not_stacked():
    """A batch whose batch dim happens to equal k must NOT be scanned
    over (stacking is explicit)."""
    rng = np.random.default_rng(2)
    X = paddle.to_tensor(rng.normal(size=(4, 8)).astype("float32"))
    Y = paddle.to_tensor(rng.integers(0, 4, 4).astype("int64"))
    _, _, step_a = _fresh()
    serial = [float(step_a(X, Y)) for _ in range(4)]
    _, _, step_b = _fresh()
    scanned = np.asarray(step_b.run_steps(4, X, Y)._data)
    np.testing.assert_allclose(serial, scanned, rtol=2e-4, atol=1e-5)


def test_run_steps_stacked_fallback_slices_microbatches():
    """Graph-break fallback must slice stacked batches per step, not
    feed the whole (k, ...) stack to every step."""
    rng = np.random.default_rng(3)
    Xk = paddle.to_tensor(rng.normal(size=(3, 16, 8)).astype("float32"))
    Yk = paddle.to_tensor(rng.integers(0, 4, (3, 16)).astype("int64"))
    _, opt, step = _fresh()
    from paddle_tpu.jit.sot import PathCache
    step._sot_cache = PathCache()  # force the per-step fallback path
    losses = step.run_steps(3, Xk, Yk, stacked=True)
    assert tuple(np.asarray(losses._data).shape) == (3,)
    assert opt._step_count == 3
