"""Hybrid-parallel engine tests on the 8-device virtual CPU mesh
(the reference's pattern of CPU-runnable distributed tests, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.engine import (
    ParallelConfig, ParallelTrainStep, shard_model_parameters,
)
from paddle_tpu.distributed.fleet.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from paddle_tpu.distributed.mesh import ProcessMesh, Replicate, Shard, init_mesh


def make_mlp():
    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = ColumnParallelLinear(16, 32, gather_output=False)
            self.act = nn.GELU()
            self.fc2 = RowParallelLinear(32, 16, input_is_parallel=True)

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    return MLP()


def test_mesh_and_placements():
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    assert mesh.shape == [2, 4]
    assert mesh.get_dim_size("mp") == 4
    jm = mesh.jax_mesh()
    assert jm.shape == {"dp": 2, "mp": 4}


def test_shard_tensor_and_reshard():
    import paddle_tpu.distributed as dist

    mesh = init_mesh([2, 4], ["dp", "mp"])
    w = paddle.randn([8, 16])
    dw = dist.shard_tensor(w, mesh, [dist.Replicate(), dist.Shard(1)])
    assert dw.shape == [8, 16]  # global view unchanged
    assert dw.placements[1] == dist.Shard(1)
    # local shard is 16/4 wide
    shard = dw._data.addressable_shards[0]
    assert shard.data.shape == (8, 4)
    np.testing.assert_allclose(dw.numpy(), w.numpy())

    rw = dist.reshard(dw, mesh, [dist.Shard(0), dist.Replicate()])
    assert rw._data.addressable_shards[0].data.shape == (4, 16)
    np.testing.assert_allclose(rw.numpy(), w.numpy())


def test_tp_param_sharding_applied():
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    m = make_mlp()
    shard_model_parameters(m, mesh)
    # column weight [16, 32] sharded on out dim over mp=4 -> local 16x8
    assert m.fc1.weight._data.addressable_shards[0].data.shape == (16, 8)
    # row weight [32, 16] sharded on in dim -> local 8x16
    assert m.fc2.weight._data.addressable_shards[0].data.shape == (8, 16)


def test_tp_dp_train_matches_single_device():
    np.random.seed(0)
    X = np.random.randn(16, 16).astype(np.float32)
    Y = np.random.randn(16, 16).astype(np.float32)

    def run(parallel):
        paddle.seed(123)
        m = make_mlp()
        opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
        loss_fn = nn.MSELoss()
        if parallel:
            mesh = ProcessMesh(np.arange(8).reshape(2, 4),
                               dim_names=["dp", "mp"])
            step = ParallelTrainStep(m, loss_fn, opt, mesh)
        else:
            step = paddle.jit.TrainStep(m, loss_fn, opt)
        losses = [float(step(paddle.to_tensor(X),
                             paddle.to_tensor(Y)).item())
                  for _ in range(5)]
        return losses, m.fc1.weight.numpy()

    l1, w1 = run(False)
    l2, w2 = run(True)
    np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(w1, w2, rtol=2e-3, atol=2e-5)


@pytest.mark.parametrize("stage", [1, 3])
def test_zero_sharding_stages(stage):
    np.random.seed(1)
    X = np.random.randn(8, 16).astype(np.float32)
    Y = np.random.randn(8, 16).astype(np.float32)

    paddle.seed(7)
    m = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    opt = optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
    cfg = ParallelConfig(dp_axes=("dp",), sharding_stage=stage,
                         sharding_axis="dp")
    step = ParallelTrainStep(m, nn.MSELoss(), opt, mesh, cfg)
    if stage >= 3:
        # params sharded over dp
        w = m[0].weight
        assert w._data.addressable_shards[0].data.shape[0] == 2  # 16/8
    losses = [float(step(paddle.to_tensor(X),
                         paddle.to_tensor(Y)).item()) for _ in range(6)]
    assert losses[-1] < losses[0]

    # slots sharded for any stage >= 1
    slots = opt._slots[id(m[0].weight)]
    m1 = slots["moment1"]
    assert m1.sharding.spec[0] == "dp" or stage < 1


def test_vocab_parallel_embedding_and_ce():
    mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])

    class TinyLM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = VocabParallelEmbedding(64, 16)
            self.proj = ColumnParallelLinear(16, 64, gather_output=False)

        def forward(self, x):
            return self.proj(self.emb(x))

    paddle.seed(3)
    m = TinyLM()
    opt = optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    ce = ParallelCrossEntropy()

    def loss_fn(logits, labels):
        return paddle.mean(ce(logits, labels))

    step = ParallelTrainStep(m, loss_fn, opt, mesh)
    X = paddle.to_tensor(np.random.randint(0, 64, (8, 12)).astype(np.int32))
    Y = paddle.to_tensor(np.random.randint(0, 64, (8, 12)).astype(np.int32))
    losses = [float(step(X, Y).item()) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_dtensor_from_local_and_to_local():
    """local is this PROCESS's block (single process: the full global
    view — the round-2 version fabricated a x8 global by replicating one
    device shard, VERDICT weak #6); to_local returns one device shard."""
    import paddle_tpu.distributed as dist

    mesh = ProcessMesh(np.arange(8), dim_names=["x"])
    local = paddle.ones([8, 4])
    g = dist.dtensor_from_local(local, mesh, [dist.Shard(0)])
    assert g.shape == [8, 4]
    back = dist.dtensor_to_local(g)
    assert back.shape == [1, 4]


def test_create_hybrid_mesh_layout():
    """ICI/DCN hybrid mesh: on a single slice it degrades to a plain
    mesh of the product shape; axis sizes = dcn*ici with DCN outermost
    (collectives on dcn=1 axes never cross slices)."""
    import paddle_tpu.distributed as dist

    mesh = dist.create_hybrid_mesh(
        ici_shape=[1, 4], dcn_shape=[2, 1], dim_names=["dp", "tp"])
    assert mesh.get_dim_size("dp") == 2
    assert mesh.get_dim_size("tp") == 4
    assert mesh._dcn_shape == [2, 1] and mesh._ici_shape == [1, 4]
    # usable for real sharding: matmul over the tp axis compiles
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    jm = mesh.jax_mesh()
    x = jax.device_put(jnp.ones((8, 8)),
                       NamedSharding(jm, PartitionSpec("dp", "tp")))
    assert float(x.sum()) == 64.0
