"""End-to-end fault-injection proofs (subprocess save→kill→resume).

Acceptance pins for the fault-tolerant training layer:
  * SIGKILL during an async checkpoint write leaves the previous
    committed checkpoint intact and ``restore_or_initialize`` resumes
    from it at the correct step;
  * SIGTERM mid-run produces a final committed checkpoint before a
    clean (rc 0) exit — directly and through the launcher's forwarding.

Slow-marked: each scenario boots a fresh interpreter (jax import).
The fast in-process protocol tests live in test_faults.py.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(os.path.dirname(__file__), "mp_scripts")
WORKER = os.path.join(SCRIPTS, "ckpt_train_worker.py")
SERVING_WORKER = os.path.join(SCRIPTS, "serving_worker.py")
FLEET_WORKER = os.path.join(SCRIPTS, "fleet_worker.py")

pytestmark = pytest.mark.slow


def _env(tmp_path, **kw):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "CKPT_ROOT": str(tmp_path / "ckpt"),
        "RESULT_FILE": str(tmp_path / "result.json"),
        "PROGRESS_FILE": str(tmp_path / "progress"),
    })
    env.update({k: str(v) for k, v in kw.items()})
    return env


def test_sigkill_during_async_write_resumes_from_committed(tmp_path):
    """Kill -9 while the async writer is mid-checkpoint: the torn step
    must be invisible to resume, which continues from the last COMMITTED
    step and finishes the run."""
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    marker = str(tmp_path / "in_write")
    # 3rd save (step 3): mark progress, then stall mid-write so the
    # parent can SIGKILL at the worst possible moment — data written,
    # commit not reached
    env = _env(
        tmp_path, TOTAL_STEPS=6,
        PADDLE_FAULTS=f"ckpt.data_written:touch:{marker}@2;"
                      f"ckpt.data_written:sleep:120@2")
    p = subprocess.Popen([sys.executable, WORKER], env=env,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    try:
        assert faults.wait_for_path(marker, timeout=120), \
            "worker never reached the injected write stall"
        p.send_signal(signal.SIGKILL)
    finally:
        p.wait(timeout=30)
    assert p.returncode == -signal.SIGKILL

    root = str(tmp_path / "ckpt")
    mgr = CheckpointManager(root, async_save=False)
    # previous committed checkpoint intact; the torn write invisible
    assert mgr.latest_step() == 2
    assert os.path.exists(os.path.join(root, "step_2", "COMMITTED"))
    leftovers = [d for d in os.listdir(root) if d != "step_1"
                 and not d.startswith("step_2")]
    assert all(not os.path.exists(os.path.join(root, d, "COMMITTED"))
               for d in leftovers), leftovers

    # resume run, no faults: must pick up at step 2 and finish
    out = subprocess.run([sys.executable, WORKER],
                         env=_env(tmp_path, TOTAL_STEPS=6),
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    result = json.load(open(tmp_path / "result.json"))
    assert result["resumed_from"] == 2
    assert result["final_step"] == 6
    assert result["opt_step"] == 6  # optimizer counter resumed, not reset
    assert result["committed"] == [5, 6]  # keep_last_n=2 + torn GC'd
    assert sorted(os.listdir(root)) == ["step_5", "step_6"]


def test_sigterm_produces_final_committed_checkpoint(tmp_path):
    """SIGTERM mid-run: the preemption handler triggers a final
    synchronous committed save and a clean rc-0 exit."""
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    env = _env(tmp_path, TOTAL_STEPS=100000, STEP_SLEEP="0.05",
               SAVE_EVERY=100000, INSTALL_PREEMPT=1)
    p = subprocess.Popen([sys.executable, WORKER], env=env,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    try:
        assert faults.wait_for_path(str(tmp_path / "progress"),
                                    timeout=240)
        time.sleep(0.3)  # let a few steps pass
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
    finally:
        p.kill()
    assert p.returncode == 0, out
    assert "PREEMPTED_SAVED" in out
    result = json.load(open(tmp_path / "result.json"))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    # the ONLY save of the run is the preemption one (interval 100000)
    assert mgr.latest_step() == result["preempted_at"] > 0
    st = None  # restore proves the final checkpoint is readable
    import numpy as np  # noqa: F401  (paddle import below needs numpy)
    import paddle_tpu as paddle

    st = {"model": {"weight": paddle.zeros([4, 4]),
                    "bias": paddle.zeros([4])},
          "opt": {"step": 0}}
    assert mgr.restore(st) == result["preempted_at"]
    assert st["opt"]["step"] == result["preempted_at"]


def test_launcher_forwards_sigterm_for_final_save(tmp_path):
    """The distributed launcher is the process the cloud signals:
    SIGTERM to it must fan out to workers, wait for their final save,
    and exit 0 without restarting the gang."""
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    env = _env(tmp_path, TOTAL_STEPS=100000, STEP_SLEEP="0.05",
               SAVE_EVERY=100000, INSTALL_PREEMPT=1)
    launcher = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restart", "3",
         "--stop_timeout", "60", WORKER],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        assert faults.wait_for_path(str(tmp_path / "progress"),
                                    timeout=240)
        time.sleep(0.3)
        launcher.send_signal(signal.SIGTERM)
        out, _ = launcher.communicate(timeout=120)
    finally:
        launcher.kill()
    # clean exit, no restart attempted despite --max_restart
    assert launcher.returncode == 0, out
    assert "forwarding to workers" in out
    result = json.load(open(tmp_path / "result.json"))
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    assert mgr.latest_step() == result["preempted_at"] > 0


# ---------------------------------------------------------------------------
# serving drain (ISSUE 6) — the subprocess/launcher versions of the
# tier-1 in-process pin in test_serving_resilience.py
# ---------------------------------------------------------------------------
def _assert_drained_result(tmp_path, n_requests, max_new=16):
    result = json.load(open(tmp_path / "result.json"))
    assert result["drained"] is True
    assert result["blocks_clean"] is True
    reasons = result["finished"]
    assert len(reasons) == n_requests          # nobody vanished
    completed = [r for r, why in reasons.items() if why == "length"]
    drained = [r for r, why in reasons.items()
               if why == "aborted:drain"]
    assert sorted(completed + drained) == sorted(reasons)
    assert drained, "SIGTERM landed too late to abort anything"
    assert completed, "SIGTERM landed before anything could finish"
    assert result["drain_aborted"] == len(drained)
    # running requests ran to completion; drained ones never started
    # (they were waiting — the engine aborts queued work immediately)
    for r in completed:
        assert result["n_tokens"][r] == max_new
    for r in drained:
        assert result["n_tokens"][r] == 0
    return result


def test_serving_worker_sigterm_drains_gracefully(tmp_path):
    """SIGTERM straight to the serving process: the engine drains —
    running requests finish, waiting ones abort structured — and the
    process exits 0 on its own."""
    env = _env(tmp_path, N_REQUESTS=8, MAX_NEW=16, STEP_SLEEP="0.05")
    p = subprocess.Popen([sys.executable, SERVING_WORKER], env=env,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    try:
        assert faults.wait_for_path(str(tmp_path / "progress"),
                                    timeout=240)
        time.sleep(0.4)                      # a few decode steps pass
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
    finally:
        p.kill()
    assert p.returncode == 0, out            # clean exit — the pin
    assert "SERVING_WORKER_DONE drained=True" in out
    _assert_drained_result(tmp_path, 8)


def test_launcher_forwards_sigterm_to_serving_worker(tmp_path):
    """The launcher is the process the cloud signals: its SIGTERM
    fan-out must reach the serving worker, whose drain then produces
    the same clean rc-0 exit with no gang restart."""
    env = _env(tmp_path, N_REQUESTS=8, MAX_NEW=16, STEP_SLEEP="0.05")
    launcher = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restart", "3",
         "--stop_timeout", "60", SERVING_WORKER],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        assert faults.wait_for_path(str(tmp_path / "progress"),
                                    timeout=240)
        time.sleep(0.4)
        launcher.send_signal(signal.SIGTERM)
        out, _ = launcher.communicate(timeout=120)
    finally:
        launcher.kill()
    assert launcher.returncode == 0, out     # no restart, clean stop
    assert "forwarding to workers" in out
    assert "SERVING_WORKER_DONE drained=True" in out
    _assert_drained_result(tmp_path, 8)


def test_fleet_sigterm_hands_off_with_token_parity(tmp_path):
    """SIGTERM to a 2-replica fleet process mid-batch: replica r0
    (which owns the signal monitor, zero drain grace) drains and its
    requests hand off to r1 — every request still finishes
    'stop'/'length' with generations BIT-IDENTICAL to the uninterrupted
    single-engine reference the worker computed up front. The hand-off
    must be invisible: no aborted:drain reaches the client."""
    env = _env(tmp_path, N_REQUESTS=6, MAX_NEW=8, STEP_SLEEP="0.05")
    p = subprocess.Popen([sys.executable, FLEET_WORKER], env=env,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    try:
        # progress only appears once the FLEET phase is stepping, so
        # the signal can never land on the reference run
        assert faults.wait_for_path(str(tmp_path / "progress"),
                                    timeout=300)
        time.sleep(0.3)                      # a few fleet steps pass
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=180)
    finally:
        p.kill()
    assert p.returncode == 0, out
    assert "FLEET_WORKER_DONE parity=True" in out
    with open(tmp_path / "result.json") as f:
        res = json.load(f)
    assert res["parity"] is True
    assert len(res["finished"]) == 6         # nobody vanished
    assert set(res["finished"].values()) <= {"stop", "length"}
    assert all(n == 8 for n in res["n_tokens"].values())
    # every request r0's drain aborted was re-dispatched to the peer
    assert res["handoffs"] >= res["r0_drain_aborted"]
    assert res["replicas_dead"] == 0         # drain, not death
