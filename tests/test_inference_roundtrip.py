"""jit.save -> jit.load round trip (the AnalysisPredictor role:
reference paddle/fluid/inference/api/analysis_predictor.h:100) and
compiled-step GradScaler support (reference HybridParallelGradScaler)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import amp, nn, optimizer
from paddle_tpu.distributed.engine import ParallelTrainStep
from paddle_tpu.distributed.mesh import ProcessMesh


def test_jit_save_load_executes(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    net.eval()
    x = paddle.randn([2, 8])
    ref = net(x).numpy()

    path = str(tmp_path / "m")
    paddle.jit.save(net, path,
                    input_spec=[paddle.jit.InputSpec([2, 8], "float32")])
    loaded = paddle.jit.load(path)
    out = loaded(x)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)
    # state dict round-trips too
    sd = loaded.state_dict()
    np.testing.assert_array_equal(sd["0.weight"],
                                  net[0].weight.numpy())


def test_jit_save_weights_only_returns_payload(tmp_path):
    net = nn.Linear(4, 4)
    path = str(tmp_path / "w")
    paddle.jit.save(net, path)
    payload = paddle.jit.load(path)
    assert isinstance(payload, dict)
    assert "state_dict" in payload


def test_jit_load_weights_only_contract(tmp_path):
    """The documented save/load asymmetry: without input_spec, load
    returns a WeightsOnlyPayload — usable as a dict, loadable into a
    rebuilt Layer, and CALLING it raises a clear error naming the fix
    (not a bare 'dict is not callable')."""
    net = nn.Linear(4, 4)
    path = str(tmp_path / "w")
    paddle.jit.save(net, path)
    payload = paddle.jit.load(path)
    assert isinstance(payload, paddle.jit.WeightsOnlyPayload)
    with pytest.raises(RuntimeError, match="input_spec"):
        payload(paddle.randn([2, 4]))
    # the supported path: rebuild + set_state_dict
    net2 = nn.Linear(4, 4)
    net2.set_state_dict(payload["state_dict"])
    np.testing.assert_array_equal(net2.weight.numpy(),
                                  net.weight.numpy())
    assert sorted(payload.state_dict()) == sorted(net.state_dict())


def test_trainstep_with_gradscaler_skips_on_overflow():
    paddle.seed(1)
    m = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    scaler = amp.GradScaler(init_loss_scaling=2.0 ** 10,
                            decr_every_n_nan_or_inf=1)
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt, scaler=scaler)
    x, y = paddle.randn([4, 4]), paddle.randn([4, 4])

    w0 = m.weight.numpy().copy()
    loss = step(x, y)
    assert np.isfinite(float(loss.item()))
    assert not np.allclose(m.weight.numpy(), w0)  # update applied

    # poison a batch -> overflow grads -> update skipped, scale backs off
    w1 = m.weight.numpy().copy()
    scale_before = scaler._scale
    bad = paddle.to_tensor(np.full((4, 4), np.inf, np.float32))
    step(bad, y)
    np.testing.assert_array_equal(m.weight.numpy(), w1)
    assert scaler._scale < scale_before


def test_trainstep_scaler_matches_unscaled_losses():
    """With finite grads the scaled path must train identically."""
    rng = np.random.RandomState(0)
    X = rng.randn(8, 4).astype(np.float32)
    Y = rng.randn(8, 4).astype(np.float32)

    def run(use_scaler):
        paddle.seed(2)
        m = nn.Linear(4, 4)
        opt = optimizer.AdamW(learning_rate=0.01,
                              parameters=m.parameters())
        scaler = amp.GradScaler() if use_scaler else None
        step = paddle.jit.TrainStep(m, nn.MSELoss(), opt, scaler=scaler)
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item()) for _ in range(5)]

    np.testing.assert_allclose(run(False), run(True), rtol=1e-5)


def test_parallel_trainstep_with_gradscaler():
    rng = np.random.RandomState(1)
    X = rng.randn(8, 16).astype(np.float32)
    Y = rng.randn(8, 16).astype(np.float32)

    paddle.seed(3)
    m = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 16))
    opt = optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    scaler = amp.GradScaler()
    mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
    step = ParallelTrainStep(m, nn.MSELoss(), opt, mesh, scaler=scaler)
    losses = [float(step(paddle.to_tensor(X),
                         paddle.to_tensor(Y)).item()) for _ in range(5)]
    assert losses[-1] < losses[0]
    assert scaler._good_steps == 5


def test_dp_no_sync_accumulation_semantics():
    """no_sync: backward inside the context accumulates into .grad
    identically to plain accumulation (nothing is synced or dropped)."""
    import paddle_tpu.distributed as dist

    paddle.seed(4)
    m = nn.Linear(4, 4)
    dp = dist.DataParallel(m)
    x1, x2 = paddle.randn([2, 4]), paddle.randn([2, 4])

    with dp.no_sync():
        dp(x1).sum().backward()
    g_partial = m.weight.grad.numpy().copy()
    dp(x2).sum().backward()
    g_total = m.weight.grad.numpy()

    m.clear_gradients() if hasattr(m, "clear_gradients") else None
    m.weight.grad = None
    m.bias.grad = None
    dp(x1).sum().backward()
    dp(x2).sum().backward()
    np.testing.assert_allclose(m.weight.grad.numpy(), g_total, rtol=1e-6)
    assert not np.allclose(g_partial, g_total)
