import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.jit import TrainStep, to_static


def test_to_static_matches_eager():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m.eval()
    x = paddle.randn([3, 4])
    eager = m(x).numpy()
    traced = to_static(m)
    static = traced(x).numpy()
    np.testing.assert_allclose(eager, static, rtol=1e-5, atol=1e-6)


def test_to_static_function():
    @to_static
    def f(a, b):
        return a * b + a

    a = paddle.to_tensor([2.0])
    b = paddle.to_tensor([3.0])
    np.testing.assert_allclose(f(a, b).numpy(), [8.0])


def test_to_static_threads_bn_buffers():
    m = nn.Sequential(nn.Conv2D(1, 2, 3, padding=1), nn.BatchNorm2D(2))
    traced = to_static(m)
    bn = m[1]
    before = bn._mean.numpy().copy()
    m.train()
    traced(paddle.randn([4, 1, 5, 5]))
    after = bn._mean.numpy()
    assert not np.allclose(before, after)


def test_train_step_descends_and_matches_eager():
    def build():
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=m.parameters())
        return m, opt

    np.random.seed(0)
    X = np.random.randn(32, 4).astype(np.float32)
    Y = (X.sum(-1, keepdims=True) * 0.5).astype(np.float32)
    loss_fn = nn.MSELoss()

    # eager training
    m1, opt1 = build()
    eager_losses = []
    for _ in range(10):
        loss = loss_fn(m1(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        opt1.step()
        opt1.clear_grad()
        eager_losses.append(float(loss.item()))

    # compiled whole-step training
    m2, opt2 = build()
    step = TrainStep(m2, loss_fn, opt2)
    jit_losses = []
    for _ in range(10):
        jit_losses.append(float(step(paddle.to_tensor(X),
                                     paddle.to_tensor(Y)).item()))

    assert jit_losses[-1] < jit_losses[0] * 0.9
    np.testing.assert_allclose(eager_losses, jit_losses, rtol=2e-3,
                               atol=1e-5)


def test_train_step_updates_params_in_layer():
    m = nn.Linear(2, 1)
    w0 = m.weight.numpy().copy()
    opt = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    step = TrainStep(m, nn.MSELoss(), opt)
    step(paddle.randn([4, 2]), paddle.randn([4, 1]))
    assert not np.allclose(m.weight.numpy(), w0)


def test_jit_save_load(tmp_path):
    m = nn.Linear(3, 2)
    path = str(tmp_path / "model")
    paddle.jit.save(m, path, input_spec=[paddle.jit.InputSpec([1, 3])])
    loaded = paddle.jit.load(path)
    assert isinstance(loaded, paddle.jit.TranslatedLayer)
    assert "weight" in loaded.state_dict()
    x = paddle.randn([1, 3])
    np.testing.assert_allclose(m(x).numpy(), loaded(x).numpy(),
                               rtol=1e-5, atol=1e-6)
