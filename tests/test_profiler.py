"""Profiler: host scopes through dispatch, scheduler windows, chrome
export, summary, throughput timer, MFU (reference profiler.py:346,79,215)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, profiler
from paddle_tpu.profiler import (
    Profiler, ProfilerState, ProfilerTarget, RecordEvent,
    estimate_mfu, export_chrome_tracing, make_scheduler,
)


def test_record_event_scopes_through_dispatch():
    p = Profiler(targets=[ProfilerTarget.CPU]).start()
    x = paddle.randn([8, 8])
    y = paddle.matmul(x, x)
    with RecordEvent("user_scope"):
        _ = paddle.add(y, y)
    p.stop()
    names = {e["name"] for e in p.host_events}
    assert "op::matmul" in names
    assert "op::add" in names
    assert "user_scope" in names
    # hook removed after stop: no growth
    n = len(p.host_events)
    _ = paddle.matmul(x, x)
    assert len(p.host_events) == n


def test_scheduler_states():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                           skip_first=1)
    states = [sched(i) for i in range(6)]
    assert states == [ProfilerState.CLOSED, ProfilerState.CLOSED,
                      ProfilerState.READY, ProfilerState.RECORD,
                      ProfilerState.RECORD_AND_RETURN,
                      ProfilerState.CLOSED]


def test_scheduler_windows_and_chrome_export(tmp_path):
    handler = export_chrome_tracing(str(tmp_path))
    p = Profiler(scheduler=make_scheduler(closed=1, ready=0, record=2,
                                          repeat=1),
                 on_trace_ready=handler)
    p.start()
    x = paddle.randn([4, 4])
    for _ in range(4):
        _ = paddle.matmul(x, x)
        p.step()
    p.stop()
    assert p.exported_paths, "trace was never exported"
    with open(p.exported_paths[0]) as f:
        trace = json.load(f)
    assert any(e["name"] == "op::matmul" for e in trace["traceEvents"])


def test_summary_aggregation():
    p = Profiler().start()
    x = paddle.randn([8, 8])
    for _ in range(3):
        _ = paddle.matmul(x, x)
    p.stop()
    stats = p.summary(print_table=False)
    assert stats["op::matmul"]["calls"] == 3
    assert stats["op::matmul"]["total_ms"] > 0


def test_benchmark_timer():
    from paddle_tpu.profiler import benchmark

    b = benchmark()
    b.begin()
    import time

    for _ in range(5):
        time.sleep(0.01)
        b.step(num_samples=32)
    b.end()
    rep = b.report()
    assert rep["steps"] == 5
    assert 5 < rep["avg_step_ms"] < 100
    assert rep["ips"] > 0


def test_estimate_mfu():
    # 1 TFLOP step in 10ms on a 197TFLOP/s chip ~= 50.7%
    mfu = estimate_mfu(1e12, 0.01, peak_flops=197e12)
    assert abs(mfu - 1e12 / 0.01 / 197e12) < 1e-9
    assert 0.4 < mfu < 0.6
    assert profiler.device_peak_flops() > 0


def test_device_summary_reports_xla_ops(tmp_path):
    """Per-op device stats from the xplane trace (reference
    profiler_statistic.py device table role)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import profiler

    prof = profiler.Profiler(targets=None, trace_dir=str(tmp_path))
    prof.start()
    f = jax.jit(lambda x: (x @ x).sum())
    jax.block_until_ready(f(jnp.ones((128, 128))))
    prof.stop()
    stats = prof.device_summary(print_table=False)
    assert isinstance(stats, dict)
    if stats:  # device plane present (CPU backend still records XLA ops)
        row = next(iter(stats.values()))
        assert {"calls", "total_ms", "avg_ms"} <= set(row)


def test_phase_classifier():
    """XLA op name -> phase bucket (the profiler_statistic.py
    kernel/communication/memcpy categories, VERDICT r4 #9)."""
    from paddle_tpu.profiler import Profiler

    assert Profiler.classify_phase("fusion.123") == "compute"
    assert Profiler.classify_phase("dot_general.7") == "compute"
    assert Profiler.classify_phase("all-reduce.1") == "collective"
    assert Profiler.classify_phase("all-gather-start") == "collective"
    assert Profiler.classify_phase("reduce-scatter.2") == "collective"
    assert Profiler.classify_phase("collective-permute.5") == "collective"
    assert Profiler.classify_phase("copy.4") == "copy"
    assert Profiler.classify_phase("copy-start.1") == "copy"
    assert Profiler.classify_phase("infeed") == "copy"


def test_phase_summary_graceful_without_device_trace(tmp_path):
    """On backends without a device plane (CPU tests), phase_summary
    returns {} and summary() stays usable."""
    from paddle_tpu import profiler

    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                             trace_dir=str(tmp_path))
    prof.start()
    import paddle_tpu as paddle
    (paddle.ones([8]) * 2).sum()
    prof.stop()
    assert prof.phase_summary(print_table=False) == {}
    s = prof.summary(print_table=False)
    assert "_device_phases" not in s


def test_summary_reports_pipeline_schedule():
    from paddle_tpu import profiler

    class FakeStep:
        schedule = "interleave"
        bubble_fraction = 0.1579
        S, V, M = 4, 2, 8

    prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
    prof.start()
    prof.stop()
    s = prof.summary(print_table=False, pipeline_step=FakeStep())
    assert s["_pipeline_schedule"]["schedule"] == "interleave"
    assert s["_pipeline_schedule"]["bubble_fraction"] == 0.1579
