"""Fleet router acceptance pins (ISSUE 8).

Two layers, matching the router's transport seam:

* model-free tests drive :class:`FleetRouter` against deterministic
  ``FakeReplica`` handles — dispatch policy, fleet-wide admission,
  weighted-DRR tenant fairness (including the randomized storm with
  bounded per-tenant skew), registry liveness, hand-off bookkeeping,
  autoscale decisions;
* tiny-Llama e2e tests pin the headline guarantee: drain hand-off is
  LOSSLESS and TOKEN-IDENTICAL — a 2-replica fleet preempted mid-run
  produces bit-identical generations (greedy AND sampled) to an
  uninterrupted single engine, and with one replica the PR-6
  ``aborted:drain`` contract is unchanged.

The slow subprocess SIGTERM version lives in test_fault_e2e.py
(fleet_worker.py); single-engine serving pins in
test_serving_resilience.py.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.distributed.replica_registry import MemStore, ReplicaRegistry
from paddle_tpu.distributed.watchdog import PreemptionMonitor
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    EngineConfig, LLMEngine, RequestOutput, SamplingParams,
)
from paddle_tpu.serving.fleet import (
    AutoscalePolicy, FleetConfig, FleetController, FleetRouter,
    InProcessReplica, LoadThresholdPolicy, ReplicaHandle, ReplicaLoad,
    TenantQueue,
)
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _no_fault_leak():
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# FakeReplica: deterministic, model-free handle — one token per request
# per step, value = 1000 + position, so generations are predictable
# ---------------------------------------------------------------------------
class FakeReplica(ReplicaHandle):
    def __init__(self, replica_id, ttft=None, capacity=4):
        self.replica_id = replica_id
        self.alive = True
        self.retiring = False
        self.ttft = ttft            # None = cold estimator (abstains)
        self.capacity = capacity
        self.reqs = {}              # rid -> [sampling, generated]
        self.rng_seen = {}          # rid -> rng_state passed at add
        self.dispatch_log = []      # rids in dispatch order (test hook)
        self._draining = False

    def admission_verdict(self, prompt_tokens):
        if not self.alive:
            return "replica is dead"
        if self._draining:
            return "replica is draining"
        if len(self.reqs) >= self.capacity:
            return "queue full"
        return None

    def estimated_ttft_ms(self, prompt_tokens):
        return self.ttft

    def load(self):
        return ReplicaLoad(
            queue_depth=0, num_running=len(self.reqs),
            kv_utilization=min(1.0, len(self.reqs)
                               / max(self.capacity, 1)))

    @property
    def is_draining(self):
        return self._draining

    @property
    def drained(self):
        return self._draining and not self.reqs

    def has_unfinished(self):
        return self.alive and bool(self.reqs)

    def add_request(self, request_id, prompt_ids, sampling, *,
                    rng_state=None):
        self.reqs[request_id] = [sampling, []]
        self.rng_seen[request_id] = rng_state
        self.dispatch_log.append(request_id)

    def abort_request(self, request_id):
        return self.reqs.pop(request_id, None) is not None

    def release_request(self, request_id):
        self.reqs.pop(request_id, None)

    def rng_state(self, request_id):
        return {"fake_state_for": request_id}

    def step(self):
        if not self.alive:
            return []
        outs = []
        for rid in list(self.reqs):
            sp, gen = self.reqs[rid]
            gen.append(1000 + len(gen))
            done = len(gen) >= sp.max_new_tokens
            outs.append(RequestOutput(
                request_id=rid, token=gen[-1], finished=done,
                generated=list(gen),
                finish_reason="length" if done else None))
            if done:
                del self.reqs[rid]
        return outs

    def start_drain(self, reason="manual"):
        self._draining = True
        outs = []
        for rid in list(self.reqs):
            sp, gen = self.reqs.pop(rid)
            outs.append(RequestOutput(
                request_id=rid, token=None, finished=True,
                generated=list(gen), finish_reason="aborted:drain"))
        return outs


def _drain_router(router, max_steps=200):
    outs = []
    for _ in range(max_steps):
        if not router.has_unfinished():
            return outs
        outs.extend(router.step())
    raise AssertionError("router failed to converge")


# ---------------------------------------------------------------------------
# dispatch policy
# ---------------------------------------------------------------------------
class TestDispatch:
    def test_prefers_lowest_estimated_ttft_when_all_warm(self):
        fast = FakeReplica("fa", ttft=10.0)
        mid = FakeReplica("fb", ttft=30.0)
        slow = FakeReplica("fc", ttft=90.0)
        router = FleetRouter([slow, fast, mid])
        router.add_request([1, 2, 3], SamplingParams(max_new_tokens=1))
        router.step()
        assert fast.dispatch_log and not mid.dispatch_log \
            and not slow.dispatch_log

    def test_least_loaded_fallback_while_any_estimate_cold(self):
        # rb is cold (no step history -> estimator abstains): the
        # router must not trust ra's number against a blind peer
        busy = FakeReplica("ra", ttft=1.0)
        busy.reqs = {"pre-%d" % i: [SamplingParams(max_new_tokens=99), []]
                     for i in range(3)}
        idle = FakeReplica("rb", ttft=None)
        router = FleetRouter([busy, idle])
        router.add_request([1, 2], SamplingParams(max_new_tokens=1))
        router.step()
        assert idle.dispatch_log == ["fleet-0"]

    def test_fleet_admits_when_any_replica_admits(self):
        full = FakeReplica("ra", capacity=0)       # always rejects
        open_ = FakeReplica("rb", capacity=4)
        router = FleetRouter([full, open_])
        rid = router.add_request([1], SamplingParams(max_new_tokens=2))
        outs = _drain_router(router)
        final = [o for o in outs if o.finished]
        assert [o.request_id for o in final] == [rid]
        assert final[0].finish_reason == "length"
        assert router.num_rejected_fleetwide == 0
        assert open_.dispatch_log == [rid]

    def test_fleet_rejects_only_when_every_replica_rejects(self):
        router = FleetRouter([FakeReplica("ra", capacity=0),
                              FakeReplica("rb", capacity=0)])
        rid = router.add_request([1], SamplingParams(max_new_tokens=2))
        outs = _drain_router(router)
        assert [(o.request_id, o.finish_reason) for o in outs] \
            == [(rid, "rejected")]
        assert router.num_rejected_fleetwide == 1
        assert router.finish_counts == {"rejected": 1}

    def test_empty_fleet_rejects(self):
        router = FleetRouter([])
        router.add_request([1], SamplingParams(max_new_tokens=2))
        outs = _drain_router(router)
        assert [o.finish_reason for o in outs] == ["rejected"]

    def test_queued_deadline_expires_in_queue(self):
        # capacity-1 replica: the second request waits in the ROUTER
        # queue past its deadline and must expire there, first-class
        r = FakeReplica("ra", capacity=1)
        router = FleetRouter([r])
        r1 = router.add_request([1], SamplingParams(max_new_tokens=6))
        r2 = router.add_request([2], SamplingParams(max_new_tokens=1,
                                                    deadline_ms=5.0))
        router.step()                      # r1 dispatched, r2 blocked
        time.sleep(0.02)
        outs = _drain_router(router)
        final = {o.request_id: o.finish_reason
                 for o in outs if o.finished}
        assert final == {r1: "length", r2: "expired"}
        assert r.dispatch_log == [r1]      # r2 never reached a replica

    def test_abort_queued_request(self):
        r = FakeReplica("ra", capacity=1)
        router = FleetRouter([r])
        r1 = router.add_request([1], SamplingParams(max_new_tokens=4))
        r2 = router.add_request([2], SamplingParams(max_new_tokens=4))
        router.step()
        assert router.abort_request(r2)
        outs = _drain_router(router)
        final = {o.request_id: o.finish_reason
                 for o in outs if o.finished}
        assert final[r1] == "length"
        assert router.get_request(r2).finish_reason == "aborted:user"
        assert r.dispatch_log == [r1]

    def test_duplicate_ids_raise(self):
        router = FleetRouter([FakeReplica("ra")])
        with pytest.raises(ValueError):
            router.attach_replica(FakeReplica("ra"))
        router.add_request("x", [1], SamplingParams(max_new_tokens=1))
        with pytest.raises(ValueError):
            router.add_request("x", [1], SamplingParams(max_new_tokens=1))
        with pytest.raises(ValueError):
            router.release_request("x")    # not finished yet


# ---------------------------------------------------------------------------
# tenant fairness (weighted DRR)
# ---------------------------------------------------------------------------
class TestTenantFairness:
    def test_drr_weighted_share(self):
        # quantum 8, cost 16: weight-2 A affords every visit, weight-1
        # B every second visit -> exact A,A,B cadence (2:1 share)
        q = TenantQueue(quantum_tokens=8, weights={"A": 2.0})
        for i in range(8):
            q.push("A", f"a{i}", 16)
            q.push("B", f"b{i}", 16)
        order = [q.pop()[0] for _ in range(9)]
        assert order.count("A") == 6 and order.count("B") == 3

    def test_drr_unpop_refunds_deficit(self):
        q = TenantQueue(quantum_tokens=10)
        q.push("A", "a0", 10)
        t, item, cost = q.pop()
        q.unpop(t, item, cost)
        assert len(q) == 1
        assert q.pop() == ("A", "a0", 10)   # still affordable, same head

    def test_idle_tenant_forfeits_banked_deficit(self):
        q = TenantQueue(quantum_tokens=10)
        q.push("A", "a0", 10)
        q.pop()
        assert q.pop() is None              # A left the rotation
        q.push("A", "a1", 30)
        # a fresh join banks from zero: 3 visits to afford cost 30
        assert q.pop() == ("A", "a1", 30)

    def test_storm_bounded_wait_skew(self):
        """Randomized arrival storm: a 4x heavier tenant must not push
        the light tenant's dispatches to the back — DRR alternates, so
        light-tenant positions stay within a small constant of ideal."""
        rng = np.random.default_rng(7)
        arrivals = ["heavy"] * 24 + ["light"] * 6
        rng.shuffle(arrivals)
        replica = FakeReplica("ra", capacity=2)
        # adaptive default quantum: every request costs 6 tokens
        # (4 prompt + 2 max_new), so the observed-mean quantum settles
        # at 6 — one dispatch per DRR visit — without the storm having
        # to size the granularity to its traffic by hand (the old flat
        # 256 default would let one visit burst ~40 small requests)
        router = FleetRouter([replica], FleetConfig())
        sp = {t: SamplingParams(max_new_tokens=2, tenant_id=t)
              for t in ("heavy", "light")}
        by_tenant = {"heavy": [], "light": []}
        for i, t in enumerate(arrivals):
            by_tenant[t].append(
                router.add_request(f"{t}-{i}", [1, 2, 3, 4], sp[t]))
        _drain_router(router)
        pos = {rid: i for i, rid in enumerate(replica.dispatch_log)}
        assert len(pos) == 30               # everyone dispatched once
        light_pos = sorted(pos[r] for r in by_tenant["light"])
        heavy_pos = sorted(pos[r] for r in by_tenant["heavy"])
        # equal weights + equal costs => near-alternation while both
        # queues are non-empty: the k-th light dispatch sits near 2k
        assert light_pos[-1] <= 2 * len(light_pos) + 4
        assert np.mean(light_pos) < np.mean(heavy_pos)
        snap = router.snapshot()
        assert snap["fleet_tenants"]["light"]["dispatched"] == 6
        assert snap["fleet_tenants"]["heavy"]["dispatched"] == 24

    def test_adaptive_quantum_tracks_mean_cost(self):
        q = TenantQueue()                   # no explicit quantum
        assert q.quantum == TenantQueue.DEFAULT_QUANTUM  # cold start
        q.push("A", "a0", 10)
        q.push("B", "b0", 30)
        assert q.quantum == 20.0            # running mean of pushes
        # refunds and hand-off re-enqueues must not skew the mean
        t, item, cost = q.pop()
        q.unpop(t, item, cost)
        q.push("C", "c0", 0, front=True)    # hand-off: cost already paid
        assert q.quantum == 20.0
        # weight-2 A affords cost-40 heads every visit (grant 2*20=40),
        # weight-1 B (grant 20) every second: same 2:1 cadence the
        # fixed-quantum share test pins, now from observed costs alone
        q2 = TenantQueue(weights={"A": 2.0})
        for i in range(6):
            q2.push("A", f"a{i}", 40)
            q2.push("B", f"b{i}", 40)
        assert q2.quantum == 40.0
        order = [q2.pop()[0] for _ in range(9)]
        assert order.count("A") == 6 and order.count("B") == 3

    def test_explicit_quantum_still_pins(self):
        q = TenantQueue(quantum_tokens=8)
        q.push("A", "a0", 1000)             # huge observed cost
        assert q.quantum == 8               # override wins
        with pytest.raises(ValueError):
            TenantQueue(quantum_tokens=0)

    def test_per_tenant_wait_recorded(self):
        router = FleetRouter([FakeReplica("ra")])
        router.add_request([1], SamplingParams(max_new_tokens=1,
                                               tenant_id="t1"))
        _drain_router(router)
        assert len(router.tenant_wait_s["t1"]) == 1
        assert router.snapshot()["fleet_tenants"]["t1"]["wait_ms_avg"] \
            >= 0.0


# ---------------------------------------------------------------------------
# registry liveness + health sweep
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_heartbeat_ttl_driven_clock(self):
        reg = ReplicaRegistry(MemStore(), ttl_s=5.0)
        reg.register("ra", now=100.0)
        reg.register("rb", now=100.0)
        assert set(reg.alive(now=104.0)) == {"ra", "rb"}
        reg.heartbeat("ra", now=106.0)
        assert set(reg.alive(now=106.0)) == {"ra"}   # rb stale
        assert reg.is_alive("rb", now=106.0) is False
        reg.heartbeat("rb", now=107.0)               # resumes -> back
        assert set(reg.alive(now=107.0)) == {"ra", "rb"}
        reg.deregister("ra")
        assert reg.members() == ["rb"]

    def test_garbage_record_reads_as_absent(self):
        store = MemStore()
        reg = ReplicaRegistry(store, ttl_s=5.0)
        reg.register("ra", now=100.0)
        store.set("serving_fleet/hb/ra", b"\xff not json")
        assert reg.record("ra") is None
        assert reg.alive(now=100.0) == {}

    def test_slash_in_replica_id_rejected(self):
        reg = ReplicaRegistry(MemStore())
        with pytest.raises(ValueError):
            reg.register("a/b")
        with pytest.raises(ValueError):
            reg.register("a__b")

    def test_stale_heartbeat_kills_replica_and_hands_off(self):
        # freeze router heartbeats after the first so rb's record can
        # go stale underneath it -> health sweep treats rb as dead and
        # its request finishes on ra, invisibly to the client. Liveness
        # is the registry's skew-immune mode: staleness means "record
        # unchanged past ttl on the READER's monotonic clock", so the
        # test leaps the reader clock and beats only ra — rb's silence
        # is what kills it, exactly what a hung worker looks like.
        ra, rb = FakeReplica("ra", ttft=5.0), FakeReplica("rb", ttft=1.0)
        reg = ReplicaRegistry(MemStore(), ttl_s=5.0)
        router = FleetRouter(
            [ra, rb], FleetConfig(heartbeat_interval_s=1e6),
            registry=reg)
        rid = router.add_request([1], SamplingParams(max_new_tokens=4))
        router.step()                           # dispatched to rb
        assert rb.dispatch_log == [rid]
        t0 = time.monotonic()
        reg._mono = lambda: t0 + 999.0          # reader leaps past ttl
        reg.heartbeat("ra")                     # ra's record changes...
        assert reg.is_alive("ra")               # ...re-observed fresh
        outs = _drain_router(router)
        final = {o.request_id: o.finish_reason
                 for o in outs if o.finished}
        assert final == {rid: "length"}
        assert rb.alive is False
        assert router.num_replicas_dead == 1
        assert router.num_handoffs == 1
        assert ra.dispatch_log == [rid]

    def test_externally_dead_handle_recovered(self):
        ra, rb = FakeReplica("ra", ttft=5.0), FakeReplica("rb", ttft=1.0)
        router = FleetRouter([ra, rb])
        rid = router.add_request([1], SamplingParams(max_new_tokens=4))
        router.step()
        rb.alive = False                        # flipped outside router
        outs = _drain_router(router)
        assert {o.request_id: o.finish_reason
                for o in outs if o.finished} == {rid: "length"}
        assert router.num_replicas_dead == 1
        assert len(router.get_request(rid).generated) == 4


# ---------------------------------------------------------------------------
# drain hand-off bookkeeping (model-free)
# ---------------------------------------------------------------------------
class TestHandoff:
    def test_drain_fault_hands_off_invisibly(self):
        ra, rb = FakeReplica("ra", ttft=1.0), FakeReplica("rb", ttft=9.0)
        router = FleetRouter([ra, rb])
        rids = [router.add_request([1, 2], SamplingParams(
            max_new_tokens=6)) for _ in range(2)]
        # fire after 2 router steps, once: ra has partial generations
        faults.install("fleet.drain_replica:flag:ra@2*1")
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert set(final) == set(rids)
        assert all(final[r].finish_reason == "length" for r in rids)
        # total token count is exact across the hand-off seam
        assert all(len(final[r].generated) == 6 for r in rids)
        assert router.num_handoffs == 2
        assert ra.is_draining
        assert all(rb.rng_seen[r] == {"fake_state_for": r}
                   for r in rids)            # sampling state rode along
        assert router.finish_counts == {"length": 2}

    def test_handoff_disabled_surfaces_pr6_abort(self):
        ra, rb = FakeReplica("ra", ttft=1.0), FakeReplica("rb", ttft=9.0)
        router = FleetRouter([ra, rb], FleetConfig(handoff=False))
        rid = router.add_request([1], SamplingParams(max_new_tokens=8))
        router.step()
        router.step()
        router.retire_replica(ra)
        outs = _drain_router(router)
        final = [o for o in outs if o.finished]
        assert [o.finish_reason for o in final] == ["aborted:drain"]
        assert final[0].generated != []      # partial progress kept
        assert router.num_handoffs == 0
        assert not rb.dispatch_log

    def test_max_handoffs_bounds_bouncing(self):
        # every replica drains the moment it's dispatched to: the
        # request must surface its abort after max_handoffs bounces,
        # not ping-pong forever
        class DrainOnStep(FakeReplica):
            def step(self):
                if self.reqs and not self._draining:
                    return self.start_drain("unstable")
                return super().step()

        router = FleetRouter(
            [DrainOnStep("ra"), DrainOnStep("rb"), DrainOnStep("rc")],
            FleetConfig(max_handoffs=2))
        rid = router.add_request([1], SamplingParams(max_new_tokens=4))
        outs = _drain_router(router)
        final = [o for o in outs if o.finished]
        assert [o.request_id for o in final] == [rid]
        assert final[0].finish_reason == "aborted:drain"
        assert router.num_handoffs == 2

    def test_slow_replica_fault_stalls_router_step(self):
        # the chaos point slows the router loop WITHOUT touching any
        # request state: generations are unchanged, only wall time grows
        ra = FakeReplica("ra", ttft=1.0)
        router = FleetRouter([ra])
        rid = router.add_request([1], SamplingParams(max_new_tokens=3))
        inj = faults.install("fleet.slow_replica:flag:0.05*2")
        t0 = time.monotonic()
        outs = _drain_router(router)
        assert time.monotonic() - t0 >= 0.1
        assert inj.faults("fleet.slow_replica")[0].fired == 2
        final = [o for o in outs if o.finished]
        assert [o.request_id for o in final] == [rid]
        assert final[0].finish_reason == "length"
        assert len(final[0].generated) == 3

    def test_kill_fault_reenqueues_in_arrival_order(self):
        ra, rb = FakeReplica("ra", ttft=1.0, capacity=8), \
            FakeReplica("rb", ttft=9.0, capacity=8)
        router = FleetRouter([ra, rb])
        rids = [router.add_request([1], SamplingParams(max_new_tokens=9))
                for _ in range(3)]
        router.step()
        assert ra.dispatch_log == rids
        faults.install("fleet.kill_replica:flag:ra*1")
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert all(final[r].finish_reason == "length" for r in rids)
        assert all(len(final[r].generated) == 9 for r in rids)
        assert rb.dispatch_log == rids       # arrival order preserved
        assert router.num_replicas_dead == 1
        assert router.num_handoffs == 3


# ---------------------------------------------------------------------------
# elastic scaling
# ---------------------------------------------------------------------------
class TestScaling:
    def test_policy_hysteresis(self):
        p = LoadThresholdPolicy(high=0.8, low=0.2, min_replicas=1,
                                max_replicas=3)
        assert p.decide(0.9, 2, 0) == 3
        assert p.decide(0.9, 3, 0) is None      # at max
        assert p.decide(0.5, 2, 0) is None      # in band
        assert p.decide(0.1, 2, 0) == 1
        assert p.decide(0.1, 1, 0) is None      # at min
        assert p.decide(0.0, 0, 5) == 1         # queued, nothing live
        with pytest.raises(ValueError):
            LoadThresholdPolicy(high=0.2, low=0.8)

    def test_policy_tenant_high_trigger(self):
        p = LoadThresholdPolicy(high=0.9, low=0.1, max_replicas=4,
                                tenant_high=0.5)
        # mean in band, one hot tenant: scale up anyway
        assert p.decide(0.4, 2, 0, tenant_load=0.8) == 3
        # a hot tenant also vetoes the scale-down leg
        assert p.decide(0.05, 2, 0, tenant_load=0.8) == 3
        # no skew -> bit-identical to the scalar policy
        assert p.decide(0.4, 2, 0, tenant_load=0.0) is None
        assert p.decide(0.05, 2, 0, tenant_load=0.0) == 1
        # knob off (default): tenant signal ignored entirely
        assert LoadThresholdPolicy(high=0.9).decide(
            0.4, 2, 0, tenant_load=0.99) is None
        with pytest.raises(ValueError):
            LoadThresholdPolicy(tenant_high=1.5)

    def test_router_tenant_load_amplifies_skew(self):
        router = FleetRouter([FakeReplica("ra", capacity=16)])
        for tenant, n in (("hot", 3), ("cold", 1)):
            for _ in range(n):
                router.add_request([1], SamplingParams(
                    max_new_tokens=99, tenant_id=tenant))
        router.step()                           # dispatch all 4
        load = router.load()                    # 4/8 occupancy = 0.5
        # share 0.75 x 2 active tenants = 1.5x amplification
        assert router.tenant_load() == pytest.approx(load * 1.5)
        assert router.tenant_dispatches == {"hot": 3, "cold": 1}
        # the poll consumed the window; nothing new dispatched since
        assert router.tenant_load() == 0.0

    def test_tick_passes_tenant_load_and_tolerates_old_policies(self):
        busy = FakeReplica("ra", capacity=16)
        router = FleetRouter([busy])
        for _ in range(4):
            router.add_request([1], SamplingParams(
                max_new_tokens=99, tenant_id="hot"))
        router.step()

        class OldPolicy(AutoscalePolicy):
            def decide(self, load, replicas_live, queued):
                return None                     # pre-kwarg signature

        ctl = FleetController(router, lambda i: FakeReplica(f"f{i}"),
                              policy=OldPolicy())
        assert ctl.tick() is None               # no TypeError escape
        # mean load 0.5 sits in band; the tenant signal (one tenant
        # owns every window dispatch at load 0.5) crosses 0.4
        router.tenant_dispatches.clear()
        router._tenant_window["hot"] = 4
        ctl.policy = LoadThresholdPolicy(high=0.9, low=0.1,
                                         tenant_high=0.4)
        assert ctl.tick() == 2
        assert router.num_scale_ups == 1

    def test_scale_to_up_and_down(self):
        router = FleetRouter([FakeReplica("f0")])
        ctl = FleetController(
            router, lambda i: FakeReplica(f"f{i}", capacity=4))
        ctl.scale_to(3)
        assert sorted(h.replica_id for h in router.dispatchable()) \
            == ["f0", "f1", "f2"]
        assert router.num_scale_ups == 2
        ctl.scale_to(1)
        assert router.num_scale_downs == 2
        router.step()                           # reap drained victims
        assert len(router.replicas) == 1
        assert len(router.registry.alive()) == 1

    def test_scale_down_drains_victims_losslessly(self):
        ra = FakeReplica("ra", capacity=8)
        router = FleetRouter([ra])
        ctl = FleetController(
            router, lambda i: FakeReplica(f"auto-{i}", capacity=8))
        rids = [router.add_request([1], SamplingParams(max_new_tokens=6))
                for _ in range(3)]
        router.step()                           # all running on ra
        ctl.scale_to(2)                         # peer appears
        router.step()
        ctl.scale_to(1)                         # ra or peer retires
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert all(final[r].finish_reason == "length" for r in rids)
        assert all(len(final[r].generated) == 6 for r in rids)
        assert len(router.replicas) == 1

    def test_autoscale_tick_counters(self):
        busy = FakeReplica("ra", capacity=16)
        busy.reqs = {f"x{i}": [SamplingParams(max_new_tokens=99), []]
                     for i in range(8)}         # occupancy 8 -> load 1.0
        router = FleetRouter([busy])
        ctl = FleetController(
            router, lambda i: FakeReplica(f"auto-{i}"),
            policy=LoadThresholdPolicy(high=0.8, low=0.2,
                                       max_replicas=2))
        assert ctl.tick() == 2                  # scaled up
        assert router.num_scale_ups == 1
        busy.reqs.clear()
        assert ctl.tick() == 1                  # scaled back down
        assert router.num_scale_downs == 1
        assert router.num_autoscale_decisions == 2


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------
class TestFleetMetrics:
    def test_profiler_gauges_and_snapshot(self):
        router = FleetRouter([FakeReplica("ra")])
        router.add_request([1, 2], SamplingParams(max_new_tokens=3,
                                                  tenant_id="t"))
        _drain_router(router)
        tag = f"#{id(router)}"
        cs = {k: v for k, v in profiler.counters().items()
              if k.endswith(tag)}
        assert cs[f"fleet/dispatched{tag}"] == 1
        assert cs[f"fleet/replicas_live{tag}"] == 1
        assert cs[f"fleet/tenant_waiting{tag}"] == 0
        snap = router.snapshot()
        for key in ("fleet_dispatched", "fleet_handoffs",
                    "fleet_rejected_fleetwide", "fleet_replicas_live",
                    "fleet_replicas_dead", "fleet_tokens_emitted",
                    "fleet_tokens_per_sec", "fleet_load",
                    "fleet_finish", "fleet_tenants", "replicas"):
            assert key in snap, key
        assert snap["fleet_finish"] == {"length": 1}
        assert snap["fleet_tokens_emitted"] == 3
        assert snap["replicas"]["ra"]["alive"] is True

    def test_dropped_router_unregisters_providers(self):
        router = FleetRouter([FakeReplica("ra")])
        tag = f"#{id(router)}"
        assert any(k.endswith(tag) for k in profiler.counters())
        del router
        import gc
        gc.collect()
        assert not any(k.endswith(tag) for k in profiler.counters())


# ---------------------------------------------------------------------------
# tiny-Llama e2e: the token-identity acceptance pins
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("max_model_len", 64)
    return EngineConfig(**kw)


def _prompts(seed, vocab, lens):
    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(0, vocab, size=n))) for n in lens]


def _reference(model, prompts, sp, ids):
    """Uninterrupted single-engine run: the token-identity oracle.
    Request ids matter — the per-request sampling stream seeds from
    the id."""
    eng = LLMEngine(model, _ecfg())
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < 500
    return {rid: list(eng.get_request(rid).generated) for rid in ids}


class TestFleetE2E:
    def test_two_replica_parity_with_single_engine(self, tiny_model):
        m = tiny_model
        prompts = _prompts(11, m.config.vocab_size, [3, 5, 7, 4, 6, 2])
        sp = SamplingParams(max_new_tokens=6)
        ids = [f"p{i}" for i in range(len(prompts))]
        ref = _reference(m, prompts, sp, ids)
        router = FleetRouter([
            InProcessReplica(m, _ecfg(), replica_id=f"r{i}")
            for i in range(2)])
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        outs = _drain_router(router, max_steps=500)
        final = {o.request_id: o for o in outs if o.finished}
        assert set(final) == set(ids)
        assert {o.finish_reason for o in final.values()} == {"length"}
        for rid in ids:
            assert final[rid].generated == ref[rid], rid
        assert router.num_dispatched == 6
        assert router.num_handoffs == 0
        # both engines saw work (6 requests over 4-seat replicas)
        assert all(router._by_id(f"r{i}").engine.finish_counts
                   .get("length", 0) > 0 for i in range(2))
        snap = router.snapshot()
        assert snap["fleet_finish"] == {"length": 6}
        # per-replica engine histograms sum to the client view here
        # (no hand-offs happened, so no double counting)
        engine_lengths = sum(
            rec.get("serving_finish/length", 0)
            for rec in snap["replicas"].values())
        assert engine_lengths == 6

    @pytest.mark.parametrize("sp", [
        SamplingParams(max_new_tokens=8),
        SamplingParams(max_new_tokens=8, temperature=0.8, top_p=0.9),
    ], ids=["greedy", "sampled"])
    def test_drain_handoff_token_identical(self, tiny_model, sp):
        """THE acceptance pin: preempt one replica of two mid-run with
        zero drain grace — every request finishes 'stop'/'length' with
        generations bit-identical to an uninterrupted single engine,
        and the client never sees aborted:drain."""
        m = tiny_model
        prompts = _prompts(12, m.config.vocab_size, [3, 5, 4, 6, 2, 5])
        ids = [f"q{i}" for i in range(len(prompts))]
        ref = _reference(m, prompts, sp, ids)
        mon = PreemptionMonitor()
        router = FleetRouter([
            InProcessReplica(m, _ecfg(drain_grace_s=0.0),
                             replica_id="r0", monitor=mon),
            InProcessReplica(m, _ecfg(drain_grace_s=0.0),
                             replica_id="r1")])
        try:
            for rid, p in zip(ids, prompts):
                router.add_request(rid, p, sampling=sp)
            outs = []
            for _ in range(3):
                outs.extend(router.step())
            r0 = router._by_id("r0")
            assert r0.engine.scheduler.num_running > 0  # mid-generation
            mon.request()          # preemption notice -> r0 drains
            outs.extend(_drain_router(router, max_steps=500))
        finally:
            mon.uninstall()
        final = {o.request_id: o for o in outs if o.finished}
        assert set(final) == set(ids)
        assert all(final[r].finish_reason in ("stop", "length")
                   for r in ids)
        for rid in ids:
            assert final[rid].generated == ref[rid], rid
        assert router.num_handoffs >= 1
        # at least one hand-off was mid-generation (resume-by-recompute
        # actually exercised, not just a queued-request migration)
        assert any(router.get_request(r).handoffs > 0
                   and len(final[r].generated) == 8 for r in ids)
        assert "aborted:drain" not in router.finish_counts
        # the hand-off carried the COMPOSITE sampling-stream state —
        # numpy bit-generator AND the device RNG key the in-graph
        # sampler draws from (what makes the sampled case above
        # bit-identical at all)
        handed = [r for r in ids if router.get_request(r).handoffs > 0]
        assert handed
        for rid in handed:
            st = router.get_request(rid).rng_state
            assert st is not None and "numpy" in st, rid
            assert len(st["device_key"]) == 2, rid

    def test_single_replica_drain_keeps_pr6_semantics(self, tiny_model):
        """No peer -> the PR-6 contract is unchanged: waiting/running
        requests abort structured with partial progress kept."""
        m = tiny_model
        prompts = _prompts(13, m.config.vocab_size, [3, 4, 5, 3])
        mon = PreemptionMonitor()
        router = FleetRouter([InProcessReplica(
            m, _ecfg(drain_grace_s=0.0), replica_id="solo",
            monitor=mon)])
        try:
            rids = [router.add_request(p, sampling=SamplingParams(
                max_new_tokens=8)) for p in prompts]
            outs = []
            for _ in range(3):
                outs.extend(router.step())
            mon.request()
            outs.extend(_drain_router(router, max_steps=500))
        finally:
            mon.uninstall()
        final = {o.request_id: o for o in outs if o.finished}
        assert set(final) == set(rids)
        drained = [r for r in rids
                   if final[r].finish_reason == "aborted:drain"]
        assert drained                         # aborts SURFACED
        assert router.num_handoffs == 0
        # mid-generation victims keep their partial progress
        assert any(final[r].generated for r in drained)
        assert router.finish_counts.get("aborted:drain") == len(drained)

    def test_kill_replica_fault_recovers_with_parity(self, tiny_model):
        m = tiny_model
        prompts = _prompts(14, m.config.vocab_size, [3, 5, 4, 6, 2, 5])
        sp = SamplingParams(max_new_tokens=6)
        ids = [f"k{i}" for i in range(len(prompts))]
        ref = _reference(m, prompts, sp, ids)
        router = FleetRouter([
            InProcessReplica(m, _ecfg(), replica_id=f"r{i}")
            for i in range(2)])
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install("fleet.kill_replica:flag:r0@4*1")
        outs = _drain_router(router, max_steps=500)
        final = {o.request_id: o for o in outs if o.finished}
        assert set(final) == set(ids)
        for rid in ids:
            assert final[rid].generated == ref[rid], rid
        assert router.num_replicas_dead == 1
        assert router._by_id("r0").alive is False
        assert router.num_handoffs >= 1
        assert "aborted:error" not in router.finish_counts

    def test_scale_up_down_e2e(self, tiny_model):
        m = tiny_model
        router = FleetRouter([InProcessReplica(m, _ecfg(),
                                               replica_id="e0")])
        ctl = FleetController(
            router, lambda i: InProcessReplica(m, _ecfg(),
                                               replica_id=f"e{i}"))
        prompts = _prompts(15, m.config.vocab_size, [3, 4, 5, 4])
        rids = [router.add_request(p, sampling=SamplingParams(
            max_new_tokens=4)) for p in prompts]
        router.step()
        ctl.scale_to(2)
        outs = _drain_router(router, max_steps=500)
        ctl.scale_to(1)
        for _ in range(20):
            router.step()
            if len(router.replicas) == 1:
                break
        final = {o.request_id: o for o in outs if o.finished}
        assert all(final[r].finish_reason == "length" for r in rids)
        assert router.num_scale_ups == 1
        assert router.num_scale_downs == 1
        assert len(router.replicas) == 1
