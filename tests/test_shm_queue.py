"""Native C++ shared-memory queue (csrc/shm_queue.cpp) — the
LoDTensorBlockingQueue-role transport for DataLoader workers."""
import multiprocessing as mp
import os
import queue
import signal
import time

import numpy as np
import pytest

from paddle_tpu.io.shm_queue import ShmQueue, native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no native toolchain")


def test_roundtrip_structured():
    q = ShmQueue(4 << 20)
    rec = ("ok", 7, [np.arange(12, dtype=np.float32).reshape(3, 4),
                     {"y": np.int64(3), "name": "batch", "flag": True,
                      "none": None}])
    q.put(rec)
    kind, bid, payload = q.get()
    assert (kind, bid) == ("ok", 7)
    np.testing.assert_array_equal(payload[0],
                                  np.arange(12, dtype=np.float32).reshape(3, 4))
    assert payload[1]["y"] == 3 and payload[1]["name"] == "batch"
    assert payload[1]["flag"] is True and payload[1]["none"] is None


def test_cross_process_fifo_and_close():
    q = ShmQueue(8 << 20)

    def child(q):
        for i in range(20):
            q.put((i, np.full((64,), i, np.float32)))
        q.close()

    p = mp.get_context("fork").Process(target=child, args=(q,))
    p.start()
    seen = []
    while True:
        try:
            i, arr = q.get()
        except EOFError:
            break
        assert arr[0] == i
        seen.append(i)
    p.join()
    assert seen == list(range(20))


def test_blocking_backpressure():
    """A full ring blocks the writer until the reader drains it."""
    q = ShmQueue(256 << 10)  # small ring

    def child(q):
        for i in range(32):
            q.put((i, np.zeros(4096, np.float32)))  # 16KB each, > ring
        q.close()

    p = mp.get_context("fork").Process(target=child, args=(q,))
    p.start()
    got = 0
    while True:
        try:
            q.get()
            got += 1
        except EOFError:
            break
    p.join()
    assert got == 32


def test_timed_get_raises_empty():
    q = ShmQueue(1 << 20)
    t0 = time.time()
    with pytest.raises(queue.Empty):
        q.get(timeout=0.2)
    assert 0.1 < time.time() - t0 < 2.0


def test_record_too_large_rejected():
    q = ShmQueue(64 << 10)
    with pytest.raises(ValueError, match="capacity"):
        q.put(np.zeros(1 << 20, np.float32))


def test_dead_writer_does_not_deadlock_reader():
    """SIGKILL a writer mid-stream: the robust mutex recovers and the
    reader unblocks with EOF/short data instead of hanging forever."""
    q = ShmQueue(512 << 10)
    stop = mp.get_context("fork").Event()

    def child(q, stop):
        i = 0
        while True:
            q.put((i, np.zeros(8192, np.float32)))  # 32KB, ring fills
            i += 1

    p = mp.get_context("fork").Process(target=child, args=(q, stop))
    p.start()
    q.get()  # at least one record arrives
    os.kill(p.pid, signal.SIGKILL)
    p.join()
    # drain until EOF or timeout-based liveness kicks in; must not hang
    t0 = time.time()
    while time.time() - t0 < 30:
        try:
            q.get(timeout=0.5)
        except queue.Empty:
            q.close()  # what DataLoader's liveness loop does
        except EOFError:
            break
    else:
        pytest.fail("reader did not unblock after writer death")


def test_roundtrip_ml_dtypes_bf16():
    """np.save can't represent ml_dtypes extended floats; the transport
    ships them as tagged uint views. A bf16 batch from a custom collate
    must round-trip dtype- and bit-exact (the device-prefetch path
    relies on dtype preservation end to end)."""
    import ml_dtypes

    q = ShmQueue(1 << 20)
    arr = (np.arange(24, dtype=np.float32) / 7).astype(
        ml_dtypes.bfloat16).reshape(4, 6)
    q.put(("ok", 0, [arr, np.arange(4, dtype=np.int64)]))
    _, _, payload = q.get()
    assert payload[0].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        payload[0].view(np.uint16), arr.view(np.uint16))
