"""Auto-config tuner: search/prune/XLA-memory-analysis/record.

Reference: python/paddle/distributed/auto_tuner/{search,prune,recorder}.py
— grid over hybrid-parallel configs, invalid-point pruning, trial
records. TPU twist under test: OOM rejection happens via compile-time
``memory_analysis`` with no execution (cheaper than the reference's
launch-per-trial), then only top-K survivors are timed.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.auto_tuner import AutoTuner, Recorder, Trial, \
    TrialConfig
from paddle_tpu.models.llama import (
    LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion,
)

CFG = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=176,
                  num_hidden_layers=2, num_attention_heads=4,
                  num_key_value_heads=4, max_position_embeddings=64)


def _builder():
    paddle.seed(0)
    m = LlamaForCausalLM(CFG)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    return m, LlamaPretrainingCriterion(CFG), opt


def _batch():
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randint(0, 256, (8, 32)).astype(np.int32))
    Y = paddle.to_tensor(rng.randint(0, 256, (8, 32)).astype(np.int32))
    return [X, Y]


def test_candidates_and_prune_rules():
    tuner = AutoTuner(_builder, _batch(), num_devices=8)
    cands = tuner.candidates()
    assert all(c.dp * c.mp == 8 for c in cands)
    # batch 8 not divisible by dp -> there is no such candidate (dp in
    # divisors of 8), but sharding with dp=1 must prune
    bad = TrialConfig(dp=1, mp=8, sharding_stage=3)
    assert tuner.prune(bad) is not None
    ok = TrialConfig(dp=4, mp=2)
    assert tuner.prune(ok) is None


def test_tune_returns_valid_config_and_records():
    tuner = AutoTuner(_builder, _batch(), mp_candidates=[2, 4],
                      sharding_stages=(0,), remat_options=(False,))
    best = tuner.tune(top_k=2, steps=1)
    assert best is not None
    assert best.dp * best.mp == 8
    rows = tuner.recorder.summary()
    # recorder output pinned: every row carries config/status/peak/time
    assert all(set(r) == {"config", "status", "reason", "peak_bytes",
                          "time_per_step"} for r in rows)
    ok_rows = [r for r in rows if r["status"] == "ok"]
    assert len(ok_rows) >= 2
    assert all(r["peak_bytes"] > 0 for r in ok_rows)
    timed = [r for r in ok_rows if r["time_per_step"] is not None]
    assert len(timed) == 2  # exactly top-K were executed
    # best-first ordering
    assert rows[0]["time_per_step"] == min(t["time_per_step"]
                                           for t in timed)


def test_memory_analysis_rejects_oom_configs():
    """A tiny budget must reject configs by ANALYSIS (no execution)."""
    tuner = AutoTuner(_builder, _batch(), mp_candidates=[2],
                      sharding_stages=(0,), remat_options=(False,),
                      memory_budget_bytes=1024)  # absurdly small
    best = tuner.tune(top_k=1, steps=1)
    assert best is None
    rows = tuner.recorder.summary()
    assert any(r["status"] == "oom" for r in rows)
    oom = [r for r in rows if r["status"] == "oom"][0]
    assert "analysis peak" in oom["reason"]


def test_recorder_save(tmp_path):
    rec = Recorder()
    rec.add(Trial(TrialConfig(dp=8), status="ok", peak_bytes=10,
                  time_per_step=0.5))
    rec.add(Trial(TrialConfig(dp=4, mp=2), status="ok", peak_bytes=9,
                  time_per_step=0.2))
    p = tmp_path / "trials.json"
    rec.save(str(p))
    import json

    rows = json.loads(p.read_text())
    assert rows[0]["config"].startswith("dp4_mp2")
    assert rec.best().config.mp == 2
