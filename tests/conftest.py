"""Test config: force an 8-device virtual CPU platform so every test —
including mesh/sharding/collective tests — runs without TPU hardware
(the role of the reference's fake_cpu_device / Gloo CPU process groups,
SURVEY.md §4).

Note: the axon TPU plugin's sitecustomize pins jax_platforms='axon,cpu' via
jax.config at interpreter start, so env vars alone don't switch platforms —
we override the config and reset backends here, before any array is built.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# XLA:CPU's fast matmul path is bf16-like; tests check f32 numerics
jax.config.update("jax_default_matmul_precision", "highest")
try:
    from jax._src import xla_bridge as _xb

    if _xb.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()
except Exception:
    pass

assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu

    paddle_tpu.seed(1234)
    yield

# Persistent XLA compilation cache: the suite compiles hundreds of graphs
# (every model family x train/eval); caching them on disk makes re-runs
# dramatically faster without changing what gets exercised.
import tempfile as _tempfile  # noqa: E402

_cache_dir = os.environ.get(
    "PADDLE_TPU_TEST_CACHE",
    os.path.join(_tempfile.gettempdir(), "paddle_tpu_xla_cache"))
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except Exception:
    pass
