"""Test config: force an 8-device virtual CPU platform so every test —
including mesh/sharding/collective tests — runs without TPU hardware
(the role of the reference's fake_cpu_device / Gloo CPU process groups,
SURVEY.md §4)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# XLA:CPU's fast matmul path is bf16-like; tests check f32 numerics
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu

    paddle_tpu.seed(1234)
    yield
