"""In-graph sampling + speculative decoding (the PR's acceptance pins).

Four layers of guarantee, each pinned here:

* device sampler == host oracle: greedy rows are BIT-identical
  (one-hot argmax), sampled rows match the oracle's distribution
  statistically (total-variation bound over a few thousand draws);
* rejection sampling is EXACT: whatever the draft proposes, the
  emitted-token marginal is the target distribution — a greedy target
  therefore makes speculative decode token-identical to the
  non-speculative engine (perfect draft AND garbage draft);
* the hot path never fetches logits: ``num_logits_fetches == 0`` for
  greedy, sampled, and speculative workloads alike;
* edge cases: k=0 is the baseline engine, an all-rejected verify still
  emits the corrected token, EOS inside an accepted draft prefix stops
  exactly there, and a draft/target tokenizer-width mismatch is a
  construction-time ValueError.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import EngineConfig, LLMEngine, SamplingParams


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


@pytest.fixture(scope="module")
def garbage_draft():
    """Same shape, different weights: proposes near-uniformly wrong
    tokens, so verification rejects essentially everything."""
    paddle.seed(777)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def _naive(model, prompt, max_new):
    ids = paddle.to_tensor(np.asarray([prompt], np.int32))
    out = model.generate(ids, max_new_tokens=max_new, use_cache=False)
    return [int(t) for t in out.numpy()[0][len(prompt):]]


def _prompts(rng, vocab, lens):
    return [list(map(int, rng.integers(0, vocab, size=n))) for n in lens]


def _run(eng, max_steps=500):
    steps = 0
    while eng.has_unfinished():
        eng.step()
        steps += 1
        assert steps < max_steps, "engine failed to converge"
    return steps


# -- configuration surface ------------------------------------------------

def test_spec_knobs_are_both_or_neither(tiny_model):
    with pytest.raises(ValueError, match="BOTH"):
        EngineConfig(draft_model=tiny_model)
    with pytest.raises(ValueError, match="BOTH"):
        EngineConfig(num_spec_tokens=2)
    with pytest.raises(ValueError, match=">= 0"):
        EngineConfig(num_spec_tokens=-1)


def test_draft_target_tokenizer_width_mismatch_raises(tiny_model):
    paddle.seed(5)
    narrow = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=128))
    narrow.eval()
    with pytest.raises(ValueError, match="tokenizer-width mismatch"):
        LLMEngine(tiny_model, EngineConfig(
            draft_model=narrow, num_spec_tokens=2))


def test_k0_is_the_baseline_engine(tiny_model):
    """num_spec_tokens=0 (the default) builds NO speculative state: no
    proposer, counters stay zero, the step is the plain ragged step."""
    eng = LLMEngine(tiny_model, EngineConfig(block_size=4))
    assert eng._spec is None and eng._spec_R == 1
    eng.add_request([5, 9, 2], sampling=SamplingParams(max_new_tokens=4))
    _run(eng)
    assert eng.num_spec_proposed == 0 and eng.num_spec_accepted == 0
    assert eng.spec_acceptance_rate == 0.0


# -- greedy token identity ------------------------------------------------

def test_spec_greedy_token_identical_perfect_draft(tiny_model):
    """Draft == target: every proposal verifies, so the engine emits
    k+1 tokens per verify step — fewer steps, identical tokens."""
    m = tiny_model
    rng = np.random.default_rng(3)
    prompts = _prompts(rng, m.config.vocab_size, [4, 7, 3, 9])
    max_new = 8
    sp = SamplingParams(max_new_tokens=max_new)

    base = LLMEngine(m, EngineConfig(block_size=4))
    for p in prompts:
        base.add_request(p, sampling=sp)
    base_steps = _run(base)

    eng = LLMEngine(m, EngineConfig(block_size=4, draft_model=m,
                                    num_spec_tokens=3))
    rids = [eng.add_request(p, sampling=sp) for p in prompts]
    spec_steps = _run(eng)

    for rid, p in zip(rids, prompts):
        req = eng.get_request(rid)
        assert req.is_finished and req.generated == _naive(m, p, max_new)
    # a perfect draft verifies (nearly) everything; the whole point is
    # fewer target dispatches for the same tokens
    assert eng.num_spec_proposed > 0
    assert eng.spec_acceptance_rate > 0.9
    assert spec_steps < base_steps
    assert eng.num_logits_fetches == 0


def test_spec_greedy_token_identical_garbage_draft(tiny_model,
                                                   garbage_draft):
    """A bad draft costs acceptance rate, NEVER correctness: rejected
    proposals are replaced by the target's own (greedy) choice, so the
    output stays token-identical to the baseline — the all-rejected
    step degrades to one token per iteration."""
    m = tiny_model
    rng = np.random.default_rng(4)
    prompts = _prompts(rng, m.config.vocab_size, [5, 8, 3])
    max_new = 6
    sp = SamplingParams(max_new_tokens=max_new)
    eng = LLMEngine(m, EngineConfig(block_size=4,
                                    draft_model=garbage_draft,
                                    num_spec_tokens=2))
    rids = [eng.add_request(p, sampling=sp) for p in prompts]
    _run(eng)
    for rid, p in zip(rids, prompts):
        req = eng.get_request(rid)
        assert req.is_finished and req.generated == _naive(m, p, max_new)
    assert eng.num_spec_proposed > 0
    assert eng.num_logits_fetches == 0
    # KV rollback after rejections left the allocator consistent
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
    eng.block_manager.check_invariants()


def test_eos_inside_accepted_draft_prefix(tiny_model):
    """EOS emitted mid-draft must truncate the step's emission exactly
    there (tokens after it in the accepted prefix are discarded)."""
    m = tiny_model
    prompt = _prompts(np.random.default_rng(6), m.config.vocab_size,
                      [6])[0]
    baseline = _naive(m, prompt, 8)
    # pick a mid-run token that FIRST occurs at its position (so the
    # engine can't legitimately stop on an earlier occurrence)
    stop_at = next(i for i in range(2, 7)
                   if baseline[i] not in baseline[:i])
    sp = SamplingParams(max_new_tokens=8, eos_token_id=baseline[stop_at])
    eng = LLMEngine(m, EngineConfig(block_size=4, draft_model=m,
                                    num_spec_tokens=3))
    rid = eng.add_request(prompt, sampling=sp)
    _run(eng)
    req = eng.get_request(rid)
    assert req.finish_reason == "stop"
    # EOS included, nothing after
    assert req.generated == baseline[:stop_at + 1]
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks


# -- rejection-sampling kernel (unit level) -------------------------------

def test_all_rejected_verify_emits_corrected_token(tiny_model):
    """Greedy target, every draft token wrong: slot emits EXACTLY one
    token — the target's own argmax at the first verify row."""
    import jax.numpy as jnp

    from paddle_tpu.ops.sampling import sample_or_verify

    rng = np.random.default_rng(0)
    s, r, v = 4, 3, 32
    logits = rng.normal(size=(s, r, v)).astype(np.float32)
    am = np.argmax(logits, axis=-1)          # (s, r)
    draft = ((am[:, :r - 1] + 1) % v).astype(np.int32)  # always wrong
    keys = rng.integers(0, 2**32, size=(s, 2), dtype=np.uint32)
    toks, n_emit, nkeys = sample_or_verify(
        jnp.asarray(logits), jnp.asarray(draft),
        jnp.full((s,), r - 1, jnp.int32), jnp.asarray(keys),
        jnp.zeros((s,)), jnp.zeros((s,), jnp.int32), jnp.ones((s,)))
    toks, n_emit = np.asarray(toks), np.asarray(n_emit)
    assert (n_emit == 1).all()
    np.testing.assert_array_equal(toks[:, 0], am[:, 0])
    assert not np.array_equal(np.asarray(nkeys), keys)  # streams moved


def test_fully_accepted_verify_emits_prefix_plus_bonus():
    """Greedy target, draft == argmax everywhere: all k accepted plus
    the bonus token from the last row (n_emit == R)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.sampling import sample_or_verify

    rng = np.random.default_rng(1)
    s, r, v = 3, 4, 16
    logits = rng.normal(size=(s, r, v)).astype(np.float32)
    am = np.argmax(logits, axis=-1)
    keys = rng.integers(0, 2**32, size=(s, 2), dtype=np.uint32)
    toks, n_emit, _ = sample_or_verify(
        jnp.asarray(logits), jnp.asarray(am[:, :r - 1].astype(np.int32)),
        jnp.full((s,), r - 1, jnp.int32), jnp.asarray(keys),
        jnp.zeros((s,)), jnp.zeros((s,), jnp.int32), jnp.ones((s,)))
    assert (np.asarray(n_emit) == r).all()
    np.testing.assert_array_equal(np.asarray(toks), am)


# -- distributional parity vs the host oracle -----------------------------

def _oracle_probs(logits, temperature, top_k, top_p):
    """The LLMEngine._sample transform, probabilities only (f64)."""
    x = logits.astype(np.float64) / temperature
    x -= x.max()
    p = np.exp(x)
    p /= p.sum()
    if top_k > 0 and top_k < p.size:
        kth = np.partition(p, -top_k)[-top_k]
        p = np.where(p >= kth, p, 0.0)
        p /= p.sum()
    if top_p < 1.0:
        order = np.argsort(-p)
        csum = np.cumsum(p[order])
        keep_n = int(np.searchsorted(csum, top_p) + 1)
        mask = np.zeros_like(p)
        mask[order[:keep_n]] = p[order[:keep_n]]
        p = mask / mask.sum()
    return p


def _tv(counts, p_ref):
    emp = counts / counts.sum()
    return 0.5 * np.abs(emp - p_ref).sum()


def test_filtered_probs_matches_oracle_transform():
    import jax.numpy as jnp

    from paddle_tpu.ops.sampling import filtered_probs

    rng = np.random.default_rng(2)
    v = 64
    logits = (rng.normal(size=(3, v)) * 3).astype(np.float32)
    cases = [(0.7, 0, 1.0), (1.3, 10, 1.0), (0.9, 0, 0.8)]
    temps = np.asarray([c[0] for c in cases], np.float32)
    ks = np.asarray([c[1] for c in cases], np.int32)
    ps = np.asarray([c[2] for c in cases], np.float32)
    dev = np.asarray(filtered_probs(jnp.asarray(logits), jnp.asarray(temps),
                                    jnp.asarray(ks), jnp.asarray(ps)))
    for i, (t, k, tp) in enumerate(cases):
        ref = _oracle_probs(logits[i], t, k, tp)
        np.testing.assert_allclose(dev[i], ref, atol=2e-4)


def test_greedy_rows_are_exact_onehot_argmax():
    """Greedy bit-identity: temperature<=0 rows are a {0,1} one-hot at
    np.argmax — not merely argmax-equal after float fuzz."""
    import jax.numpy as jnp

    from paddle_tpu.ops.sampling import filtered_probs

    rng = np.random.default_rng(3)
    logits = rng.normal(size=(5, 40)).astype(np.float32)
    logits[2, 7] = logits[2, 31]  # a tie: first occurrence must win
    dev = np.asarray(filtered_probs(
        jnp.asarray(logits), jnp.zeros((5,), jnp.float32),
        jnp.zeros((5,), jnp.int32), jnp.ones((5,), jnp.float32)))
    assert set(np.unique(dev)) <= {0.0, 1.0}
    np.testing.assert_array_equal(np.argmax(dev, -1), np.argmax(logits, -1))


def test_device_draws_match_oracle_distribution():
    """Total variation between N device categorical draws and the host
    oracle's exact distribution stays under the statistical bound."""
    import jax.numpy as jnp

    from paddle_tpu.ops.sampling import sample_tokens

    rng = np.random.default_rng(4)
    v, n = 48, 4096
    row = (rng.normal(size=(v,)) * 2).astype(np.float32)
    t, tp = 0.8, 0.9
    p_ref = _oracle_probs(row, t, 0, tp)
    keys = rng.integers(0, 2**32, size=(n, 2), dtype=np.uint32)
    toks, nkeys = sample_tokens(
        jnp.broadcast_to(jnp.asarray(row), (n, v)), jnp.asarray(keys),
        jnp.full((n,), t, jnp.float32), jnp.zeros((n,), jnp.int32),
        jnp.full((n,), tp, jnp.float32))
    counts = np.bincount(np.asarray(toks), minlength=v)
    assert _tv(counts, p_ref) < 0.05
    # truncated support respected exactly, not just statistically
    assert counts[p_ref == 0.0].sum() == 0
    assert not np.array_equal(np.asarray(nkeys), keys)


def test_verify_emission_marginal_is_target_distribution():
    """The rejection-sampling guarantee, empirically: with a fixed
    point-mass proposal, the FIRST emitted token's marginal equals the
    target distribution, and the acceptance fraction equals p(t0)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.sampling import sample_or_verify

    rng = np.random.default_rng(5)
    v, n = 32, 4096
    logits = (rng.normal(size=(2, v)) * 2).astype(np.float32)  # (R=2, V)
    t = 0.9
    p_ref = _oracle_probs(logits[0], t, 0, 1.0)
    t0 = int(np.argsort(p_ref)[-3])  # a mid-mass proposal
    keys = rng.integers(0, 2**32, size=(n, 2), dtype=np.uint32)
    toks, n_emit, _ = sample_or_verify(
        jnp.broadcast_to(jnp.asarray(logits), (n, 2, v)),
        jnp.full((n, 1), t0, jnp.int32), jnp.ones((n,), jnp.int32),
        jnp.asarray(keys), jnp.full((n,), t, jnp.float32),
        jnp.zeros((n,), jnp.int32), jnp.ones((n,), jnp.float32))
    toks, n_emit = np.asarray(toks), np.asarray(n_emit)
    counts = np.bincount(toks[:, 0], minlength=v)
    assert _tv(counts, p_ref) < 0.05
    accept_frac = float((n_emit == 2).mean())
    assert abs(accept_frac - p_ref[t0]) < 0.05


# -- sampled speculative engine runs --------------------------------------

def test_spec_sampled_reproducible_and_fetchless(tiny_model,
                                                 garbage_draft):
    """Seeded sampled requests through the speculative engine are
    reproducible across engines (per-request device RNG streams), and
    the whole run fetches zero logits."""
    m = tiny_model
    prompts = _prompts(np.random.default_rng(8), m.config.vocab_size,
                       [5, 7, 4])
    sp = [SamplingParams(max_new_tokens=6, temperature=0.8, top_p=0.9,
                         seed=100 + i) for i in range(len(prompts))]

    def run_once():
        eng = LLMEngine(m, EngineConfig(block_size=4, draft_model=m,
                                        num_spec_tokens=2))
        rids = [eng.add_request(p, sampling=s)
                for p, s in zip(prompts, sp)]
        _run(eng)
        return eng, [eng.get_request(r).generated for r in rids]

    eng1, out1 = run_once()
    eng2, out2 = run_once()
    assert out1 == out2
    assert eng1.num_logits_fetches == 0 and eng2.num_logits_fetches == 0
    assert eng1.num_sampled_steps > 0
    assert eng1.num_spec_proposed > 0
    assert 0.0 <= eng1.spec_acceptance_rate <= 1.0
