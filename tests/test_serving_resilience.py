"""Serving resilience layer e2e on XLA:CPU (ISSUE 6 acceptance pins).

Fast, tier-1, all failure modes injected deterministically through
``PADDLE_FAULTS``-style installs:

* SIGTERM during an 8-request mixed prefill/decode run drains
  gracefully — running requests complete with CORRECT tokens, waiting
  requests return ``aborted:drain``, the loop exits clean;
* swap-based preemption (``swap_mode='host'``) is token-identical to
  recompute preemption under both genuine and forced OOM;
* per-request deadlines expire wherever the request is; admission
  control rejects as a first-class output;
* a NaN-poisoned request aborts ALONE while its batch peers finish
  with parity; transient step failures retry; exhausted retries and
  hung steps fail the engine WITH structured outputs (drain
  semantics, no request just vanishes).

The slow subprocess/launcher versions live in test_fault_e2e.py; the
model-free allocator/scheduler invariants in test_serving.py.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.watchdog import PreemptionMonitor
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    EngineConfig, EngineStepError, LLMEngine, SamplingParams,
    StepHungError,
)
from paddle_tpu.testing import faults


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny()          # 4 heads / 2 KV heads: GQA path
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _no_fault_leak():
    yield
    faults.clear()


def _naive(model, prompt, max_new):
    ids = paddle.to_tensor(np.asarray([prompt], np.int32))
    out = model.generate(ids, max_new_tokens=max_new, use_cache=False)
    return [int(t) for t in out.numpy()[0][len(prompt):]]


def _prompts(rng, vocab, lens):
    return [list(map(int, rng.integers(0, vocab, size=n))) for n in lens]


def _serve(eng, collect=None, max_steps=500):
    outs = []
    steps = 0
    while eng.has_unfinished():
        outs.extend(eng.step())
        eng.block_manager.check_invariants()
        steps += 1
        assert steps < max_steps, "engine failed to converge"
        if collect is not None:
            collect(eng, steps)
    return outs


# ---------------------------------------------------------------------------
# graceful drain (SIGTERM mid-run) — the tier-1 acceptance pin
# ---------------------------------------------------------------------------
def test_sigterm_mid_run_drains_gracefully(tiny_model):
    """8 requests, 4 running + 4 waiting, SIGTERM injected mid-decode:
    the running half completes with naive-parity tokens, the waiting
    half returns structured ``aborted:drain`` outputs, every KV block
    returns to the free list, and the loop exits on its own."""
    m = tiny_model
    rng = np.random.default_rng(10)
    prompts = _prompts(rng, m.config.vocab_size,
                       [3, 5, 7, 4, 6, 2, 5, 3])
    max_new = 6
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=4,
                                    max_model_len=64))
    monitor = PreemptionMonitor()
    eng.install_preemption_handler(monitor)
    try:
        # a REAL SIGTERM, delivered by the fault point mid-run (after
        # the prefill and two decode steps — mixed-phase, batch hot)
        faults.install("serving.step:sigterm@2*1")
        sp = SamplingParams(max_new_tokens=max_new)
        rids = [eng.add_request(p, sampling=sp) for p in prompts]
        outs = _serve(eng)
    finally:
        monitor.uninstall()

    final = {o.request_id: o for o in outs if o.finished}
    assert set(final) == set(rids)            # nobody vanished
    drained = [r for r in rids
               if final[r].finish_reason == "aborted:drain"]
    completed = [r for r in rids if final[r].finish_reason == "length"]
    assert sorted(drained + completed) == sorted(rids)
    # only 4 sequences fit the engine; the rest had not started and
    # must be the drained ones, with zero tokens
    assert len(completed) == 4 and len(drained) == 4
    assert all(final[r].token is None and final[r].generated == []
               for r in drained)
    # the running half produced CORRECT tokens, not just any tokens
    for rid, p in zip(rids, prompts):
        if rid in completed:
            assert eng.get_request(rid).generated == \
                _naive(m, p, max_new), rid
    assert eng.drained and eng.is_draining   # drain latched + finished
    assert eng.num_drains_started == 1
    assert eng.num_drain_aborted == 4
    assert eng.num_drains_completed == 1
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
    # a draining engine admits nothing: structured rejection, not error
    late = eng.add_request(prompts[0], sampling=sp)
    assert eng.get_request(late).finish_reason == "rejected"
    assert eng.num_rejected == 1
    pend = eng.step()                        # pending flushed exactly once
    assert [o.finish_reason for o in pend] == ["rejected"]
    assert eng.step() == []


def test_drain_api_grace_budget_aborts_stragglers(tiny_model):
    """A zero-grace drain can't wait for the running batch: everything
    still in flight aborts with ``aborted:drain`` — with its partial
    progress in the output — and the engine reports drained."""
    m = tiny_model
    rng = np.random.default_rng(11)
    prompts = _prompts(rng, m.config.vocab_size, [4, 6])
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=64))
    sp = SamplingParams(max_new_tokens=8)
    rids = [eng.add_request(p, sampling=sp) for p in prompts]
    for _ in range(3):            # prefill + 2 decodes
        eng.step()
    outs = eng.drain(grace_s=0.0)
    final = {o.request_id: o for o in outs if o.finished}
    assert set(final) == set(rids)
    for rid in rids:
        assert final[rid].finish_reason == "aborted:drain"
        # progress preserved: prefill + 2 decode tokens
        assert len(final[rid].generated) == 3
        assert eng.get_request(rid).is_finished
    assert eng.drained
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks


# ---------------------------------------------------------------------------
# deadlines + admission control
# ---------------------------------------------------------------------------
def test_deadline_expires_waiting_and_running(tiny_model):
    """TTL enforcement at iteration boundaries: a queued request whose
    deadline passed expires before ever running; a RUNNING request
    expires mid-decode keeping its partial progress; an undeadlined
    peer in the same batch is untouched and exact."""
    m = tiny_model
    rng = np.random.default_rng(12)
    p_run, p_wait, p_free = _prompts(rng, m.config.vocab_size, [5, 4, 6])
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=4,
                                    max_model_len=64))
    max_new = 8
    # expires mid-run: long enough for prefill + a few decode steps
    r_run = eng.add_request(p_run, sampling=SamplingParams(
        max_new_tokens=max_new, deadline_ms=250))
    # expires before it ever runs
    r_wait = eng.add_request(p_wait, sampling=SamplingParams(
        max_new_tokens=max_new, deadline_ms=20))
    r_free = eng.add_request(p_free, sampling=SamplingParams(
        max_new_tokens=max_new))
    time.sleep(0.03)              # r_wait's TTL passes pre-first-step

    def stall(eng_, steps):
        if steps == 3:
            time.sleep(0.3)       # r_run's TTL passes mid-decode

    outs = _serve(eng, collect=stall)
    final = {o.request_id: o for o in outs if o.finished}
    assert final[r_wait].finish_reason == "expired"
    assert final[r_wait].generated == []
    assert final[r_run].finish_reason == "expired"
    assert 0 < len(final[r_run].generated) < max_new  # partial progress
    assert final[r_free].finish_reason == "length"
    assert eng.get_request(r_free).generated == \
        _naive(m, p_free, max_new)
    assert eng.num_expired == 2
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks


def test_admission_rejects_on_queue_depth(tiny_model):
    """Backpressure: past ``max_queue_depth`` waiting requests, new
    arrivals get first-class 'rejected' outputs (callback included) and
    never touch the scheduler; admitted ones are unaffected."""
    m = tiny_model
    rng = np.random.default_rng(13)
    prompts = _prompts(rng, m.config.vocab_size, [4, 5, 3, 6, 4])
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=1,
                                    max_model_len=64, max_queue_depth=2))
    events = []
    sp = SamplingParams(max_new_tokens=4)
    rids = [eng.add_request(
        p, sampling=sp,
        callback=lambda r, tok, done: events.append((r, tok, done)))
        for p in prompts]
    # depth check at add time (no step ran between adds, so nothing
    # left the queue): r0 queues at depth 0, r1 at depth 1, r2/r3/r4
    # each see depth 2 >= max_queue_depth -> rejected
    rejected = [r for r in rids
                if eng.get_request(r).finish_reason == "rejected"]
    assert rejected == rids[2:]
    assert eng.num_rejected == 3
    assert [e for e in events if e[1] is None] == \
        [(r, None, True) for r in rejected]   # terminal callbacks fired
    outs = _serve(eng)
    final = {o.request_id: o for o in outs if o.finished}
    assert set(final) == set(rids)            # rejections flushed too
    for rid, p in zip(rids[:2], prompts[:2]):
        assert final[rid].finish_reason == "length"
        assert eng.get_request(rid).generated == _naive(m, p, 4)
    # rejected requests are FINISHED and releasable like any other
    assert eng.release_request(rids[4]).finish_reason == "rejected"


def test_admission_rejects_on_ttft_slo(tiny_model):
    """SLO-aware admission: once step-time history exists, an arrival
    whose estimated TTFT exceeds the SLO is rejected; a cold engine
    abstains (no history -> no guess-based rejects)."""
    m = tiny_model
    rng = np.random.default_rng(14)
    p = _prompts(rng, m.config.vocab_size, [4])[0]
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=64,
                                    ttft_slo_ms=1e-3))
    sp = SamplingParams(max_new_tokens=3)
    # cold engine: the estimator abstains, the request is admitted
    first = eng.add_request(p, sampling=sp)
    assert eng.get_request(first).finish_reason is None
    _serve(eng)
    assert eng.get_request(first).finish_reason == "length"
    # warm engine: any real step time exceeds a 1 microsecond SLO
    second = eng.add_request(p, sampling=sp)
    assert eng.get_request(second).finish_reason == "rejected"
    verdict = eng.admission.verdict(eng)
    assert verdict is not None and "SLO" in verdict


# ---------------------------------------------------------------------------
# swap-based preemption
# ---------------------------------------------------------------------------
def test_swap_preemption_token_parity_with_recompute(tiny_model):
    """The acceptance pin: under genuine forced OOM (cache too small
    for the batch), swap_mode='host' must preempt via host spill and
    produce TOKEN-IDENTICAL outputs to the recompute path — which is
    itself pinned against the naive generate."""
    m = tiny_model
    rng = np.random.default_rng(15)
    prompts = _prompts(rng, m.config.vocab_size, [6, 8, 5, 7])
    max_new = 8
    sp = SamplingParams(max_new_tokens=max_new)

    def run(mode):
        eng = LLMEngine(m, EngineConfig(
            block_size=4, num_blocks=10, max_num_seqs=4,
            max_model_len=32, swap_mode=mode))
        rids = [eng.add_request(p, sampling=sp) for p in prompts]
        _serve(eng)
        return eng, [eng.get_request(r).generated for r in rids]

    eng_r, toks_r = run("recompute")
    eng_h, toks_h = run("host")
    assert eng_r.scheduler.num_preemptions > 0, "config must force OOM"
    assert eng_h.scheduler.num_swap_outs > 0
    assert eng_h.scheduler.num_swap_ins == eng_h.scheduler.num_swap_outs
    assert toks_h == toks_r
    assert toks_r == [_naive(m, p, max_new) for p in prompts]
    for eng in (eng_r, eng_h):
        assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
    assert eng_h.block_manager.num_free_host_blocks == \
        eng_h.cfg.num_host_blocks
    snap = eng_h.metrics.snapshot()
    assert snap["serving_swapped_out"] == eng_h.scheduler.num_swap_outs
    assert snap["serving_swapped_in"] == eng_h.scheduler.num_swap_ins


def test_forced_oom_injection_targets_a_request(tiny_model):
    """The ``serving.force_oom`` flag fault makes a ROOMY cache OOM on
    a chosen victim's slot growth: deterministic swap-preemption
    coverage without tuning cache sizes; parity still holds."""
    m = tiny_model
    rng = np.random.default_rng(16)
    prompts = _prompts(rng, m.config.vocab_size, [5, 4, 6])
    max_new = 6
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=4,
                                    max_model_len=64, swap_mode="host"))
    sp = SamplingParams(max_new_tokens=max_new)
    rids = [eng.add_request(p, sampling=sp) for p in prompts]
    # victim = the SECOND request, on its first two block growths
    faults.install(f"serving.force_oom.{rids[1]}:flag*2")
    outs = _serve(eng)
    faults.clear()
    assert eng.scheduler.num_preemptions > 0
    victim = eng.get_request(rids[1])
    assert victim.num_swaps > 0 or victim.num_preemptions > 0
    final = {o.request_id: o for o in outs if o.finished}
    for rid, p in zip(rids, prompts):
        assert final[rid].finish_reason == "length"
        assert eng.get_request(rid).generated == _naive(m, p, max_new)
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
    assert eng.block_manager.num_free_host_blocks == \
        eng.cfg.num_host_blocks


# ---------------------------------------------------------------------------
# step-level fault isolation
# ---------------------------------------------------------------------------
def test_nan_poisoned_request_aborts_alone(tiny_model):
    """The acceptance pin: a NaN-poisoned row aborts with
    'aborted:nonfinite' and its KV blocks free, while the REST of the
    batch completes with exact naive parity."""
    m = tiny_model
    rng = np.random.default_rng(17)
    prompts = _prompts(rng, m.config.vocab_size, [5, 4, 6])
    max_new = 6
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=4,
                                    max_model_len=64))
    sp = SamplingParams(max_new_tokens=max_new)
    rids = [eng.add_request(p, sampling=sp) for p in prompts]
    # poison row 1 (the middle request) on the second decode step
    faults.install("serving.nan_logits:flag:1@2*1")
    outs = _serve(eng)
    faults.clear()
    final = {o.request_id: o for o in outs if o.finished}
    assert final[rids[1]].finish_reason == "aborted:nonfinite"
    assert 0 < len(final[rids[1]].generated) < max_new
    assert eng.num_poisoned_aborts == 1
    for rid, p in zip(rids, prompts):
        if rid != rids[1]:
            assert final[rid].finish_reason == "length"
            assert eng.get_request(rid).generated == \
                _naive(m, p, max_new), rid
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks


def test_nan_guard_covers_sampled_decode_path(tiny_model):
    """The guard must also cover sampled (temperature>0) rows — which
    now ride the same in-graph path as greedy, with NO logits fetch:
    poisoned row aborts, sampled peer finishes."""
    m = tiny_model
    rng = np.random.default_rng(18)
    pg, ps = _prompts(rng, m.config.vocab_size, [5, 5])
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=64))
    rg = eng.add_request(pg, sampling=SamplingParams(max_new_tokens=4))
    rs = eng.add_request(ps, sampling=SamplingParams(
        max_new_tokens=4, temperature=0.8, seed=7))
    faults.install("serving.nan_logits:flag:0@1*1")
    outs = _serve(eng)
    faults.clear()
    final = {o.request_id: o for o in outs if o.finished}
    assert final[rg].finish_reason == "aborted:nonfinite"
    assert final[rs].finish_reason == "length"
    assert len(final[rs].generated) == 4
    assert eng.num_poisoned_aborts == 1
    assert eng.num_logits_fetches == 0    # sampled rows stay in-graph


def test_transient_step_failure_retries_and_recovers(tiny_model):
    """Two injected step failures, three retries budgeted: the run
    completes with exact tokens and reports step_retries == 2."""
    m = tiny_model
    rng = np.random.default_rng(19)
    p = _prompts(rng, m.config.vocab_size, [5])[0]
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=64, max_step_retries=3,
                                    step_retry_backoff_s=0.01))
    faults.install("serving.step:raise*2")
    out = eng.generate([p], SamplingParams(max_new_tokens=6))
    faults.clear()
    assert eng.num_step_retries == 2
    assert out[0] == _naive(m, p, 6)
    assert eng.metrics.snapshot()["serving_step_retries"] == 2


def test_exhausted_retries_abort_with_structured_outputs(tiny_model):
    """Past the retry budget the engine fails CLOSED: EngineStepError
    carries one 'aborted:error' output per live request (running AND
    waiting), the scheduler is empty, every block reclaimed."""
    m = tiny_model
    rng = np.random.default_rng(20)
    prompts = _prompts(rng, m.config.vocab_size, [5, 4, 6, 5])
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=64, max_step_retries=1,
                                    step_retry_backoff_s=0.01,
                                    max_queue_depth=3))
    sp = SamplingParams(max_new_tokens=4)
    rids = [eng.add_request(p, sampling=sp) for p in prompts]
    # the 4th add is REJECTED (depth 3 >= 3): its pending output must
    # ride the exception too, not vanish with the failed step
    assert eng.get_request(rids[3]).finish_reason == "rejected"
    faults.install("serving.step:raise")
    with pytest.raises(EngineStepError, match="retry budget") as ei:
        eng.step()
    faults.clear()
    assert sorted(o.request_id for o in ei.value.outputs) == sorted(rids)
    reasons = {o.request_id: o.finish_reason for o in ei.value.outputs}
    assert reasons.pop(rids[3]) == "rejected"
    assert set(reasons.values()) == {"aborted:error"}
    # nothing vanished: 3 structured aborts, engine empty, blocks back
    assert not eng.has_unfinished()
    assert eng.num_step_retries == 1
    assert all(eng.get_request(r).finish_reason == "aborted:error"
               for r in rids[:3])
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks
    eng.block_manager.check_invariants()
    # fail-closed: a fatally-failed engine admits nothing more (with
    # donated caches the next dispatch would read invalidated buffers)
    post = eng.add_request(prompts[0], sampling=sp)
    assert eng.get_request(post).finish_reason == "rejected"
    pend = eng.step()                 # flushes the rejection, no dispatch
    assert [o.finish_reason for o in pend] == ["rejected"]
    assert eng.step() == []


def test_hung_step_watchdog_fails_engine_with_drain_semantics(tiny_model):
    """A step that blows through the watchdog deadline (injected slow
    dispatch on a WARM shape) surfaces as StepHungError once it
    completes, with every request aborted as structured output."""
    m = tiny_model
    rng = np.random.default_rng(21)
    p = _prompts(rng, m.config.vocab_size, [5])[0]
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=64,
                                    step_timeout_s=0.1))
    rid = eng.add_request(p, sampling=SamplingParams(max_new_tokens=6))
    # skip prefill and the first decode (both COLD shapes, which get
    # the compile allowance); the third step is warm with a 0.1s
    # deadline and sleeps 0.5s
    faults.install("serving.step:sleep:0.5@2*1")
    with pytest.raises(StepHungError, match="watchdog deadline") as ei:
        _serve(eng)
    faults.clear()
    assert [o.finish_reason for o in ei.value.outputs] == \
        ["aborted:error"]
    assert eng.get_request(rid).is_finished
    assert not eng.has_unfinished()
    assert eng.block_manager.num_free_blocks == eng.cfg.num_blocks


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------
def test_resilience_counters_via_profiler(tiny_model):
    """The new serving/* gauges ride the PR-3 counter-provider
    machinery like every other serving metric."""
    from paddle_tpu import profiler

    m = tiny_model
    rng = np.random.default_rng(22)
    p = _prompts(rng, m.config.vocab_size, [4])[0]
    eng = LLMEngine(m, EngineConfig(block_size=4, max_num_seqs=2,
                                    max_model_len=64, swap_mode="host",
                                    max_queue_depth=0))
    # max_queue_depth=0 rejects EVERYTHING: cheap counter traffic
    rid = eng.add_request(p, sampling=SamplingParams(max_new_tokens=2))
    assert eng.get_request(rid).finish_reason == "rejected"
    c = profiler.counters()
    for gauge, want in (("rejected", 1), ("swapped_out", 0),
                        ("swapped_in", 0), ("expired", 0),
                        ("poisoned_aborts", 0), ("step_retries", 0),
                        ("drain_started", 0), ("drain_completed", 0)):
        assert c[f"serving/{gauge}#{id(eng)}"] == want, gauge
    snap = eng.metrics.snapshot()
    assert snap["serving_rejected"] == 1
    assert snap["kv_host_blocks_total"] == eng.cfg.num_host_blocks
