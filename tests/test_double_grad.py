"""Second- and higher-order eager autograd (create_graph=True).

Reference contract: python/paddle/base/dygraph/base.py:600-630 and
test/legacy_test/test_paddle_imperative_double_grad.py — paddle.grad with
create_graph=True returns gradients that carry tape nodes and can be
differentiated again.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_second_derivative_cubic():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-6)
    assert not g.stop_gradient, "create_graph grad must carry the tape"
    (g2,) = paddle.grad(g, [x])
    np.testing.assert_allclose(g2.numpy(), [12.0], rtol=1e-6)


def test_third_derivative():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x ** 4
    (g1,) = paddle.grad(y, [x], create_graph=True)
    (g2,) = paddle.grad(g1, [x], create_graph=True)
    (g3,) = paddle.grad(g2, [x])
    np.testing.assert_allclose(g1.numpy(), [4 * 27.0], rtol=1e-6)
    np.testing.assert_allclose(g2.numpy(), [12 * 9.0], rtol=1e-6)
    np.testing.assert_allclose(g3.numpy(), [24 * 3.0], rtol=1e-6)


def test_grad_does_not_pollute_other_leaves():
    """paddle.grad accumulates ONLY into the requested inputs (the
    GeneralGrad role) — other leaves' .grad stay untouched."""
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    w = paddle.to_tensor([3.0, 4.0], stop_gradient=False)
    y = (x * w).sum()
    (gx,) = paddle.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), [3.0, 4.0])
    assert w.grad is None
    assert x.grad is None  # paddle.grad leaves .grad untouched too


def test_grad_wrt_interior_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    h = x * 3.0
    y = (h * h).sum()
    (gh,) = paddle.grad(y, [h])
    np.testing.assert_allclose(gh.numpy(), [6.0, 12.0])


def test_gradient_penalty_matches_pure_jax():
    """WGAN-GP-style training step: grads of a gradient-norm penalty wrt
    weights must match a pure-JAX double-grad reference."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    xw = rng.standard_normal((4, 3)).astype("float32")
    ww = rng.standard_normal((3, 1)).astype("float32")

    x = paddle.to_tensor(xw, stop_gradient=False)
    w = paddle.to_tensor(ww, stop_gradient=False)
    out = paddle.matmul(paddle.nn.functional.relu(paddle.matmul(x, w)),
                        paddle.ones([1, 1]))
    s = out.sum()
    (gx,) = paddle.grad(s, [x], create_graph=True)
    penalty = ((gx * gx).sum(axis=1).sqrt() - 1.0).pow(2).mean()
    penalty.backward()
    got = w.grad.numpy()

    def f(xv, wv):
        return jnp.sum(jnp.maximum(xv @ wv, 0) @ jnp.ones((1, 1)))

    def pen(wv):
        g = jax.grad(f, argnums=0)(jnp.asarray(xw), wv)
        return jnp.mean((jnp.sqrt(jnp.sum(g * g, axis=1)) - 1.0) ** 2)

    want = np.asarray(jax.grad(pen)(jnp.asarray(ww)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_double_grad_multi_input_op():
    """d/dx of (x*y) wrt y then wrt x — cross second derivatives."""
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = paddle.to_tensor([5.0], stop_gradient=False)
    z = (x * x * y).sum()
    (gx,) = paddle.grad(z, [x], create_graph=True)  # 2xy = 20
    np.testing.assert_allclose(gx.numpy(), [20.0])
    (gxy,) = paddle.grad(gx, [y])  # d(2xy)/dy = 2x = 4
    np.testing.assert_allclose(gxy.numpy(), [4.0])


def test_double_grad_composes_with_jit():
    @paddle.jit.to_static
    def step(xv):
        xv.stop_gradient = False
        y = (xv ** 3).sum()
        (g,) = paddle.grad(y, [xv], create_graph=True)
        return (g * g).sum()

    r = step(paddle.to_tensor([1.0, 2.0]))
    np.testing.assert_allclose(float(r), 9.0 + 144.0, rtol=1e-5)


def test_double_grad_through_recompute():
    from paddle_tpu.distributed.fleet.recompute import recompute

    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = recompute(lambda t: t * t * t, x).sum()
    (g,) = paddle.grad(y, [x], create_graph=True)
    (g2,) = paddle.grad(g, [x])
    np.testing.assert_allclose(g2.numpy(), [12.0], rtol=1e-6)


def test_pylayer_create_graph_raises():
    """Opaque user backward cannot be differentiated again — must raise
    loudly, never return silent zeros."""

    class Square(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensor()
            return g * 2.0 * x

    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = Square.apply(x).sum()
    with pytest.raises(NotImplementedError):
        paddle.grad(y, [x], create_graph=True)


def test_backward_still_accumulates_all_leaves():
    """Plain .backward() keeps reference semantics: every leaf gets .grad."""
    x = paddle.to_tensor([1.0], stop_gradient=False)
    w = paddle.to_tensor([2.0], stop_gradient=False)
    (x * w).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    np.testing.assert_allclose(w.grad.numpy(), [1.0])


def test_hessian_vector_product_pattern():
    """HVP via grad-of-(grad·v) — the PINN/optimization workhorse."""
    xw = np.array([1.0, 2.0, 3.0], dtype="float32")
    v = np.array([1.0, 0.5, -1.0], dtype="float32")
    x = paddle.to_tensor(xw, stop_gradient=False)
    y = (x * x * x).sum()
    (g,) = paddle.grad(y, [x], create_graph=True)
    gv = (g * paddle.to_tensor(v)).sum()
    (hvp,) = paddle.grad(gv, [x])
    want = 6.0 * xw * v  # H = diag(6x)
    np.testing.assert_allclose(hvp.numpy(), want, rtol=1e-5)
