"""Semi-auto parallel dygraph API (reference auto_parallel/api.py):
shard_optimizer with ShardingStage1/3, ShardDataloader, dist.to_static /
DistModel, and dtensor_from_local assembling true per-process blocks.
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.mesh import ProcessMesh, Replicate, Shard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh2d():
    return ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])


def test_shard_optimizer_stage1_places_slots():
    paddle.seed(0)
    m = nn.Linear(8, 16)
    opt = optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    mesh = _mesh2d()
    opt = dist.shard_optimizer(opt, dist.ShardingStage1("dp", mesh))
    x = paddle.randn([4, 8])
    loss = (m(x) ** 2).mean()
    loss.backward()
    opt.step()
    w = m.parameters()[0]
    slots = opt._slots[id(w)]
    assert "moment1" in slots
    spec = str(slots["moment1"].sharding.spec)
    assert "dp" in spec, spec
    # and the param itself stays as placed by the user (unsharded here)
    assert "dp" not in str(getattr(w._data.sharding, "spec", ""))


def test_shard_optimizer_stage3_shards_params():
    paddle.seed(0)
    m = nn.Linear(8, 16)
    opt = optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    mesh = _mesh2d()
    opt = dist.shard_optimizer(opt, dist.ShardingStage3("dp", mesh))
    w = m.parameters()[0]
    assert "dp" in str(w._data.sharding.spec)
    x = paddle.randn([4, 8])
    loss = (m(x) ** 2).mean()
    loss.backward()
    opt.step()
    slots = opt._slots[id(w)]
    assert "dp" in str(slots["moment1"].sharding.spec)


def test_shard_optimizer_default_follows_param_placement():
    paddle.seed(0)
    m = nn.Linear(8, 16)
    mesh = _mesh2d()
    w = m.parameters()[0]
    d = dist.shard_tensor(w, mesh, [Shard(0), Replicate()])
    w._data = d._data
    opt = optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
    opt = dist.shard_optimizer(opt)  # no shard_fn: inherit placements
    x = paddle.randn([4, 8])
    loss = (m(x) ** 2).mean()
    loss.backward()
    opt.step()
    slots = opt._slots[id(w)]
    assert slots["moment1"].sharding.is_equivalent_to(
        w._data.sharding, w._data.ndim)


def test_shard_dataloader_places_batches():
    from paddle_tpu.io import DataLoader, TensorDataset

    mesh = _mesh2d()
    X = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(16, 4))
    Y = paddle.to_tensor(np.arange(16, dtype=np.int64))
    loader = DataLoader(TensorDataset([X, Y]), batch_size=8)
    sharded = dist.shard_dataloader(loader, [mesh], shard_dims="dp")
    assert len(sharded) == len(loader)
    for xb, yb in sharded:
        assert "dp" in str(xb._data.sharding.spec)
        assert xb._process_mesh is mesh
        break


def test_to_static_distmodel_matches_trainstep():
    X = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    Y = np.random.RandomState(1).randn(8, 4).astype(np.float32)

    def run_plain():
        paddle.seed(5)
        m = nn.Linear(8, 4)
        opt = optimizer.AdamW(learning_rate=0.01,
                              parameters=m.parameters())
        step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
        return [float(step(paddle.to_tensor(X),
                           paddle.to_tensor(Y)).item()) for _ in range(4)]

    def run_dist():
        paddle.seed(5)
        m = nn.Linear(8, 4)
        opt = optimizer.AdamW(learning_rate=0.01,
                              parameters=m.parameters())
        dm = dist.to_static(m, None, nn.MSELoss(), opt, mesh=_mesh2d())
        dm.train()
        return [float(dm(paddle.to_tensor(X),
                         paddle.to_tensor(Y)).item()) for _ in range(4)]

    np.testing.assert_allclose(run_plain(), run_dist(), rtol=5e-4,
                               atol=1e-6)


def test_distmodel_eval_and_predict_modes():
    m = nn.Linear(8, 4)
    dm = dist.to_static(m, None, nn.MSELoss(),
                        optimizer.SGD(0.1, parameters=m.parameters()),
                        mesh=_mesh2d())
    x = paddle.randn([4, 8])
    y = paddle.randn([4, 4])
    dm.eval()
    ev = dm(x, y)
    assert ev.shape == []
    dm.predict()
    out = dm(x)
    assert out.shape == [4, 4]


def test_dtensor_from_local_single_process_identity():
    """With one process the local block IS the global tensor; values must
    round-trip exactly (round 2 fabricated replicated shards)."""
    mesh = ProcessMesh(np.arange(8), dim_names=["x"])
    local = np.arange(32, dtype=np.float32).reshape(8, 4)
    d = dist.dtensor_from_local(paddle.to_tensor(local), mesh, [Shard(0)])
    assert list(d.shape) == [8, 4]
    np.testing.assert_array_equal(np.asarray(d._data), local)
    # each device holds a distinct row block
    shards = {tuple(np.asarray(s.data).ravel()[:1])
              for s in d._data.addressable_shards}
    assert len(shards) == 8


def test_dtensor_from_local_rejects_bad_shape():
    mesh = ProcessMesh(np.arange(8), dim_names=["x"])
    local = np.zeros((5, 4), np.float32)  # 5 not divisible over 8 devices
    with pytest.raises(Exception):
        dist.dtensor_from_local(paddle.to_tensor(local), mesh, [Shard(0)])


def test_dtensor_from_local_distinct_blocks_multiprocess(tmp_path):
    """Two processes contribute DISTINCT local blocks; the assembled
    global must contain both (the round-2 bug returned rank 0's data
    everywhere)."""
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        try:
            from jax._src import xla_bridge as _xb
            if _xb.backends_are_initialized():
                from jax.extend.backend import clear_backends
                clear_backends()
        except Exception:
            pass
        import numpy as np
        from paddle_tpu.distributed import env as denv
        denv.init_parallel_env()
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.mesh import ProcessMesh, Shard

        rank = jax.process_index()
        mesh = ProcessMesh(np.arange(2), dim_names=["x"])
        local = np.full((2, 3), float(rank + 1), np.float32)
        d = dist.dtensor_from_local(paddle.to_tensor(local), mesh,
                                    [Shard(0)])
        assert list(d.shape) == [4, 3], d.shape
        # gather to replicated and check both blocks are present
        g = dist.unshard_dtensor(d)
        full = np.asarray(g._data.addressable_shards[0].data)
        assert np.allclose(full[:2], 1.0) and np.allclose(full[2:], 2.0), \\
            full
        print("DTENSOR_OK rank", rank, flush=True)
    """))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    log_dir = tmp_path / "logs"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(log_dir), str(worker)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    logs = "".join(f.read_text() for f in sorted(log_dir.glob("workerlog.*")))
    assert r.returncode == 0, logs + r.stdout + r.stderr
    assert logs.count("DTENSOR_OK") == 2, logs


class TestEagerDistAttrPropagation:
    """Dist attrs survive eager ops (the generated dist branch's
    set-output-dist-attrs step, dist_api_gen.py:46-66): metadata, not just
    values, is asserted after each op."""

    def test_elementwise_and_chain(self):
        mesh = _mesh2d()
        x = dist.shard_tensor(paddle.ones([8, 16]), mesh,
                              [Shard(0), Shard(1)])
        y = x + x
        assert y.is_dist() and y.process_mesh is mesh
        assert y.placements == [Shard(0), Shard(1)]
        z = (x * 2.0 + 1.0) / 2.0
        assert z.is_dist() and z.placements == [Shard(0), Shard(1)]

    def test_matmul_reduction_transpose_reshape(self):
        mesh = _mesh2d()
        x = dist.shard_tensor(paddle.ones([8, 16]), mesh,
                              [Shard(0), Shard(1)])
        w = dist.shard_tensor(paddle.ones([16, 4]), mesh,
                              [Replicate(), Shard(0)])
        z = paddle.matmul(x, w)
        assert z.is_dist() and z.placements[0] == Shard(0)
        r = x.sum(axis=1)
        assert r.is_dist() and r.placements[0] == Shard(0)
        t = x.transpose([1, 0])
        assert t.is_dist() and t.placements == [Shard(1), Shard(0)]
        rs = x.reshape([8, 4, 4])
        assert rs.is_dist() and rs.placements[0] == Shard(0)

    def test_mixed_dist_dense_operand(self):
        mesh = _mesh2d()
        x = dist.shard_tensor(paddle.ones([8, 16]), mesh,
                              [Shard(0), Replicate()])
        dense = paddle.ones([8, 16])
        y = x + dense
        assert y.is_dist() and y.placements[0] == Shard(0)

    def test_reshard_on_computed_tensor(self):
        """reshard after a compute chain needs no manual re-annotation."""
        mesh = _mesh2d()
        x = dist.shard_tensor(paddle.ones([8, 16]), mesh,
                              [Shard(0), Shard(1)])
        c = (x * 2.0 + 1.0).sum(axis=1)
        assert c.is_dist()
        out = dist.reshard(c, mesh, [Replicate(), Replicate()])
        assert out.placements == [Replicate(), Replicate()]
        np.testing.assert_allclose(out.numpy(), np.full((8,), 48.0))

    def test_grad_flow_keeps_values(self):
        mesh = _mesh2d()
        x = dist.shard_tensor(paddle.ones([8, 16]), mesh,
                              [Shard(0), Replicate()],
                              stop_gradient=False)
        loss = (x * x).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * np.ones((8, 16)))
