"""audio features + text viterbi/datasets (reference: python/paddle/
audio/, python/paddle/text/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio
from paddle_tpu import text


# ---------------------------------------------------------------------------
# audio functional
# ---------------------------------------------------------------------------
def test_hz_mel_roundtrip():
    for htk in (False, True):
        for hz in (60.0, 440.0, 4000.0):
            mel = audio.functional.hz_to_mel(hz, htk=htk)
            back = audio.functional.mel_to_hz(mel, htk=htk)
            np.testing.assert_allclose(back, hz, rtol=1e-5)


def test_fbank_matrix_properties():
    fb = audio.functional.compute_fbank_matrix(16000, 512, n_mels=40)
    m = fb.numpy()
    assert m.shape == (40, 257)
    assert (m >= 0).all()
    # every filter has support
    assert (m.sum(axis=1) > 0).all()


def test_window_functions():
    for w in ("hann", "hamming", "blackman", "rect"):
        win = audio.functional.get_window(w, 64).numpy()
        assert win.shape == (64,)
        assert win.max() <= 1.0 + 1e-6
    hann = audio.functional.get_window("hann", 64).numpy()
    np.testing.assert_allclose(hann[0], 0.0, atol=1e-7)


def test_spectrogram_parseval_sine():
    """A pure tone concentrates energy in the right frequency bin."""
    sr, n_fft = 8000, 256
    t = np.arange(sr, dtype=np.float32) / sr
    freq = 1000.0
    x = paddle.to_tensor(np.sin(2 * np.pi * freq * t))
    spec = audio.Spectrogram(n_fft=n_fft, hop_length=128)(x)
    s = spec.numpy()  # [freq_bins, frames]
    assert s.shape[0] == 1 + n_fft // 2
    peak_bin = s.mean(axis=1).argmax()
    expect_bin = round(freq * n_fft / sr)
    assert abs(int(peak_bin) - expect_bin) <= 1, (peak_bin, expect_bin)


def test_mel_spectrogram_and_mfcc_shapes():
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 4000).astype(np.float32))
    mel = audio.MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
    assert mel.shape[0] == 2 and mel.shape[1] == 32
    logmel = audio.LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
    assert logmel.shape == mel.shape
    mfcc = audio.MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)(x)
    assert mfcc.shape[0] == 2 and mfcc.shape[1] == 13


# ---------------------------------------------------------------------------
# text
# ---------------------------------------------------------------------------
def test_viterbi_decode_simple_chain():
    """A chain with a dominant diagonal transition keeps the best tag."""
    B, T, N = 2, 5, 4
    pot = np.full((B, T, N), -1.0, np.float32)
    pot[:, :, 1] = 2.0  # tag 1 always best unary
    trans = np.full((N, N), -0.5, np.float32)
    np.fill_diagonal(trans, 1.0)
    scores, paths = text.viterbi_decode(
        paddle.to_tensor(pot), paddle.to_tensor(trans),
        include_bos_eos_tag=False)
    assert list(paths.shape) == [B, T]
    np.testing.assert_array_equal(paths.numpy(),
                                  np.full((B, T), 1, np.int64))
    # score = T*2 unary + (T-1)*1 diagonal transitions
    np.testing.assert_allclose(scores.numpy(),
                               np.full((B,), 2.0 * T + (T - 1) * 1.0),
                               rtol=1e-5)


def test_viterbi_decoder_layer_matches_bruteforce():
    rng = np.random.RandomState(0)
    B, T, N = 3, 4, 3
    pot = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    dec = text.ViterbiDecoder(paddle.to_tensor(trans),
                              include_bos_eos_tag=False)
    scores, paths = dec(paddle.to_tensor(pot))

    # brute force over all tag sequences
    import itertools

    for b in range(B):
        best, best_path = -np.inf, None
        for seq in itertools.product(range(N), repeat=T):
            s = pot[b, 0, seq[0]]
            for t in range(1, T):
                s += trans[seq[t - 1], seq[t]] + pot[b, t, seq[t]]
            if s > best:
                best, best_path = s, seq
        np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                   rtol=1e-5)
        np.testing.assert_array_equal(paths.numpy()[b],
                                      np.asarray(best_path))


def test_uci_housing_trains():
    from paddle_tpu import nn, optimizer
    from paddle_tpu.io import DataLoader

    ds = text.UCIHousing(mode="train")
    assert len(ds) > 100
    m = nn.Linear(13, 1)
    opt = optimizer.Adam(learning_rate=0.05, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    losses = []
    for _ in range(3):
        for xb, yb in DataLoader(ds, batch_size=64, shuffle=True):
            losses.append(float(step(xb, yb).item()))
    assert losses[-1] < losses[0]


def test_imdb_synthetic_separable():
    ds = text.Imdb(mode="train", n_samples=200)
    doc, lbl = ds[0]
    assert doc.dtype == np.int64
    # class-conditional vocab ranges hold
    for i in range(50):
        d, l = ds[i]
        if l == 0:
            assert d.max() < ds.vocab_size // 2
        else:
            assert d.min() >= ds.vocab_size // 2
