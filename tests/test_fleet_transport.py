"""Out-of-process fleet transport pins (ISSUE 12).

Three layers, cheapest first:

* protocol units — framing, deadlines, idempotent retry, late-reply
  hygiene, error mapping, the ``fleet.rpc_delay``/``fleet.rpc_drop``
  fault points;
* **loopback** tests — a real :class:`RpcClient` talking to a real
  :class:`ReplicaServicer` over a socketpair, with the servicer thread
  hosting a real tiny-Llama engine in-process. "SIGKILL" here is an
  abrupt server-side socket sever with no farewell frame — byte-for-
  byte what the client observes when the worker process is killed —
  which makes the headline pin (mid-decode kill resumes bit-identical,
  greedy AND sampled) runnable in the non-slow tier. The true
  multiprocess versions live in test_fleet_subprocess.py (slow);
* router bookkeeping regressions — hand-off budget consumed exactly
  once per death, the ``handoff_exhausted`` counter, dead-handle abort
  hygiene — and the registry's skew-immune monotonic liveness.
"""
import socket
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.replica_registry import MemStore, ReplicaRegistry
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    EngineConfig, LLMEngine, RequestOutput, SamplingParams,
)
from paddle_tpu.serving.fleet import (
    FleetConfig, FleetRouter, InProcessReplica, ReplicaGone,
    ReplicaHandle, ReplicaLoad, ReplicaServicer, RpcClient,
    RpcRemoteError, RpcTimeout, SubprocessReplica,
)
from paddle_tpu.serving.fleet.transport import (
    IDEMPOTENT_METHODS, MUTATION_METHODS, RpcError, recv_frame,
    send_frame,
)
from paddle_tpu.serving.request import FINISH_REASONS
from paddle_tpu.testing import faults


@pytest.fixture(autouse=True)
def _no_fault_leak():
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------
class TestFraming:
    def test_roundtrip_and_eof(self):
        a, b = socket.socketpair()
        send_frame(a, {"id": 1, "method": "ping", "params": {}})
        send_frame(a, {"id": 2, "x": [1, 2, 3]})
        assert recv_frame(b) == {"id": 1, "method": "ping", "params": {}}
        assert recv_frame(b) == {"id": 2, "x": [1, 2, 3]}
        a.close()
        assert recv_frame(b) is None       # clean EOF
        b.close()

    def test_oversized_length_prefix_is_connection_loss(self):
        a, b = socket.socketpair()
        a.sendall(b"\xff\xff\xff\xff")     # 4 GiB frame: garbage
        with pytest.raises(OSError):
            recv_frame(b)
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# RpcClient semantics against a hand-rolled server
# ---------------------------------------------------------------------------
def _server(sock, script):
    """Serve frames per `script(msg) -> reply | None (swallow)`."""

    def run():
        try:
            while True:
                msg = recv_frame(sock)
                if msg is None:
                    return
                reply = script(msg)
                if reply is not None:
                    send_frame(sock, reply)
        except OSError:
            pass

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


class TestRpcClient:
    def _client(self, script, **kw):
        a, b = socket.socketpair()
        _server(b, script)
        kw.setdefault("backoff_base_s", 0.01)
        return RpcClient(a, **kw), b

    def test_call_response_matched_by_id(self):
        cl, _ = self._client(
            lambda m: {"id": m["id"], "ok": True,
                       "result": m["params"]["x"] * 2})
        # "double" is a test-only verb outside the fleet partition, so
        # it must be classified explicitly at the call site
        assert cl.call("double", {"x": 21}, idempotent=True) == 42
        assert cl.call("double", {"x": 3}, idempotent=True) == 6
        assert cl.stats["calls"] == 2
        cl.close()

    def test_unclassified_verb_raises_not_defaults(self):
        """PR 19 shipped tier_stats dispatched but classified nowhere —
        it silently became a non-retried mutation. Now an unclassified
        verb refuses to pick a retry policy at all."""
        cl, _ = self._client(
            lambda m: {"id": m["id"], "ok": True, "result": 1})
        with pytest.raises(RpcError, match="neither"):
            cl.call("double", {"x": 1})
        # explicit classification and partitioned verbs still work
        assert cl.call("tier_stats", {}) == 1   # now IDEMPOTENT
        assert "tier_stats" in IDEMPOTENT_METHODS
        assert IDEMPOTENT_METHODS.isdisjoint(MUTATION_METHODS)
        cl.close()

    def test_mutation_timeout_no_retry(self):
        calls = []

        def swallow(m):
            calls.append(m["method"])
            return None

        cl, _ = self._client(swallow)
        with pytest.raises(RpcTimeout):
            cl.call("step", {}, deadline_s=0.1, idempotent=False)
        time.sleep(0.05)
        assert calls == ["step"]          # exactly one attempt
        assert cl.stats["timeouts"] == 1
        cl.close()

    def test_idempotent_retries_with_backoff_then_succeeds(self):
        seen = []

        def flaky(m):
            seen.append(m["id"])
            if len(seen) == 1:
                return None               # lose the first reply
            return {"id": m["id"], "ok": True, "result": "pong"}

        cl, _ = self._client(flaky)
        assert cl.call("ping", {}, deadline_s=0.15) == "pong"
        assert cl.stats["retries"] == 1
        assert seen[0] != seen[1]         # the retry is a NEW sequence
        cl.close()

    def test_late_reply_to_abandoned_call_never_poisons_next(self):
        def script(m):
            if m["method"] == "slow":
                # reply AFTER the caller's deadline has expired
                time.sleep(0.25)
                return {"id": m["id"], "ok": True, "result": "stale"}
            return {"id": m["id"], "ok": True, "result": "fresh"}

        cl, _ = self._client(script)
        with pytest.raises(RpcTimeout):
            cl.call("slow", {}, deadline_s=0.05, idempotent=False)
        # the stale reply lands while this call is pending; ids differ
        assert cl.call("fast", {}, deadline_s=2.0,
                       idempotent=False) == "fresh"
        cl.close()

    def test_eof_mid_call_raises_replica_gone_not_timeout(self):
        def die(m):
            raise OSError("boom")          # server loop exits, EOF

        cl, srv = self._client(die)
        srv.shutdown(socket.SHUT_RDWR)
        srv.close()
        time.sleep(0.05)
        with pytest.raises(ReplicaGone):
            cl.call("step", {}, deadline_s=5.0, idempotent=False)
        assert cl.closed
        cl.close()

    def test_remote_error_mapping(self):
        stub = _StubReplica()
        svc = ReplicaServicer(stub)
        assert svc.handle({"id": 1, "method": "nope", "params": {}})[
            "ok"] is False
        cl, _ = self._client(ReplicaServicer(stub).handle)
        with pytest.raises(ValueError):   # known types cross as themselves
            cl.call("add_request", {
                "request_id": "r", "prompt_ids": [],
                "sampling": {"max_new_tokens": 0}}, idempotent=False)
        with pytest.raises(RpcRemoteError):
            cl.call("no_such_verb", {}, idempotent=False)
        cl.close()

    def test_rpc_drop_fault_mutation_dies_query_retries(self):
        cl, _ = self._client(
            lambda m: {"id": m["id"], "ok": True, "result": "pong"})
        with faults.injected("fleet.rpc_drop:flag*1"):
            with pytest.raises(RpcTimeout):   # mutation: one lost frame
                cl.call("step", {}, deadline_s=1.0, idempotent=False)
        with faults.injected("fleet.rpc_drop:flag*1"):
            # idempotent: the retry re-sends and succeeds
            assert cl.call("ping", {}, deadline_s=1.0) == "pong"
            assert cl.stats["retries"] >= 1
        cl.close()

    def test_rpc_delay_fault_adds_latency(self):
        cl, _ = self._client(
            lambda m: {"id": m["id"], "ok": True, "result": 1})
        with faults.injected("fleet.rpc_delay:sleep:0.2*1"):
            t0 = time.monotonic()
            assert cl.call("load", {}) == 1
            assert time.monotonic() - t0 >= 0.2
        cl.close()


class _StubReplica(ReplicaHandle):
    """Minimal servicer target for protocol-level tests."""

    def __init__(self):
        self.replica_id = "stub"
        self.alive = True
        self.retiring = False

    def admission_verdict(self, prompt_tokens):
        return None

    def estimated_ttft_ms(self, prompt_tokens):
        return 1.0

    def load(self):
        return ReplicaLoad()

    @property
    def is_draining(self):
        return False

    @property
    def drained(self):
        return False

    def has_unfinished(self):
        return False

    def add_request(self, request_id, prompt_ids, sampling, *,
                    rng_state=None):
        pass  # SamplingParams(max_new_tokens=0) raises before this

    def abort_request(self, request_id):
        return False

    def release_request(self, request_id):
        pass

    def rng_state(self, request_id):
        return None

    def step(self):
        return []

    def start_drain(self, reason="manual"):
        return []


# ---------------------------------------------------------------------------
# loopback: real engine behind a real socket; sever() == SIGKILL as the
# client sees it
# ---------------------------------------------------------------------------
class Loopback:
    def __init__(self, inner, client_kw=None):
        self.inner = inner
        a, b = socket.socketpair()
        self._server_sock = b
        threading.Thread(target=ReplicaServicer(inner).serve, args=(b,),
                         daemon=True).start()
        self.client = RpcClient(a, name=inner.replica_id,
                                **(client_kw or {}))
        self.handle = SubprocessReplica(inner.replica_id, self.client)
        # fleet.worker_kill's SIGKILL, loopback edition: the server
        # half vanishes abruptly — no farewell frame, replies in flight
        # lost — exactly the byte stream a killed process leaves behind
        self.handle.hard_kill = self.sever

    def sever(self):
        try:
            self._server_sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._server_sock.close()


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    return model


def _ecfg(**kw):
    kw.setdefault("block_size", 4)
    kw.setdefault("max_num_seqs", 8)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("drain_grace_s", 0.0)
    return EngineConfig(**kw)


def _prompts(model, n, seed=7):
    rng = np.random.default_rng(seed)
    return [list(map(int, rng.integers(0, model.config.vocab_size,
                                       size=3 + i % 4)))
            for i in range(n)]


def _reference(model, prompts, sp, ids):
    eng = LLMEngine(model, _ecfg())
    for rid, p in zip(ids, prompts):
        eng.add_request(rid, p, sampling=sp)
    while eng.has_unfinished():
        eng.step()
    return {rid: list(eng.get_request(rid).generated) for rid in ids}


def _drain_router(router, max_steps=300):
    outs = []
    for _ in range(max_steps):
        if not router.has_unfinished():
            return outs
        outs.extend(router.step())
    raise AssertionError("router failed to converge")


def _sp(sampled):
    if sampled:
        return SamplingParams(max_new_tokens=8, temperature=0.8,
                              top_p=0.9)
    return SamplingParams(max_new_tokens=8)


class TestLoopbackE2E:
    @pytest.mark.parametrize("sampled", [False, True],
                             ids=["greedy", "sampled"])
    def test_generate_over_transport_matches_engine(self, tiny_model,
                                                    sampled):
        sp = _sp(sampled)
        prompts = _prompts(tiny_model, 3)
        ids = [f"t{i}" for i in range(3)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                       replica_id="L0"))
        router = FleetRouter([lb.handle])
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert all(final[r].finish_reason == "length" for r in ids)

    @pytest.mark.parametrize("sampled", [False, True],
                             ids=["greedy", "sampled"])
    def test_sigkill_mid_decode_resumes_bit_identical(self, tiny_model,
                                                      sampled):
        # THE pin: the worker dies with no warning mid-decode; every
        # in-flight request resumes on the peer and the client-visible
        # token streams are bit-identical to an uninterrupted single
        # engine — for sampling, from the piggybacked composite
        # rng_state (the dead worker can't be queried post-mortem).
        sp = _sp(sampled)
        prompts = _prompts(tiny_model, 6)
        ids = [f"k{i}" for i in range(6)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb0 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                        replica_id="L0"))
        lb1 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                        replica_id="L1"))
        router = FleetRouter([lb0.handle, lb1.handle])
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install("fleet.worker_kill:flag:L0@3*1")
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert all(final[r].finish_reason == "length" for r in ids)
        assert not lb0.handle.alive
        assert router.num_handoffs >= 1
        assert router.num_replicas_dead == 1
        # exactly-once emission: every token reached the client once
        counts = {}
        for o in outs:
            if o.token is not None:
                counts[o.request_id] = counts.get(o.request_id, 0) + 1
        assert counts == {r: len(ref[r]) for r in ids}

    def test_drain_over_transport_hands_off_bit_identical(self,
                                                          tiny_model):
        # SIGTERM path through the wire: start_drain's reply carries
        # the aborts AND their rng states in one frame
        sp = _sp(True)
        prompts = _prompts(tiny_model, 4)
        ids = [f"d{i}" for i in range(4)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb0 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                        replica_id="L0"))
        lb1 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                        replica_id="L1"))
        router = FleetRouter([lb0.handle, lb1.handle])
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        for _ in range(3):
            router.step()
        router.retire_replica(lb0.handle)
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert lb0.handle.replica_id not in [
            h.replica_id for h in router.replicas]  # reaped after drain

    def test_chaos_storm_no_strands_no_dups_pools_full(self, tiny_model):
        # randomized kill/drop/delay interleaving (schedule drawn from
        # a seeded rng, two rounds). Invariants, not outcomes: every
        # request terminates with a FINISH_REASONS member, every token
        # reaches the client exactly once, and the surviving engines'
        # block pools drain back to full.
        for seed in (0, 1):
            sched = np.random.default_rng(seed)
            n = 8
            prompts = _prompts(tiny_model, n, seed=20 + seed)
            ids = [f"c{seed}-{i}" for i in range(n)]
            lbs = [Loopback(InProcessReplica(tiny_model, _ecfg(),
                                             replica_id=f"S{seed}{j}"))
                   for j in range(3)]
            for lb in lbs:
                # peer data plane on: KV/prefix ships go worker↔worker
                # by ticket, degrading to relay/recompute under fire
                lb.handle.peer_endpoint = lb.inner.start_peer()
            # disaggregated roles (third replica serves both) so the
            # storm exercises prefill->decode ships under fire too
            router = FleetRouter(
                [lb.handle for lb in lbs],
                FleetConfig(roles={f"S{seed}0": "prefill",
                                   f"S{seed}1": "decode"},
                            prefix_ship_threshold=1))
            # fleet prefix layer under fire: warm one 2-block shared
            # header pre-faults, then advertise every replica's digest
            # via manual registry beats (loopback handles are
            # self_heartbeat — in tests nothing beats for them), so
            # the storm dispatches on adverts that go stale the moment
            # churn evicts the blocks. Threshold 1: the first affinity
            # match already makes the header ship-eligible.
            shared = [int(t) for t in sched.integers(
                1, tiny_model.config.vocab_size, size=8)]
            router.add_request(f"c{seed}-warm", shared + [7, 8, 9],
                               sampling=_sp(False))
            _drain_router(router)
            for lb in lbs:
                router.registry.heartbeat(
                    lb.handle.replica_id,
                    meta={"prefix": lb.handle.prefix_digest()})
            for i in range(4):
                rid = f"c{seed}-h{i}"
                ids.append(rid)
                tail = [int(t) for t in sched.integers(
                    1, tiny_model.config.vocab_size,
                    size=3 + int(sched.integers(0, 3)))]
                prompts.append(shared + tail)
                router.add_request(rid, shared + tail,
                                   sampling=_sp(i % 2 == 0))
            for i, (rid, p) in enumerate(zip(ids[:n], prompts[:n])):
                router.add_request(rid, p, sampling=_sp(i % 2 == 1))
            spec = ";".join([
                f"fleet.worker_kill:flag:S{seed}0"
                f"@{sched.integers(2, 5)}*1",
                f"fleet.worker_kill:flag:S{seed}1"
                f"@{sched.integers(5, 8)}*1",
                f"fleet.rpc_drop:flag@{sched.integers(3, 30)}"
                f"*{sched.integers(1, 3)}",
                f"fleet.rpc_delay:sleep:0.01@{sched.integers(1, 20)}"
                f"*{sched.integers(1, 4)}",
                # KV-ship chaos: dropped/corrupt ships must degrade to
                # recompute without duplicating or stranding a request
                f"fleet.kv_ship_drop:flag@{sched.integers(1, 5)}"
                f"*{sched.integers(1, 3)}",
                f"fleet.kv_ship_corrupt:flag@{sched.integers(1, 5)}"
                f"*{sched.integers(1, 3)}",
                f"fleet.kv_ship_delay:flag:0.005@{sched.integers(1, 8)}"
                f"*{sched.integers(1, 3)}",
                # proactive prefix ships under the same fire: dropped
                # or corrupted ships must leave the destination merely
                # cold, never corrupt
                f"fleet.prefix_ship_drop:flag@{sched.integers(0, 2)}"
                f"*{sched.integers(1, 2)}",
                f"fleet.prefix_ship_corrupt:flag@{sched.integers(0, 2)}"
                f"*{sched.integers(1, 2)}",
                # peer-rung chaos: failed pushes must degrade one rung
                # (relay, then recompute) with every ticket accounted
                f"fleet.peer_connect_fail:flag@{sched.integers(0, 3)}"
                f"*{sched.integers(1, 3)}",
                f"fleet.peer_send_drop:flag@{sched.integers(0, 3)}"
                f"*{sched.integers(1, 3)}",
                f"fleet.peer_frame_corrupt:flag@{sched.integers(0, 3)}"
                f"*{sched.integers(1, 3)}",
                f"fleet.peer_stall:sleep:0.05@{sched.integers(0, 3)}"
                f"*{sched.integers(1, 2)}",
            ])
            faults.install(spec)
            outs = _drain_router(router, max_steps=400)
            faults.clear()
            if not router.dispatchable() and router.has_unfinished():
                # everything died with work queued: the supervisor's
                # job is a fresh replica; here the test plays it
                fresh = Loopback(InProcessReplica(
                    tiny_model, _ecfg(), replica_id=f"S{seed}9"))
                router.attach_replica(fresh.handle)
                lbs.append(fresh)
                outs += _drain_router(router, max_steps=400)
            final = {o.request_id: o for o in outs if o.finished}
            assert set(final) == set(ids)            # no strands
            assert all(final[r].finish_reason in FINISH_REASONS
                       for r in ids)
            counts = {}
            for o in outs:
                if o.token is not None:
                    counts[o.request_id] = counts.get(o.request_id,
                                                      0) + 1
            for r in ids:                            # no duplicates
                assert counts.get(r, 0) == len(final[r].generated), r
            for lb in lbs:                           # pools return full
                if lb.handle.alive:
                    bm = lb.inner.engine.block_manager
                    assert bm.num_free_blocks == bm.num_blocks
                    assert bm.num_free_host_blocks == bm.num_host_blocks
                    # no survivor holds uncommitted staged peer payloads
                    lis = lb.inner.peer_listener
                    if lis is not None:
                        lis.gc()
                        assert lis.pending_count == 0
            # ticket accounting survives the storm: every issued ticket
            # ended in exactly one counted outcome
            assert router.num_tickets_issued == \
                sum(router.ticket_outcomes.values())
            # the prefix layer was actually exercised: at least one
            # proactive ship was attempted (landed or failed cleanly)
            assert (router.num_prefix_ships
                    + router.num_prefix_ship_failures) >= 1


# ---------------------------------------------------------------------------
# disaggregated serving: prefill/decode roles + KV-ship (ISSUE 13)
# ---------------------------------------------------------------------------
def _disagg_pair(model, seed_prefix="P"):
    lb_p = Loopback(InProcessReplica(model, _ecfg(),
                                     replica_id=f"{seed_prefix}pre"))
    lb_d = Loopback(InProcessReplica(model, _ecfg(),
                                     replica_id=f"{seed_prefix}dec"))
    router = FleetRouter(
        [lb_p.handle, lb_d.handle],
        FleetConfig(roles={f"{seed_prefix}pre": "prefill",
                           f"{seed_prefix}dec": "decode"}))
    return lb_p, lb_d, router


def _token_counts(outs):
    counts = {}
    for o in outs:
        if o.token is not None:
            counts[o.request_id] = counts.get(o.request_id, 0) + 1
    return counts


class TestDisaggKVShip:
    @pytest.mark.parametrize("sampled", [False, True],
                             ids=["greedy", "sampled"])
    def test_disagg_parity_over_transport(self, tiny_model, sampled):
        # THE tentpole pin: every request prefills on the prefill-role
        # replica, its committed KV ships over the wire (binary frame),
        # and the decode-role replica continues it mid-context — token
        # streams bit-identical to an uninterrupted single engine,
        # with ZERO prompt tokens recomputed.
        sp = _sp(sampled)
        n = 5
        prompts = _prompts(tiny_model, n)
        ids = [f"g{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _disagg_pair(tiny_model, "A")
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert _token_counts(outs) == {r: len(ref[r]) for r in ids}
        assert router.num_kv_ship_requests == n
        assert router.num_kv_ship_bytes > 0
        assert router.num_kv_ship_blocks > 0
        assert router.num_tokens_recomputed == 0
        assert router.num_recompute_fallbacks == 0
        # ships are planned transfers, not failure hand-offs
        assert router.num_handoffs == 0
        assert lb_d.inner.engine.num_continuation_admits == n
        snap = router.snapshot()
        assert snap["fleet_kv_ship_requests"] == n
        assert isinstance(snap["fleet_kv_ship_ms_avg"], float)

    def test_drain_hand_off_ships_blocks_zero_recompute(self,
                                                        tiny_model):
        # SIGTERM-drain upgrade: the drain reply piggybacks the parked
        # KV, the peer imports it, and the hand-off recomputes ZERO
        # prompt tokens (counter-asserted) — still bit-identical.
        sp = _sp(True)
        prompts = _prompts(tiny_model, 4)
        ids = [f"dr{i}" for i in range(4)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb0 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                        replica_id="B0"))
        lb1 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                        replica_id="B1"))
        router = FleetRouter([lb0.handle, lb1.handle])
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        for _ in range(4):
            router.step()   # everyone well into decode
        router.retire_replica(lb0.handle)
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert router.num_handoffs >= 1
        assert router.num_kv_ship_requests >= 1
        assert router.num_tokens_recomputed == 0
        assert router.num_recompute_fallbacks == 0

    @pytest.mark.parametrize("fault", ["drop", "corrupt"],
                             ids=["dropped", "corrupt"])
    def test_kv_ship_fault_falls_back_to_recompute(self, tiny_model,
                                                   fault):
        # a dropped ship never reaches the peer; a corrupt one fails
        # the import-side CRC. Both degrade to resume-by-recompute on
        # the decode side — bit-identical, never duplicated or lost.
        sp = _sp(True)
        n = 4
        prompts = _prompts(tiny_model, n)
        ids = [f"f{fault[0]}{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p, lb_d, router = _disagg_pair(tiny_model, fault[0].upper())
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install(f"fleet.kv_ship_{fault}:flag*{n}")
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert _token_counts(outs) == {r: len(ref[r]) for r in ids}
        assert router.num_recompute_fallbacks == n
        assert router.num_kv_ship_requests == 0
        assert router.num_tokens_recomputed > 0
        assert lb_d.inner.engine.num_continuation_admits == 0

    def test_decode_replica_sigkill_recompute_fallback(self,
                                                       tiny_model):
        # crash hand-off: the decode replica dies mid-decode with no
        # farewell; its requests recover from router-side bookkeeping
        # by recompute on the surviving decode replica — bit-identical.
        sp = _sp(True)
        n = 4
        prompts = _prompts(tiny_model, n)
        ids = [f"x{i}" for i in range(n)]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb_p = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                         replica_id="Xpre"))
        lb_d0 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                          replica_id="Xdec0"))
        lb_d1 = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                          replica_id="Xdec1"))
        router = FleetRouter(
            [lb_p.handle, lb_d0.handle, lb_d1.handle],
            FleetConfig(roles={"Xpre": "prefill", "Xdec0": "decode",
                               "Xdec1": "decode"}))
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        faults.install("fleet.worker_kill:flag:Xdec0@4*1")
        outs = _drain_router(router, max_steps=400)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert _token_counts(outs) == {r: len(ref[r]) for r in ids}
        assert not lb_d0.handle.alive
        assert router.num_replicas_dead == 1
        assert router.num_kv_ship_requests >= 1
        # the stranded requests resumed by recompute somewhere
        assert router.num_tokens_recomputed > 0

    def test_no_decode_peer_keeps_decoding_on_prefill_replica(
            self, tiny_model):
        # availability beats purity: a prefill-only fleet never ships
        # (no peer) and still serves correctly
        sp = _sp(False)
        prompts = _prompts(tiny_model, 2)
        ids = ["np0", "np1"]
        ref = _reference(tiny_model, prompts, sp, ids)
        lb = Loopback(InProcessReplica(tiny_model, _ecfg(),
                                       replica_id="solo"))
        router = FleetRouter([lb.handle],
                             FleetConfig(roles={"solo": "prefill"}))
        for rid, p in zip(ids, prompts):
            router.add_request(rid, p, sampling=sp)
        outs = _drain_router(router)
        final = {o.request_id: o for o in outs if o.finished}
        assert {r: list(final[r].generated) for r in ids} == ref
        assert router.num_kv_ship_requests == 0
        assert router.num_recompute_fallbacks == 0

    def test_role_relearned_from_registry_heartbeat(self):
        # restart story: a rebuilt router attaches role-less handles;
        # the worker's self-heartbeat meta carries the role and the
        # next health sweep re-learns it
        reg = ReplicaRegistry(MemStore(), ttl_s=30.0)
        h = _StubReplica()
        h.replica_id = "w0-g1"
        h.self_heartbeat = True
        h.role = None
        router = FleetRouter([h], registry=reg)
        reg.heartbeat("w0-g1", meta={"pid": 1234, "role": "decode"})
        router.step()
        assert h.role == "decode"
        # sticky: later beats without meta must not erase it
        reg.heartbeat("w0-g1", meta={"pid": 1234})
        router.step()
        assert h.role == "decode"

    def test_export_import_content_identical(self, tiny_model):
        # the shipped bytes land bit-for-bit: gather the source blocks
        # and the imported blocks off both engines and compare
        eng_a = InProcessReplica(tiny_model, _ecfg(),
                                 replica_id="ca").engine
        eng_b = InProcessReplica(tiny_model, _ecfg(),
                                 replica_id="cb").engine
        sp = SamplingParams(max_new_tokens=4)
        prompt = _prompts(tiny_model, 1)[0] * 3   # multi-block prompt
        eng_a.add_request("src", prompt, sampling=sp)
        eng_a.step()   # prefill commits + first token
        req = eng_a.get_request("src")
        assert req.num_cached > 0
        meta, payload = eng_a.export_kv("src")
        src_table = eng_a.block_manager.export_blocks(
            "src", meta["tokens_covered"])
        k_src, v_src = eng_a._swapper.gather(src_table)
        eng_b.import_kv("dst", list(req.tokens), sampling=sp,
                        meta=meta, payload=payload)
        dst_table = eng_b.block_manager.export_blocks(
            "dst", meta["tokens_covered"])
        k_dst, v_dst = eng_b._swapper.gather(dst_table)
        np.testing.assert_array_equal(k_src, k_dst)
        np.testing.assert_array_equal(v_src, v_dst)
        bm = eng_b.block_manager
        for b in dst_table:
            assert bm.ref_count(b) == 1
        eng_b.abort_request("dst")
        eng_b.release_request("dst")
        eng_a.abort_request("src")
        eng_a.release_request("src")
        for eng in (eng_a, eng_b):
            bm = eng.block_manager
            bm.check_invariants()
            assert bm.num_free_blocks == bm.num_blocks


# ---------------------------------------------------------------------------
# hand-off budget + dead-handle bookkeeping regressions (model-free)
# ---------------------------------------------------------------------------
class FakeReplica(ReplicaHandle):
    """Same shape as test_fleet.FakeReplica, trimmed to what's used."""

    def __init__(self, replica_id, ttft=None, capacity=8):
        self.replica_id = replica_id
        self.alive = True
        self.retiring = False
        self.ttft = ttft
        self.capacity = capacity
        self.reqs = {}
        self.dispatch_log = []
        self._draining = False

    def admission_verdict(self, prompt_tokens):
        if not self.alive:
            return "replica is dead"
        if self._draining:
            return "replica is draining"
        if len(self.reqs) >= self.capacity:
            return "queue full"
        return None

    def estimated_ttft_ms(self, prompt_tokens):
        return self.ttft

    def load(self):
        return ReplicaLoad(num_running=len(self.reqs),
                           kv_utilization=min(1.0, len(self.reqs)
                                              / max(self.capacity, 1)))

    @property
    def is_draining(self):
        return self._draining

    @property
    def drained(self):
        return self._draining and not self.reqs

    def has_unfinished(self):
        return self.alive and bool(self.reqs)

    def add_request(self, request_id, prompt_ids, sampling, *,
                    rng_state=None):
        self.reqs[request_id] = [sampling, []]
        self.dispatch_log.append(request_id)

    def abort_request(self, request_id):
        return self.reqs.pop(request_id, None) is not None

    def release_request(self, request_id):
        self.reqs.pop(request_id, None)

    def rng_state(self, request_id):
        return {"fake_state_for": request_id}

    def step(self):
        if not self.alive:
            return []
        outs = []
        for rid in list(self.reqs):
            sp, gen = self.reqs[rid]
            gen.append(1000 + len(gen))
            done = len(gen) >= sp.max_new_tokens
            outs.append(RequestOutput(
                request_id=rid, token=gen[-1], finished=done,
                generated=list(gen),
                finish_reason="length" if done else None))
            if done:
                del self.reqs[rid]
        return outs

    def start_drain(self, reason="manual"):
        self._draining = True
        outs = []
        for rid in list(self.reqs):
            sp, gen = self.reqs.pop(rid)
            outs.append(RequestOutput(
                request_id=rid, token=None, finished=True,
                generated=list(gen), finish_reason="aborted:drain"))
        return outs


class TestHandoffBudget:
    def test_process_death_consumes_budget_exactly_once(self):
        # the handle dies outside the router's sight; however many
        # health-sweep passes observe the corpse, each stranded request
        # pays ONE hand-off slot for the one death
        ra = FakeReplica("ra", ttft=1.0)
        rb = FakeReplica("rb", ttft=9.0)
        router = FleetRouter([ra, rb])
        rids = [router.add_request([1], SamplingParams(max_new_tokens=6))
                for _ in range(2)]
        router.step()
        assert ra.dispatch_log == rids
        ra.alive = False                     # process gone
        outs = []
        router._health_sweep(outs)           # discovery pass
        for _ in range(4):                   # sweep spam: same corpse
            router._health_sweep(outs)
        assert router.num_handoffs == 2      # one slot per request
        assert all(router.get_request(r).handoffs == 1 for r in rids)
        assert router.num_replicas_dead == 1
        final = {o.request_id: o for o in _drain_router(router)
                 if o.finished}
        assert all(final[r].finish_reason == "length" for r in rids)
        assert all(router.get_request(r).handoffs == 1 for r in rids)

    def test_repeated_kill_replica_is_idempotent(self):
        ra = FakeReplica("ra", ttft=1.0)
        rb = FakeReplica("rb", ttft=9.0)
        router = FleetRouter([ra, rb])
        rid = router.add_request([1], SamplingParams(max_new_tokens=4))
        router.step()
        outs = []
        router.kill_replica("ra", outputs=outs)
        router.kill_replica("ra", outputs=outs)
        router.kill_replica("ra", outputs=outs)
        assert router.num_replicas_dead == 1
        assert router.num_handoffs == 1
        assert router.get_request(rid).handoffs == 1

    def test_handoff_exhausted_counter_pinned(self):
        ra = FakeReplica("ra", ttft=1.0)
        rb = FakeReplica("rb", ttft=9.0)
        router = FleetRouter([ra, rb], FleetConfig(max_handoffs=0))
        rid = router.add_request([1], SamplingParams(max_new_tokens=4))
        router.step()
        outs = []
        router.kill_replica("ra", outputs=outs)
        assert router.num_handoff_exhausted == 1
        assert [o.finish_reason for o in outs] == ["aborted:error"]
        router.kill_replica("ra", outputs=outs)    # corpse re-kill
        assert router.num_handoff_exhausted == 1   # not re-counted
        assert router.snapshot()["fleet_handoff_exhausted"] == 1
        assert router.get_request(rid).finish_reason == "aborted:error"

    def test_handoff_exhausted_counts_drain_path_too(self):
        class DrainOnStep(FakeReplica):
            def step(self):
                if self.reqs and not self._draining:
                    return self.start_drain("unstable")
                return super().step()

        router = FleetRouter(
            [DrainOnStep("ra"), DrainOnStep("rb"), DrainOnStep("rc")],
            FleetConfig(max_handoffs=1))
        router.add_request([1], SamplingParams(max_new_tokens=4))
        outs = _drain_router(router)
        assert [o.finish_reason for o in outs
                if o.finished] == ["aborted:drain"]
        assert router.num_handoffs == 1
        assert router.num_handoff_exhausted == 1

    def test_abort_on_dead_replica_unassigns(self):
        # pre-fix, the dead handle kept the aborted request in
        # _assigned and every health sweep "recovered" the corpse again
        ra = FakeReplica("ra", ttft=1.0)
        rb = FakeReplica("rb", ttft=9.0)
        router = FleetRouter([ra, rb])
        rid = router.add_request([1], SamplingParams(max_new_tokens=9))
        router.step()
        ra.alive = False
        assert router.abort_request(rid) is True
        assert not router._assigned["ra"]
        outs = []
        for _ in range(3):
            router._health_sweep(outs)
        assert router.num_replicas_dead == 0   # nothing left to recover
        assert router.num_handoffs == 0


# ---------------------------------------------------------------------------
# registry: skew-immune monotonic liveness
# ---------------------------------------------------------------------------
class TestRegistryMonotonic:
    def test_wall_clock_skew_cannot_fake_death(self):
        # a writer whose wall clock is 999s behind still reads as alive:
        # liveness keys on the record CHANGING, not on its ts field
        store = MemStore()
        writer = ReplicaRegistry(store, ttl_s=2.0)
        reader = ReplicaRegistry(store, ttl_s=2.0)
        writer.heartbeat("w", now=time.time() - 999.0)   # skewed clock
        assert reader.is_alive("w")
        writer.heartbeat("w", now=time.time() - 999.0)
        assert set(reader.alive()) == {"w"}

    def test_silence_past_ttl_is_death_on_reader_clock(self):
        store = MemStore()
        writer = ReplicaRegistry(store, ttl_s=2.0)
        reader = ReplicaRegistry(store, ttl_s=2.0)
        writer.heartbeat("w")
        t0 = time.monotonic()
        reader._mono = lambda: t0
        assert reader.is_alive("w")                 # observed at t0
        reader._mono = lambda: t0 + 1.5
        assert reader.is_alive("w")                 # inside ttl
        reader._mono = lambda: t0 + 2.5
        assert reader.is_alive("w") is False        # silent past ttl
        writer.heartbeat("w")                       # resumes
        assert reader.is_alive("w")

    def test_writer_restart_reads_as_fresh(self):
        # a restarted worker's counter restarts too; the nonce makes
        # the record read as changed, never as a stale continuation
        store = MemStore()
        w1 = ReplicaRegistry(store, ttl_s=2.0)
        reader = ReplicaRegistry(store, ttl_s=2.0)
        for _ in range(3):
            w1.heartbeat("w")
        t0 = time.monotonic()
        reader._mono = lambda: t0
        assert reader.is_alive("w")
        reader._mono = lambda: t0 + 5.0             # w1 long silent
        assert reader.is_alive("w") is False
        w2 = ReplicaRegistry(store, ttl_s=2.0)      # new process
        w2.heartbeat("w")
        assert reader.is_alive("w")

    def test_legacy_record_without_seq_falls_back_to_ts(self):
        import json

        store = MemStore()
        reader = ReplicaRegistry(store, ttl_s=5.0)
        store.set("serving_fleet/hb/old",
                  json.dumps({"ts": time.time()}))
        assert reader.is_alive("old")
        store.set("serving_fleet/hb/old",
                  json.dumps({"ts": time.time() - 100.0}))
        assert reader.is_alive("old") is False

    def test_explicit_now_keeps_simulated_clock_contract(self):
        reg = ReplicaRegistry(MemStore(), ttl_s=5.0)
        reg.heartbeat("a", now=100.0)
        assert reg.is_alive("a", now=104.0)
        assert reg.is_alive("a", now=106.0) is False

    def test_worker_kill_fault_noop_without_hard_kill(self):
        ra = FakeReplica("ra", ttft=1.0)
        rb = FakeReplica("rb", ttft=9.0)
        router = FleetRouter([ra, rb])
        rid = router.add_request([1], SamplingParams(max_new_tokens=3))
        faults.install("fleet.worker_kill:flag:ra*1")
        outs = _drain_router(router)
        assert ra.alive                       # no transport, no SIGKILL
        final = {o.request_id: o.finish_reason for o in outs
                 if o.finished}
        assert final == {rid: "length"}


# ---------------------------------------------------------------------------
# replicated control plane under fire (ISSUE 16): two routers, the
# lease fault points joined to the transport storm
# ---------------------------------------------------------------------------
class TestReplicatedStorm:
    def test_replicated_chaos_storm_exact_accounting(self, tiny_model):
        """Two loopback routers over one shared store, the full fault
        menu at once: the owning router SIGKILLed mid-storm, a lease
        renewal dropped, a live lease stolen, a worker killed, RPC
        drops and delays. Invariants, not outcomes: every request in
        exactly one terminal bucket, every token delivered exactly
        once, no orphaned lease, every issued ticket in exactly one
        outcome, peer listeners empty, surviving pools full."""
        from paddle_tpu.serving.fleet import LeaseStore

        for seed in (0, 1):
            sched = np.random.default_rng(100 + seed)
            n = 8
            prompts = _prompts(tiny_model, n, seed=40 + seed)
            ids = [f"rs{seed}-{i}" for i in range(n)]
            lbs = [Loopback(InProcessReplica(
                       tiny_model, _ecfg(), replica_id=f"RS{seed}{j}"))
                   for j in range(3)]
            for lb in lbs:
                lb.handle.peer_endpoint = lb.inner.start_peer()
            store = MemStore()
            cfg = FleetConfig(heartbeat_interval_s=0.0,
                              router_ttl_s=0.3, lease_ttl_s=0.6)
            routers = []
            for name in ("A", "B"):
                reg = ReplicaRegistry(store, ttl_s=30.0)
                routers.append(FleetRouter(
                    [lb.handle for lb in lbs], cfg, reg,
                    lease_store=LeaseStore(store, ttl_s=0.6),
                    router_id=f"{name}{seed}"))
            ra, rb = routers
            ra.step(); rb.step()  # discover each other
            for i, (rid, p) in enumerate(zip(ids, prompts)):
                (ra if i % 2 == 0 else rb).add_request(
                    rid, p, sampling=_sp(i % 2 == 1))
            outs = []

            def joint(steps):
                for _ in range(steps):
                    for r in routers:
                        outs.extend(r.step())

            joint(3)  # every request dispatched AND leased
            spec = ";".join([
                # the router holding half the traffic dies mid-decode
                f"fleet.router_kill:flag:A{seed}"
                f"@{sched.integers(1, 3)}*1",
                # one renewal write dropped: owner must self-fence and
                # the request recovers through the expired bucket
                f"fleet.lease_expire:flag:{ids[2]}*1",
                # a live lease force-adopted out from under its owner
                f"fleet.lease_steal:flag:{ids[5]}*1",
                # plus the PR-12/14 transport storm underneath
                f"fleet.worker_kill:flag:RS{seed}0"
                f"@{sched.integers(2, 6)}*1",
                f"fleet.rpc_drop:flag@{sched.integers(3, 30)}"
                f"*{sched.integers(1, 3)}",
                f"fleet.rpc_delay:sleep:0.01@{sched.integers(1, 20)}"
                f"*{sched.integers(1, 4)}",
            ])
            faults.install(spec)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                joint(1)
                live = [r for r in routers if not r.router_dead]
                # quiesce = live routers idle AND no lease open: the
                # dead router's requests stay leased until a peer's
                # sweep adopts and finishes them
                if (not any(r.has_unfinished() for r in live)
                        and routers[0].lease_store.active() == 0):
                    break
                time.sleep(0.005)
            faults.clear()
            live = [r for r in routers if not r.router_dead]
            assert live and not any(r.has_unfinished() for r in live)

            # every request reached EXACTLY ONE terminal, fleet-wide
            final = {}
            for o in outs:
                if o.finished:
                    assert o.request_id not in final, \
                        f"{o.request_id} got two terminals"
                    final[o.request_id] = o
            assert set(final) == set(ids)  # no strands
            assert all(final[r].finish_reason in FINISH_REASONS
                       for r in ids)
            # every token delivered exactly once (failover replays
            # nothing, fencing loses nothing)
            counts = {}
            for o in outs:
                if o.token is not None:
                    counts[o.request_id] = counts.get(o.request_id,
                                                      0) + 1
            for r in ids:
                assert counts.get(r, 0) == len(final[r].generated), r
            # the failover actually happened and was counted once
            assert ra.router_dead
            assert sum(r.num_router_failovers for r in routers) == 1
            # lease accounting is exact: every incarnation in exactly
            # one bucket, nothing orphaned at quiesce
            acq = sum(r.lease_store.num_acquired for r in routers)
            closed = sum(r.lease_store.num_completed
                         + r.lease_store.num_adopted
                         + r.lease_store.num_expired for r in routers)
            assert acq == closed
            assert routers[0].lease_store.active() == 0
            # the injected lease faults really fired
            assert sum(r.lease_store.num_renew_dropped
                       for r in routers) >= 1
            # per-router ticket accounting partitions
            for r in routers:
                assert r.num_tickets_issued == \
                    sum(r.ticket_outcomes.values())
            # surviving engines: pools back to full, listeners empty
            for lb in lbs:
                if lb.handle.alive:
                    bm = lb.inner.engine.block_manager
                    assert bm.num_free_blocks == bm.num_blocks
                    lis = lb.inner.peer_listener
                    if lis is not None:
                        lis.gc()
                        assert lis.pending_count == 0
