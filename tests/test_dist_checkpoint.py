"""Distributed checkpoint: sharded save + reshard-on-load across meshes
(reference: distributed/checkpoint/save_state_dict.py / load_state_dict.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.engine import (
    ParallelConfig, ParallelTrainStep, shard_model_parameters,
)
from paddle_tpu.distributed.fleet.mp_layers import (
    ColumnParallelLinear, RowParallelLinear,
)
from paddle_tpu.distributed.mesh import ProcessMesh


def make_mlp():
    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = ColumnParallelLinear(16, 32, gather_output=False)
            self.fc2 = RowParallelLinear(32, 16, input_is_parallel=True)

        def forward(self, x):
            return self.fc2(self.fc1(x))

    return MLP()


def test_save_load_reshard_across_meshes(tmp_path):
    """Save under mesh(2,4) TP + ZeRO, reload under mesh(4,2) and under a
    fresh unsharded model: values bitwise equal."""
    paddle.seed(0)
    m = make_mlp()
    mesh24 = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    cfg = ParallelConfig(dp_axes=("dp",), sharding_stage=3,
                         sharding_axis="dp")
    shard_model_parameters(m, mesh24, cfg)
    ref = {k: v.numpy().copy() for k, v in m.state_dict().items()}
    ckpt.save_state_dict(m.state_dict(), str(tmp_path / "ck"))

    # reload under a transposed mesh
    paddle.seed(123)  # different init
    m2 = make_mlp()
    mesh42 = ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])
    shard_model_parameters(m2, mesh42, cfg)
    assert not np.allclose(m2.fc1.weight.numpy(), ref["fc1.weight"])
    ckpt.load_state_dict(m2.state_dict(), str(tmp_path / "ck"))
    for k, v in m2.state_dict().items():
        np.testing.assert_array_equal(v.numpy(), ref[k], err_msg=k)
    # shardings preserved on the new mesh
    assert m2.fc1.weight._data.sharding.spec[1] == "mp"

    # reload into a plain single-device model
    paddle.seed(77)
    m3 = make_mlp()
    ckpt.load_state_dict(m3.state_dict(), str(tmp_path / "ck"))
    for k, v in m3.state_dict().items():
        np.testing.assert_array_equal(v.numpy(), ref[k], err_msg=k)


def test_save_load_optimizer_state_nested(tmp_path):
    """Nested dicts (model + optimizer slots) round-trip."""
    paddle.seed(1)
    m = nn.Linear(8, 8)
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    step(paddle.randn([4, 8]), paddle.randn([4, 8]))

    state = {"model": m.state_dict(), "opt": opt.state_dict()}
    ref_w = m.weight.numpy().copy()
    ckpt.save_state_dict(state, str(tmp_path / "ck2"))

    paddle.seed(2)
    m2 = nn.Linear(8, 8)
    opt2 = optimizer.AdamW(learning_rate=1e-3, parameters=m2.parameters())
    step2 = paddle.jit.TrainStep(m2, nn.MSELoss(), opt2)
    step2(paddle.randn([4, 8]), paddle.randn([4, 8]))
    state2 = {"model": m2.state_dict(), "opt": opt2.state_dict()}
    ckpt.load_state_dict(state2, str(tmp_path / "ck2"))
    np.testing.assert_array_equal(m2.weight.numpy(), ref_w)


def test_bf16_roundtrip(tmp_path):
    x = paddle.ones([4, 4]).astype("bfloat16") * 1.5
    ckpt.save_state_dict({"x": x}, str(tmp_path / "ckb"))
    y = paddle.zeros([4, 4]).astype("bfloat16")
    ckpt.load_state_dict({"x": y}, str(tmp_path / "ckb"))
    assert str(y.dtype).endswith("bfloat16")
    np.testing.assert_array_equal(np.asarray(y._data, dtype=np.float32),
                                  np.full((4, 4), 1.5, np.float32))


def test_missing_tensor_raises(tmp_path):
    ckpt.save_state_dict({"a": paddle.ones([2])}, str(tmp_path / "ckm"))
    with pytest.raises(KeyError):
        ckpt.load_state_dict({"a": paddle.ones([2]),
                              "b": paddle.ones([2])}, str(tmp_path / "ckm"))


def test_shape_mismatch_raises(tmp_path):
    ckpt.save_state_dict({"a": paddle.ones([2])}, str(tmp_path / "cks"))
    with pytest.raises(ValueError):
        ckpt.load_state_dict({"a": paddle.ones([3])}, str(tmp_path / "cks"))
