"""nn long-tail layers (nn/layers_extra.py + ops/nn_extras.py):
pooling/unpooling/fractional, shuffles, fold, conv transposes, the
remaining losses (torch-referenced), BiRNN and beam-search decoding.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def t(a):
    return paddle.to_tensor(np.asarray(a, "float32"))


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


def test_pool3d_and_adaptive(rng):
    x3 = t(rng.standard_normal((1, 2, 4, 4, 4)))
    assert tuple(nn.MaxPool3D(2)(x3).shape) == (1, 2, 2, 2, 2)
    assert tuple(nn.AvgPool3D(2)(x3).shape) == (1, 2, 2, 2, 2)
    assert tuple(nn.AdaptiveAvgPool3D(3)(x3).shape) == (1, 2, 3, 3, 3)
    x1 = t(rng.standard_normal((1, 2, 7)))
    assert tuple(nn.AdaptiveMaxPool1D(3)(x1).shape) == (1, 2, 3)
    assert tuple(nn.AdaptiveAvgPool1D(3)(x1).shape) == (1, 2, 3)
    # numerics: avg_pool3d == reshape-mean for divisible sizes
    got = nn.AvgPool3D(2)(x3).numpy()
    want = x3.numpy().reshape(1, 2, 2, 2, 2, 2, 2, 2).mean(
        axis=(3, 5, 7))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fractional_pool(rng):
    f = nn.FractionalMaxPool2D((3, 3), random_u=0.3)(
        t(rng.standard_normal((1, 1, 7, 7))))
    assert tuple(f.shape) == (1, 1, 3, 3)
    assert np.isfinite(f.numpy()).all()
    f3 = nn.FractionalMaxPool3D((2, 3, 3), random_u=0.3)(
        t(rng.standard_normal((1, 2, 4, 7, 7))))
    assert tuple(f3.shape) == (1, 2, 2, 3, 3)
    # global max must survive any pooling partition
    x = t(rng.standard_normal((1, 1, 6, 6)))
    out = nn.FractionalMaxPool2D((2, 2), random_u=0.7)(x)
    assert np.isclose(out.numpy().max(), x.numpy().max())


def test_max_unpool(rng):
    up = nn.MaxUnPool1D(2)(t([[[3.0, 4.0]]]),
                           paddle.to_tensor(np.asarray([[[1, 3]]])))
    np.testing.assert_allclose(up.numpy(), [[[0.0, 3.0, 0.0, 4.0]]])
    up2 = nn.MaxUnPool2D(2)(
        t([[[[5.0]]]]), paddle.to_tensor(np.asarray([[[[3]]]])))
    np.testing.assert_allclose(up2.numpy(),
                               [[[[0.0, 0.0], [0.0, 5.0]]]])


def test_shuffles_pads_softmax2d(rng):
    x = t(rng.standard_normal((1, 4, 2, 2)))
    cs = nn.ChannelShuffle(2)(x)
    assert tuple(cs.shape) == (1, 4, 2, 2)
    # channel_shuffle permutes channels only
    np.testing.assert_allclose(np.sort(cs.numpy(), axis=1),
                               np.sort(x.numpy(), axis=1))
    pu = nn.PixelUnshuffle(2)(t(rng.standard_normal((1, 1, 4, 4))))
    assert tuple(pu.shape) == (1, 4, 2, 2)
    zp = nn.ZeroPad2D([1, 1, 2, 2])(t(rng.standard_normal((1, 1, 2, 2))))
    assert tuple(zp.shape) == (1, 1, 6, 4)
    s2 = nn.Softmax2D()(t(rng.standard_normal((1, 3, 2, 2))))
    np.testing.assert_allclose(s2.numpy().sum(axis=1),
                               np.ones((1, 2, 2)), rtol=1e-5)
    uf = nn.Unflatten(1, [2, 2])(t(rng.standard_normal((3, 4))))
    assert tuple(uf.shape) == (3, 2, 2)


def test_fold_inverts_unfold(rng):
    img = t(rng.standard_normal((1, 1, 4, 4)))
    col = nn.functional.unfold(img, 2, strides=2)
    rec = nn.Fold((4, 4), 2, strides=2)(col)
    np.testing.assert_allclose(rec.numpy(), img.numpy(), rtol=1e-6)


def test_rrelu(rng):
    x = t(rng.standard_normal((64,)))
    layer = nn.RReLU(0.1, 0.3)
    layer.eval()
    got = layer(x).numpy()
    want = np.where(x.numpy() >= 0, x.numpy(), 0.2 * x.numpy())
    np.testing.assert_allclose(got, want, rtol=1e-5)
    layer.train()
    tr = layer(x).numpy()
    neg = x.numpy() < 0
    slopes = tr[neg] / x.numpy()[neg]
    assert (slopes >= 0.1 - 1e-6).all() and (slopes <= 0.3 + 1e-6).all()


def test_conv_transposes_match_torch(rng):
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF

    w1 = rng.standard_normal((2, 3, 3)).astype("float32")
    x1 = rng.standard_normal((1, 2, 5)).astype("float32")
    ours = paddle.ops.get_op("conv1d_transpose")(
        t(x1), t(w1), None, stride=2).numpy()
    ref = TF.conv_transpose1d(torch.tensor(x1), torch.tensor(w1),
                              stride=2).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    w3 = rng.standard_normal((2, 3, 2, 2, 2)).astype("float32")
    x3 = rng.standard_normal((1, 2, 3, 3, 3)).astype("float32")
    ours = paddle.ops.get_op("conv3d_transpose")(
        t(x3), t(w3), None, stride=2, padding=1).numpy()
    ref = TF.conv_transpose3d(torch.tensor(x3), torch.tensor(w3),
                              stride=2, padding=1).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
    # layer classes construct + run
    c1 = nn.Conv1DTranspose(2, 3, 3, stride=2)
    assert tuple(c1(t(x1)).shape) == (1, 3, 11)
    c3 = nn.Conv3DTranspose(2, 3, 2, stride=2)
    assert tuple(c3(t(x3)).shape) == (1, 3, 6, 6, 6)


def test_losses_match_torch(rng):
    torch = pytest.importorskip("torch")
    import torch.nn.functional as TF

    inp = rng.standard_normal((4, 5)).astype("float32")
    lab = rng.standard_normal((4, 5)).astype("float32")
    var = rng.random((4, 5)).astype("float32") + 0.1
    y = np.sign(rng.standard_normal((4, 5))).astype("float32")
    bl = (rng.random((4, 5)) > 0.5).astype("float32")
    cls = rng.integers(0, 5, 4)
    pos = np.abs(rng.standard_normal((4, 5))).astype("float32")

    cases = [
        (nn.GaussianNLLLoss()(t(inp), t(lab), t(var)),
         TF.gaussian_nll_loss(torch.tensor(inp), torch.tensor(lab),
                              torch.tensor(var))),
        (nn.HingeEmbeddingLoss()(t(inp), t(y)),
         TF.hinge_embedding_loss(torch.tensor(inp), torch.tensor(y))),
        (nn.MultiLabelSoftMarginLoss()(t(inp), t(bl)),
         TF.multilabel_soft_margin_loss(torch.tensor(inp),
                                        torch.tensor(bl))),
        (nn.MultiMarginLoss()(t(inp),
                              paddle.to_tensor(cls.astype("int32"))),
         TF.multi_margin_loss(torch.tensor(inp), torch.tensor(cls))),
        (nn.PoissonNLLLoss()(t(inp), t(pos)),
         TF.poisson_nll_loss(torch.tensor(inp), torch.tensor(pos))),
        (nn.SoftMarginLoss()(t(inp), t(y)),
         TF.soft_margin_loss(torch.tensor(inp), torch.tensor(y))),
    ]
    for got, want in cases:
        np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=1e-3)

    a = rng.standard_normal((4, 8)).astype("float32")
    p = rng.standard_normal((4, 8)).astype("float32")
    n = rng.standard_normal((4, 8)).astype("float32")
    np.testing.assert_allclose(
        nn.TripletMarginLoss()(t(a), t(p), t(n)).numpy(),
        TF.triplet_margin_loss(torch.tensor(a), torch.tensor(p),
                               torch.tensor(n)).numpy(), rtol=1e-3)
    # custom-distance variant agrees with default for L2
    got = nn.TripletMarginWithDistanceLoss()(t(a), t(p), t(n))
    assert np.isfinite(float(got.numpy()))


def test_hsigmoid_trains(rng):
    paddle.seed(0)
    hs = nn.HSigmoidLoss(8, 6)
    opt = optimizer.Adam(learning_rate=0.1, parameters=hs.parameters())
    X = t(rng.standard_normal((16, 8)))
    L = paddle.to_tensor(rng.integers(0, 6, 16).astype("int32"))
    l0 = None
    for _ in range(25):
        loss = hs(X, L).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if l0 is None:
            l0 = float(loss.numpy())
    assert float(loss.numpy()) < l0 * 0.8


def test_birnn(rng):
    cell_fw = nn.SimpleRNNCell(4, 6)
    cell_bw = nn.SimpleRNNCell(4, 6)
    out, (sf, sb) = nn.BiRNN(cell_fw, cell_bw)(
        t(rng.standard_normal((2, 5, 4))))
    assert tuple(out.shape) == (2, 5, 12)
    # forward half equals a forward-only RNN
    from paddle_tpu.nn.rnn import RNN

    fw_out, _ = RNN(cell_fw)(t(rng.standard_normal((2, 5, 4))))
    assert tuple(fw_out.shape) == (2, 5, 6)


def test_beam_search_decode(rng):
    paddle.seed(0)
    emb = nn.Embedding(10, 4)
    proj = nn.Linear(6, 10)
    cell = nn.SimpleRNNCell(4, 6)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=9,
                               beam_size=3,
                               embedding_fn=lambda ids: emb(ids),
                               output_fn=lambda h: proj(h))
    ids, lps = nn.dynamic_decode(dec, max_step_num=6, batch_size=2)
    assert ids.shape[0] == 2 and ids.shape[1] == 3
    assert tuple(lps.shape) == (2, 3)
    # beams are sorted best-first per batch
    l = lps.numpy()
    assert (np.diff(l, axis=1) <= 1e-5).all()


def test_rnncellbase_initial_states(rng):
    class MyCell(nn.RNNCellBase):
        def __init__(self):
            super().__init__()
            self.hidden_size = 7

    c = MyCell()
    s = c.get_initial_states(t(rng.standard_normal((3, 4))))
    assert tuple(s.shape) == (3, 7)


def test_pool3d_ceil_mode_and_layout(rng):
    x = t(rng.standard_normal((1, 1, 5, 5, 5)))
    out = paddle.ops.get_op("max_pool3d")(x, 2, stride=2,
                                          ceil_mode=True)
    assert tuple(out.shape) == (1, 1, 3, 3, 3)
    # ceil-mode averages never count padded cells
    ones = t(np.ones((1, 1, 3, 3, 3)))
    av = paddle.ops.get_op("avg_pool3d")(ones, 2, stride=2,
                                         ceil_mode=True)
    np.testing.assert_allclose(av.numpy(), 1.0, rtol=1e-6)
    # channels-last layout
    xn = rng.standard_normal((1, 4, 4, 4, 2)).astype("float32")
    got = paddle.ops.get_op("max_pool3d")(t(xn), 2,
                                          data_format="NDHWC").numpy()
    want = paddle.ops.get_op("max_pool3d")(
        t(xn.transpose(0, 4, 1, 2, 3)), 2).numpy().transpose(
        0, 2, 3, 4, 1)
    np.testing.assert_allclose(got, want)


def test_fractional_return_mask_feeds_unpool(rng):
    xf = t(rng.standard_normal((1, 1, 6, 6)))
    out, mask = paddle.ops.get_op("fractional_max_pool2d")(
        xf, (3, 3), random_u=0.4, return_mask=True)
    flat = xf.numpy().reshape(-1)
    np.testing.assert_allclose(out.numpy().reshape(-1),
                               flat[mask.numpy().reshape(-1)])


def test_soft_margin_loss_stable_at_large_logits():
    v = nn.SoftMarginLoss()(t([-100.0]), t([1.0]))
    assert np.isclose(float(v.numpy()), 100.0, rtol=1e-3)


def test_beam_ancestry_greedy_equivalence(rng):
    """Beam=1 decode must equal the argmax rollout — only true when
    sequences are backtracked through parent beams (gather_tree)."""
    paddle.seed(0)
    emb = nn.Embedding(10, 4)
    proj = nn.Linear(6, 10)
    cell = nn.SimpleRNNCell(4, 6)
    dec1 = nn.BeamSearchDecoder(cell, 0, 9, 1, embedding_fn=emb,
                                output_fn=proj)
    ids1, _ = nn.dynamic_decode(dec1, max_step_num=5, batch_size=1)
    tok = paddle.to_tensor(np.asarray([0], "int32"))
    st = None
    want = []
    for _ in range(ids1.shape[-1]):
        o, st = cell(emb(tok), st)
        nxt = int(np.argmax(proj(o).numpy()))
        want.append(nxt)
        tok = paddle.to_tensor(np.asarray([nxt], "int32"))
    np.testing.assert_allclose(ids1.numpy()[0, 0], want)
