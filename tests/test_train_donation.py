"""TrainStep buffer donation correctness.

Donation is a pure buffer-aliasing contract: XLA updates params/slots in
place in HBM instead of allocating outputs and copying. It must be
numerically INVISIBLE — these tests pin donated and non-donated runs to
bit-identical losses and params over multiple steps, on the f32 path,
the bf16 + f32-master-weights path, and across the SOT guard-miss /
re-explore path (where a discarded dispatch has already consumed the
donated buffers and TrainStep must hand the eager explore the
re-materialized state).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _fresh(donate, seed=7, dtype="float32", multi_precision=False):
    paddle.set_default_dtype(dtype)
    try:
        paddle.seed(seed)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=m.parameters(),
                             multi_precision=multi_precision)
        step = paddle.jit.TrainStep(m, nn.CrossEntropyLoss(), opt,
                                    donate=donate)
    finally:
        paddle.set_default_dtype("float32")
    return m, opt, step


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    X = paddle.to_tensor(rng.normal(size=(16, 8)).astype("float32"))
    Y = paddle.to_tensor(rng.integers(0, 4, 16).astype("int64"))
    return X, Y


def _run(step, n=5):
    X, Y = _batch()
    return [np.asarray(step(X, Y)._data) for _ in range(n)]


def _assert_states_equal(m_a, m_b, opt_a, opt_b):
    for pa, pb in zip(m_a.parameters(), m_b.parameters()):
        np.testing.assert_array_equal(np.asarray(pa._data),
                                      np.asarray(pb._data))
        sa, sb = opt_a._slots[id(pa)], opt_b._slots[id(pb)]
        assert sa.keys() == sb.keys()
        for k in sa:
            np.testing.assert_array_equal(np.asarray(sa[k]),
                                          np.asarray(sb[k]))


def test_donated_matches_undonated_f32():
    m_d, opt_d, step_d = _fresh(donate=True)
    m_u, opt_u, step_u = _fresh(donate=False)
    losses_d = _run(step_d, n=5)
    losses_u = _run(step_u, n=5)
    np.testing.assert_array_equal(losses_d, losses_u)
    _assert_states_equal(m_d, m_u, opt_d, opt_u)


def test_donated_matches_undonated_bf16_master_weights():
    m_d, opt_d, step_d = _fresh(donate=True, dtype="bfloat16",
                                multi_precision=True)
    m_u, opt_u, step_u = _fresh(donate=False, dtype="bfloat16",
                                multi_precision=True)
    assert "bfloat16" in str(m_d.parameters()[0].dtype)
    assert "master_weight" in opt_d._slots[id(m_d.parameters()[0])]
    losses_d = _run(step_d, n=5)
    losses_u = _run(step_u, n=5)
    np.testing.assert_array_equal(losses_d, losses_u)
    _assert_states_equal(m_d, m_u, opt_d, opt_u)


def test_donation_consumes_old_buffers():
    """The donated step must actually donate: the pre-step param buffer
    is deleted after the dispatch (this is what removes the HBM copy),
    while donate=False leaves it readable."""
    m_d, _, step_d = _fresh(donate=True)
    m_u, _, step_u = _fresh(donate=False)
    X, Y = _batch()
    old_d = [p._data for p in m_d.parameters()]
    old_u = [p._data for p in m_u.parameters()]
    step_d(X, Y)
    step_u(X, Y)
    assert all(a.is_deleted() for a in old_d), \
        "donate=True did not consume the input buffers"
    assert not any(a.is_deleted() for a in old_u)
    # carried references were rebound, not left dangling
    for p in m_d.parameters():
        assert not p._data.is_deleted()
        np.asarray(p._data)  # readable


class _Gated(nn.Layer):
    """Data-dependent Python branch: forces a graph break -> SOT
    guard-path specialization, and a sign flip in the batch mean forces
    a guard miss -> discarded donated dispatch -> eager re-explore ->
    retrace of the new path."""

    def __init__(self):
        super().__init__()
        self.pos = nn.Linear(8, 4)
        self.neg = nn.Linear(8, 4)

    def forward(self, x):
        if x.mean() > 0:
            return self.pos(x)
        return self.neg(x)


def _fresh_gated(donate, seed=11):
    paddle.seed(seed)
    m = _Gated()
    opt = optimizer.Adam(learning_rate=0.01, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.CrossEntropyLoss(), opt,
                                donate=donate)
    return m, opt, step


def test_donation_retrace_after_guard_miss():
    rng = np.random.default_rng(3)
    base = rng.normal(size=(16, 8)).astype("float32")
    X_pos = paddle.to_tensor(np.abs(base))
    X_neg = paddle.to_tensor(-np.abs(base))
    Y = paddle.to_tensor(rng.integers(0, 4, 16).astype("int64"))
    # alternate signs: every flip is a guard miss on the MRU path
    schedule = [X_pos, X_neg, X_pos, X_neg, X_neg, X_pos]
    m_d, opt_d, step_d = _fresh_gated(donate=True)
    m_u, opt_u, step_u = _fresh_gated(donate=False)
    losses_d = [np.asarray(step_d(x, Y)._data) for x in schedule]
    losses_u = [np.asarray(step_u(x, Y)._data) for x in schedule]
    assert step_d._sot_cache is not None and len(step_d._sot_cache) == 2
    assert step_d._sot_cache.guard_mismatches >= 3
    np.testing.assert_array_equal(losses_d, losses_u)
    _assert_states_equal(m_d, m_u, opt_d, opt_u)
    # state is live and usable after the donated guard-miss churn
    for p in m_d.parameters():
        assert not p._data.is_deleted()


def test_redispatch_after_consumed_donation_fails_loudly():
    """If a dispatch fails AFTER consuming the donated state, a retry
    must raise the designed guard error (restore-from-checkpoint
    guidance), not jax's raw deleted-array error."""
    m, _, step = _fresh(donate=True)
    X, Y = _batch()
    step(X, Y)
    # simulate an execution failure that consumed the donated buffers
    m.parameters()[0]._data.delete()
    step._dispatch_failed = True
    with pytest.raises(RuntimeError, match="donate=False"):
        step(X, Y)


def test_run_steps_donated_matches_undonated():
    X, Y = _batch()
    m_d, opt_d, step_d = _fresh(donate=True)
    m_u, opt_u, step_u = _fresh(donate=False)
    l_d = np.asarray(step_d.run_steps(5, X, Y)._data)
    l_u = np.asarray(step_u.run_steps(5, X, Y)._data)
    np.testing.assert_array_equal(l_d, l_u)
    _assert_states_equal(m_d, m_u, opt_d, opt_u)
