"""Serving-path attention variants (reference:
incubate/nn/functional/block_multihead_attention.py,
variable_length_memory_efficient_attention.py). References are dense
numpy attention with explicit masks."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import functional as F


def _t(x):
    return paddle.to_tensor(np.asarray(x))


def _dense_attn(q, k, v, scale=None, causal=False, klen=None):
    """numpy reference: q (H,S,D), k/v (H,T,D)."""
    h, s, d = q.shape
    t = k.shape[1]
    scale = scale or 1.0 / np.sqrt(d)
    logits = np.einsum("hsd,htd->hst", q, k) * scale
    if klen is not None:
        logits[:, :, klen:] = -1e30
    if causal:
        for i in range(s):
            logits[:, i, i + 1:] = -1e30
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("hst,htd->hsd", p, v)


def test_varlen_attention_matches_dense_per_sequence():
    rng = np.random.default_rng(0)
    b, h, s, d = 2, 2, 6, 8
    q = rng.normal(size=(b, h, s, d)).astype("float32")
    k = rng.normal(size=(b, h, s, d)).astype("float32")
    v = rng.normal(size=(b, h, s, d)).astype("float32")
    lens = np.asarray([4, 6], "int32")
    out = F.variable_length_memory_efficient_attention(
        _t(q), _t(k), _t(v), _t(lens), _t(lens)).numpy()
    for bi in range(b):
        L = lens[bi]
        ref = _dense_attn(q[bi, :, :L], k[bi, :, :L], v[bi, :, :L])
        np.testing.assert_allclose(out[bi, :, :L], ref, rtol=2e-4,
                                   atol=2e-5)
        np.testing.assert_allclose(out[bi, :, L:], 0.0)  # padding zeroed


def test_varlen_attention_gqa_broadcast():
    rng = np.random.default_rng(1)
    b, h, kh, s, d = 1, 4, 2, 5, 8
    q = rng.normal(size=(b, h, s, d)).astype("float32")
    k = rng.normal(size=(b, kh, s, d)).astype("float32")
    v = rng.normal(size=(b, kh, s, d)).astype("float32")
    lens = np.asarray([s], "int32")
    out = F.variable_length_memory_efficient_attention(
        _t(q), _t(k), _t(v), _t(lens), _t(lens)).numpy()
    kk = np.repeat(k, 2, axis=1)
    vv = np.repeat(v, 2, axis=1)
    ref = _dense_attn(q[0], kk[0], vv[0])
    np.testing.assert_allclose(out[0], ref, rtol=2e-4, atol=2e-5)


def _fill_paged_cache(rng, b, lens, bs, kh, d, n_blocks):
    """Build a paged cache + the equivalent dense K/V."""
    mb = (max(lens) + bs - 1) // bs
    kc = np.zeros((n_blocks, bs, kh, d), "float32")
    vc = np.zeros((n_blocks, bs, kh, d), "float32")
    bt = np.full((b, mb), -1, "int32")
    dense_k = np.zeros((b, max(lens), kh, d), "float32")
    dense_v = np.zeros((b, max(lens), kh, d), "float32")
    nxt = 0
    for bi in range(b):
        for blk in range((lens[bi] + bs - 1) // bs):
            bt[bi, blk] = nxt
            n_tok = min(bs, lens[bi] - blk * bs)
            kv = rng.normal(size=(n_tok, kh, d)).astype("float32")
            vv = rng.normal(size=(n_tok, kh, d)).astype("float32")
            kc[nxt, :n_tok] = kv
            vc[nxt, :n_tok] = vv
            dense_k[bi, blk * bs: blk * bs + n_tok] = kv
            dense_v[bi, blk * bs: blk * bs + n_tok] = vv
            nxt += 1
    return kc, vc, bt, dense_k, dense_v


def test_paged_attention_matches_dense():
    rng = np.random.default_rng(2)
    b, h, d, bs = 2, 2, 8, 4
    lens = [6, 10]
    kc, vc, bt, dk, dv = _fill_paged_cache(rng, b, lens, bs, h, d, 8)
    q = rng.normal(size=(b, h, d)).astype("float32")
    out = F.paged_attention(_t(q), _t(kc), _t(vc), _t(bt),
                            _t(np.asarray(lens, "int32"))).numpy()
    for bi in range(b):
        L = lens[bi]
        ref = _dense_attn(q[bi][:, None, :],
                          dk[bi, :L].transpose(1, 0, 2),
                          dv[bi, :L].transpose(1, 0, 2))
        np.testing.assert_allclose(out[bi], ref[:, 0], rtol=2e-4,
                                   atol=2e-5)


def test_block_multihead_attention_prefill_then_decode():
    """Prefill writes the paged cache; a decode step then attends to
    prefix+self and must match dense causal attention over the full
    sequence."""
    rng = np.random.default_rng(3)
    b, h, d, bs, s = 1, 2, 8, 4, 6
    n_blocks = 4
    kc = np.zeros((n_blocks, bs, h, d), "float32")
    vc = np.zeros((n_blocks, bs, h, d), "float32")
    bt = np.asarray([[0, 1]], "int32")

    qkv = rng.normal(size=(b, s, 3, h, d)).astype("float32")
    out_p, kc2, vc2 = F.block_multihead_attention(
        _t(qkv), _t(kc), _t(vc),
        seq_lens_encoder=_t(np.asarray([s], "int32")),
        seq_lens_decoder=_t(np.asarray([0], "int32")),
        seq_lens_this_time=_t(np.asarray([s], "int32")),
        block_tables=_t(bt), block_size=bs)
    # prefill output == dense causal attention over the s tokens
    ref = _dense_attn(qkv[0, :, 0].transpose(1, 0, 2),
                      qkv[0, :, 1].transpose(1, 0, 2),
                      qkv[0, :, 2].transpose(1, 0, 2), causal=True)
    np.testing.assert_allclose(out_p.numpy()[0].transpose(1, 0, 2), ref,
                               rtol=2e-4, atol=2e-5)

    # decode one token
    qkv_d = rng.normal(size=(b, 1, 3, h, d)).astype("float32")
    out_d, kc3, vc3 = F.block_multihead_attention(
        _t(qkv_d), kc2, vc2,
        seq_lens_encoder=_t(np.asarray([0], "int32")),
        seq_lens_decoder=_t(np.asarray([s], "int32")),
        seq_lens_this_time=_t(np.asarray([1], "int32")),
        block_tables=_t(bt), block_size=bs)
    full_k = np.concatenate([qkv[0, :, 1], qkv_d[0, :, 1]], axis=0)
    full_v = np.concatenate([qkv[0, :, 2], qkv_d[0, :, 2]], axis=0)
    ref_d = _dense_attn(qkv_d[0, :, 0].transpose(1, 0, 2),
                        full_k.transpose(1, 0, 2),
                        full_v.transpose(1, 0, 2))
    np.testing.assert_allclose(out_d.numpy()[0, 0], ref_d[:, 0],
                               rtol=2e-4, atol=2e-5)


def test_device_plugin_registry():
    from paddle_tpu import device

    with np.testing.assert_raises(ValueError):
        device.register_backend("bad")  # neither path nor factory
    name = device.register_backend(
        "demo_backend", factory=lambda *a, **k: None)
    assert name == "demo_backend"
    assert "demo_backend" in device.registered_backends()
    assert "demo_backend" in device.get_all_custom_device_type()
    with np.testing.assert_raises(ValueError):
        device.register_backend("demo_backend",
                                factory=lambda *a, **k: None)


def test_fused_allreduce_gradients_single_process_noop():
    """World size 1: utility must be a no-op that leaves grads intact
    (multi-process behavior is pinned by tests/mp_scripts)."""
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.fleet.utils import (
        fused_allreduce_gradients, fused_parameters,
    )

    m = nn.Linear(4, 2)
    out = m(paddle.ones([3, 4]))
    paddle.sum(out).backward()
    g0 = m.parameters()[0].grad.numpy().copy()
    fused_allreduce_gradients(list(m.parameters()), group=None)
    np.testing.assert_allclose(m.parameters()[0].grad.numpy(), g0)
    groups = fused_parameters(m.parameters())
    assert sum(len(g) for g in groups) == len(list(m.parameters()))


def test_prefill_with_padding_keeps_token0():
    """Padded qkv rows (seq_lens_this_time < S) must not clobber cached
    K/V of real tokens (regression: pad rows scattered to slot 0)."""
    rng = np.random.default_rng(5)
    b, h, d, bs = 1, 2, 4, 4
    kc = np.zeros((4, bs, h, d), "float32")
    vc = np.zeros((4, bs, h, d), "float32")
    bt = np.asarray([[0, 1]], "int32")
    qkv = rng.normal(size=(b, 6, 3, h, d)).astype("float32")
    _, kc2, vc2 = F.block_multihead_attention(
        _t(qkv), _t(kc), _t(vc),
        seq_lens_encoder=_t(np.asarray([3], "int32")),
        seq_lens_decoder=_t(np.asarray([0], "int32")),
        seq_lens_this_time=_t(np.asarray([3], "int32")),
        block_tables=_t(bt), block_size=bs)
    np.testing.assert_allclose(kc2.numpy()[0, 0], qkv[0, 0, 1],
                               rtol=1e-6)  # token 0 intact
    np.testing.assert_allclose(kc2.numpy()[0, 3], 0.0)  # pad not written
