"""PTQ/QAT quantization (reference: python/paddle/quantization/ —
ptq.py, qat.py, observers, quanters)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.quantization import (
    AbsmaxObserver, FakeQuanterWithAbsMaxObserver, PTQ, QAT, QuantConfig,
    quant_dequant,
)


def test_quant_dequant_roundtrip_error_bounded():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.uniform(-2, 2, (64,)).astype(np.float32))
    out = quant_dequant(x, 2.0, bit_length=8)
    # max error is half an int8 quantization step of scale 2.0
    step = 2.0 / 127
    assert np.abs(out.numpy() - x.numpy()).max() <= step / 2 + 1e-6


def test_absmax_observer_tracks_running_max():
    ob = AbsmaxObserver()
    ob.observe(paddle.to_tensor(np.array([1.0, -3.0], np.float32)))
    ob.observe(paddle.to_tensor(np.array([0.5], np.float32)))
    assert ob.scale() == 3.0


def test_ptq_flow_linear():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randn(32, 8).astype(np.float32))
    ref = model(X).numpy()

    ptq = PTQ(QuantConfig())
    qmodel = ptq.quantize(model)
    # calibration passes feed the observers
    for _ in range(4):
        qmodel(X)
    converted = ptq.convert(qmodel)
    out = converted(X).numpy()
    # int8 simulation stays close to fp32
    err = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.1, err
    # weights are actually stored as int8
    from paddle_tpu.quantization import ConvertedQuantLayer

    layers = [l for _, l in converted.named_sublayers()
              if isinstance(l, ConvertedQuantLayer)]
    assert len(layers) == 2
    assert layers[0].qweight.dtype == np.int8


def test_qat_trains_through_fake_quant():
    """STE lets gradients flow through the fake-quant: loss descends."""
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    qat = QAT(QuantConfig())
    qmodel = qat.quantize(model)
    rng = np.random.RandomState(0)
    X = paddle.to_tensor(rng.randn(64, 8).astype(np.float32))
    W = rng.randn(8, 1).astype(np.float32)
    Y = paddle.to_tensor(X.numpy() @ W)
    # calibrate scales eagerly first
    qmodel(X)
    opt = optimizer.Adam(learning_rate=0.01,
                         parameters=qmodel.parameters())
    losses = []
    for _ in range(30):
        loss = nn.MSELoss()(qmodel(X), Y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_quant_config_type_filter():
    model = nn.Sequential(nn.Linear(4, 4), nn.Conv2D(1, 1, 3))
    cfg = QuantConfig()
    cfg.add_type_config(nn.Linear, activation=AbsmaxObserver,
                        weight=AbsmaxObserver)
    q = PTQ(cfg).quantize(model)
    from paddle_tpu.quantization import QuantedLayer

    kinds = {type(l).__name__ for _, l in q.named_sublayers()}
    assert "QuantedLayer" in kinds
