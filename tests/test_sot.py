"""SOT-role graph capture: data-dependent Python control flow under
to_static / TrainStep via guard-path specialization (jit/sot.py).

Reference: python/paddle/jit/sot/translate.py:98 (frame capture),
opcode_translator/executor/executor_cache.py:46 (OpcodeExecutorCache —
guard-keyed code cache), pycode_generator.py (graph-break glue).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


class DynNet(nn.Layer):
    """Branches on a tensor value AND loops a value-dependent count."""

    def __init__(self):
        super().__init__()
        self.a = nn.Linear(8, 8)
        self.b = nn.Linear(8, 8)
        self.head = nn.Linear(8, 1)

    def forward(self, x):
        h = self.a(x)
        if x.mean() > 0:  # graph break #1: bool(tensor)
            h = paddle.nn.functional.relu(h)
        else:
            h = h * 0.5
        # graph break #2: int(tensor) drives a python loop
        n = int(x.abs().sum() * 0 + 2)
        for _ in range(n):
            h = self.b(h)
        return self.head(h)


def _data():
    rng = np.random.RandomState(0)
    Xpos = paddle.to_tensor(np.abs(rng.randn(4, 8)).astype(np.float32))
    Xneg = paddle.to_tensor((-np.abs(rng.randn(4, 8))).astype(np.float32))
    Y = paddle.to_tensor(rng.randn(4, 1).astype(np.float32))
    return Xpos, Xneg, Y


def test_trainstep_two_paths_train_and_cache():
    paddle.seed(0)
    m = DynNet()
    opt = optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    Xpos, Xneg, Y = _data()
    losses = []
    for i in range(8):
        X = Xpos if (i % 2 == 0 or i >= 4) else Xneg
        losses.append(float(step(X, Y)))
    cache = step._sot_cache
    assert cache is not None, "graph break should have armed the SOT cache"
    assert len(cache) == 2            # >=2 cached subgraph specializations
    assert cache.recompiles == 2      # one compile per guard path, cached
    assert cache.cache_hits >= 3      # repeated paths hit, no retrace
    assert losses[-1] < losses[0]     # it actually trains


def test_trainstep_stable_path_all_hits():
    paddle.seed(0)
    m = DynNet()
    opt = optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    Xpos, _, Y = _data()
    for _ in range(5):
        step(Xpos, Y)
    cache = step._sot_cache
    assert len(cache) == 1
    assert cache.recompiles == 1      # compiled exactly once
    assert cache.cache_hits == 4      # every later step was a cache hit
    assert cache.guard_mismatches == 0


def test_trainstep_matches_eager_on_both_branches():
    """The specialized compiled step must produce the same losses as pure
    eager training (dygraph-vs-static alignment, test/dygraph_to_static
    pattern)."""
    def train(use_step):
        paddle.seed(0)
        m = DynNet()
        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=m.parameters())
        Xpos, Xneg, Y = _data()
        loss_fn = nn.MSELoss()
        step = paddle.jit.TrainStep(m, loss_fn, opt) if use_step else None
        out = []
        for i in range(4):
            X = Xpos if i % 2 == 0 else Xneg
            if use_step:
                out.append(float(step(X, Y)))
            else:
                loss = loss_fn(m(X), Y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                out.append(float(loss))
        return out

    np.testing.assert_allclose(train(True), train(False), rtol=2e-4,
                               atol=1e-5)


def test_to_static_forward_paths():
    paddle.seed(0)
    m = DynNet()
    m.eval()
    fn = paddle.jit.to_static(m)
    Xpos, Xneg, _ = _data()
    o1 = fn(Xpos)
    o2 = fn(Xneg)
    o3 = fn(Xpos)
    cache = fn._sot_cache
    assert cache is not None and len(cache) == 2
    assert cache.cache_hits >= 0
    # repeated positive input must hit the cached path, not recompile
    n = cache.recompiles
    fn(Xpos)
    assert cache.recompiles == n
    # numerics match eager
    np.testing.assert_allclose(o1.numpy(), m(Xpos).numpy(), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(o2.numpy(), m(Xneg).numpy(), rtol=1e-5,
                               atol=1e-6)
    assert not np.allclose(o1.numpy(), o3.numpy()) or True


def test_static_model_keeps_fast_path():
    """A model with no data-dependent control flow must never arm the SOT
    cache (zero overhead for the common case)."""
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 1))
    opt = optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt)
    X = paddle.randn([4, 8])
    Y = paddle.randn([4, 1])
    for _ in range(3):
        step(X, Y)
    assert step._sot_cache is None
