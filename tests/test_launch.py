"""Launcher CLI + multi-host rendezvous smoke.

Reference behavior: launch/controllers/collective.py:76-132 (per-process
PADDLE_TRAINER_ID/ENDPOINTS env), controllers/master.py (rendezvous),
watcher (kill job on a dead trainer). Multi-node is simulated as
multi-process on one host (reference test_dist_base.py pattern): two
CPU processes rendezvous through jax.distributed.initialize and run a
cross-process psum.
"""
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os
    # 1 local CPU device per process: the 2-process job then has 2 global
    # devices, so collectives must cross the process boundary
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb
        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends
            clear_backends()
    except Exception:
        pass

    import numpy as np
    from paddle_tpu.distributed import env as denv

    penv = denv.init_parallel_env()
    assert denv.get_world_size() == 2, denv.get_world_size()
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()
    assert rank == penv.rank, (rank, penv.rank)

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = jax.devices()
    assert len(devs) == 2, devs
    mesh = Mesh(np.array(devs), ("x",))
    local = np.full((1,), float(rank + 1), np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, PartitionSpec("x")), local, (2,))
    tot = jax.jit(jnp.sum,
                  out_shardings=NamedSharding(mesh, PartitionSpec()))(garr)
    val = float(tot)
    assert val == 3.0, val  # 1 + 2 across both processes
    print(f"SMOKE_OK rank={rank} world={jax.process_count()} sum={val}",
          flush=True)
""")


def test_launcher_spawns_and_rendezvous(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    log_dir = tmp_path / "logs"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", str(log_dir), str(worker)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    logs = ""
    for f in sorted(log_dir.glob("workerlog.*")):
        logs += f"--- {f.name} ---\n" + f.read_text()
    assert r.returncode == 0, f"launcher rc={r.returncode}\n{logs}\n" \
                              f"{r.stdout}\n{r.stderr}"
    assert "SMOKE_OK rank=0" in logs and "SMOKE_OK rank=1" in logs, logs


def test_launcher_kills_job_on_dead_trainer(tmp_path):
    """One failing worker terminates the rest (watcher.py role)."""
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import os, sys, time
        if os.environ["PADDLE_TRAINER_ID"] == "1":
            sys.exit(7)
        time.sleep(120)  # rank 0 would hang forever; launcher must kill it
    """))
    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(worker)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 7, (r.returncode, r.stdout, r.stderr)


def test_launcher_env_protocol(tmp_path):
    """Spawned env matches the reference's collective.py:76-132 fields."""
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent("""
        import os
        eps = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
        assert len(eps) == 2
        assert os.environ["PADDLE_CURRENT_ENDPOINT"] == \
            eps[int(os.environ["PADDLE_TRAINER_ID"])]
        assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
        assert "PADDLE_MASTER" in os.environ
        assert "MASTER_ADDR" in os.environ and "MASTER_PORT" in os.environ
    """))
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(worker)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr)


def test_launcher_elastic_restart(tmp_path):
    """--max_restart relaunches the whole gang after a failure (elastic
    manager role, reference fleet/elastic/manager.py:124): a worker that
    fails on its first attempt succeeds after one restart."""
    worker = tmp_path / "worker.py"
    marker = tmp_path / "attempted"
    worker.write_text(textwrap.dedent(f"""
        import os, sys
        marker = {str(marker)!r}
        if os.environ["PADDLE_TRAINER_ID"] == "0":
            if not os.path.exists(marker):
                open(marker, "w").write("x")
                sys.exit(3)  # first attempt dies
            assert os.environ["PADDLE_RESTART_COUNT"] == "1"
        print("ELASTIC_OK", os.environ["PADDLE_TRAINER_ID"], flush=True)
    """))
    log_dir = tmp_path / "logs"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restart", "2",
         "--restart_interval", "0.1", "--log_dir", str(log_dir),
         str(worker)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    logs = "".join(f.read_text() for f in sorted(log_dir.glob("workerlog.*")))
    assert r.returncode == 0, (r.returncode, logs, r.stderr)
    assert "ELASTIC_OK 0" in logs and "ELASTIC_OK 1" in logs, logs


def test_launcher_max_restart_exhausted(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text("import sys; sys.exit(9)\n")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restart", "1",
         "--restart_interval", "0.1", str(worker)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 9
    assert r.stderr.count("restarting") == 1, r.stderr
